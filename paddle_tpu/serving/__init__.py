"""paddle_tpu.serving — dynamic-batching TPU inference serving.

Role parity: Paddle Serving / the reference's server-side inference
deployment story, rebuilt TPU-native over the compile-once Predictor:

- shape buckets (buckets.py) pin the executable universe so the
  Executor compile cache never storms under variable-length traffic;
- a dynamic micro-batcher (batcher.py) coalesces concurrent requests
  into padded bucket batches with bounded-queue backpressure and
  per-request deadlines;
- ``Server`` (server.py) AOT-warms every bucket at start, serves
  ``/stats`` + ``/health`` over the fleet KV HTTP server, and drains
  gracefully on stop.
"""
from .batcher import Batcher, InferenceRequest  # noqa: F401
from .buckets import (  # noqa: F401
    BucketSpec,
    DeadlineExceededError,
    QueueFullError,
    RequestTooLargeError,
    ServerClosedError,
    ServingError,
)
from .server import Server, ServingConfig  # noqa: F401

__all__ = [
    "Batcher", "BucketSpec", "DeadlineExceededError", "InferenceRequest",
    "QueueFullError", "RequestTooLargeError", "Server", "ServerClosedError",
    "ServingConfig", "ServingError",
]
