"""paddle_tpu.serving — dynamic-batching TPU inference serving.

Role parity: Paddle Serving / the reference's server-side inference
deployment story, rebuilt TPU-native over the compile-once Predictor:

- shape buckets (buckets.py) pin the executable universe so the
  Executor compile cache never storms under variable-length traffic;
- a dynamic micro-batcher (batcher.py) coalesces concurrent requests
  into padded bucket batches with bounded-queue backpressure and
  per-request deadlines;
- ``Server`` (server.py) AOT-warms every bucket at start, serves
  ``/stats`` + ``/health`` over the fleet KV HTTP server, and drains
  gracefully on stop;
- the GENERATIVE path (decode.py + kv_cache.py): ``DecodeEngine``
  runs autoregressive decode over a fixed slot batch with a paged,
  device-resident KV cache (Pallas paged-attention kernels on TPU),
  continuous batching at step boundaries, streaming token replies,
  and deadline reaping mid-decode; prefix-cache page sharing
  (``PrefixIndex`` refcounts + copy-on-write) lets same-prefix
  prompts skip both HBM and prefill compute, chunked prefill keeps
  long prompts from stalling the slot batch, and speculative
  decoding (draft model + one batched verify) multiplies greedy
  tokens-per-dispatch bitwise-losslessly; ``DecodeServer``
  replicates N engines behind one least-loaded admission point with
  per-replica ``/stats``.
"""
from .batcher import Batcher, InferenceRequest, RequestBase  # noqa: F401
from .buckets import (  # noqa: F401
    BucketSpec,
    DeadlineExceededError,
    QueueFullError,
    RequestAbandonedError,
    RequestTooLargeError,
    ServerClosedError,
    ServingError,
    prefill_bucket_grid,
)
from .decode import (  # noqa: F401
    DecodeConfig,
    DecodeEngine,
    DecodeRequest,
    TransformerLM,
)
from .disagg import (  # noqa: F401
    Autoscaler,
    DisaggConfig,
    DisaggRequest,
    DisaggServer,
)
from .kv_cache import (  # noqa: F401
    CacheConfig,
    CacheExhaustedError,
    KVPageExport,
    PagedKVCache,
    PageAllocator,
    PrefixIndex,
)
from .server import (  # noqa: F401
    DecodeServer,
    Server,
    ServingConfig,
    least_loaded_order,
)

__all__ = [
    "Autoscaler", "Batcher", "BucketSpec", "CacheConfig",
    "CacheExhaustedError", "DeadlineExceededError", "DecodeConfig",
    "DecodeEngine", "DecodeRequest", "DecodeServer", "DisaggConfig",
    "DisaggRequest", "DisaggServer", "InferenceRequest",
    "KVPageExport", "PageAllocator", "PagedKVCache", "PrefixIndex",
    "QueueFullError", "RequestAbandonedError", "RequestBase",
    "RequestTooLargeError", "Server", "ServerClosedError",
    "ServingConfig", "ServingError", "TransformerLM",
    "least_loaded_order", "prefill_bucket_grid",
]
