"""Paged per-slot KV cache for autoregressive decode (serving/decode.py).

Layout (the vLLM PagedAttention idea, TPU-native): all keys/values for
every serving slot live in TWO device arrays of fixed-size pages

    k_pages, v_pages : [num_layers, num_pages, page_size, heads, head_dim]

and each slot owns an ordered list of page ids (its *page table*).  A
slot's logical sequence position ``t`` maps to page ``table[t // page]``
offset ``t % page``.  Pages are allocated from a host-side free list at
admission and returned the moment a request finishes — a finished slot
frees its memory immediately instead of padding to the longest request
in a batch.

Page 0 is the TRASH page: it is never allocated, dead slots' per-step
writes land there, and an empty page-table entry points at it.  Reads
are always masked by the slot's live length, so trash contents are
never observable.

The device arrays themselves are registered in a ``framework.Scope``
and threaded through ``Executor.run_persistent`` with donation — the
cache never round-trips to host between steps.

Admission is conservative: a request reserves
``ceil((prompt_len + max_new_tokens) / page_size)`` pages up front, so
a decode step can never fail on cache exhaustion mid-generation (the
price is vLLM-style optimistic over-commit is out of scope; the
allocator still shares one pool across slots, so short requests leave
room for more concurrent long ones than a dense [slots, max_seq] layout
would).
"""
from __future__ import annotations

import math
import threading
from typing import List, Optional, Sequence

import numpy as np

K_PAGES_VAR = "__decode_k_pages__"
V_PAGES_VAR = "__decode_v_pages__"


class CacheExhaustedError(RuntimeError):
    """The page pool cannot cover a request's worst-case reservation."""


class CacheConfig:
    """Geometry of the paged cache (everything static / compile-time)."""

    def __init__(self, num_layers: int, num_heads: int, head_dim: int,
                 num_slots: int, max_seq_len: int, page_size: int,
                 num_pages: Optional[int] = None, dtype="float32"):
        if max_seq_len % page_size:
            raise ValueError(
                f"max_seq_len ({max_seq_len}) must be a multiple of "
                f"page_size ({page_size})")
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.num_slots = int(num_slots)
        self.max_seq_len = int(max_seq_len)
        self.page_size = int(page_size)
        self.pages_per_slot = self.max_seq_len // self.page_size
        # default pool: every slot can hold a max-length sequence, plus
        # the reserved trash page — admission then only ever blocks on
        # free SLOTS, never pages.  A smaller explicit pool exercises
        # real paging pressure (admission waits for pages).
        self.num_pages = int(num_pages) if num_pages is not None \
            else self.num_slots * self.pages_per_slot + 1
        if self.num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is trash)")
        self.dtype = np.dtype(dtype)

    def pages_for(self, seq_len: int) -> int:
        return max(1, math.ceil(int(seq_len) / self.page_size))

    def page_bytes(self) -> int:
        return (self.page_size * self.num_heads * self.head_dim
                * self.dtype.itemsize)

    def cache_bytes(self) -> int:
        """Total device bytes of BOTH page arrays (k + v)."""
        return 2 * self.num_layers * self.num_pages * self.page_bytes()


class PageAllocator:
    """Host-side free list over page ids 1..num_pages-1 (0 is trash)."""

    def __init__(self, num_pages: int):
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._lock = threading.Lock()

    @property
    def num_free(self) -> int:
        with self._lock:
            return len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Take n pages, or None (atomically nothing) when the pool
        cannot cover the request."""
        with self._lock:
            if n > len(self._free):
                return None
            taken = self._free[-n:]
            del self._free[-n:]
            return list(reversed(taken))

    def free(self, pages: Sequence[int]) -> None:
        with self._lock:
            for p in pages:
                if p != 0:
                    self._free.append(int(p))


class PagedKVCache:
    """Host bookkeeping (page tables, lengths, allocator) + the device
    page arrays, which live in ``scope`` so Executor.run_persistent can
    donate them through each decode step."""

    def __init__(self, config: CacheConfig, scope):
        import jax.numpy as jnp

        self.config = config
        self.scope = scope
        self.allocator = PageAllocator(config.num_pages)
        c = config
        # per-slot host mirrors: the scheduler reads/writes these; the
        # device sees them as small per-step i32 feeds
        self.page_table = np.zeros((c.num_slots, c.pages_per_slot),
                                   np.int32)
        self.lengths = np.zeros((c.num_slots,), np.int32)
        self._slot_pages: List[List[int]] = [[] for _ in range(c.num_slots)]
        shape = (c.num_layers, c.num_pages, c.page_size, c.num_heads,
                 c.head_dim)
        scope.set_var(K_PAGES_VAR, jnp.zeros(shape, c.dtype))
        scope.set_var(V_PAGES_VAR, jnp.zeros(shape, c.dtype))

    # -- slot lifecycle ---------------------------------------------------
    def claim(self, slot: int, reserve_tokens: int) -> bool:
        """Reserve pages covering ``reserve_tokens`` positions for the
        slot; False when the pool can't cover it (caller retries later)."""
        n = self.config.pages_for(reserve_tokens)
        pages = self.allocator.alloc(n)
        if pages is None:
            return False
        self._slot_pages[slot] = pages
        row = np.zeros((self.config.pages_per_slot,), np.int32)
        row[:n] = pages
        self.page_table[slot] = row
        self.lengths[slot] = 0
        return True

    def release(self, slot: int) -> None:
        self.allocator.free(self._slot_pages[slot])
        self._slot_pages[slot] = []
        self.page_table[slot] = 0
        self.lengths[slot] = 0

    def slot_pages(self, slot: int) -> List[int]:
        return list(self._slot_pages[slot])

    def write_coords(self, slot: int):
        """(page_id, offset) for the NEXT position of the slot."""
        t = int(self.lengths[slot])
        return (int(self.page_table[slot][t // self.config.page_size]),
                t % self.config.page_size)

    def arrays(self):
        return (self.scope.get_var(K_PAGES_VAR),
                self.scope.get_var(V_PAGES_VAR))


# -- pure jit-side helpers (operate on the page arrays functionally) ------

def scatter_token_layer(pages, layer: int, val, page_id, offset):
    """Write one new position per slot: val [S, H, D] lands at
    (layer, page_id[s], offset[s]) — dead slots pass page 0 (trash)."""
    return pages.at[layer, page_id, offset].set(
        val.astype(pages.dtype))


def scatter_prompt_layer(pages, layer: int, val, page_ids):
    """Write a whole prompt's positions for one slot: val
    [n_pages*page, H, D] (padded to a page multiple) is stored page-
    wholesale into ``page_ids`` [n_pages]."""
    n = page_ids.shape[0]
    page = pages.shape[2]
    v = val.reshape(n, page, val.shape[1], val.shape[2])
    return pages.at[layer, page_ids].set(v.astype(pages.dtype))
