"""Paged per-slot KV cache with prefix sharing for autoregressive decode
(serving/decode.py).

Layout (the vLLM PagedAttention idea, TPU-native): all keys/values for
every serving slot live in TWO device arrays of fixed-size pages

    k_pages, v_pages : [num_layers, num_pages, page_size, heads, head_dim]

and each slot owns an ordered list of page ids (its *page table*).  A
slot's logical sequence position ``t`` maps to page ``table[t // page]``
offset ``t % page``.  Pages are allocated from a host-side free list at
admission and returned when their REFCOUNT drops to zero — a finished
slot releases its references immediately instead of padding to the
longest request in a batch.

Page 0 is the TRASH page: it is never allocated, dead slots' per-step
writes land there, and an empty page-table entry points at it.  Reads
are always masked by the slot's live length, so trash contents are
never observable.

**Prefix sharing** (this file's tentpole): at millions of users most
prompts open with the same system/template prefix, so recomputing and
re-storing its K/V per request wastes both HBM and prefill compute.
When a request finishes, its pages are registered in a host-side
``PrefixIndex`` — an exact token-content trie keyed by
``(parent_page_id, page_token_tuple)``, collision-free by construction
(no hashing shortcut can serve a wrong byte).  Admission walks the trie
over the new prompt: every matched page is SHARED into the slot's page
table with a refcount bump instead of being allocated and prefilled.
Sharing rules that keep the device arrays coherent:

- A registered page is immutable (the index itself holds one
  reference).  A slot may only write a page it solely owns
  (``refcount == 1`` and unregistered).
- The trie's final entry may be a *partial* page (a prompt tail shorter
  than one page).  A consumer that matches it borrows the page and
  must **copy-on-write** before its first divergent token lands there:
  ``plan_cow`` swaps the slot's reserved spare page into the table and
  returns the ``(src, dst)`` device copy the engine must perform before
  its next write dispatch.
- Worst-case reservation stays shared-aware and exhaustion-proof: a
  claim allocates ``total_pages - shared_full_pages`` fresh pages —
  when a partial page is borrowed, one of those fresh pages is held
  back as the CoW spare, so the mid-decode copy can NEVER fail on an
  empty pool (a decode step still never dies on cache exhaustion).
- Under pool pressure, admission evicts least-recently-hit CHILDLESS
  index entries whose pages only the index references (bottom-up, so a
  reused page id can never be mistaken for a live trie parent).

The device arrays themselves are registered in a ``framework.Scope``
and threaded through ``Executor.run_persistent`` with donation — the
cache never round-trips to host between steps.  The speculative-decode
draft model's page pools (serving/decode.py) are indexed by the SAME
page ids, so sharing, reservation, and CoW cover them for free (the
engine's CoW copy spans every pool).

**Quantized storage** (``FLAGS_decode_kv_quant``): pages are stored
int8 beside parallel scale pools ``[layers, pages, page_size, heads]``
(one float32 scale per head per position-in-page; see
:class:`CacheConfig` for why the scale granularity is the page's
positions rather than one scalar per page).  Writes quantize in the
step that produces the K/V (``write_token_layer`` /
``write_prompt_layer``); both attention paths dequantize inline
(``ops/pallas_decode_attention.py``).  Bytes per page roughly halve vs
bf16, and since the admission reservation is page-count-based, a pool
sized to a fixed byte budget admits ~2x the concurrent requests.
Freed pages' scale planes reset to ``SCALE_EPS`` (batched, flushed at
release/claim) so ``debug_check`` can audit scale-pool/page-pool
agreement.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..monitor import stat_add
from ..observe.histogram import stat_time
from ..ops.quant_ops import SCALE_EPS

K_PAGES_VAR = "__decode_k_pages__"
V_PAGES_VAR = "__decode_v_pages__"
K_SCALES_VAR = "__decode_k_scales__"
V_SCALES_VAR = "__decode_v_scales__"

KV_QMAX = 127.0  # symmetric int8 grid for quantized pages


class CacheExhaustedError(RuntimeError):
    """The page pool cannot cover a request's worst-case reservation."""


class KVPageExport:
    """A self-describing export of one slot's leading KV pages — the
    disaggregated-serving migration payload (serving/disagg.py).

    ``arrays`` maps every pool var name from ``state_var_names()``
    (data pages AND, when quantized, the scale planes) to a
    ``[layers, n_pages, ...]`` slice gathered out of the source pool.
    The slices are fresh buffers (a jax gather never aliases the
    donated pool), so a payload stays valid after the source engine's
    next step; ``np.asarray`` each array for the host-bounce transport
    when source and destination do not share a backend.  ``quantized``
    and ``page_size`` let the destination reject a geometry-mismatched
    install before touching its pools."""

    __slots__ = ("n_tokens", "n_pages", "src_pages", "arrays",
                 "quantized", "page_size", "nbytes")

    def __init__(self, n_tokens: int, n_pages: int,
                 src_pages: Sequence[int], arrays: Dict[str, object],
                 quantized: bool, page_size: int):
        self.n_tokens = int(n_tokens)
        self.n_pages = int(n_pages)
        self.src_pages = list(src_pages)
        self.arrays = dict(arrays)
        self.quantized = bool(quantized)
        self.page_size = int(page_size)
        self.nbytes = sum(
            int(np.prod(a.shape)) * a.dtype.itemsize
            for a in self.arrays.values())


class CacheConfig:
    """Geometry of the paged cache (everything static / compile-time).

    ``quantized=True`` (``FLAGS_decode_kv_quant``) stores pages as int8
    with a parallel per-page scale pool: one float32 scale per head per
    position-in-page (a ``[page_size, heads]`` scale plane per page,
    living in ``k/v_scales [layers, pages, page_size, heads]``).  The
    position-granular plane — rather than one scalar per page — is what
    keeps stored bytes WRITE-ONCE: re-deriving a position (a rejected
    speculative row, a chunked-prefill replay) re-quantizes only itself,
    so page content is order-independent and speculative decode stays
    bitwise-equal to its own non-speculative quantized run.  Bytes per
    position drop from ``2*head_dim`` (bf16) to ``head_dim + 4`` —
    about half — which is exactly what ``page_bytes()`` reports, so the
    worst-case admission reservation and the PR 8 HBM accounting both
    see the shrink and a fixed pool byte budget holds ~2x the pages."""

    def __init__(self, num_layers: int, num_heads: int, head_dim: int,
                 num_slots: int, max_seq_len: int, page_size: int,
                 num_pages: Optional[int] = None, dtype="float32",
                 quantized: bool = False):
        if max_seq_len % page_size:
            raise ValueError(
                f"max_seq_len ({max_seq_len}) must be a multiple of "
                f"page_size ({page_size})")
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.num_slots = int(num_slots)
        self.max_seq_len = int(max_seq_len)
        self.page_size = int(page_size)
        self.pages_per_slot = self.max_seq_len // self.page_size
        # default pool: every slot can hold a max-length sequence, plus
        # the reserved trash page — admission then only ever blocks on
        # free SLOTS, never pages.  A smaller explicit pool exercises
        # real paging pressure (admission waits for pages).
        self.num_pages = int(num_pages) if num_pages is not None \
            else self.num_slots * self.pages_per_slot + 1
        if self.num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is trash)")
        self.quantized = bool(quantized)
        # ``dtype`` stays the COMPUTE/reference dtype (what dequantized
        # values and the full-recompute oracle use); ``store_dtype`` is
        # what the page pools hold
        self.dtype = np.dtype(dtype)
        self.store_dtype = np.dtype(np.int8) if self.quantized \
            else self.dtype
        self.scale_dtype = np.dtype(np.float32)

    def pages_for(self, seq_len: int) -> int:
        return max(1, math.ceil(int(seq_len) / self.page_size))

    def page_bytes(self) -> int:
        """Device bytes ONE page costs in one pool — including its
        scale plane when quantized, so capacity math can't hide the
        scale overhead."""
        data = (self.page_size * self.num_heads * self.head_dim
                * self.store_dtype.itemsize)
        if self.quantized:
            data += (self.page_size * self.num_heads
                     * self.scale_dtype.itemsize)
        return data

    def per_page_pool_bytes(self) -> int:
        """Total device bytes one page costs across EVERY pool (k + v,
        all layers, scale planes included) — the unit a fixed byte
        budget is divided by to size ``num_pages``."""
        return 2 * self.num_layers * self.page_bytes()

    def cache_bytes(self) -> int:
        """Total device bytes of the page arrays (k + v, scale pools
        included when quantized)."""
        return self.num_pages * self.per_page_pool_bytes()


class PageAllocator:
    """Host-side free list over page ids 1..num_pages-1 (0 is trash).

    A double free corrupts the pool silently (two slots end up writing
    the same page), so ``free`` detects it via a mirror set and raises
    LOUDLY instead."""

    def __init__(self, num_pages: int):
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._free_set = set(self._free)
        self._lock = threading.Lock()

    @property
    def num_free(self) -> int:
        with self._lock:
            return len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """Take n pages, or None (atomically nothing) when the pool
        cannot cover the request."""
        if n <= 0:
            # guard the n==0 slice below (`self._free[-0:]` is the
            # WHOLE list, not an empty one) — a fully-shared claim
            # legitimately needs zero fresh pages
            return []
        with self._lock:
            if n > len(self._free):
                return None
            taken = self._free[-n:]
            del self._free[-n:]
            self._free_set.difference_update(taken)
            return list(reversed(taken))

    def free(self, pages: Sequence[int]) -> None:
        with self._lock:
            for p in pages:
                p = int(p)
                if p == 0:
                    continue
                if p in self._free_set:
                    raise RuntimeError(
                        f"double free of KV-cache page {p}: the page is "
                        f"already on the free list (refcount/lifecycle "
                        f"bug — a slot release or eviction ran twice)")
                self._free.append(p)
                self._free_set.add(p)


class _PrefixEntry:
    __slots__ = ("page_id", "parent", "tokens", "full", "children",
                 "tick")

    def __init__(self, page_id, parent, tokens, full, tick):
        self.page_id = page_id
        self.parent = parent
        self.tokens = tokens
        self.full = full
        self.children = 0
        self.tick = tick


class PrefixIndex:
    """Exact-content trie over registered (immutable) pages.

    Node key = ``(parent_page_id, tuple(page_tokens))`` — page ids are
    unique while resident, so the chain match is exact and a prompt can
    never hit a page holding different bytes (no hash collisions by
    construction).  Entries record their token content, so the FINAL
    partial page of a prompt can be matched as a token-prefix of a
    registered tail (the consumer then copy-on-writes at its first
    divergent token).  Single-threaded by contract: only the engine
    thread mutates it (admission / release / eviction)."""

    def __init__(self, page_size: int):
        self.page_size = int(page_size)
        self._by_key: Dict[tuple, _PrefixEntry] = {}
        self._children: Dict[int, List[_PrefixEntry]] = {}
        self._by_page: Dict[int, _PrefixEntry] = {}
        self._tick = 0

    def __len__(self) -> int:
        return len(self._by_page)

    def is_registered(self, page_id: int) -> bool:
        return int(page_id) in self._by_page

    def lookup(self, prompt: Sequence[int]) -> Tuple[List[int],
                                                     Optional[int]]:
        """Longest registered prefix of ``prompt``: ``(full_pages,
        partial_page)`` — ordered page ids for every whole matched page
        and, when the REMAINING prompt tail is a token-prefix of a
        registered page's content, that page id (the CoW candidate).
        A partial hit therefore always means the ENTIRE prompt is
        cache-covered."""
        p = self.page_size
        prompt = [int(t) for t in prompt]
        n = len(prompt)
        self._tick += 1
        full: List[int] = []
        parent = 0
        while (len(full) + 1) * p <= n:
            toks = tuple(prompt[len(full) * p:(len(full) + 1) * p])
            e = self._by_key.get((parent, toks))
            if e is None:
                break
            e.tick = self._tick
            full.append(e.page_id)
            parent = e.page_id
        partial = None
        m = n - len(full) * p
        if m > 0:
            tail = tuple(prompt[len(full) * p:])
            for e in self._children.get(parent, ()):
                if len(e.tokens) >= m and e.tokens[:m] == tail:
                    e.tick = self._tick
                    partial = e.page_id
                    break
        return full, partial

    def register(self, pages: Sequence[int], tokens: Sequence[int],
                 on_new) -> int:
        """Register the chain of ``pages`` holding ``tokens`` (page i
        holds tokens[i*p:(i+1)*p]; the last page may be partial).  An
        existing identical entry is adopted as the chain parent and the
        caller's duplicate page is simply not registered (it frees
        normally).  ``on_new(page_id)`` is called for each page the
        index takes a reference on.  Returns newly registered count."""
        p = self.page_size
        tokens = [int(t) for t in tokens]
        parent = 0
        new = 0
        for i, pid in enumerate(pages):
            pid = int(pid)
            toks = tuple(tokens[i * p:(i + 1) * p])
            if not toks or pid == 0:
                break
            existing = self._by_key.get((parent, toks))
            if existing is not None:
                parent = existing.page_id
                if len(toks) < p:
                    break
                continue
            if pid in self._by_page:
                # the page is already registered under another key —
                # never alias one page into two trie positions
                break
            e = _PrefixEntry(pid, parent, toks, len(toks) == p,
                             self._tick)
            self._by_key[(parent, toks)] = e
            self._children.setdefault(parent, []).append(e)
            if parent in self._by_page:
                self._by_page[parent].children += 1
            self._by_page[pid] = e
            on_new(pid)
            new += 1
            if not e.full:
                break
            parent = pid
        return new

    def evict(self, n_pages: int, can_evict, on_evict) -> int:
        """Free up to ``n_pages`` pages by removing least-recently-hit
        CHILDLESS entries whose page ``can_evict(pid)`` approves (only
        the index references it).  Bottom-up by construction: an entry
        with children is never removed, so a freed-and-reused page id
        can never be mistaken for a live chain parent.  O(entries) per
        eviction — fine at host-bookkeeping scale."""
        freed = 0
        while freed < n_pages:
            victims = [e for e in self._by_page.values()
                       if e.children == 0 and can_evict(e.page_id)]
            if not victims:
                break
            e = min(victims, key=lambda v: v.tick)
            self._remove(e)
            on_evict(e.page_id)
            freed += 1
        return freed

    def _remove(self, e: _PrefixEntry) -> None:
        del self._by_key[(e.parent, e.tokens)]
        sibs = self._children[e.parent]
        sibs.remove(e)
        if not sibs:
            del self._children[e.parent]
        if e.parent in self._by_page:
            self._by_page[e.parent].children -= 1
        del self._by_page[e.page_id]


class ClaimInfo:
    """What an admission claim resolved to (prefix-cache accounting)."""

    __slots__ = ("hit_tokens", "full_hits", "partial", "hit_pages",
                 "prompt_pages", "fresh_pages")

    def __init__(self, hit_tokens, full_hits, partial, hit_pages,
                 prompt_pages, fresh_pages):
        self.hit_tokens = hit_tokens      # prompt positions cache-covered
        self.full_hits = full_hits        # whole shared pages
        self.partial = partial            # borrowed a partial tail page
        self.hit_pages = hit_pages        # full_hits + (1 if partial)
        self.prompt_pages = prompt_pages  # ceil(len(prompt)/page)
        self.fresh_pages = fresh_pages    # newly allocated pages


class PagedKVCache:
    """Host bookkeeping (page tables, lengths, refcounts, allocator,
    prefix index) + the device page arrays, which live in ``scope`` so
    Executor.run_persistent can donate them through each decode step."""

    def __init__(self, config: CacheConfig, scope, prefix_cache=True):
        import jax.numpy as jnp

        self.config = config
        self.scope = scope
        # optional per-request tracing hook: ``on_event(slot, name,
        # **attrs)`` fired on cache lifecycle events (cow_swap, evict,
        # register) — the decode engine wires it to the owning
        # request's timeline (observe/request_trace.py); ``slot`` is
        # None for events with no slot owner (evictions during an
        # admission allocation)
        self.on_event = None
        self.allocator = PageAllocator(config.num_pages)
        self.prefix: Optional[PrefixIndex] = \
            PrefixIndex(config.page_size) if prefix_cache else None
        c = config
        # per-slot host mirrors: the scheduler reads/writes these; the
        # device sees them as small per-step i32 feeds
        self.page_table = np.zeros((c.num_slots, c.pages_per_slot),
                                   np.int32)
        self.lengths = np.zeros((c.num_slots,), np.int32)
        self._slot_pages: List[List[int]] = [[] for _ in range(c.num_slots)]
        # every page id a slot holds ONE reference on (table pages +
        # the CoW spare); release decrefs exactly this list
        self._slot_refs: List[List[int]] = [[] for _ in range(c.num_slots)]
        # reserved CoW target for a borrowed partial page (at most one)
        self._cow_spare: List[List[int]] = [[] for _ in range(c.num_slots)]
        self._refs = [0] * c.num_pages
        shape = (c.num_layers, c.num_pages, c.page_size, c.num_heads,
                 c.head_dim)
        scope.set_var(K_PAGES_VAR, jnp.zeros(shape, c.store_dtype))
        scope.set_var(V_PAGES_VAR, jnp.zeros(shape, c.store_dtype))
        # quantized mode: parallel per-page scale pools (one scale per
        # head per position-in-page), plus the freed-page reset queue
        # the scale audit relies on.  ``scale_vars`` also collects any
        # EXTRA scale pools sharing this cache's page ids (the decode
        # engine appends its draft-model scale pools) so resets and
        # audits cover every pool.
        self.scale_vars: List[str] = []
        self._pending_scale_resets: List[int] = []
        # pages installed by a disagg migration, while owned by their
        # admitting slot: page id -> slot.  An installed page is a
        # FRESH page (refcount exactly 1, never index-registered) until
        # its slot releases — debug_check audits exactly that.
        self._migrated_in: Dict[int, int] = {}
        if c.quantized:
            sshape = (c.num_layers, c.num_pages, c.page_size,
                      c.num_heads)
            scope.set_var(K_SCALES_VAR,
                          jnp.full(sshape, SCALE_EPS, c.scale_dtype))
            scope.set_var(V_SCALES_VAR,
                          jnp.full(sshape, SCALE_EPS, c.scale_dtype))
            self.scale_vars = [K_SCALES_VAR, V_SCALES_VAR]

    def state_var_names(self) -> Tuple[str, ...]:
        """Scope names a persistent step must thread (in order): the
        two page pools, plus the scale pools when quantized."""
        names = (K_PAGES_VAR, V_PAGES_VAR)
        if self.config.quantized:
            names += (K_SCALES_VAR, V_SCALES_VAR)
        return names

    def _fire(self, slot, name, **attrs) -> None:
        hook = self.on_event
        if hook is None:
            return
        try:
            hook(slot, name, **attrs)
        except Exception:  # noqa: BLE001 — instrumentation must never
            stat_add("request_trace_errors")  # corrupt cache bookkeeping

    # -- refcounts --------------------------------------------------------
    def _incref(self, pid: int) -> None:
        self._refs[pid] += 1

    def _decref(self, pid: int) -> None:
        r = self._refs[pid] = self._refs[pid] - 1
        if r < 0:
            raise RuntimeError(
                f"KV-cache page {pid} refcount went negative — a "
                f"release/eviction path dropped a reference it never "
                f"held")
        if r == 0:
            self.allocator.free([pid])
            self._migrated_in.pop(pid, None)
            if self.config.quantized:
                # hygiene + auditability: a freed page's scale plane is
                # reset to SCALE_EPS (flushed in one batched device op
                # at the end of the release/claim that freed it).  Not
                # load-bearing for numerics — the write path quantizes
                # each position with its own fresh scale and reads are
                # length-masked — but it makes "this page is free" an
                # observable device-side fact debug_check() can assert.
                self._pending_scale_resets.append(pid)

    def flush_scale_resets(self) -> None:
        """Apply pending freed-page scale resets to every scale pool
        (the cache's own + any engine-registered extras).  Runs in the
        owner thread between step dispatches — eager jax ops, never
        racing a donated in-flight step."""
        if not self._pending_scale_resets:
            return
        import jax.numpy as jnp

        pids = np.asarray(sorted(set(self._pending_scale_resets)),
                          np.int32)
        self._pending_scale_resets = []
        for name in self.scale_vars:
            arr = self.scope.get_var(name)
            self.scope.set_var(
                name, arr.at[:, pids].set(jnp.asarray(
                    SCALE_EPS, arr.dtype)))

    def refcount(self, pid: int) -> int:
        return self._refs[int(pid)]

    @property
    def shared_pages(self) -> int:
        """Pages currently pinned by the prefix index."""
        return len(self.prefix) if self.prefix is not None else 0

    def _alloc_evicting(self, n: int) -> Optional[List[int]]:
        """Allocate n pages, evicting cache-only prefix entries under
        pressure (least-recently-hit, childless first)."""
        pages = self.allocator.alloc(n)
        if pages is not None or self.prefix is None:
            return pages
        short = n - self.allocator.num_free
        evicted = self.prefix.evict(
            short, can_evict=lambda pid: self._refs[pid] == 1,
            on_evict=self._decref)
        if evicted:
            stat_add("decode_prefix_evictions", evicted)
            self._fire(None, "evict", pages=evicted)
        return self.allocator.alloc(n)

    # -- slot lifecycle ---------------------------------------------------
    def claim(self, slot: int, reserve_tokens: int,
              prompt: Optional[Sequence[int]] = None
              ) -> Optional[ClaimInfo]:
        """Reserve pages covering ``reserve_tokens`` positions for the
        slot, sharing every registered prefix page of ``prompt``; None
        when the pool can't cover the FRESH remainder (caller retries
        later).  Shared-aware worst case: ``total - shared_full`` fresh
        pages are taken either way — with a partial borrow one of them
        is held back as the CoW spare, so the later copy-on-write can
        never hit an empty pool."""
        total = self.config.pages_for(reserve_tokens)
        full_hits: List[int] = []
        partial: Optional[int] = None
        if self.prefix is not None and prompt is not None:
            full_hits, partial = self.prefix.lookup(prompt)
        hits = full_hits + ([partial] if partial is not None else [])
        # pin the matched pages BEFORE the eviction-backed allocation:
        # a just-matched childless tail page is index-only (refcount 1)
        # and would otherwise be a legal eviction victim — freed and
        # handed straight back as this claim's "fresh" page, aliasing
        # one physical page under two table roles
        for pid in hits:
            self._incref(pid)
        n_fresh = total - len(full_hits)
        fresh = self._alloc_evicting(n_fresh)
        if fresh is None and partial is not None:
            # drop the partial borrow under pressure: unpinned, its
            # page becomes an eviction candidate again, and the fresh
            # count is unchanged (the borrow traded its CoW spare for
            # a plain page) — so any reservation the submit-time check
            # admitted can still be satisfied instead of deadlocking
            # the queue head behind its own matched page
            self._decref(partial)
            partial = None
            hits = list(full_hits)
            fresh = self._alloc_evicting(n_fresh)
        if fresh is None:
            for pid in hits:
                self._decref(pid)  # still index-pinned: never frees
            return None
        for pid in fresh:
            self._incref(pid)
        table_pages = list(full_hits)
        rest = list(fresh)
        spare: List[int] = []
        if partial is not None:
            spare = [rest.pop(0)]
            table_pages.append(partial)
        table_pages += rest
        self._slot_pages[slot] = table_pages
        self._slot_refs[slot] = hits + fresh
        self._cow_spare[slot] = spare
        row = np.zeros((self.config.pages_per_slot,), np.int32)
        row[:len(table_pages)] = table_pages
        self.page_table[slot] = row
        self.lengths[slot] = 0
        self.flush_scale_resets()  # evictions may have freed pages
        prompt_len = len(prompt) if prompt is not None else 0
        hit_tokens = len(full_hits) * self.config.page_size
        if partial is not None:
            hit_tokens = prompt_len  # partial hit == full prompt cover
        return ClaimInfo(
            hit_tokens=hit_tokens, full_hits=len(full_hits),
            partial=partial is not None,
            hit_pages=len(full_hits) + (1 if partial is not None else 0),
            prompt_pages=self.config.pages_for(max(prompt_len, 1))
            if prompt is not None else 0,
            fresh_pages=len(fresh))

    def release(self, slot: int,
                register_tokens: Optional[Sequence[int]] = None) -> None:
        """Drop the slot's references.  When ``register_tokens`` is
        given (the token content whose K/V the slot's leading pages
        hold), those pages are first registered in the prefix index —
        the index takes its own reference, so registered pages survive
        the release for future prompts to share."""
        # a migrated-in page's owned-fresh invariant ends with its
        # slot: from here it is an ordinary page (registrable in the
        # index, sharable, freeable)
        for pid in self._slot_pages[slot]:
            self._migrated_in.pop(pid, None)
        if register_tokens and self.prefix is not None:
            n_pages = self.config.pages_for(len(register_tokens))
            new = self.prefix.register(
                self._slot_pages[slot][:n_pages], register_tokens,
                on_new=self._incref)
            if new:
                self._fire(slot, "register", pages=new,
                           tokens=len(register_tokens))
        for pid in self._slot_refs[slot]:
            self._decref(pid)
        self._slot_pages[slot] = []
        self._slot_refs[slot] = []
        self._cow_spare[slot] = []
        self.page_table[slot] = 0
        self.lengths[slot] = 0
        self.flush_scale_resets()

    def slot_pages(self, slot: int) -> List[int]:
        return list(self._slot_pages[slot])

    # -- disaggregated-serving page migration -----------------------------
    def export_pages(self, pages: Sequence[int]) -> Dict[str, object]:
        """Gather the given page ids out of EVERY pool this cache
        threads through the persistent step (data pages + scale planes
        when quantized) into fresh device arrays, keyed by pool var
        name.  Must run on the engine thread between step dispatches —
        the gather's operand ordering against the donated pools is then
        guaranteed by jax dispatch order, and its result never aliases
        a pool buffer, so the payload survives the source's next
        step."""
        idx = np.asarray([int(p) for p in pages], np.int32)
        return {name: self.scope.get_var(name)[:, idx]
                for name in self.state_var_names()}

    def install_pages(self, slot: int, export: "KVPageExport") -> None:
        """Scatter a migrated payload into the slot's leading
        ``export.n_pages`` table pages (claimed fresh — a migrated
        admission never prefix-shares, so every destination page is
        solely owned).  Covers every pool the payload carries; records
        ``migrate_pages_total`` / ``migrate_bytes_total`` /
        ``migrate_seconds``.  Engine-thread-only, like every pool
        mutation."""
        import jax.numpy as jnp

        t0 = time.monotonic()
        names = self.state_var_names()
        if set(export.arrays) != set(names):
            raise ValueError(
                f"migration payload pools {sorted(export.arrays)} do "
                f"not match destination pools {sorted(names)} — "
                f"source/destination kv_quant configs disagree")
        if export.page_size != self.config.page_size:
            raise ValueError(
                f"migration payload page_size {export.page_size} != "
                f"destination page_size {self.config.page_size}")
        dst = self._slot_pages[slot][:export.n_pages]
        if len(dst) < export.n_pages:
            raise ValueError(
                f"slot {slot} holds {len(dst)} pages but the payload "
                f"carries {export.n_pages}")
        idx = np.asarray(dst, np.int32)
        for name in names:
            pool = self.scope.get_var(name)
            arr = export.arrays[name]
            want = (pool.shape[0], export.n_pages) + tuple(pool.shape[2:])
            if tuple(arr.shape) != want:
                raise ValueError(
                    f"migration payload {name} shape "
                    f"{tuple(arr.shape)} != expected {want}")
            self.scope.set_var(
                name, pool.at[:, idx].set(jnp.asarray(arr, pool.dtype)))
        for pid in dst:
            self._migrated_in[pid] = slot
        stat_add("migrate_pages_total", export.n_pages)
        stat_add("migrate_bytes_total", export.nbytes)
        stat_time("migrate_seconds", time.monotonic() - t0)
        self._fire(slot, "migrate_install", pages=list(dst),
                   bytes=export.nbytes)

    # -- copy-on-write ----------------------------------------------------
    def writable(self, slot: int, position: int) -> bool:
        pid = int(self.page_table[slot][int(position)
                                        // self.config.page_size])
        if pid == 0:
            return True  # trash absorbs anything
        return self._refs[pid] == 1 and not (
            self.prefix is not None and self.prefix.is_registered(pid))

    def plan_cow(self, slot: int, positions: Sequence[int]
                 ) -> List[Tuple[int, int]]:
        """Make every page covering ``positions`` writable by the slot.
        Shared/registered pages are swapped for the slot's reserved
        spare (falling back to a fresh allocation, which the
        reservation accounting makes unreachable); the page table is
        updated NOW and the returned ``(src, dst)`` copies MUST be
        performed on-device by the caller before its next write
        dispatch."""
        plans: List[Tuple[int, int]] = []
        p = self.config.page_size
        for idx in sorted({int(pos) // p for pos in positions}):
            pid = int(self.page_table[slot][idx])
            if pid == 0 or self.writable(slot, idx * p):
                continue
            if self._cow_spare[slot]:
                dst = self._cow_spare[slot].pop()
            else:
                got = self._alloc_evicting(1)
                if got is None:
                    raise CacheExhaustedError(
                        f"copy-on-write for slot {slot} page index "
                        f"{idx} found an empty pool — the shared-aware "
                        f"reservation accounting is broken (a spare "
                        f"page should have been held at admission)")
                dst = got[0]
                self._incref(dst)
                self._slot_refs[slot].append(dst)
            self.page_table[slot][idx] = dst
            self._slot_pages[slot][idx] = dst
            self._slot_refs[slot].remove(pid)
            # shared pages are held by the index and/or other slots, so
            # this decref can never free the page mid-copy
            self._decref(pid)
            self._fire(slot, "cow_swap", src=pid, dst=dst,
                       page_index=idx)
            plans.append((pid, dst))
        return plans

    def write_coords(self, slot: int):
        """(page_id, offset) for the NEXT position of the slot."""
        t = int(self.lengths[slot])
        return (int(self.page_table[slot][t // self.config.page_size]),
                t % self.config.page_size)

    def arrays(self):
        return (self.scope.get_var(K_PAGES_VAR),
                self.scope.get_var(V_PAGES_VAR))

    # -- integrity audit (chaos tests / debugging) ------------------------
    def debug_check(self) -> None:
        """Assert the refcount/free-list/index books balance: every
        page is exactly one of {free, referenced}, and each page's
        refcount equals index-pin + per-slot references.  When the
        cache is quantized the audit extends to scale-pool/page-pool
        agreement: every scale in every pool is finite, and every FREE
        page's scale plane is reset to ``SCALE_EPS`` (in every pool —
        the cache's own and any engine-registered draft pools).  Raises
        AssertionError with the discrepancy."""
        self.flush_scale_resets()
        want = [0] * self.config.num_pages
        for slot_refs in self._slot_refs:
            for pid in slot_refs:
                want[pid] += 1
        if self.prefix is not None:
            for pid in list(self.prefix._by_page):
                want[pid] += 1
        with self.allocator._lock:
            free = set(self.allocator._free)
            assert len(free) == len(self.allocator._free), \
                "free list holds duplicate pages"
        for pid in range(1, self.config.num_pages):
            assert self._refs[pid] == want[pid], (
                f"page {pid}: refcount {self._refs[pid]} != "
                f"{want[pid]} held references")
            in_free = pid in free
            assert in_free == (self._refs[pid] == 0), (
                f"page {pid}: refcount {self._refs[pid]} but "
                f"{'on' if in_free else 'not on'} the free list")
        # migrated-in pages (disagg): while owned by their admitting
        # slot an installed page is FRESH — exactly one reference (the
        # slot's), never pinned by the prefix index, and (quantized)
        # carrying the live scale plane the source wrote
        for pid, slot in self._migrated_in.items():
            assert self._refs[pid] == 1, (
                f"migrated-in page {pid} (slot {slot}): refcount "
                f"{self._refs[pid]} != 1 — a migrated page leaked into "
                f"sharing before its slot released")
            assert self.prefix is None or \
                not self.prefix.is_registered(pid), (
                    f"migrated-in page {pid} (slot {slot}) is "
                    f"registered in the prefix index while still "
                    f"slot-owned")
            assert pid in self._slot_pages[slot], (
                f"migrated-in page {pid} not in slot {slot}'s table")
        if self.config.quantized and self._migrated_in:
            mig_idx = np.asarray(sorted(self._migrated_in), np.int32)
            for name in self.scale_vars:
                plane = np.asarray(self.scope.get_var(name))[:, mig_idx]
                assert np.isfinite(plane).all() and (plane > 0).all(), (
                    f"scale pool {name}: migrated-in pages "
                    f"{mig_idx.tolist()} hold non-finite/non-positive "
                    f"scales — the migration dropped a scale plane")
        if not self.config.quantized:
            return
        free_idx = np.asarray(sorted(free), np.int32)
        for name in self.scale_vars:
            arr = np.asarray(self.scope.get_var(name))
            assert np.isfinite(arr).all(), (
                f"scale pool {name} holds non-finite scales — a write "
                f"path stored an unclamped/overflowed scale")
            assert (arr > 0).all(), (
                f"scale pool {name} holds non-positive scales")
            if len(free_idx):
                stale = arr[:, free_idx]
                assert np.all(stale == np.float32(SCALE_EPS)), (
                    f"scale pool {name}: freed pages "
                    f"{free_idx[np.argwhere(np.any(stale != np.float32(SCALE_EPS), axis=(0, 2, 3)))].ravel().tolist()} "
                    f"kept live scales — a free path skipped the reset")


# -- pure jit-side helpers (operate on the page arrays functionally) ------

def scatter_token_layer(pages, layer: int, val, page_id, offset):
    """Write one new position per row: val [R, H, D] lands at
    (layer, page_id[r], offset[r]) — dead rows pass page 0 (trash)."""
    return pages.at[layer, page_id, offset].set(
        val.astype(pages.dtype))


def scatter_prompt_layer(pages, layer: int, val, page_ids):
    """Write a whole prompt's positions for one slot: val
    [n_pages*page, H, D] (padded to a page multiple) is stored page-
    wholesale into ``page_ids`` [n_pages]."""
    n = page_ids.shape[0]
    page = pages.shape[2]
    v = val.reshape(n, page, val.shape[1], val.shape[2])
    return pages.at[layer, page_ids].set(v.astype(pages.dtype))


def quantize_kv(val):
    """Symmetric int8 quantization of K/V values at per-position
    per-head granularity: ``val [..., H, D] -> (q int8 [..., H, D],
    scale f32 [..., H])`` with the scale clamped PER SLICE (an all-zero
    head stores exact zeros instead of dividing by ~0 — the
    quant_ops._abs_max per-slice-clamp contract).  Pure and
    position-local, so every write path (single-token decode, chunked
    prefill rows, whole-prompt prefill, speculative re-writes) produces
    IDENTICAL stored bytes for identical values — the order-independence
    the bitwise spec/chunk composition tests pin."""
    import jax.numpy as jnp

    v = val.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(v), axis=-1) / KV_QMAX,
                        SCALE_EPS)
    q = jnp.clip(jnp.round(v / scale[..., None]), -KV_QMAX, KV_QMAX) \
        .astype(jnp.int8)
    return q, scale


def dequantize_kv(q, scale, dtype):
    """Inverse of :func:`quantize_kv` (broadcast the per-position
    per-head scale back over head_dim)."""
    import jax.numpy as jnp

    return (q.astype(jnp.float32)
            * scale.astype(jnp.float32)[..., None]).astype(dtype)


def write_token_layer(pages, scales, layer: int, val, page_id, offset):
    """Quantization-aware :func:`scatter_token_layer`: returns
    ``(pages, scales)``.  ``scales=None`` is the unquantized path
    (pages store ``val`` directly, scales pass through)."""
    if scales is None:
        return scatter_token_layer(pages, layer, val, page_id,
                                   offset), None
    q, s = quantize_kv(val)
    return (pages.at[layer, page_id, offset].set(q),
            scales.at[layer, page_id, offset].set(
                s.astype(scales.dtype)))


def write_prompt_layer(pages, scales, layer: int, val, page_ids):
    """Quantization-aware :func:`scatter_prompt_layer`: returns
    ``(pages, scales)``; page-wholesale like the unquantized path, but
    each position quantizes independently — bitwise-identical bytes to
    the per-row chunked path writing the same values."""
    if scales is None:
        return scatter_prompt_layer(pages, layer, val, page_ids), None
    n = page_ids.shape[0]
    page = pages.shape[2]
    v = val.reshape(n, page, val.shape[1], val.shape[2])
    q, s = quantize_kv(v)
    return (pages.at[layer, page_ids].set(q),
            scales.at[layer, page_ids].set(s.astype(scales.dtype)))
