"""`paddle.version` parity (reference python/paddle/version.py, build-time
generated there; static here)."""
full_version = "0.3.0"
major = "0"
minor = "3"
patch = "0"
rc = "0"
istaged = True
commit = "tpu-native"
with_mkl = "OFF"  # XLA is the single backend


def show():
    print(f"full_version: {full_version}")
    print(f"commit: {commit}")


def mkl():
    return with_mkl
