"""Inference engine: compile-once predictor over saved inference models.

Role parity: reference paddle/fluid/inference/ — AnalysisConfig +
AnalysisPredictor (api/analysis_predictor.h:82, Run:120, ZeroCopyRun:165,
OptimizeInferenceProgram:188).  TPU-native redesign: the reference's
analysis pass pipeline (fusion passes, TRT/Lite subgraph capture) is
XLA's job — "optimize" = compile the whole pruned program once per feed
shape; `Run` is one cached XLA executable call.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Union

import numpy as np


class Config:
    """AnalysisConfig parity: where the model lives + execution knobs."""

    def __init__(self, model_dir: Optional[str] = None,
                 params_file: Optional[str] = None):
        self._model_dir = model_dir
        self._model_filename = None
        self._params_filename = params_file
        self._device_id = 0
        self._use_tpu = True

    def set_model(self, model_dir: str, params_file: Optional[str] = None):
        self._model_dir = model_dir
        self._params_filename = params_file

    def model_dir(self) -> Optional[str]:
        return self._model_dir

    def enable_tpu(self, device_id: int = 0):
        self._use_tpu = True
        self._device_id = device_id

    def disable_gpu(self):  # reference-API shim: CPU fallback
        self._use_tpu = False

    # reference knobs that are XLA's job: accepted, recorded, no-op
    def switch_ir_optim(self, enable: bool = True):
        pass

    def enable_memory_optim(self):
        pass


class Predictor:
    """Compile-once server for a saved inference model.

    Reference AnalysisPredictor: load program+params, run analysis passes,
    execute with NaiveExecutor.  Here: load program+params, let the
    Executor's compile cache hold one XLA executable per feed-shape
    bucket, run with zero per-step recompilation.
    """

    def __init__(self, config: Union[Config, str]):
        from ..fluid.io import load_inference_model
        from ..framework import Executor, Scope
        from ..framework.place import CPUPlace, TPUPlace, _default_place

        if isinstance(config, str):
            config = Config(config)
        if config.model_dir() is None:
            raise ValueError("Config has no model dir; call set_model()")
        self._config = config
        self._scope = Scope()
        place = _default_place() if config._use_tpu else CPUPlace()
        self._exe = Executor(place)
        # load into THIS predictor's scope — never clobber live variables
        # in the process-global scope
        from ..framework.scope import _switch_scope

        old = _switch_scope(self._scope)
        try:
            program, feed_names, fetch_targets = load_inference_model(
                config.model_dir(), self._exe,
                model_filename=config._model_filename,
                params_filename=config._params_filename)
        finally:
            _switch_scope(old)
        self._program = program
        self._feed_names = list(feed_names)
        self._fetch_targets = fetch_targets

    # -- reference API ----------------------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._feed_names)

    def get_output_names(self) -> List[str]:
        return [v.name for v in self._fetch_targets]

    def run(self, feeds: Union[Dict[str, np.ndarray],
                               Sequence[np.ndarray]]):
        """One inference call; compiles on first use per feed shape."""
        if not isinstance(feeds, dict):
            if len(feeds) != len(self._feed_names):
                raise ValueError(
                    f"expected {len(self._feed_names)} inputs "
                    f"{self._feed_names}, got {len(feeds)}")
            feeds = dict(zip(self._feed_names, feeds))
        missing = [n for n in self._feed_names if n not in feeds]
        if missing:
            raise KeyError(f"missing inputs: {missing}")
        return self._exe.run(self._program, feed=feeds,
                             fetch_list=self._fetch_targets,
                             scope=self._scope)


def create_predictor(config: Config) -> Predictor:
    """Reference paddle_infer.create_predictor."""
    return Predictor(config)
