"""Executor: compiles whole program blocks to single XLA computations.

Role parity: reference Executor (paddle/fluid/framework/executor.cc:180,
python/paddle/fluid/executor.py:913) — same ``run(program, feed,
fetch_list)`` contract.  TPU-native redesign (SURVEY.md §7): instead of the
reference's per-op interpreter hot loop (executor.cc:474-480, one scope
lookup + InferShape + kernel launch per op per step), the block is traced
ONCE through the lowering registry into a jax function

    (feeds, state, rng) -> (fetches, new_state, rng')

jitted with the state donated (in-place param update semantics), cached by
(program fingerprint, feed spec, fetch list, state spec).  Per-step cost is
one XLA executable launch; scheduling/fusion/memory are XLA's job (this
collapses the reference's ParallelExecutor/SSA-graph machinery,
parallel_executor.cc:504).

Pipelined dispatch (FLAGS_max_inflight_steps, default 2): ``run`` returns
a lazy :class:`StepHandle` instead of forcing a device→host sync per
step; up to N steps stay in flight and dispatch backpressures by
draining the oldest.  NaN-scan, FLAGS_benchmark sync, and StepTimer
accounting happen at window-drain points (``Executor.drain``, handle
reads, backpressure, ``close``, checkpoint snapshots) so telemetry only
ever reflects completed steps.  ``FLAGS_max_inflight_steps=0`` restores
the legacy synchronous fetch path.
"""
from __future__ import annotations

import collections
import logging
import threading
import weakref
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import dtypes
from .lowering import PSEUDO_OPS, LoweringContext, get_lowering
from .place import CPUPlace, Place, _default_place
from .program import Program, Variable, default_main_program
from .scope import (PackedParamRef, Scope, StackedParamRef, global_scope,
                    is_device_array as _is_device_array)

logger = logging.getLogger(__name__)

RNG_VAR = "@RNG_KEY@"
NAN_FLAGS_VAR = "@NAN_FLAGS@"

# ops executed host-side by an interpretive walk (file I/O cannot live
# inside a compiled XLA computation); reference runs these through the
# same C++ executor hot loop (save_op.cc:85, load_op.cc:67)
HOST_OPS = {"save", "load", "save_combine", "load_combine"}


def _make_scan_fn(step_fn, state_mut, state_const, state_out, feed_names,
                  scan_steps):
    """Wrap a single-step `step_fn(env, rng) -> (fetches, new_rng)` into the
    K-step lax.scan harness shared by the single-device and sharded paths.

    scan_steps=None: feeds are stacked with a leading step dim (scan xs).
    scan_steps=K: single-step feeds reused every iteration (xs=None).
    Write-only persistent outputs (not read back each step) are stacked and
    the last step's value wins.
    """
    from jax import lax

    mut_set = set(state_mut)
    write_only = tuple(n for n in state_out if n not in mut_set)

    def fn(feed_stacks, mut_vals, const_vals, rng):
        def body(carry, xs):
            mut, key = carry
            env = {}
            env.update(zip(state_mut, mut))
            env.update(zip(state_const, const_vals))
            env.update(zip(feed_names, feed_stacks if xs is None else xs))
            fetches, new_key = step_fn(env, key)
            wo = tuple(env[n] for n in write_only)
            new_mut = tuple(env[n] for n in state_mut)
            return (new_mut, new_key), (fetches, wo)

        xs = None if scan_steps is not None else feed_stacks
        (final_mut, final_rng), (fetch_stacks, wo_stacks) = lax.scan(
            body, (mut_vals, rng), xs, length=scan_steps)
        final = dict(zip(state_mut, final_mut))
        final.update({n: s[-1] for n, s in zip(write_only, wo_stacks)})
        new_state = tuple(final[n] for n in state_out)
        return fetch_stacks, new_state, final_rng

    return fn


@dataclass
class _Compiled:
    fn: object
    feed_names: Tuple[str, ...]
    state_mut: Tuple[str, ...]  # read & overwritten -> donated buffers
    state_const: Tuple[str, ...]  # read-only state
    state_out: Tuple[str, ...]
    fetch_names: Tuple[str, ...]
    uses_rng: bool
    # multi-process SPMD: converts process-local feed/state values into
    # global jax.Arrays over the mesh before the executable call
    globalize: object = None
    # FLAGS_check_nan_inf: (op type, build site) per scanned op, parallel
    # to the extra NAN_FLAGS fetch; nan_scan records that the sentinel
    # fetch was appended even when the op list is empty
    nan_ops: Tuple = ()
    nan_scan: bool = False
    # pipeline v3: PackPlan sharding params+opt state per stage; run()
    # calls its ensure_packed before assembling the state tuple
    pipeline_pack: object = None
    n_calls: int = 0
    # step telemetry (observe/step_stats.py): static per-step FLOPs
    # (hapi/model_stat.py accounting) and allreduce payload bytes
    flops_per_step: float = 0.0
    allreduce_bytes: int = 0
    # XLA introspection (observe/xla_stats.py): the raw jax.jit callable
    # for the AOT lower+compile at first dispatch, and the device the
    # mesh-less path pins execution to (None when a mesh owns placement)
    jit_fn: object = None
    jit_device: object = None
    # step-phase attribution (observe/phases.py): the compile-time cost
    # model — predicted compute seconds + per-collective exposed/hidden
    # ledger — consulted at each window drain; None when the plane is
    # off or the model could not price this program
    phase_plan: object = None


class _InflightStep:
    """One dispatched-but-not-yet-synced executor step in the window."""

    __slots__ = ("sync_refs", "nan_flags", "nan_ops", "t_dispatch",
                 "steps", "examples", "compiled", "flops_per_step",
                 "allreduce_bytes", "host_s", "phase_plan", "drained")

    def __init__(self, sync_refs, nan_flags, nan_ops, t_dispatch, steps,
                 examples, compiled, flops_per_step, allreduce_bytes,
                 host_s=0.0, phase_plan=None):
        self.sync_refs = sync_refs          # fetch device arrays (never
        self.nan_flags = nan_flags          # donated, safe to hold)
        self.nan_ops = nan_ops
        self.t_dispatch = t_dispatch
        self.steps = steps
        self.examples = examples
        self.compiled = compiled
        self.flops_per_step = flops_per_step
        self.allreduce_bytes = allreduce_bytes
        # phase attribution (observe/phases.py): dispatch-side host
        # seconds (pass pipeline + analysis + feed prep, backpressure
        # excluded) and the entry's compile-time cost model
        self.host_s = host_s
        self.phase_plan = phase_plan
        self.drained = False


class _InflightWindow:
    """Bounded FIFO of in-flight pipelined steps (FLAGS_max_inflight_steps).

    Dispatch pushes; ``backpressure`` drains the oldest entries until the
    window is under the cap, so ahead-of-device Python can never pile up
    unbounded live fetch buffers.  A drain is the truth point moved out
    of the dispatch path: it blocks until the step's fetches are ready
    (``fetch_sync_seconds`` histogram + ``dispatch/drain`` span), feeds
    the StepTimer with the inter-drain wall time (== real per-step loop
    time in steady state), checks the NaN-scan flags, and updates the
    ``executor_inflight_steps`` gauge.  Entries hold only fetch buffers —
    never scope state, which a later step may donate."""

    def __init__(self):
        self._entries = collections.deque()
        self._lock = threading.RLock()
        self._last_drain: Optional[float] = None
        # a drain failure (XLA runtime error, NaN-scan raise) that was
        # hit on a NON-raising path (StepTimer.summary's telemetry
        # drain) is parked here and re-raised at the next raising drain
        # point — a drained entry is popped, so without this the error
        # would be consumed forever
        self._failed: Optional[BaseException] = None

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def push(self, entry: _InflightStep):
        with self._lock:
            self._entries.append(entry)
        _update_inflight_gauge()

    def _raise_pending(self):
        if self._failed is not None:
            e, self._failed = self._failed, None
            raise e

    def backpressure(self, cap: int):
        """Block until fewer than ``cap`` steps are in flight."""
        with self._lock:
            self._raise_pending()
            while len(self._entries) >= max(cap, 1):
                self._drain_oldest()

    def drain_through(self, entry: _InflightStep):
        """Drain (in order) every entry up to and including ``entry``."""
        with self._lock:
            self._raise_pending()
            while not entry.drained and self._entries:
                self._drain_oldest()

    def drain_all(self, raise_errors: bool = True):
        """Drain everything.  ``raise_errors=False`` (the telemetry
        read path) parks a drain failure in ``_failed`` instead of
        raising, so the error is delivered at the next raising drain
        point rather than swallowed."""
        with self._lock:
            if raise_errors:
                self._raise_pending()
            while self._entries:
                self._drain_oldest(raise_errors=raise_errors)

    def _drain_oldest(self, raise_errors: bool = True):
        import time as _time

        import jax

        from ..monitor import stat_add
        from ..observe import flight as _flight
        from ..observe import step_stats as _step_stats
        from ..observe import tracer as otrace
        from ..observe.histogram import stat_time

        # the entry stays IN the deque while its drain blocks (popped in
        # the finally): a hung device call is then visible to the stall
        # watchdog's lock-free sample (observe/health.py) as a live
        # window entry whose age keeps growing — popping first would
        # make the one step that matters invisible mid-hang
        e = self._entries[0]
        try:
            t0 = _time.perf_counter()
            try:
                with otrace.span("dispatch/drain", steps=e.steps,
                                 n=len(e.sync_refs)):
                    jax.block_until_ready(e.sync_refs)
                    if e.nan_flags is not None:
                        jax.block_until_ready(e.nan_flags)
            except BaseException as err:
                # a drain that RAISES is still progress (the process is
                # failing, not hung): advance the drained counter so the
                # stall watchdog never mistakes a delivered error for a
                # stall
                stat_add("executor_steps_drained", e.steps)
                _flight.record("executor/drain_error", steps=e.steps,
                               error=f"{type(err).__name__}: {err}"[:500])
                if raise_errors:
                    raise
                if self._failed is None:
                    self._failed = err
                return
            stat_add("executor_steps_drained", e.steps)
        finally:
            self._entries.popleft()
            e.drained = True
            _update_inflight_gauge()
        now = _time.perf_counter()
        stat_time("fetch_sync_seconds", now - t0)
        # inter-drain wall time: in a steady pipelined loop drains are
        # forced by backpressure once per dispatch, so this IS the
        # training loop's per-step period (input wait included) — the
        # number that says how fast the LOOP is, not just the chip
        start = e.t_dispatch if self._last_drain is None \
            else max(self._last_drain, e.t_dispatch)
        self._last_drain = now
        _step_stats.step_timer().record_run(
            max(now - start, 0.0), steps=e.steps, examples=e.examples,
            compiled=e.compiled, flops_per_step=e.flops_per_step,
            allreduce_bytes_per_step=e.allreduce_bytes)
        # step-phase attribution + anomaly trigger (observe/phases.py,
        # observe/profiler_capture.py): the drain is THE truth point —
        # wall = inter-drain loop period, sync = this drain's block,
        # host = the dispatch-side host seconds carried on the entry
        from ..observe import phases as _phases
        from ..observe import profiler_capture as _prof

        _phases.on_step_drained(
            wall_s=max(now - start, 0.0), sync_s=now - t0, host_s=e.host_s,
            steps=e.steps, plan=e.phase_plan, compiled=e.compiled)
        _prof.on_step_drained(max(now - start, 0.0) / max(e.steps, 1),
                              compiled=e.compiled)
        if e.nan_flags is not None:
            try:
                _raise_on_nan(np.asarray(e.nan_flags), e.nan_ops)
            except BaseException as err:
                _flight.record("executor/nan_detected",
                               error=f"{err}"[:500])
                if raise_errors:
                    raise
                if self._failed is None:
                    self._failed = err


def _raise_on_nan(nan_flags, nan_ops):
    """Host-side check of the per-op finite flags fetched by the
    nan-scan (shared by the sync path and the window drain)."""
    nan_flags = nan_flags.astype(bool)
    if not nan_ops:
        return
    ok = nan_flags.reshape(-1, len(nan_ops)).all(axis=0)
    if not ok.all():
        i = int(np.argmin(ok))
        op_type, site = nan_ops[i]
        raise RuntimeError(
            f"FLAGS_check_nan_inf: op {op_type!r} (built at "
            f"{site}) produced NaN/Inf (op #{i} of the compiled "
            f"block)")


class StepHandle(list):
    """Lazy fetch list of one pipelined ``Executor.run``/``run_steps``.

    A ``list`` subclass so every existing consumer keeps working —
    indexing, iteration, unpacking, ``len`` — but the device→host sync
    is deferred: items start as jax device arrays and materialize on
    access.  With ``materialize=True`` (the ``run(return_numpy=True)``
    contract) ``handle[i]`` returns a cached ``np.ndarray``; reading any
    item first drains the executor's in-flight window through this step
    (telemetry + NaN-scan fire there).  ``numpy()`` materializes
    everything; ``block_until_ready()`` syncs without converting."""

    def __init__(self, fetches, window=None, entry=None, materialize=True):
        list.__init__(self, fetches)
        self._window = window
        self._entry = entry
        self._materialize = materialize

    def block_until_ready(self):
        """Wait for this step (and every older in-flight step) to
        complete on device; no host transfer."""
        if self._window is not None and self._entry is not None:
            self._window.drain_through(self._entry)
        else:
            import jax

            jax.block_until_ready([v for v in list.__iter__(self)
                                   if _is_jax_array(v)])
        return self

    def numpy(self):
        """Materialize every fetch to host numpy (the one sync point);
        returns a plain list."""
        from ..observe import tracer as otrace

        self.block_until_ready()
        with otrace.span("executor/fetch", n=list.__len__(self)):
            out = []
            for i in range(list.__len__(self)):
                v = list.__getitem__(self, i)
                if not isinstance(v, np.ndarray):
                    v = np.asarray(v)
                    if self._materialize:
                        list.__setitem__(self, i, v)
                out.append(v)
            return out

    def device_arrays(self):
        """The raw stored values, no sync (device arrays until the item
        has been materialized through access)."""
        return list(list.__iter__(self))

    def _resolve(self, i):
        v = list.__getitem__(self, i)
        if self._materialize and not isinstance(v, np.ndarray):
            from ..observe import tracer as otrace

            self.block_until_ready()
            with otrace.span("executor/fetch", n=1):
                v = np.asarray(v)
            list.__setitem__(self, i, v)
        return v

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return [self._resolve(i)
                    for i in range(*idx.indices(list.__len__(self)))]
        return self._resolve(idx)

    def __iter__(self):
        for i in range(list.__len__(self)):
            yield self._resolve(i)

    def __array__(self, dtype=None, copy=None):
        arr = np.asarray(self.numpy())
        return arr.astype(dtype) if dtype is not None else arr


# every constructed Executor, for the process-wide drain points (ckpt
# snapshot, StepTimer.summary): a checkpoint must capture a quiescent
# state and telemetry reads must reflect completed steps
_LIVE_EXECUTORS: "weakref.WeakSet[Executor]" = weakref.WeakSet()

# thread id -> perf_counter start of an in-flight FIRST executable call
# (trace + XLA compile).  Sampled lock-free by the stall watchdog
# (observe/health.py): a legitimate multi-minute compile must not read
# as a hung device step, so the watchdog scales its timeout while one
# is active (GIL-atomic dict set/del; telemetry only)
_ACTIVE_COMPILES: Dict[int, float] = {}


def _update_inflight_gauge():
    """executor_inflight_steps = TOTAL in-flight steps across every live
    Executor (a per-window write would make the single process gauge
    flap between unrelated executors).  Reads other windows' deque
    lengths without their locks: len() is GIL-atomic and this is a
    gauge, not an invariant."""
    from ..monitor import stat_set

    try:
        total = sum(len(exe._window._entries)
                    for exe in list(_LIVE_EXECUTORS))
    except RuntimeError:  # WeakSet mutated by a concurrent construction
        return            # telemetry only: the next push/drain re-writes
    stat_set("executor_inflight_steps", total)


def drain_all(raise_errors: bool = True):
    """Drain the in-flight window of every live Executor (the process-
    wide quiescence point: ckpt snapshots and telemetry summaries call
    this so they only ever observe completed steps).  With
    ``raise_errors=False`` (telemetry reads) a drain failure is parked
    on its window and re-raised at the next raising drain point instead
    of being lost."""
    for exe in list(_LIVE_EXECUTORS):
        exe._window.drain_all(raise_errors=raise_errors)


def quiesce_all(raise_errors: bool = True):
    """Process-wide quiescence for the elastic supervisor: drain every
    live Executor's in-flight window AND every pending async checkpoint
    save, so the next restore observes only completed steps and
    committed (or cleanly failed) checkpoints.  ``raise_errors=False``
    parks drain failures for the next raising drain point — a failed
    attempt's own exception is already being handled."""
    drain_all(raise_errors=raise_errors)
    try:
        from ..ckpt import wait_all as _ckpt_wait_all

        _ckpt_wait_all(raise_errors=raise_errors)
    except ImportError:  # pragma: no cover - partial installs
        pass


def close_all() -> int:
    """Re-init hook for topology changes: close every live Executor
    (drains its window, then drops all its compiled-program caches) so
    a rebuild on a NEW device mesh starts from a clean slate instead
    of reusing executables keyed to the dead topology.  Returns the
    number of executors closed."""
    n = 0
    for exe in list(_LIVE_EXECUTORS):
        try:
            exe.close()
        except Exception:  # noqa: BLE001 - a failing drain on a dying
            pass           # topology must not block the re-init
        _LIVE_EXECUTORS.discard(exe)
        n += 1
    _update_inflight_gauge()
    return n


_threefry_partitionable_applied = False


def _maybe_enable_partitionable_threefry():
    """Switch jax to the partitionable threefry implementation (the
    modern default upstream).  The legacy implementation generates
    DIFFERENT bits when XLA shards the consumer of a random op — a
    dropout mask inside the tensor-parallel GSPMD executable would
    silently differ from the same program's replicated run (repro:
    bernoulli under jit with a dp-sharded consumer output), breaking
    the tp-vs-oracle loss-parity contract.  Partitionable threefry's
    bit-stream is sharding-invariant, so every path — single-device,
    shard_map dp, GSPMD tp — draws identical values for identical
    keys.  Applied process-wide at the first Executor construction:
    consistency REQUIRES one mode everywhere."""
    global _threefry_partitionable_applied

    if _threefry_partitionable_applied:
        return
    from .jax_compat import update_config

    if update_config("jax_threefry_partitionable", True):
        _threefry_partitionable_applied = True


_compile_cache_dir_applied: Optional[str] = None


def _maybe_enable_compile_cache():
    """FLAGS_compile_cache_dir -> jax persistent compilation cache
    (guarded via jax_compat: a jax without the knob is a silent no-op).
    Re-checked per Executor construction so setting the flag after
    import still takes effect."""
    global _compile_cache_dir_applied

    from . import flags

    d = flags.flag("compile_cache_dir")
    if not d or d == _compile_cache_dir_applied:
        return
    from ..monitor import stat_add
    from .jax_compat import update_config

    if update_config("jax_compilation_cache_dir", d):
        _compile_cache_dir_applied = d
        stat_add("executor_compile_cache_dir_set")


def _block_written(program, block_idx: int) -> set:
    """All names written anywhere inside a block (incl. nested blocks)."""
    sub = program.blocks[block_idx]
    out: set = set()
    for sop in sub.ops:
        out.update(sop.output_arg_names())
        for aname in ("sub_block", "sub_block_t", "sub_block_f"):
            if sop.has_attr(aname):
                out |= _block_written(program, int(sop.attr(aname)))
    return out


def _ctrl_attr_reads(program, op) -> List[str]:
    """cond_pair branch-output names that are NOT produced inside the
    branch (a branch returning an unchanged outer var / captured const):
    the lowering reads them from the env, so they are external reads."""
    reads: List[str] = []
    if op.type == "cond_pair":
        for aname, sb in (("t_outs", "sub_block_t"),
                          ("f_outs", "sub_block_f")):
            written = _block_written(program, int(op.attr(sb)))
            for n in (op.attr(aname, []) or []):
                if n not in written:
                    reads.append(n)
    return reads


def _sub_external_reads(program, block_idx: int) -> List[str]:
    """Names a sub-block reads from its surroundings (closures for the
    lax.while_loop/lax.cond lowering)."""
    sub = program.blocks[block_idx]
    local_written: set = set()
    ext: List[str] = []
    for sop in sub.ops:
        for n in sop.input_arg_names() + _ctrl_attr_reads(program, sop):
            if n not in local_written and n not in ext:
                ext.append(n)
        for aname in ("sub_block", "sub_block_t", "sub_block_f"):
            if sop.has_attr(aname):
                for n in _sub_external_reads(program, int(sop.attr(aname))):
                    if n not in local_written and n not in ext:
                        ext.append(n)
        local_written.update(sop.output_arg_names())
    return ext


# ops whose effect is not visible through their outputs (p2p send/recv
# pairs match POSITIONALLY per ring, so dropping either end corrupts the
# pairing; barrier is a rendezvous; print emits a host debug callback) —
# the pass-pipeline DCE must never slice them away
SIDE_EFFECT_OPS = {"send_v2", "partial_send", "recv_v2", "partial_recv",
                   "barrier", "print"}

# communication ops: each lowering gets its own tracer span with
# payload bytes + dtype args (observe/tracer.py), and the allreduce
# subset feeds the StepTimer's bytes/step accounting
COLLECTIVE_OPS = {"c_allreduce_sum", "c_allreduce_max", "c_allreduce_min",
                  "c_allreduce_prod", "allreduce", "mp_allreduce_sum",
                  "c_broadcast", "c_allgather", "c_reducescatter",
                  "c_reduce_sum", "c_reduce_max", "c_reduce_min",
                  "c_scatter", "c_concat", "c_split", "c_shard_slice",
                  "send_v2", "partial_send", "recv_v2", "partial_recv",
                  "barrier"}
_ALLREDUCE_OPS = {"c_allreduce_sum", "c_allreduce_max", "c_allreduce_min",
                  "c_allreduce_prod", "allreduce", "mp_allreduce_sum"}


def _collective_span_args(env, op, mesh=None):
    """bytes/dtype args for a collective's tracer span, read off the
    traced input value (static shapes at trace time).

    Tensor-parallel programs (GSPMD path): a grad collective carrying
    the ShardingPropagationPass's ``__tp_spec__`` stamp reports the
    dp-axis payload its reduce actually moves — the mp-SHARD bytes,
    with an explicit ``axes`` arg — because the grad stays mp-sharded
    through its dp sum (the acceptance telemetry for "grad allreduce
    over the dp axis only")."""
    names = op.input_arg_names()
    v = env.get(names[0]) if names else None
    if v is None or not hasattr(v, "shape") or not hasattr(v, "dtype"):
        return {"var": names[0] if names else ""}
    n = 1
    for s in v.shape:
        n *= int(s)
    nbytes = n * np.dtype(v.dtype).itemsize
    args = {"bytes": nbytes, "dtype": str(v.dtype),
            "var": names[0] if names else ""}
    from .passes import TP_SPEC_ATTR

    tp_spec = op.attr(TP_SPEC_ATTR, None)
    if tp_spec and mesh is not None and "mp" in mesh.axis_names:
        if "mp" in str(tp_spec).split(","):
            args["bytes"] = nbytes // int(mesh.shape["mp"])
        args["axes"] = "dp"
        args["tp_spec"] = str(tp_spec)
    return args


def _program_allreduce_bytes(block, op_list) -> int:
    """Static allreduce payload per step, from the post-pass op stream
    (so fused buckets count once at their coalesced size).  A
    LayerScanPass-stacked collective moves ``__layer_stack__`` x its
    var's declared per-layer bytes — the stack axis is a runtime
    artifact the var metadata does not carry."""
    from .passes import LAYER_STACK_ATTR

    total = 0
    for op in op_list:
        if op.type not in _ALLREDUCE_OPS:
            continue
        names = op.input_arg_names()
        var = block._find_var_recursive(names[0]) if names else None
        if var is None or not var.shape or any(int(s) <= 0 for s in var.shape):
            continue
        try:
            itemsize = np.dtype(dtypes.to_np(var.dtype)).itemsize
        except (KeyError, ValueError, TypeError):
            continue
        n = 1
        for s in var.shape:
            n *= int(s)
        total += n * itemsize * max(int(op.attr(LAYER_STACK_ATTR, 0) or 0), 1)
    return total


def _prune_ops(program, fetch_names, keep_side_effect_ops=False):
    """Backward slice: keep only ops whose outputs (transitively) feed the
    fetch list (reference framework/prune.h / Executor.run(use_prune)).
    An eval fetch on a training program thus skips backward+optimizer ops
    instead of silently advancing the parameters.

    ``keep_side_effect_ops`` (the pass-pipeline DCE caller) additionally
    keeps ops with no outputs and the SIDE_EFFECT_OPS unconditionally."""
    block = program.global_block
    needed = set(fetch_names)
    keep = []
    for op in reversed(block.ops):
        if op.type in PSEUDO_OPS:
            continue
        keep_this = bool(set(op.output_arg_names()) & needed)
        if keep_side_effect_ops and (
                op.type in SIDE_EFFECT_OPS or not op.output_arg_names()):
            keep_this = True
        if keep_this:
            keep.append(op)
            needed.update(op.input_arg_names())
            needed.update(_ctrl_attr_reads(program, op))
            for aname in ("sub_block", "sub_block_t", "sub_block_f"):
                if op.has_attr(aname):
                    needed.update(
                        _sub_external_reads(program, int(op.attr(aname))))
    keep.reverse()
    return keep


def _feed_spec(block, feed: Dict[str, np.ndarray]):
    spec = []
    arrays = {}
    for name in sorted(feed):
        val = feed[name]
        if not _is_jax_array(val):  # device arrays pass through untouched
            val = np.asarray(val)
            var = block._find_var_recursive(name)
            if var is not None and var.dtype:
                want = dtypes.to_np(var.dtype)
                if val.dtype != want:
                    val = val.astype(want)
        arrays[name] = val
        spec.append((name, tuple(val.shape), str(val.dtype)))
    return tuple(spec), arrays


class Executor:
    def __init__(self, place: Optional[Place] = None, mesh=None):
        self.place = place if place is not None else _default_place()
        self._cache: Dict[tuple, _Compiled] = {}
        # (program fingerprint, feed names, scope id) -> (state_in, state_out)
        self._analysis_cache: Dict[tuple, tuple] = {}
        # (program fingerprint, fetch names) -> pruned op list
        self._prune_cache: Dict[tuple, list] = {}
        # (program fingerprint, pass config, fetch/feed names, scope) ->
        # pass-rewritten program (or the original when no pass applied)
        self._pass_cache: Dict[tuple, Program] = {}
        self._mesh = mesh  # explicit mesh wins over the global parallel env
        # pipelined dispatch (FLAGS_max_inflight_steps): the bounded
        # window of dispatched-but-unsynced steps owned by this executor
        self._window = _InflightWindow()
        _LIVE_EXECUTORS.add(self)
        _maybe_enable_compile_cache()
        _maybe_enable_partitionable_threefry()
        # flight recorder + health plane (observe/): the run-metadata
        # event fires once per process, executor creation is a
        # lifecycle event, and FLAGS_stall_timeout_s > 0 arms the stall
        # watchdog — all ~zero cost when the flags are off
        from ..observe import flight as _flight
        from ..observe import health as _health

        _flight.record_run_metadata()
        _flight.record("executor/created",
                       place=type(self.place).__name__,
                       device_id=self.place.device_id)
        _health.maybe_start_watchdog()
        # continuous low-duty-cycle profiling (FLAGS_prof_continuous_s)
        from ..observe import profiler_capture as _prof

        _prof.maybe_start_continuous()

    def _active_mesh(self):
        if self._mesh is not None:
            return self._mesh
        try:
            from ..distributed.parallel_env import get_mesh

            return get_mesh()
        except ImportError:
            return None

    # ------------------------------------------------------------------
    def run(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, np.ndarray]] = None,
        fetch_list: Optional[Sequence] = None,
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
        use_program_cache: bool = True,  # always cached; kept for API parity
        use_prune: bool = False,
    ):
        import jax

        program = program if program is not None else default_main_program()
        feed = dict(feed or {})
        scope = scope if scope is not None else global_scope()
        fetch_names = tuple(
            v.name if isinstance(v, Variable) else str(v) for v in (fetch_list or [])
        )

        block = program.global_block

        # host-side I/O programs (save/load ops write files; reference
        # save_op.cc:85/load_op.cc:67 run through the executor the same
        # way) are interpreted on host, never compiled
        if any(op.type in HOST_OPS for op in block.ops):
            return self._run_host_ops(program, scope, fetch_names,
                                      return_numpy)

        spec, feed_arrays = _feed_spec(block, feed)

        import os as _os

        acp_on = _os.environ.get("PADDLE_RUNNING_ENV") == \
            "PADDLE_EDL_AUTO_CHECKPOINT" or _acp_configured()
        if acp_on:
            from ..incubate.checkpoint import auto_checkpoint as _acp

            _acp.maybe_resume(self, program, scope, fed=bool(feed))

        fetches, inflight = self._dispatch(program, feed, feed_arrays, spec,
                                           fetch_names, scope,
                                           multi_step=False,
                                           scan_steps=None,
                                           use_prune=use_prune)

        # localsgd strategy: periodic cross-replica parameter averaging
        # (set by LocalSGDMetaOptimizer; see fleet/collective_transpiler.py)
        localsgd = getattr(program, "_localsgd", None)
        if localsgd is not None:
            localsgd.average_step(self, scope=scope)

        # auto-checkpoint hook (reference executor.py:1200)
        if acp_on:
            from ..incubate.checkpoint import auto_checkpoint as _acp

            _acp.on_executor_run(self, program, scope, fed=bool(feed))

        if inflight is not None:
            # pipelined mode (FLAGS_max_inflight_steps > 0): a lazy
            # handle — the device->host sync happens when the caller
            # reads an item (or at a window-drain point), never here
            return StepHandle(fetches, window=self._window, entry=inflight,
                              materialize=return_numpy)
        if return_numpy:
            from ..observe import tracer as otrace

            # legacy sync mode: the host-blocking device->host transfer
            # of the fetch list (reference Executor fetch phase)
            with otrace.span("executor/fetch", n=len(fetches)):
                return [np.asarray(v) for v in fetches]
        return list(fetches)

    # ------------------------------------------------------------------
    def warmup(
        self,
        program: Optional[Program] = None,
        feed_specs: Optional[Sequence[Dict]] = None,
        fetch_list: Optional[Sequence] = None,
        scope: Optional[Scope] = None,
    ) -> int:
        """AOT-compile one executable per feed spec (the serving layer's
        warm start; reference AnalysisPredictor warms by running once —
        here every shape bucket is warmed BEFORE traffic arrives).

        ``feed_specs`` is an iterable of feed descriptions: each one a
        dict mapping feed name -> ``(shape, dtype)`` (or a concrete
        array used as-is).  Every spec is run once on zero-filled feeds
        through the normal compile-cache path, so later ``run`` calls
        with the same shapes are pure cache hits.  All scope variables
        the warmup runs wrote — including the RNG key — are restored
        afterwards: warmup is state-neutral.  Returns the number of
        executables freshly compiled (0 if every spec was already
        cached).
        """
        program = program if program is not None else default_main_program()
        scope = scope if scope is not None else global_scope()
        if fetch_list is None:
            names = getattr(program, "_fetch_names", None)
            if not names:
                raise ValueError(
                    "warmup needs fetch_list= (or a program that records "
                    "its fetch contract, e.g. via load_inference_model)")
            fetch_list = [program.global_block.var(n) for n in names]
        n0 = len(self._cache)
        # device arrays must be COPIED, not just re-referenced: the jitted
        # step donates the state tuple (donate_argnums), so the warmup run
        # deletes the live buffers and a shallow snapshot would restore
        # dead arrays.  The whole scope CHAIN is snapshotted — state read
        # through a parent scope is donated all the same.
        snapshots = []
        s = scope
        while s is not None:
            snapshots.append((s, {
                k: (v.copy() if _is_jax_array(v) else v)
                for k, v in s._vars.items()
            }))
            s = s._parent
        try:
            for spec in (feed_specs or []):
                feed = {}
                for name, sd in spec.items():
                    if isinstance(sd, np.ndarray) or _is_jax_array(sd):
                        feed[name] = sd
                    else:
                        shape, dtype = sd
                        feed[name] = np.zeros(
                            tuple(int(s) for s in shape), dtype)
                self.run(program, feed=feed, fetch_list=fetch_list,
                         scope=scope)
        finally:
            # quiesce before restoring: warmup steps still in the
            # pipelined window must finish (and account their telemetry)
            # before their scope writes are rolled back.  The restore
            # must run even when the drain RAISES (a warmup step failing
            # on device): skipping it would leave warmup-mutated —
            # donation-dead — state in the user's scope
            try:
                self.drain()
            finally:
                for s, snap in snapshots:
                    s._vars.clear()
                    s._vars.update(snap)
        return len(self._cache) - n0

    # ------------------------------------------------------------------
    def run_steps(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, np.ndarray]] = None,
        fetch_list: Optional[Sequence] = None,
        scope: Optional[Scope] = None,
        return_numpy: bool = False,
        steps: Optional[int] = None,
    ):
        """Run the program K times in ONE XLA executable call.

        Two feed modes:
        - ``steps=None``: every feed carries a leading step dimension of
          equal extent K (one batch per step).
        - ``steps=K``: feeds are single-step shaped and the SAME batch is
          reused for all K steps without re-transfer (synthetic-data /
          warm-cache benchmarking mode).

        The whole block is wrapped in ``lax.scan`` over the step dim, so
        the K steps run back-to-back on device with zero host round-trips —
        the TPU-native replacement for the reference's
        ``train_from_dataset`` C++ loop (executor.cc:166) + buffered_reader
        double-buffering.  Fetches come back stacked with a leading K dim,
        as device arrays by default (jax arrays are async: no sync until
        the caller converts/reads them).
        """
        import jax

        program = program if program is not None else default_main_program()
        feed = dict(feed or {})
        if not feed:
            raise ValueError("run_steps requires at least one feed")
        scope = scope if scope is not None else global_scope()
        fetch_names = tuple(
            v.name if isinstance(v, Variable) else str(v) for v in (fetch_list or [])
        )
        if getattr(program, "_localsgd", None) is not None:
            raise NotImplementedError(
                "run_steps does not support localsgd programs: the periodic "
                "parameter averaging hook runs between executor calls and "
                "would be skipped inside the on-device scan; use exe.run")
        block = program.global_block
        if steps is None:
            step_dims = {np.shape(v)[0] for v in feed.values()}
            if len(step_dims) != 1:
                raise ValueError(
                    f"all run_steps feeds must share the same leading step "
                    f"dim; got {sorted(step_dims)}")
            if 0 in step_dims:
                raise ValueError("run_steps needs at least one step")
            # spec over the per-step shapes (leading dim stripped); device
            # arrays are sliced lazily — no host transfer
            per_step_feed = {
                k: (v[0] if _is_jax_array(v) else np.asarray(v)[0])
                for k, v in feed.items()
            }
            spec, _ = _feed_spec(block, per_step_feed)
        else:
            if steps < 1:
                raise ValueError(f"steps must be >= 1, got {steps}")
            spec, _ = _feed_spec(block, feed)
        feed_arrays = {}
        for name, _, dt in spec:
            arr = feed[name]
            if _is_jax_array(arr):  # device arrays pass through untouched
                feed_arrays[name] = arr
                continue
            arr = np.asarray(arr)
            if str(arr.dtype) != dt:
                arr = arr.astype(dt)
            feed_arrays[name] = arr

        fetches, inflight = self._dispatch(program, feed, feed_arrays, spec,
                                           fetch_names, scope,
                                           multi_step=True,
                                           scan_steps=steps)
        if inflight is not None:
            return StepHandle(fetches, window=self._window, entry=inflight,
                              materialize=return_numpy)
        if return_numpy:
            return [np.asarray(v) for v in fetches]
        return list(fetches)

    # ------------------------------------------------------------------
    def run_persistent(
        self,
        fn,
        state_names: Sequence[str],
        args: Sequence = (),
        scope: Optional[Scope] = None,
    ):
        """Run one step of a pre-jitted function whose PERSISTENT state
        lives in ``scope`` as device arrays — the ``run_steps``-style
        entry for externally-built steps (the serving decode engine's
        KV cache rides this: the cache tensors never round-trip to
        host between steps).

        ``fn(state_tuple, *args) -> (outputs, new_state_tuple)`` where
        ``state_tuple`` is the current device value of every name in
        ``state_names`` (in order).  The caller owns jitting — jit with
        ``donate_argnums=(0,)`` so each step updates the state buffers
        in place on TPU/GPU.  After the call the scope holds the new
        state, so checkpoint/inspection paths (``np.asarray`` on the
        var) keep working, and the executor's dispatch/drain counters
        move so the stall watchdog and health plane see decode progress
        like any other step.
        """
        from ..monitor import stat_add
        from ..observe import tracer as otrace

        scope = scope if scope is not None else global_scope()
        missing = [n for n in state_names if not scope.has_var(n)]
        if missing:
            raise KeyError(
                f"run_persistent state vars not in scope: {missing}")
        state = tuple(scope.get_var(n) for n in state_names)
        with otrace.span("executor/persistent", state=len(state)):
            outputs, new_state = fn(state, *args)
        if len(new_state) != len(state):
            raise ValueError(
                f"run_persistent fn returned {len(new_state)} state "
                f"values for {len(state)} state vars")
        for n, v in zip(state_names, new_state):
            scope.set_var(n, v)
        # persistent steps are synchronous from the window's point of
        # view (the caller reads the outputs immediately): count them
        # dispatched AND drained so progress telemetry stays truthful
        stat_add("executor_run")
        stat_add("executor_steps_dispatched")
        stat_add("executor_steps_drained")
        return outputs

    # ------------------------------------------------------------------
    def _dispatch(self, program, feed, feed_arrays, spec, fetch_names, scope,
                  multi_step, scan_steps, use_prune=False):
        """Shared run/run_steps tail: state analysis, compile-cache lookup,
        RNG seeding, the executable call, and scope write-back.  Every
        phase is a tracer span (observe/tracer.py) and every call feeds
        the StepTimer (observe/step_stats.py) — the per-run cost of both
        is a flag check when the tracer is off.

        Returns ``(fetches, inflight)``: ``inflight`` is the window
        entry when the call was dispatched pipelined
        (FLAGS_max_inflight_steps > 0), else None (legacy sync mode)."""
        from ..observe import tracer as otrace

        with otrace.span("executor/run", multi_step=bool(multi_step)):
            return self._dispatch_impl(program, feed, feed_arrays, spec,
                                       fetch_names, scope, multi_step,
                                       scan_steps, use_prune)

    def _dispatch_impl(self, program, feed, feed_arrays, spec, fetch_names,
                       scope, multi_step, scan_steps, use_prune=False):
        import time as _time

        import jax

        from . import flags
        from ..monitor import stat_add
        from ..observe import step_stats as _step_stats
        from ..observe import tracer as otrace

        # phase attribution: dispatch-side host seconds = entry-to-launch
        # wall MINUS the backpressure drain block (that block is an older
        # step's sync time, charged to that step at ITS drain)
        t_enter = _time.perf_counter()
        t_backpressure = 0.0

        # graph-pass pipeline (framework/passes.py): fused gradient
        # allreduce + cast/dead-op cleanup, applied to a cached clone so
        # the caller's program is never mutated
        with otrace.span("executor/pass_pipeline"):
            program = self._apply_graph_passes(program, fetch_names, feed,
                                               scope)

        # scan-over-layers stacker (LayerScanPass): per-layer weight
        # families ride the compiled step as ONE stacked carrier array
        # each; the scope keeps serving per-layer names through
        # StackedParamRef views.  Runs on EVERY compile path (single-
        # device, shard_map dp, GSPMD tp, run_steps) BEFORE state
        # analysis — the analysis reads the carrier names and must find
        # them in the scope.  Steady state is a no-op per dispatch.
        lplan = getattr(program, "_layer_plan", None)
        if lplan is not None:
            lplan.ensure_stacked(scope)

        ops = None
        if use_prune and fetch_names:
            pkey = (program.fingerprint(), fetch_names)
            ops = self._prune_cache.get(pkey)
            if ops is None:
                ops = _prune_ops(program, fetch_names)
                self._prune_cache[pkey] = ops
            else:
                stat_add("executor_prune_cache_hit")
        nan_scan = bool(flags.flag("check_nan_inf"))

        # state the program will read from the scope (the full op walk is
        # cached; cache hits only re-check that the state vars still exist)
        akey = (program.fingerprint(), frozenset(feed), scope.serial,
                fetch_names if ops is not None else None)
        cached = self._analysis_cache.get(akey)
        if cached is not None and all(scope.has_var(n) for n in cached[0]):
            state_in, state_out = cached
            stat_add("executor_analysis_cache_hit")
        else:
            with otrace.span("executor/analysis"):
                state_in, state_out = self._analyze_state(
                    program, set(feed), scope, ops=ops)
            self._analysis_cache[akey] = (state_in, state_out)
        def _svspec(n):
            v = scope.get_var(n)
            if isinstance(v, (PackedParamRef, StackedParamRef)) \
                    or _is_jax_array(v):
                return (n, tuple(v.shape), str(v.dtype))
            return (n, tuple(np.shape(v)), str(np.asarray(v).dtype))

        state_spec = tuple(_svspec(n) for n in state_in)

        mesh = self._active_mesh()
        key = (
            ("multi_step", scan_steps) if multi_step else None,
            program.fingerprint(),
            spec,
            fetch_names,
            state_spec,
            type(self.place).__name__,
            self.place.device_id,
            id(mesh),
            ops is not None,
            nan_scan,
            # flags read at trace time must key the cache, or flipping
            # them between runs is silently ignored; any flag defined
            # with affects_lowering=True joins automatically
            flags.lowering_key(),
        )
        from ..observe import flight as _flight

        entry = self._cache.get(key)
        if entry is None:
            stat_add("executor_compile")
            # the backend is definitionally in use from here on: the
            # one safe point to flight-record the device topology
            # (jax.devices() on a DEAD backend is the hang itself) —
            # and to unlock the heartbeat's live HBM sampling for the
            # same reason (observe/xla_stats.py)
            _flight.record_device_topology()
            from ..observe import xla_stats as _xla_stats

            _xla_stats.mark_backend_in_use()
            _flight.record("executor/compile",
                           fingerprint=program.fingerprint()[:16],
                           fetches=len(fetch_names),
                           multi_step=bool(multi_step))
            entry = self._compile(program, spec, state_in, state_out,
                                  fetch_names, mesh=mesh,
                                  multi_step=multi_step, scan_steps=scan_steps,
                                  ops=ops, nan_scan=nan_scan)
            self._cache[key] = entry
        else:
            stat_add("executor_cache_hit")
        stat_add("executor_run")

        # rng key lives in the scope so runs are deterministic/resumable
        if not scope.has_var(RNG_VAR) or scope.get_var(RNG_VAR) is None:
            seed = program.random_seed or 0
            scope.set_var(RNG_VAR, jax.random.PRNGKey(seed))

        if entry.pipeline_pack is not None:
            entry.pipeline_pack.ensure_packed(scope, mesh)

        def _state_value(n):
            # a per-layer member an unrolled edge op still reads
            # individually (a trimmed layer-scan run) lives as a
            # StackedParamRef view: hand jit the live device SLICE of
            # its carrier, not the view object
            v = scope.get_var(n)
            if isinstance(v, StackedParamRef):
                return v.device_value()
            return v

        feed_vals = tuple(feed_arrays[n] for n in entry.feed_names)
        mut_vals = tuple(_state_value(n) for n in entry.state_mut)
        const_vals = tuple(_state_value(n) for n in entry.state_const)
        rng = scope.get_var(RNG_VAR)

        if entry.globalize is not None:
            feed_vals, mut_vals, const_vals, rng = entry.globalize(
                feed_vals, mut_vals, const_vals, rng)

        # pipelined dispatch (FLAGS_max_inflight_steps): backpressure
        # BEFORE launching the next step so at most `max_inflight` steps
        # are ever in flight; 0 keeps the legacy synchronous-fetch path
        max_inflight = int(flags.flag("max_inflight_steps"))
        pipelined = max_inflight > 0

        if pipelined:
            _t_bp0 = _time.perf_counter()
            self._window.backpressure(max_inflight)
            t_backpressure = _time.perf_counter() - _t_bp0

        # examples/steps for the StepTimer; FLOPs/allreduce bytes are
        # the compile-time static accounting on the entry
        if multi_step:
            n_steps = scan_steps
            if n_steps is None and feed_arrays:
                n_steps = int(np.shape(next(iter(feed_arrays.values())))[0])
            n_steps = int(n_steps or 1)
        else:
            n_steps = 1
        batch = next((s[0] for _, s, _ in spec if s), 0)

        # jit traces lazily: the FIRST call of a fresh entry is the real
        # trace+XLA-compile (the "executor/lowering" span and per-
        # collective spans nest inside it); later calls are pure execute
        first_call = entry.n_calls == 0
        outer = otrace.span("executor/compile") if first_call \
            else otrace.NULL_SPAN
        t_exec0 = _time.perf_counter()
        if first_call:
            _ACTIVE_COMPILES[threading.get_ident()] = t_exec0
        try:
            with outer:
                if first_call:
                    # XLA introspection (observe/xla_stats.py): AOT
                    # lower+compile with telemetry, HBM accounting, and
                    # the pre-dispatch budget gate — MemoryBudgetError
                    # propagates from here with NOTHING dispatched
                    self._introspect_first_compile(
                        entry, program, mesh,
                        (feed_vals, mut_vals, const_vals, rng),
                        scope, spec, n_steps)
                with otrace.span("executor/execute"):
                    fetches, new_state, new_rng = entry.fn(
                        feed_vals, mut_vals, const_vals, rng)
                    if not pipelined and flags.flag("benchmark"):
                        # reference FLAGS_benchmark: sync so the recorded
                        # time is the step, not the async dispatch
                        jax.block_until_ready((fetches, new_state))
        finally:
            if first_call:
                _ACTIVE_COMPILES.pop(threading.get_ident(), None)
        entry.n_calls += 1

        for n, v in zip(entry.state_out, new_state):
            scope.set_var(n, v)
        if entry.uses_rng:
            scope.set_var(RNG_VAR, new_rng)

        if pipelined:
            nan_flags = None
            if entry.nan_scan:
                # keep the sentinel on device: the host check moves to
                # the window-drain point (no per-step sync)
                nan_flags = fetches[-1]
                fetches = fetches[:-1]
            # a fetched var that is ALSO a state output may share its
            # XLA buffer with the scope array the NEXT dispatch donates
            # (jit dedupes identical outputs); give the handle its own
            # buffer so a held, undrained fetch can't be overwritten —
            # CPU donation is a no-op, but TPU/GPU donation is real
            out_set = set(entry.state_out)
            if any(n in out_set for n in entry.fetch_names):
                import jax.numpy as jnp

                fetches = tuple(
                    jnp.copy(v) if n in out_set and _is_jax_array(v)
                    else v
                    for n, v in zip(entry.fetch_names, fetches))
            inflight = _InflightStep(
                sync_refs=tuple(fetches), nan_flags=nan_flags,
                nan_ops=entry.nan_ops, t_dispatch=t_exec0, steps=n_steps,
                examples=int(batch) * n_steps, compiled=first_call,
                flops_per_step=entry.flops_per_step,
                allreduce_bytes=entry.allreduce_bytes,
                host_s=max(t_exec0 - t_enter - t_backpressure, 0.0),
                phase_plan=entry.phase_plan)
            self._window.push(inflight)
            stat_add("executor_steps_dispatched", n_steps)
            _flight.record("executor/dispatch", steps=n_steps,
                           compiled=first_call, inflight=len(self._window))
            if flags.flag("benchmark") or entry.nan_scan:
                # both flags mean "per-call semantics": FLAGS_benchmark
                # wants the recorded time to be the step, nan-scan wants
                # the raise inside the offending run — drain right away
                # (accounting/raise still happen AT the drain point)
                self._window.drain_through(inflight)
            return fetches, inflight

        # legacy sync mode: telemetry + nan check at dispatch.  The
        # call above already blocked (or will on first read), so the
        # step counts as dispatched AND drained for the health plane
        stat_add("executor_steps_dispatched", n_steps)
        stat_add("executor_steps_drained", n_steps)
        _flight.record("executor/dispatch", steps=n_steps,
                       compiled=first_call, sync=True)
        _step_stats.step_timer().record_run(
            _time.perf_counter() - t_exec0, steps=n_steps,
            examples=int(batch) * n_steps, compiled=first_call,
            flops_per_step=entry.flops_per_step,
            allreduce_bytes_per_step=entry.allreduce_bytes)
        if entry.nan_scan:
            # NOT named `flags`: that would shadow the framework.flags
            # module imported at the top of this scope
            nan_flags = np.asarray(fetches[-1])
            fetches = fetches[:-1]
            _raise_on_nan(nan_flags, entry.nan_ops)
        return fetches, None

    # ------------------------------------------------------------------
    def _introspect_first_compile(self, entry, program, mesh, args, scope,
                                  spec, n_steps):
        """AOT-lower + compile the fresh entry BEFORE its first dispatch
        (observe/xla_stats.py): compile wall time into the
        ``compile_seconds`` histogram, executable size / HLO module
        stats / per-chip HBM footprint (``compiled.memory_analysis``)
        onto ``/metrics``, a ``compile_done`` flight event, the
        TPShardingPlan-joined per-var attribution table, and the
        ``FLAGS_hbm_budget_fraction`` gate — which raises
        :class:`~..observe.xla_stats.MemoryBudgetError` with nothing
        dispatched.  On success the compiled executable replaces the
        entry's callable so the compile is paid once.

        Everything short of a budget rejection is best-effort: a jax
        without AOT stages (or a path ``lower()`` cannot handle) falls
        back to the lazy first-call trace with the telemetry skipped."""
        from . import flags

        if entry.jit_fn is None or not flags.flag("xla_introspect"):
            return
        import contextlib
        import time as _time

        import jax

        from ..monitor import stat_add
        from ..observe import tracer as otrace
        from ..observe import xla_stats

        t0 = _time.perf_counter()
        try:
            ctx = jax.default_device(entry.jit_device) \
                if entry.jit_device is not None else contextlib.nullcontext()
            with otrace.span("executor/aot_compile"), ctx:
                compiled = entry.jit_fn.lower(*args).compile()
        except Exception as e:  # noqa: BLE001 — lazy path unchanged
            stat_add("xla_introspect_unavailable")
            logger.debug("XLA AOT introspection unavailable: %s", e)
            return
        seconds = _time.perf_counter() - t0

        # per-var sizes for the attribution join: scope state (params,
        # optimizer slots — the shardable bytes) + this call's feeds
        size_entries = []
        for name in entry.state_mut + entry.state_const:
            v = scope.get_var(name)
            if hasattr(v, "shape") and hasattr(v, "dtype"):
                size_entries.append(
                    (name, tuple(int(s) for s in v.shape), str(v.dtype),
                     "state"))
        for name, shape, dt in spec:
            size_entries.append((name, tuple(shape), dt, "feed"))
        device = entry.jit_device
        if device is None and mesh is not None:
            device = mesh.devices.flat[0]

        rec = xla_stats.on_compile(
            compiled, fingerprint=program.fingerprint(), seconds=seconds,
            size_entries=size_entries,
            plan=getattr(program, "_tp_plan", None), mesh=mesh,
            n_steps=n_steps, program_flops=entry.flops_per_step,
            device=device)
        if rec.get("xla_flops_per_step"):
            # MFU honesty: the hand-rolled IR count misprices fused ops
            # (mfu_flops_mismatch counted in on_compile) — XLA's own
            # per-chip number feeds the StepTimer from here on, and the
            # phase cost model re-prices its compute side to match
            entry.flops_per_step = float(rec["xla_flops_per_step"])
            if entry.phase_plan is not None:
                entry.phase_plan.update_flops(entry.flops_per_step)

        orig_fn = entry.fn

        def run_compiled(feed_vals, mut_vals, const_vals, rng):
            try:
                return compiled(feed_vals, mut_vals, const_vals, rng)
            except (TypeError, ValueError):
                # an input aval/sharding drifted from the AOT signature
                # (e.g. state restored from a checkpoint with another
                # layout): the lazy jit path re-specializes, an AOT
                # executable cannot — fall back permanently
                stat_add("xla_aot_fallbacks")
                entry.fn = orig_fn
                return orig_fn(feed_vals, mut_vals, const_vals, rng)

        entry.fn = run_compiled

    # ------------------------------------------------------------------
    def _apply_graph_passes(self, program, fetch_names, feed, scope):
        """Run the framework.passes pipeline over ``program`` before
        lowering (reference build-strategy graph passes).  The result —
        a rewritten clone, or the original object when no pass changed
        anything — is cached per (fingerprint, pass config, fetch/feed
        names, scope serial); FLAGS_fuse_passes (affects_lowering=True)
        gates the whole pipeline AND re-keys the compile cache."""
        from . import flags
        from . import passes as passes_mod

        pipe_meta = getattr(program, "_pipeline", None)
        if pipe_meta is not None:
            # the pipeline executor owns its schedule rewrite, but the
            # dp×mp×pp composition still needs ShardingPropagationPass:
            # its plan + partial anchors drive the manual Megatron mp
            # sharding inside the GPipe shard_map
            # (distributed/pipeline.py).  The fuse/cast/DCE passes stay
            # off — the pipeline splits the op stream per stage itself.
            if not (passes_mod.has_tp_marks(program)
                    or passes_mod.has_ep_marks(program)):
                return program
            pipeline = passes_mod.PassPipeline(
                [passes_mod.ShardingPropagationPass()])
        elif not flags.flag("fuse_passes"):
            # FLAGS_fuse_passes gates the OPTIMIZATION passes only.  Two
            # passes answer to their own switches and still run: a
            # tensor-parallel program needs its sharding plan (the dp
            # loss-grad scale was removed at transpile time, so running
            # it un-sharded would be numerically wrong, not just slow),
            # and scan-over-layers was asked for explicitly via
            # FLAGS_layer_scan / recompute_configs scan stamps — its
            # own gate, not the fusion flag, decides it
            reduced = []
            if passes_mod.has_tp_marks(program) \
                    or passes_mod.has_ep_marks(program):
                reduced.append(passes_mod.ShardingPropagationPass())
            if passes_mod.LayerScanPass._config(program)[0]:
                reduced.append(passes_mod.LayerScanPass())
            if not reduced:
                return program
            pipeline = passes_mod.PassPipeline(reduced)
        else:
            pipeline = passes_mod.default_pipeline()
        from ..monitor import stat_add

        mesh = self._active_mesh()
        # flags read at PASS time (FLAGS_layer_scan and friends decide
        # whether/how programs are rewritten) must key the pass cache
        # exactly like they key the compile cache — flipping the scan
        # flag or the remat policy between runs must re-run the
        # pipeline, not serve the stale rewrite
        key = (program.fingerprint(), pipeline.config_key(), fetch_names,
               frozenset(feed), scope.serial, id(mesh),
               flags.lowering_key())
        cached = self._pass_cache.get(key)
        if cached is not None:
            stat_add("executor_pass_cache_hit")
            return cached
        ctx = passes_mod.PassContext(fetch_names=fetch_names,
                                     feed_names=tuple(feed), scope=scope,
                                     mesh=mesh)
        out = pipeline.apply(program, ctx)
        if out is not program and pipe_meta is not None:
            # clone() is a proto round-trip: the pipeline metadata is a
            # python attr and must ride onto the rewritten clone or the
            # compile path would fall through to the non-pipeline branch
            out._pipeline = pipe_meta
        self._pass_cache[key] = out
        return out

    # ------------------------------------------------------------------
    def _run_host_ops(self, program, scope, fetch_names, return_numpy):
        """Interpret a host I/O block (save/load programs).  Mixed
        compute+io blocks are rejected: build a separate save program as
        the reference's io.py does."""
        # a save program must observe a quiescent pipeline (telemetry +
        # NaN checks of in-flight steps fire before any file is written)
        self.drain()
        from . import var_io

        block = program.global_block
        for op in block.ops:
            if op.type in PSEUDO_OPS:
                continue
            if op.type not in HOST_OPS:
                raise NotImplementedError(
                    f"op {op.type!r} cannot run in a host I/O program; "
                    f"save/load programs must contain only save/load ops "
                    f"(build them via fluid.io helpers)")
            if op.type == "save":
                name = op.inputs["X"][0]
                var_io.save_var(np.asarray(scope.get_var(name)),
                                op.attr("file_path"))
            elif op.type == "load":
                name = op.outputs["Out"][0]
                scope.set_var(name, var_io.load_var(op.attr("file_path")))
            elif op.type == "save_combine":
                names = list(op.inputs["X"])
                var_io.save_combine(
                    {n: np.asarray(scope.get_var(n)) for n in names},
                    names, op.attr("file_path"))
            elif op.type == "load_combine":
                names = list(op.outputs["Out"])
                loaded = var_io.load_combine(op.attr("file_path"))
                missing = [n for n in names if n not in loaded]
                if missing:
                    raise KeyError(
                        f"load_combine: vars {missing} not present in "
                        f"{op.attr('file_path')!r}")
                for n in names:
                    scope.set_var(n, loaded[n])
        if fetch_names:
            vals = [scope.get_var(n) for n in fetch_names]
            return [np.asarray(v) for v in vals] if return_numpy else vals
        return []

    # ------------------------------------------------------------------
    def _analyze_state(self, program: Program, feed_names: set, scope: Scope,
                       ops=None):
        """Static use/def analysis of the root block (plus sub-blocks).

        state_in  = names read before written that are not feeds (must come
                    from the scope: parameters, optimizer state, ...)
        state_out = names written that should persist back into the scope
                    (persistable vars, or anything already living in scope).
        ``ops`` restricts the walk to a pruned op list (use_prune).
        """
        written: set = set()
        state_in: List[str] = []
        state_out: List[str] = []
        seen_out: set = set()

        def visit_block(block, op_list):
            for op in op_list:
                if op.type in PSEUDO_OPS:
                    continue
                reads = list(op.input_arg_names()) \
                    + _ctrl_attr_reads(program, op)
                for aname in ("sub_block", "sub_block_t", "sub_block_f"):
                    if op.has_attr(aname):
                        reads.extend(
                            _sub_external_reads(program, int(op.attr(aname))))
                for name in reads:
                    if name in feed_names or name in written:
                        continue
                    if name not in state_in:
                        if not scope.has_var(name) or scope.get_var(name) is None:
                            raise RuntimeError(
                                f"op {op.type!r} reads {name!r} which is neither a "
                                f"feed nor initialized in the scope. Did you run the "
                                f"startup program? (op built at: "
                                f"{op.callstack[-1] if op.callstack else '?'})"
                            )
                        state_in.append(name)
                for name in op.output_arg_names():
                    written.add(name)
                    var = block._find_var_recursive(name)
                    persistable = (var is not None and var.persistable) or scope.has_var(name)
                    if persistable and name not in seen_out:
                        seen_out.add(name)
                        state_out.append(name)

        block = program.global_block
        visit_block(block, ops if ops is not None else block.ops)
        return tuple(state_in), tuple(state_out)

    # ------------------------------------------------------------------
    def _compile(self, program, feed_spec, state_in, state_out, fetch_names,
                 mesh=None, multi_step=False, scan_steps=None, ops=None,
                 nan_scan=False) -> _Compiled:
        import jax
        import jax.numpy as jnp

        feed_names = tuple(n for n, _, _ in feed_spec)
        block = program.global_block
        op_list = [op for op in (ops if ops is not None else block.ops)
                   if op.type not in PSEUDO_OPS]
        # tensor-parallel plan (ShardingPropagationPass output on the
        # post-pass program).  A tp-stamped program WITHOUT a plan means
        # the pass could not run — refuse rather than fall through to
        # the shard_map dp path, whose gradient math assumes the dp
        # loss-grad scale the tp transpile removed.
        tp_plan = getattr(program, "_tp_plan", None)
        if tp_plan is None:
            from .passes import has_ep_marks, has_tp_marks

            if has_tp_marks(program):
                raise ValueError(
                    "this program was built with DistributedStrategy."
                    "tensor_parallel but the executor has no mesh with "
                    "an 'mp' axis; build one with init_parallel_env("
                    "mesh_shape=(dp, mp), axis_names=('dp', 'mp')) or "
                    "set_mesh(Mesh(devs.reshape(dp, mp), ('dp', 'mp')))")
            if has_ep_marks(program):
                raise ValueError(
                    "this program was built with DistributedStrategy."
                    "expert_parallel but the executor has no mesh with "
                    "an 'ep' axis; build one with init_parallel_env("
                    "mesh_shape=(dp, ep), axis_names=('dp', 'ep')) or "
                    "FLAGS_ep_degree")
        # static per-step accounting for the StepTimer/MFU readout; a
        # failure here must never fail a compile
        try:
            from ..hapi.model_stat import program_flops

            flops_per_step = float(program_flops(program))
            # a symbolic-batch program (-1 leading dims) prices
            # per-SAMPLE FLOPs (model_stat counts -1 as 1): scale by
            # the concrete feed batch this executable was compiled for
            if feed_spec and flops_per_step:
                name0, shape0, _ = feed_spec[0]
                var0 = block._find_var_recursive(name0)
                if (var0 is not None and var0.shape and shape0
                        and int(var0.shape[0]) <= 0):
                    flops_per_step *= max(int(shape0[0]), 1)
        except Exception:  # noqa: BLE001 — telemetry only
            flops_per_step = 0.0
        if tp_plan is not None:
            # per-CHIP FLOPs under tensor parallelism: each chip holds
            # 1/mp of every sharded layer, so comparing program FLOPs
            # against FLAGS_device_peak_tflops without the division
            # overstates MFU by mp× on sharded runs
            flops_per_step /= max(tp_plan.mp_degree, 1)
            # per-grad dp-allreduce payloads from the plan: mp-sharded
            # grads move only their shard over dp (the post-pass op
            # stream's var shapes are global and would overcount)
            allreduce_bytes = sum(
                int(r.get("bytes", 0))
                for r in tp_plan.grad_reduce.values())
        else:
            allreduce_bytes = _program_allreduce_bytes(block, op_list)
        # step-phase attribution (observe/phases.py): price this
        # program's compute + collectives once at compile; consulted at
        # every window drain.  Never fails a compile (None on error).
        from . import flags as _pflags
        from ..observe import phases as _phases

        phase_plan = _phases.build_phase_plan(
            block, op_list, mesh=mesh, tp_plan=tp_plan,
            flops_per_step=flops_per_step,
            cm_chunks=int(_pflags.flag("collective_matmul_chunks") or 0)
            if tp_plan is not None else 0,
            moe_chunks=int(_pflags.flag("moe_alltoall_chunks") or 0))
        out_set = set(state_out)
        state_mut = tuple(n for n in state_in if n in out_set)
        state_const = tuple(n for n in state_in if n not in out_set)
        if nan_scan and getattr(program, "_pipeline", None) is not None:
            # the pipeline executor re-derives its own fetch contract;
            # per-op scanning inside the GPipe switch is a later
            # milestone — warn instead of breaking the run
            logger.warning("FLAGS_check_nan_inf is not supported for "
                           "pipeline programs; scan skipped")
            nan_scan = False
        if nan_scan:
            # per-op finite flags come back as an extra fetch; _dispatch
            # raises host-side naming the first bad op (reference
            # FLAGS_check_nan_inf, operator.cc:1129)
            fetch_names = tuple(fetch_names) + (NAN_FLAGS_VAR,)

        def trace_block(env, rng, axis_env=(), ring_axes=None, fold_axes=()):
            from ..observe import tracer as otrace

            ctx = LoweringContext(block, env, rng_key=rng, mesh=mesh,
                                  axis_env=axis_env, ring_axes=ring_axes,
                                  fold_axes=fold_axes)
            from . import flags as _flags_mod
            from .lowering import apply_tp_constraints
            from .passes import TP_CONSTRAINT_ATTR

            # latency-hiding collective matmul: row-chunk anchored
            # row-parallel matmuls so XLA emits one mp reduce per chunk
            # (ops/collective_matmul.py); 0/1 keeps the plain lowering
            cm_chunks = int(_flags_mod.flag("collective_matmul_chunks")
                            or 0) if tp_plan is not None else 0

            flags = []
            with otrace.span("executor/lowering", ops=len(op_list)):
                for op in op_list:
                    try:
                        chunked = False
                        if cm_chunks > 1 and mesh is not None \
                                and op.has_attr(TP_CONSTRAINT_ATTR):
                            from ..ops.collective_matmul import (
                                maybe_chunked_gspmd)

                            chunked = maybe_chunked_gspmd(
                                ctx, op, mesh, cm_chunks)
                        if chunked:
                            pass  # lowering + constraints emitted chunked
                        elif op.type in COLLECTIVE_OPS:
                            # per-collective span: payload bytes + dtype
                            # read off the traced value (host time ==
                            # trace cost; the args are what the timeline
                            # is really for)
                            with otrace.span(f"collective/{op.type}",
                                             **_collective_span_args(
                                                 env, op, mesh=mesh)):
                                get_lowering(op.type)(ctx, op)
                        else:
                            get_lowering(op.type)(ctx, op)
                        if not chunked and tp_plan is not None \
                                and op.has_attr(TP_CONSTRAINT_ATTR):
                            # sharding anchors: pin the propagated spec
                            # so XLA places the mp partial-sum reduce at
                            # THIS op (Megatron f/g operator placement)
                            apply_tp_constraints(env, op, mesh)
                    except Exception as e:
                        site = op.callstack[-1] if op.callstack \
                            else "<unknown>"
                        raise type(e)(
                            f"while lowering op {op.type!r} (built at "
                            f"{site}): {e}"
                        ) from e
                    if nan_scan:
                        ok = jnp.bool_(True)
                        for n in op.output_arg_names():
                            v = env.get(n)
                            if v is not None and hasattr(v, "dtype") \
                                    and jnp.issubdtype(v.dtype,
                                                       jnp.floating):
                                ok = jnp.logical_and(
                                    ok, jnp.isfinite(v).all())
                        flags.append(ok)
            if nan_scan:
                env[NAN_FLAGS_VAR] = jnp.stack(flags) if flags else \
                    jnp.ones((0,), jnp.bool_)
            missing = [n for n in fetch_names if n not in env]
            if missing:
                raise KeyError(f"fetch vars not produced by program: {missing}")
            return ctx

        pipe = getattr(program, "_pipeline", None)
        if pipe is not None and mesh is not None \
                and "pp" in mesh.axis_names:
            if multi_step:
                raise NotImplementedError(
                    "run_steps over the pipeline executor is not supported "
                    "yet; call run per step")
            from ..distributed.pipeline import (PACKED_STATE_VAR,
                                                build_pipeline_fn,
                                                plan_packing)

            plan = plan_packing(program, int(mesh.shape["pp"]), state_in,
                                state_out, pipe, tp_plan=tp_plan)
            owned = plan.owned_names
            ro_owned = sorted(owned & set(state_const))
            if ro_owned:
                raise NotImplementedError(
                    f"stage-owned state {ro_owned} is read-only in the "
                    f"program; pipeline state sharding expects params and "
                    f"slots to be updated each step")
            p_mut = (PACKED_STATE_VAR,) + tuple(
                n for n in state_mut if n not in owned)
            p_const = tuple(n for n in state_const if n not in owned)
            p_out = (PACKED_STATE_VAR,) + tuple(
                n for n in state_out if n not in owned)

            fn = build_pipeline_fn(
                program, mesh, feed_names, p_mut, p_const,
                p_out, fetch_names, pipe["loss_name"],
                pipe["params_grads"], pipe["num_microbatches"],
                pipe["bwd_end"], plan)
            pipe_jfn = jax.jit(fn, donate_argnums=(1,))
            return _Compiled(
                fn=pipe_jfn,
                feed_names=feed_names,
                state_mut=p_mut,
                state_const=p_const,
                state_out=p_out,
                fetch_names=fetch_names,
                uses_rng=True,
                pipeline_pack=plan,
                flops_per_step=flops_per_step,
                allreduce_bytes=allreduce_bytes,
                jit_fn=pipe_jfn,
                phase_plan=phase_plan,
            )

        globalize = None
        if tp_plan is not None:
            # tensor-parallel GSPMD path: the whole block is ONE logical
            # program jitted with NamedSharding in/out specs from the
            # plan — semantics stay single-program (loss parity is by
            # construction), sharding is pure layout, and XLA inserts
            # the dp grad reduces and mp partial-sum reduces.  The
            # placer rides the globalize hook: state laid out
            # differently (startup output, restored checkpoint) is
            # device_put onto the plan's shardings before the call.
            run_on_device, globalize = self._build_gspmd_fn(
                mesh, tp_plan, feed_spec, feed_names, state_mut,
                state_const, state_out, fetch_names, trace_block,
                multi_step=multi_step, scan_steps=scan_steps)
        elif mesh is None and not multi_step:
            def fn(feed_vals, mut_vals, const_vals, rng):
                env = {}
                env.update(zip(state_mut, mut_vals))
                env.update(zip(state_const, const_vals))
                env.update(zip(feed_names, feed_vals))
                ctx = trace_block(env, rng)
                fetches = tuple(env[n] for n in fetch_names)
                new_state = tuple(env[n] for n in state_out)
                return fetches, new_state, ctx.rng_key
        elif mesh is None and multi_step:
            def step_fn(env, key):
                ctx = trace_block(env, key)
                return tuple(env[n] for n in fetch_names), ctx.rng_key

            fn = _make_scan_fn(step_fn, state_mut, state_const, state_out,
                               feed_names, scan_steps)
        else:
            fn, globalize = self._build_sharded_fn(
                program, mesh, feed_spec, feed_names, state_mut, state_const,
                state_out, fetch_names, trace_block, multi_step=multi_step,
                scan_steps=scan_steps)

        jit_device = None
        if tp_plan is None:
            # jit traces lazily on first call; donating the mutable
            # state gives in-place parameter-update memory behavior
            # (buffers alias outputs).
            jfn = jax.jit(fn, donate_argnums=(1,))
            device = self.place.jax_device()

            if mesh is None:
                jit_device = device

                def run_on_device(feed_vals, mut_vals, const_vals, rng):
                    with jax.default_device(device):
                        return jfn(feed_vals, mut_vals, const_vals, rng)
            else:
                run_on_device = jfn  # placement is the mesh's job
        else:
            jfn = run_on_device  # _build_gspmd_fn returned the jit callable

        compiled = _Compiled(
            fn=run_on_device,
            feed_names=feed_names,
            state_mut=state_mut,
            state_const=state_const,
            state_out=tuple(state_out),
            fetch_names=fetch_names,
            uses_rng=True,
            globalize=globalize,
            nan_ops=tuple(
                (op.type, op.callstack[-1] if op.callstack else "?")
                for op in op_list) if nan_scan else (),
            nan_scan=nan_scan,
            flops_per_step=flops_per_step,
            allreduce_bytes=allreduce_bytes,
            jit_fn=jfn,
            jit_device=jit_device,
            phase_plan=phase_plan,
        )
        return compiled

    def _build_sharded_fn(self, program, mesh, feed_spec, feed_names, state_mut,
                          state_const, state_out, fetch_names, trace_block,
                          multi_step=False, scan_steps=None):
        """SPMD execution over the mesh (reference ParallelExecutor role).

        The whole block runs inside shard_map: feeds are split on their
        batch dim over the 'dp' axis, state (params/opt accumulators) is
        replicated, and the program's own c_* collective ops become real
        XLA collectives.  Fetch semantics match the reference's
        all-workers view: scalars come back as the cross-replica mean
        (== full-batch loss for mean losses), batched tensors are
        re-assembled by all_gather on dim 0.
        """
        import jax
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from .jax_compat import shard_map

        axis_names = tuple(mesh.axis_names)
        dp_axis = "dp" if "dp" in axis_names else axis_names[0]
        dp_size = int(mesh.shape[dp_axis])
        # feeds are process-local: each rank supplies its own shard, so
        # divisibility is judged against the devices THIS process feeds
        n_procs = len({d.process_index for d in mesh.devices.flat})
        local_dp = max(dp_size // n_procs, 1)
        try:
            from ..distributed.parallel_env import ring_axes as _ring_axes

            rings = _ring_axes()
        except ImportError:
            rings = {}

        feed_in_specs = []
        sharded_feeds = set()
        for name, shape, _ in feed_spec:
            if len(shape) == 0 or shape[0] <= 1:
                feed_in_specs.append(P())  # scalars/broadcast feeds replicate
            elif shape[0] % local_dp == 0:
                feed_in_specs.append(P(dp_axis))
                sharded_feeds.add(name)
            else:
                raise ValueError(
                    f"feed {name!r} batch dim {shape[0]} is not divisible by "
                    f"the local data-parallel degree {local_dp} (global dp "
                    f"{dp_size} over {n_procs} processes); pad the batch or "
                    f"resize the mesh (silent replication would waste "
                    f"{local_dp}x compute)")
        feed_in_specs = tuple(feed_in_specs)

        # static dp-variance analysis: which vars differ across dp shards?
        # feeds sharded on dp are varying; ops propagate variance from
        # inputs to outputs; allreduce/broadcast/allgather make values
        # replica-invariant again.  Drives the fetch re-assembly below.
        _CLEARING = {"c_allreduce_sum", "c_allreduce_max", "c_allreduce_min",
                     "c_allreduce_prod", "c_broadcast", "c_allgather",
                     "allreduce"}
        # ZeRO-1 sharded optimizer state lives split over the dp axis;
        # recorded as __sharded_accumulators__ attrs on the rewired
        # optimizer ops so it survives clone/proto round-trips
        sharded_state = set()
        for op in program.global_block.ops:
            accs = op.attr("__sharded_accumulators__", None)
            if accs:
                sharded_state.update(accs)
        varying = set(sharded_feeds) | sharded_state
        for op in program.global_block.ops:
            if op.type in PSEUDO_OPS:
                continue
            if op.type in _CLEARING:
                for n in op.output_arg_names():
                    varying.discard(n)
                continue
            if op.type == "c_shard_slice":
                varying.update(op.output_arg_names())
                continue
            if op.type == "uncoalesce_tensor":
                # split-back of a fused (already allreduced) gradient
                # buffer: the outputs inherit the BUFFER's variance, even
                # though the grad names were varying before fusion
                if any(n in varying for n in op.input_arg_names()):
                    varying.update(op.output_arg_names())
                else:
                    for n in op.output_arg_names():
                        varying.discard(n)
                continue
            if any(n in varying for n in op.input_arg_names()):
                varying.update(op.output_arg_names())

        def step_once(env, rng):
            # the program key advances identically on every shard;
            # per-shard randomness (dropout) folds the dp index in at the
            # op (LoweringContext.next_key(per_shard=True)) — replica-
            # invariant randomness (param init) must NOT differ per shard
            ctx = trace_block(env, rng, axis_env=axis_names,
                              ring_axes=rings, fold_axes=(dp_axis,))
            new_rng = ctx.rng_key if ctx.rng_consumed else rng
            fetches = []
            for n in fetch_names:
                v = env[n]
                if n == NAN_FLAGS_VAR:
                    # AND across shards (pmin of the 0/1 flags)
                    import jax.numpy as jnp

                    fetches.append(
                        lax.pmin(v.astype(jnp.int32), axis_names))
                    continue
                if n not in varying:
                    fetches.append(v)  # replica-invariant: local copy is it
                elif getattr(v, "ndim", 0) == 0 or v.size == 1:
                    # dp-varying scalars (losses, metrics): cross-replica
                    # mean == the full-batch value for mean-reduced losses
                    fetches.append(lax.pmean(v, axis_names))
                else:
                    # dp-varying batched values: re-assemble the full batch
                    fetches.append(lax.all_gather(v, dp_axis, axis=0, tiled=True))
            return tuple(fetches), new_rng

        if not multi_step:
            def traced(feed_vals, mut_vals, const_vals, rng):
                env = {}
                env.update(zip(state_mut, mut_vals))
                env.update(zip(state_const, const_vals))
                env.update(zip(feed_names, feed_vals))
                fetches, new_rng = step_once(env, rng)
                new_state = tuple(env[n] for n in state_out)
                return fetches, new_state, new_rng

            feed_specs_final = feed_in_specs
        else:
            traced = _make_scan_fn(step_once, state_mut, state_const,
                                   state_out, feed_names, scan_steps)

            if scan_steps is not None:
                # single-step-shaped feeds reused every iteration: the
                # batch dim is dim 0, same sharding as the per-step path
                feed_specs_final = feed_in_specs
            else:
                # feeds carry a leading step dim: replicate it, shard the
                # per-step batch dim (now dim 1) over dp
                feed_specs_final = tuple(
                    P(*((None,) + tuple(s))) if s else P()
                    for s in (tuple(spec) for spec in feed_in_specs)
                )

        def state_spec(n):
            return P(dp_axis) if n in sharded_state else P()

        fn = shard_map(
            traced,
            mesh=mesh,
            in_specs=(feed_specs_final,
                      tuple(state_spec(n) for n in state_mut),
                      tuple(state_spec(n) for n in state_const),
                      P()),
            out_specs=(tuple(P() for _ in fetch_names),
                       tuple(state_spec(n) for n in state_out),
                       P()),
            check_vma=False,
        )

        # ---- multi-process: each rank holds only ITS shard of the data
        # (reference trainers each feed their own batch).  jit over a
        # multi-host mesh needs global jax.Arrays, so process-local
        # feeds/state are assembled with make_array_from_process_local_data
        # (the jax.distributed rendezvous replaces c_gen_nccl_id /
        # c_comm_init; SURVEY §5 comm backend).
        multiproc = any(d.process_index != jax.process_index()
                        for d in mesh.devices.flat)
        globalize = None
        if multiproc:
            from jax.sharding import NamedSharding

            proc = jax.process_index()
            # contiguous process blocks along dp (mesh devices are built
            # process-major, see parallel_env.init_parallel_env); only
            # valid when processes tile the dp axis alone — a mesh whose
            # OTHER axes span processes would make the dp block span
            # several processes and the slice below wrong
            procs_on_dp = sorted({d.process_index
                                  for d in mesh.devices.flat})
            if sharded_state:
                dp_idx = axis_names.index(dp_axis)
                rows = np.moveaxis(mesh.devices, dp_idx, 0)
                if any(len({d.process_index for d in np.ravel(row)}) != 1
                       for row in rows):
                    raise NotImplementedError(
                        f"ZeRO-sharded state on a multi-process mesh "
                        f"requires each '{dp_axis}' position to belong to "
                        f"exactly one process (processes must tile the dp "
                        f"axis); reshape the mesh or disable sharding")
            proc_pos = procs_on_dp.index(proc)

            def to_global(val, pspec, state_name=None):
                if _is_jax_array(val) and not getattr(
                        val, "is_fully_addressable", True):
                    return val  # already a global array (prior step output)
                arr = np.asarray(val)
                if state_name is not None and state_name in sharded_state \
                        and arr.shape:
                    # ZeRO state: every process initialized the FULL
                    # array (replicated startup); hand jax only the
                    # slice this process's dp block owns
                    blk = arr.shape[0] // len(procs_on_dp)
                    arr = arr[proc_pos * blk:(proc_pos + 1) * blk]
                return jax.make_array_from_process_local_data(
                    NamedSharding(mesh, pspec), arr)

            def globalize(feed_vals, mut_vals, const_vals, rng):
                feeds = tuple(to_global(v, s)
                              for v, s in zip(feed_vals, feed_specs_final))
                muts = tuple(
                    to_global(v, state_spec(n), state_name=n)
                    for n, v in zip(state_mut, mut_vals))
                consts = tuple(
                    to_global(v, state_spec(n), state_name=n)
                    for n, v in zip(state_const, const_vals))
                return feeds, muts, consts, to_global(rng, P())

        return fn, globalize

    def _build_gspmd_fn(self, mesh, tp_plan, feed_spec, feed_names,
                        state_mut, state_const, state_out, fetch_names,
                        trace_block, multi_step=False, scan_steps=None):
        """Tensor-parallel execution: ``jax.jit`` over the dp×mp mesh
        with per-var ``NamedSharding`` in/out specs from the
        :class:`~.passes.TPShardingPlan` (GSPMD; SNIPPETS.md [2]/[3]
        pjit substrate).

        Unlike the shard_map dp path there is no manual axis
        environment: the traced program keeps GLOBAL shapes and
        single-program semantics (program c_* collectives lower to
        identity), the in/out shardings lay state out over the mesh —
        tp-matched params and their optimizer slots physically live as
        1/mp shards per chip — and XLA's SPMD partitioner inserts the
        collectives: dp all-reduces for gradients (over shard-sized
        payloads, since grads inherit their param's mp sharding) and
        mp partial-sum reduces at the pass's constraint anchors.

        Scope arrays come back sharded and stay sharded across steps
        (donation aliases them in place); fetches are forced replicated
        so handle reads and ``np.asarray`` reassemble transparently."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        if any(d.process_index != jax.process_index()
               for d in mesh.devices.flat):
            raise NotImplementedError(
                "tensor_parallel over a multi-process mesh is not "
                "implemented yet: process-local shards would need "
                "make_array_from_process_local_data assembly per the "
                "plan's 2D specs; run one process (all chips local) or "
                "use the dp-only shard_map path")

        dp_axis = tp_plan.dp_axis if tp_plan.dp_axis in mesh.axis_names \
            else None
        dp_size = int(mesh.shape[dp_axis]) if dp_axis else 1

        def feed_pspec(shape):
            # batch-dim dp sharding when it divides evenly; GSPMD
            # semantics are identical either way (a replicated feed
            # still computes the same global value), so non-divisible
            # batches replicate instead of erroring like the shard_map
            # path must
            if (not shape or dp_axis is None or int(shape[0]) <= 1
                    or int(shape[0]) % dp_size):
                return P()
            return P(dp_axis)

        base_feed_specs = tuple(feed_pspec(s) for _, s, _ in feed_spec)
        if multi_step and scan_steps is None:
            # stacked feeds: leading step dim replicated, per-step batch
            # dim (now dim 1) sharded over dp
            feed_specs = tuple(P(*((None,) + tuple(s)))
                               for s in base_feed_specs)
        else:
            feed_specs = base_feed_specs

        def state_sharding(n):
            return NamedSharding(mesh, tp_plan.partition_spec(n))

        repl = NamedSharding(mesh, P())

        if not multi_step:
            def traced(feed_vals, mut_vals, const_vals, rng):
                env = {}
                env.update(zip(state_mut, mut_vals))
                env.update(zip(state_const, const_vals))
                env.update(zip(feed_names, feed_vals))
                ctx = trace_block(env, rng)
                fetches = tuple(env[n] for n in fetch_names)
                new_state = tuple(env[n] for n in state_out)
                return fetches, new_state, ctx.rng_key
        else:
            def step_fn(env, key):
                ctx = trace_block(env, key)
                return tuple(env[n] for n in fetch_names), ctx.rng_key

            traced = _make_scan_fn(step_fn, state_mut, state_const,
                                   state_out, feed_names, scan_steps)

        feed_sh = tuple(NamedSharding(mesh, s) for s in feed_specs)
        mut_sh = tuple(state_sharding(n) for n in state_mut)
        const_sh = tuple(state_sharding(n) for n in state_const)
        in_sh = (feed_sh, mut_sh, const_sh, repl)
        out_sh = (tuple(repl for _ in fetch_names),
                  tuple(state_sharding(n) for n in state_out),
                  repl)
        jfn = jax.jit(traced, in_shardings=in_sh, out_shardings=out_sh,
                      donate_argnums=(1,))

        def _place(vals, shardings):
            # jit with explicit in_shardings REJECTS committed arrays
            # laid out differently (e.g. mesh-replicated startup output,
            # or a checkpoint restored onto another topology): reshard
            # those with device_put.  Steady-state arrays already match
            # (the step's out_shardings produced them) and np feeds are
            # sharded by jit itself — both skip the copy.
            return tuple(
                jax.device_put(v, s)
                if _is_jax_array(v) and getattr(v, "sharding", None) != s
                else v
                for v, s in zip(vals, shardings))

        def placer(feed_vals, mut_vals, const_vals, rng):
            return (_place(feed_vals, feed_sh), _place(mut_vals, mut_sh),
                    _place(const_vals, const_sh),
                    _place((rng,), (repl,))[0])

        return jfn, placer

    def drain(self):
        """Block until every in-flight pipelined step has completed:
        telemetry is recorded, NaN-scan flags are checked, and the scope
        holds a quiescent state.  No-op when nothing is in flight."""
        self._window.drain_all()

    def close(self):
        # quiesce the pipeline first: in-flight steps must complete (and
        # their telemetry/NaN checks fire) before caches are dropped
        self.drain()
        # drain pending async checkpoint saves NEXT: a shutdown must
        # never abandon a queued snapshot mid-write (the manager's
        # atomic commit makes a torn abort recoverable, but a clean
        # close should finish the work it accepted)
        try:
            from ..ckpt import wait_all as _ckpt_wait_all

            _ckpt_wait_all(raise_errors=False)
        except ImportError:  # pragma: no cover - partial installs
            pass
        # clear EVERY per-program cache: long-lived serving processes
        # otherwise leak analysis/prune/pass entries for dead programs
        self._cache.clear()
        self._analysis_cache.clear()
        self._prune_cache.clear()
        self._pass_cache.clear()


# the one shared jax-Array duck-type probe lives in scope.py (leaf
# module); this alias keeps the historical local name
_is_jax_array = _is_device_array


def _acp_configured() -> bool:
    import sys

    acp = sys.modules.get("paddle_tpu.incubate.checkpoint.auto_checkpoint")
    return acp is not None and acp._cfg is not None


# ---------------------------------------------------------------------------
# convenience used by tests and the fluid-style API
# ---------------------------------------------------------------------------


def run_startup(startup_program=None, place=None, scope=None):
    from .program import default_startup_program

    exe = Executor(place or CPUPlace())
    exe.run(startup_program or default_startup_program(), scope=scope)
    return exe
