"""Executor: compiles whole program blocks to single XLA computations.

Role parity: reference Executor (paddle/fluid/framework/executor.cc:180,
python/paddle/fluid/executor.py:913) — same ``run(program, feed,
fetch_list)`` contract.  TPU-native redesign (SURVEY.md §7): instead of the
reference's per-op interpreter hot loop (executor.cc:474-480, one scope
lookup + InferShape + kernel launch per op per step), the block is traced
ONCE through the lowering registry into a jax function

    (feeds, state, rng) -> (fetches, new_state, rng')

jitted with the state donated (in-place param update semantics), cached by
(program fingerprint, feed spec, fetch list, state spec).  Per-step cost is
one XLA executable launch; scheduling/fusion/memory are XLA's job (this
collapses the reference's ParallelExecutor/SSA-graph machinery,
parallel_executor.cc:504).
"""
from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import dtypes
from .lowering import PSEUDO_OPS, LoweringContext, get_lowering
from .place import CPUPlace, Place, _default_place
from .program import Program, Variable, default_main_program
from .scope import Scope, global_scope

logger = logging.getLogger(__name__)

RNG_VAR = "@RNG_KEY@"


@dataclass
class _Compiled:
    fn: object
    feed_names: Tuple[str, ...]
    state_mut: Tuple[str, ...]  # read & overwritten -> donated buffers
    state_const: Tuple[str, ...]  # read-only state
    state_out: Tuple[str, ...]
    fetch_names: Tuple[str, ...]
    uses_rng: bool
    n_calls: int = 0


def _feed_spec(block, feed: Dict[str, np.ndarray]):
    spec = []
    arrays = {}
    for name in sorted(feed):
        val = np.asarray(feed[name])
        var = block._find_var_recursive(name)
        if var is not None and var.dtype:
            want = dtypes.to_np(var.dtype)
            if val.dtype != want:
                val = val.astype(want)
        arrays[name] = val
        spec.append((name, val.shape, str(val.dtype)))
    return tuple(spec), arrays


class Executor:
    def __init__(self, place: Optional[Place] = None, mesh=None):
        self.place = place if place is not None else _default_place()
        self._cache: Dict[tuple, _Compiled] = {}
        # (program fingerprint, feed names, scope id) -> (state_in, state_out)
        self._analysis_cache: Dict[tuple, tuple] = {}
        self._mesh = mesh  # explicit mesh wins over the global parallel env

    def _active_mesh(self):
        if self._mesh is not None:
            return self._mesh
        try:
            from ..distributed.parallel_env import get_mesh

            return get_mesh()
        except ImportError:
            return None

    # ------------------------------------------------------------------
    def run(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, np.ndarray]] = None,
        fetch_list: Optional[Sequence] = None,
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
        use_program_cache: bool = True,  # always cached; kept for API parity
    ):
        import jax

        program = program if program is not None else default_main_program()
        feed = dict(feed or {})
        scope = scope if scope is not None else global_scope()
        fetch_names = tuple(
            v.name if isinstance(v, Variable) else str(v) for v in (fetch_list or [])
        )

        block = program.global_block
        spec, feed_arrays = _feed_spec(block, feed)

        # state the program will read from the scope (the full op walk is
        # cached; cache hits only re-check that the state vars still exist)
        akey = (program.fingerprint(), frozenset(feed), id(scope))
        cached = self._analysis_cache.get(akey)
        if cached is not None and all(scope.has_var(n) for n in cached[0]):
            state_in, state_out = cached
        else:
            state_in, state_out = self._analyze_state(program, set(feed), scope)
            self._analysis_cache[akey] = (state_in, state_out)
        state_spec = tuple(
            (n, tuple(np.shape(scope.get_var(n))), str(np.asarray(scope.get_var(n)).dtype))
            if not _is_jax_array(scope.get_var(n))
            else (n, tuple(scope.get_var(n).shape), str(scope.get_var(n).dtype))
            for n in state_in
        )

        mesh = self._active_mesh()
        key = (
            program.fingerprint(),
            spec,
            fetch_names,
            state_spec,
            type(self.place).__name__,
            self.place.device_id,
            id(mesh),
        )
        entry = self._cache.get(key)
        if entry is None:
            entry = self._compile(program, spec, state_in, state_out, fetch_names,
                                  mesh=mesh)
            self._cache[key] = entry

        # rng key lives in the scope so runs are deterministic/resumable
        if not scope.has_var(RNG_VAR) or scope.get_var(RNG_VAR) is None:
            seed = program.random_seed or 0
            scope.set_var(RNG_VAR, jax.random.PRNGKey(seed))

        feed_vals = tuple(feed_arrays[n] for n in entry.feed_names)
        mut_vals = tuple(scope.get_var(n) for n in entry.state_mut)
        const_vals = tuple(scope.get_var(n) for n in entry.state_const)
        rng = scope.get_var(RNG_VAR)

        fetches, new_state, new_rng = entry.fn(feed_vals, mut_vals, const_vals, rng)
        entry.n_calls += 1

        for n, v in zip(entry.state_out, new_state):
            scope.set_var(n, v)
        if entry.uses_rng:
            scope.set_var(RNG_VAR, new_rng)

        # localsgd strategy: periodic cross-replica parameter averaging
        # (set by LocalSGDMetaOptimizer; see fleet/collective_transpiler.py)
        localsgd = getattr(program, "_localsgd", None)
        if localsgd is not None:
            localsgd.average_step(self, scope=scope)

        if return_numpy:
            return [np.asarray(v) for v in fetches]
        return list(fetches)

    # ------------------------------------------------------------------
    def _analyze_state(self, program: Program, feed_names: set, scope: Scope):
        """Static use/def analysis of the root block (plus sub-blocks).

        state_in  = names read before written that are not feeds (must come
                    from the scope: parameters, optimizer state, ...)
        state_out = names written that should persist back into the scope
                    (persistable vars, or anything already living in scope).
        """
        written: set = set()
        state_in: List[str] = []
        state_out: List[str] = []
        seen_out: set = set()

        def visit_block(block):
            for op in block.ops:
                if op.type in PSEUDO_OPS:
                    continue
                for name in op.input_arg_names():
                    if name in feed_names or name in written:
                        continue
                    if name not in state_in:
                        if not scope.has_var(name) or scope.get_var(name) is None:
                            raise RuntimeError(
                                f"op {op.type!r} reads {name!r} which is neither a "
                                f"feed nor initialized in the scope. Did you run the "
                                f"startup program? (op built at: "
                                f"{op.callstack[-1] if op.callstack else '?'})"
                            )
                        state_in.append(name)
                # sub-blocks (control flow) contribute reads conservatively
                for aname in ("sub_block", "block"):
                    if op.has_attr(aname):
                        pass  # handled by control-flow lowering; vars resolved there
                for name in op.output_arg_names():
                    written.add(name)
                    var = block._find_var_recursive(name)
                    persistable = (var is not None and var.persistable) or scope.has_var(name)
                    if persistable and name not in seen_out:
                        seen_out.add(name)
                        state_out.append(name)

        visit_block(program.global_block)
        return tuple(state_in), tuple(state_out)

    # ------------------------------------------------------------------
    def _compile(self, program, feed_spec, state_in, state_out, fetch_names,
                 mesh=None) -> _Compiled:
        import jax

        feed_names = tuple(n for n, _, _ in feed_spec)
        block = program.global_block
        out_set = set(state_out)
        state_mut = tuple(n for n in state_in if n in out_set)
        state_const = tuple(n for n in state_in if n not in out_set)

        def trace_block(env, rng, axis_env=(), ring_axes=None):
            ctx = LoweringContext(block, env, rng_key=rng, mesh=mesh,
                                  axis_env=axis_env, ring_axes=ring_axes)
            for op in block.ops:
                if op.type in PSEUDO_OPS:
                    continue
                try:
                    get_lowering(op.type)(ctx, op)
                except Exception as e:
                    site = op.callstack[-1] if op.callstack else "<unknown>"
                    raise type(e)(
                        f"while lowering op {op.type!r} (built at {site}): {e}"
                    ) from e
            missing = [n for n in fetch_names if n not in env]
            if missing:
                raise KeyError(f"fetch vars not produced by program: {missing}")
            return ctx

        if mesh is None:
            def fn(feed_vals, mut_vals, const_vals, rng):
                env = {}
                env.update(zip(state_mut, mut_vals))
                env.update(zip(state_const, const_vals))
                env.update(zip(feed_names, feed_vals))
                ctx = trace_block(env, rng)
                fetches = tuple(env[n] for n in fetch_names)
                new_state = tuple(env[n] for n in state_out)
                return fetches, new_state, ctx.rng_key
        else:
            fn = self._build_sharded_fn(
                program, mesh, feed_spec, feed_names, state_mut, state_const,
                state_out, fetch_names, trace_block)

        # jit traces lazily on first call; donating the mutable state gives
        # in-place parameter-update memory behavior (buffers alias outputs).
        jfn = jax.jit(fn, donate_argnums=(1,))
        device = self.place.jax_device()

        if mesh is None:
            def run_on_device(feed_vals, mut_vals, const_vals, rng):
                with jax.default_device(device):
                    return jfn(feed_vals, mut_vals, const_vals, rng)
        else:
            run_on_device = jfn  # placement is the mesh's job

        compiled = _Compiled(
            fn=run_on_device,
            feed_names=feed_names,
            state_mut=state_mut,
            state_const=state_const,
            state_out=tuple(state_out),
            fetch_names=fetch_names,
            uses_rng=True,
        )
        return compiled

    def _build_sharded_fn(self, program, mesh, feed_spec, feed_names, state_mut,
                          state_const, state_out, fetch_names, trace_block):
        """SPMD execution over the mesh (reference ParallelExecutor role).

        The whole block runs inside shard_map: feeds are split on their
        batch dim over the 'dp' axis, state (params/opt accumulators) is
        replicated, and the program's own c_* collective ops become real
        XLA collectives.  Fetch semantics match the reference's
        all-workers view: scalars come back as the cross-replica mean
        (== full-batch loss for mean losses), batched tensors are
        re-assembled by all_gather on dim 0.
        """
        import jax
        from jax import lax
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        axis_names = tuple(mesh.axis_names)
        dp_axis = "dp" if "dp" in axis_names else axis_names[0]
        dp_size = int(mesh.shape[dp_axis])
        try:
            from ..distributed.parallel_env import ring_axes as _ring_axes

            rings = _ring_axes()
        except ImportError:
            rings = {}

        feed_in_specs = []
        sharded_feeds = set()
        for name, shape, _ in feed_spec:
            if len(shape) == 0 or shape[0] <= 1:
                feed_in_specs.append(P())  # scalars/broadcast feeds replicate
            elif shape[0] % dp_size == 0:
                feed_in_specs.append(P(dp_axis))
                sharded_feeds.add(name)
            else:
                raise ValueError(
                    f"feed {name!r} batch dim {shape[0]} is not divisible by "
                    f"the data-parallel degree {dp_size}; pad the batch or "
                    f"resize the mesh (silent replication would waste "
                    f"{dp_size}x compute)")
        feed_in_specs = tuple(feed_in_specs)

        # static dp-variance analysis: which vars differ across dp shards?
        # feeds sharded on dp are varying; ops propagate variance from
        # inputs to outputs; allreduce/broadcast/allgather make values
        # replica-invariant again.  Drives the fetch re-assembly below.
        _CLEARING = {"c_allreduce_sum", "c_allreduce_max", "c_allreduce_min",
                     "c_allreduce_prod", "c_broadcast", "c_allgather",
                     "allreduce"}
        varying = set(sharded_feeds)
        for op in program.global_block.ops:
            if op.type in PSEUDO_OPS:
                continue
            if op.type in _CLEARING:
                for n in op.output_arg_names():
                    varying.discard(n)
                continue
            if any(n in varying for n in op.input_arg_names()):
                varying.update(op.output_arg_names())

        def traced(feed_vals, mut_vals, const_vals, rng):
            env = {}
            env.update(zip(state_mut, mut_vals))
            env.update(zip(state_const, const_vals))
            env.update(zip(feed_names, feed_vals))
            # per-shard randomness: fold the dp index into the key; the
            # carried key advances identically on every shard
            local_rng = jax.random.fold_in(rng, lax.axis_index(dp_axis))
            ctx = trace_block(env, local_rng, axis_env=axis_names,
                              ring_axes=rings)
            new_rng = jax.random.split(rng, 2)[0] if ctx.rng_consumed else rng
            fetches = []
            for n in fetch_names:
                v = env[n]
                if n not in varying:
                    fetches.append(v)  # replica-invariant: local copy is it
                elif getattr(v, "ndim", 0) == 0 or v.size == 1:
                    # dp-varying scalars (losses, metrics): cross-replica
                    # mean == the full-batch value for mean-reduced losses
                    fetches.append(lax.pmean(v, axis_names))
                else:
                    # dp-varying batched values: re-assemble the full batch
                    fetches.append(lax.all_gather(v, dp_axis, axis=0, tiled=True))
            new_state = tuple(env[n] for n in state_out)
            return tuple(fetches), new_state, new_rng

        return shard_map(
            traced,
            mesh=mesh,
            in_specs=(feed_in_specs,
                      tuple(P() for _ in state_mut),
                      tuple(P() for _ in state_const),
                      P()),
            out_specs=(tuple(P() for _ in fetch_names),
                       tuple(P() for _ in state_out),
                       P()),
            check_vma=False,
        )

    def close(self):
        self._cache.clear()


def _is_jax_array(x) -> bool:
    return hasattr(x, "sharding") and hasattr(x, "dtype")


# ---------------------------------------------------------------------------
# convenience used by tests and the fluid-style API
# ---------------------------------------------------------------------------


def run_startup(startup_program=None, place=None, scope=None):
    from .program import default_startup_program

    exe = Executor(place or CPUPlace())
    exe.run(startup_program or default_startup_program(), scope=scope)
    return exe
