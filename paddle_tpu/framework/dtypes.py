"""Dtype bridging between the IR enum, numpy, and jax.

Role parity: reference framework.proto VarType::Type + data_type.h maps
(`framework::proto::VarType::FP32` etc.) — here a single table keyed by the
proto enum in paddle_tpu/proto/ir.proto.
"""
from __future__ import annotations

import numpy as np

from . import ir_pb2

# Public names mirror the reference's string dtype vocabulary so user code
# like ``fluid.data(..., dtype='float32')`` works unchanged.
_STR_TO_ENUM = {
    "float32": ir_pb2.DT_FP32,
    "float64": ir_pb2.DT_FP64,
    "float16": ir_pb2.DT_FP16,
    "bfloat16": ir_pb2.DT_BF16,
    "int8": ir_pb2.DT_INT8,
    "int16": ir_pb2.DT_INT16,
    "int32": ir_pb2.DT_INT32,
    "int64": ir_pb2.DT_INT64,
    "uint8": ir_pb2.DT_UINT8,
    "uint16": ir_pb2.DT_UINT16,
    "uint32": ir_pb2.DT_UINT32,
    "uint64": ir_pb2.DT_UINT64,
    "bool": ir_pb2.DT_BOOL,
    "complex64": ir_pb2.DT_COMPLEX64,
    "complex128": ir_pb2.DT_COMPLEX128,
}

_ENUM_TO_STR = {v: k for k, v in _STR_TO_ENUM.items()}


def to_enum(dtype) -> int:
    """Normalize a dtype spec (str | np.dtype | jnp dtype | enum) to the IR enum."""
    if isinstance(dtype, int):
        if dtype not in _ENUM_TO_STR and dtype != ir_pb2.DT_UNDEFINED:
            raise ValueError(f"unknown dtype enum {dtype}")
        return dtype
    if isinstance(dtype, str):
        if dtype not in _STR_TO_ENUM:
            raise ValueError(f"unknown dtype string {dtype!r}")
        return _STR_TO_ENUM[dtype]
    # numpy / jax dtype objects (incl. ml_dtypes.bfloat16)
    name = np.dtype(dtype).name if not hasattr(dtype, "name") else dtype.name
    if name not in _STR_TO_ENUM:
        name = np.dtype(dtype).name
    if name not in _STR_TO_ENUM:
        raise ValueError(f"unknown dtype {dtype!r}")
    return _STR_TO_ENUM[name]


def to_str(dtype) -> str:
    return _ENUM_TO_STR[to_enum(dtype)]


def to_np(dtype):
    """IR enum/str -> numpy dtype (bfloat16 via ml_dtypes)."""
    s = to_str(dtype)
    if s == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(s)


def to_jnp(dtype):
    import jax.numpy as jnp

    s = to_str(dtype)
    return jnp.dtype(s)


def is_floating(dtype) -> bool:
    return to_enum(dtype) in (
        ir_pb2.DT_FP32,
        ir_pb2.DT_FP64,
        ir_pb2.DT_FP16,
        ir_pb2.DT_BF16,
    )


def is_integer(dtype) -> bool:
    return to_enum(dtype) in (
        ir_pb2.DT_INT8,
        ir_pb2.DT_INT16,
        ir_pb2.DT_INT32,
        ir_pb2.DT_INT64,
        ir_pb2.DT_UINT8,
        ir_pb2.DT_UINT16,
        ir_pb2.DT_UINT32,
        ir_pb2.DT_UINT64,
    )
