"""Program-IR optimization pass pipeline.

Role parity: reference build-strategy graph passes
(framework/ir/pass.h, build_strategy.cc) — most prominently
`fuse_all_reduce_op_pass` + `coalesce_tensor_op` (Horovod-style tensor
fusion): instead of one latency-bound `c_allreduce_sum` per gradient,
same-dtype grads are flattened into size-capped fused buffers and
reduced per bucket.  On a ResNet/BERT step this turns hundreds of
small collectives into a handful of bandwidth-bound ones.

TPU-native framing: passes are *program rewrites applied before
lowering*, not graph-node surgery on an SSA graph — the Executor clones
the program, runs the pipeline on the clone, and compiles the rewritten
clone, so the user's program object is never mutated (with
``fuse_all_reduce_ops=False`` or ``FLAGS_fuse_passes=0`` the exact
pre-pass program compiles).  Application is cached per
``(program.fingerprint(), pass config)`` by the Executor; the
``FLAGS_fuse_passes`` flag is registered with ``affects_lowering=True``
so flipping it re-keys the compile cache too.

Passes in default order:

0. ``ShardingPropagationPass`` — tensor-parallel auto-sharding: maps
   the ordered regex partition rules the TensorParallelMetaOptimizer
   stamped onto the program over every var, propagates specs through
   the op stream (``with_sharding_constraint`` anchors at matmul ops,
   replicated fallback), makes optimizer slots inherit their param's
   spec, and attaches the :class:`TPShardingPlan` the Executor lowers
   to ``NamedSharding`` jit in/out specs on the dp×mp mesh.  Runs
   FIRST so the fuse pass below sees its per-collective spec stamps.
1. ``FuseAllReducePass`` — groups the `c_allreduce_sum` ops the
   collective transpiler marked (``__fused_allreduce__`` attr) into
   per-dtype buckets capped at ``__fuse_grad_size_mb__`` (default 32 MB,
   ``DistributedStrategy.fuse_grad_size_in_MB``), and rewrites each
   bucket into ``coalesce_tensor`` (flatten+concat) → one
   ``c_allreduce_sum`` → ``uncoalesce_tensor`` (split+reshape back),
   anchored at the LAST original allreduce of the bucket so the fused
   collective still launches as soon as its last gradient is produced
   (comm/backward overlap is preserved).  Under the fp16/bf16 allreduce
   strategy the per-grad cast pairs collapse to one pair per bucket.
2. ``RedundantCastEliminationPass`` — removes `cast` ops whose input
   provably already holds the target dtype (tracked by a conservative
   forward dataflow; unknown dtypes are never touched).
3. ``DeadOpEliminationPass`` — drops ops that feed neither a fetch nor
   persistent/scope-resident state, reusing the executor's
   ``_prune_ops`` backward slice (side-effect ops like `send_v2` are
   always kept).

Observability (``paddle_tpu.monitor``): ``pass_fused_allreduce_buckets``,
``pass_allreduce_ops_before`` / ``pass_allreduce_ops_after``,
``pass_dead_ops_removed``, ``pass_casts_removed``, and the Executor's
``executor_pass_cache_hit``.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import dtypes

GRAD_SUFFIX_TP = "@GRAD"  # == program.GRAD_SUFFIX (local: no import cycle)

__all__ = [
    "FUSED_ALLREDUCE_ATTR",
    "FUSE_SIZE_ATTR",
    "DEFAULT_FUSE_MB",
    "TP_RULES_ATTR",
    "TP_DEGREE_ATTR",
    "TP_SPEC_ATTR",
    "TP_CONSTRAINT_ATTR",
    "DP_LOSS_SCALE_ATTR",
    "DEFAULT_MEGATRON_RULES",
    "encode_spec",
    "decode_spec",
    "TPShardingPlan",
    "Pass",
    "PassContext",
    "PassPipeline",
    "ShardingPropagationPass",
    "FuseAllReducePass",
    "RedundantCastEliminationPass",
    "DeadOpEliminationPass",
    "register_pass",
    "default_pipeline",
    "apply_passes",
]

# op-attr markers stamped by the collective transpiler
# (distributed/fleet/collective_transpiler.py GradAllReduce) on the ops
# it wants fused; attrs — not python side channels — so the linkage
# survives clone/proto round-trips and joins the program fingerprint
FUSED_ALLREDUCE_ATTR = "__fused_allreduce__"
FUSE_SIZE_ATTR = "__fuse_grad_size_mb__"
DEFAULT_FUSE_MB = 32.0

# tensor-parallel markers (TensorParallelMetaOptimizer stamps the first
# two on the program's optimizer ops; ShardingPropagationPass stamps the
# next two per-op).  All are op attrs so the tp contract survives
# clone/proto round-trips AND joins the program fingerprint — a changed
# rule list re-keys every executor cache automatically.
TP_RULES_ATTR = "__tp_rules__"          # list of "regex\tspec" strings
TP_DEGREE_ATTR = "__tp_degree__"        # required mp degree (0 = any)
TP_SPEC_ATTR = "__tp_spec__"            # on grad collectives: grad's spec
TP_CONSTRAINT_ATTR = "__tp_constraint__"  # list of "var\tspec" anchors
# stamped by GradAllReduce/ShardingMetaOptimizer on the 1/nranks
# loss-grad scale op so the tensor-parallel meta-optimizer can remove it
# (GSPMD computes global-batch-mean gradients directly; keeping the
# scale would shrink every gradient by the dp degree)
DP_LOSS_SCALE_ATTR = "__dp_loss_scale__"


def encode_spec(spec) -> str:
    """Partition spec tuple -> attr string: ``(None,'mp')`` -> "None,mp".
    The empty tuple (fully replicated / scalar) encodes as ""."""
    return ",".join("None" if s is None else str(s) for s in spec)


def decode_spec(enc: str):
    """Inverse of :func:`encode_spec`."""
    if not enc:
        return ()
    return tuple(None if tok == "None" else tok for tok in enc.split(","))


# Megatron-LM style defaults over this framework's parameter naming
# (layer_helper: "<name>.w_0"/"<name>.b_0"; text/static_models.py BERT:
# enc_<i>_{q,k,v,out}, enc_<i>_{ffn1,ffn2}, word_embedding).  Ordered:
# first match wins.  Anything unmatched stays replicated — plain fc
# stacks have no inherent row/column orientation, so generic fc params
# are NOT sharded by default (pass partition_rules for custom nets).
DEFAULT_MEGATRON_RULES = (
    # attention QKV projections: column-parallel (heads split over mp)
    (r"(_q|_k|_v|_qkv|_query|_key|_value)\.w_\d+$", "None,mp"),
    (r"(_q|_k|_v|_qkv|_query|_key|_value)\.b_\d+$", "mp"),
    # attention/vocab output projections: row-parallel (mp-sharded
    # contraction; the pass anchors the partial-sum reduce there)
    (r"(_out|_proj|_o)\.w_\d+$", "mp,None"),
    # transformer FFN: first fc column-parallel, second row-parallel
    (r"(_ffn1|_fc1|_h_4h)\.w_\d+$", "None,mp"),
    (r"(_ffn1|_fc1|_h_4h)\.b_\d+$", "mp"),
    (r"(_ffn2|_fc2|_4h_h)\.w_\d+$", "mp,None"),
    # vocab-parallel embedding table (rows = vocab over mp)
    (r"^word_embedding$", "mp,None"),
)


class TPShardingPlan:
    """The ShardingPropagationPass output: name -> partition-axes tuple
    over the named (dp, mp) mesh, plus the static grad-reduce
    accounting the telemetry layer reads.

    Attached to the POST-pass program object (``program._tp_plan``);
    the Executor compiles the tp program through ``jax.jit`` with
    ``NamedSharding`` in/out specs built from this plan (GSPMD —
    semantics stay those of the single logical program, sharding is
    pure layout, and XLA inserts the mp partial-sum reduces the
    constraint anchors pin)."""

    __slots__ = ("specs", "mp_degree", "dp_axis", "mp_axis",
                 "grad_reduce", "n_sharded", "n_fallback")

    def __init__(self, specs, mp_degree, dp_axis="dp", mp_axis="mp",
                 grad_reduce=None, n_sharded=0, n_fallback=0):
        self.specs = dict(specs)
        self.mp_degree = int(mp_degree)
        self.dp_axis = dp_axis
        self.mp_axis = mp_axis
        # grad name -> {"axes": ("dp",), "bytes": per-step payload of
        # its dp allreduce (shard-local bytes for mp-sharded grads)}
        self.grad_reduce = dict(grad_reduce or {})
        self.n_sharded = int(n_sharded)
        self.n_fallback = int(n_fallback)

    def spec_tuple(self, name: str) -> tuple:
        return tuple(self.specs.get(name, ()))

    def partition_spec(self, name: str):
        from jax.sharding import PartitionSpec

        return PartitionSpec(*self.specs.get(name, ()))

    def named_sharding(self, mesh, name: str):
        from jax.sharding import NamedSharding

        return NamedSharding(mesh, self.partition_spec(name))

    def shard_divisor(self, name: str, mesh=None) -> int:
        """How many chips one copy of ``name`` is split over: the
        product of the mesh-axis sizes in its spec (1 for replicated or
        unknown vars).  The HBM-attribution join
        (observe/xla_stats.py): per-chip bytes = global bytes / this."""
        n = 1
        for ax in self.specs.get(name, ()):
            if ax is None:
                continue
            for a in (ax if isinstance(ax, (tuple, list)) else (ax,)):
                if mesh is not None and a in mesh.axis_names:
                    n *= int(mesh.shape[a])
        return max(n, 1)

    def spec_str(self, name: str) -> str:
        """Human-readable spec for error messages / attribution tables:
        ``P(None, 'mp')`` for sharded vars, ``replicated`` otherwise."""
        spec = self.specs.get(name, ())
        if not spec or all(ax is None for ax in spec):
            return "replicated"
        return "P(" + ", ".join(
            "None" if ax is None else repr(ax) for ax in spec) + ")"

    def __repr__(self):
        return (f"TPShardingPlan(mp={self.mp_degree}, "
                f"sharded={self.n_sharded}, fallback={self.n_fallback})")


class PassContext:
    """Per-application context: what the Executor knows at dispatch time.

    ``fetch_names``/``feed_names``/``scope`` feed the dead-op slice and
    the cast dataflow; all three join the Executor's pass-cache key.
    ``mesh`` (the executor's active mesh) drives the tensor-parallel
    sharding pass and joins the cache key by identity."""

    def __init__(self, fetch_names: Sequence[str] = (),
                 feed_names: Sequence[str] = (), scope=None, mesh=None):
        self.fetch_names = tuple(fetch_names)
        self.feed_names = tuple(feed_names)
        self.scope = scope
        self.mesh = mesh
        # per-application scratch for passes (e.g. DCE memoizes its
        # prune slice across should_apply/apply)
        self._memo: Dict[tuple, object] = {}


class Pass:
    """One program rewrite.  ``apply`` mutates ``program`` in place and
    returns True iff it changed anything (drives the pipeline's
    copy-on-write: an all-no-op run hands the ORIGINAL program back to
    the Executor)."""

    name = "pass"

    def should_apply(self, program, ctx: PassContext) -> bool:
        return True

    def apply(self, program, ctx: PassContext) -> bool:
        raise NotImplementedError


PASS_REGISTRY: Dict[str, type] = {}


def register_pass(cls):
    """Register a Pass subclass into the ordered default registry and
    rebuild the default pipeline on next use (a registration after the
    first Executor run would otherwise be silently inert)."""
    global _default_pipeline
    if cls.name in PASS_REGISTRY:
        raise KeyError(f"pass {cls.name!r} already registered")
    PASS_REGISTRY[cls.name] = cls
    _default_pipeline = None
    return cls


def _numel(shape) -> int:
    n = 1
    for s in shape:
        n *= int(s)
    return n


def _itemsize(dtype_str: str) -> int:
    return int(np.dtype(dtypes.to_np(dtype_str)).itemsize)


def _marked_inplace_cast(op, name: str) -> bool:
    return (op.type == "cast" and bool(op.attr(FUSED_ALLREDUCE_ATTR))
            and op.inputs.get("X", []) == [name]
            and op.outputs.get("Out", []) == [name])


def has_tp_marks(program) -> bool:
    """True when a TensorParallelMetaOptimizer stamped this program
    (the executor refuses to run such a program outside the GSPMD tp
    path — the dp loss-grad scale was removed, so the shard_map dp
    path would compute wrong gradients)."""
    return any(op.attr(TP_RULES_ATTR) for op in program.global_block.ops)


# ops whose output provably carries its (first) input's partition spec
# through unchanged — the propagation walks only through these plus the
# structured handlers below; everything else resets to unknown
_TP_SPEC_PRESERVING = {
    "relu", "gelu", "tanh", "sigmoid", "softmax", "dropout", "cast",
    "scale", "assign", "c_identity", "recompute_barrier", "relu_grad",
    "gelu_grad", "tanh_grad", "sigmoid_grad", "dropout_grad",
    "layer_norm",  # Y spec == X spec (mean/var reduce over trailing
                   # dims is GSPMD's job when those dims are sharded)
}

_TP_MATMUL_OPS = {"mul", "matmul", "matmul_v2"}


@register_pass
class ShardingPropagationPass(Pass):
    """Tensor-parallel auto-sharding (GSPMD substrate; SNIPPETS.md [2]
    ``match_partition_rules`` -> ``NamedSharding`` -> pjit).

    Input contract: the TensorParallelMetaOptimizer stamped the
    program's optimizer ops with ``TP_RULES_ATTR`` (ordered regex ->
    spec rules) and ``TP_DEGREE_ATTR``; ``ctx.mesh`` is a named mesh
    with an 'mp' axis.

    What it does:

    1. **Param matching** — every block var is matched against the
       ordered rules (first match wins); a matched var whose sharded
       dims are not divisible by the mp degree falls back to replicated
       (counted in ``pass_tp_fallback_replicated``, never dropped).
    2. **Slot inheritance** — optimizer accumulator slots (Velocity,
       Moment1/2, ... — the _OPTIMIZER_ACC_SLOTS table) and param-shaped
       persistable extras (MasterParam) inherit their Param's spec;
       ZeRO-1 ``__sharded_accumulators__`` of replicated params get
       P('dp') on dim 0 instead (optimizer-state memory still drops by
       the dp degree under GSPMD layout sharding).
    3. **Propagation** — a forward walk assigns specs to intermediates
       (matmul contraction/output rules, elementwise merge, transpose
       permute, spec-preserving ops, ``X@GRAD`` inherits X's spec) and
       stamps ``TP_CONSTRAINT_ATTR`` on matmul-family anchor ops so the
       lowering applies ``with_sharding_constraint`` there — pinning
       the Megatron pattern: a row-parallel matmul's output constrained
       replicated-on-mp forces XLA to place the mp partial-sum reduce
       at that op.  Unknown intermediates stay unconstrained
       (replicated fallback; GSPMD chooses).
    4. **Grad-collective stamping** — transpiler-inserted
       ``c_allreduce_sum`` ops whose grad is mp-sharded get
       ``TP_SPEC_ATTR`` (so FuseAllReducePass never buckets across
       sharding specs, and the collective span/byte telemetry reports
       the dp-axis shard payload, not the full grad).
    5. Attaches the :class:`TPShardingPlan` as ``program._tp_plan`` for
       the Executor's GSPMD compile path.
    """

    name = "sharding_propagation"

    def should_apply(self, program, ctx):
        mesh = getattr(ctx, "mesh", None)
        if mesh is None or "mp" not in getattr(mesh, "axis_names", ()):
            return False
        return has_tp_marks(program)

    def apply(self, program, ctx):
        import re

        from ..monitor import stat_set

        mesh = ctx.mesh
        mp_degree = int(mesh.shape["mp"])
        block = program.global_block
        ops = block.ops

        rules, want_degree = self._read_config(ops)
        if want_degree and want_degree != mp_degree:
            raise ValueError(
                f"tensor_parallel_degree={want_degree} but the active "
                f"mesh's 'mp' axis has {mp_degree} devices; rebuild the "
                f"mesh (init_parallel_env(mesh_shape=(dp, {want_degree}), "
                f"axis_names=('dp', 'mp'))) or unset the degree")
        # a spec/anchor naming a mesh axis that does not exist would
        # crash deep inside jax at trace time; any axis absent from
        # THIS mesh (a pure-mp 1D mesh has no 'dp'; user rules may name
        # arbitrary axes) degrades to None (replicated on that dim)
        axes = set(mesh.axis_names)

        def sanitize(spec):
            return tuple(s if s in axes else None for s in spec)

        compiled_rules = [(re.compile(pat), sanitize(decode_spec(enc)))
                          for pat, enc in rules]

        # -- 1. rule-match every var (params seed the state layout) ----
        specs: Dict[str, tuple] = {}
        n_sharded = n_fallback = 0
        for name, var in block.vars.items():
            spec = self._match(compiled_rules, name)
            if spec is None:
                continue
            spec = self._fit(spec, var.shape)
            if spec is None or not any(s == "mp" for s in spec):
                continue
            if not self._divisible(var.shape, spec, mp_degree):
                n_fallback += 1
                continue
            specs[name] = spec
            n_sharded += 1

        # -- 2. optimizer slots inherit their param's spec -------------
        self._inherit_slots(block, ops, specs, has_dp="dp" in axes)

        # -- 3+4. propagate, stamp anchors and grad collectives --------
        grad_reduce = self._propagate(block, ops, dict(specs), ctx,
                                      mp_degree, has_dp="dp" in axes)

        program._tp_plan = TPShardingPlan(
            specs, mp_degree, grad_reduce=grad_reduce,
            n_sharded=n_sharded, n_fallback=n_fallback)
        program._bump()
        stat_set("pass_tp_sharded_vars", n_sharded)
        stat_set("pass_tp_fallback_replicated", n_fallback)
        stat_set("pass_tp_mp_degree", mp_degree)
        return True

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _read_config(ops):
        for op in ops:
            enc = op.attr(TP_RULES_ATTR)
            if enc:
                rules = []
                for ent in enc:
                    pat, _, spec = ent.partition("\t")
                    rules.append((pat, spec))
                return rules, int(op.attr(TP_DEGREE_ATTR, 0) or 0)
        return [], 0

    @staticmethod
    def _match(compiled_rules, name):
        for rx, spec in compiled_rules:
            if rx.search(name):
                return spec
        return None

    @staticmethod
    def _fit(spec, shape):
        """Right-size a rule spec to the var's rank: a 2-dim rule on a
        scalar/1-dim var keeps its TRAILING entries ("None,mp" applies
        to a bias as "mp"); over-long specs never shard a var they
        don't fit."""
        rank = len(shape)
        if rank == 0:
            return None
        if len(spec) > rank:
            spec = spec[-rank:]
        if len(spec) < rank:
            spec = (None,) * (rank - len(spec)) + tuple(spec)
        return tuple(spec)

    @staticmethod
    def _divisible(shape, spec, mp_degree):
        for dim, s in zip(shape, spec):
            if s == "mp" and int(dim) % mp_degree != 0:
                return False
        return True

    @staticmethod
    def _inherit_slots(block, ops, specs, has_dp=True):
        """Optimizer accumulator slots (and param-shaped persistable
        extras like MasterParam) inherit their Param's spec; ZeRO-1
        ``__sharded_accumulators__`` of replicated params get P('dp')
        on dim 0 instead (state memory still drops by the dp degree —
        GSPMD layout sharding replaces the shard_map reducescatter
        machinery, whose c_* ops lower to identity on this path)."""
        # slot table lives with the optimizer-op knowledge in fleet;
        # lazy import avoids a framework->fleet import cycle
        from ..distributed.fleet.meta_optimizers import (
            _OPTIMIZER_ACC_SLOTS, _OPTIMIZER_OP_TYPES)

        for op in ops:
            zero_accs = set(op.attr("__sharded_accumulators__", None) or ())
            if op.type not in _OPTIMIZER_OP_TYPES and not zero_accs:
                continue
            pnames = op.inputs.get("Param", [])
            # the ZeRO transpile rewires Param to "<name>@SHARD"; the
            # rule matched the base param name
            base = pnames[0][:-len("@SHARD")] \
                if pnames and pnames[0].endswith("@SHARD") else \
                (pnames[0] if pnames else None)
            pspec = specs.get(base) if base else None
            pvar = block._find_var_recursive(base) if base else None
            acc_slots = _OPTIMIZER_ACC_SLOTS.get(op.type, ())
            for slot, names in op.inputs.items():
                if slot in ("Param", "Grad", "LearningRate"):
                    continue
                for nm in names:
                    if nm in specs:
                        continue
                    var = block._find_var_recursive(nm)
                    if var is None or not var.shape:
                        continue
                    param_shaped = (pvar is not None
                                    and tuple(var.shape) == tuple(pvar.shape))
                    if pspec is not None and (slot in acc_slots
                                              or (param_shaped
                                                  and var.persistable)
                                              or nm in zero_accs):
                        specs[nm] = pspec
                    elif nm in zero_accs and has_dp:
                        # ZeRO accumulator of a replicated param: keep
                        # the optimizer-state-over-dp layout
                        specs[nm] = ("dp",) + (None,) * (len(var.shape) - 1)

    def _propagate(self, block, ops, known, ctx, mp_degree, has_dp=True):
        """Forward spec walk over the op stream.  ``known`` maps var
        name -> spec tuple (entries None|'dp'|'mp'); feeds seed 'dp' on
        their batch dim (when the mesh has one).  Returns the per-grad
        reduce accounting for grads riding a transpiler c_allreduce_sum."""
        if has_dp:
            for fname in ctx.feed_names:
                var = block._find_var_recursive(fname)
                if var is not None and len(var.shape) >= 1 \
                        and fname not in known:
                    known[fname] = ("dp",) + (None,) * (len(var.shape) - 1)

        grad_reduce: Dict[str, dict] = {}
        for op in ops:
            if op.type in _TP_MATMUL_OPS:
                self._prop_matmul(op, known)
            elif op.type == "transpose" or op.type == "transpose2":
                self._prop_transpose(op, known)
            elif op.type.startswith("elementwise_") \
                    and not op.type.endswith("_grad"):
                self._prop_elementwise(op, known)
            elif op.type in _TP_SPEC_PRESERVING:
                xs = op.inputs.get("X", [])
                spec = known.get(xs[0]) if len(xs) == 1 else None
                for n in op.output_arg_names():
                    if spec is not None and self._rank_ok(block, n, spec):
                        known[n] = spec
                    else:
                        known.pop(n, None)
            elif op.type == "c_allreduce_sum":
                # transpiler grad collective: identity under GSPMD (the
                # grad is already the global sum); stamp the grad's spec
                # so fuse bucketing and telemetry stay shard-aware
                g = op.inputs.get("X", [None])[0]
                spec = known.get(g)
                var = block._find_var_recursive(g) if g else None
                if var is not None and var.shape \
                        and all(int(s) > 0 for s in var.shape):
                    try:
                        nbytes = _numel(var.shape) * _itemsize(
                            dtypes.to_str(var.dtype))
                    except (KeyError, ValueError):
                        continue
                    if spec and "mp" in spec:
                        nbytes //= mp_degree
                        op.attrs[TP_SPEC_ATTR] = encode_spec(spec)
                    grad_reduce[g] = {"axes": ("dp",), "bytes": nbytes}
                continue
            elif op.type.endswith("_grad"):
                # the gradient of a var shares its var's layout (the
                # Megatron memo: dW of a column-parallel W is itself
                # column-parallel); unknown bases reset to unknown
                for n in op.output_arg_names():
                    base_spec = None
                    if n.endswith(GRAD_SUFFIX_TP):
                        base_spec = known.get(n[:-len(GRAD_SUFFIX_TP)])
                    if base_spec is not None \
                            and self._rank_ok(block, n, base_spec):
                        known[n] = base_spec
                    else:
                        known.pop(n, None)
            else:
                for n in op.output_arg_names():
                    known.pop(n, None)
        return grad_reduce

    @staticmethod
    def _rank_ok(block, name, spec):
        var = block._find_var_recursive(name)
        return var is not None and len(var.shape) == len(spec)

    def _prop_matmul(self, op, known):
        """out spec = x row dims + y col dim; an mp-sharded contraction
        makes the output a partial sum — anchoring a constraint on the
        output (its non-contracted spec) makes XLA place the mp reduce
        exactly here (Megatron's g operator)."""
        xs, ys = op.inputs.get("X", []), op.inputs.get("Y", [])
        outs = op.output_arg_names()
        if len(xs) != 1 or len(ys) != 1 or len(outs) != 1:
            return
        xspec, yspec = known.get(xs[0]), known.get(ys[0])
        if xspec is None and yspec is None:
            known.pop(outs[0], None)
            return
        var = op.block._find_var_recursive(outs[0])
        if var is None or not var.shape:
            known.pop(outs[0], None)
            return
        rank = len(var.shape)
        if op.type == "mul":
            ncol = int(op.attr("x_num_col_dims", 1) or 1)
            row = tuple(xspec[:ncol]) if xspec is not None \
                else (None,) * ncol
            col = (yspec[-1] if yspec is not None else None,)
            spec = row + col
            contracted = ((xspec is not None
                           and any(s == "mp" for s in xspec[ncol:]))
                          or (yspec is not None
                              and any(s == "mp" for s in yspec[:-1])))
        else:  # matmul / matmul_v2: batch dims ride through from X
            tx = bool(op.attr("transpose_X", op.attr("trans_x", False)))
            ty = bool(op.attr("transpose_Y", op.attr("trans_y", False)))
            xrow = (xspec[-1] if tx else xspec[-2]) \
                if xspec is not None and len(xspec) >= 2 else None
            xk = (xspec[-2] if tx else xspec[-1]) \
                if xspec is not None and len(xspec) >= 2 else None
            ycol = (yspec[-2] if ty else yspec[-1]) \
                if yspec is not None and len(yspec) >= 2 else None
            yk = (yspec[-1] if ty else yspec[-2]) \
                if yspec is not None and len(yspec) >= 2 else None
            batch = tuple(xspec[:rank - 2]) if xspec is not None \
                and len(xspec) == rank else (None,) * (rank - 2)
            spec = batch + (xrow, ycol)
            contracted = (xk == "mp") or (yk == "mp")
        if len(spec) != rank:
            known.pop(outs[0], None)
            return
        spec = tuple(s if s in (None, "dp", "mp") else None for s in spec)
        known[outs[0]] = spec
        if contracted or any(s == "mp" for s in spec):
            # anchor: pin the output layout so the partial-sum reduce
            # (or the sharded-activation layout) lands at this op
            ents = list(op.attrs.get(TP_CONSTRAINT_ATTR, []) or [])
            ents.append(f"{outs[0]}\t{encode_spec(spec)}")
            op.attrs[TP_CONSTRAINT_ATTR] = ents

    @staticmethod
    def _prop_transpose(op, known):
        xs = op.inputs.get("X", [])
        outs = op.output_arg_names()
        axes = [int(a) for a in (op.attr("axis", []) or [])]
        spec = known.get(xs[0]) if len(xs) == 1 else None
        if spec is None or len(axes) != len(spec) or not outs:
            for n in outs:
                known.pop(n, None)
            return
        known[outs[0]] = tuple(spec[a] for a in axes)

    @staticmethod
    def _prop_elementwise(op, known):
        xs, ys = op.inputs.get("X", []), op.inputs.get("Y", [])
        outs = op.output_arg_names()
        if len(xs) != 1 or len(outs) != 1:
            return
        xspec = known.get(xs[0])
        if xspec is not None:
            known[outs[0]] = xspec  # Y broadcasts into X's layout
        else:
            known.pop(outs[0], None)


@register_pass
class FuseAllReducePass(Pass):
    """Bucketed gradient-allreduce fusion (reference
    fuse_all_reduce_op_pass + coalesce_tensor_op).

    Only `c_allreduce_sum` ops carrying ``__fused_allreduce__`` are
    touched: the transpiler stamps exactly the per-gradient collectives
    it inserted, so user-built collectives and the sharding
    reduce-scatter path are never rewritten.  Grads whose var has an
    unknown/dynamic shape stay unfused (loudly counted, never dropped).

    Safe-placement invariant: the transpiler emits each allreduce
    immediately after its grad's last producer and every grad CONSUMER
    (optimizer/merge/clip/dgc) sits after the whole backward region, so
    anchoring the fused collective at the bucket's last original
    allreduce can never move a reduction past a read of its input.
    """

    name = "fuse_allreduce"

    def should_apply(self, program, ctx):
        return any(op.type == "c_allreduce_sum"
                   and op.attr(FUSED_ALLREDUCE_ATTR)
                   for op in program.global_block.ops)

    def apply(self, program, ctx):
        from ..monitor import stat_set

        block = program.global_block
        ops = block.ops
        n_before = sum(1 for op in ops if op.type == "c_allreduce_sum")

        entries = self._collect(block, ops)
        if not entries:
            return False
        buckets = self._bucketize(entries)
        fuse_buckets = [b for b in buckets if len(b["items"]) >= 2]
        if not fuse_buckets:
            return False

        removed: set = set()
        anchor_to_bucket: Dict[int, tuple] = {}
        for bi, b in enumerate(fuse_buckets):
            for e in b["items"]:
                removed.update(e["remove"])
            anchor = max(e["anchor"] for e in b["items"])
            anchor_to_bucket[anchor] = (bi, b)

        new_ops: List = []
        for i, op in enumerate(ops):
            if i in anchor_to_bucket:
                bi, b = anchor_to_bucket[i]
                new_ops.extend(self._emit_bucket(block, bi, b))
                continue
            if i in removed:
                continue
            new_ops.append(op)
        block.ops[:] = new_ops
        program._bump()

        n_after = sum(1 for op in new_ops if op.type == "c_allreduce_sum")
        stat_set("pass_fused_allreduce_buckets", len(fuse_buckets))
        stat_set("pass_allreduce_ops_before", n_before)
        stat_set("pass_allreduce_ops_after", n_after)
        return True

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _collect(block, ops) -> List[dict]:
        """One marked allreduce (+ its adjacent marked fp16 cast pair)
        per entry, in program order."""
        entries = []
        for i, op in enumerate(ops):
            if op.type != "c_allreduce_sum" \
                    or not op.attr(FUSED_ALLREDUCE_ATTR):
                continue
            xs = op.inputs.get("X", [])
            if len(xs) != 1 or op.outputs.get("Out", []) != xs:
                continue  # only the transpiler's in-place form fuses
            g = xs[0]
            var = block._find_var_recursive(g)
            if var is None or any(int(s) <= 0 for s in var.shape):
                continue  # unknown/dynamic shape: leave unfused
            try:
                dtype = dtypes.to_str(var.dtype)
            except (KeyError, ValueError):
                continue
            remove = [i]
            anchor = i
            pre = i > 0 and _marked_inplace_cast(ops[i - 1], g)
            post = i + 1 < len(ops) and _marked_inplace_cast(ops[i + 1], g)
            if pre and post:
                remove += [i - 1, i + 1]
                anchor = i + 1
            entries.append({
                "grad": g,
                "shape": tuple(int(s) for s in var.shape),
                "dtype": dtype,
                "bytes": _numel(var.shape) * _itemsize(dtype),
                "fp16": pre and post,
                "ring_id": int(op.attr("ring_id", 0) or 0),
                # tensor-parallel spec stamped by ShardingPropagationPass
                # (runs first): joins the bucket key so differently-
                # sharded grads NEVER share a fused buffer — a coalesce
                # across layouts would force GSPMD to re-shard every
                # member to one layout and back
                "tp_spec": str(op.attr(TP_SPEC_ATTR, "") or ""),
                "cap": float(op.attr(FUSE_SIZE_ATTR, DEFAULT_FUSE_MB))
                * 1024.0 * 1024.0,
                "anchor": anchor,
                "remove": remove,
            })
        return entries

    @staticmethod
    def _bucketize(entries) -> List[dict]:
        """Greedy size-capped bucketing in program order, one bucket
        stream per (dtype, ring, fp16) key — mixed-dtype grads never
        share a fused buffer."""
        buckets: List[dict] = []
        open_buckets: Dict[tuple, dict] = {}
        for e in entries:
            key = (e["dtype"], e["ring_id"], e["fp16"], e["tp_spec"])
            if e["bytes"] > e["cap"]:
                # an over-cap grad gets its own CLOSED bucket without
                # evicting the key's open bucket — neighbors on either
                # side of a huge embedding grad keep fusing together
                buckets.append({"key": key, "items": [e],
                                "bytes": e["bytes"]})
                continue
            b = open_buckets.get(key)
            if b is None or b["bytes"] + e["bytes"] > e["cap"]:
                b = {"key": key, "items": [], "bytes": 0}
                open_buckets[key] = b
                buckets.append(b)
            b["items"].append(e)
            b["bytes"] += e["bytes"]
        return buckets

    @staticmethod
    def _emit_bucket(block, bucket_idx: int, bucket: dict) -> List:
        from .program import Operator

        dtype, ring_id, fp16, tp_spec = bucket["key"]
        grads = [e["grad"] for e in bucket["items"]]
        shapes = [e["shape"] for e in bucket["items"]]
        sections = [_numel(s) for s in shapes]
        # deterministic name: re-transpiles of the same program fuse to
        # identical fingerprints, so compiled executables stay shared
        fused = f"@FUSED_GRAD@{dtype}@r{ring_id}@{bucket_idx}"
        block.create_var(name=fused, shape=[sum(sections)], dtype=dtype,
                         stop_gradient=True)
        seq = [Operator(block, "coalesce_tensor", {"Input": grads},
                        {"FusedOutput": [fused]},
                        {"dtype": dtypes.to_enum(dtype)})]
        if fp16:
            seq.append(Operator(block, "cast", {"X": [fused]},
                                {"Out": [fused]},
                                {"out_dtype": dtypes.to_enum("bfloat16")}))
        fused_attrs = {"ring_id": ring_id, "use_calc_stream": True}
        if tp_spec:
            # a homogeneous tp bucket keeps its members' spec visible to
            # the collective span/byte telemetry (the fused 1-D buffer's
            # dp payload is the member shards' sum, flagged 'mp'-sharded)
            fused_attrs[TP_SPEC_ATTR] = tp_spec
        seq.append(Operator(block, "c_allreduce_sum", {"X": [fused]},
                            {"Out": [fused]}, fused_attrs))
        if fp16:
            seq.append(Operator(block, "cast", {"X": [fused]},
                                {"Out": [fused]},
                                {"out_dtype": dtypes.to_enum(dtype)}))
        seq.append(Operator(
            block, "uncoalesce_tensor", {"Input": [fused]},
            {"Output": grads},
            {"sections": sections,
             "dims": [int(d) for s in shapes for d in s],
             "ranks": [len(s) for s in shapes]}))
        return seq


# ops that provably hand their (single) input's runtime dtype through to
# every output — the only ops the cast dataflow tracks through
_DTYPE_PRESERVING = {
    "assign", "c_identity", "c_allreduce_sum", "c_allreduce_max",
    "c_allreduce_min", "c_allreduce_prod", "c_broadcast", "c_allgather",
    "allreduce", "mp_allreduce_sum",
}


@register_pass
class RedundantCastEliminationPass(Pass):
    """Remove `cast` ops whose input PROVABLY already holds the target
    dtype (reference delete_cast_op_pass role).

    Conservative forward dataflow: a name's runtime dtype is known only
    when written by a `cast` (the attr names it) or by a
    dtype-preserving op with a known input.  Everything else — feeds
    included — starts/resets to unknown: jax device-array feeds pass
    through ``_feed_spec`` WITHOUT dtype coercion, so even a feed's
    declared var dtype is not trustworthy, and a declared-fp32 var that
    currently holds bf16 bits (the in-place fp16-allreduce pattern) can
    never be mistaken for fp32.
    """

    name = "redundant_cast_eliminate"

    def should_apply(self, program, ctx):
        return any(op.type == "cast" for op in program.global_block.ops)

    def apply(self, program, ctx):
        from ..monitor import stat_add
        from .lowering import PSEUDO_OPS
        from .program import Operator

        block = program.global_block
        cur: Dict[str, str] = {}
        new_ops: List = []
        n_removed = 0
        for op in block.ops:
            if op.type in PSEUDO_OPS:
                new_ops.append(op)
                continue
            if op.type == "cast":
                xs = op.inputs.get("X", [])
                outs = op.outputs.get("Out", [])
                dst = None
                try:
                    dst = dtypes.to_str(op.attr("out_dtype"))
                except (KeyError, ValueError, TypeError):
                    pass
                if len(xs) == 1 and len(outs) == 1 and dst is not None:
                    if cur.get(xs[0]) == dst:
                        n_removed += 1
                        if xs[0] == outs[0]:
                            continue  # in-place no-op cast: drop outright
                        op = Operator(block, "assign", {"X": [xs[0]]},
                                      {"Out": [outs[0]]})
                    cur[outs[0]] = dst
                    new_ops.append(op)
                    continue
            if op.type in _DTYPE_PRESERVING:
                ins = op.input_arg_names()
                known = cur.get(ins[0]) if len(ins) == 1 else None
                for n in op.output_arg_names():
                    if known is not None:
                        cur[n] = known
                    else:
                        cur.pop(n, None)
            else:
                for n in op.output_arg_names():
                    cur.pop(n, None)
            new_ops.append(op)
        if not n_removed:
            return False
        block.ops[:] = new_ops
        program._bump()
        stat_add("pass_casts_removed", n_removed)
        return True


@register_pass
class DeadOpEliminationPass(Pass):
    """Drop ops whose outputs feed neither a fetch nor persistent state
    (reference eager deletion / graph DCE role), reusing the executor's
    ``_prune_ops`` backward slice.

    Roots: the dispatch fetch list, every persistable write, and every
    write whose name already lives in the scope chain (the same
    liveness rule ``_analyze_state`` uses for state_out), so optimizer
    updates and user-visible state always survive.  Ops with no outputs
    and the p2p/barrier side-effect ops are kept unconditionally.
    """

    name = "dead_op_eliminate"

    @staticmethod
    def _live_ops(program, ctx):
        """(kept op list, dead count) — O(ops); cheap enough that
        ``should_apply`` runs it on the ORIGINAL program, so the common
        nothing-to-remove case never pays the pipeline's clone.
        Memoized on the ctx per (program identity, version) so the
        should_apply/apply sequence slices each program once."""
        from .executor import _prune_ops
        from .lowering import PSEUDO_OPS

        memo_key = ("dce_live", id(program), program._version)
        hit = ctx._memo.get(memo_key)
        if hit is not None:
            return hit

        block = program.global_block
        roots = set(ctx.fetch_names)
        for op in block.ops:
            for n in op.output_arg_names():
                var = block._find_var_recursive(n)
                if (var is not None and var.persistable) or (
                        ctx.scope is not None and ctx.scope.has_var(n)):
                    roots.add(n)
        if not roots:
            result = (None, 0)
        else:
            keep = _prune_ops(program, sorted(roots),
                              keep_side_effect_ops=True)
            keep_ids = {id(op) for op in keep}
            new_ops = [op for op in block.ops
                       if op.type in PSEUDO_OPS or id(op) in keep_ids]
            result = (new_ops, len(block.ops) - len(new_ops))
        ctx._memo[memo_key] = result
        return result

    def should_apply(self, program, ctx):
        return self._live_ops(program, ctx)[1] > 0

    def apply(self, program, ctx):
        from ..monitor import stat_add

        new_ops, n_removed = self._live_ops(program, ctx)
        if not n_removed:
            return False
        program.global_block.ops[:] = new_ops
        program._bump()
        stat_add("pass_dead_ops_removed", n_removed)
        return True


class PassPipeline:
    """Ordered pass application with copy-on-write semantics.

    ``apply`` runs every pass on a CLONE of the program and returns the
    clone when any pass changed it, else the original object — the
    caller (Executor) caches the result per
    ``(program.fingerprint(), config_key, fetch, feeds, scope)``.
    """

    def __init__(self, passes: Optional[Sequence[Pass]] = None):
        self._passes: Tuple[Pass, ...] = tuple(
            passes if passes is not None
            else (cls() for cls in PASS_REGISTRY.values()))

    @property
    def passes(self) -> Tuple[Pass, ...]:
        return self._passes

    def config_key(self) -> tuple:
        """Joins the Executor's pass-cache key; per-pass knobs that ride
        op attrs (e.g. the fuse bucket cap) are already part of the
        program fingerprint."""
        return tuple(p.name for p in self._passes)

    def apply(self, program, ctx: Optional[PassContext] = None):
        from ..monitor import stat_add
        from ..observe import tracer as otrace

        ctx = ctx or PassContext()
        if not any(p.should_apply(program, ctx) for p in self._passes):
            return program
        work = program.clone()
        changed = False
        for p in self._passes:
            if p.should_apply(work, ctx):
                # one tracer span per pass, nested under the Executor's
                # executor/pass_pipeline span (observe/tracer.py)
                with otrace.span(f"pass/{p.name}"):
                    changed = bool(p.apply(work, ctx)) or changed
        stat_add("pass_pipeline_apply")
        return work if changed else program


_default_pipeline: Optional[PassPipeline] = None


def default_pipeline() -> PassPipeline:
    global _default_pipeline
    if _default_pipeline is None:
        _default_pipeline = PassPipeline()
    return _default_pipeline


def apply_passes(program, fetch_names: Sequence[str] = (),
                 feed_names: Sequence[str] = (), scope=None, mesh=None):
    """One-shot convenience: run the default pipeline over ``program``
    (returns the rewritten clone, or ``program`` itself when nothing
    applied)."""
    return default_pipeline().apply(
        program, PassContext(fetch_names=fetch_names,
                             feed_names=feed_names, scope=scope, mesh=mesh))
