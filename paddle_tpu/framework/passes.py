"""Program-IR optimization pass pipeline.

Role parity: reference build-strategy graph passes
(framework/ir/pass.h, build_strategy.cc) — most prominently
`fuse_all_reduce_op_pass` + `coalesce_tensor_op` (Horovod-style tensor
fusion): instead of one latency-bound `c_allreduce_sum` per gradient,
same-dtype grads are flattened into size-capped fused buffers and
reduced per bucket.  On a ResNet/BERT step this turns hundreds of
small collectives into a handful of bandwidth-bound ones.

TPU-native framing: passes are *program rewrites applied before
lowering*, not graph-node surgery on an SSA graph — the Executor clones
the program, runs the pipeline on the clone, and compiles the rewritten
clone, so the user's program object is never mutated (with
``fuse_all_reduce_ops=False`` or ``FLAGS_fuse_passes=0`` the exact
pre-pass program compiles).  Application is cached per
``(program.fingerprint(), pass config)`` by the Executor; the
``FLAGS_fuse_passes`` flag is registered with ``affects_lowering=True``
so flipping it re-keys the compile cache too.

Passes in default order:

0. ``ShardingPropagationPass`` — tensor-parallel auto-sharding: maps
   the ordered regex partition rules the TensorParallelMetaOptimizer
   stamped onto the program over every var, propagates specs through
   the op stream (``with_sharding_constraint`` anchors at matmul ops,
   replicated fallback), makes optimizer slots inherit their param's
   spec, and attaches the :class:`TPShardingPlan` the Executor lowers
   to ``NamedSharding`` jit in/out specs on the dp×mp mesh.  Runs
   FIRST so the fuse pass below sees its per-collective spec stamps.
1. ``FuseAllReducePass`` — groups the `c_allreduce_sum` ops the
   collective transpiler marked (``__fused_allreduce__`` attr) into
   per-dtype buckets capped at ``__fuse_grad_size_mb__`` (default 32 MB,
   ``DistributedStrategy.fuse_grad_size_in_MB``), and rewrites each
   bucket into ``coalesce_tensor`` (flatten+concat) → one
   ``c_allreduce_sum`` → ``uncoalesce_tensor`` (split+reshape back),
   anchored at the LAST original allreduce of the bucket so the fused
   collective still launches as soon as its last gradient is produced
   (comm/backward overlap is preserved).  Under the fp16/bf16 allreduce
   strategy the per-grad cast pairs collapse to one pair per bucket.
2. ``RedundantCastEliminationPass`` — removes `cast` ops whose input
   provably already holds the target dtype (tracked by a conservative
   forward dataflow; unknown dtypes are never touched).
3. ``DeadOpEliminationPass`` — drops ops that feed neither a fetch nor
   persistent/scope-resident state, reusing the executor's
   ``_prune_ops`` backward slice (side-effect ops like `send_v2` are
   always kept).

Observability (``paddle_tpu.monitor``): ``pass_fused_allreduce_buckets``,
``pass_allreduce_ops_before`` / ``pass_allreduce_ops_after``,
``pass_dead_ops_removed``, ``pass_casts_removed``, and the Executor's
``executor_pass_cache_hit``.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import dtypes

GRAD_SUFFIX_TP = "@GRAD"  # == program.GRAD_SUFFIX (local: no import cycle)

__all__ = [
    "FUSED_ALLREDUCE_ATTR",
    "FUSE_SIZE_ATTR",
    "DEFAULT_FUSE_MB",
    "TP_RULES_ATTR",
    "TP_DEGREE_ATTR",
    "TP_SPEC_ATTR",
    "TP_CONSTRAINT_ATTR",
    "EMB_SHARD_ATTR",
    "decode_anchor",
    "DP_LOSS_SCALE_ATTR",
    "EP_DEGREE_ATTR",
    "MOE_EP_ATTR",
    "has_ep_marks",
    "LAYER_SCAN_ATTR",
    "LAYER_SCAN_POLICY_ATTR",
    "LAYER_STACK_ATTR",
    "LAYER_STACK_PREFIX",
    "DEFAULT_MEGATRON_RULES",
    "encode_spec",
    "decode_spec",
    "TPShardingPlan",
    "LayerScanPlan",
    "Pass",
    "PassContext",
    "PassPipeline",
    "ShardingPropagationPass",
    "LayerScanPass",
    "FuseAllReducePass",
    "RedundantCastEliminationPass",
    "DeadOpEliminationPass",
    "register_pass",
    "default_pipeline",
    "apply_passes",
]

# op-attr markers stamped by the collective transpiler
# (distributed/fleet/collective_transpiler.py GradAllReduce) on the ops
# it wants fused; attrs — not python side channels — so the linkage
# survives clone/proto round-trips and joins the program fingerprint
FUSED_ALLREDUCE_ATTR = "__fused_allreduce__"
FUSE_SIZE_ATTR = "__fuse_grad_size_mb__"
DEFAULT_FUSE_MB = 32.0

# tensor-parallel markers (TensorParallelMetaOptimizer stamps the first
# two on the program's optimizer ops; ShardingPropagationPass stamps the
# next two per-op).  All are op attrs so the tp contract survives
# clone/proto round-trips AND joins the program fingerprint — a changed
# rule list re-keys every executor cache automatically.
TP_RULES_ATTR = "__tp_rules__"          # list of "regex\tspec" strings
TP_DEGREE_ATTR = "__tp_degree__"        # required mp degree (0 = any)
TP_SPEC_ATTR = "__tp_spec__"            # on grad collectives: grad's spec
TP_CONSTRAINT_ATTR = "__tp_constraint__"  # list of "var\tspec" anchors
# stamped on lookup_table(_v2) ops whose table the pass row-sharded
# over 'mp' (value = the mp degree): the embedding lowering
# (ops/embedding_ops.py) routes these through the sharded engine —
# explicit all-to-all on the manual pipeline×mp path, custom_vjp dense
# reference + layout anchor under GSPMD
EMB_SHARD_ATTR = "__emb_row_sharded__"
# stamped by GradAllReduce/ShardingMetaOptimizer on the 1/nranks
# loss-grad scale op so the tensor-parallel meta-optimizer can remove it
# (GSPMD computes global-batch-mean gradients directly; keeping the
# scale would shrink every gradient by the dp degree)
DP_LOSS_SCALE_ATTR = "__dp_loss_scale__"

# expert-parallel markers.  ExpertParallelMetaOptimizer stamps
# EP_DEGREE_ATTR on the program's optimizer ops (required 'ep' degree,
# 0 = any — the same contract as TP_DEGREE_ATTR); ShardingPropagationPass
# stamps MOE_EP_ATTR (value = the ep degree) on each moe_ffn / moe_ffn_grad
# op whose stacked expert weights it sharded P('ep', ...), which is what
# makes the lowering (ops/moe_ops.py) pin the [E, capacity, D] dispatch
# buffer to the 'ep' axis — the constraint XLA materializes as the
# dispatch/combine all-to-all pair.  Op attrs, so the contract survives
# clone/proto round-trips and joins the program fingerprint.
EP_DEGREE_ATTR = "__ep_degree__"
MOE_EP_ATTR = "__moe_ep__"

# scan-over-layers markers.  The first two are stamped by the
# RecomputeMetaOptimizer (DistributedStrategy.recompute_configs
# 'scan_layers' / 'policy' extras) on the program's optimizer ops — op
# attrs, so the contract survives clone/proto round-trips and re-keys
# every executor cache via the fingerprint; they OVERRIDE the
# FLAGS_layer_scan* defaults for this program.  LAYER_STACK_ATTR is
# stamped by LayerScanPass on ops whose runtime payload carries the
# stacked (num_layers, ...) leading axis over a var whose DECLARED shape
# stays per-layer (the stack axis is a pass-internal runtime artifact;
# the block metadata keeps the per-layer logical view for checkpoint /
# sharding-plan / attribution joins) — byte accounting must multiply by
# it (FuseAllReducePass bucket sizing, executor allreduce telemetry).
LAYER_SCAN_ATTR = "__layer_scan__"            # min isomorphic run length
LAYER_SCAN_POLICY_ATTR = "__layer_scan_policy__"  # remat policy name
LAYER_STACK_ATTR = "__layer_stack__"          # num stacked layers
# scope/block name prefix of a stacked weight family's carrier array;
# ckpt snapshot_scope SKIPS these (the per-layer StackedParamRef views
# are what checkpoints save, keeping resume elastic across the flag)
LAYER_STACK_PREFIX = "@LAYER_STACK@"

# collective-identity stamps for the phase-attribution ledger
# (observe/phases.py).  FuseAllReducePass stamps both on each fused
# c_allreduce_sum it emits: COMM_ID_ATTR is the stable bucket identity
# ("bucket:<dtype>@r<ring>@<idx>" — deterministic across re-transpiles,
# like the fused var name), COMM_OVERLAP_ATTR marks a bucket the
# overlap stretch (FLAGS_overlap_grad_allreduce) closed at its scan
# boundary, i.e. one whose bulk payload dispatches UNDER the remaining
# backward compute and is therefore modeled as hidden comm.  Op attrs —
# not side channels — so the identity survives clone/proto round-trips
# and joins the program fingerprint.
COMM_ID_ATTR = "__comm_id__"
COMM_OVERLAP_ATTR = "__comm_overlap__"


def encode_spec(spec) -> str:
    """Partition spec tuple -> attr string: ``(None,'mp')`` -> "None,mp".
    The empty tuple (fully replicated / scalar) encodes as ""."""
    return ",".join("None" if s is None else str(s) for s in spec)


def decode_spec(enc: str):
    """Inverse of :func:`encode_spec`."""
    if not enc:
        return ()
    return tuple(None if tok == "None" else tok for tok in enc.split(","))


def decode_anchor(ent: str):
    """Parse one ``TP_CONSTRAINT_ATTR`` entry -> (var, spec tuple,
    partial).  Entries are "var\\tspec" (layout anchor) or
    "var\\tspec\\tP" (PARTIAL-SUM anchor: the op's mp-sharded
    contraction makes the output a partial sum, so the manual
    pipeline×mp path must psum it over 'mp' and the GSPMD path may
    decompose it into latency-hiding collective-matmul chunks)."""
    parts = str(ent).split("\t")
    name = parts[0]
    spec = decode_spec(parts[1]) if len(parts) > 1 else ()
    partial = len(parts) > 2 and parts[2] == "P"
    return name, spec, partial


# Megatron-LM style defaults over this framework's parameter naming
# (layer_helper: "<name>.w_0"/"<name>.b_0"; text/static_models.py BERT:
# enc_<i>_{q,k,v,out}, enc_<i>_{ffn1,ffn2}, word_embedding).  Ordered:
# first match wins.  Anything unmatched stays replicated — plain fc
# stacks have no inherent row/column orientation, so generic fc params
# are NOT sharded by default (pass partition_rules for custom nets).
DEFAULT_MEGATRON_RULES = (
    # attention QKV projections: column-parallel (heads split over mp)
    (r"(_q|_k|_v|_qkv|_query|_key|_value)\.w_\d+$", "None,mp"),
    (r"(_q|_k|_v|_qkv|_query|_key|_value)\.b_\d+$", "mp"),
    # attention/vocab output projections: row-parallel (mp-sharded
    # contraction; the pass anchors the partial-sum reduce there)
    (r"(_out|_proj|_o)\.w_\d+$", "mp,None"),
    # transformer FFN: first fc column-parallel, second row-parallel
    (r"(_ffn1|_fc1|_h_4h)\.w_\d+$", "None,mp"),
    (r"(_ffn1|_fc1|_h_4h)\.b_\d+$", "mp"),
    (r"(_ffn2|_fc2|_4h_h)\.w_\d+$", "mp,None"),
    # vocab-parallel embedding table (rows = vocab over mp)
    (r"^word_embedding$", "mp,None"),
)


class TPShardingPlan:
    """The ShardingPropagationPass output: name -> partition-axes tuple
    over the named (dp, mp) mesh, plus the static grad-reduce
    accounting the telemetry layer reads.

    Attached to the POST-pass program object (``program._tp_plan``);
    the Executor compiles the tp program through ``jax.jit`` with
    ``NamedSharding`` in/out specs built from this plan (GSPMD —
    semantics stay those of the single logical program, sharding is
    pure layout, and XLA inserts the mp partial-sum reduces the
    constraint anchors pin)."""

    __slots__ = ("specs", "mp_degree", "dp_axis", "mp_axis",
                 "grad_reduce", "n_sharded", "n_fallback", "ep_degree")

    def __init__(self, specs, mp_degree, dp_axis="dp", mp_axis="mp",
                 grad_reduce=None, n_sharded=0, n_fallback=0,
                 ep_degree=1):
        self.specs = dict(specs)
        self.mp_degree = int(mp_degree)
        self.ep_degree = int(ep_degree)
        self.dp_axis = dp_axis
        self.mp_axis = mp_axis
        # grad name -> {"axes": ("dp",), "bytes": per-step payload of
        # its dp allreduce (shard-local bytes for mp-sharded grads)}
        self.grad_reduce = dict(grad_reduce or {})
        self.n_sharded = int(n_sharded)
        self.n_fallback = int(n_fallback)

    def spec_tuple(self, name: str) -> tuple:
        return tuple(self.specs.get(name, ()))

    def partition_spec(self, name: str):
        from jax.sharding import PartitionSpec

        return PartitionSpec(*self.specs.get(name, ()))

    def named_sharding(self, mesh, name: str):
        from jax.sharding import NamedSharding

        return NamedSharding(mesh, self.partition_spec(name))

    def shard_divisor(self, name: str, mesh=None) -> int:
        """How many chips one copy of ``name`` is split over: the
        product of the mesh-axis sizes in its spec (1 for replicated or
        unknown vars).  The HBM-attribution join
        (observe/xla_stats.py): per-chip bytes = global bytes / this."""
        n = 1
        for ax in self.specs.get(name, ()):
            if ax is None:
                continue
            for a in (ax if isinstance(ax, (tuple, list)) else (ax,)):
                if mesh is not None and a in mesh.axis_names:
                    n *= int(mesh.shape[a])
        return max(n, 1)

    def spec_str(self, name: str) -> str:
        """Human-readable spec for error messages / attribution tables:
        ``P(None, 'mp')`` for sharded vars, ``replicated`` otherwise."""
        spec = self.specs.get(name, ())
        if not spec or all(ax is None for ax in spec):
            return "replicated"
        return "P(" + ", ".join(
            "None" if ax is None else repr(ax) for ax in spec) + ")"

    def __repr__(self):
        return (f"TPShardingPlan(mp={self.mp_degree}, "
                f"ep={self.ep_degree}, "
                f"sharded={self.n_sharded}, fallback={self.n_fallback})")


class PassContext:
    """Per-application context: what the Executor knows at dispatch time.

    ``fetch_names``/``feed_names``/``scope`` feed the dead-op slice and
    the cast dataflow; all three join the Executor's pass-cache key.
    ``mesh`` (the executor's active mesh) drives the tensor-parallel
    sharding pass and joins the cache key by identity."""

    def __init__(self, fetch_names: Sequence[str] = (),
                 feed_names: Sequence[str] = (), scope=None, mesh=None):
        self.fetch_names = tuple(fetch_names)
        self.feed_names = tuple(feed_names)
        self.scope = scope
        self.mesh = mesh
        # per-application scratch for passes (e.g. DCE memoizes its
        # prune slice across should_apply/apply)
        self._memo: Dict[tuple, object] = {}


class Pass:
    """One program rewrite.  ``apply`` mutates ``program`` in place and
    returns True iff it changed anything (drives the pipeline's
    copy-on-write: an all-no-op run hands the ORIGINAL program back to
    the Executor)."""

    name = "pass"

    def should_apply(self, program, ctx: PassContext) -> bool:
        return True

    def apply(self, program, ctx: PassContext) -> bool:
        raise NotImplementedError


PASS_REGISTRY: Dict[str, type] = {}


def register_pass(cls=None, *, before: Optional[str] = None):
    """Register a Pass subclass into the ordered default registry and
    rebuild the default pipeline on next use (a registration after the
    first Executor run would otherwise be silently inert).  ``before``
    inserts the pass ahead of an already-registered name instead of
    appending — how a pass defined outside this module (the weight-quant
    pass in slim/quantization.py) claims its pipeline position."""
    if cls is None:
        return lambda c: register_pass(c, before=before)
    global _default_pipeline
    if cls.name in PASS_REGISTRY:
        raise KeyError(f"pass {cls.name!r} already registered")
    if before is None:
        PASS_REGISTRY[cls.name] = cls
    else:
        if before not in PASS_REGISTRY:
            raise KeyError(f"register_pass(before={before!r}): no such "
                           f"registered pass")
        items = []
        for name, c in PASS_REGISTRY.items():
            if name == before:
                items.append((cls.name, cls))
            items.append((name, c))
        PASS_REGISTRY.clear()
        PASS_REGISTRY.update(items)
    _default_pipeline = None
    return cls


_EXTERNAL_PASSES_LOADED = False


def _ensure_external_passes():
    """Import the modules that register passes from OUTSIDE this file
    so the default registry is complete before a pipeline snapshots it.
    Lazy (first pipeline construction, i.e. first Executor dispatch):
    importing slim at module-import time would cycle through the
    framework package."""
    global _EXTERNAL_PASSES_LOADED
    if _EXTERNAL_PASSES_LOADED:
        return
    _EXTERNAL_PASSES_LOADED = True
    from ..slim import quantization  # noqa: F401 — import registers
                                     # PostTrainingWeightQuantPass


def _numel(shape) -> int:
    n = 1
    for s in shape:
        n *= int(s)
    return n


def _itemsize(dtype_str: str) -> int:
    return int(np.dtype(dtypes.to_np(dtype_str)).itemsize)


def _marked_inplace_cast(op, name: str) -> bool:
    return (op.type == "cast" and bool(op.attr(FUSED_ALLREDUCE_ATTR))
            and op.inputs.get("X", []) == [name]
            and op.outputs.get("Out", []) == [name])


def has_tp_marks(program) -> bool:
    """True when a TensorParallelMetaOptimizer stamped this program
    (the executor refuses to run such a program outside the GSPMD tp
    path — the dp loss-grad scale was removed, so the shard_map dp
    path would compute wrong gradients)."""
    return any(op.attr(TP_RULES_ATTR) for op in program.global_block.ops)


def has_ep_marks(program) -> bool:
    """True when an ExpertParallelMetaOptimizer stamped this program —
    like a tp-marked program it must run the GSPMD path (the dp
    loss-grad scale was removed at minimize time)."""
    return any(op.attr(EP_DEGREE_ATTR) is not None
               for op in program.global_block.ops)


# ops whose output provably carries its (first) input's partition spec
# through unchanged — the propagation walks only through these plus the
# structured handlers below; everything else resets to unknown
_TP_SPEC_PRESERVING = {
    "relu", "gelu", "tanh", "sigmoid", "softmax", "dropout", "cast",
    "scale", "assign", "c_identity", "recompute_barrier", "relu_grad",
    "gelu_grad", "tanh_grad", "sigmoid_grad", "dropout_grad",
    "layer_norm",  # Y spec == X spec (mean/var reduce over trailing
                   # dims is GSPMD's job when those dims are sharded)
}

_TP_MATMUL_OPS = {"mul", "matmul", "matmul_v2"}


@register_pass
class ShardingPropagationPass(Pass):
    """Tensor-parallel auto-sharding (GSPMD substrate; SNIPPETS.md [2]
    ``match_partition_rules`` -> ``NamedSharding`` -> pjit).

    Input contract: the TensorParallelMetaOptimizer stamped the
    program's optimizer ops with ``TP_RULES_ATTR`` (ordered regex ->
    spec rules) and ``TP_DEGREE_ATTR``; ``ctx.mesh`` is a named mesh
    with an 'mp' axis.

    What it does:

    1. **Param matching** — every block var is matched against the
       ordered rules (first match wins); a matched var whose sharded
       dims are not divisible by the mp degree falls back to replicated
       (counted in ``pass_tp_fallback_replicated``, never dropped).
    2. **Slot inheritance** — optimizer accumulator slots (Velocity,
       Moment1/2, ... — the _OPTIMIZER_ACC_SLOTS table) and param-shaped
       persistable extras (MasterParam) inherit their Param's spec;
       ZeRO-1 ``__sharded_accumulators__`` of replicated params get
       P('dp') on dim 0 instead (optimizer-state memory still drops by
       the dp degree under GSPMD layout sharding).
    3. **Propagation** — a forward walk assigns specs to intermediates
       (matmul contraction/output rules, elementwise merge, transpose
       permute, spec-preserving ops, ``X@GRAD`` inherits X's spec) and
       stamps ``TP_CONSTRAINT_ATTR`` on matmul-family anchor ops so the
       lowering applies ``with_sharding_constraint`` there — pinning
       the Megatron pattern: a row-parallel matmul's output constrained
       replicated-on-mp forces XLA to place the mp partial-sum reduce
       at that op.  Unknown intermediates stay unconstrained
       (replicated fallback; GSPMD chooses).
    4. **Grad-collective stamping** — transpiler-inserted
       ``c_allreduce_sum`` ops whose grad is mp-sharded get
       ``TP_SPEC_ATTR`` (so FuseAllReducePass never buckets across
       sharding specs, and the collective span/byte telemetry reports
       the dp-axis shard payload, not the full grad).
    5. Attaches the :class:`TPShardingPlan` as ``program._tp_plan`` for
       the Executor's GSPMD compile path.
    """

    name = "sharding_propagation"

    def should_apply(self, program, ctx):
        mesh = getattr(ctx, "mesh", None)
        if mesh is None:
            return False
        axes = getattr(mesh, "axis_names", ())
        if "mp" in axes and has_tp_marks(program):
            return True
        return "ep" in axes and has_ep_marks(program)

    def apply(self, program, ctx):
        import re

        from ..monitor import stat_set

        mesh = ctx.mesh
        axes_present = set(getattr(mesh, "axis_names", ()))
        mp_degree = int(mesh.shape["mp"]) if "mp" in axes_present else 1
        ep_degree = int(mesh.shape["ep"]) if "ep" in axes_present else 1
        block = program.global_block
        ops = block.ops

        want_ep = self._read_ep_degree(ops)
        if want_ep is not None:
            if "ep" not in axes_present:
                raise ValueError(
                    "this program was built with DistributedStrategy."
                    "expert_parallel but the executor's mesh has no "
                    "'ep' axis; rebuild it with init_parallel_env("
                    "mesh_shape=(dp, ep), axis_names=('dp', 'ep')) or "
                    "FLAGS_ep_degree")
            if want_ep and want_ep != ep_degree:
                raise ValueError(
                    f"expert_parallel_degree={want_ep} but the active "
                    f"mesh's 'ep' axis has {ep_degree} devices; rebuild "
                    f"the mesh or unset the degree")

        rules, want_degree = self._read_config(ops)
        if want_degree and want_degree != mp_degree:
            raise ValueError(
                f"tensor_parallel_degree={want_degree} but the active "
                f"mesh's 'mp' axis has {mp_degree} devices; rebuild the "
                f"mesh (init_parallel_env(mesh_shape=(dp, {want_degree}), "
                f"axis_names=('dp', 'mp'))) or unset the degree")
        # a spec/anchor naming a mesh axis that does not exist would
        # crash deep inside jax at trace time; any axis absent from
        # THIS mesh (a pure-mp 1D mesh has no 'dp'; user rules may name
        # arbitrary axes) degrades to None (replicated on that dim)
        axes = set(mesh.axis_names)

        def sanitize(spec):
            return tuple(s if s in axes else None for s in spec)

        compiled_rules = [(re.compile(pat), sanitize(decode_spec(enc)))
                          for pat, enc in rules]

        # -- 1. rule-match every var (params seed the state layout) ----
        specs: Dict[str, tuple] = {}
        n_sharded = n_fallback = 0
        for name, var in block.vars.items():
            spec = self._match(compiled_rules, name)
            if spec is None:
                continue
            spec = self._fit(spec, var.shape)
            if spec is None or not any(s == "mp" for s in spec):
                continue
            if not self._divisible(var.shape, spec, mp_degree):
                n_fallback += 1
                continue
            specs[name] = spec
            n_sharded += 1

        # -- 1b. sparse embedding tables default to row-sharding ------
        # an is_sparse lookup is an explicit request for the
        # distributed engine (fleet.distributed_embedding /
        # nn.Embedding(sparse=True)): its table row-shards over 'mp'
        # even without a matching partition rule; indivisible vocabs
        # fall back to replicated like any rule match
        for op in ops:
            if op.type not in ("lookup_table", "lookup_table_v2") \
                    or not bool(op.attr("is_sparse", False)):
                continue
            wname = op.inputs.get("W", [None])[0]
            if not wname or wname in specs:
                continue
            var = block._find_var_recursive(wname)
            if var is None or len(var.shape) < 2:
                continue
            spec = ("mp",) + (None,) * (len(var.shape) - 1)
            if not self._divisible(var.shape, spec, mp_degree):
                n_fallback += 1
                continue
            specs[wname] = spec
            n_sharded += 1

        # -- 1c. moe expert weights shard over 'ep' --------------------
        # stacked expert carriers ([E, ...] with E = num_experts on dim
        # 0) of every moe_ffn op seed P('ep', None, ...) — no partition
        # rule needed, the op IS the request; an expert count not
        # divisible by the ep degree falls back replicated like any
        # rule match (the op then runs all experts on every chip)
        n_moe = 0
        if "ep" in axes:
            for op in ops:
                if op.type != "moe_ffn":
                    continue
                for slot in ("W1", "B1", "W2", "B2"):
                    wname = op.inputs.get(slot, [None])[0]
                    if not wname or wname in specs:
                        continue
                    var = block._find_var_recursive(wname)
                    if var is None or not var.shape:
                        continue
                    if int(var.shape[0]) % ep_degree != 0:
                        n_fallback += 1
                        continue
                    specs[wname] = ("ep",) + (None,) * (len(var.shape) - 1)
                    n_sharded += 1
                    n_moe += 1

        # -- 2. optimizer slots inherit their param's spec -------------
        self._inherit_slots(block, ops, specs, has_dp="dp" in axes)

        # -- 3+4. propagate, stamp anchors and grad collectives --------
        grad_reduce = self._propagate(block, ops, dict(specs), ctx,
                                      mp_degree, has_dp="dp" in axes,
                                      ep_degree=ep_degree)

        # -- 3b. strict ep-flow walk: refuse consumers of ep-sharded
        # state outside the routed-FFN family (the mp-flow-walk idiom)
        if ep_degree > 1:
            self._check_ep_consumers(ops, specs)

        program._tp_plan = TPShardingPlan(
            specs, mp_degree, grad_reduce=grad_reduce,
            n_sharded=n_sharded, n_fallback=n_fallback,
            ep_degree=ep_degree)
        program._bump()
        stat_set("pass_tp_sharded_vars", n_sharded)
        stat_set("pass_tp_fallback_replicated", n_fallback)
        stat_set("pass_tp_mp_degree", mp_degree)
        if "ep" in axes:
            stat_set("pass_ep_sharded_weights", n_moe)
            stat_set("pass_ep_degree", ep_degree)
        return True

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _read_config(ops):
        for op in ops:
            enc = op.attr(TP_RULES_ATTR)
            if enc:
                rules = []
                for ent in enc:
                    pat, _, spec = ent.partition("\t")
                    rules.append((pat, spec))
                return rules, int(op.attr(TP_DEGREE_ATTR, 0) or 0)
        return [], 0

    @staticmethod
    def _read_ep_degree(ops):
        """The ExpertParallelMetaOptimizer stamp: the required ep degree
        (0 = any), or None when the program is not ep-marked."""
        for op in ops:
            deg = op.attr(EP_DEGREE_ATTR)
            if deg is not None:
                return int(deg)
        return None

    @staticmethod
    def _check_ep_consumers(ops, specs):
        """An ep-sharded var holds only this chip's experts — any op
        outside the routed-FFN family reading one would silently compute
        on a 1/ep slice as if it were the whole tensor.  Refuse at plan
        time, naming the op and var (the PR 15 mp-flow-walk idiom)."""
        from ..distributed.fleet.meta_optimizers import _OPTIMIZER_OP_TYPES

        ep_vars = {n for n, sp in specs.items() if "ep" in sp}
        ep_vars |= {n + GRAD_SUFFIX_TP for n in list(ep_vars)}
        allowed = {"moe_ffn", "moe_ffn_grad", "c_allreduce_sum", "sum",
                   "cast", "assign", "scale", "share_buffer",
                   "dequant_matmul"} | set(_OPTIMIZER_OP_TYPES)
        for op in ops:
            if op.type in allowed:
                continue
            for names in op.inputs.values():
                for n in names:
                    if n in ep_vars:
                        raise ValueError(
                            f"op {op.type!r} consumes expert-parallel-"
                            f"sharded var {n!r} (P('ep', ...)): each "
                            f"chip holds only 1/ep of the experts, so "
                            f"only the routed-FFN family (moe_ffn / "
                            f"moe_ffn_grad), gradient collectives, and "
                            f"optimizer ops may read it — keep the "
                            f"computation inside the expert FFN or "
                            f"replicate the var")

    @staticmethod
    def _match(compiled_rules, name):
        for rx, spec in compiled_rules:
            if rx.search(name):
                return spec
        return None

    @staticmethod
    def _fit(spec, shape):
        """Right-size a rule spec to the var's rank: a 2-dim rule on a
        scalar/1-dim var keeps its TRAILING entries ("None,mp" applies
        to a bias as "mp"); over-long specs never shard a var they
        don't fit."""
        rank = len(shape)
        if rank == 0:
            return None
        if len(spec) > rank:
            spec = spec[-rank:]
        if len(spec) < rank:
            spec = (None,) * (rank - len(spec)) + tuple(spec)
        return tuple(spec)

    @staticmethod
    def _divisible(shape, spec, mp_degree):
        for dim, s in zip(shape, spec):
            if s == "mp" and int(dim) % mp_degree != 0:
                return False
        return True

    @staticmethod
    def _inherit_slots(block, ops, specs, has_dp=True):
        """Optimizer accumulator slots (and param-shaped persistable
        extras like MasterParam) inherit their Param's spec; ZeRO-1
        ``__sharded_accumulators__`` of replicated params get P('dp')
        on dim 0 instead (state memory still drops by the dp degree —
        GSPMD layout sharding replaces the shard_map reducescatter
        machinery, whose c_* ops lower to identity on this path)."""
        # slot table lives with the optimizer-op knowledge in fleet;
        # lazy import avoids a framework->fleet import cycle
        from ..distributed.fleet.meta_optimizers import (
            _OPTIMIZER_ACC_SLOTS, _OPTIMIZER_OP_TYPES)

        for op in ops:
            zero_accs = set(op.attr("__sharded_accumulators__", None) or ())
            if op.type not in _OPTIMIZER_OP_TYPES and not zero_accs:
                continue
            pnames = op.inputs.get("Param", [])
            # the ZeRO transpile rewires Param to "<name>@SHARD"; the
            # rule matched the base param name
            base = pnames[0][:-len("@SHARD")] \
                if pnames and pnames[0].endswith("@SHARD") else \
                (pnames[0] if pnames else None)
            pspec = specs.get(base) if base else None
            pvar = block._find_var_recursive(base) if base else None
            acc_slots = _OPTIMIZER_ACC_SLOTS.get(op.type, ())
            for slot, names in op.inputs.items():
                if slot in ("Param", "Grad", "LearningRate"):
                    continue
                for nm in names:
                    if nm in specs:
                        continue
                    var = block._find_var_recursive(nm)
                    if var is None or not var.shape:
                        continue
                    param_shaped = (pvar is not None
                                    and tuple(var.shape) == tuple(pvar.shape))
                    if pspec is not None and (slot in acc_slots
                                              or (param_shaped
                                                  and var.persistable)
                                              or nm in zero_accs):
                        specs[nm] = pspec
                    elif nm in zero_accs and has_dp:
                        # ZeRO accumulator of a replicated param: keep
                        # the optimizer-state-over-dp layout
                        specs[nm] = ("dp",) + (None,) * (len(var.shape) - 1)

    def _propagate(self, block, ops, known, ctx, mp_degree, has_dp=True,
                   ep_degree=1):
        """Forward spec walk over the op stream.  ``known`` maps var
        name -> spec tuple (entries None|'dp'|'mp'); feeds seed 'dp' on
        their batch dim (when the mesh has one).  Returns the per-grad
        reduce accounting for grads riding a transpiler c_allreduce_sum."""
        if has_dp:
            for fname in ctx.feed_names:
                var = block._find_var_recursive(fname)
                if var is not None and len(var.shape) >= 1 \
                        and fname not in known:
                    known[fname] = ("dp",) + (None,) * (len(var.shape) - 1)

        grad_reduce: Dict[str, dict] = {}
        for op in ops:
            if op.type in _TP_MATMUL_OPS:
                self._prop_matmul(op, known)
            elif op.type == "transpose" or op.type == "transpose2":
                self._prop_transpose(op, known)
            elif op.type.startswith("elementwise_") \
                    and not op.type.endswith("_grad"):
                self._prop_elementwise(op, known)
            elif op.type in _TP_SPEC_PRESERVING:
                xs = op.inputs.get("X", [])
                spec = known.get(xs[0]) if len(xs) == 1 else None
                for n in op.output_arg_names():
                    if spec is not None and self._rank_ok(block, n, spec):
                        known[n] = spec
                    else:
                        known.pop(n, None)
            elif op.type == "flash_attention":
                # fused attention is per-head batched math: a heads-dim
                # (mp) sharded q/k/v rides through the kernel locally —
                # the Megatron shape is kept internally (softmax is
                # per-head), so out spec = Q's spec, anchored when mp
                # is present so XLA keeps the layout through the kernel
                qs = op.inputs.get("Q", [])
                spec = known.get(qs[0]) if len(qs) == 1 else None
                outs = op.output_arg_names()
                if spec is not None and outs \
                        and self._rank_ok(block, outs[0], spec):
                    known[outs[0]] = spec
                    if any(s == "mp" for s in spec):
                        ents = list(op.attrs.get(TP_CONSTRAINT_ATTR,
                                                 []) or [])
                        ents.append(f"{outs[0]}\t{encode_spec(spec)}")
                        op.attrs[TP_CONSTRAINT_ATTR] = ents
                else:
                    for n in outs:
                        known.pop(n, None)
            elif op.type in ("lookup_table", "lookup_table_v2"):
                self._prop_lookup(op, known, mp_degree)
            elif op.type == "moe_ffn":
                # tokens go in and come out in caller order — Out rides
                # X's spec; AuxLoss/ExpertLoad are replicated scalars/
                # vectors.  When the expert stack was ep-sharded, stamp
                # the op so the lowering pins the [E, C, D] dispatch
                # buffer to 'ep' (the all-to-all anchor) and the phase
                # ledger can price the wire (COMM_ID_ATTR identity).
                xs = op.inputs.get("X", [None])[0]
                spec = known.get(xs) if xs else None
                out = op.outputs.get("Out", [None])[0]
                if out:
                    if spec is not None and self._rank_ok(block, out, spec):
                        known[out] = spec
                    else:
                        known.pop(out, None)
                for slot in ("AuxLoss", "ExpertLoad"):
                    n = op.outputs.get(slot, [None])[0]
                    if n:
                        known.pop(n, None)
                w1 = op.inputs.get("W1", [None])[0]
                if w1 and "ep" in (known.get(w1) or ()):
                    op.attrs[MOE_EP_ATTR] = int(ep_degree)
                    if not op.attr(COMM_ID_ATTR):
                        op.attrs[COMM_ID_ATTR] = f"moe:{out}"
            elif op.type == "c_allreduce_sum":
                # transpiler grad collective: identity under GSPMD (the
                # grad is already the global sum); stamp the grad's spec
                # so fuse bucketing and telemetry stay shard-aware
                g = op.inputs.get("X", [None])[0]
                spec = known.get(g)
                var = block._find_var_recursive(g) if g else None
                if var is not None and var.shape \
                        and all(int(s) > 0 for s in var.shape):
                    try:
                        nbytes = _numel(var.shape) * _itemsize(
                            dtypes.to_str(var.dtype))
                    except (KeyError, ValueError):
                        continue
                    shard_div = 1
                    if spec and "mp" in spec:
                        shard_div *= mp_degree
                    if spec and "ep" in spec:
                        shard_div *= ep_degree
                    if shard_div > 1:
                        nbytes //= shard_div
                        op.attrs[TP_SPEC_ATTR] = encode_spec(spec)
                    grad_reduce[g] = {"axes": ("dp",), "bytes": nbytes}
                continue
            elif op.type.endswith("_grad"):
                if op.type in ("lookup_table_grad", "lookup_table_v2_grad"):
                    # mirror the forward's engine stamp: the generic-vjp
                    # lowering re-emits the forward from the GRAD op's
                    # own attrs (copied at backward-build time, before
                    # this pass ran), so without the stamp the manual
                    # pipeline×mp re-emission would gather from a local
                    # shard as if it were the global table
                    wname = op.inputs.get("W", [None])[0]
                    wspec = known.get(wname) if wname else None
                    if wspec and wspec[0] == "mp" \
                            and not any(s == "mp" for s in wspec[1:]):
                        op.attrs[EMB_SHARD_ATTR] = int(mp_degree)
                elif op.type == "moe_ffn_grad":
                    # mirror the forward stamp: the generic-vjp lowering
                    # re-emits the forward from the GRAD op's own attrs
                    # (copied at backward-build time, before this pass
                    # ran) — without it the recomputed forward would
                    # skip the ep all-to-all anchors
                    w1 = op.inputs.get("W1", [None])[0]
                    if w1 and "ep" in (known.get(w1) or ()):
                        op.attrs[MOE_EP_ATTR] = int(ep_degree)
                # the gradient of a var shares its var's layout (the
                # Megatron memo: dW of a column-parallel W is itself
                # column-parallel); unknown bases reset to unknown
                for n in op.output_arg_names():
                    base_spec = None
                    if n.endswith(GRAD_SUFFIX_TP):
                        base_spec = known.get(n[:-len(GRAD_SUFFIX_TP)])
                    if base_spec is not None \
                            and self._rank_ok(block, n, base_spec):
                        known[n] = base_spec
                    else:
                        known.pop(n, None)
            else:
                for n in op.output_arg_names():
                    known.pop(n, None)
        return grad_reduce

    @staticmethod
    def _rank_ok(block, name, spec):
        var = block._find_var_recursive(name)
        return var is not None and len(var.shape) == len(spec)

    def _prop_matmul(self, op, known):
        """out spec = x row dims + y col dim; an mp-sharded contraction
        makes the output a partial sum — anchoring a constraint on the
        output (its non-contracted spec) makes XLA place the mp reduce
        exactly here (Megatron's g operator)."""
        xs, ys = op.inputs.get("X", []), op.inputs.get("Y", [])
        outs = op.output_arg_names()
        if len(xs) != 1 or len(ys) != 1 or len(outs) != 1:
            return
        xspec, yspec = known.get(xs[0]), known.get(ys[0])
        if xspec is None and yspec is None:
            known.pop(outs[0], None)
            return
        var = op.block._find_var_recursive(outs[0])
        if var is None or not var.shape:
            known.pop(outs[0], None)
            return
        rank = len(var.shape)
        if op.type == "mul":
            ncol = int(op.attr("x_num_col_dims", 1) or 1)
            row = tuple(xspec[:ncol]) if xspec is not None \
                else (None,) * ncol
            col = (yspec[-1] if yspec is not None else None,)
            spec = row + col
            contracted = ((xspec is not None
                           and any(s == "mp" for s in xspec[ncol:]))
                          or (yspec is not None
                              and any(s == "mp" for s in yspec[:-1])))
        else:  # matmul / matmul_v2: batch dims ride through from X
            tx = bool(op.attr("transpose_X", op.attr("trans_x", False)))
            ty = bool(op.attr("transpose_Y", op.attr("trans_y", False)))
            xrow = (xspec[-1] if tx else xspec[-2]) \
                if xspec is not None and len(xspec) >= 2 else None
            xk = (xspec[-2] if tx else xspec[-1]) \
                if xspec is not None and len(xspec) >= 2 else None
            ycol = (yspec[-2] if ty else yspec[-1]) \
                if yspec is not None and len(yspec) >= 2 else None
            yk = (yspec[-1] if ty else yspec[-2]) \
                if yspec is not None and len(yspec) >= 2 else None
            batch = tuple(xspec[:rank - 2]) if xspec is not None \
                and len(xspec) == rank else (None,) * (rank - 2)
            spec = batch + (xrow, ycol)
            contracted = (xk == "mp") or (yk == "mp")
        if len(spec) != rank:
            known.pop(outs[0], None)
            return
        spec = tuple(s if s in (None, "dp", "mp") else None for s in spec)
        known[outs[0]] = spec
        if contracted or any(s == "mp" for s in spec):
            # anchor: pin the output layout so the partial-sum reduce
            # (or the sharded-activation layout) lands at this op.
            # Contracted anchors carry a "\tP" partial flag: the manual
            # pipeline×mp path psums them over 'mp' (Megatron's g
            # operator) and the chunked collective-matmul lowering
            # targets exactly these ops
            ents = list(op.attrs.get(TP_CONSTRAINT_ATTR, []) or [])
            ents.append(f"{outs[0]}\t{encode_spec(spec)}"
                        + ("\tP" if contracted else ""))
            op.attrs[TP_CONSTRAINT_ATTR] = ents

    @staticmethod
    def _prop_lookup(op, known, mp_degree):
        """Embedding lookup over a row-sharded table (W P('mp', None)):
        the engine returns a value replicated on 'mp' whose leading
        dims follow the ids' layout — stamp that as a layout anchor
        (under GSPMD the constraint is where XLA places the lookup's
        gather comm) and stamp ``EMB_SHARD_ATTR`` = the degree so the
        lowering dispatches to the sharded engine.  A table sharded any
        other way is outside engine scope: output unknown."""
        ws = op.inputs.get("W", [])
        outs = op.output_arg_names()
        if len(ws) != 1 or len(outs) != 1:
            return
        wspec = known.get(ws[0])
        var = op.block._find_var_recursive(outs[0])
        if wspec is None or not any(s == "mp" for s in wspec) \
                or var is None or not var.shape:
            known.pop(outs[0], None)
            return
        if wspec[0] != "mp" or any(s == "mp" for s in wspec[1:]):
            known.pop(outs[0], None)
            return
        rank = len(var.shape)
        ids = op.inputs.get("Ids", [None])[0]
        head = tuple(known.get(ids, ()))[:rank - 1]
        head = head + (None,) * (rank - 1 - len(head))
        # the engine needs ids replicated on mp; an mp entry in the ids
        # spec degrades that dim to replicated (GSPMD regathers)
        spec = tuple(None if s == "mp" else s for s in head) + (None,)
        known[outs[0]] = spec
        ents = list(op.attrs.get(TP_CONSTRAINT_ATTR, []) or [])
        ents.append(f"{outs[0]}\t{encode_spec(spec)}")
        op.attrs[TP_CONSTRAINT_ATTR] = ents
        op.attrs[EMB_SHARD_ATTR] = int(mp_degree)

    @staticmethod
    def _prop_transpose(op, known):
        xs = op.inputs.get("X", [])
        outs = op.output_arg_names()
        axes = [int(a) for a in (op.attr("axis", []) or [])]
        spec = known.get(xs[0]) if len(xs) == 1 else None
        if spec is None or len(axes) != len(spec) or not outs:
            for n in outs:
                known.pop(n, None)
            return
        known[outs[0]] = tuple(spec[a] for a in axes)

    @staticmethod
    def _prop_elementwise(op, known):
        xs, ys = op.inputs.get("X", []), op.inputs.get("Y", [])
        outs = op.output_arg_names()
        if len(xs) != 1 or len(outs) != 1:
            return
        xspec = known.get(xs[0])
        if xspec is not None:
            known[outs[0]] = xspec  # Y broadcasts into X's layout
        else:
            known.pop(outs[0], None)


class LayerScanPlan:
    """Scope-side stacker/unstacker for a layer-scanned program.

    ``stacks`` holds one entry per scope-resident weight family the
    LayerScanPass stacked (params, optimizer slots): carrier name,
    ordered per-layer member names, per-layer shape and dtype.  The
    Executor calls :meth:`ensure_stacked` on every dispatch (all
    compile paths) BEFORE state analysis:

    - first call / after a checkpoint restore: concrete per-layer scope
      values are packed host-side into one ``(num_layers, *shape)``
      carrier array and each member becomes a
      :class:`~.scope.StackedParamRef` view — checkpoints, paddle.save,
      ``LocalShard`` and the attribution join keep seeing per-layer
      values, so resume stays elastic across the scan flag;
    - steady state (all members are views): no-op;
    - a few concrete members over a live carrier (a trimmed run's
      unrolled edge layer updating its param per-step, or a partial
      restore): refreshed in place with a device-side ``.at[i].set`` —
      no host sync on the hot path.
    """

    __slots__ = ("stacks",)

    def __init__(self, stacks):
        self.stacks = tuple(stacks)

    def ensure_stacked(self, scope):
        from .scope import StackedParamRef, is_device_array

        for st in self.stacks:
            carrier, members = st["carrier"], st["members"]
            have_carrier = scope.has_var(carrier) \
                and scope.get_var(carrier) is not None
            vals, concrete_idx = [], []
            for i, m in enumerate(members):
                v = scope.get_var(m) \
                    if scope.has_var(m) and scope.get_var(m) is not None \
                    else None
                if v is None and not have_carrier:
                    raise RuntimeError(
                        f"layer-scan stacked state var {m!r} is not "
                        f"initialized in the scope; run the startup "
                        f"program first")
                vals.append(v)
                if v is not None and not (isinstance(v, StackedParamRef)
                                          and v.stack_name == carrier):
                    concrete_idx.append(i)
            if have_carrier and not concrete_idx:
                continue  # steady state
            if have_carrier and len(concrete_idx) < len(members):
                # incremental: a stale layer slice is refreshed on
                # device; members that are views already read the live
                # carrier and need no copy
                import jax.numpy as jnp

                buf = scope.get_var(carrier)
                if not is_device_array(buf):
                    # a host-packed carrier the program only READS is
                    # never replaced by a jit output, so it can still
                    # be numpy here — which has no .at
                    buf = jnp.asarray(buf)
                for i in concrete_idx:
                    v = vals[i]
                    if not is_device_array(v):
                        v = np.asarray(v)
                    buf = buf.at[i].set(jnp.asarray(v, dtype=buf.dtype))
                scope.set_var(carrier, buf)
            else:
                # full (re)pack: first call after startup, or a restore
                # that replaced every view — host-side, off the hot path
                arrs = [np.asarray(v) for v in vals]
                scope.set_var(carrier, np.stack(arrs, axis=0))
            for i, m in enumerate(members):
                scope.set_var(m, StackedParamRef(
                    scope, carrier, i, st["shape"], st["dtype"]))

    def __repr__(self):
        return f"LayerScanPlan(stacks={len(self.stacks)})"


# op types that must never sit inside a scanned segment: host I/O,
# control flow (their sub-blocks would need nested region handling),
# positional p2p pairs (scan would re-order the ring FIFO), and the
# fuse pass's own coalesce machinery
_LS_BREAKER_OPS = {
    "while", "cond_pair", "layer_scan", "layer_index", "feed", "fetch",
    "save", "load", "save_combine", "load_combine", "send_v2",
    "partial_send", "recv_v2", "partial_recv", "barrier", "print",
    "coalesce_tensor", "uncoalesce_tensor",
}
_LS_SUB_BLOCK_ATTRS = ("sub_block", "sub_block_t", "sub_block_f",
                       "layer_block")
# attrs excluded from the isomorphism comparison (placement annotations
# carry no trace semantics here)
_LS_IGNORED_ATTRS = {"op_device"}


class _LayerStack:
    """One stacked family the pass knows about: ordered member names ->
    carrier.  ``kind``: 'state' (scope-resident, managed by
    LayerScanPlan), 'ys' (produced by a scan in this program), or a
    pending carry stack ('carry_pre'/'carry_post') that only
    materializes a stacked output if something consumes it."""

    __slots__ = ("carrier", "members", "template", "kind", "index_of",
                 "producer", "active")

    def __init__(self, carrier, members, template, kind, producer=None):
        self.carrier = carrier
        self.members = tuple(members)
        self.template = template
        self.kind = kind
        self.index_of = {m: i for i, m in enumerate(self.members)}
        self.producer = producer  # producing _RunPlan for ys/carry kinds
        self.active = kind in ("state", "ys")


class _RunPlan:
    """One accepted isomorphic run, fully planned for emission."""

    __slots__ = ("start", "L", "M", "tpl", "sigmas", "shared", "carries",
                 "xs", "ys", "pulled", "policy")

    def __init__(self, start, L, M, tpl, sigmas):
        self.start = start
        self.L = L
        self.M = M
        self.tpl = tpl          # template ops (program Operators)
        self.sigmas = sigmas    # per segment: {template name -> member}
        self.shared = []        # names identical across segments
        self.carries = []       # (t_tpl, w_tpl) chained pairs
        self.xs = []            # dicts: tpl, members, src, stack, flip,
        #                         slice (start, stop) or None
        self.ys = []            # dicts: tpl, members, pre, stack,
        #                         flip, update_start (None = fresh/full)
        self.pulled = []        # (template allreduce op, ys index)
        self.policy = ""

    @property
    def end(self):
        return self.start + self.L * self.M


@register_pass
class LayerScanPass(Pass):
    """Scan-over-layers: detect maximal runs of isomorphic op segments
    (same op types/slots/attrs/topology, differing only in var names —
    the shape a repeated-layer model builder emits for its forward,
    backward, and optimizer regions) and rewrite each run into ONE
    ``layer_scan`` region op that ops/layer_scan.py lowers to a single
    ``jax.lax.scan`` over leading-axis-stacked per-layer weights, with
    the body optionally wrapped in ``jax.checkpoint`` under a
    configurable remat policy (FLAGS_layer_scan_policy /
    ``recompute_configs['policy']``).

    Why: whole-block jit re-traces and re-compiles the fully unrolled
    program, so trace+compile wall time and executable size grow
    linearly with depth — the 48-100+ repeated-layer shapes tensor
    parallelism makes trainable.  Scanning collapses the region to one
    traced body: HLO op count and compile time become ~constant in
    depth while per-step numerics stay BITWISE identical (each scan
    iteration lowers exactly the ops the unrolled program would, in the
    same order, with the same RNG-split chain threaded through the
    carry).

    Detection contract (anything else is left untouched, loudly:
    ``pass_layer_scan_skipped`` + a per-reason counter):

    - segments must be attr-identical under a positionally-consistent
      bijective renaming; mapped vars must agree on shape AND dtype
      (stacking needs rectangular families);
    - every template input classifies as shared (same name each layer),
      carry (layer k reads what layer k-1 wrote), or a per-layer xs
      family; every output as carry-out or a per-layer ys family;
    - per-layer weights/slots whose members live in the scope become
      scope-resident stacked carriers (:class:`LayerScanPlan`); grads
      and activations stack as internal scan ys consumed by later runs
      (the backward scan reads the forward scan's activation stacks,
      the optimizer scan reads the backward's grad stacks);
    - a later run whose families align with an existing stack only on a
      sub-range (layer 0's backward segment differs when the input
      needs no grad) is TRIMMED to the aligned window, the edge layers
      staying unrolled;
    - transpiler-marked per-grad allreduces inside a segment are pulled
      out of the body and re-emitted ONCE on the stacked grad carrier
      (stamped ``LAYER_STACK_ATTR`` so fuse bucketing and byte
      telemetry size them as num_layers x the per-layer bytes).
    """

    name = "layer_scan"

    # -- config ------------------------------------------------------------
    @staticmethod
    def _config(program):
        """(enabled, min_layers, policy): program stamps (strategy
        plumbing via RecomputeMetaOptimizer) override the FLAGS_*
        defaults."""
        from . import flags

        enabled = bool(flags.flag("layer_scan"))
        min_layers = int(flags.flag("layer_scan_min_layers") or 4)
        policy = str(flags.flag("layer_scan_policy") or "")
        for op in program.global_block.ops:
            # RecomputeMetaOptimizer may stamp scan_layers, policy, or
            # BOTH (recompute_configs={'policy': ...} alone picks the
            # remat policy for a FLAGS_layer_scan-enabled run)
            has_n = op.has_attr(LAYER_SCAN_ATTR)
            p = op.attr(LAYER_SCAN_POLICY_ATTR, None)
            if not (has_n or p):
                continue
            if has_n:
                v = int(op.attr(LAYER_SCAN_ATTR) or 0)
                if v > 0:
                    enabled = True
                    min_layers = v
            if p:
                policy = str(p)
            break
        return enabled, max(min_layers, 2), policy

    def should_apply(self, program, ctx):
        if getattr(program, "_pipeline", None) is not None:
            return False
        enabled, min_layers, _ = self._config(program)
        return enabled and len(program.global_block.ops) >= 2 * min_layers

    # -- structural fingerprints -------------------------------------------
    @staticmethod
    def _is_breaker(op):
        if op.type in _LS_BREAKER_OPS:
            return True
        if any(op.has_attr(a) for a in _LS_SUB_BLOCK_ATTRS):
            return True
        # ZeRO-sharded optimizer state is laid out over the dp axis by
        # name; stacking those members would break the shard_map specs
        if op.attr("__sharded_accumulators__", None):
            return True
        return False

    @staticmethod
    def _var_sig(block, name):
        v = block._find_var_recursive(name)
        if v is None:
            return ("?",)
        return (tuple(int(s) for s in v.shape), int(v.dtype),
                bool(v.persistable))

    @classmethod
    def _op_key(cls, block, op):
        """Structural fingerprint: everything about the op EXCEPT the
        concrete var names.  Name-bearing attrs (tp constraint anchors)
        are canonicalized positionally against the op's own outputs."""
        out_names = op.output_arg_names()

        def canon_attr(k, v):
            if k == TP_CONSTRAINT_ATTR:
                ents = []
                for ent in (v or []):
                    nm, _, spec = str(ent).partition("\t")
                    if nm in out_names:
                        ents.append((out_names.index(nm), spec))
                    else:
                        ents.append((-1, nm, spec))  # conservative
                return tuple(ents)
            if isinstance(v, (list, tuple)):
                return tuple(v)
            return v

        def slots(d):
            return tuple(
                (s, tuple(cls._var_sig(block, n) for n in names))
                for s, names in sorted(d.items()))

        attrs = tuple(sorted(
            (k, canon_attr(k, v)) for k, v in op.attrs.items()
            if k not in _LS_IGNORED_ATTRS))
        return (op.type, slots(op.inputs), slots(op.outputs), attrs)

    # -- run detection ------------------------------------------------------
    def _find_runs(self, block, ops, min_layers, max_period=256):
        """Non-overlapping (start, period, count) candidates, greedy in
        stream order; candidates are verified/classified later."""
        n = len(ops)
        breaker = [self._is_breaker(op) for op in ops]
        interned: Dict[tuple, int] = {}
        kid = []
        positions: Dict[int, List[int]] = {}
        for i, op in enumerate(ops):
            if breaker[i]:
                kid.append(-1 - i)  # unique: never matches anything
                continue
            k = interned.setdefault(self._op_key(block, op), len(interned))
            kid.append(k)
            positions.setdefault(k, []).append(i)

        runs = []
        i = 0
        while i < n:
            if breaker[i]:
                i += 1
                continue
            limit = min(max_period, (n - i) // min_layers)
            found = None
            for p in positions.get(kid[i], ()):
                L = p - i
                if L <= 0:
                    continue
                if L > limit:
                    break
                if kid[i:i + L] != kid[i + L:i + 2 * L]:
                    continue
                M = 2
                while i + (M + 1) * L <= n \
                        and kid[i + M * L:i + (M + 1) * L] == kid[i:i + L]:
                    M += 1
                if M >= min_layers:
                    found = (L, M)
                    break
            if found:
                L, M = found
                runs.append((i, L, M))
                i += L * M
            else:
                i += 1
        return runs

    # -- renaming + classification -----------------------------------------
    @staticmethod
    def _sigma(tpl_ops, seg_ops):
        """Positional renaming template->segment; None on conflict or
        non-bijectivity."""
        fwd: Dict[str, str] = {}
        rev: Dict[str, str] = {}
        for a, b in zip(tpl_ops, seg_ops):
            for da, db in ((a.inputs, b.inputs), (a.outputs, b.outputs)):
                for slot, names in da.items():
                    other = db.get(slot, [])
                    if len(other) != len(names):
                        return None
                    for x, y in zip(names, other):
                        if fwd.setdefault(x, y) != y:
                            return None
                        if rev.setdefault(y, x) != x:
                            return None
        return fwd

    def _classify(self, ops, start, L, M):
        """Build the run's role model.  Returns (plan, reason): plan is
        a _RunPlan with shared/carries/xs/ys member tuples filled in
        (stack alignment happens later), reason names the rejection."""
        tpl = ops[start:start + L]
        sigmas = []
        for k in range(M):
            s = self._sigma(tpl, ops[start + k * L:start + (k + 1) * L])
            if s is None:
                return None, "rename_conflict"
            sigmas.append(s)

        tpl_writes = list(dict.fromkeys(
            n for op in tpl for n in op.output_arg_names()))
        written = set(tpl_writes)
        ext_in = []
        seen_w: set = set()
        for op in tpl:
            for n in op.input_arg_names():
                if n not in seen_w and n not in ext_in:
                    ext_in.append(n)
            seen_w.update(op.output_arg_names())

        # who writes each member name (cross-segment dependency map)
        write_owner: Dict[str, int] = {}
        for j, s in enumerate(sigmas):
            for w in tpl_writes:
                m = s[w]
                if write_owner.setdefault(m, j) != j:
                    return None, "output_classify"

        plan = _RunPlan(start, L, M, tpl, sigmas)

        def members(t):
            return tuple(s[t] for s in sigmas)

        carry_w: set = set()
        for t in ext_in:
            mem = members(t)
            if all(m == t for m in mem):
                if t in written:
                    return None, "shared_written"
                plan.shared.append(t)
                continue
            cw = None
            for w in tpl_writes:
                if w in carry_w:
                    continue
                if all(sigmas[k][t] == sigmas[k - 1][w]
                       for k in range(1, M)):
                    cw = w
                    break
            if cw is not None and write_owner.get(mem[0]) is None:
                plan.carries.append((t, cw))
                carry_w.add(cw)
                continue
            if len(set(mem)) == M and all(
                    write_owner.get(m, k) == k for k, m in enumerate(mem)):
                # per-layer xs family (a member may be written by its
                # OWN segment — the in-place optimizer update — but
                # never by a sibling)
                plan.xs.append({"tpl": t, "members": mem})
                continue
            return None, "input_classify"

        for w in tpl_writes:
            if w in carry_w:
                continue
            mem = members(w)
            if len(set(mem)) != M:
                return None, "output_classify"
            plan.ys.append({"tpl": w, "members": mem, "pre": False})
        return plan, None

    # -- stack alignment ----------------------------------------------------
    @staticmethod
    def _family_window(mem, stacks_of):
        """Longest contiguous segment window [a, b) over which the
        member tuple is either entirely absent from every known stack
        (a fresh family) or maps to a contiguous ascending/descending
        index slice of ONE stack.  Returns (a, b)."""
        n = len(mem)
        best = (0, 0)

        def better(w):
            nonlocal best
            if w[1] - w[0] > best[1] - best[0]:
                best = w

        # fresh runs
        a = None
        for i in range(n + 1):
            fresh = i < n and not stacks_of(mem[i])
            if fresh and a is None:
                a = i
            elif not fresh and a is not None:
                better((a, i))
                a = None

        # mapped runs, per candidate stack
        cands = []
        for m in (mem[0], mem[n // 2], mem[-1]):
            for st in stacks_of(m):
                if st not in cands:
                    cands.append(st)
        for st in cands:
            pos = [st.index_of.get(m) for m in mem]
            a = None
            dirn = 0
            for i in range(n + 1):
                ok = i < n and pos[i] is not None
                if ok and a is not None:
                    step = pos[i] - pos[i - 1]
                    if dirn == 0 and step in (1, -1):
                        dirn = step
                    elif step != dirn:
                        better((a, i))
                        a, dirn = i, 0
                        continue
                if ok and a is None:
                    a, dirn = i, 0
                elif not ok and a is not None:
                    better((a, i))
                    a, dirn = None, 0
        return best

    # -- planning one run ---------------------------------------------------
    def _plan_run(self, block, ops, start, L, M, registry, member_stacks,
                  min_layers, tp_plan, scope):
        """Classify + align a detected run against the stack registry;
        returns (_RunPlan, None) or (None, reason).  Stacks created for
        a run that is ultimately rejected are rolled back so they can
        never serve a later run's alignment."""
        created: List[_LayerStack] = []

        def rollback(reason):
            for st in created:
                registry.pop(st.carrier, None)
                for m in st.members:
                    lst = member_stacks.get(m)
                    if lst and st in lst:
                        lst.remove(st)
            return None, reason

        def stacks_of(name):
            return member_stacks.get(name, ())

        a, b = 0, M
        for _ in range(4):
            plan, reason = self._classify(ops, start + a * L, L, b - a)
            if plan is None:
                return None, reason
            lo, hi = 0, b - a
            for fam in plan.xs:
                wa, wb = self._family_window(fam["members"], stacks_of)
                lo, hi = max(lo, wa), min(hi, wb)
            if hi - lo < min_layers:
                return None, "stack_align"
            if (lo, hi) == (0, b - a):
                break
            a, b = a + lo, a + hi
        else:
            return None, "stack_align"
        plan.start = start + a * L

        # xs: bind to carriers / gather lists
        for fam in plan.xs:
            mem = fam["members"]
            hits = [st for st in stacks_of(mem[0]) if self._slice_of(
                mem, st) is not None]
            if hits:
                st = hits[0]
                s0, flip = self._slice_of(mem, st)
                fam.update(src="c", stack=st, flip=flip,
                           slice=None if (s0 == 0 and len(mem) ==
                                          len(st.members))
                           else (s0, s0 + len(mem)))
                st.active = True
            else:
                if any(stacks_of(m) for m in mem):
                    return rollback("family_mismatch")
                tvar = block._find_var_recursive(fam["tpl"])
                if tvar is None or not tvar.shape:
                    return rollback("var_missing")
                state = all(
                    (lambda v: v is not None and v.persistable)(
                        block._find_var_recursive(m))
                    or (scope is not None and scope.has_var(m))
                    for m in mem)
                if state:
                    st = self._new_stack(block, fam["tpl"], mem, "state",
                                         registry, member_stacks)
                    created.append(st)
                    fam.update(src="c", stack=st, flip=0, slice=None)
                else:
                    fam.update(src="g", stack=None, flip=0, slice=None)
            if tp_plan is not None and not self._tp_uniform(
                    tp_plan, fam["members"]):
                return rollback("tp_spec_mismatch")

        # ys: fresh stacks, or in-place updates of state carriers
        for fam in plan.ys:
            mem = fam["members"]
            upd = None
            for st in stacks_of(mem[0]):
                sl = self._slice_of(mem, st)
                if sl is not None and st.kind == "state":
                    upd = (st, sl)
                    break
            if upd is not None:
                st, (s0, flip) = upd
                fam.update(stack=st, flip=flip,
                           update_start=None if (s0 == 0 and len(mem) ==
                                                 len(st.members) and
                                                 not flip) else s0)
                continue
            if any(stacks_of(m) for m in mem):
                return rollback("ys_conflict")
            st = self._new_stack(block, fam["tpl"], mem, "ys", registry,
                                 member_stacks, producer=plan)
            created.append(st)
            fam.update(stack=st, flip=0, update_start=None)
            if tp_plan is not None and not self._tp_uniform(tp_plan, mem):
                return rollback("tp_spec_mismatch")

        # pending carry stacks: later consumers (the backward scan over
        # forward activations) or outside readers activate them.  BOTH
        # the iteration-start (pre) and iteration-end (post) views are
        # registered — the backward's activation families span either,
        # depending on whether the chained value is consumed before or
        # after its layer's update — and only the consumed one ever
        # emits a stacked output
        for (t, w) in plan.carries:
            mem_in = tuple(s[t] for s in plan.sigmas)
            mem_out = tuple(s[w] for s in plan.sigmas)
            for kind, tpl_n, mem in (("carry_pre", t, mem_in),
                                     ("carry_post", w, mem_out)):
                if any(st.members == mem
                       for m in mem for st in member_stacks.get(m, ())):
                    continue  # identical family already registered
                created.append(self._new_stack(
                    block, tpl_n, mem, kind, registry, member_stacks,
                    producer=plan))

        return plan, None

    @staticmethod
    def _slice_of(mem, st):
        """(start, flip) when ``mem`` is a contiguous ascending or
        descending index slice of stack ``st``, else None."""
        pos = [st.index_of.get(m) for m in mem]
        if any(p is None for p in pos):
            return None
        if len(pos) == 1:
            return pos[0], 0
        step = pos[1] - pos[0]
        if step not in (1, -1):
            return None
        if any(pos[i + 1] - pos[i] != step for i in range(len(pos) - 1)):
            return None
        return (pos[0], 0) if step == 1 else (pos[-1], 1)

    @staticmethod
    def _tp_uniform(tp_plan, mem):
        specs = {tuple(tp_plan.specs.get(m, ())) for m in mem}
        return len(specs) == 1

    @staticmethod
    def _new_stack(block, tpl_name, mem, kind, registry, member_stacks,
                   producer=None):
        carrier = LAYER_STACK_PREFIX + tpl_name
        if carrier in registry:
            # same template name reused by a disjoint family (two runs
            # whose templates landed on the same layer): uniquify
            n = 2
            while f"{carrier}#{n}" in registry:
                n += 1
            carrier = f"{carrier}#{n}"
        tvar = block._find_var_recursive(tpl_name)
        # the carrier's DECLARED shape stays per-layer (see
        # LAYER_STACK_ATTR): consumers that need physical bytes must
        # multiply by the stamp
        block.create_var(
            name=carrier,
            shape=list(tvar.shape) if tvar is not None else [],
            dtype=(tvar.dtype if tvar is not None else "float32"),
            persistable=bool(kind == "state"),
            stop_gradient=True)
        st = _LayerStack(carrier, mem, tpl_name, kind, producer=producer)
        registry[carrier] = st
        for m in mem:
            member_stacks.setdefault(m, []).append(st)
        return st

    # -- emission -----------------------------------------------------------
    def _emit_run(self, block, plan, policy):
        """Emit the layer_scan op (+ pulled-out stacked allreduces) for
        one planned run.  layer_index materializations are appended by
        the caller, which knows the outside readers."""
        from .program import Operator

        program = block.program
        tblock = program._create_block(parent_idx=block.idx)
        program._rollback()

        # pull transpiler-marked in-place grad allreduces out of the
        # body: the scan emits the stacked pre-reduce grads and ONE
        # collective covers all layers (bitwise: an elementwise sum per
        # layer == the same sum on the stacked array)
        ys_by_tpl = {f["tpl"]: f for f in plan.ys}
        pulled = []
        for j, op in enumerate(plan.tpl):
            if op.type != "c_allreduce_sum" \
                    or not op.attr(FUSED_ALLREDUCE_ATTR):
                continue
            xs_n = op.inputs.get("X", [])
            if len(xs_n) != 1 or op.outputs.get("Out", []) != xs_n:
                continue
            g = xs_n[0]
            fam = ys_by_tpl.get(g)
            if fam is None or fam.get("update_start") is not None \
                    or fam.get("flip"):
                continue
            # nothing later in the body may read the pre-reduce value
            if any(g in later.input_arg_names()
                   for later in plan.tpl[j + 1:]):
                continue
            pulled.append((j, op, fam))
        pulled_idx = {j for j, _, _ in pulled}

        for j, op in enumerate(plan.tpl):
            if j in pulled_idx:
                continue
            tblock.ops.append(Operator(
                tblock, op.type,
                {s: list(n) for s, n in op.inputs.items()},
                {s: list(n) for s, n in op.outputs.items()},
                dict(op.attrs)))

        sig0, sigN = plan.sigmas[0], plan.sigmas[-1]
        inputs = {}
        outputs = {}
        attrs = {
            "layer_block": tblock.idx,
            "num_layers": plan.M,
        }
        if policy:
            attrs["remat_policy"] = policy
        if plan.carries:
            inputs["CarryIn"] = [sig0[t] for t, _ in plan.carries]
            outputs["CarryOut"] = [sigN[w] for _, w in plan.carries]
            attrs["carry_in_tpl"] = [t for t, _ in plan.carries]
            attrs["carry_out_tpl"] = [w for _, w in plan.carries]
        if plan.shared:
            inputs["Shared"] = list(plan.shared)

        stacked_in, gather_in = [], []
        xs_tpl, xs_src, xs_flip, xs_start, xs_stop = [], [], [], [], []
        for fam in plan.xs:
            xs_tpl.append(fam["tpl"])
            xs_src.append(fam["src"])
            xs_flip.append(int(fam.get("flip") or 0))
            sl = fam.get("slice")
            xs_start.append(-1 if sl is None else int(sl[0]))
            xs_stop.append(-1 if sl is None else int(sl[1]))
            if fam["src"] == "c":
                stacked_in.append(fam["stack"].carrier)
            else:
                gather_in.extend(fam["members"])
        if xs_tpl:
            attrs.update(xs_tpl=xs_tpl, xs_src=xs_src, xs_flip=xs_flip,
                         xs_start=xs_start, xs_stop=xs_stop)
        if stacked_in:
            inputs["StackedIn"] = stacked_in
        if gather_in:
            inputs["GatherIn"] = gather_in

        ys_tpl, ys_pre, ys_flip, ys_ustart, stacked_out = [], [], [], [], []
        for fam in plan.ys:
            st = fam["stack"]
            if st.kind in ("carry_pre", "carry_post") and not st.active:
                continue  # nobody consumes this carry stack
            ys_tpl.append(fam["tpl"])
            ys_pre.append(int(bool(fam.get("pre"))))
            ys_flip.append(int(fam.get("flip") or 0))
            us = fam.get("update_start")
            ys_ustart.append(-1 if us is None else int(us))
            stacked_out.append(st.carrier)
        if ys_tpl:
            attrs.update(ys_tpl=ys_tpl, ys_pre=ys_pre, ys_flip=ys_flip,
                         ys_update_start=ys_ustart)
            outputs["StackedOut"] = stacked_out

        seq = [Operator(block, "layer_scan", inputs, outputs, attrs)]
        for _, op, fam in pulled:
            ar_attrs = dict(op.attrs)
            ar_attrs[LAYER_STACK_ATTR] = plan.M
            carrier = fam["stack"].carrier
            seq.append(Operator(block, "c_allreduce_sum",
                                {"X": [carrier]}, {"Out": [carrier]},
                                ar_attrs))
        return seq

    # -- apply --------------------------------------------------------------
    def apply(self, program, ctx):
        from ..monitor import stat_add, stat_set

        def skip(reason):
            stat_add("pass_layer_scan_skipped")
            stat_add(f"pass_layer_scan_skipped_{reason}")

        _, min_layers, policy = self._config(program)
        block = program.global_block
        ops = list(block.ops)

        runs = self._find_runs(block, ops, min_layers)
        if not runs:
            skip("no_repeats")
            return False

        tp_plan = getattr(program, "_tp_plan", None)
        registry: Dict[str, _LayerStack] = {}
        member_stacks: Dict[str, List[_LayerStack]] = {}
        plans: List[_RunPlan] = []
        for (start, L, M) in runs:
            plan, reason = self._plan_run(
                block, ops, start, L, M, registry, member_stacks,
                min_layers, tp_plan, ctx.scope)
            if plan is None:
                skip(reason)
                continue
            plans.append(plan)
        if not plans:
            return False

        # -- validation against the surviving unrolled ops ------------------
        run_ranges = [(p.start, p.end) for p in plans]

        def outside(i):
            return not any(a <= i < b for a, b in run_ranges)

        # an outside op writing an xs member in the carrier's STALE
        # window would be read stale through the stack: drop such plans
        # (their ops fall back to the unrolled stream).  The window
        # depends on who fills the carrier: a state stack is packed by
        # ensure_stacked BEFORE the program runs, so any outside write
        # preceding the consuming scan is a hazard; a producer-backed
        # stack (ys / activated carry) is filled DURING the producing
        # run's execution, so writes before the producer are captured
        # (a transformer's embedding dropout writing layer 0's residual
        # input before the forward scan is the canonical safe case) and
        # only the [producer.end, consumer.start) gap is stale.
        # Dropping a producer also drops every plan consuming one of
        # its stacks — iterate to the fixpoint (bounded by len(plans)).
        for _ in range(len(plans) + 1):
            outside_writes: Dict[str, List[int]] = {}
            for i, op in enumerate(ops):
                if outside(i):
                    for n in op.output_arg_names():
                        outside_writes.setdefault(n, []).append(i)
            alive = set(id(p) for p in plans)
            bad = []
            for p in plans:
                for fam in p.xs:
                    if fam["src"] != "c":
                        continue
                    st = fam["stack"]
                    if st.producer is not None \
                            and id(st.producer) not in alive:
                        bad.append(p)
                        break
                    lo = st.producer.end if st.producer is not None else 0
                    if any(lo <= i < p.start
                           for m in fam["members"]
                           for i in outside_writes.get(m, ())):
                        bad.append(p)
                        break
            if not bad:
                break
            for p in bad:
                plans.remove(p)
                skip("outside_write")
            run_ranges = [(p.start, p.end) for p in plans]
        if not plans:
            return False

        # -- which stacked members must materialize per-layer ---------------
        # (read by a surviving unrolled op after the producing run, a
        # fetch, or a persistable write-back that no state carrier
        # covers)
        reads_after: Dict[str, int] = {}
        for i, op in enumerate(ops):
            if outside(i):
                for n in op.input_arg_names():
                    reads_after[n] = max(reads_after.get(n, -1), i)
        fetches = set(ctx.fetch_names)
        need: Dict[int, List[tuple]] = {}  # plan idx -> (stack, member, idx)
        for pi, p in enumerate(plans):
            for fam in p.ys:
                st = fam["stack"]
                for m in fam["members"]:
                    wanted = m in fetches
                    if not wanted and m in reads_after \
                            and reads_after[m] >= p.end:
                        wanted = True
                    if not wanted and st.kind == "ys":
                        var = block._find_var_recursive(m)
                        if (var is not None and var.persistable) or (
                                ctx.scope is not None
                                and ctx.scope.has_var(m)):
                            # persistable per-layer write-back with no
                            # scope-view coverage: keep the write
                            wanted = True
                    if wanted:
                        st.active = True
                        need.setdefault(pi, []).append(
                            (st, m, st.index_of[m]))
            for (t, w) in p.carries:
                for tpl_n, kind in ((t, "carry_pre"), (w, "carry_post")):
                    mem = tuple(s[tpl_n] for s in p.sigmas)
                    sts = [s for s in member_stacks.get(mem[0], [])
                           if s.kind == kind and s.members == mem]
                    if not sts:
                        continue
                    st = sts[0]
                    # the final carry-out is bound directly by CarryOut;
                    # a carry-pre's first member is the run's EXTERNAL
                    # initial value — neither needs a stacked slice
                    excluded = {mem[-1]} if kind == "carry_post" \
                        else {mem[0]}
                    for m in mem:
                        if m in excluded:
                            continue
                        if m in fetches or reads_after.get(m, -1) >= p.end:
                            st.active = True
                            need.setdefault(pi, []).append(
                                (st, m, st.index_of[m]))

        # activated carry stacks become ys entries of their producer
        for p in plans:
            for (t, w) in p.carries:
                for tpl_n, pre, kind in ((t, True, "carry_pre"),
                                         (w, False, "carry_post")):
                    mem = tuple(s[tpl_n] for s in p.sigmas)
                    sts = [s for s in member_stacks.get(mem[0], [])
                           if s.kind == kind and s.members == mem
                           and s.active]
                    if sts and not any(f["stack"] is sts[0]
                                       for f in p.ys):
                        p.ys.append({"tpl": tpl_n, "members": mem,
                                     "pre": pre, "stack": sts[0],
                                     "flip": 0, "update_start": None})

        # -- rebuild the op stream ------------------------------------------
        from .program import Operator

        plan_at = {p.start: p for p in plans}
        new_ops: List = []
        i = 0
        n_layers_total = 0
        while i < len(ops):
            p = plan_at.get(i)
            if p is None:
                if outside(i):
                    new_ops.append(ops[i])
                i += 1
                continue
            seq = self._emit_run(block, p, policy)
            new_ops.extend(seq)
            for (st, m, j) in need.get(plans.index(p), []):
                new_ops.append(Operator(
                    block, "layer_index", {"X": [st.carrier]},
                    {"Out": [m]}, {"index": int(j)}))
            n_layers_total += p.M
            i = p.end

        block.ops[:] = new_ops
        program._bump()

        # -- scope plan + tp plan growth ------------------------------------
        used: set = set()
        for p in plans:
            for fam in p.xs:
                if fam.get("stack") is not None:
                    used.add(fam["stack"].carrier)
            for fam in p.ys:
                used.add(fam["stack"].carrier)
        state_stacks = []
        for st in registry.values():
            if st.kind != "state" or st.carrier not in used:
                continue
            tvar = block._find_var_recursive(st.template)
            state_stacks.append({
                "carrier": st.carrier,
                "members": st.members,
                "shape": tuple(int(s) for s in tvar.shape)
                if tvar is not None else (),
                "dtype": np.dtype(dtypes.to_np(tvar.dtype))
                if tvar is not None else np.dtype("float32"),
            })
        program._layer_plan = LayerScanPlan(state_stacks)

        if tp_plan is not None:
            for st in registry.values():
                if st.carrier not in used:
                    continue
                spec = tuple(tp_plan.specs.get(st.members[0], ()))
                if spec:
                    tp_plan.specs[st.carrier] = (None,) + spec
                # a pulled-out stacked allreduce replaces its members'
                # per-grad dp-reduce accounting entries
                moved = [m for m in st.members
                         if m in tp_plan.grad_reduce]
                if moved:
                    total = sum(int(tp_plan.grad_reduce.pop(m)["bytes"])
                                for m in moved)
                    tp_plan.grad_reduce[st.carrier] = {
                        "axes": ("dp",), "bytes": total}

        stat_set("pass_layer_scan_segments", len(plans))
        stat_set("pass_layer_scan_layers", n_layers_total)
        return True


@register_pass
class FuseAllReducePass(Pass):
    """Bucketed gradient-allreduce fusion (reference
    fuse_all_reduce_op_pass + coalesce_tensor_op).

    Only `c_allreduce_sum` ops carrying ``__fused_allreduce__`` are
    touched: the transpiler stamps exactly the per-gradient collectives
    it inserted, so user-built collectives and the sharding
    reduce-scatter path are never rewritten.  Grads whose var has an
    unknown/dynamic shape stay unfused (loudly counted, never dropped).

    Safe-placement invariant: the transpiler emits each allreduce
    immediately after its grad's last producer and every grad CONSUMER
    (optimizer/merge/clip/dgc) sits after the whole backward region, so
    anchoring the fused collective at the bucket's last original
    allreduce can never move a reduction past a read of its input.
    """

    name = "fuse_allreduce"

    def should_apply(self, program, ctx):
        return any(op.type == "c_allreduce_sum"
                   and op.attr(FUSED_ALLREDUCE_ATTR)
                   for op in program.global_block.ops)

    def apply(self, program, ctx):
        from ..monitor import stat_set

        block = program.global_block
        ops = block.ops
        n_before = sum(1 for op in ops if op.type == "c_allreduce_sum")

        entries = self._collect(block, ops)
        if not entries:
            return False
        # read barrier: the bucket's coalesced reduction lands at the
        # LAST member's anchor, so any op reading a member grad before
        # that anchor would see a pre-reduce value.  Record each
        # entry's first post-anchor read; _bucketize closes a bucket
        # rather than let a later member's anchor cross it.  Unrolled
        # transpiles never hit this (every allreduce precedes the
        # optimizer reads); a layer-scanned program's layer_index
        # materializations read the stacked grad carrier right after
        # its pulled-out allreduce, with edge-layer allreduces behind.
        readers: Dict[str, List[int]] = {}
        for i, op in enumerate(ops):
            for n in op.input_arg_names():
                readers.setdefault(n, []).append(i)
        for e in entries:
            skip = set(e["remove"])
            e["first_read"] = next(
                (j for j in readers.get(e["grad"], ())
                 if j > e["anchor"] and j not in skip), len(ops))
        # overlap stretch (FLAGS_overlap_grad_allreduce): chain-adjacency
        # between consecutive entries — True when ONLY bucket-member ops
        # (the marked allreduces + their cast pairs) sit between them in
        # the op stream.  A gap means backward COMPUTE separates the two
        # collectives: fusing a stacked grad carrier across that gap
        # would drag the bulk payload's dispatch past the remaining
        # backward segment, serializing the very comm the scan boundary
        # lets us hide.
        member_idx = {i for e in entries for i in e["remove"]}
        for k in range(len(entries) - 1):
            lo = max(entries[k]["remove"])
            hi = min(entries[k + 1]["remove"])
            entries[k]["adj_next"] = all(
                j in member_idx for j in range(lo + 1, hi))
        if entries:
            entries[-1]["adj_next"] = False
        from . import flags as _flags

        buckets = self._bucketize(
            entries, overlap=bool(_flags.flag("overlap_grad_allreduce")))
        fuse_buckets = [b for b in buckets if len(b["items"]) >= 2]
        if not fuse_buckets:
            return False

        removed: set = set()
        anchor_to_bucket: Dict[int, tuple] = {}
        for bi, b in enumerate(fuse_buckets):
            for e in b["items"]:
                removed.update(e["remove"])
            anchor = max(e["anchor"] for e in b["items"])
            anchor_to_bucket[anchor] = (bi, b)

        new_ops: List = []
        for i, op in enumerate(ops):
            if i in anchor_to_bucket:
                bi, b = anchor_to_bucket[i]
                new_ops.extend(self._emit_bucket(block, bi, b))
                continue
            if i in removed:
                continue
            new_ops.append(op)
        block.ops[:] = new_ops
        program._bump()

        n_after = sum(1 for op in new_ops if op.type == "c_allreduce_sum")
        stat_set("pass_fused_allreduce_buckets", len(fuse_buckets))
        stat_set("pass_allreduce_ops_before", n_before)
        stat_set("pass_allreduce_ops_after", n_after)
        return True

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _collect(block, ops) -> List[dict]:
        """One marked allreduce (+ its adjacent marked fp16 cast pair)
        per entry, in program order."""
        entries = []
        for i, op in enumerate(ops):
            if op.type != "c_allreduce_sum" \
                    or not op.attr(FUSED_ALLREDUCE_ATTR):
                continue
            xs = op.inputs.get("X", [])
            if len(xs) != 1 or op.outputs.get("Out", []) != xs:
                continue  # only the transpiler's in-place form fuses
            g = xs[0]
            var = block._find_var_recursive(g)
            if var is None or any(int(s) <= 0 for s in var.shape):
                continue  # unknown/dynamic shape: leave unfused
            try:
                dtype = dtypes.to_str(var.dtype)
            except (KeyError, ValueError):
                continue
            remove = [i]
            anchor = i
            pre = i > 0 and _marked_inplace_cast(ops[i - 1], g)
            post = i + 1 < len(ops) and _marked_inplace_cast(ops[i + 1], g)
            if pre and post:
                remove += [i - 1, i + 1]
                anchor = i + 1
            # a LayerScanPass-stacked grad carries num_layers x the
            # per-layer payload over a var whose DECLARED shape stays
            # per-layer (the stack axis is a runtime artifact): size the
            # bucket — and the uncoalesce split sections — by the TRUE
            # stacked shape, or a 48-layer stack would be bucketed at
            # 1/48th of the bytes it actually moves
            stack = int(op.attr(LAYER_STACK_ATTR, 0) or 0)
            shape = tuple(int(s) for s in var.shape)
            if stack > 1:
                shape = (stack,) + shape
            entries.append({
                "stacked": stack > 1,
                "grad": g,
                "shape": shape,
                "dtype": dtype,
                "bytes": _numel(shape) * _itemsize(dtype),
                "fp16": pre and post,
                "ring_id": int(op.attr("ring_id", 0) or 0),
                # tensor-parallel spec stamped by ShardingPropagationPass
                # (runs first): joins the bucket key so differently-
                # sharded grads NEVER share a fused buffer — a coalesce
                # across layouts would force GSPMD to re-shard every
                # member to one layout and back
                "tp_spec": str(op.attr(TP_SPEC_ATTR, "") or ""),
                "cap": float(op.attr(FUSE_SIZE_ATTR, DEFAULT_FUSE_MB))
                * 1024.0 * 1024.0,
                "anchor": anchor,
                "remove": remove,
            })
        return entries

    @staticmethod
    def _bucketize(entries, overlap=False) -> List[dict]:
        """Greedy size-capped bucketing in program order, one bucket
        stream per (dtype, ring, fp16) key — mixed-dtype grads never
        share a fused buffer.

        ``overlap`` (FLAGS_overlap_grad_allreduce): a bucket holding a
        LayerScanPass-STACKED grad carrier (num_layers x per-layer
        bytes, produced whole by the backward scan) refuses to admit an
        UNSTACKED entry that sits past intervening backward compute —
        the unrolled edge-layer tail.  Fusing across that scan
        boundary would delay the bulk payload's allreduce until the
        last edge-layer grad instead of dispatching it under the
        remaining backward compute.  Everything else keeps the plain
        greedy stream: unrolled programs (ResNet's 161→4) and
        stacked-with-stacked fusion are untouched."""
        from ..monitor import stat_add

        buckets: List[dict] = []
        open_buckets: Dict[tuple, dict] = {}
        for pos, e in enumerate(entries):
            key = (e["dtype"], e["ring_id"], e["fp16"], e["tp_spec"])
            if e["bytes"] > e["cap"]:
                # an over-cap grad gets its own CLOSED bucket without
                # evicting the key's open bucket — neighbors on either
                # side of a huge embedding grad keep fusing together
                buckets.append({"key": key, "items": [e],
                                "bytes": e["bytes"]})
                continue
            b = open_buckets.get(key)
            if b is not None and e["anchor"] >= b["min_read"]:
                # adding this entry would move the bucket's emission
                # point (= max member anchor) past an existing member's
                # first read — that reader would see the pre-reduce
                # value.  Close at the read barrier instead.
                open_buckets.pop(key)
                b = None
            if b is not None and overlap and b["has_stacked"] \
                    and not e.get("stacked", False) \
                    and not all(entries[j].get("adj_next", False)
                                for j in range(b["last_pos"], pos)):
                # scan-boundary stretch: the open bucket carries a
                # stacked grad whose backward segment (the scan)
                # already finished, and this UNSTACKED edge-layer grad
                # sits past intervening backward compute — close the
                # bucket so the carrier's bulk allreduce dispatches now
                # and overlaps that compute, instead of being dragged
                # to the tail.  Stacked-with-stacked fusion across
                # compute keeps the old greedy semantics (their byte
                # ratio makes the delay symmetric).
                closed = open_buckets.pop(key)
                # the closed bucket's comm runs under the remaining
                # backward compute: the phase ledger models it hidden
                closed["overlap_hidden"] = True
                b = None
                stat_add("pass_overlap_stretched_buckets")
            if b is None or b["bytes"] + e["bytes"] > e["cap"]:
                b = {"key": key, "items": [], "bytes": 0,
                     "min_read": float("inf"), "has_stacked": False,
                     "last_pos": pos}
                open_buckets[key] = b
                buckets.append(b)
            b["items"].append(e)
            b["bytes"] += e["bytes"]
            b["has_stacked"] = b["has_stacked"] or e.get("stacked", False)
            b["last_pos"] = pos
            b["min_read"] = min(b["min_read"],
                                e.get("first_read", float("inf")))
        return buckets

    @staticmethod
    def _emit_bucket(block, bucket_idx: int, bucket: dict) -> List:
        from .program import Operator

        dtype, ring_id, fp16, tp_spec = bucket["key"]
        grads = [e["grad"] for e in bucket["items"]]
        shapes = [e["shape"] for e in bucket["items"]]
        sections = [_numel(s) for s in shapes]
        # deterministic name: re-transpiles of the same program fuse to
        # identical fingerprints, so compiled executables stay shared
        fused = f"@FUSED_GRAD@{dtype}@r{ring_id}@{bucket_idx}"
        block.create_var(name=fused, shape=[sum(sections)], dtype=dtype,
                         stop_gradient=True)
        seq = [Operator(block, "coalesce_tensor", {"Input": grads},
                        {"FusedOutput": [fused]},
                        {"dtype": dtypes.to_enum(dtype)})]
        if fp16:
            seq.append(Operator(block, "cast", {"X": [fused]},
                                {"Out": [fused]},
                                {"out_dtype": dtypes.to_enum("bfloat16")}))
        fused_attrs = {"ring_id": ring_id, "use_calc_stream": True,
                       # ledger identity (observe/phases.py): stable
                       # across re-transpiles like the fused var name
                       COMM_ID_ATTR: f"bucket:{dtype}@r{ring_id}@{bucket_idx}"}
        if bucket.get("overlap_hidden"):
            fused_attrs[COMM_OVERLAP_ATTR] = True
        if tp_spec:
            # a homogeneous tp bucket keeps its members' spec visible to
            # the collective span/byte telemetry (the fused 1-D buffer's
            # dp payload is the member shards' sum, flagged 'mp'-sharded)
            fused_attrs[TP_SPEC_ATTR] = tp_spec
        seq.append(Operator(block, "c_allreduce_sum", {"X": [fused]},
                            {"Out": [fused]}, fused_attrs))
        if fp16:
            seq.append(Operator(block, "cast", {"X": [fused]},
                                {"Out": [fused]},
                                {"out_dtype": dtypes.to_enum(dtype)}))
        seq.append(Operator(
            block, "uncoalesce_tensor", {"Input": [fused]},
            {"Output": grads},
            {"sections": sections,
             "dims": [int(d) for s in shapes for d in s],
             "ranks": [len(s) for s in shapes]}))
        return seq


# ops that provably hand their (single) input's runtime dtype through to
# every output — the only ops the cast dataflow tracks through
_DTYPE_PRESERVING = {
    "assign", "c_identity", "c_allreduce_sum", "c_allreduce_max",
    "c_allreduce_min", "c_allreduce_prod", "c_broadcast", "c_allgather",
    "allreduce", "mp_allreduce_sum",
}


@register_pass
class RedundantCastEliminationPass(Pass):
    """Remove `cast` ops whose input PROVABLY already holds the target
    dtype (reference delete_cast_op_pass role).

    Conservative forward dataflow: a name's runtime dtype is known only
    when written by a `cast` (the attr names it) or by a
    dtype-preserving op with a known input.  Everything else — feeds
    included — starts/resets to unknown: jax device-array feeds pass
    through ``_feed_spec`` WITHOUT dtype coercion, so even a feed's
    declared var dtype is not trustworthy, and a declared-fp32 var that
    currently holds bf16 bits (the in-place fp16-allreduce pattern) can
    never be mistaken for fp32.
    """

    name = "redundant_cast_eliminate"

    def should_apply(self, program, ctx):
        return any(op.type == "cast" for op in program.global_block.ops)

    def apply(self, program, ctx):
        from ..monitor import stat_add
        from .lowering import PSEUDO_OPS
        from .program import Operator

        block = program.global_block
        cur: Dict[str, str] = {}
        new_ops: List = []
        n_removed = 0
        for op in block.ops:
            if op.type in PSEUDO_OPS:
                new_ops.append(op)
                continue
            if op.type == "cast":
                xs = op.inputs.get("X", [])
                outs = op.outputs.get("Out", [])
                dst = None
                try:
                    dst = dtypes.to_str(op.attr("out_dtype"))
                except (KeyError, ValueError, TypeError):
                    pass
                if len(xs) == 1 and len(outs) == 1 and dst is not None:
                    if cur.get(xs[0]) == dst:
                        n_removed += 1
                        if xs[0] == outs[0]:
                            continue  # in-place no-op cast: drop outright
                        op = Operator(block, "assign", {"X": [xs[0]]},
                                      {"Out": [outs[0]]})
                    cur[outs[0]] = dst
                    new_ops.append(op)
                    continue
            if op.type in _DTYPE_PRESERVING:
                ins = op.input_arg_names()
                known = cur.get(ins[0]) if len(ins) == 1 else None
                for n in op.output_arg_names():
                    if known is not None:
                        cur[n] = known
                    else:
                        cur.pop(n, None)
            else:
                for n in op.output_arg_names():
                    cur.pop(n, None)
            new_ops.append(op)
        if not n_removed:
            return False
        block.ops[:] = new_ops
        program._bump()
        stat_add("pass_casts_removed", n_removed)
        return True


@register_pass(before="sharding_propagation")
class FlashAttentionPass(Pass):
    """Rewrite the unfused attention chain — matmul(Q·Kᵀ, alpha) ->
    [elementwise_add mask] -> softmax -> matmul(·V) — plus its generic
    grad chain into the fused ``flash_attention`` /
    ``flash_attention_grad`` ops (ops/flash_attention.py: Pallas
    online-softmax forward keeping only per-row statistics, tiled
    recompute backward, one custom_vjp — HBM ~O(N) instead of the
    O(N²) materialized score tensor the plain chain costs).

    Gated by FLAGS_flash_attention ('never' = no rewrite, so the
    flag-off program stays bitwise-identical to the unfused chain;
    'auto' rewrites only on a TPU backend so CPU/tier-1 numerics never
    move; the flag is affects_lowering, so flips re-key the executor's
    pass and compile caches).  Registered ahead of sharding
    propagation: the fused op carries its own mp rule (heads-dim
    sharding rides through — the Megatron shape is kept internally)
    and LayerScanPass later sees the already-fused layer body, so the
    rewrite composes with remat policies and the tp f/g anchors.

    Conservative refusals — the chain is left alone when:
    - any intermediate (scores / masked scores / probs, or their grad
      twins) is fetched, persistable, or consumed outside the group
      (e.g. a dropout on the attention probs: the standard flash
      trade-off is no probs dropout);
    - the mask wants gradients (the fused op treats it as a constant
      additive bias);
    - the grad chain is only partially present or its cotangent wiring
      was renamed/summed (fan-out) — fusing half a backward would
      recompute the other half wrong;
    - shapes/attrs are off-pattern (non-rank-4 operands, transposed
      layouts, non-unit alpha on the probs·V matmul, softmax on a
      non-last axis).
    """

    name = "flash_attention_fuse"

    @staticmethod
    def _engaged():
        from . import flags

        mode = str(flags.flag("flash_attention") or "auto")
        if mode == "never":
            return False
        if mode == "always":
            return True
        import jax

        return jax.default_backend() == "tpu"

    def should_apply(self, program, ctx):
        return self._engaged() and any(
            op.type == "softmax" for op in program.global_block.ops)

    # -- chain matching ----------------------------------------------------
    @staticmethod
    def _slot1(op, group, slot):
        ns = op.inputs.get(slot, []) if group == "in" \
            else op.outputs.get(slot, [])
        return ns[0] if len(ns) == 1 else None

    def _match_group(self, block, ops, sm, producers, consumers,
                     fetched, claimed):
        """Match one fwd(+grad) group around a softmax op; returns None
        on any refusal condition."""
        s1 = self._slot1

        def rank(n):
            var = block._find_var_recursive(n)
            return len(var.shape) if var is not None and var.shape else 0

        def persistable(n):
            var = block._find_var_recursive(n)
            return bool(var is not None
                        and getattr(var, "persistable", False))

        masked = s1(sm, "in", "X")
        probs = s1(sm, "out", "Out")
        if not masked or not probs:
            return None
        if int(sm.attr("axis", -1)) not in (-1, rank(probs) - 1):
            return None

        prod = producers.get(masked)
        add = mask = None
        if prod is not None and prod.type == "elementwise_add":
            if int(prod.attr("axis", -1)) != -1:
                return None
            add, mask = prod, s1(prod, "in", "Y")
            scores = s1(prod, "in", "X")
            qk = producers.get(scores) if scores else None
        else:
            scores, qk = masked, prod
        if qk is None or qk.type != "matmul" or id(qk) in claimed:
            return None
        if bool(qk.attr("transpose_X", False)) \
                or not bool(qk.attr("transpose_Y", False)):
            return None
        q, k = s1(qk, "in", "X"), s1(qk, "in", "Y")

        pv = next((c for c in consumers.get(probs, [])
                   if c.type == "matmul"
                   and s1(c, "in", "X") == probs), None)
        if pv is None or bool(pv.attr("transpose_X", False)) \
                or bool(pv.attr("transpose_Y", False)) \
                or float(pv.attr("alpha", 1.0)) != 1.0:
            return None
        v, ctxv = s1(pv, "in", "Y"), s1(pv, "out", "Out")

        names = [q, k, v, scores, probs, ctxv] + ([mask] if add else [])
        if not all(names):
            return None
        if any(rank(n) != 4 for n in (q, k, v)):
            return None
        if add and rank(mask) != 4:
            return None

        fwd = [qk] + ([add] if add else []) + [sm, pv]
        if any(id(m) in claimed for m in fwd):
            return None

        # -- the matching generic grad chain (reverse order) --------------
        def find_grad(t, outname):
            cands = [o for o in ops if o.type == t
                     and s1(o, "in", "Out") == outname]
            return cands[0] if len(cands) == 1 else None

        g_pv = find_grad("matmul_grad", ctxv)
        g_sm = find_grad("softmax_grad", probs)
        g_add = find_grad("elementwise_add_grad", masked) if add else None
        g_qk = find_grad("matmul_grad", scores)
        grads = [g for g in (g_pv, g_sm, g_add, g_qk) if g is not None]
        if grads:
            need = 4 if add else 3
            if len(grads) != need:
                return None  # partial grad chain: refuse, don't half-fuse
            if any(g_add.outputs.get("Y" + GRAD_SUFFIX_TP, [])) \
                    if g_add is not None else False:
                return None  # learnable mask: fused op won't grad it
            if s1(g_pv, "in", "X") != probs or s1(g_pv, "in", "Y") != v \
                    or s1(g_qk, "in", "X") != q \
                    or s1(g_qk, "in", "Y") != k:
                return None
            # cotangent wiring must be the straight-line chain
            gp = (g_pv.outputs.get("X" + GRAD_SUFFIX_TP, [""]) + [""])[0]
            gm = (g_sm.outputs.get("X" + GRAD_SUFFIX_TP, [""]) + [""])[0]
            gs = (g_add.outputs.get("X" + GRAD_SUFFIX_TP, [""])
                  + [""])[0] if g_add is not None else gm
            if s1(g_sm, "in", "Out" + GRAD_SUFFIX_TP) != gp:
                return None
            if g_add is not None and \
                    s1(g_add, "in", "Out" + GRAD_SUFFIX_TP) != gm:
                return None
            if s1(g_qk, "in", "Out" + GRAD_SUFFIX_TP) != gs:
                return None
            grad_inner = [n for n in (gp, gm,
                                      gs if g_add is not None else None)
                          if n]
        else:
            grad_inner = []

        members = fwd + grads
        inner = [scores, probs] + ([masked] if add else []) + grad_inner
        for n in inner:
            if n in fetched or persistable(n):
                return None
            if any(all(c is not m for m in members)
                   for c in consumers.get(n, [])):
                return None  # intermediate escapes the group
        return {
            "fwd": fwd, "grads": grads, "q": q, "k": k, "v": v,
            "mask": mask if add else None, "ctxv": ctxv,
            "alpha": float(qk.attr("alpha", 1.0)),
            "g_pv": g_pv, "g_qk": g_qk,
        }

    def apply(self, program, ctx):
        from ..monitor import stat_add
        from .program import Operator

        block = program.global_block
        ops = list(block.ops)
        pos = {id(op): i for i, op in enumerate(ops)}
        producers, consumers = {}, {}
        for op in ops:
            for n in op.input_arg_names():
                consumers.setdefault(n, []).append(op)
            for n in op.output_arg_names():
                producers[n] = op
        fetched = set(ctx.fetch_names)

        claimed: set = set()
        groups = []
        for sm in ops:
            if sm.type != "softmax":
                continue
            g = self._match_group(block, ops, sm, producers, consumers,
                                  fetched, claimed)
            if g is None:
                continue
            if g["grads"]:
                # moving dv's definition to the grad-group tail is only
                # sound when nothing in between reads it
                tail = pos[id(g["g_qk"])]
                dv = (g["g_pv"].outputs.get(
                    "Y" + GRAD_SUFFIX_TP, [""]) + [""])[0]
                if dv and any(pos[id(c)] < tail
                              for c in consumers.get(dv, [])):
                    continue
            for m in g["fwd"] + g["grads"]:
                claimed.add(id(m))
            groups.append(g)
        if not groups:
            return False

        emit_at, skip = {}, set()
        for g in groups:
            attrs = {"scale": g["alpha"], "causal": False}
            inputs = {"Q": [g["q"]], "K": [g["k"]], "V": [g["v"]]}
            if g["mask"]:
                inputs["Mask"] = [g["mask"]]
            fop = Operator(block, "flash_attention", inputs,
                           {"Out": [g["ctxv"]]}, dict(attrs))
            emit_at[pos[id(g["fwd"][-1])]] = fop
            for m in g["fwd"]:
                skip.add(id(m))
            if g["grads"]:
                g_pv, g_qk = g["g_pv"], g["g_qk"]
                gin = dict(inputs)
                gin["Out"] = [g["ctxv"]]
                gin["Out" + GRAD_SUFFIX_TP] = [
                    self._slot1(g_pv, "in", "Out" + GRAD_SUFFIX_TP)]
                gout = {}
                dq = (g_qk.outputs.get("X" + GRAD_SUFFIX_TP, [""])
                      + [""])[0]
                dk = (g_qk.outputs.get("Y" + GRAD_SUFFIX_TP, [""])
                      + [""])[0]
                dv = (g_pv.outputs.get("Y" + GRAD_SUFFIX_TP, [""])
                      + [""])[0]
                if dq:
                    gout["Q" + GRAD_SUFFIX_TP] = [dq]
                if dk:
                    gout["K" + GRAD_SUFFIX_TP] = [dk]
                if dv:
                    gout["V" + GRAD_SUFFIX_TP] = [dv]
                gattrs = dict(attrs)
                gattrs["__fwd_type__"] = "flash_attention"
                gattrs["__fwd_out_slots__"] = ["Out"]
                gop = Operator(block, "flash_attention_grad", gin, gout,
                               gattrs)
                emit_at[pos[id(g_qk)]] = gop
                for m in g["grads"]:
                    skip.add(id(m))

        new_ops = []
        for i, op in enumerate(ops):
            if i in emit_at:
                new_ops.append(emit_at[i])
            elif id(op) not in skip:
                new_ops.append(op)
        block.ops[:] = new_ops
        program._bump()
        stat_add("pass_flash_attention_fused", len(groups))
        stat_add("pass_flash_attention_grad_fused",
                 sum(1 for g in groups if g["grads"]))
        return True


@register_pass
class DeadOpEliminationPass(Pass):
    """Drop ops whose outputs feed neither a fetch nor persistent state
    (reference eager deletion / graph DCE role), reusing the executor's
    ``_prune_ops`` backward slice.

    Roots: the dispatch fetch list, every persistable write, and every
    write whose name already lives in the scope chain (the same
    liveness rule ``_analyze_state`` uses for state_out), so optimizer
    updates and user-visible state always survive.  Ops with no outputs
    and the p2p/barrier side-effect ops are kept unconditionally.
    """

    name = "dead_op_eliminate"

    @staticmethod
    def _live_ops(program, ctx):
        """(kept op list, dead count) — O(ops); cheap enough that
        ``should_apply`` runs it on the ORIGINAL program, so the common
        nothing-to-remove case never pays the pipeline's clone.
        Memoized on the ctx per (program identity, version) so the
        should_apply/apply sequence slices each program once."""
        from .executor import _prune_ops
        from .lowering import PSEUDO_OPS

        memo_key = ("dce_live", id(program), program._version)
        hit = ctx._memo.get(memo_key)
        if hit is not None:
            return hit

        block = program.global_block
        roots = set(ctx.fetch_names)
        for op in block.ops:
            for n in op.output_arg_names():
                var = block._find_var_recursive(n)
                if (var is not None and var.persistable) or (
                        ctx.scope is not None and ctx.scope.has_var(n)):
                    roots.add(n)
        if not roots:
            result = (None, 0)
        else:
            keep = _prune_ops(program, sorted(roots),
                              keep_side_effect_ops=True)
            keep_ids = {id(op) for op in keep}
            new_ops = [op for op in block.ops
                       if op.type in PSEUDO_OPS or id(op) in keep_ids]
            result = (new_ops, len(block.ops) - len(new_ops))
        ctx._memo[memo_key] = result
        return result

    def should_apply(self, program, ctx):
        return self._live_ops(program, ctx)[1] > 0

    def apply(self, program, ctx):
        from ..monitor import stat_add

        new_ops, n_removed = self._live_ops(program, ctx)
        if not n_removed:
            return False
        program.global_block.ops[:] = new_ops
        program._bump()
        stat_add("pass_dead_ops_removed", n_removed)
        return True


class PassPipeline:
    """Ordered pass application with copy-on-write semantics.

    ``apply`` runs every pass on a CLONE of the program and returns the
    clone when any pass changed it, else the original object — the
    caller (Executor) caches the result per
    ``(program.fingerprint(), config_key, fetch, feeds, scope)``.
    """

    def __init__(self, passes: Optional[Sequence[Pass]] = None):
        if passes is None:
            _ensure_external_passes()
        self._passes: Tuple[Pass, ...] = tuple(
            passes if passes is not None
            else (cls() for cls in PASS_REGISTRY.values()))

    @property
    def passes(self) -> Tuple[Pass, ...]:
        return self._passes

    def config_key(self) -> tuple:
        """Joins the Executor's pass-cache key; per-pass knobs that ride
        op attrs (e.g. the fuse bucket cap) are already part of the
        program fingerprint."""
        return tuple(p.name for p in self._passes)

    def apply(self, program, ctx: Optional[PassContext] = None):
        from ..monitor import stat_add
        from ..observe import tracer as otrace

        ctx = ctx or PassContext()
        if not any(p.should_apply(program, ctx) for p in self._passes):
            return program
        work = program.clone()
        changed = False
        for p in self._passes:
            if p.should_apply(work, ctx):
                # one tracer span per pass, nested under the Executor's
                # executor/pass_pipeline span (observe/tracer.py)
                with otrace.span(f"pass/{p.name}"):
                    changed = bool(p.apply(work, ctx)) or changed
        stat_add("pass_pipeline_apply")
        return work if changed else program


_default_pipeline: Optional[PassPipeline] = None


def default_pipeline() -> PassPipeline:
    global _default_pipeline
    if _default_pipeline is None:
        _default_pipeline = PassPipeline()
    return _default_pipeline


def apply_passes(program, fetch_names: Sequence[str] = (),
                 feed_names: Sequence[str] = (), scope=None, mesh=None):
    """One-shot convenience: run the default pipeline over ``program``
    (returns the rewritten clone, or ``program`` itself when nothing
    applied)."""
    return default_pipeline().apply(
        program, PassContext(fetch_names=fetch_names,
                             feed_names=feed_names, scope=scope, mesh=mesh))
