"""Program-IR optimization pass pipeline.

Role parity: reference build-strategy graph passes
(framework/ir/pass.h, build_strategy.cc) — most prominently
`fuse_all_reduce_op_pass` + `coalesce_tensor_op` (Horovod-style tensor
fusion): instead of one latency-bound `c_allreduce_sum` per gradient,
same-dtype grads are flattened into size-capped fused buffers and
reduced per bucket.  On a ResNet/BERT step this turns hundreds of
small collectives into a handful of bandwidth-bound ones.

TPU-native framing: passes are *program rewrites applied before
lowering*, not graph-node surgery on an SSA graph — the Executor clones
the program, runs the pipeline on the clone, and compiles the rewritten
clone, so the user's program object is never mutated (with
``fuse_all_reduce_ops=False`` or ``FLAGS_fuse_passes=0`` the exact
pre-pass program compiles).  Application is cached per
``(program.fingerprint(), pass config)`` by the Executor; the
``FLAGS_fuse_passes`` flag is registered with ``affects_lowering=True``
so flipping it re-keys the compile cache too.

Passes in default order:

1. ``FuseAllReducePass`` — groups the `c_allreduce_sum` ops the
   collective transpiler marked (``__fused_allreduce__`` attr) into
   per-dtype buckets capped at ``__fuse_grad_size_mb__`` (default 32 MB,
   ``DistributedStrategy.fuse_grad_size_in_MB``), and rewrites each
   bucket into ``coalesce_tensor`` (flatten+concat) → one
   ``c_allreduce_sum`` → ``uncoalesce_tensor`` (split+reshape back),
   anchored at the LAST original allreduce of the bucket so the fused
   collective still launches as soon as its last gradient is produced
   (comm/backward overlap is preserved).  Under the fp16/bf16 allreduce
   strategy the per-grad cast pairs collapse to one pair per bucket.
2. ``RedundantCastEliminationPass`` — removes `cast` ops whose input
   provably already holds the target dtype (tracked by a conservative
   forward dataflow; unknown dtypes are never touched).
3. ``DeadOpEliminationPass`` — drops ops that feed neither a fetch nor
   persistent/scope-resident state, reusing the executor's
   ``_prune_ops`` backward slice (side-effect ops like `send_v2` are
   always kept).

Observability (``paddle_tpu.monitor``): ``pass_fused_allreduce_buckets``,
``pass_allreduce_ops_before`` / ``pass_allreduce_ops_after``,
``pass_dead_ops_removed``, ``pass_casts_removed``, and the Executor's
``executor_pass_cache_hit``.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import dtypes

__all__ = [
    "FUSED_ALLREDUCE_ATTR",
    "FUSE_SIZE_ATTR",
    "DEFAULT_FUSE_MB",
    "Pass",
    "PassContext",
    "PassPipeline",
    "FuseAllReducePass",
    "RedundantCastEliminationPass",
    "DeadOpEliminationPass",
    "register_pass",
    "default_pipeline",
    "apply_passes",
]

# op-attr markers stamped by the collective transpiler
# (distributed/fleet/collective_transpiler.py GradAllReduce) on the ops
# it wants fused; attrs — not python side channels — so the linkage
# survives clone/proto round-trips and joins the program fingerprint
FUSED_ALLREDUCE_ATTR = "__fused_allreduce__"
FUSE_SIZE_ATTR = "__fuse_grad_size_mb__"
DEFAULT_FUSE_MB = 32.0


class PassContext:
    """Per-application context: what the Executor knows at dispatch time.

    ``fetch_names``/``feed_names``/``scope`` feed the dead-op slice and
    the cast dataflow; all three join the Executor's pass-cache key.
    """

    def __init__(self, fetch_names: Sequence[str] = (),
                 feed_names: Sequence[str] = (), scope=None):
        self.fetch_names = tuple(fetch_names)
        self.feed_names = tuple(feed_names)
        self.scope = scope
        # per-application scratch for passes (e.g. DCE memoizes its
        # prune slice across should_apply/apply)
        self._memo: Dict[tuple, object] = {}


class Pass:
    """One program rewrite.  ``apply`` mutates ``program`` in place and
    returns True iff it changed anything (drives the pipeline's
    copy-on-write: an all-no-op run hands the ORIGINAL program back to
    the Executor)."""

    name = "pass"

    def should_apply(self, program, ctx: PassContext) -> bool:
        return True

    def apply(self, program, ctx: PassContext) -> bool:
        raise NotImplementedError


PASS_REGISTRY: Dict[str, type] = {}


def register_pass(cls):
    """Register a Pass subclass into the ordered default registry and
    rebuild the default pipeline on next use (a registration after the
    first Executor run would otherwise be silently inert)."""
    global _default_pipeline
    if cls.name in PASS_REGISTRY:
        raise KeyError(f"pass {cls.name!r} already registered")
    PASS_REGISTRY[cls.name] = cls
    _default_pipeline = None
    return cls


def _numel(shape) -> int:
    n = 1
    for s in shape:
        n *= int(s)
    return n


def _itemsize(dtype_str: str) -> int:
    return int(np.dtype(dtypes.to_np(dtype_str)).itemsize)


def _marked_inplace_cast(op, name: str) -> bool:
    return (op.type == "cast" and bool(op.attr(FUSED_ALLREDUCE_ATTR))
            and op.inputs.get("X", []) == [name]
            and op.outputs.get("Out", []) == [name])


@register_pass
class FuseAllReducePass(Pass):
    """Bucketed gradient-allreduce fusion (reference
    fuse_all_reduce_op_pass + coalesce_tensor_op).

    Only `c_allreduce_sum` ops carrying ``__fused_allreduce__`` are
    touched: the transpiler stamps exactly the per-gradient collectives
    it inserted, so user-built collectives and the sharding
    reduce-scatter path are never rewritten.  Grads whose var has an
    unknown/dynamic shape stay unfused (loudly counted, never dropped).

    Safe-placement invariant: the transpiler emits each allreduce
    immediately after its grad's last producer and every grad CONSUMER
    (optimizer/merge/clip/dgc) sits after the whole backward region, so
    anchoring the fused collective at the bucket's last original
    allreduce can never move a reduction past a read of its input.
    """

    name = "fuse_allreduce"

    def should_apply(self, program, ctx):
        return any(op.type == "c_allreduce_sum"
                   and op.attr(FUSED_ALLREDUCE_ATTR)
                   for op in program.global_block.ops)

    def apply(self, program, ctx):
        from ..monitor import stat_set

        block = program.global_block
        ops = block.ops
        n_before = sum(1 for op in ops if op.type == "c_allreduce_sum")

        entries = self._collect(block, ops)
        if not entries:
            return False
        buckets = self._bucketize(entries)
        fuse_buckets = [b for b in buckets if len(b["items"]) >= 2]
        if not fuse_buckets:
            return False

        removed: set = set()
        anchor_to_bucket: Dict[int, tuple] = {}
        for bi, b in enumerate(fuse_buckets):
            for e in b["items"]:
                removed.update(e["remove"])
            anchor = max(e["anchor"] for e in b["items"])
            anchor_to_bucket[anchor] = (bi, b)

        new_ops: List = []
        for i, op in enumerate(ops):
            if i in anchor_to_bucket:
                bi, b = anchor_to_bucket[i]
                new_ops.extend(self._emit_bucket(block, bi, b))
                continue
            if i in removed:
                continue
            new_ops.append(op)
        block.ops[:] = new_ops
        program._bump()

        n_after = sum(1 for op in new_ops if op.type == "c_allreduce_sum")
        stat_set("pass_fused_allreduce_buckets", len(fuse_buckets))
        stat_set("pass_allreduce_ops_before", n_before)
        stat_set("pass_allreduce_ops_after", n_after)
        return True

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _collect(block, ops) -> List[dict]:
        """One marked allreduce (+ its adjacent marked fp16 cast pair)
        per entry, in program order."""
        entries = []
        for i, op in enumerate(ops):
            if op.type != "c_allreduce_sum" \
                    or not op.attr(FUSED_ALLREDUCE_ATTR):
                continue
            xs = op.inputs.get("X", [])
            if len(xs) != 1 or op.outputs.get("Out", []) != xs:
                continue  # only the transpiler's in-place form fuses
            g = xs[0]
            var = block._find_var_recursive(g)
            if var is None or any(int(s) <= 0 for s in var.shape):
                continue  # unknown/dynamic shape: leave unfused
            try:
                dtype = dtypes.to_str(var.dtype)
            except (KeyError, ValueError):
                continue
            remove = [i]
            anchor = i
            pre = i > 0 and _marked_inplace_cast(ops[i - 1], g)
            post = i + 1 < len(ops) and _marked_inplace_cast(ops[i + 1], g)
            if pre and post:
                remove += [i - 1, i + 1]
                anchor = i + 1
            entries.append({
                "grad": g,
                "shape": tuple(int(s) for s in var.shape),
                "dtype": dtype,
                "bytes": _numel(var.shape) * _itemsize(dtype),
                "fp16": pre and post,
                "ring_id": int(op.attr("ring_id", 0) or 0),
                "cap": float(op.attr(FUSE_SIZE_ATTR, DEFAULT_FUSE_MB))
                * 1024.0 * 1024.0,
                "anchor": anchor,
                "remove": remove,
            })
        return entries

    @staticmethod
    def _bucketize(entries) -> List[dict]:
        """Greedy size-capped bucketing in program order, one bucket
        stream per (dtype, ring, fp16) key — mixed-dtype grads never
        share a fused buffer."""
        buckets: List[dict] = []
        open_buckets: Dict[tuple, dict] = {}
        for e in entries:
            key = (e["dtype"], e["ring_id"], e["fp16"])
            if e["bytes"] > e["cap"]:
                # an over-cap grad gets its own CLOSED bucket without
                # evicting the key's open bucket — neighbors on either
                # side of a huge embedding grad keep fusing together
                buckets.append({"key": key, "items": [e],
                                "bytes": e["bytes"]})
                continue
            b = open_buckets.get(key)
            if b is None or b["bytes"] + e["bytes"] > e["cap"]:
                b = {"key": key, "items": [], "bytes": 0}
                open_buckets[key] = b
                buckets.append(b)
            b["items"].append(e)
            b["bytes"] += e["bytes"]
        return buckets

    @staticmethod
    def _emit_bucket(block, bucket_idx: int, bucket: dict) -> List:
        from .program import Operator

        dtype, ring_id, fp16 = bucket["key"]
        grads = [e["grad"] for e in bucket["items"]]
        shapes = [e["shape"] for e in bucket["items"]]
        sections = [_numel(s) for s in shapes]
        # deterministic name: re-transpiles of the same program fuse to
        # identical fingerprints, so compiled executables stay shared
        fused = f"@FUSED_GRAD@{dtype}@r{ring_id}@{bucket_idx}"
        block.create_var(name=fused, shape=[sum(sections)], dtype=dtype,
                         stop_gradient=True)
        seq = [Operator(block, "coalesce_tensor", {"Input": grads},
                        {"FusedOutput": [fused]},
                        {"dtype": dtypes.to_enum(dtype)})]
        if fp16:
            seq.append(Operator(block, "cast", {"X": [fused]},
                                {"Out": [fused]},
                                {"out_dtype": dtypes.to_enum("bfloat16")}))
        seq.append(Operator(block, "c_allreduce_sum", {"X": [fused]},
                            {"Out": [fused]},
                            {"ring_id": ring_id, "use_calc_stream": True}))
        if fp16:
            seq.append(Operator(block, "cast", {"X": [fused]},
                                {"Out": [fused]},
                                {"out_dtype": dtypes.to_enum(dtype)}))
        seq.append(Operator(
            block, "uncoalesce_tensor", {"Input": [fused]},
            {"Output": grads},
            {"sections": sections,
             "dims": [int(d) for s in shapes for d in s],
             "ranks": [len(s) for s in shapes]}))
        return seq


# ops that provably hand their (single) input's runtime dtype through to
# every output — the only ops the cast dataflow tracks through
_DTYPE_PRESERVING = {
    "assign", "c_identity", "c_allreduce_sum", "c_allreduce_max",
    "c_allreduce_min", "c_allreduce_prod", "c_broadcast", "c_allgather",
    "allreduce", "mp_allreduce_sum",
}


@register_pass
class RedundantCastEliminationPass(Pass):
    """Remove `cast` ops whose input PROVABLY already holds the target
    dtype (reference delete_cast_op_pass role).

    Conservative forward dataflow: a name's runtime dtype is known only
    when written by a `cast` (the attr names it) or by a
    dtype-preserving op with a known input.  Everything else — feeds
    included — starts/resets to unknown: jax device-array feeds pass
    through ``_feed_spec`` WITHOUT dtype coercion, so even a feed's
    declared var dtype is not trustworthy, and a declared-fp32 var that
    currently holds bf16 bits (the in-place fp16-allreduce pattern) can
    never be mistaken for fp32.
    """

    name = "redundant_cast_eliminate"

    def should_apply(self, program, ctx):
        return any(op.type == "cast" for op in program.global_block.ops)

    def apply(self, program, ctx):
        from ..monitor import stat_add
        from .lowering import PSEUDO_OPS
        from .program import Operator

        block = program.global_block
        cur: Dict[str, str] = {}
        new_ops: List = []
        n_removed = 0
        for op in block.ops:
            if op.type in PSEUDO_OPS:
                new_ops.append(op)
                continue
            if op.type == "cast":
                xs = op.inputs.get("X", [])
                outs = op.outputs.get("Out", [])
                dst = None
                try:
                    dst = dtypes.to_str(op.attr("out_dtype"))
                except (KeyError, ValueError, TypeError):
                    pass
                if len(xs) == 1 and len(outs) == 1 and dst is not None:
                    if cur.get(xs[0]) == dst:
                        n_removed += 1
                        if xs[0] == outs[0]:
                            continue  # in-place no-op cast: drop outright
                        op = Operator(block, "assign", {"X": [xs[0]]},
                                      {"Out": [outs[0]]})
                    cur[outs[0]] = dst
                    new_ops.append(op)
                    continue
            if op.type in _DTYPE_PRESERVING:
                ins = op.input_arg_names()
                known = cur.get(ins[0]) if len(ins) == 1 else None
                for n in op.output_arg_names():
                    if known is not None:
                        cur[n] = known
                    else:
                        cur.pop(n, None)
            else:
                for n in op.output_arg_names():
                    cur.pop(n, None)
            new_ops.append(op)
        if not n_removed:
            return False
        block.ops[:] = new_ops
        program._bump()
        stat_add("pass_casts_removed", n_removed)
        return True


@register_pass
class DeadOpEliminationPass(Pass):
    """Drop ops whose outputs feed neither a fetch nor persistent state
    (reference eager deletion / graph DCE role), reusing the executor's
    ``_prune_ops`` backward slice.

    Roots: the dispatch fetch list, every persistable write, and every
    write whose name already lives in the scope chain (the same
    liveness rule ``_analyze_state`` uses for state_out), so optimizer
    updates and user-visible state always survive.  Ops with no outputs
    and the p2p/barrier side-effect ops are kept unconditionally.
    """

    name = "dead_op_eliminate"

    @staticmethod
    def _live_ops(program, ctx):
        """(kept op list, dead count) — O(ops); cheap enough that
        ``should_apply`` runs it on the ORIGINAL program, so the common
        nothing-to-remove case never pays the pipeline's clone.
        Memoized on the ctx per (program identity, version) so the
        should_apply/apply sequence slices each program once."""
        from .executor import _prune_ops
        from .lowering import PSEUDO_OPS

        memo_key = ("dce_live", id(program), program._version)
        hit = ctx._memo.get(memo_key)
        if hit is not None:
            return hit

        block = program.global_block
        roots = set(ctx.fetch_names)
        for op in block.ops:
            for n in op.output_arg_names():
                var = block._find_var_recursive(n)
                if (var is not None and var.persistable) or (
                        ctx.scope is not None and ctx.scope.has_var(n)):
                    roots.add(n)
        if not roots:
            result = (None, 0)
        else:
            keep = _prune_ops(program, sorted(roots),
                              keep_side_effect_ops=True)
            keep_ids = {id(op) for op in keep}
            new_ops = [op for op in block.ops
                       if op.type in PSEUDO_OPS or id(op) in keep_ids]
            result = (new_ops, len(block.ops) - len(new_ops))
        ctx._memo[memo_key] = result
        return result

    def should_apply(self, program, ctx):
        return self._live_ops(program, ctx)[1] > 0

    def apply(self, program, ctx):
        from ..monitor import stat_add

        new_ops, n_removed = self._live_ops(program, ctx)
        if not n_removed:
            return False
        program.global_block.ops[:] = new_ops
        program._bump()
        stat_add("pass_dead_ops_removed", n_removed)
        return True


class PassPipeline:
    """Ordered pass application with copy-on-write semantics.

    ``apply`` runs every pass on a CLONE of the program and returns the
    clone when any pass changed it, else the original object — the
    caller (Executor) caches the result per
    ``(program.fingerprint(), config_key, fetch, feeds, scope)``.
    """

    def __init__(self, passes: Optional[Sequence[Pass]] = None):
        self._passes: Tuple[Pass, ...] = tuple(
            passes if passes is not None
            else (cls() for cls in PASS_REGISTRY.values()))

    @property
    def passes(self) -> Tuple[Pass, ...]:
        return self._passes

    def config_key(self) -> tuple:
        """Joins the Executor's pass-cache key; per-pass knobs that ride
        op attrs (e.g. the fuse bucket cap) are already part of the
        program fingerprint."""
        return tuple(p.name for p in self._passes)

    def apply(self, program, ctx: Optional[PassContext] = None):
        from ..monitor import stat_add
        from ..observe import tracer as otrace

        ctx = ctx or PassContext()
        if not any(p.should_apply(program, ctx) for p in self._passes):
            return program
        work = program.clone()
        changed = False
        for p in self._passes:
            if p.should_apply(work, ctx):
                # one tracer span per pass, nested under the Executor's
                # executor/pass_pipeline span (observe/tracer.py)
                with otrace.span(f"pass/{p.name}"):
                    changed = bool(p.apply(work, ctx)) or changed
        stat_add("pass_pipeline_apply")
        return work if changed else program


_default_pipeline: Optional[PassPipeline] = None


def default_pipeline() -> PassPipeline:
    global _default_pipeline
    if _default_pipeline is None:
        _default_pipeline = PassPipeline()
    return _default_pipeline


def apply_passes(program, fetch_names: Sequence[str] = (),
                 feed_names: Sequence[str] = (), scope=None):
    """One-shot convenience: run the default pipeline over ``program``
    (returns the rewritten clone, or ``program`` itself when nothing
    applied)."""
    return default_pipeline().apply(
        program, PassContext(fetch_names=fetch_names,
                             feed_names=feed_names, scope=scope))
