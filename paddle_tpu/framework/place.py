"""Device identity ("Place") for the TPU-native framework.

Role parity: reference paddle/fluid/platform/place.h (CPUPlace:26,
CUDAPlace:37, XPUPlace:62, variant Place:103).  Here a Place is a small
Python value object that resolves to a concrete ``jax.Device``; there are no
streams or device contexts — XLA/PJRT owns scheduling and memory, which is
the TPU-native replacement for the reference's DeviceContext/allocator
stack (device_context.h:61, memory/allocation/*).
"""
from __future__ import annotations

import functools


class Place:
    """Base device identity."""

    device_id: int = 0

    def __eq__(self, other):
        return type(self) is type(other) and self.device_id == other.device_id

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def __repr__(self):
        return f"{type(self).__name__}({self.device_id})"

    def jax_device(self):
        raise NotImplementedError


class CPUPlace(Place):
    def __init__(self):
        self.device_id = 0

    def jax_device(self):
        import jax

        return jax.devices("cpu")[0]


class TPUPlace(Place):
    """An accelerator chip visible to JAX.

    On a real TPU host this is one chip; in CPU-simulation test runs
    (``--xla_force_host_platform_device_count=N``) it is one virtual device.
    """

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def jax_device(self):
        devs = accelerator_devices()
        if self.device_id >= len(devs):
            raise RuntimeError(
                f"TPUPlace({self.device_id}) out of range: {len(devs)} device(s) visible"
            )
        return devs[self.device_id]


class CUDAPlace(TPUPlace):
    """Compatibility alias: reference scripts that pin CUDAPlace(i) run on
    the accelerator chip i of this framework instead."""


class CUDAPinnedPlace(CPUPlace):
    """Compatibility alias; host memory staging is PJRT's job here."""

    def __init__(self):
        super().__init__()


@functools.lru_cache(maxsize=None)
def accelerator_devices():
    """All non-CPU jax devices, else CPU devices (simulation mode)."""
    import jax

    devs = [d for d in jax.devices() if d.platform != "cpu"]
    return tuple(devs) if devs else tuple(jax.devices())


def is_compiled_with_cuda() -> bool:  # API parity helper
    return False


def is_compiled_with_tpu() -> bool:
    import jax

    return any(d.platform != "cpu" for d in jax.devices())


def _default_place() -> Place:
    import jax

    if any(d.platform != "cpu" for d in jax.devices()):
        return TPUPlace(0)
    return CPUPlace()
