"""Device identity ("Place") for the TPU-native framework.

Role parity: reference paddle/fluid/platform/place.h (CPUPlace:26,
CUDAPlace:37, XPUPlace:62, variant Place:103).  Here a Place is a small
Python value object that resolves to a concrete ``jax.Device``; there are no
streams or device contexts — XLA/PJRT owns scheduling and memory, which is
the TPU-native replacement for the reference's DeviceContext/allocator
stack (device_context.h:61, memory/allocation/*).
"""
from __future__ import annotations

import functools


class Place:
    """Base device identity."""

    device_id: int = 0

    def __eq__(self, other):
        return type(self) is type(other) and self.device_id == other.device_id

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def __repr__(self):
        return f"{type(self).__name__}({self.device_id})"

    def jax_device(self):
        raise NotImplementedError


class CPUPlace(Place):
    def __init__(self):
        self.device_id = 0

    def jax_device(self):
        import jax

        return jax.devices("cpu")[0]


class TPUPlace(Place):
    """An accelerator chip visible to JAX.

    On a real TPU host this is one chip; in CPU-simulation test runs
    (``--xla_force_host_platform_device_count=N``) it is one virtual device.
    """

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def jax_device(self):
        devs = accelerator_devices()
        if self.device_id >= len(devs):
            raise RuntimeError(
                f"TPUPlace({self.device_id}) out of range: {len(devs)} device(s) visible"
            )
        return devs[self.device_id]


class CUDAPlace(TPUPlace):
    """Compatibility alias: reference scripts that pin CUDAPlace(i) run on
    the accelerator chip i of this framework instead."""


class CUDAPinnedPlace(CPUPlace):
    """Compatibility alias; host memory staging is PJRT's job here."""

    def __init__(self):
        super().__init__()


@functools.lru_cache(maxsize=None)
def accelerator_devices():
    """All non-CPU jax devices, else CPU devices (simulation mode)."""
    import jax

    devs = [d for d in jax.devices() if d.platform != "cpu"]
    return tuple(devs) if devs else tuple(jax.devices())


def is_compiled_with_cuda() -> bool:  # API parity helper
    return False


def is_compiled_with_tpu() -> bool:
    import jax

    return any(d.platform != "cpu" for d in jax.devices())


_pinned_place: Place | None = None  # set by set_device


def _default_place() -> Place:
    import jax

    if _pinned_place is not None:
        return _pinned_place
    if any(d.platform != "cpu" for d in jax.devices()):
        return TPUPlace(0)
    return CPUPlace()


def set_device(device: str) -> Place:
    """Pin the process to a device (reference paddle.set_device,
    python/paddle/device.py).

    ``set_device("cpu")`` pins the live jax platform config so ONLY the
    CPU backend initializes — this matters on accelerator hosts where
    initializing the accelerator plugin is expensive or (during an
    outage) hangs: env vars alone are not enough when a site hook
    forces the platform list after jax import.  ``set_device("tpu")``
    (or the "gpu" compat alias) restores accelerator-first selection.
    Already-initialized backends are cleared so the new selection takes
    effect mid-process (existing arrays keep referencing their original
    client and stay readable).  Returns the corresponding Place, which
    also becomes the default place.
    """
    import jax

    global _pinned_place
    d = device.split(":")[0].lower()
    idx = int(device.split(":")[1]) if ":" in device else 0
    if d == "cpu":
        place: Place = CPUPlace()
        want = "cpu"
    elif d in ("tpu", "gpu", "xpu", "npu"):
        place = TPUPlace(idx)
        want = None  # accelerator-first
    else:
        raise ValueError(
            f"unknown device {device!r}; expected cpu/tpu/gpu")
    if jax.config.jax_platforms != want:
        jax.config.update("jax_platforms", want)
        # a config update after backend init is otherwise a silent
        # no-op; clearing rebuilds backends under the new selection.
        # Same-platform calls (incl. index-only changes) skip this —
        # clearing drops every jit cache and re-inits the backend.
        try:
            from jax.extend.backend import clear_backends

            clear_backends()
        except Exception:
            pass
        accelerator_devices.cache_clear()
    _pinned_place = place
    return place


def get_device() -> str:
    """Reference paddle.get_device: 'cpu' or 'tpu:<id>'."""
    p = _default_place()
    return "cpu" if isinstance(p, CPUPlace) else f"tpu:{p.device_id}"
