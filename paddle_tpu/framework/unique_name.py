"""Unique name generation for program variables.

Role parity: reference python/paddle/fluid/unique_name.py (UniqueNameGenerator,
generate, guard, switch).
"""
from __future__ import annotations

import contextlib
from collections import defaultdict


class UniqueNameGenerator:
    def __init__(self):
        self.ids = defaultdict(int)

    def __call__(self, key: str) -> str:
        i = self.ids[key]
        self.ids[key] += 1
        return f"{key}_{i}"


_generator = UniqueNameGenerator()


def generate(key: str) -> str:
    return _generator(key)


def switch(new_generator: UniqueNameGenerator | None = None) -> UniqueNameGenerator:
    global _generator
    old = _generator
    _generator = new_generator if new_generator is not None else UniqueNameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator: UniqueNameGenerator | None = None):
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
