"""Op lowering registry: IR op -> jax/XLA emission.

Role parity: this registry replaces the reference's entire kernel dispatch
machinery — OpRegistry/OpKernelType (op_registry.h:256, op_kernel_type.h)
and OperatorWithKernel::RunImpl's choose/prepare/infershape/launch sequence
(operator.cc:1017-1141).  TPU-native: there is no per-step dispatch at all;
each rule runs **once at trace time**, emitting jax ops into the single XLA
computation the Executor compiles.  Kernel selection by (place, dtype,
layout, library) collapses to "XLA decides".

A rule has signature ``rule(ctx, op) -> None`` and communicates through the
trace environment (``ctx.get``/``ctx.set``).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

LOWERINGS: Dict[str, Callable] = {}

# ops the executor itself handles (data movement endpoints)
PSEUDO_OPS = {"feed", "fetch"}


def register_lower(*op_types: str):
    def deco(fn):
        for t in op_types:
            if t in LOWERINGS:
                raise RuntimeError(f"duplicate lowering for op {t!r}")
            LOWERINGS[t] = fn
        return fn

    return deco


# installed by ops/grad_generic.py: fallback for unregistered *_grad ops
GENERIC_GRAD_LOWERING: Optional[Callable] = None


def apply_tp_constraints(env, op, mesh):
    """Tensor-parallel sharding anchors: apply
    ``lax.with_sharding_constraint`` to the op outputs the
    ShardingPropagationPass stamped (``TP_CONSTRAINT_ATTR`` entries,
    "var\\tspec").  This is how the per-var shardings the pass computed
    reach the jitted computation at trace time — XLA's SPMD partitioner
    then places the mp partial-sum reduces exactly at these anchors
    (Megatron's f/g operators, GSPMD-style).

    Defensive by design: a constraint whose rank no longer matches the
    traced value (a rewritten program) is skipped, never fatal."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from .passes import TP_CONSTRAINT_ATTR, decode_anchor

    from ..monitor import stat_add

    for ent in op.attr(TP_CONSTRAINT_ATTR, []) or []:
        name, spec, _partial = decode_anchor(ent)
        v = env.get(name)
        if v is None or getattr(v, "ndim", None) != len(spec):
            # visible on /metrics: a program rewrite that silently
            # dropped an anchor shows up as a skip count, not as an
            # unexplained mp-collective placement regression
            stat_add("tp_constraint_skipped")
            continue
        env[name] = jax.lax.with_sharding_constraint(
            v, NamedSharding(mesh, PartitionSpec(*spec)))


def get_lowering(op_type: str) -> Callable:
    try:
        return LOWERINGS[op_type]
    except KeyError:
        if op_type.endswith("_grad") and GENERIC_GRAD_LOWERING is not None:
            return GENERIC_GRAD_LOWERING
        raise NotImplementedError(
            f"no TPU lowering registered for op {op_type!r}; "
            f"{len(LOWERINGS)} ops available"
        ) from None


class LoweringContext:
    """Trace-time environment for one block lowering.

    ``env`` maps var name -> traced jax value (SSA: last write wins, which
    reproduces the reference's scope-mutation semantics inside a functional
    program — SURVEY.md §7 'In-place/aliasing').
    """

    def __init__(self, block, env: dict, rng_key=None, mesh=None, axis_env=(),
                 ring_axes=None, fold_axes=()):
        self.block = block
        self.program = block.program
        self.env = env
        self._rng = rng_key
        self.mesh = mesh
        # names of spmd axes currently in scope (inside shard_map)
        self.axis_env = tuple(axis_env)
        # ring_id -> mesh axis name (collective ops; see ops/collective.py)
        self.ring_axes = dict(ring_axes or {})
        # axes whose index is folded into per-shard keys (next_key(
        # per_shard=True)); replica-invariant randomness (param init)
        # must NOT fold or each shard initializes differently — the
        # reference broadcasts params from device 0 for the same reason
        # (multi_devices_graph_pass param broadcast)
        self.fold_axes = tuple(fold_axes)
        self.rng_consumed = False

    def axis_size(self, axis) -> int:
        """Static size of a mesh axis (or product over several)."""
        if self.mesh is None:
            return 1
        if isinstance(axis, (tuple, list)):
            n = 1
            for a in axis:
                n *= int(self.mesh.shape[a])
            return n
        return int(self.mesh.shape[axis])

    # -- values -----------------------------------------------------------
    def get(self, name: str):
        if name not in self.env:
            raise KeyError(
                f"op input {name!r} is not defined at this point in the program "
                "(not a feed, not in scope, not produced by an earlier op)"
            )
        return self.env[name]

    def get_opt(self, name: Optional[str]):
        if not name:
            return None
        return self.env.get(name)

    def set(self, name: str, value):
        self.env[name] = value

    # -- op slot helpers ---------------------------------------------------
    def in1(self, op, slot: str):
        names = op.inputs.get(slot, [])
        return self.get(names[0]) if names else None

    def in_list(self, op, slot: str) -> List:
        return [self.get(n) for n in op.inputs.get(slot, [])]

    def out_name(self, op, slot: str) -> Optional[str]:
        names = op.outputs.get(slot, [])
        return names[0] if names else None

    def set_out(self, op, slot: str, value):
        name = self.out_name(op, slot)
        if name is not None:
            self.env[name] = value

    def var_dtype(self, name: str):
        from . import dtypes

        v = self.block._find_var_recursive(name)
        return dtypes.to_jnp(v.dtype if v is not None else "float32")

    # -- randomness --------------------------------------------------------
    def next_key(self, per_shard=False):
        """Draw the next program key.  ``per_shard=True`` additionally
        folds in the dp shard index (dropout masks must differ per data
        shard); the default key is replica-invariant so param init and
        other P()-state randomness stay identical across shards."""
        import jax

        if self._rng is None:
            raise RuntimeError("program uses random ops but no RNG key was threaded")
        self.rng_consumed = True
        self._rng, k = jax.random.split(self._rng)
        if per_shard:
            k = self.fold_shard(k)
        return k

    def fold_shard(self, key):
        """Fold the shard index of every fold axis into ``key``."""
        import jax
        from jax import lax

        for ax in self.fold_axes:
            key = jax.random.fold_in(key, lax.axis_index(ax))
        return key

    @property
    def rng_key(self):
        return self._rng

    def lower_op(self, op):
        get_lowering(op.type)(self, op)

    def lower_block(self, block):
        old = self.block
        self.block = block
        try:
            for op in block.ops:
                if op.type in PSEUDO_OPS:
                    continue
                self.lower_op(op)
        finally:
            self.block = old
