"""Tier-1 config: the FLAGS_* registry (reference platform/flags.cc +
global_value_getter_setter.cc, python paddle.set_flags/get_flags).

Flags initialize from FLAGS_<name> environment variables (reference gflags
env behavior) and are mutable at runtime via set_flags.  SURVEY §5 keeps
the reference's 3-tier config shape: this module is tier 1; BuildStrategy/
ExecutionStrategy are tier 2; DistributedStrategy proto is tier 3.
"""
from __future__ import annotations

import os
from typing import Dict

_TRUTHY = {"1", "true", "True", "TRUE", "yes", "on"}


def _parse(raw: str, default):
    if isinstance(default, bool):
        return raw in _TRUTHY
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


class _Flag:
    __slots__ = ("name", "value", "default", "help")

    def __init__(self, name, default, help_=""):
        self.name = name
        self.default = default
        self.help = help_
        raw = os.environ.get("FLAGS_" + name)
        self.value = _parse(raw, default) if raw is not None else default


_REGISTRY: Dict[str, _Flag] = {}


_LOWERING_FLAGS: set = set()  # flags read at trace time (key compiles)


def lowering_key() -> tuple:
    """State of every flag that affects op lowering — folded into the
    Executor compile-cache key so flipping any of them re-lowers
    instead of silently reusing a stale compiled program."""
    return tuple(sorted(
        (n, _REGISTRY[n].value) for n in _LOWERING_FLAGS))


def define_flag(name: str, default, help_: str = "",
                affects_lowering: bool = False):
    if name in _REGISTRY:
        raise KeyError(f"flag {name!r} already defined")
    _REGISTRY[name] = _Flag(name, default, help_)
    if affects_lowering:
        _LOWERING_FLAGS.add(name)


def get_flags(flags):
    """paddle.get_flags parity: str or list -> {name: value}."""
    names = [flags] if isinstance(flags, str) else list(flags)
    out = {}
    for n in names:
        key = n[6:] if n.startswith("FLAGS_") else n
        if key not in _REGISTRY:
            raise KeyError(f"unknown flag {n!r}")
        out[n] = _REGISTRY[key].value
    return out


def set_flags(flags: Dict):
    """paddle.set_flags parity: {FLAGS_name or name: value}."""
    for n, v in flags.items():
        key = n[6:] if n.startswith("FLAGS_") else n
        if key not in _REGISTRY:
            raise KeyError(f"unknown flag {n!r}")
        f = _REGISTRY[key]
        f.value = _parse(v, f.default) if isinstance(v, str) else type(f.default)(v)


def flag(name: str):
    """Internal fast accessor."""
    return _REGISTRY[name].value


def flags_snapshot() -> Dict:
    """Current value of EVERY registered flag (flight-recorder run
    metadata + postmortem bundles: the config a failure ran under is
    half the diagnosis)."""
    return {n: f.value for n, f in sorted(_REGISTRY.items())}


# ---- the registry (reference platform/flags.cc equivalents that are
# meaningful under XLA; memory/GC/cudnn knobs are N/A by design) ----------
define_flag("check_nan_inf", False,
            "scan every op output for NaN/Inf after each executor run "
            "(reference operator.cc:1129 + nan_inf_utils_detail)")
define_flag("benchmark", False, "sync + time each executor call")
define_flag("paddle_num_threads", 1, "host-side intra-op threads (XLA-owned)")
define_flag("use_tpu", True, "prefer the TPU backend when available")
define_flag("eager_delete_tensor_gb", 0.0, "N/A under XLA (kept for parity)")
define_flag("allocator_strategy", "xla", "memory is PJRT/XLA-owned")
define_flag("cpu_deterministic", False,
            "force deterministic reductions on CPU runs")
define_flag("seed", 0, "global random seed override (0 = program seed)")
define_flag("flash_attention", "auto",
            "fused attention kernel engagement: 'auto' (flash only when "
            "the score tensor would threaten HBM), 'always', 'never'. "
            "Also gates the FlashAttentionPass graph rewrite of unfused "
            "matmul/softmax chains ('never' = no rewrite, bitwise "
            "restore; 'auto' rewrites on TPU backends only)",
            affects_lowering=True)
define_flag("fuse_passes", True,
            "enable the graph-pass pipeline (framework/passes.py): fused "
            "bucketed gradient allreduce, redundant-cast elimination, "
            "dead-op elimination — applied before lowering; "
            "affects_lowering so flipping it re-keys the compile cache",
            affects_lowering=True)
define_flag("enable_tracer", False,
            "record host-side spans (executor phases, per-pass, "
            "per-collective, serving batch lifecycle) into the in-process "
            "ring buffer (paddle_tpu.observe); export any time with "
            "observe.export_chrome_trace() — independent of jax.profiler "
            "captures (reference FLAGS_enable_rpc_profiler / DeviceTracer "
            "role, CUPTI replaced by a pure-host ring buffer)")
define_flag("ckpt_async_save", True,
            "CheckpointManager default (paddle_tpu.ckpt): hand "
            "serialization + shard writes to the background writer "
            "thread so save() blocks only for the device->host snapshot")
define_flag("ckpt_keep_n", 5,
            "checkpoint retention default: keep the N newest committed "
            "steps (0 = keep everything); keep_every_n_steps multiples "
            "survive GC regardless")
define_flag("ckpt_fsync", True,
            "fsync shard/manifest files and directories at commit — the "
            "atomicity guarantee against power loss; disable only for "
            "tests/benchmarks on throwaway dirs")
define_flag("ckpt_verify_restore", True,
            "verify the SHA-256 of every shard against the manifest "
            "before restoring (off: existence+size checks only)")
define_flag("device_peak_tflops", 275.0,
            "per-chip peak TFLOP/s used by the MFU estimate "
            "(observe/step_stats.py); default is TPU v4/v5e-class bf16 "
            "peak — set to your part's number for honest utilization")
define_flag("max_inflight_steps", 2,
            "pipelined step dispatch (framework/executor.py): Executor."
            "run returns a lazy StepHandle and up to this many steps may "
            "be in flight on the device before dispatch backpressures "
            "(drains the oldest step).  0 = legacy synchronous fetch "
            "(every run blocks on device->host transfer of its fetch "
            "list).  NaN-scan, FLAGS_benchmark sync, and StepTimer "
            "accounting all happen at window-drain points; "
            "FLAGS_benchmark / FLAGS_check_nan_inf force an immediate "
            "drain per step so their semantics stay per-call")
define_flag("flight_recorder", True,
            "record structured lifecycle events (run metadata, executor "
            "dispatch/drain, ckpt save/restore, serving start/stop) into "
            "the bounded in-process flight-recorder ring "
            "(paddle_tpu.observe.flight); ~µs per event, read back by "
            "postmortem bundles and observe.flight.tail()")
define_flag("flight_recorder_file", "",
            "optional always-on JSONL sink for flight-recorder events: "
            "every event is appended + flushed to this path, so a "
            "process that dies without running any handler still leaves "
            "its event tail on disk; empty = ring buffer only")
define_flag("stall_timeout_s", 0.0,
            "stall watchdog (paddle_tpu.observe.health): when > 0, a "
            "daemon thread samples executor progress (steps dispatched "
            "vs drained, in-flight window age) and dumps a postmortem "
            "bundle (all-thread stacks, Chrome trace, metrics snapshot, "
            "flight-recorder tail, flags) after this many seconds of "
            "no-progress with work pending; 0 = disabled")
define_flag("postmortem_dir", "postmortem",
            "directory postmortem bundles are written under (stall "
            "watchdog, crash hook, bench failure records); each dump is "
            "its own bundle_<ts>_<pid>_<reason> subdirectory — read one "
            "with: python -m tools.postmortem <dir>")
define_flag("heartbeat_interval_s", 10.0,
            "cluster health telemetry (observe/health.py): period of "
            "each rank's HealthReporter heartbeat PUT to the fleet KV "
            "HTTP server; a rank is reported dead on /metrics/cluster "
            "after 3 missed intervals")
define_flag("xla_introspect", True,
            "XLA compile introspection (paddle_tpu.observe.xla_stats): "
            "every Executor compile is AOT-lowered so its wall time "
            "(compile_seconds histogram), executable size, and per-chip "
            "HBM footprint (compiled.memory_analysis) are recorded "
            "BEFORE the first dispatch — the footprint feeds the "
            "FLAGS_hbm_budget_fraction gate.  Capability-guarded: a jax "
            "without AOT stages falls back to the lazy first-call "
            "compile with the telemetry skipped")
define_flag("hbm_budget_fraction", 0.0,
            "pre-dispatch memory budget gate: when > 0, a program whose "
            "predicted per-chip HBM footprint (from "
            "compiled.memory_analysis after lowering) exceeds this "
            "fraction of the device's memory is rejected with a "
            "MemoryBudgetError naming the largest vars and their "
            "sharding specs — a readable report instead of an opaque "
            "RESOURCE_EXHAUSTED mid-step.  0 = gate disabled")
define_flag("hbm_bytes_per_device", 0,
            "explicit per-device HBM capacity in bytes for the budget "
            "gate; 0 = probe device.memory_stats()['bytes_limit'] "
            "(unavailable on the CPU backend, where the gate then "
            "capability-skips unless this override is set)")
define_flag("hlo_dump_dir", "",
            "save each compile's optimized HLO module text under this "
            "directory (hlo_<fingerprint>_<n>.txt) beside the "
            "postmortem bundles; empty = disabled")
define_flag("layer_scan", False,
            "scan-over-layers compile-time optimization (framework/"
            "passes.py LayerScanPass): detect maximal runs of isomorphic "
            "repeated op segments (the forward/backward/optimizer "
            "regions a repeated-layer model builder emits), stack their "
            "per-layer weights on a leading num_layers axis, and lower "
            "each run to ONE jax.lax.scan — trace+compile time and "
            "executable size become ~constant in depth instead of "
            "linear, with bitwise-identical step numerics.  Also "
            "enabled per-program by DistributedStrategy."
            "recompute_configs={'scan_layers': N}; non-matching "
            "programs are left untouched (pass_layer_scan_skipped "
            "counters name why)",
            affects_lowering=True)
define_flag("layer_scan_min_layers", 4,
            "minimum isomorphic segment repeat count before "
            "LayerScanPass rewrites a run (shorter runs gain nothing "
            "and shallow nets keep their unrolled executables); "
            "recompute_configs={'scan_layers': N} overrides per program",
            affects_lowering=True)
define_flag("layer_scan_policy", "",
            "XLA rematerialization policy wrapped around the layer_scan "
            "body via jax.checkpoint: '' (no wrap), 'nothing_saveable', "
            "'dots_saveable', or 'save_anything' (= jax "
            "everything_saveable) — extends the program-level "
            "recompute_barrier support to XLA remat choices per scanned "
            "block.  A jax without checkpoint_policies degrades to "
            "plain jax.checkpoint (counter remat_policy_unavailable)",
            affects_lowering=True)
define_flag("layer_scan_unroll", 1,
            "lax.scan unroll= factor for layer_scan regions (>1 trades "
            "compile time back for per-step dispatch overhead on very "
            "cheap bodies); dropped silently on a jax whose lax.scan "
            "lacks the knob",
            affects_lowering=True)
define_flag("compile_cache_dir", "",
            "persistent XLA compilation cache directory (sets jax's "
            "jax_compilation_cache_dir through framework/jax_compat.py "
            "when the installed jax has the knob): restarted jobs reuse "
            "compiled executables instead of re-tracing + re-compiling; "
            "empty = disabled.  Applied when an Executor is constructed; "
            "counted once as executor_compile_cache_dir_set")
define_flag("decode_slots", 8,
            "decode engine (paddle_tpu.serving.decode): fixed slot-batch "
            "capacity of one DecodeEngine replica — the number of "
            "requests decoding JOINTLY in each compiled step; new "
            "requests claim free slots at step boundaries (continuous "
            "batching), finished/expired slots free immediately")
define_flag("decode_max_seq_len", 256,
            "decode engine: per-slot sequence capacity (prompt + "
            "generated), and the width of the paged KV cache's per-slot "
            "page table; must be a multiple of FLAGS_decode_page_size")
define_flag("decode_page_size", 16,
            "decode engine: positions per KV-cache page "
            "(serving/kv_cache.py) — pages are the allocation grain, "
            "reserved at admission and freed the moment a request "
            "finishes; also the per-grid-step DMA size of the Pallas "
            "paged decode-attention kernel")
define_flag("decode_max_new_tokens", 64,
            "decode engine: default generation budget when a request "
            "does not pass max_new_tokens; admission reserves cache "
            "pages for prompt + this many positions")
define_flag("decode_prefix_cache", True,
            "decode engine: share KV-cache pages across requests whose "
            "prompts open with the same token prefix "
            "(serving/kv_cache.py PrefixIndex) — admission skips both "
            "the HBM reservation AND the prefill compute for hit "
            "pages, with refcounts + copy-on-write at the first "
            "divergent token; finished requests register their pages "
            "for future hits (evicted LRU under pool pressure)")
define_flag("decode_prefill_chunk_pages", 0,
            "decode engine: chunked prefill — a prompt longer than "
            "this many cache pages fills them across SEVERAL step "
            "boundaries instead of stalling the whole slot batch on "
            "one long prefill dispatch (protects ttft_ms_p99 for the "
            "slots already decoding); 0 = off (one prefill dispatch "
            "per request)")
define_flag("decode_ragged_prefill", 0,
            "decode engine: ragged prefill packing — pack up to this "
            "many requests' chunk tails into ONE multi-row chunk "
            "dispatch (per-row (page, offset) coords make rows "
            "independent), instead of padding each prompt to its "
            "power-of-two bucket; needs decode_prefill_chunk_pages > 0; "
            "0 = off (per-request padded dispatches)")
define_flag("request_trace_sample", 1.0,
            "per-request tracing (paddle_tpu.observe.request_trace): "
            "head-sampling fraction of NORMAL completions whose full "
            "timeline is retained in the bounded finished-trace ring "
            "(deterministic exact rate).  Recording itself is always on "
            "and ~free (one monotonic read + a tuple append per "
            "lifecycle event); tail retention keeps every SLO violator "
            "and abnormal ending (deadline/abandoned/rejected/error) "
            "REGARDLESS of this flag — 0 retains only the traces you'd "
            "page on")
define_flag("request_trace_ring", 512,
            "capacity of the retained finished-trace ring "
            "(request_trace.TraceStore); oldest retained traces fall "
            "off — in-flight timelines are unaffected")
define_flag("slo_ttft_p99_ms", 0.0,
            "SLO objective (paddle_tpu.observe.slo): time-to-first-"
            "token p99 target in ms — a request whose ttft exceeds it "
            "(or that dies before first token) burns the 1% error "
            "budget; 0 = objective disabled.  Burn-rate/budget gauges "
            "ride /metrics as slo_burn_rate_ttft_p99_ppm / "
            "slo_budget_remaining_ttft_p99_ppm")
define_flag("slo_tpot_p50_ms", 0.0,
            "SLO objective: per-request MEAN time-per-output-token p50 "
            "target in ms (budget 50%); 0 = disabled")
define_flag("slo_error_rate_ppm", 10000,
            "SLO objective: allowed fraction of requests ending in any "
            "outcome other than 'completed', in parts-per-million "
            "(default 10000 = 1%); 0 = disabled.  Always-on by default "
            "so decode_goodput_rps and the burn gauges exist out of "
            "the box")
define_flag("slo_windows_s", "60,300",
            "comma-separated rolling window lengths (seconds) for the "
            "multi-window burn-rate evaluation (SRE-workbook style: "
            "short window catches fast burn, long window slow bleed); "
            "goodput is measured over the shortest window")
define_flag("weight_quant", "",
            "post-training weight-only quantization "
            "(slim/quantization.py PostTrainingWeightQuantPass): rewrite "
            "matmul-family weights to a compact carrier + per-output-"
            "channel scales lowered through the dequant-fused "
            "ops/quant_ops.dequant_matmul kernel.  '' = off; 'int8' = "
            "symmetric int8; 'fp8_e4m3' = float8 e4m3 where the "
            "installed jax has the dtype (probed via jax_compat, falls "
            "back to int8 with quant_fp8_unavailable counted).  "
            "Per-program override: slim.quantization.mark_weight_quant",
            affects_lowering=True)
define_flag("elastic_max_restarts", 3,
            "elastic training supervisor (distributed/fleet/elastic): "
            "restart budget — how many times ElasticSupervisor.run may "
            "restart (in place) or re-shard (after a dead rank) "
            "following a classified failure before raising a terminal "
            "ElasticTerminated with the full restart history; bench.py "
            "flagship rounds share the same budget for device-failure "
            "retries")
define_flag("elastic_preflight_timeout_s", 240.0,
            "deadline for ONE subprocess-isolated device preflight "
            "probe (fleet.elastic.preflight_device: import jax + a "
            "tiny jit dispatch in a CHILD process, so a wedged backend "
            "can never hang the supervisor itself); this is the BENCH "
            "r04/r05 'device init did not complete within 240s' bound, "
            "now a structured init_timeout verdict retried with "
            "backoff instead of a zeroed round")
define_flag("elastic_backoff_s", 10.0,
            "base backoff between elastic restart/preflight attempts; "
            "attempt k sleeps backoff * 2^(k-1) — exponential, so a "
            "transiently-held chip (an orphaned worker still being "
            "reaped) gets time to come back without burning the "
            "restart budget in seconds")
define_flag("decode_kv_quant", False,
            "decode engine: store KV-cache pages int8 with a parallel "
            "per-page scale pool (serving/kv_cache.py) — scales are "
            "per position-in-page per head, written by the SAME step "
            "that writes the page bytes, so stored content is "
            "write-once and order-independent (speculative decode "
            "stays bitwise-equal to its own non-speculative quantized "
            "run).  Roughly halves bytes per page vs bf16, so a fixed "
            "pool byte budget holds ~2x the pages -> ~2x decode slots; "
            "attention dequantizes pages inline in both the reference "
            "and Pallas paths")
define_flag("pp_degree", 0,
            "default pipeline-parallel degree for shapeless mesh "
            "building: parallel_env.init_parallel_env() called with "
            "NEITHER mesh_shape NOR axis_names factors the visible "
            "devices into a (dp, pp) named mesh with this many "
            "pipeline stages (0 = no pipeline axis; a non-divisor "
            "device count is rejected loudly).  The stage COUNT a "
            "program runs with is always the mesh's 'pp' axis size — "
            "this flag only sizes meshes built without an explicit "
            "shape, and an explicit axis_names argument wins over it; "
            "3-axis (dp, mp, pp) meshes are built with an explicit "
            "mesh_shape")
define_flag("overlap_grad_allreduce", True,
            "stretch FuseAllReducePass buckets across the layer-scan "
            "boundary (framework/passes.py): a bucket holding a stacked "
            "grad-carrier allreduce (the LayerScanPass pulled-out "
            "collective carrying num_layers x per-layer bytes) closes "
            "at its producing backward segment instead of being dragged "
            "to the last collective of the whole backward — the bulk "
            "grad payload dispatches as soon as the backward scan "
            "finishes and overlaps the remaining (unrolled edge-layer) "
            "backward compute.  Off = one greedy bucket stream anchored "
            "at its last member (the pre-overlap sequential schedule, "
            "the bench A/B baseline)",
            affects_lowering=True)
define_flag("collective_matmul_chunks", 0,
            "latency-hiding collective matmul (ops/collective_matmul."
            "py): decompose each tensor-parallel ROW-PARALLEL matmul + "
            "mp partial-sum reduce (the ops ShardingPropagationPass "
            "anchored as contracted) into this many output-row chunks — "
            "chunk k's reduce overlaps chunk k+1's matmul on hardware "
            "with async collectives (Wang et al., ASPLOS 2023).  "
            "Applies to the GSPMD tensor-parallel path AND the manual "
            "pipeline×mp path; a shape not divisible by the chunk count "
            "(x its sharded mesh axes) falls back to the unchunked "
            "lowering, counted collective_matmul_fallback.  0/1 = off; "
            "pure-jnp semantics, so CPU tier-1 runs stay exact",
            affects_lowering=True)
define_flag("ep_degree", 0,
            "default expert-parallel degree for shapeless mesh "
            "building: parallel_env.init_parallel_env() called with "
            "NEITHER mesh_shape NOR axis_names factors the visible "
            "devices into a (dp, ep) named mesh — or (dp, ep, pp) when "
            "FLAGS_pp_degree also asks for stages — with this many "
            "expert shards (0 = no ep axis; a non-divisor device "
            "count, or an ep x pp product exceeding the visible "
            "devices, is rejected loudly at carve time with the axis "
            "named).  The expert-parallel degree a program runs with "
            "is always the mesh's 'ep' axis size — this flag only "
            "sizes meshes built without an explicit shape, and an "
            "explicit axis_names argument wins over it")
define_flag("moe_alltoall_chunks", 0,
            "latency-hiding MoE all-to-all (ops/moe_ops.py): slice the "
            "expert-parallel dispatch/combine all-to-all and the "
            "expert FFN einsums into this many CAPACITY-axis chunks — "
            "chunk k's all-to-all overlaps chunk k+1's expert compute "
            "(the collective-matmul chunking idiom generalized to "
            "all-to-all).  Chunk outputs are CONCATENATED and combined "
            "once, so chunked and sequential schedules stay bitwise-"
            "identical; a capacity not divisible by the chunk count "
            "falls back to the unchunked lowering, counted "
            "moe_alltoall_fallback.  0/1 = off; pure-jnp semantics, "
            "so CPU tier-1 runs stay exact",
            affects_lowering=True)
define_flag("decode_spec_k", 0,
            "decode engine: speculative decoding window — a draft "
            "model (DecodeEngine(draft_model=, draft_weights=)) "
            "proposes this many tokens per round and the target model "
            "verifies them in ONE batched step; greedy output stays "
            "bitwise-identical to non-speculative decode (rejected "
            "proposals fall back to the target's own token); 0 = off, "
            "ignored unless a draft model is configured")
define_flag("phase_attribution", True,
            "step-phase attribution (paddle_tpu.observe.phases): "
            "decompose each drained step's wall time into compute / "
            "exposed-collective / host-blocked / input-wait buckets "
            "(phase_*_seconds_micro gauges + the per-collective "
            "exposed-vs-hidden ledger on /stats and /metrics).  Pure "
            "observer: never affects lowering or numerics — the "
            "measured split comes from timestamps the drain path "
            "already takes, the predicted split from the compile-time "
            "cost model (deterministic on CPU/tier-1)")
define_flag("phase_interconnect_gbps", 100.0,
            "assumed per-chip interconnect bandwidth (GB/s) for the "
            "phase-attribution cost model's predicted collective "
            "times (observe/phases.py) — TPU v4/v5e ICI-class default; "
            "set to your fabric's number for honest predicted "
            "comm fractions.  Prediction only: measured phases and "
            "step numerics never read it")
define_flag("prof_trigger_ratio", 0.0,
            "anomaly-triggered profiling (observe/profiler_capture): "
            "when a drained step's wall time exceeds this ratio x the "
            "rolling step-time baseline (or an slo_burn_rate_* gauge "
            "trips past its budget), capture ONE bounded jax.profiler "
            "trace window + phase snapshot into a postmortem bundle "
            "(phases.json section), then latch until the step time "
            "drops back under the threshold; 0 = disabled")
define_flag("prof_cooldown_s", 60.0,
            "minimum seconds between two anomaly-triggered captures "
            "(observe/profiler_capture): after one bundle is written "
            "the trigger stays quiet for this long even if the episode "
            "re-trips — a sustained regression produces one bundle per "
            "cooldown window, not one per step; the capture itself "
            "perturbs step times, so this also keeps the observer from "
            "triggering on its own overhead")
define_flag("prof_capture_s", 2.0,
            "bound (seconds) of one anomaly/continuous profiler "
            "capture window — the trace is stopped after this long no "
            "matter what, so a capture can never become the overhead "
            "it is meant to explain")
define_flag("prof_continuous_s", 0.0,
            "continuous low-duty-cycle profiling: every this many "
            "seconds, capture one FLAGS_prof_capture_s trace window "
            "(duty cycle = capture_s / continuous_s) — the always-on "
            "fleet profiling mode; 0 = disabled.  Captures are "
            "capability-skipped (prof_trace_unavailable counted) on "
            "backends without jax.profiler trace support")
define_flag("flight_recorder_max_mb", 0.0,
            "size-based rotation for the FLAGS_flight_recorder_file "
            "JSONL sink: when the active segment exceeds this many MB "
            "it is rotated to <path>.1 (one previous segment kept, so "
            "the post-crash tail always spans >= this much history); "
            "0 = unbounded (the pre-rotation behavior)")
define_flag("disagg_prefill_replicas", 1,
            "disaggregated serving (paddle_tpu.serving.disagg): "
            "replicas in the PREFILL set of a DisaggServer — they run "
            "only (chunked) prefill + first-token sampling, then hand "
            "the request's KV pages off to a decode replica; the "
            "DistServe/Mooncake split that stops long prefills from "
            "stealing decode step time")
define_flag("disagg_decode_replicas", 1,
            "disaggregated serving: replicas in the DECODE set — they "
            "admit requests by INSTALLING migrated KV pages (no "
            "prefill compute) and emit from the first decode step; "
            "tokens stay bitwise-equal to a local prefill because the "
            "migrated admission reuses the full-prefix-hit contract "
            "(lengths start at prompt-1, same fold_in(key, 0) "
            "sampling)")
define_flag("disagg_migrate_host_bounce", False,
            "disaggregated serving: force KV-page migration through "
            "host memory (np.asarray out / device_put in) even when "
            "prefill and decode replicas share a process/backend — "
            "the cross-host transport path, also the A/B knob for "
            "measuring migration overhead; off = device-to-device "
            "pool-slice copy when possible")
define_flag("disagg_handoff_timeout_s", 120.0,
            "disaggregated serving: how long the router waits for a "
            "prefill replica to finish one request's prefill leg "
            "before treating the replica as failed and re-dispatching "
            "the request (counted disagg_redispatches_total)")
define_flag("disagg_redispatch_retries", 2,
            "disaggregated serving: how many times the router "
            "re-dispatches one request after a prefill-replica "
            "failure (death, timeout, lost payload) before failing "
            "the request to the client; each retry picks a surviving "
            "replica, so a killed replica drops zero requests while "
            "any prefill capacity remains")
define_flag("disagg_autoscale_interval_s", 1.0,
            "disagg autoscaler: seconds between policy ticks of the "
            "background Autoscaler thread (Autoscaler.serve_forever); "
            "each tick reads SLO burn + queue depths and may re-role "
            "at most one replica")
define_flag("disagg_autoscale_cooldown_s", 30.0,
            "disagg autoscaler: minimum seconds between two re-roles "
            "— the anti-flap floor; a trigger firing inside the "
            "window is counted (autoscale_cooldown_skips_total) and "
            "dropped, never queued")
define_flag("disagg_autoscale_burn_high", 1.0,
            "disagg autoscaler: ttft-objective SLO burn rate at/above "
            "which a decode replica is re-roled into the prefill set "
            "(prefill capacity is what ttft burn starves); paired "
            "with disagg_autoscale_burn_low as hysteresis so the two "
            "thresholds can never chase each other")
define_flag("disagg_autoscale_burn_low", 0.25,
            "disagg autoscaler: ttft burn rate at/below which the "
            "prefill side is considered healthy enough to GIVE UP a "
            "replica to the decode set (only then does decode queue "
            "pressure trigger a prefill->decode re-role) — the lower "
            "half of the hysteresis band")
define_flag("disagg_autoscale_queue_high", 4,
            "disagg autoscaler: mean decode-replica queue depth "
            "at/above which (with prefill burn under burn_low) a "
            "prefill replica is re-roled into the decode set")
