from . import ir_pb2  # noqa: F401
from .dtypes import to_enum, to_jnp, to_np, to_str  # noqa: F401
from .executor import Executor, StepHandle  # noqa: F401
from .place import (  # noqa: F401
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    Place,
    TPUPlace,
    get_device,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    set_device,
)
from .program import (  # noqa: F401
    Block,
    Operator,
    Parameter,
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    grad_var_name,
    program_guard,
)
from .scope import Scope, global_scope  # noqa: F401
