"""Program / Block / Variable / Operator — the graph IR builders.

Role parity: reference python/paddle/fluid/framework.py (Program/Block/
Variable/Operator/Parameter, program_guard, default_main_program) and the
C++ desc wrappers (program_desc.h, block_desc.h, op_desc.h, var_desc.h).

Design (TPU-native): the IR is *the contract*, not the execution engine.
Blocks are never interpreted op-by-op; the Executor lowers a whole block to
a single jitted XLA computation (see executor.py).  Hence Variables carry
no storage — runtime values live in a Scope of jax arrays keyed by name.
Serialization is the proto in paddle_tpu/proto/ir.proto.
"""
from __future__ import annotations

import contextlib
import hashlib
import traceback
from typing import Dict, List, Optional, Sequence

import numpy as np

from . import dtypes, ir_pb2, unique_name

# ---------------------------------------------------------------------------
# Attribute helpers
# ---------------------------------------------------------------------------


def _attr_to_proto(value) -> ir_pb2.Attr:
    a = ir_pb2.Attr()
    if isinstance(value, bool):
        a.b = value
    elif isinstance(value, (int, np.integer)):
        a.i = int(value)
    elif isinstance(value, (float, np.floating)):
        a.f = float(value)
    elif isinstance(value, str):
        a.s = value
    elif isinstance(value, Block):
        a.block = value.idx
    elif isinstance(value, (list, tuple, np.ndarray)):
        vals = list(value)
        if len(vals) and isinstance(vals[0], Block):
            a.blocks.v.extend([b.idx for b in vals])
        elif len(vals) and isinstance(vals[0], bool):
            a.bools.v.extend([bool(v) for v in vals])
        elif all(isinstance(v, (int, np.integer)) for v in vals):
            a.ints.v.extend([int(v) for v in vals])
        elif all(isinstance(v, (int, float, np.integer, np.floating)) for v in vals):
            a.floats.v.extend([float(v) for v in vals])
        elif all(isinstance(v, str) for v in vals):
            a.strings.v.extend(vals)
        else:
            raise TypeError(f"unsupported list attribute {value!r}")
    else:
        raise TypeError(f"unsupported attribute type {type(value)}: {value!r}")
    return a


def _attr_from_proto(a: ir_pb2.Attr):
    kind = a.WhichOneof("value")
    if kind is None:
        return None
    v = getattr(a, kind)
    if kind in ("ints", "floats", "strings", "bools", "blocks"):
        return list(v.v)
    return v


# ---------------------------------------------------------------------------
# Variable
# ---------------------------------------------------------------------------


class Variable:
    """A named slot in a Block.  Holds metadata only (shape may contain -1)."""

    def __init__(
        self,
        block: "Block",
        name: str,
        shape: Sequence[int] | None = None,
        dtype="float32",
        persistable: bool = False,
        stop_gradient: bool = False,
        kind: int = ir_pb2.VK_DENSE,
        is_parameter: bool = False,
    ):
        self.block = block
        self.name = name
        self.shape = tuple(int(s) for s in shape) if shape is not None else ()
        self.dtype = dtypes.to_enum(dtype)
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.kind = kind
        self.is_parameter = is_parameter
        # populated by initializers / optimizer plumbing
        self.initializer = None
        self.regularizer = None
        self.optimize_attr = {"learning_rate": 1.0}
        self.trainable = not stop_gradient

    # -- api parity -------------------------------------------------------
    @property
    def dtype_str(self) -> str:
        return dtypes.to_str(self.dtype)

    @property
    def lod_level(self) -> int:
        return 0  # ragged tensors are pad+mask in this framework

    def numel(self) -> int:
        n = 1
        for s in self.shape:
            n *= max(s, 0)
        return n

    def __repr__(self):
        return (
            f"Variable(name={self.name!r}, shape={list(self.shape)}, "
            f"dtype={self.dtype_str}, persistable={self.persistable})"
        )

    # -- serialization ----------------------------------------------------
    def to_proto(self) -> ir_pb2.VarDef:
        p = ir_pb2.VarDef(
            name=self.name,
            kind=self.kind,
            dtype=self.dtype,
            persistable=self.persistable,
            stop_gradient=self.stop_gradient,
            is_parameter=self.is_parameter,
        )
        p.shape.extend(self.shape)
        return p

    @staticmethod
    def from_proto(block: "Block", p: ir_pb2.VarDef) -> "Variable":
        return Variable(
            block,
            p.name,
            shape=list(p.shape),
            dtype=p.dtype if p.dtype != ir_pb2.DT_UNDEFINED else "float32",
            persistable=p.persistable,
            stop_gradient=p.stop_gradient,
            kind=p.kind,
            is_parameter=p.is_parameter,
        )


class Parameter(Variable):
    """A trainable persistable variable (reference framework.py Parameter)."""

    def __init__(self, block, name, shape, dtype="float32", trainable=True, **kw):
        super().__init__(
            block,
            name,
            shape=shape,
            dtype=dtype,
            persistable=True,
            stop_gradient=not trainable,
            is_parameter=True,
        )
        self.trainable = trainable
        for k, v in kw.items():
            setattr(self, k, v)


# ---------------------------------------------------------------------------
# Operator
# ---------------------------------------------------------------------------


# device_guard annotation stack (reference fluid.device_guard,
# framework.py device_guard — ops created inside get attr op_device; the
# pipeline optimizer maps "stage:N" annotations to pipeline stages)
_device_guard_stack: List[str] = []


def device_guard(device: str):
    import contextlib

    @contextlib.contextmanager
    def guard():
        _device_guard_stack.append(device)
        try:
            yield
        finally:
            _device_guard_stack.pop()

    return guard()


class Operator:
    """One op in a block: type + slot->names inputs/outputs + attrs."""

    def __init__(
        self,
        block: "Block",
        type: str,
        inputs: Optional[Dict[str, object]] = None,
        outputs: Optional[Dict[str, object]] = None,
        attrs: Optional[Dict[str, object]] = None,
    ):
        self.block = block
        self.type = type
        self.inputs: Dict[str, List[str]] = _normalize_slots(inputs)
        self.outputs: Dict[str, List[str]] = _normalize_slots(outputs)
        self.attrs: Dict[str, object] = dict(attrs or {})
        # Blocks in attrs are stored by index for serialization friendliness.
        for k, v in list(self.attrs.items()):
            if isinstance(v, Block):
                self.attrs[k] = v.idx
        if _device_guard_stack and "op_device" not in self.attrs:
            self.attrs["op_device"] = _device_guard_stack[-1]
        self.callstack: List[str] = _capture_callstack()

    # -- access -----------------------------------------------------------
    def input(self, slot: str) -> List[str]:
        return list(self.inputs.get(slot, []))

    def output(self, slot: str) -> List[str]:
        return list(self.outputs.get(slot, []))

    def input_arg_names(self) -> List[str]:
        return [n for ns in self.inputs.values() for n in ns]

    def output_arg_names(self) -> List[str]:
        return [n for ns in self.outputs.values() for n in ns]

    def attr(self, name: str, default=None):
        return self.attrs.get(name, default)

    def has_attr(self, name: str) -> bool:
        return name in self.attrs

    def _rename_input(self, old: str, new: str):
        for ns in self.inputs.values():
            for i, n in enumerate(ns):
                if n == old:
                    ns[i] = new

    def _rename_output(self, old: str, new: str):
        for ns in self.outputs.values():
            for i, n in enumerate(ns):
                if n == old:
                    ns[i] = new

    def __repr__(self):
        ins = {k: v for k, v in self.inputs.items()}
        outs = {k: v for k, v in self.outputs.items()}
        return f"Operator({self.type}, in={ins}, out={outs}, attrs={self.attrs})"

    # -- serialization ----------------------------------------------------
    def to_proto(self) -> ir_pb2.OpDef:
        p = ir_pb2.OpDef(type=self.type)
        for slot, names in self.inputs.items():
            p.inputs.append(ir_pb2.Slot(name=slot, args=names))
        for slot, names in self.outputs.items():
            p.outputs.append(ir_pb2.Slot(name=slot, args=names))
        for k, v in self.attrs.items():
            p.attrs[k].CopyFrom(_attr_to_proto(v))
        p.callstack.extend(self.callstack[-3:])
        return p

    @staticmethod
    def from_proto(block: "Block", p: ir_pb2.OpDef) -> "Operator":
        op = Operator.__new__(Operator)
        op.block = block
        op.type = p.type
        op.inputs = {s.name: list(s.args) for s in p.inputs}
        op.outputs = {s.name: list(s.args) for s in p.outputs}
        op.attrs = {k: _attr_from_proto(a) for k, a in p.attrs.items()}
        op.callstack = list(p.callstack)
        return op


def _normalize_slots(slots) -> Dict[str, List[str]]:
    out: Dict[str, List[str]] = {}
    for slot, val in (slots or {}).items():
        if val is None:
            continue
        if isinstance(val, (Variable, str)):
            val = [val]
        names = [v.name if isinstance(v, Variable) else str(v) for v in val]
        out[slot] = names
    return out


def _capture_callstack() -> List[str]:
    # Keep user frames only; error messages carrying build-site stacks are a
    # product feature of the reference (framework/op_call_stack.h).
    stack = traceback.extract_stack()[:-3]
    frames = [
        f"{f.filename}:{f.lineno} {f.name}"
        for f in stack
        if "/paddle_tpu/" not in f.filename
    ]
    return frames[-5:]


# ---------------------------------------------------------------------------
# Block
# ---------------------------------------------------------------------------


class Block:
    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    # -- vars -------------------------------------------------------------
    def create_var(self, name=None, **kwargs) -> Variable:
        if name is None:
            name = unique_name.generate("tmp_var")
        if name in self.vars:
            return self.vars[name]
        v = Variable(self, name, **kwargs)
        self.vars[name] = v
        self.program._bump()
        return v

    def create_parameter(self, name, shape, dtype="float32", **kw) -> Parameter:
        p = Parameter(self, name, shape, dtype=dtype, **kw)
        self.vars[name] = p
        self.program._bump()
        return p

    def var(self, name: str) -> Variable:
        v = self._find_var_recursive(name)
        if v is None:
            raise KeyError(f"variable {name!r} not found in block {self.idx}")
        return v

    def has_var(self, name: str) -> bool:
        return self._find_var_recursive(name) is not None

    def _find_var_recursive(self, name: str) -> Optional[Variable]:
        blk: Optional[Block] = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = (
                self.program.blocks[blk.parent_idx] if blk.parent_idx >= 0 else None
            )
        return None

    @property
    def parent_block(self) -> Optional["Block"]:
        return self.program.blocks[self.parent_idx] if self.parent_idx >= 0 else None

    # -- ops --------------------------------------------------------------
    def append_op(self, type, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        self.program._bump()
        return op

    def _insert_op(self, index, type, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(index, op)
        self.program._bump()
        return op

    def _remove_op(self, index: int):
        del self.ops[index]
        self.program._bump()

    # -- serialization ----------------------------------------------------
    def to_proto(self) -> ir_pb2.BlockDef:
        p = ir_pb2.BlockDef(idx=self.idx, parent_idx=self.parent_idx)
        for v in self.vars.values():
            p.vars.append(v.to_proto())
        for op in self.ops:
            p.ops.append(op.to_proto())
        return p

    @staticmethod
    def from_proto(program: "Program", p: ir_pb2.BlockDef) -> "Block":
        b = Block(program, p.idx, p.parent_idx)
        for vp in p.vars:
            v = Variable.from_proto(b, vp)
            b.vars[v.name] = v
        for op_p in p.ops:
            b.ops.append(Operator.from_proto(b, op_p))
        return b


# ---------------------------------------------------------------------------
# Program
# ---------------------------------------------------------------------------


class Program:
    """An ordered forest of Blocks; the unit of compilation.

    The Executor compiles ``(program fingerprint, feed-spec, fetch-list)``
    to one XLA executable; ``_bump`` invalidates the fingerprint on any
    mutation so cached executables are never stale.
    """

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self.current_block_idx = 0
        self.random_seed = 0
        self._version = 0
        self._fingerprint_cache: Optional[str] = None
        # set of var names an AMP pass decided to keep fp32 (populated later)
        self._amp_fp32_vars: set = set()

    # -- structure --------------------------------------------------------
    @property
    def global_block(self) -> Block:
        return self.blocks[0]

    def block(self, idx: int) -> Block:
        return self.blocks[idx]

    def current_block(self) -> Block:
        return self.blocks[self.current_block_idx]

    def _create_block(self, parent_idx: Optional[int] = None) -> Block:
        parent = self.current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent)
        self.blocks.append(b)
        self.current_block_idx = b.idx
        self._bump()
        return b

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    def _bump(self):
        self._version += 1
        self._fingerprint_cache = None

    # -- queries ----------------------------------------------------------
    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.list_vars() if isinstance(v, Parameter) or v.is_parameter]

    # -- serialization ----------------------------------------------------
    def to_proto(self) -> ir_pb2.ProgramDef:
        p = ir_pb2.ProgramDef(version=1, random_seed=self.random_seed)
        for b in self.blocks:
            p.blocks.append(b.to_proto())
        return p

    def serialize_to_string(self) -> bytes:
        return self.to_proto().SerializeToString()

    @staticmethod
    def parse_from_string(data: bytes) -> "Program":
        p = ir_pb2.ProgramDef()
        p.ParseFromString(data)
        return Program.from_proto(p)

    @staticmethod
    def from_proto(p: ir_pb2.ProgramDef) -> "Program":
        prog = Program()
        prog.blocks = [Block.from_proto(prog, bp) for bp in p.blocks]
        prog.random_seed = p.random_seed
        prog._bump()
        return prog

    def fingerprint(self) -> str:
        if self._fingerprint_cache is None:
            h = hashlib.sha1()
            for b in self.blocks:
                for op in b.ops:
                    h.update(op.type.encode())
                    for slot in sorted(op.inputs):
                        h.update(f"{slot}:{','.join(op.inputs[slot])};".encode())
                    for slot in sorted(op.outputs):
                        h.update(f">{slot}:{','.join(op.outputs[slot])};".encode())
                    for k in sorted(op.attrs):
                        h.update(f"@{k}={op.attrs[k]!r}".encode())
                for name in sorted(b.vars):
                    v = b.vars[name]
                    h.update(
                        f"v{name}:{v.shape}:{v.dtype}:{v.persistable}".encode()
                    )
            h.update(str(self.random_seed).encode())
            self._fingerprint_cache = h.hexdigest()
        return self._fingerprint_cache

    def clone(self, for_test: bool = False) -> "Program":
        prog = Program.from_proto(self.to_proto())
        prog.random_seed = self.random_seed
        # re-link Parameter-ness lost by proto round trip
        if for_test:
            for b in prog.blocks:
                for op in b.ops:
                    if "is_test" in op.attrs:
                        op.attrs["is_test"] = True
                    if op.type in ("dropout",):
                        op.attrs["is_test"] = True
                    if op.type in ("batch_norm", "sync_batch_norm"):
                        op.attrs["is_test"] = True
                        op.attrs["use_global_stats"] = True
        return prog

    def __repr__(self):
        n_ops = sum(len(b.ops) for b in self.blocks)
        return f"Program(blocks={len(self.blocks)}, ops={n_ops}, version={self._version})"


# ---------------------------------------------------------------------------
# Default programs & guards (reference framework.py program_guard etc.)
# ---------------------------------------------------------------------------

_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


def switch_main_program(program: Program) -> Program:
    global _main_program
    old, _main_program = _main_program, program
    return old


def switch_startup_program(program: Program) -> Program:
    global _startup_program
    old, _startup_program = _startup_program, program
    return old


_guard_depth = 0


def in_program_guard() -> bool:
    """True while user code is inside a program_guard block — used by the
    2.0 dual-mode dispatch to route input-less ops (creation/random) into
    the graph instead of executing them eagerly."""
    return _guard_depth > 0


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    global _guard_depth
    old_main = switch_main_program(main_program)
    old_startup = None
    if startup_program is not None:
        old_startup = switch_startup_program(startup_program)
    _guard_depth += 1
    try:
        yield
    finally:
        _guard_depth -= 1
        switch_main_program(old_main)
        if old_startup is not None:
            switch_startup_program(old_startup)


GRAD_SUFFIX = "@GRAD"


def grad_var_name(name: str) -> str:
    return name + GRAD_SUFFIX
