"""Graph autodiff: append_backward over program blocks.

Role parity: reference python/paddle/fluid/backward.py (`append_backward`
:1275 — reverse walk, per-op grad-op makers, sum-op insertion on fan-out,
`calc_gradient`:1728) and the C++ GradOpDescMaker registry
(framework/grad_op_desc_maker.h).

TPU-native twist: most ops need no hand-written grad kernel.  The default
grad maker emits a single ``<type>_grad`` op carrying the forward op's
slots; its default lowering (ops/grad_generic.py) rebuilds the forward
computation at trace time and applies ``jax.vjp``.  Because forward and
backward live in ONE compiled XLA computation, XLA CSEs the recomputed
forward — so this costs nothing at runtime while giving every registered
forward op an automatic, exact gradient.  Ops where recompute is wrong
(randomness) or wasteful register explicit makers/lowerings.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Callable, Dict, List, Optional

from . import dtypes
from .program import Block, Operator, Variable, grad_var_name

GRAD_SUFFIX = "@GRAD"

# forward op type -> maker(bwd_ctx, op, out_grads) -> {input_name: grad_name}
GRAD_MAKERS: Dict[str, Callable] = {}

# ops that terminate gradient flow
NO_GRAD_OPS = {
    "fill_constant",
    "gaussian_random",
    "uniform_random",
    "truncated_gaussian_random",
    "randint",
    "randperm",
    "feed",
    "fetch",
    "shape",
    "size",
    "accuracy",
    "auc",
    "arg_max",
    "arg_min",
    "equal",
    "not_equal",
    "less_than",
    "less_equal",
    "greater_than",
    "greater_equal",
    "logical_and",
    "logical_or",
    "logical_not",
    "logical_xor",
    "assign_value",
    "eye",
    "range",
    "linspace",
    "one_hot",
    "one_hot_v2",
    "increment",
    "print",
    "isfinite",
    "isfinite_v2",
    "isnan_v2",
    "isinf_v2",
}


def register_grad_maker(*op_types: str):
    def deco(fn):
        for t in op_types:
            GRAD_MAKERS[t] = fn
        return fn

    return deco


class BackwardContext:
    """State for one append_backward pass over a block."""

    def __init__(self, block: Block, no_grad_set):
        self.block = block
        self.no_grad_set = set(no_grad_set or ())
        self._rename_counter = defaultdict(int)

    def wants_grad(self, name: str) -> bool:
        if name in self.no_grad_set:
            return False
        var = self.block._find_var_recursive(name)
        if var is None:
            return True  # unknown vars: be permissive
        if var.stop_gradient:
            return False
        return dtypes.is_floating(var.dtype)

    def grad_contribution_name(self, name: str, pending: dict) -> str:
        """Canonical grad name, or a renamed one if contributions already exist."""
        base = grad_var_name(name)
        n = len(pending.get(name, []))
        if n == 0:
            return base
        self._rename_counter[name] += 1
        return f"{base}@RENAME@{self._rename_counter[name]}"

    def ensure_grad_var(self, gname: str, like: Optional[str]):
        if self.block.has_var(gname):
            return
        var = self.block._find_var_recursive(like) if like else None
        self.block.create_var(
            name=gname,
            shape=var.shape if var is not None else (),
            dtype=var.dtype if var is not None else "float32",
            stop_gradient=True,
        )

    def append(self, type, inputs, outputs, attrs=None) -> Operator:
        return self.block.append_op(type, inputs, outputs, attrs)


def default_grad_maker(bctx: BackwardContext, op: Operator, out_grads: Dict[str, str]):
    """Emit one generic `<type>_grad` op (lowered by ops/grad_generic.py)."""
    gtype = op.type + "_grad"
    inputs = {}
    for slot, names in op.inputs.items():
        inputs[slot] = list(names)
    for slot, names in op.outputs.items():
        inputs[slot] = list(names)
        gnames = [out_grads.get(n, "") for n in names]
        if any(gnames):
            inputs[slot + GRAD_SUFFIX] = gnames
    outputs = {}
    produced = {}
    for slot, names in op.inputs.items():
        gouts = []
        any_grad = False
        for n in names:
            if bctx.wants_grad(n):
                g = f"__pending__{n}"  # placeholder; caller renames
                gouts.append(g)
                any_grad = True
            else:
                gouts.append("")
        if any_grad:
            outputs[slot + GRAD_SUFFIX] = gouts
    attrs = dict(op.attrs)
    attrs["__fwd_type__"] = op.type
    attrs["__fwd_out_slots__"] = list(op.outputs.keys())
    gop = Operator(bctx.block, gtype, inputs, outputs, attrs)
    return gop


def _finalize_out_grads(bctx, pending, op) -> Dict[str, str]:
    """Collapse pending contributions for each of op's outputs into one grad
    var, inserting a sum op on fan-out (reference backward.py sum-op logic)."""
    out_grads = {}
    for out_name in dict.fromkeys(op.output_arg_names()):
        contribs = pending.get(out_name)
        if not contribs:
            continue
        if len(contribs) == 1:
            out_grads[out_name] = contribs[0]
        else:
            target = grad_var_name(out_name)
            bctx.ensure_grad_var(target, out_name)
            bctx.append("sum", {"X": list(contribs)}, {"Out": target})
            out_grads[out_name] = target
        pending[out_name] = [out_grads[out_name]]
    return out_grads


RECOMPUTE_SUFFIX = "@RECOMPUTE"

# ops whose outputs must NOT be recomputed (re-running them yields different
# values): keep their stored outputs in the backward instead
_NONDETERMINISTIC_OPS = {
    "dropout", "gaussian_random", "uniform_random",
    "truncated_gaussian_random", "randint", "randperm",
}


def _emit_recompute_segments(bctx, block, fwd_ops, checkpoints, keep_names):
    """Activation recompute (reference backward.py:689
    `_append_backward_ops_with_checkpoints_`): re-emit forward ops so the
    backward reads fresh copies of non-checkpoint activations instead of
    keeping them alive from the forward pass.

    TPU-native twist: forward+backward are ONE XLA computation, so naive
    duplication would be CSE'd straight back.  Each checkpoint/param/feed
    entering a recomputed segment is routed through a `recompute_barrier`
    op (lowered to lax.optimization_barrier) which blocks CSE — XLA then
    truly recomputes the segment in the backward and frees the original
    activations after the forward.

    Returns {activation_name -> recomputed_name} for the grad emission to
    rename against.
    """
    ckpt = set(checkpoints)
    keep = set(keep_names) | ckpt
    rc_map: Dict[str, str] = {}
    barriered: Dict[str, str] = {}

    def barrier(name: str) -> str:
        if name not in barriered:
            bname = name + "@RCBAR"
            bctx.ensure_grad_var(bname, name)
            bctx.append("recompute_barrier", {"X": [name]}, {"Out": [bname]})
            barriered[name] = bname
        return barriered[name]

    for op in fwd_ops:
        if op.type in _NONDETERMINISTIC_OPS or op.type in NO_GRAD_OPS:
            continue
        outs = [n for n in op.output_arg_names() if n and n not in keep]
        if not outs:
            continue
        var_ok = True
        for n in outs:
            v = block._find_var_recursive(n)
            if v is not None and v.persistable:
                var_ok = False
        if not var_ok:
            continue
        new_inputs = {}
        for slot, names in op.inputs.items():
            renamed = []
            for n in names:
                if n in rc_map:
                    renamed.append(rc_map[n])
                else:
                    # EVERY external input (checkpoint, param, feed) enters
                    # through the barrier — otherwise the re-emitted ops
                    # have byte-identical inputs to the originals and XLA
                    # CSEs the duplicate away, keeping activations alive
                    renamed.append(barrier(n))
            new_inputs[slot] = renamed
        new_outputs = {}
        for slot, names in op.outputs.items():
            renamed = []
            for n in names:
                if n and n not in keep:
                    rn = n + RECOMPUTE_SUFFIX
                    bctx.ensure_grad_var(rn, n)
                    rc_map[n] = rn
                    renamed.append(rn)
                else:
                    renamed.append(n)
            new_outputs[slot] = renamed
        bctx.append(op.type, new_inputs, new_outputs, dict(op.attrs))
    return rc_map


def append_backward(
    loss: Variable,
    parameter_list=None,
    no_grad_set=None,
    callbacks=None,
    checkpoints=None,
):
    """Append grad ops computing d(loss)/d(params); returns [(param, grad)].

    Only root-block autodiff (control-flow sub-block autodiff arrives with
    the control-flow lowering)."""
    block = loss.block
    program = block.program
    bctx = BackwardContext(block, no_grad_set)

    fwd_ops = list(block.ops)

    # seed: d loss / d loss = 1
    loss_grad = grad_var_name(loss.name)
    bctx.ensure_grad_var(loss_grad, loss.name)
    block.append_op(
        "fill_constant",
        {},
        {"Out": loss_grad},
        {
            "shape": list(loss.shape),
            "value": 1.0,
            "dtype": loss.dtype,
        },
    )

    pending: Dict[str, List[str]] = defaultdict(list)
    pending[loss.name].append(loss_grad)

    # activation recompute: re-emit forward segments behind a CSE fence and
    # point grad ops at the recomputed copies (reference backward.py:689)
    rc_map: Dict[str, str] = {}
    if checkpoints:
        keep = {p.name for p in program.all_parameters()}
        rc_map = _emit_recompute_segments(
            bctx, block, fwd_ops, [getattr(c, "name", c) for c in checkpoints],
            keep)

    for op in reversed(fwd_ops):
        if op.type in NO_GRAD_OPS:
            continue
        if not any(pending.get(o) for o in op.output_arg_names()):
            continue
        out_grads = _finalize_out_grads(bctx, pending, op)
        if not out_grads:
            continue
        maker = GRAD_MAKERS.get(op.type, default_grad_maker)
        gop = maker(bctx, op, out_grads)
        if gop is None:
            continue
        gops = gop if isinstance(gop, (list, tuple)) else [gop]
        if rc_map:
            # forward-value slots read the recomputed copies; @GRAD slots
            # keep original-derived names (the grad graph's own wiring)
            for g in gops:
                for slot, names in list(g.inputs.items()):
                    if slot.endswith(GRAD_SUFFIX):
                        continue
                    g.inputs[slot] = [rc_map.get(n, n) for n in names]
        for g in gops:
            # resolve placeholder grad names to (possibly renamed) real ones
            for slot, names in list(g.outputs.items()):
                resolved = []
                for n in names:
                    if n.startswith("__pending__"):
                        src = n[len("__pending__") :]
                        gname = bctx.grad_contribution_name(src, pending)
                        bctx.ensure_grad_var(gname, src)
                        pending[src].append(gname)
                        resolved.append(gname)
                    elif n:
                        resolved.append(n)
                    else:
                        resolved.append("")
                g.outputs[slot] = [r for r in resolved]
            block.ops.append(g)
            program._bump()

    # collect (param, grad) pairs
    if parameter_list is not None:
        params = [
            block.var(p) if isinstance(p, str) else p for p in parameter_list
        ]
    else:
        params = [v for v in program.all_parameters() if v.trainable]
    params_and_grads = []
    for p in params:
        contribs = pending.get(p.name, [])
        if not contribs:
            continue
        if len(contribs) > 1:
            target = grad_var_name(p.name)
            bctx.ensure_grad_var(target, p.name)
            bctx.append("sum", {"X": list(contribs)}, {"Out": target})
        else:
            target = contribs[0]
            canonical = grad_var_name(p.name)
            if target != canonical:
                bctx.ensure_grad_var(canonical, p.name)
                bctx.append("assign", {"X": target}, {"Out": canonical})
                target = canonical
        params_and_grads.append((p, block.var(target)))
    return params_and_grads


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """Gradients of `targets` w.r.t. arbitrary `inputs` (reference
    backward.py:1728).  Single-target, root-block version."""
    tgts = targets if isinstance(targets, (list, tuple)) else [targets]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if len(tgts) != 1:
        raise NotImplementedError("calc_gradient supports a single target for now")
    pg = append_backward(tgts[0], parameter_list=[v.name for v in ins], no_grad_set=no_grad_set)
    by_name = {p.name: g for p, g in pg}
    return [by_name.get(v.name) for v in ins]


# ---------------------------------------------------------------------------
# explicit grad makers for ops with special backward contracts
# ---------------------------------------------------------------------------


@register_grad_maker("softmax_with_cross_entropy")
def _swce_maker(bctx, op, out_grads):
    loss_g = out_grads.get(op.output("Loss")[0])
    if loss_g is None:
        return default_grad_maker(bctx, op, out_grads)
    logits = op.input("Logits")[0]
    if not bctx.wants_grad(logits):
        return None
    return Operator(
        bctx.block,
        "softmax_with_cross_entropy_grad",
        {
            "Softmax": op.output("Softmax"),
            "Label": op.input("Label"),
            "Loss@GRAD": [loss_g],
        },
        {"Logits@GRAD": [f"__pending__{logits}"]},
        dict(op.attrs),
    )


@register_grad_maker("dropout")
def _dropout_maker(bctx, op, out_grads):
    g = out_grads.get(op.output("Out")[0])
    x = op.input("X")[0]
    if g is None or not bctx.wants_grad(x):
        return None
    return Operator(
        bctx.block,
        "dropout_grad",
        {"Mask": op.output("Mask"), "Out@GRAD": [g]},
        {"X@GRAD": [f"__pending__{x}"]},
        dict(op.attrs),
    )


@register_grad_maker("mean")
def _mean_maker(bctx, op, out_grads):
    g = out_grads.get(op.output("Out")[0])
    x = op.input("X")[0]
    if g is None or not bctx.wants_grad(x):
        return None
    return Operator(
        bctx.block,
        "mean_grad",
        {"X": [x], "Out@GRAD": [g]},
        {"X@GRAD": [f"__pending__{x}"]},
    )


@register_grad_maker("reshape2", "reshape")
def _reshape_maker(bctx, op, out_grads):
    g = out_grads.get(op.output("Out")[0])
    x = op.input("X")[0]
    if g is None or not bctx.wants_grad(x):
        return None
    return Operator(
        bctx.block,
        "reshape_like_grad",
        {"X": [x], "Out@GRAD": [g]},
        {"X@GRAD": [f"__pending__{x}"]},
    )


@register_grad_maker("transpose2", "transpose")
def _transpose_maker(bctx, op, out_grads):
    g = out_grads.get(op.output("Out")[0])
    x = op.input("X")[0]
    if g is None or not bctx.wants_grad(x):
        return None
    return Operator(
        bctx.block,
        "transpose2_grad",
        {"Out@GRAD": [g]},
        {"X@GRAD": [f"__pending__{x}"]},
        {"axis": list(op.attr("axis", []))},
    )


@register_grad_maker("while")
def _while_maker(bctx, op, out_grads):
    raise NotImplementedError(
        "gradients through `while` loops are not supported: XLA/jax has no "
        "reverse-mode rule for lax.while_loop (unbounded trip count). For "
        "differentiable recurrences use the lax.scan-backed RNN ops "
        "(gru/lstm/rnn) or unroll a fixed-length loop")


@register_grad_maker("assign", "share_data")
def _assign_maker(bctx, op, out_grads):
    g = out_grads.get(op.output("Out")[0])
    x = op.input("X")[0]
    if g is None or not bctx.wants_grad(x):
        return None
    return Operator(
        bctx.block, "assign", {"X": [g]}, {"Out": [f"__pending__{x}"]}
    )
