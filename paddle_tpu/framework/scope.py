"""Scope: runtime variable storage (name -> device array).

Role parity: reference paddle/fluid/framework/scope.h:52 (hierarchical
name->Variable maps) and tensor.h:46.  TPU-native simplification: values are
jax Arrays owned by PJRT; a Scope is a flat dict with an optional parent
chain.  There is no per-op lookup on the hot path — the Executor gathers the
state tuple once per compiled step.
"""
from __future__ import annotations

import itertools
from typing import Dict, Optional

import numpy as np


def is_device_array(x) -> bool:
    """jax Array duck-type probe — THE shared detection rule (executor,
    io, scope all import this one; a rule change lands everywhere)."""
    return hasattr(x, "sharding") and hasattr(x, "dtype")


class _TensorView:
    """Minimal ``.get_tensor()`` compatibility object."""

    def __init__(self, scope: "Scope", name: str):
        self._scope = scope
        self._name = name

    def set(self, array, place=None):
        # a jax device array passes through untouched: np.asarray here
        # would force a pointless device->host->device round trip (the
        # scope stores device arrays natively)
        if not is_device_array(array):
            array = np.asarray(array)
        self._scope.set_var(self._name, array, place)

    def shape(self):
        v = self._scope.get_var(self._name)
        return list(v.shape)

    def __array__(self, dtype=None):
        arr = np.asarray(self._scope.get_var(self._name))
        return arr.astype(dtype) if dtype is not None else arr


class _VarView:
    def __init__(self, scope: "Scope", name: str):
        self._scope = scope
        self._name = name

    def get_tensor(self) -> _TensorView:
        return _TensorView(self._scope, self._name)


class PackedParamRef:
    """Lazy view of one variable inside a pipeline-packed state buffer.

    Pipeline v3 shards parameters + optimizer slots per stage: the scope
    holds ONE (n_stages, width) buffer sharded over the 'pp' mesh axis,
    and each owned variable becomes this lightweight view.  Reading the
    view (np.asarray — the paddle.save / checkpoint / inspection path)
    gathers the owning stage's row and slices the variable back out;
    writing a concrete array over it (scope.set_var — the paddle.load /
    restore path) signals the executor to re-pack before the next step.
    """

    __slots__ = ("_scope", "_packed_name", "stage", "offset", "shape",
                 "dtype", "mp_degree", "mp_dim")

    def __init__(self, scope, packed_name, stage, offset, shape, dtype,
                 mp_degree=1, mp_dim=None):
        self._scope = scope
        self._packed_name = packed_name
        self.stage = int(stage)
        self.offset = int(offset)
        # DECLARED (global) shape: the view always materializes the
        # true full value, even when the packed buffer holds per-mp-rank
        # shards (the dp×mp×pp composition, distributed/pipeline.py) —
        # checkpoints and inspection stay topology-independent
        self.shape = tuple(int(d) for d in shape)
        self.dtype = np.dtype(dtype)
        self.mp_degree = int(mp_degree)
        self.mp_dim = mp_dim if mp_dim is None else int(mp_dim)

    @property
    def size(self):
        n = 1
        for d in self.shape:
            n *= d
        return n

    @property
    def local_shape(self):
        """Shape of ONE packed entry: the per-mp-rank shard for a
        tensor-parallel-sharded var, the full shape otherwise."""
        if self.mp_dim is None:
            return self.shape
        ls = list(self.shape)
        ls[self.mp_dim] //= self.mp_degree
        return tuple(ls)

    def __array__(self, dtype=None, copy=None):
        buf = self._scope.get_var(self._packed_name)
        lshape = self.local_shape
        lsize = 1
        for d in lshape:
            lsize *= d
        if self.mp_degree <= 1:
            row = np.asarray(buf[self.stage])
            arr = row[self.offset:self.offset + lsize] \
                .reshape(lshape).astype(self.dtype)
        else:
            rows = np.asarray(buf[self.stage])  # (MP, W)
            if self.mp_dim is None:
                # replicated across mp ranks: every row holds the same
                # bytes (identical local updates keep them in lockstep)
                arr = rows[0, self.offset:self.offset + lsize] \
                    .reshape(lshape).astype(self.dtype)
            else:
                shards = [rows[r, self.offset:self.offset + lsize]
                          .reshape(lshape)
                          for r in range(self.mp_degree)]
                arr = np.concatenate(shards, axis=self.mp_dim) \
                    .astype(self.dtype)
        return arr.astype(dtype) if dtype is not None else arr

    def __repr__(self):
        return (f"PackedParamRef(stage={self.stage}, shape={self.shape}, "
                f"dtype={self.dtype}"
                + (f", mp={self.mp_degree}@{self.mp_dim}"
                   if self.mp_degree > 1 else "") + ")")


class StackedParamRef:
    """Lazy per-layer view into a layer-stacked state array.

    The LayerScanPass (framework/passes.py) stacks per-layer weights,
    optimizer slots, and their gradients into one leading-axis
    ``(num_layers, *shape)`` scope array per weight family so the whole
    repeated-layer region compiles as a single ``jax.lax.scan``.  The
    scope keeps serving the PER-LAYER names through this view: reading
    it (``np.asarray`` — checkpoints, paddle.save, tests, attribution)
    slices layer ``index`` out of the stacked carrier; writing a
    concrete array over it (checkpoint restore, paddle.load) signals
    ``LayerScanPlan.ensure_stacked`` to re-pack before the next step —
    so checkpoints stay per-layer and elastic across the scan flag.
    """

    __slots__ = ("_scope", "stack_name", "index", "shape", "dtype")

    def __init__(self, scope, stack_name, index, shape, dtype):
        self._scope = scope
        self.stack_name = stack_name
        self.index = int(index)
        self.shape = tuple(int(d) for d in shape)
        self.dtype = np.dtype(dtype)

    def __array__(self, dtype=None, copy=None):
        buf = self._scope.get_var(self.stack_name)
        arr = np.asarray(buf[self.index]).reshape(self.shape)
        if arr.dtype != self.dtype:
            arr = arr.view(self.dtype) if arr.itemsize == self.dtype.itemsize \
                else arr.astype(self.dtype)
        return arr.astype(dtype) if dtype is not None else arr

    def device_value(self):
        """The layer's slice as a (device) array — no host transfer."""
        return self._scope.get_var(self.stack_name)[self.index]

    def __repr__(self):
        return (f"StackedParamRef({self.stack_name!r}[{self.index}], "
                f"shape={self.shape}, dtype={self.dtype})")


_scope_serial = itertools.count()


class Scope:
    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, object] = {}
        self._parent = parent
        self._kids = []
        # monotone id for executor caches: id() of a GC'd scope can be
        # recycled by a new scope and silently serve stale analysis
        self.serial = next(_scope_serial)

    # -- core -------------------------------------------------------------
    def has_var(self, name: str) -> bool:
        return name in self._vars or (self._parent is not None and self._parent.has_var(name))

    def get_var(self, name: str):
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s._parent
        raise KeyError(f"variable {name!r} not found in scope")

    def set_var(self, name: str, value, place=None):
        if place is not None:
            import jax

            value = jax.device_put(value, place.jax_device())
        self._vars[name] = value

    def erase(self, name: str):
        self._vars.pop(name, None)

    def local_var_names(self):
        return list(self._vars)

    def new_scope(self) -> "Scope":
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def drop_kids(self):
        self._kids.clear()

    # -- reference-api compatibility --------------------------------------
    def var(self, name: str) -> _VarView:
        self._vars.setdefault(name, None)
        return _VarView(self, name)

    def find_var(self, name: str) -> Optional[_VarView]:
        return _VarView(self, name) if self.has_var(name) else None


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


def _switch_scope(scope: Scope) -> Scope:
    global _global_scope
    old, _global_scope = _global_scope, scope
    return old
