"""Version-compatibility shims over the installed jax.

The TPU-native code targets the modern ``jax.shard_map`` entry point
(with its ``check_vma`` flag); jax 0.4.x ships the same machinery as
``jax.experimental.shard_map.shard_map`` with the flag named
``check_rep``.  Importing through this module keeps every SPMD call
site version-agnostic — without it, the whole distributed test tier
dies on ``ImportError: cannot import name 'shard_map'`` under older
jax.
"""
from __future__ import annotations

import inspect
from typing import Optional

import jax

_impl = getattr(jax, "shard_map", None)
if not callable(_impl):  # jax <= 0.4.x (or a module-shaped placeholder)
    from jax.experimental.shard_map import shard_map as _impl

# probe the flag spelling ONCE — a per-call try/except would swallow
# unrelated TypeErrors (bad in_specs, ...) and re-raise a misleading
# "unexpected keyword" instead of the real diagnostic
try:
    _params = inspect.signature(_impl).parameters
except (TypeError, ValueError):  # C-level / exotic callable
    _params = {}
_CHECK_FLAG = ("check_vma" if "check_vma" in _params
               else "check_rep" if "check_rep" in _params
               else None)


def shard_map(f, mesh, in_specs, out_specs, check_vma=True):
    kw = {_CHECK_FLAG: check_vma} if _CHECK_FLAG else {}
    return _impl(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                 **kw)


_CONFIG_MISSING = object()


def config_value(name, default=_CONFIG_MISSING):
    """Guarded ``jax.config`` accessor: config entries come and go
    across jax versions (``jax_cpu_collectives_implementation`` does not
    exist before the pluggable CPU-collectives work), and a bare
    ``jax.config.<name>`` raises ``AttributeError`` on versions without
    the entry.  Returns ``default`` when the entry is absent; with no
    default, absence returns the (distinct, falsy-ish) sentinel
    ``jax_compat._CONFIG_MISSING`` so callers can tell "missing" from a
    legitimately-``None`` value."""
    return getattr(jax.config, name, default)


def has_config(name) -> bool:
    return config_value(name) is not _CONFIG_MISSING


def update_config(name, value) -> bool:
    """``jax.config.update`` only when the entry exists on this jax;
    returns whether the update happened (a no-op on versions without
    the knob — the caller decides whether that is fatal)."""
    if not has_config(name):
        return False
    jax.config.update(name, value)
    return True


# ---------------------------------------------------------------------------
# AOT-compiled-executable introspection (observe/xla_stats.py).  All four
# accessors are capability guards over jax's AOT stages API: the shapes
# of compiled.memory_analysis()/cost_analysis()/runtime_executable()
# vary across jax versions (and some builds lack them outright), so the
# introspection layer reads through here and treats None/0 as "this jax
# can't say" — never as an error.
# ---------------------------------------------------------------------------


def compiled_memory_stats(compiled):
    """``compiled.memory_analysis()`` (the per-module XLA memory stats
    object with ``argument/output/temp/alias/generated_code
    _size_in_bytes`` attributes) or None when this jax/backend does not
    expose it.  Under SPMD partitioning the module is the PER-DEVICE
    partitioned program, so the sizes are per-chip."""
    fn = getattr(compiled, "memory_analysis", None)
    if fn is None:
        return None
    try:
        return fn()
    except Exception:  # noqa: BLE001 - introspection must never fail a run
        return None


def compiled_cost_analysis(compiled):
    """``compiled.cost_analysis()`` flattened to one plain dict (older
    jax returns a one-element list of mappings), or None."""
    fn = getattr(compiled, "cost_analysis", None)
    if fn is None:
        return None
    try:
        c = fn()
    except Exception:  # noqa: BLE001
        return None
    if isinstance(c, (list, tuple)):
        c = c[0] if c else None
    if c is None:
        return None
    try:
        return dict(c)
    except (TypeError, ValueError):
        return None


def executable_code_bytes(compiled) -> int:
    """Size of the generated machine code, via the loaded executable;
    0 when the backend does not report it (the CPU backend)."""
    try:
        return int(
            compiled.runtime_executable().size_of_generated_code_in_bytes())
    except Exception:  # noqa: BLE001
        return 0


def compiled_text(compiled):
    """Optimized HLO module text (``compiled.as_text()``) or None."""
    try:
        t = compiled.as_text()
    except Exception:  # noqa: BLE001
        return None
    return t if isinstance(t, str) else None


def device_memory_stats(device=None):
    """``device.memory_stats()`` as a plain dict (TPU/GPU report
    ``bytes_in_use``/``bytes_limit``/``peak_bytes_in_use``; the CPU
    backend returns None) — None when unavailable.  ``device`` defaults
    to the first local device."""
    try:
        if device is None:
            device = jax.local_devices()[0]
        ms = device.memory_stats()
    except Exception:  # noqa: BLE001 - a dead device must not raise here
        return None
    if not ms:
        return None
    return dict(ms)


def float8_e4m3_dtype():
    """The float8 e4m3 dtype of the installed jax (weight-only fp8
    serving, ops/quant_ops.py), or None when this jax/ml_dtypes build
    lacks it — the quant pass then degrades to int8 and counts
    ``quant_fp8_unavailable`` so the telemetry says why the mode flag
    had no effect."""
    import jax.numpy as jnp

    for name in ("float8_e4m3fn", "float8_e4m3"):
        dt = getattr(jnp, name, None)
        if dt is not None:
            return dt
    return None


def axis_size(axis_name):
    """``lax.axis_size`` (newer jax); older jax constant-folds
    ``psum(1, axis)`` to the same static int inside shard_map."""
    from jax import lax

    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return lax.psum(1, axis_name)


# ---------------------------------------------------------------------------
# Scan-over-layers support (framework/passes.py LayerScanPass +
# ops/layer_scan.py).  ``jax.checkpoint_policies`` and lax.scan's
# ``unroll=`` both arrived mid-0.x: a jax without the policy namespace
# degrades to plain ``jax.checkpoint`` (counted once per degraded wrap
# as ``remat_policy_unavailable`` so the telemetry says WHY a policy
# flag had no effect), and a lax.scan without ``unroll`` simply drops
# the knob.  Mirrors the PR 8 AOT-stages capability pattern: probe the
# installed jax, never version-compare strings.
# ---------------------------------------------------------------------------

# framework-facing policy names -> jax.checkpoint_policies attr names
# ("save_anything" is this framework's spelling of "do not recompute
# anything the body produced" == everything_saveable)
_CHECKPOINT_POLICY_NAMES = {
    "nothing_saveable": "nothing_saveable",
    "dots_saveable": "dots_saveable",
    "checkpoint_dots": "dots_saveable",  # historical jax alias
    "save_anything": "everything_saveable",
    "everything_saveable": "everything_saveable",
    "dots_with_no_batch_dims_saveable": "dots_with_no_batch_dims_saveable",
}

REMAT_POLICIES = tuple(_CHECKPOINT_POLICY_NAMES)


def checkpoint_policy(name):
    """Resolve a policy name to the ``jax.checkpoint_policies`` callable,
    or None when this jax lacks the namespace / the specific policy
    (the caller decides whether that degrades or fails)."""
    if not name:
        return None
    pols = getattr(jax, "checkpoint_policies", None)
    if pols is None:
        return None
    return getattr(pols, _CHECKPOINT_POLICY_NAMES.get(name, str(name)), None)


def wrap_checkpoint(fn, policy_name: str = ""):
    """``jax.checkpoint(fn, policy=<resolved>)`` with capability
    degradation: no policy support -> plain ``jax.checkpoint`` (counter
    ``remat_policy_unavailable``); no checkpoint at all (exotic builds)
    -> ``fn`` unchanged.  With ``policy_name`` empty the wrap is skipped
    entirely — primal values are bitwise-identical either way, so the
    un-wrapped body stays the cheapest default."""
    if not policy_name:
        return fn
    ckpt = getattr(jax, "checkpoint", None) or getattr(jax, "remat", None)
    if ckpt is None:
        return fn
    pol = checkpoint_policy(policy_name)
    if pol is None:
        from ..monitor import stat_add

        stat_add("remat_policy_unavailable")
        return ckpt(fn)
    try:
        return ckpt(fn, policy=pol)
    except TypeError:  # jax.checkpoint without the policy= kwarg
        from ..monitor import stat_add

        stat_add("remat_policy_unavailable")
        return ckpt(fn)


# ---------------------------------------------------------------------------
# jax.profiler capture (observe/profiler_capture.py).  Same capability
# pattern as the AOT accessors: probe the installed jax, treat a missing
# or failing profiler as "this jax can't say" (False) — never an error,
# never a version-string compare.  The CPU tier-1 backend typically has
# start_trace but produces host-only traces; a build without
# jax.profiler at all (or one whose start raises) degrades to False and
# the capture layer counts ``prof_trace_unavailable``.
# ---------------------------------------------------------------------------


def profiler_start(log_dir: str) -> bool:
    """Begin a ``jax.profiler`` trace into ``log_dir``; returns whether
    a trace actually started (False = this jax/backend can't)."""
    try:
        from jax import profiler as _prof
    except ImportError:
        return False
    start = getattr(_prof, "start_trace", None)
    if start is None:
        return False
    try:
        start(log_dir)
    except Exception:  # noqa: BLE001 - a second live trace, a dead
        return False   # backend, an unwritable dir: all mean "no trace"
    return True


def profiler_stop() -> bool:
    """Stop the live ``jax.profiler`` trace; returns whether the stop
    succeeded.  Safe to call when no trace is live (returns False)."""
    try:
        from jax import profiler as _prof
    except ImportError:
        return False
    stop = getattr(_prof, "stop_trace", None)
    if stop is None:
        return False
    try:
        stop()
    except Exception:  # noqa: BLE001 - no trace in flight etc.
        return False
    return True


_scan_unroll_supported: Optional[bool] = None


def scan(body, init, xs, length=None, reverse=False, unroll=1):
    """``lax.scan`` with the ``unroll=`` knob applied only where the
    installed jax has it (probed once); ``unroll<=1`` never passes the
    kwarg, so the default path is identical on every jax."""
    global _scan_unroll_supported

    from jax import lax

    kw = {}
    if unroll and int(unroll) > 1:
        if _scan_unroll_supported is None:
            try:
                _scan_unroll_supported = (
                    "unroll" in inspect.signature(lax.scan).parameters)
            except (TypeError, ValueError):
                _scan_unroll_supported = False
        if _scan_unroll_supported:
            kw["unroll"] = int(unroll)
    return lax.scan(body, init, xs, length=length, reverse=reverse, **kw)
