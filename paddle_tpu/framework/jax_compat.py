"""Version-compatibility shims over the installed jax.

The TPU-native code targets the modern ``jax.shard_map`` entry point
(with its ``check_vma`` flag); jax 0.4.x ships the same machinery as
``jax.experimental.shard_map.shard_map`` with the flag named
``check_rep``.  Importing through this module keeps every SPMD call
site version-agnostic — without it, the whole distributed test tier
dies on ``ImportError: cannot import name 'shard_map'`` under older
jax.
"""
from __future__ import annotations

import inspect

import jax

_impl = getattr(jax, "shard_map", None)
if not callable(_impl):  # jax <= 0.4.x (or a module-shaped placeholder)
    from jax.experimental.shard_map import shard_map as _impl

# probe the flag spelling ONCE — a per-call try/except would swallow
# unrelated TypeErrors (bad in_specs, ...) and re-raise a misleading
# "unexpected keyword" instead of the real diagnostic
try:
    _params = inspect.signature(_impl).parameters
except (TypeError, ValueError):  # C-level / exotic callable
    _params = {}
_CHECK_FLAG = ("check_vma" if "check_vma" in _params
               else "check_rep" if "check_rep" in _params
               else None)


def shard_map(f, mesh, in_specs, out_specs, check_vma=True):
    kw = {_CHECK_FLAG: check_vma} if _CHECK_FLAG else {}
    return _impl(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                 **kw)


_CONFIG_MISSING = object()


def config_value(name, default=_CONFIG_MISSING):
    """Guarded ``jax.config`` accessor: config entries come and go
    across jax versions (``jax_cpu_collectives_implementation`` does not
    exist before the pluggable CPU-collectives work), and a bare
    ``jax.config.<name>`` raises ``AttributeError`` on versions without
    the entry.  Returns ``default`` when the entry is absent; with no
    default, absence returns the (distinct, falsy-ish) sentinel
    ``jax_compat._CONFIG_MISSING`` so callers can tell "missing" from a
    legitimately-``None`` value."""
    return getattr(jax.config, name, default)


def has_config(name) -> bool:
    return config_value(name) is not _CONFIG_MISSING


def update_config(name, value) -> bool:
    """``jax.config.update`` only when the entry exists on this jax;
    returns whether the update happened (a no-op on versions without
    the knob — the caller decides whether that is fatal)."""
    if not has_config(name):
        return False
    jax.config.update(name, value)
    return True


def axis_size(axis_name):
    """``lax.axis_size`` (newer jax); older jax constant-folds
    ``psum(1, axis)`` to the same static int inside shard_map."""
    from jax import lax

    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return lax.psum(1, axis_name)
