"""Variable serialization: the byte format behind save/load ops.

Role parity: reference framework/save_load_util.cc + the LoDTensor byte
stream written by save_op.cc:85 (version + dims + dtype + data).  The
TPU-native format keeps the same shape — a small versioned header plus raw
bytes — but uses a JSON header instead of the C++ struct layout (bitwise
format compatibility with the reference is a non-goal; API and round-trip
fidelity are the contract).
"""
from __future__ import annotations

import json
import os
import struct
from typing import Dict, List

import numpy as np

MAGIC = b"PTPUVAR1"
COMBINE_MAGIC = b"PTPUCMB1"


def _header_bytes(arr: np.ndarray) -> bytes:
    h = json.dumps({"dtype": str(arr.dtype),
                    "shape": list(arr.shape)}).encode()
    return struct.pack("<I", len(h)) + h


def _read_header(f):
    (hlen,) = struct.unpack("<I", f.read(4))
    h = json.loads(f.read(hlen).decode())
    return np.dtype(h["dtype"]), tuple(h["shape"])


def save_var(arr: np.ndarray, path: str) -> None:
    arr = np.ascontiguousarray(arr)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "wb") as f:
        f.write(MAGIC)
        f.write(_header_bytes(arr))
        f.write(arr.tobytes())


def load_var(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        magic = f.read(len(MAGIC))
        if magic != MAGIC:
            raise ValueError(
                f"{path!r} is not a paddle_tpu variable file "
                f"(bad magic {magic!r})")
        dtype, shape = _read_header(f)
        data = f.read()
    return np.frombuffer(data, dtype=dtype).reshape(shape).copy()


def save_combine(arrays: Dict[str, np.ndarray], order: List[str],
                 path: str) -> None:
    """All vars in one file, in the given order (reference
    save_combine_op)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "wb") as f:
        f.write(COMBINE_MAGIC)
        f.write(struct.pack("<I", len(order)))
        for name in order:
            arr = np.ascontiguousarray(arrays[name])
            nb = name.encode()
            f.write(struct.pack("<I", len(nb)) + nb)
            f.write(_header_bytes(arr))
            payload = arr.tobytes()
            f.write(struct.pack("<Q", len(payload)))
            f.write(payload)


def load_combine(path: str) -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    with open(path, "rb") as f:
        magic = f.read(len(COMBINE_MAGIC))
        if magic != COMBINE_MAGIC:
            raise ValueError(
                f"{path!r} is not a paddle_tpu combined-params file "
                f"(bad magic {magic!r})")
        (count,) = struct.unpack("<I", f.read(4))
        for _ in range(count):
            (nlen,) = struct.unpack("<I", f.read(4))
            name = f.read(nlen).decode()
            dtype, shape = _read_header(f)
            (plen,) = struct.unpack("<Q", f.read(8))
            data = f.read(plen)
            out[name] = np.frombuffer(data, dtype=dtype).reshape(shape).copy()
    return out
