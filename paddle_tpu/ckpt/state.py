"""Device-state extraction for checkpoints: scope -> host arrays.

The step boundary is the only moment the training state is consistent,
so ``snapshot_scope`` runs there on the caller's thread: every scope
variable is copied device->host (``np.asarray`` == ``jax.device_get``)
and the resulting dict is immutable from the executor's point of view —
the compiled step donates and replaces scope arrays, it never mutates
them in place, so the background writer can serialize the snapshot
while training continues.

Multi-process layout: a process saves exactly what it can address.
Fully-addressable arrays (single process, or replicated values) come
back as plain ``np.ndarray``; a globally-sharded array (ZeRO optimizer
state over the dp axis) comes back as a :class:`LocalShard` carrying
this process's contiguous axis-0 block plus the global shape, so every
rank writes only its own bytes and restore re-assembles the full value
from the rank files (elastic: the reading world size need not match the
writing one).
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np


class LocalShard:
    """This process's contiguous block of a globally-sharded array.

    ``array`` is host data; ``global_shape`` is the full value's shape;
    ``origin`` is the block's per-dimension start offset within the
    global value.  ``origin=None`` is the legacy axis-0 contract:
    restore concatenates the rank blocks in rank order (mesh devices
    are built process-major, so axis-0 blocks are contiguous per
    process — see parallel_env.init_parallel_env).  With an explicit
    origin the block may live anywhere — the non-axis-0 / 2D layouts
    tensor-parallel NamedShardings produce (a column-parallel weight's
    block starts at (0, k·N/mp)) — and restore places each rank's
    block at its recorded offset."""

    __slots__ = ("array", "global_shape", "origin")

    def __init__(self, array, global_shape, origin=None):
        self.array = np.asarray(array)
        self.global_shape = tuple(int(d) for d in global_shape)
        self.origin = (tuple(int(o) for o in origin)
                       if origin is not None else None)

    @property
    def dtype(self):
        return self.array.dtype

    def __repr__(self):
        o = f", origin={self.origin}" if self.origin is not None else ""
        return (f"LocalShard(block={self.array.shape}, "
                f"global={self.global_shape}{o})")


def _assemble_blocks(blocks, ndim):
    """Assemble this process's device blocks — {origin tuple: np
    block} — into ONE contiguous hyperrectangle.  Blocks must tile the
    cartesian grid of their per-dim origins (true for any NamedSharding
    layout: every mesh axis slices one tensor dim evenly).  Returns
    (array, origin)."""
    per_dim = [sorted({o[d] for o in blocks}) for d in range(ndim)]
    grid_shape = tuple(len(s) for s in per_dim)
    expect = 1
    for g in grid_shape:
        expect *= g
    if expect != len(blocks):
        raise ValueError(
            f"process-local shards do not tile a contiguous block: "
            f"{len(blocks)} blocks over a {grid_shape} origin grid")
    # stitch one dim at a time, innermost first
    def stitch(prefix, dim):
        if dim == ndim:
            return blocks[tuple(prefix)]
        parts = [stitch(prefix + [o], dim + 1) for o in per_dim[dim]]
        return np.concatenate(parts, axis=dim) if len(parts) > 1 \
            else parts[0]

    return stitch([], 0), tuple(s[0] for s in per_dim)


def _host_value(v):
    """One scope value -> np.ndarray | LocalShard | None (skip)."""
    if v is None:
        return None
    # jax array (duck-typed; see executor._is_jax_array)
    if hasattr(v, "sharding") and hasattr(v, "dtype"):
        if getattr(v, "is_fully_addressable", True):
            return np.asarray(v)
        # multi-process global array: gather the addressable blocks,
        # keyed (and deduped — replication over a mesh axis puts the
        # same block on several local devices) by their global origin
        ndim = len(v.shape)
        blocks = {}
        for s in v.addressable_shards:
            idx = tuple(s.index) if s.index else (slice(None),) * ndim
            origin = tuple(
                (sl.start or 0) if isinstance(sl, slice) else int(sl)
                for sl in idx)
            if origin not in blocks:
                blocks[origin] = np.asarray(s.data)
        if len(blocks) == 1:
            origin, arr = next(iter(blocks.items()))
            if arr.shape == tuple(v.shape):
                return arr  # replicated across this process's devices
            return LocalShard(arr, v.shape, origin=origin)
        arr, origin = _assemble_blocks(blocks, ndim)
        if arr.shape == tuple(v.shape):
            return arr
        return LocalShard(arr, v.shape, origin=origin)
    try:
        arr = np.asarray(v)
    except Exception:
        return None
    if arr.dtype == object:
        return None
    return arr


def snapshot_scope(scope, var_names: Optional[Sequence[str]] = None
                   ) -> Dict[str, object]:
    """Copy the scope's state to host.  ``var_names=None`` takes every
    local variable (parameters, optimizer slots, AMP loss-scale state,
    the RNG key — the executor writes nothing else back).

    Pipelined dispatch: every live Executor's in-flight window is
    drained first, so the snapshot captures a quiescent, bitwise-
    consistent state (and any pending NaN-scan raises BEFORE a poisoned
    checkpoint is written)."""
    try:
        from ..framework.executor import drain_all as _drain_all

        _drain_all()
    except ImportError:  # pragma: no cover - partial installs
        pass
    if var_names is None:
        var_names = [n for n in scope.local_var_names()]
    out: Dict[str, object] = {}
    for n in var_names:
        hv = _host_value(scope.get_var(n) if scope.has_var(n) else None)
        if hv is not None:
            out[n] = hv
    return out


def restore_scope(scope, state: Dict[str, np.ndarray],
                  var_names: Optional[Sequence[str]] = None) -> list:
    """Write restored host arrays into the scope.  Values go in as
    uncommitted np arrays: the next executor run places (and shards)
    them per the compiled step's input specs, so a checkpoint written on
    one topology restores onto any other."""
    names = set(var_names) if var_names is not None else None
    restored = []
    for n, v in state.items():
        if names is not None and n not in names:
            continue
        scope.set_var(n, v)
        restored.append(n)
    return restored
