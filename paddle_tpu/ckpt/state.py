"""Device-state extraction for checkpoints: scope -> host arrays.

The step boundary is the only moment the training state is consistent,
so ``snapshot_scope`` runs there on the caller's thread: every scope
variable is copied device->host (``np.asarray`` == ``jax.device_get``)
and the resulting dict is immutable from the executor's point of view —
the compiled step donates and replaces scope arrays, it never mutates
them in place, so the background writer can serialize the snapshot
while training continues.

Multi-process layout: a process saves exactly what it can address.
Fully-addressable arrays (single process, or replicated values) come
back as plain ``np.ndarray``; a globally-sharded array (ZeRO optimizer
state over the dp axis) comes back as a :class:`LocalShard` carrying
this process's contiguous axis-0 block plus the global shape, so every
rank writes only its own bytes and restore re-assembles the full value
from the rank files (elastic: the reading world size need not match the
writing one).
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np


class LocalShard:
    """This process's contiguous block of a globally-sharded array.

    ``array`` is host data; ``global_shape`` is the full value's shape;
    ``origin`` is the block's per-dimension start offset within the
    global value.  ``origin=None`` is the legacy axis-0 contract:
    restore concatenates the rank blocks in rank order (mesh devices
    are built process-major, so axis-0 blocks are contiguous per
    process — see parallel_env.init_parallel_env).  With an explicit
    origin the block may live anywhere — the non-axis-0 / 2D layouts
    tensor-parallel NamedShardings produce (a column-parallel weight's
    block starts at (0, k·N/mp)) — and restore places each rank's
    block at its recorded offset."""

    __slots__ = ("array", "global_shape", "origin")

    def __init__(self, array, global_shape, origin=None):
        self.array = np.asarray(array)
        self.global_shape = tuple(int(d) for d in global_shape)
        self.origin = (tuple(int(o) for o in origin)
                       if origin is not None else None)

    @property
    def dtype(self):
        return self.array.dtype

    def __repr__(self):
        o = f", origin={self.origin}" if self.origin is not None else ""
        return (f"LocalShard(block={self.array.shape}, "
                f"global={self.global_shape}{o})")


def _assemble_blocks(blocks, ndim):
    """Assemble this process's device blocks — {origin tuple: np
    block} — into ONE contiguous hyperrectangle.  Blocks must tile the
    cartesian grid of their per-dim origins (true for any NamedSharding
    layout: every mesh axis slices one tensor dim evenly).  Returns
    (array, origin)."""
    per_dim = [sorted({o[d] for o in blocks}) for d in range(ndim)]
    grid_shape = tuple(len(s) for s in per_dim)
    expect = 1
    for g in grid_shape:
        expect *= g
    if expect != len(blocks):
        raise ValueError(
            f"process-local shards do not tile a contiguous block: "
            f"{len(blocks)} blocks over a {grid_shape} origin grid")
    # stitch one dim at a time, innermost first
    def stitch(prefix, dim):
        if dim == ndim:
            return blocks[tuple(prefix)]
        parts = [stitch(prefix + [o], dim + 1) for o in per_dim[dim]]
        return np.concatenate(parts, axis=dim) if len(parts) > 1 \
            else parts[0]

    return stitch([], 0), tuple(s[0] for s in per_dim)


def _host_value(v, _stack_cache=None):
    """One scope value -> np.ndarray | LocalShard | None (skip).

    ``_stack_cache``: per-snapshot {carrier name: gathered host array}
    so the members of one layer stack share a single cross-process
    gather instead of paying it once per layer."""
    if v is None:
        return None
    # layer-scan per-layer view (framework/scope.py StackedParamRef):
    # resolve the stacked carrier through the SAME machinery so a
    # multi-process global carrier takes the gather path, then slice
    # the layer out host-side.  A carrier this process cannot assemble
    # in full must fail LOUDLY — np.asarray(view) on a non-addressable
    # global array raises, and the generic except below would silently
    # drop the parameter from the checkpoint.
    from ..framework.scope import StackedParamRef

    if isinstance(v, StackedParamRef):
        buf = v._scope.get_var(v.stack_name)
        if (hasattr(buf, "sharding")
                and not getattr(buf, "is_fully_addressable", True)):
            carrier = (_stack_cache.get(v.stack_name)
                       if _stack_cache is not None else None)
            if carrier is None:
                carrier = _host_value(buf)
                if not isinstance(carrier, np.ndarray):
                    from .manager import CheckpointError

                    raise CheckpointError(
                        f"layer stack {v.stack_name!r} is not "
                        f"host-assemblable in this process (got "
                        f"{type(carrier).__name__}); cannot checkpoint "
                        f"its per-layer view [{v.index}]")
                if _stack_cache is not None:
                    _stack_cache[v.stack_name] = carrier
            arr = carrier[v.index].reshape(v.shape)
            if arr.dtype != v.dtype:
                arr = (arr.view(v.dtype)
                       if arr.itemsize == v.dtype.itemsize
                       else arr.astype(v.dtype))
            return arr
        # fully addressable: the view's __array__ transfers just the
        # layer's device slice
        return np.asarray(v)
    # jax array (duck-typed; see executor._is_jax_array)
    if hasattr(v, "sharding") and hasattr(v, "dtype"):
        if getattr(v, "is_fully_addressable", True):
            return np.asarray(v)
        # multi-process global array: gather the addressable blocks,
        # keyed (and deduped — replication over a mesh axis puts the
        # same block on several local devices) by their global origin
        ndim = len(v.shape)
        blocks = {}
        for s in v.addressable_shards:
            idx = tuple(s.index) if s.index else (slice(None),) * ndim
            origin = tuple(
                (sl.start or 0) if isinstance(sl, slice) else int(sl)
                for sl in idx)
            if origin not in blocks:
                blocks[origin] = np.asarray(s.data)
        if len(blocks) == 1:
            origin, arr = next(iter(blocks.items()))
            if arr.shape == tuple(v.shape):
                return arr  # replicated across this process's devices
            return LocalShard(arr, v.shape, origin=origin)
        arr, origin = _assemble_blocks(blocks, ndim)
        if arr.shape == tuple(v.shape):
            return arr
        return LocalShard(arr, v.shape, origin=origin)
    try:
        arr = np.asarray(v)
    except Exception:
        return None
    if arr.dtype == object:
        return None
    return arr


def snapshot_scope(scope, var_names: Optional[Sequence[str]] = None
                   ) -> Dict[str, object]:
    """Copy the scope's state to host.  ``var_names=None`` takes every
    local variable (parameters, optimizer slots, AMP loss-scale state,
    the RNG key — the executor writes nothing else back).

    Pipelined dispatch: every live Executor's in-flight window is
    drained first, so the snapshot captures a quiescent, bitwise-
    consistent state (and any pending NaN-scan raises BEFORE a poisoned
    checkpoint is written)."""
    try:
        from ..framework.executor import drain_all as _drain_all

        _drain_all()
    except ImportError:  # pragma: no cover - partial installs
        pass
    if var_names is None:
        # layer-scan stacked carriers (@LAYER_STACK@...) are a runtime
        # layout artifact: their bytes are exactly the per-layer
        # StackedParamRef views saved below, so writing both would
        # double the checkpoint AND pin it to the scan flag.  Per-layer
        # entries keep resume elastic: a restore writes concrete
        # per-layer arrays and the next scanned run re-packs them.
        from ..framework.passes import LAYER_STACK_PREFIX

        var_names = [n for n in scope.local_var_names()
                     if not n.startswith(LAYER_STACK_PREFIX)]
    out: Dict[str, object] = {}
    stack_cache: Dict[str, np.ndarray] = {}
    for n in var_names:
        hv = _host_value(scope.get_var(n) if scope.has_var(n) else None,
                         _stack_cache=stack_cache)
        if hv is not None:
            out[n] = hv
    return out


def restore_scope(scope, state: Dict[str, np.ndarray],
                  var_names: Optional[Sequence[str]] = None) -> list:
    """Write restored host arrays into the scope.  Values go in as
    uncommitted np arrays: the next executor run places (and shards)
    them per the compiled step's input specs, so a checkpoint written on
    one topology restores onto any other."""
    names = set(var_names) if var_names is not None else None
    restored = []
    for n, v in state.items():
        if names is not None and n not in names:
            continue
        scope.set_var(n, v)
        restored.append(n)
    return restored
