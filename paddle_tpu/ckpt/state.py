"""Device-state extraction for checkpoints: scope -> host arrays.

The step boundary is the only moment the training state is consistent,
so ``snapshot_scope`` runs there on the caller's thread: every scope
variable is copied device->host (``np.asarray`` == ``jax.device_get``)
and the resulting dict is immutable from the executor's point of view —
the compiled step donates and replaces scope arrays, it never mutates
them in place, so the background writer can serialize the snapshot
while training continues.

Multi-process layout: a process saves exactly what it can address.
Fully-addressable arrays (single process, or replicated values) come
back as plain ``np.ndarray``; a globally-sharded array (ZeRO optimizer
state over the dp axis) comes back as a :class:`LocalShard` carrying
this process's contiguous axis-0 block plus the global shape, so every
rank writes only its own bytes and restore re-assembles the full value
from the rank files (elastic: the reading world size need not match the
writing one).
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np


class LocalShard:
    """This process's contiguous axis-0 block of a globally-sharded
    array.  ``array`` is host data; ``global_shape`` is the full value's
    shape.  Restore concatenates the rank blocks in rank order (mesh
    devices are built process-major, so axis-0 blocks are contiguous per
    process — see parallel_env.init_parallel_env)."""

    __slots__ = ("array", "global_shape")

    def __init__(self, array, global_shape):
        self.array = np.asarray(array)
        self.global_shape = tuple(int(d) for d in global_shape)

    @property
    def dtype(self):
        return self.array.dtype

    def __repr__(self):
        return (f"LocalShard(block={self.array.shape}, "
                f"global={self.global_shape})")


def _host_value(v):
    """One scope value -> np.ndarray | LocalShard | None (skip)."""
    if v is None:
        return None
    # jax array (duck-typed; see executor._is_jax_array)
    if hasattr(v, "sharding") and hasattr(v, "dtype"):
        if getattr(v, "is_fully_addressable", True):
            return np.asarray(v)
        # multi-process global array: gather the addressable blocks
        blocks = {}
        for s in v.addressable_shards:
            idx = s.index[0] if s.index else slice(None)
            start = idx.start or 0 if isinstance(idx, slice) else 0
            blocks[start] = s.data
        parts = [np.asarray(blocks[k]) for k in sorted(blocks)]
        if len(parts) == 1 and parts[0].shape == tuple(v.shape):
            return parts[0]  # replicated across this process's devices
        return LocalShard(np.concatenate(parts, axis=0), v.shape)
    try:
        arr = np.asarray(v)
    except Exception:
        return None
    if arr.dtype == object:
        return None
    return arr


def snapshot_scope(scope, var_names: Optional[Sequence[str]] = None
                   ) -> Dict[str, object]:
    """Copy the scope's state to host.  ``var_names=None`` takes every
    local variable (parameters, optimizer slots, AMP loss-scale state,
    the RNG key — the executor writes nothing else back).

    Pipelined dispatch: every live Executor's in-flight window is
    drained first, so the snapshot captures a quiescent, bitwise-
    consistent state (and any pending NaN-scan raises BEFORE a poisoned
    checkpoint is written)."""
    try:
        from ..framework.executor import drain_all as _drain_all

        _drain_all()
    except ImportError:  # pragma: no cover - partial installs
        pass
    if var_names is None:
        var_names = [n for n in scope.local_var_names()]
    out: Dict[str, object] = {}
    for n in var_names:
        hv = _host_value(scope.get_var(n) if scope.has_var(n) else None)
        if hv is not None:
            out[n] = hv
    return out


def restore_scope(scope, state: Dict[str, np.ndarray],
                  var_names: Optional[Sequence[str]] = None) -> list:
    """Write restored host arrays into the scope.  Values go in as
    uncommitted np arrays: the next executor run places (and shards)
    them per the compiled step's input specs, so a checkpoint written on
    one topology restores onto any other."""
    names = set(var_names) if var_names is not None else None
    restored = []
    for n, v in state.items():
        if names is not None and n not in names:
            continue
        scope.set_var(n, v)
        restored.append(n)
    return restored
