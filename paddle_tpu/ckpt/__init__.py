"""``paddle_tpu.ckpt`` — asynchronous, atomic checkpointing.

The production checkpoint subsystem (SURVEY §5 failure-recovery row,
beyond the reference's blocking ``save_persistables``):

- :class:`CheckpointManager` — async background writes, atomic
  tmp+manifest+rename commits, SHA-256 integrity, retention GC,
  pending-save coalescing, per-rank sharded multi-process commit.
- :func:`snapshot_scope` / :class:`LocalShard` — device->host state
  extraction on the step boundary (the only blocking part of a save).
- :class:`ResumableIterator` — data-iterator position as checkpoint
  state, so resume continues the exact batch sequence.
- :class:`KVBarrier` — commit barrier over the fleet KV HTTP server.
- :func:`wait_all` — drain every live manager (``Executor.close()`` and
  interpreter exit call this; a shutdown never abandons a queued save).

``paddle_tpu.distributed.checkpoint`` (``save_sharded``/``load_sharded``),
``paddle_tpu.incubate.checkpoint.auto_checkpoint`` and
``hapi.callbacks.ModelCheckpoint`` are all built on this manager.
"""
from .data import ResumableIterator
from .manager import CheckpointError, CheckpointManager, KVBarrier, wait_all
from .state import LocalShard, restore_scope, snapshot_scope

__all__ = [
    "CheckpointManager", "CheckpointError", "KVBarrier", "wait_all",
    "LocalShard", "snapshot_scope", "restore_scope", "ResumableIterator",
]
