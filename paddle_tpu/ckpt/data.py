"""Resumable data-iterator position for full-state checkpoints.

The reference's auto-checkpoint restores parameters but restarts the
input pipeline from scratch, so a resumed run re-reads batches it
already trained on.  :class:`ResumableIterator` wraps any re-iterable
loader (``paddle_tpu.io.DataLoader``, a list of batches, ...) into an
endless batch stream that tracks ``(epoch, batch)``; its state rides a
:class:`~paddle_tpu.ckpt.CheckpointManager` save (register it as a
component) and restore fast-forwards the underlying loader to the exact
position, so the resumed feed sequence is bitwise the uninterrupted
one.  Determinism contract: the loader must produce the same batch
sequence per epoch (shuffle off, a seeded sampler, or a sampler with
``set_epoch`` — which is called with each epoch number).
"""
from __future__ import annotations

from typing import Optional

__all__ = ["ResumableIterator"]


class ResumableIterator:
    def __init__(self, loader):
        self._loader = loader
        self.epoch = 0
        self.batch = 0          # batches already consumed this epoch
        self._it = None
        self._skip = 0

    # -- iteration --------------------------------------------------------
    def _start_epoch(self) -> None:
        sampler = getattr(self._loader, "batch_sampler", None)
        if sampler is not None and hasattr(sampler, "set_epoch"):
            sampler.set_epoch(self.epoch)
        self._it = iter(self._loader)

    def __iter__(self):
        return self

    def __next__(self):
        if self._it is None:
            self._start_epoch()
            skip, self._skip = self._skip, 0
            for done in range(skip):  # fast-forward after a restore
                try:
                    next(self._it)
                except StopIteration:
                    # The loader is shorter than it was at save time
                    # (dataset shrank / different loader): surfacing the
                    # bare StopIteration would silently END the
                    # consumer's for-loop instead of flagging the stale
                    # checkpoint state.
                    from .manager import CheckpointError

                    # leave a coherent position: a caller that catches
                    # this and keeps iterating restarts THIS epoch from
                    # batch 0 (not half-consumed with a stale counter)
                    self.batch = 0
                    self._it = None
                    raise CheckpointError(
                        f"resume fast-forward exhausted the loader "
                        f"after {done} of {skip} batches (epoch "
                        f"{self.epoch}): the restored iterator position "
                        f"does not fit the current loader") from None
        try:
            b = next(self._it)
        except StopIteration:
            self.epoch += 1
            self.batch = 0
            self._start_epoch()
            b = next(self._it)  # an empty loader raises StopIteration
        self.batch += 1
        return b

    # -- checkpoint component contract ------------------------------------
    def state_dict(self) -> dict:
        return {"epoch": self.epoch, "batch": self.batch}

    def set_state_dict(self, state: Optional[dict]) -> None:
        state = state or {}
        self.epoch = int(state.get("epoch", 0))
        self.batch = int(state.get("batch", 0))
        self._it = None
        self._skip = self.batch
