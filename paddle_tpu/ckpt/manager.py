"""Asynchronous, atomic checkpoint manager with retention + integrity.

Role parity: the reference's checkpoint story is synchronous
``save_persistables`` plus the incubate auto-checkpoint hook — a save
blocks the step loop for the full serialize+write, a crash mid-write
leaves a directory indistinguishable from a checkpoint, and nothing
prunes old snapshots.  This module is the production replacement
(SURVEY §5 failure-recovery row):

- **Async**: ``save(step, scope=...)`` snapshots device state to host on
  the caller's thread (the only blocking part — one device_get copy),
  then hands serialization + file writes to a background writer thread;
  the step loop continues immediately.  A queued-but-unstarted save is
  COALESCED away when a newer one arrives (the newest state wins; the
  writer never falls behind unboundedly).  Coalescing applies to
  single-process managers only — with ``world_size > 1`` pending saves
  queue strictly FIFO, because the commit barriers require every rank's
  writer to execute the identical step sequence and a rank-local drop
  decision would desynchronize them.
- **Atomic**: shards are written into ``step_<N>.tmp``; the commit
  fsyncs every file, writes a SHA-256 manifest of every shard, fsyncs
  it, and renames the directory to ``step_<N>``.  A crash at ANY point
  before the rename leaves only a ``.tmp`` directory that restore never
  looks at; corruption after the rename is caught by the manifest hash
  check.
- **Integrity + fallback**: ``restore()`` validates the manifest
  (existence, size, SHA-256 of every file) and automatically falls back
  to the newest *intact* step when the latest is torn or corrupt.
- **Retention**: ``keep_n`` newest steps plus every
  ``keep_every_n_steps`` multiple survive GC; stale ``.tmp`` leftovers
  from crashed runs are swept too.
- **Multi-process**: every rank writes exactly its own shard file
  (``shard_r<k>.npz`` + ``meta_r<k>.json``); rank 0 commits — hash,
  manifest, rename — only after a barrier confirms all ranks finished
  writing (the fleet KV HTTP server doubles as the barrier transport
  via :class:`KVBarrier`; multi-host jax runs default to
  ``sync_global_devices``).

Observability: ``ckpt/snapshot|serialize|write|commit`` tracer spans,
``ckpt_save_blocking_seconds`` / ``ckpt_write_seconds`` histograms, and
``ckpt_bytes_written`` / ``ckpt_saves`` / ``ckpt_save_failures`` /
``ckpt_saves_coalesced`` / ``ckpt_restores`` / ``ckpt_restore_fallbacks``
/ ``ckpt_gc_removed`` counters — all exported on ``/metrics``.
"""
from __future__ import annotations

import collections
import hashlib
import json
import logging
import os
import re
import shutil
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..framework import flags as _flags
from .state import LocalShard, restore_scope, snapshot_scope

logger = logging.getLogger(__name__)

__all__ = ["CheckpointManager", "CheckpointError", "KVBarrier", "wait_all"]

_STEP_RE = re.compile(r"^step_(\d+)$")
_TMP_RE = re.compile(r"^step_(\d+)\.tmp$")
_MANIFEST = "MANIFEST.json"
# FIFO (multi-rank) backlog cap: save() blocks once this many snapshots
# are pending, bounding host memory when the writer falls behind
_MAX_PENDING_SAVES = 2


class CheckpointError(RuntimeError):
    """A checkpoint could not be written or no intact one restored."""


# every live manager, so Executor.close()/atexit can drain pending saves
_LIVE: "weakref.WeakSet[CheckpointManager]" = weakref.WeakSet()


def wait_all(raise_errors: bool = True) -> None:
    """Drain pending async saves of every live manager (the
    ``Executor.close()`` / interpreter-exit hook: a shutdown must never
    abandon a queued snapshot mid-write)."""
    for m in list(_LIVE):
        try:
            m.wait()
        except CheckpointError:
            if raise_errors:
                raise
            logger.exception("checkpoint drain failed for %s", m.dirname)


def _atexit_drain():  # pragma: no cover - interpreter teardown
    wait_all(raise_errors=False)


import atexit  # noqa: E402

atexit.register(_atexit_drain)


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _fsync_dir(path: str) -> None:
    if not _flags.flag("ckpt_fsync"):
        return
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # platforms without directory fds
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _np_restore_dtype(arr: np.ndarray, dtype_str: str) -> np.ndarray:
    """npz round-trips extended dtypes (bfloat16) as raw void bytes —
    view-cast back through the recorded dtype string."""
    if str(arr.dtype) == dtype_str:
        return arr
    try:
        want = np.dtype(dtype_str)
    except TypeError:
        try:
            import ml_dtypes  # registers bfloat16/float8 with numpy

            want = np.dtype(getattr(ml_dtypes, dtype_str))
        except (ImportError, AttributeError):
            return arr
    return arr.view(want)


class KVBarrier:
    """Rendezvous over the fleet KV HTTP server: every rank PUTs
    ``ckpt_barrier/<prefix><tag>:g<gen>/<rank>`` and polls until all
    ranks arrived.

    ``gen`` counts prior uses of the SAME tag by this instance, so a
    tag — e.g. a re-save of the same step — never reuses live keys
    within a process lifetime.  Per-tag (not a global call counter) on
    purpose: after an asymmetric save failure one rank has consumed
    fewer barrier calls than the others, and a global counter would
    desynchronize every subsequent tag permanently; per-tag counts
    re-align as soon as a fresh tag comes along.  Keys from two
    completed barriers back are swept by rank 0 (any rank completing a
    later barrier has provably passed the earlier one, so its keys can
    have no readers left).  Across a crash+restart
    against a long-lived KV server, pass a run-unique ``prefix`` (job
    id, launch timestamp) to make stale keys unreachable; without one,
    a restart whose (tag, gen) collides with the crashed run's can at
    worst time out — the commit protocol never renames before the
    post-write barrier, so staleness degrades to a failed save, not a
    torn checkpoint.

    ``dead_ranks_fn`` (optional) wires the health plane in: a zero-arg
    callable returning the currently dead-listed ranks (e.g.
    ``fleet.elastic.dead_ranks_from_cluster(url)``).  A barrier whose
    expected world SHRANK mid-wait — a participant died — then fails
    fast with the missing rank NAMED instead of burning the full
    deadline; the elastic supervisor classifies that as a topology
    change and re-shards."""

    def __init__(self, endpoint: str, rank: int, world_size: int,
                 timeout: float = 120.0, prefix: str = "",
                 dead_ranks_fn: Optional[Callable[[], Sequence[int]]]
                 = None):
        self.endpoint = endpoint.rstrip("/")
        if not self.endpoint.startswith("http"):
            self.endpoint = "http://" + self.endpoint
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.timeout = float(timeout)
        self.prefix = (prefix + ":") if prefix else ""
        self.dead_ranks_fn = dead_ranks_fn
        self._tag_gens: Dict[str, int] = {}
        self._past_tags: list = []

    def _url(self, tag: str, rank: int) -> str:
        return f"{self.endpoint}/ckpt_barrier/{self.prefix}{tag}/{rank}"

    def __call__(self, tag: str) -> None:
        import urllib.error
        import urllib.request

        gen = self._tag_gens.get(tag, 0)
        self._tag_gens[tag] = gen + 1
        gen_tag = f"{tag}:g{gen}"
        deadline = time.monotonic() + self.timeout
        # URLError (connection refused/reset — the KV server restarting
        # or not up yet) is as transient as a 404 HTTPError: retry both
        # until the deadline instead of failing the whole save.
        req = urllib.request.Request(self._url(gen_tag, self.rank),
                                     data=b"1", method="PUT")
        while True:
            try:
                # clamp to the remaining deadline: a stalled-but-
                # accepting server would otherwise hold a sub-5s
                # barrier budget for the full socket timeout
                urllib.request.urlopen(req, timeout=min(
                    5.0, max(0.1, deadline - time.monotonic())))
                break
            except (urllib.error.URLError, TimeoutError) as e:
                if time.monotonic() >= deadline:
                    raise CheckpointError(
                        f"KVBarrier {gen_tag!r}: cannot announce to KV "
                        f"server {self.endpoint} after {self.timeout}s: "
                        f"{e}") from e
                time.sleep(0.05)
        missing = set(range(self.world_size))
        last_dead_check = 0.0
        while missing:
            for r in sorted(missing):
                try:
                    urllib.request.urlopen(
                        self._url(gen_tag, r),
                        timeout=min(5.0, max(
                            0.1, deadline - time.monotonic())))
                    missing.discard(r)
                except (urllib.error.URLError, TimeoutError):
                    pass
            if not missing:
                break
            # participant loss: once the health plane dead-lists a
            # rank we are still waiting on, the barrier can NEVER
            # complete — fail fast with the rank named (throttled:
            # dead_ranks_fn may be an HTTP poll)
            if self.dead_ranks_fn is not None and \
                    time.monotonic() - last_dead_check >= 0.25:
                last_dead_check = time.monotonic()
                try:
                    dead = {int(x) for x in (self.dead_ranks_fn() or ())}
                except Exception:  # noqa: BLE001 - no evidence,
                    dead = set()   # no verdict
                lost = sorted(dead & missing)
                if lost:
                    raise CheckpointError(
                        f"KVBarrier {gen_tag!r}: rank(s) {lost} "
                        f"dead-listed by the health plane while still "
                        f"missing from the barrier "
                        f"(world={self.world_size}); failing fast "
                        f"instead of waiting out the {self.timeout}s "
                        f"deadline")
            if time.monotonic() >= deadline:
                raise CheckpointError(
                    f"KVBarrier {gen_tag!r}: ranks {sorted(missing)} "
                    f"missing after {self.timeout}s "
                    f"(world={self.world_size})")
            time.sleep(0.02)
        # deferred cleanup: sweep the barrier TWO completed barriers
        # back.  Every rank trims its list (it would otherwise grow
        # unbounded over a long run); only rank 0 issues the DELETEs.
        self._past_tags.append((tag, gen_tag))
        if len(self._past_tags) > 2:
            old_tag, old_gen_tag = self._past_tags.pop(0)
            # the swept barrier's server keys are gone, so its gen
            # count can go too (manager tags are job-unique — keeping
            # every count would leak one entry per barrier for the
            # process lifetime).  Keep it while a LATER use of the same
            # tag is still live, so a reset can't re-mint its gen.
            # Rank 0 additionally keeps the count when a DELETE failed:
            # stale arrival keys + a re-minted gen would release a
            # reused tag's barrier EARLY on the polling ranks, but the
            # committer polling a gen nobody PUT just times out — the
            # failure mode stays a failed save, never a bad commit.
            swept = True
            if self.rank == 0:
                for r in range(self.world_size):
                    try:
                        # best-effort cleanup after the barrier already
                        # succeeded: clamp to the leftover deadline so a
                        # stalled server can't hold the writer ~5s per
                        # rank past the configured budget
                        urllib.request.urlopen(urllib.request.Request(
                            self._url(old_gen_tag, r), method="DELETE"),
                            timeout=min(5.0, max(
                                0.5, deadline - time.monotonic())))
                    except (urllib.error.URLError, TimeoutError):
                        swept = False
            if swept and all(t != old_tag for t, _ in self._past_tags):
                self._tag_gens.pop(old_tag, None)


def _default_barrier(tag: str) -> None:
    """Multi-host jax runs rendezvous through the coordination service;
    single-process runs need no barrier."""
    try:
        import jax

        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(f"ckpt:{tag}")
    except ImportError:  # pragma: no cover
        pass


class _Job:
    __slots__ = ("step", "state", "host_state")

    def __init__(self, step, state, host_state):
        self.step = int(step)
        self.state = state
        self.host_state = host_state


class CheckpointManager:
    """See module docstring.  ``keep_n=None`` / ``async_save=None``
    default from ``FLAGS_ckpt_keep_n`` / ``FLAGS_ckpt_async_save``
    (``keep_n=0`` keeps everything)."""

    def __init__(self, dirname: str, keep_n: Optional[int] = None,
                 keep_every_n_steps: Optional[int] = None,
                 async_save: Optional[bool] = None,
                 rank: Optional[int] = None,
                 world_size: Optional[int] = None,
                 barrier: Optional[Callable[[str], None]] = None):
        self.dirname = os.path.abspath(dirname)
        self.keep_n = int(_flags.flag("ckpt_keep_n") if keep_n is None
                          else keep_n)
        self.keep_every_n_steps = (int(keep_every_n_steps)
                                   if keep_every_n_steps else None)
        self.async_save = bool(_flags.flag("ckpt_async_save")
                               if async_save is None else async_save)
        self._rank = rank
        self._world = world_size
        self._barrier = barrier if barrier is not None else _default_barrier
        self._components: Dict[str, object] = {}
        self._fault_hook: Optional[Callable[[str, int], None]] = None
        self._cond = threading.Condition()
        self._queue: "collections.deque[_Job]" = collections.deque()
        self._active: Optional[_Job] = None
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        # count of jobs RUN (not queued): in lockstep on every rank —
        # the queue is strictly FIFO for world>1 and a job that fails
        # INSIDE _run_job still consumed its sequence number on all
        # ranks — so it stamps the barrier tags and a re-save of a
        # failed step can never collide with the stale half-used tags
        # of the first attempt.  Known liveness limit: a save that
        # fails on one rank BEFORE its job runs (snapshot error, closed
        # race) leaves that rank a seq behind; later saves then fail
        # loudly by barrier timeout until process restart.  Commits are
        # never corrupted by this — rank 0 only renames after its
        # barriers pass.
        self._job_seq = 0
        _LIVE.add(self)

    # -- topology ---------------------------------------------------------
    @property
    def rank(self) -> int:
        if self._rank is not None:
            return self._rank
        try:
            import jax

            return jax.process_index()
        except ImportError:  # pragma: no cover
            return 0

    @property
    def world_size(self) -> int:
        if self._world is not None:
            return self._world
        try:
            import jax

            return jax.process_count()
        except ImportError:  # pragma: no cover
            return 1

    # -- test/fault-injection hook ---------------------------------------
    def set_fault_hook(self, fn: Optional[Callable[[str, int], None]]):
        """``fn(phase, step)`` is called from the WRITER thread at
        ``serialize`` / ``write_shard`` / ``pre_commit`` / ``post_commit``.
        Raising simulates a crash at that point (the torn ``.tmp`` state
        is left on disk exactly as a killed process would leave it)."""
        self._fault_hook = fn

    def _fault(self, phase: str, step: int) -> None:
        if self._fault_hook is not None:
            self._fault_hook(phase, step)

    # -- host-side components (LR scheduler, data iterator, ...) ---------
    def register(self, name: str, obj) -> None:
        """Attach a host-side component exposing ``state_dict()`` /
        ``set_state_dict()`` (LRScheduler, ResumableIterator, AMP
        grad-scaler wrappers...).  Its JSON state rides every save and
        is pushed back on restore."""
        for attr in ("state_dict", "set_state_dict"):
            if not hasattr(obj, attr):
                raise TypeError(
                    f"component {name!r} must expose state_dict/"
                    f"set_state_dict (got {type(obj).__name__})")
        self._components[name] = obj

    # -- save -------------------------------------------------------------
    def save(self, step: int, scope=None, var_names=None, state=None,
             host_state: Optional[dict] = None, wait: bool = False
             ) -> List[str]:
        """Checkpoint ``step``.  Exactly one of ``scope`` (device state
        extracted via :func:`snapshot_scope`) or ``state`` (a ready
        name->array dict) supplies the payload.  Returns the saved
        variable names.  With ``async_save`` the call returns as soon as
        the host snapshot exists; a prior background failure is reported
        on ``wait()``/``close()`` (and counted on ``/metrics``), never
        raised here."""
        from ..monitor import stat_time
        from ..observe import tracer as otrace

        if self._closed:
            raise CheckpointError("CheckpointManager is closed")
        t0 = time.perf_counter()
        if state is None:
            if scope is None:
                from ..framework.scope import global_scope

                scope = global_scope()
            with otrace.span("ckpt/snapshot", step=int(step)):
                state = snapshot_scope(scope, var_names)
        if self.world_size == 1:
            # a partial shard in a single-process manager would commit a
            # checkpoint missing every other rank's block — restore's
            # re-assembly check rejects it, but only at resume time.
            # Fail the SAVE instead of silently writing a dead snapshot
            # (e.g. rank-0-local auto-checkpoint over ZeRO-sharded
            # state: use distributed.checkpoint.save_sharded there).
            for name, v in state.items():
                if isinstance(v, LocalShard) \
                        and tuple(v.array.shape) != tuple(v.global_shape):
                    raise CheckpointError(
                        f"var {name!r} is a partial shard "
                        f"({v.array.shape} of global {v.global_shape}) "
                        f"but this manager has world_size=1: the other "
                        f"ranks' blocks would never be written and the "
                        f"checkpoint could not restore. Save "
                        f"multi-process-sharded state through a manager "
                        f"with rank/world_size set on every rank "
                        f"(distributed.checkpoint.save_sharded)")
        host = dict(host_state or {})
        if self._components:
            host["components"] = {n: c.state_dict()
                                  for n, c in self._components.items()}
        job = _Job(step, state, host)
        # flight-record the ACCEPTANCE separately from the commit
        # (ckpt/commit, in _run_job): a save that enqueues but never
        # commits is exactly the kind of hang the recorder exists for
        from ..observe import flight as _flight

        _flight.record("ckpt/save", step=int(step), vars=len(state),
                       async_save=self.async_save)
        if not self.async_save:
            self._run_job(job)
            stat_time("ckpt_save_blocking_seconds",
                      time.perf_counter() - t0)
            return sorted(state)
        # Coalescing is a per-rank timing decision, so it is only safe
        # when this manager is the sole committer: with world>1 the
        # commit barriers assume every rank's writer executes the
        # identical step sequence, and rank A dropping a step rank B
        # already started would deadlock the barrier.  Multi-rank
        # managers therefore queue strictly FIFO.
        can_coalesce = self.world_size == 1
        with self._cond:
            if self._closed:
                # the entry check at the top of save() is unlocked; a
                # close() racing the snapshot could otherwise see us
                # enqueue onto a closed (no longer drained) manager
                raise CheckpointError("CheckpointManager is closed")
            if can_coalesce and self._queue:
                # coalesce: the unstarted stale save is superseded
                # (coalescing keeps the queue depth at <= 1)
                from ..monitor import stat_add

                stale = self._queue.pop()
                stat_add("ckpt_saves_coalesced")
                logger.info("ckpt: coalescing pending save of step %d "
                            "under newer step %d", stale.step, job.step)
            elif not can_coalesce:
                # FIFO needs explicit backpressure: each _Job holds a
                # full host snapshot, so an unbounded backlog on a slow
                # filesystem would exhaust host RAM.  Blocking here is
                # rank-symmetric — every rank issues the identical save
                # sequence, so all ranks block at the same save index.
                while len(self._queue) >= _MAX_PENDING_SAVES \
                        and not self._closed:
                    self._cond.wait(timeout=0.1)
                if self._closed:
                    # close() won the race: enqueueing now would spawn a
                    # writer on a closed manager (out of _LIVE, never
                    # drained) and silently lose the checkpoint
                    raise CheckpointError("CheckpointManager is closed")
            self._queue.append(job)
            self._ensure_thread()
            self._cond.notify_all()
        stat_time("ckpt_save_blocking_seconds", time.perf_counter() - t0)
        if wait:
            self.wait()
        return sorted(state)

    def wait(self) -> None:
        """Barrier: block until no save is queued or in flight; re-raise
        the first background failure."""
        with self._cond:
            while self._queue or self._active is not None:
                self._cond.wait(timeout=0.1)
            err, self._error = self._error, None
        if err is not None:
            raise CheckpointError(
                f"background checkpoint save failed: {err}") from err

    def close(self) -> None:
        """Drain pending saves and stop the writer thread."""
        try:
            self.wait()
        finally:
            with self._cond:
                self._closed = True
                self._cond.notify_all()
            if self._thread is not None:
                self._thread.join(timeout=5)
                self._thread = None
            _LIVE.discard(self)

    # -- writer thread ----------------------------------------------------
    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._writer_loop, name="ckpt-writer", daemon=True)
            self._thread.start()

    def _writer_loop(self) -> None:
        from ..monitor import stat_add

        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait(timeout=0.25)
                if self._closed and not self._queue:
                    return
                self._active = job = self._queue.popleft()
                self._cond.notify_all()  # free a backpressure-blocked save()
            try:
                self._run_job(job)
            except BaseException as e:  # noqa: BLE001 - writer survives
                stat_add("ckpt_save_failures")
                from ..observe import flight as _flight

                _flight.record("ckpt/save_error", step=int(job.step),
                               error=f"{type(e).__name__}: {e}"[:500])
                logger.exception(
                    "ckpt: background save of step %d failed (torn "
                    ".tmp left for inspection; restore() will fall "
                    "back to the previous intact step)", job.step)
                with self._cond:
                    if self._error is None:
                        self._error = e
            finally:
                with self._cond:
                    self._active = None
                    self._cond.notify_all()

    # -- the actual write -------------------------------------------------
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.dirname, f"step_{int(step)}")

    def _run_job(self, job: _Job) -> None:
        from ..monitor import stat_add, stat_time
        from ..observe import tracer as otrace

        t0 = time.perf_counter()
        rank, world = self.rank, self.world_size
        seq, self._job_seq = self._job_seq, self._job_seq + 1
        tag = f"{job.step}:j{seq}"
        tmp = self._step_dir(job.step) + ".tmp"
        final = self._step_dir(job.step)
        if rank == 0:
            if os.path.isdir(tmp):
                shutil.rmtree(tmp)
            os.makedirs(tmp, exist_ok=True)
        if world > 1:
            self._barrier(f"mkdir:{tag}")
            os.makedirs(tmp, exist_ok=True)  # racing mkdir is fine

        self._fault("serialize", job.step)
        # rank>0 contributes only ITS shards; replicated/full values are
        # written once, by rank 0
        payload: Dict[str, np.ndarray] = {}
        var_meta: Dict[str, dict] = {}
        with otrace.span("ckpt/serialize", step=job.step,
                         vars=len(job.state)):
            for name, v in job.state.items():
                if isinstance(v, LocalShard):
                    payload[name] = v.array
                    var_meta[name] = {
                        "dtype": str(v.array.dtype),
                        "shape": list(v.array.shape),
                        "sharded": True,
                        "global_shape": list(v.global_shape),
                    }
                    if v.origin is not None:
                        # non-axis-0 / 2D block (tensor-parallel
                        # NamedSharding layouts): restore places the
                        # block at this offset instead of concatenating
                        # rank blocks along axis 0
                        var_meta[name]["origin"] = list(v.origin)
                elif rank == 0:
                    arr = np.asarray(v)
                    payload[name] = arr
                    var_meta[name] = {"dtype": str(arr.dtype),
                                      "shape": list(arr.shape),
                                      "sharded": False}

        shard_name = f"shard_r{rank}.npz"
        meta_name = f"meta_r{rank}.json"
        shard_path = os.path.join(tmp, shard_name)
        with otrace.span("ckpt/write", step=job.step,
                         bytes=sum(a.nbytes for a in payload.values())):
            self._fault("write_shard", job.step)
            with open(shard_path, "wb") as f:
                np.savez(f, **payload)
                f.flush()
                if _flags.flag("ckpt_fsync"):
                    os.fsync(f.fileno())
            meta = {"format": 1, "step": job.step, "rank": rank,
                    "world_size": world, "shard": shard_name,
                    "vars": var_meta}
            if rank == 0:
                meta["host_state"] = job.host_state
                meta["created_unix"] = time.time()
            mp = os.path.join(tmp, meta_name)
            with open(mp, "w") as f:
                json.dump(meta, f)
                f.flush()
                if _flags.flag("ckpt_fsync"):
                    os.fsync(f.fileno())

        # -- commit: all ranks durable -> rank 0 manifests + renames ----
        with otrace.span("ckpt/commit", step=job.step):
            if world > 1:
                self._barrier(f"written:{tag}")
            if rank == 0:
                self._fault("pre_commit", job.step)
                files = {}
                for fname in sorted(os.listdir(tmp)):
                    p = os.path.join(tmp, fname)
                    files[fname] = {"sha256": _sha256(p),
                                    "bytes": os.path.getsize(p)}
                manifest = {"format": 1, "step": job.step,
                            "world_size": world, "files": files}
                mpath = os.path.join(tmp, _MANIFEST)
                with open(mpath, "w") as f:
                    json.dump(manifest, f)
                    f.flush()
                    if _flags.flag("ckpt_fsync"):
                        os.fsync(f.fileno())
                _fsync_dir(tmp)
                if os.path.isdir(final):  # re-save of an existing step
                    shutil.rmtree(final)
                os.rename(tmp, final)
                _fsync_dir(self.dirname)
                self._fault("post_commit", job.step)
            if world > 1:
                # save() callers on every rank return only once the
                # checkpoint is visible
                self._barrier(f"committed:{tag}")

        dt = time.perf_counter() - t0
        stat_time("ckpt_write_seconds", dt)
        stat_add("ckpt_saves")
        stat_add("ckpt_bytes_written",
                 sum(a.nbytes for a in payload.values()))
        from ..observe import flight as _flight

        _flight.record("ckpt/commit", step=int(job.step), rank=rank,
                       write_seconds=round(dt, 4),
                       bytes=sum(a.nbytes for a in payload.values()))
        if rank == 0:
            self._gc(current_step=job.step)

    # -- retention --------------------------------------------------------
    def _gc(self, current_step: int) -> None:
        from ..monitor import stat_add

        steps = self.all_steps()
        keep = set(steps if self.keep_n <= 0 else steps[-self.keep_n:])
        if self.keep_every_n_steps:
            keep |= {s for s in steps
                     if s % self.keep_every_n_steps == 0}
        keep.add(current_step)
        removed = 0
        for s in steps:
            if s not in keep:
                shutil.rmtree(self._step_dir(s), ignore_errors=True)
                removed += 1
        # stale .tmp leftovers from crashed runs — ANY step, including
        # ones ahead of the resumed position (a crash at step 100
        # resumed from 90 must not park a full-size torn dir until
        # training passes 100 again).  The writer is serial, so the
        # only live tmp — this job's — has already been renamed.
        try:
            entries = os.listdir(self.dirname)
        except OSError:
            entries = []
        for e in entries:
            if _TMP_RE.match(e):
                shutil.rmtree(os.path.join(self.dirname, e),
                              ignore_errors=True)
                removed += 1
        if removed:
            stat_add("ckpt_gc_removed", removed)

    # -- discovery / validation ------------------------------------------
    def all_steps(self) -> List[int]:
        """Committed (renamed) step numbers, ascending.  Intactness is
        judged at restore time."""
        try:
            entries = os.listdir(self.dirname)
        except OSError:
            return []
        out = []
        for e in entries:
            m = _STEP_RE.match(e)
            if m and os.path.isdir(os.path.join(self.dirname, e)):
                out.append(int(m.group(1)))
        return sorted(out)

    def next_step(self) -> int:
        steps = self.all_steps()
        return (steps[-1] + 1) if steps else 0

    def validate(self, step: int) -> Tuple[bool, str]:
        """Manifest check for one committed step: every listed file must
        exist with matching size and SHA-256."""
        d = self._step_dir(step)
        mpath = os.path.join(d, _MANIFEST)
        if not os.path.isfile(mpath):
            return False, "missing MANIFEST.json"
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            return False, f"unreadable manifest: {e}"
        files = manifest.get("files", {})
        # a commit must carry every writing rank's shard+meta — a
        # manifest hashed while a rank was still writing (a broken
        # barrier) must read as torn, not crash re-assembly later
        for k in range(int(manifest.get("world_size", 1) or 1)):
            if f"meta_r{k}.json" not in files:
                return False, f"manifest lists no rank-{k} meta"
            if f"shard_r{k}.npz" not in files:
                return False, f"manifest lists no rank-{k} shard"
        for fname, rec in files.items():
            p = os.path.join(d, fname)
            if not os.path.isfile(p):
                return False, f"missing file {fname}"
            if os.path.getsize(p) != rec.get("bytes"):
                return False, f"size mismatch on {fname}"
            if _flags.flag("ckpt_verify_restore") \
                    and _sha256(p) != rec.get("sha256"):
                return False, f"hash mismatch on {fname}"
        return True, "ok"

    def latest_intact_step(self) -> Optional[int]:
        for s in reversed(self.all_steps()):
            if self.validate(s)[0]:
                return s
        return None

    # -- restore ----------------------------------------------------------
    def restore(self, scope=None, step: Optional[int] = None,
                var_names: Optional[Sequence[str]] = None
                ) -> Optional[dict]:
        """Load the newest intact checkpoint (or exactly ``step``).

        Falls back — loudly — past torn or corrupt steps.  Returns
        ``None`` when the directory holds no committed checkpoint at
        all; raises :class:`CheckpointError` when checkpoints exist but
        none validates (data present yet unusable must not silently
        become a fresh run).  The returned meta dict carries ``step``,
        ``host_state``, ``vars`` and — when ``scope`` is None —
        ``state`` (the merged host arrays)."""
        from ..monitor import stat_add
        from ..observe import flight as _flight

        steps = self.all_steps()
        if step is not None:
            if step not in steps:
                raise CheckpointError(
                    f"no committed checkpoint for step {step} in "
                    f"{self.dirname} (have {steps or 'none'})")
            candidates = [step]
        else:
            candidates = list(reversed(steps))
        if not candidates:
            return None
        reasons = []
        for s in candidates:
            ok, why = self.validate(s)
            if not ok:
                stat_add("ckpt_restore_fallbacks")
                _flight.record("ckpt/restore_fallback", step=int(s),
                               reason=str(why)[:300])
                logger.warning(
                    "ckpt: step %d in %s is not intact (%s); falling "
                    "back", s, self.dirname, why)
                reasons.append(f"step {s}: {why}")
                continue
            state, host = self._read_step(s)
            meta = {"step": s, "host_state": host,
                    "vars": sorted(state)}
            if scope is not None:
                restore_scope(scope, state, var_names)
            else:
                meta["state"] = state
            comps = (host or {}).get("components", {})
            for name, cstate in comps.items():
                obj = self._components.get(name)
                if obj is not None:
                    obj.set_state_dict(cstate)
            stat_add("ckpt_restores")
            _flight.record("ckpt/restore", step=int(s),
                           vars=len(meta["vars"]))
            return meta
        raise CheckpointError(
            f"no intact checkpoint in {self.dirname}: "
            + "; ".join(reasons))

    def _read_step(self, step: int) -> Tuple[Dict[str, np.ndarray], dict]:
        d = self._step_dir(step)
        metas = []
        for fname in sorted(os.listdir(d)):
            if fname.startswith("meta_r") and fname.endswith(".json"):
                with open(os.path.join(d, fname)) as f:
                    metas.append(json.load(f))
        metas.sort(key=lambda m: m.get("rank", 0))
        host_state = {}
        # name -> {"sharded": bool, parts: [(rank, arr)], dtype}
        merged: Dict[str, np.ndarray] = {}
        shard_parts: Dict[str, List[Tuple[int, np.ndarray]]] = {}
        shard_info: Dict[str, dict] = {}
        # origin-carrying shards (tensor-parallel non-axis-0 / 2D
        # blocks): placed by offset; legacy entries (no origin) keep the
        # axis-0 rank-order concat contract
        origin_parts: Dict[str, List[Tuple[tuple, np.ndarray]]] = {}
        for m in metas:
            if m.get("rank", 0) == 0:
                host_state = m.get("host_state", {}) or {}
            with np.load(os.path.join(d, m["shard"])) as z:
                for name, rec in m.get("vars", {}).items():
                    arr = _np_restore_dtype(z[name], rec["dtype"])
                    if rec.get("sharded") and rec.get("origin") is not None:
                        origin_parts.setdefault(name, []).append(
                            (tuple(int(o) for o in rec["origin"]), arr))
                        shard_info[name] = rec
                    elif rec.get("sharded"):
                        shard_parts.setdefault(name, []).append(
                            (m.get("rank", 0), arr))
                        shard_info[name] = rec
                    else:
                        merged[name] = arr
        for name, parts in shard_parts.items():
            parts.sort(key=lambda p: p[0])
            full = np.concatenate([a for _, a in parts], axis=0)
            want = tuple(shard_info[name].get("global_shape") or ())
            if want and full.shape != want:
                raise CheckpointError(
                    f"sharded var {name!r} re-assembles to {full.shape}, "
                    f"manifest says {want} (rank files inconsistent)")
            merged[name] = full
        for name, parts in origin_parts.items():
            want = tuple(shard_info[name].get("global_shape") or ())
            self._check_origin_coverage(name, parts, want)
            full = np.empty(want, dtype=parts[0][1].dtype)
            for origin, arr in parts:
                sl = tuple(slice(o, o + s)
                           for o, s in zip(origin, arr.shape))
                full[sl] = arr
            merged[name] = full
        return merged, host_state

    @staticmethod
    def _check_origin_coverage(name, parts, want):
        """HOLES mean a rank's contribution is missing — an
        unrestorable value must fail loudly here, not corrupt training
        silently.  NamedSharding blocks are axis-aligned rectangles on
        a regular per-dimension origin grid, so coverage is checked
        arithmetically in O(#blocks) — NOT with a global-shape bool
        mask, which would add a byte per element of peak restore
        memory (25% overhead on an fp32 table)."""
        blocks = {}
        for origin, arr in parts:
            if len(origin) != len(want) or any(
                    o + s > w for o, s, w in zip(origin, arr.shape, want)):
                raise CheckpointError(
                    f"sharded var {name!r}: block {arr.shape} at "
                    f"origin {origin} does not fit global {want}")
            prev = blocks.get(origin)
            if prev is not None and prev != arr.shape:
                raise CheckpointError(
                    f"sharded var {name!r}: conflicting blocks "
                    f"{prev} vs {arr.shape} at origin {origin}")
            blocks[origin] = arr.shape  # replicated dups collapse
        per_dim = [sorted({o[d] for o in blocks})
                   for d in range(len(want))]
        for d, origins in enumerate(per_dim):
            if origins and origins[0] != 0:
                raise CheckpointError(
                    f"sharded var {name!r}: dim {d} grid starts at "
                    f"{origins[0]}, not 0 (missing rank file?)")
        # every grid cell present, each dim's origins+extents tiling
        # [0, want_d] exactly
        import itertools

        for origin in itertools.product(*per_dim):
            shape = blocks.get(origin)
            if shape is None:
                raise CheckpointError(
                    f"sharded var {name!r}: no block at grid origin "
                    f"{origin} of global {want} (missing rank file?)")
            for d, (o, s) in enumerate(zip(origin, shape)):
                nxt = per_dim[d].index(o) + 1
                end = per_dim[d][nxt] if nxt < len(per_dim[d]) \
                    else want[d]
                if o + s != end:
                    raise CheckpointError(
                        f"sharded var {name!r}: block at {origin} "
                        f"spans [{o}, {o + s}) on dim {d} but the "
                        f"grid expects [{o}, {end}) over global "
                        f"{want} (holes or overlap)")
