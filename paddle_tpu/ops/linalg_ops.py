"""Linear-algebra & tensor-math ops.

Reference parity: operators/{cholesky,inverse,addmm,mv,kron,cross,dist,
trace,logsumexp,norm,multiplex,unbind,...}_op.cc — direct jnp/lax
mappings; gradients via the generic vjp fallback (jax ships VJPs for the
decompositions).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.lowering import register_lower


@register_lower("cholesky")
def _cholesky(ctx, op):
    x = ctx.in1(op, "X")
    upper = bool(op.attr("upper", False))
    l = jnp.linalg.cholesky(x)
    ctx.set_out(op, "Out", jnp.swapaxes(l, -1, -2) if upper else l)


@register_lower("inverse")
def _inverse(ctx, op):
    ctx.set_out(op, "Output", jnp.linalg.inv(ctx.in1(op, "Input")))


@register_lower("addmm")
def _addmm(ctx, op):
    inp = ctx.in1(op, "Input")
    x = ctx.in1(op, "X")
    y = ctx.in1(op, "Y")
    alpha = float(op.attr("Alpha", 1.0))
    beta = float(op.attr("Beta", 1.0))
    ctx.set_out(op, "Out", beta * inp + alpha * (x @ y))


@register_lower("mv")
def _mv(ctx, op):
    ctx.set_out(op, "Out", ctx.in1(op, "X") @ ctx.in1(op, "Vec"))


@register_lower("kron")
def _kron(ctx, op):
    ctx.set_out(op, "Out", jnp.kron(ctx.in1(op, "X"), ctx.in1(op, "Y")))


@register_lower("cross")
def _cross(ctx, op):
    x = ctx.in1(op, "X")
    y = ctx.in1(op, "Y")
    dim = op.attr("dim", None)
    if dim is None or int(dim) == -2147483648:  # INT_MIN sentinel: first dim-3
        dim = next(i for i, s in enumerate(x.shape) if s == 3)
    ctx.set_out(op, "Out", jnp.cross(x, y, axis=int(dim)))


@register_lower("dist")
def _dist(ctx, op):
    x = ctx.in1(op, "X")
    y = ctx.in1(op, "Y")
    p = float(op.attr("p", 2.0))
    d = jnp.abs(x - y)
    if p == float("inf"):
        out = jnp.max(d)
    elif p == float("-inf"):
        out = jnp.min(d)
    elif p == 0:
        out = jnp.sum((d != 0).astype(x.dtype))
    else:
        out = jnp.power(jnp.sum(jnp.power(d, p)), 1.0 / p)
    ctx.set_out(op, "Out", out)


@register_lower("trace")
def _trace(ctx, op):
    x = ctx.in1(op, "Input")
    ctx.set_out(op, "Out", jnp.trace(
        x, offset=int(op.attr("offset", 0)),
        axis1=int(op.attr("axis1", 0)), axis2=int(op.attr("axis2", 1))))


@register_lower("logsumexp")
def _logsumexp(ctx, op):
    x = ctx.in1(op, "X")
    axis = op.attr("axis", [0]) or None
    if bool(op.attr("reduce_all", False)):
        axis = None
    else:
        axis = tuple(int(a) for a in axis)
    ctx.set_out(op, "Out", jax.scipy.special.logsumexp(
        x, axis=axis, keepdims=bool(op.attr("keepdim", False))))


@register_lower("norm")
def _norm(ctx, op):
    """L2-normalize along axis (reference norm_op.cc: Out = X / norm)."""
    x = ctx.in1(op, "X")
    axis = int(op.attr("axis", -1))
    eps = float(op.attr("epsilon", 1e-10))
    n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    ctx.set_out(op, "Out", x / n)
    ctx.set_out(op, "Norm", n)


@register_lower("multiplex")
def _multiplex(ctx, op):
    ids = ctx.in1(op, "Ids")  # [N, 1]
    xs = ctx.in_list(op, "X")
    stacked = jnp.stack(xs)  # [K, N, D]
    idx = ids.reshape(-1).astype(jnp.int32)
    out = stacked[idx, jnp.arange(stacked.shape[1])]
    ctx.set_out(op, "Out", out)


@register_lower("unbind")
def _unbind(ctx, op):
    x = ctx.in1(op, "X")
    axis = int(op.attr("axis", 0))
    outs = [jnp.squeeze(s, axis) for s in jnp.split(x, x.shape[axis], axis)]
    for name, val in zip(op.outputs.get("Out", []), outs):
        ctx.set(name, val)


@register_lower("minus")
def _minus(ctx, op):
    ctx.set_out(op, "Out", ctx.in1(op, "X") - ctx.in1(op, "Y"))


@register_lower("partial_sum")
def _partial_sum(ctx, op):
    xs = ctx.in_list(op, "X")
    start = int(op.attr("start_index", 0))
    length = int(op.attr("length", -1))
    end = None if length < 0 else start + length
    ctx.set_out(op, "Out", sum(x[:, start:end] for x in xs))


@register_lower("partial_concat")
def _partial_concat(ctx, op):
    xs = ctx.in_list(op, "X")
    start = int(op.attr("start_index", 0))
    length = int(op.attr("length", -1))
    end = None if length < 0 else start + length
    ctx.set_out(op, "Out", jnp.concatenate([x[:, start:end] for x in xs],
                                           axis=1))


@register_lower("segment_pool")
def _segment_pool(ctx, op):
    x = ctx.in1(op, "X")
    seg = ctx.in1(op, "SegmentIds").astype(jnp.int32)
    pooltype = op.attr("pooltype", "SUM")
    n = x.shape[0]  # segments bounded by row count (static shape)
    if pooltype == "SUM":
        out = jax.ops.segment_sum(x, seg, num_segments=n)
    elif pooltype == "MEAN":
        s = jax.ops.segment_sum(x, seg, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones((x.shape[0],), x.dtype), seg,
                                  num_segments=n)
        out = s / jnp.maximum(cnt, 1.0)[:, None]
    elif pooltype == "MAX":
        out = jax.ops.segment_max(x, seg, num_segments=n)
    else:
        out = jax.ops.segment_min(x, seg, num_segments=n)
    ctx.set_out(op, "Out", out)
    ctx.set_out(op, "SummedIds", jax.ops.segment_sum(
        jnp.ones((x.shape[0], 1), x.dtype), seg, num_segments=n))


def backtrack_beams(ids, parents):
    """Beam ancestry walk shared by gather_tree and beam_search_decode:
    ids/parents [T, B, W] (parents local to each batch's beam group) ->
    re-threaded beams [T, B, W], chronological."""
    t, b, w = ids.shape
    binx = jnp.arange(b)[:, None]

    def step(parent, tup):
        id_t, par_t = tup
        out = id_t[binx, parent]
        nxt = par_t[binx, parent]
        return nxt, out

    init = jnp.tile(jnp.arange(w)[None, :], (b, 1))
    _, outs = jax.lax.scan(step, init, (ids[::-1], parents[::-1]))
    return outs[::-1]


@register_lower("gather_tree")
def _gather_tree(ctx, op):
    """Beam-search ancestry walk (reference gather_tree_op.cc): ids/parents
    [T, B, W] -> full beams re-threaded from the last step backwards."""
    ctx.set_out(op, "Out", backtrack_beams(ctx.in1(op, "Ids"),
                                           ctx.in1(op, "Parents")))
