"""Detection ops: anchors, box coding, IoU, YOLO decoding.

Reference parity: operators/detection/ — the dense, statically-shaped
subset (prior_box, anchor_generator, box_coder, iou_similarity,
yolo_box, box_clip).  NMS-style ops with data-dependent output shapes
live in nms_ops.py as masked fixed-size lowerings.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.lowering import register_lower


@register_lower("prior_box")
def _prior_box(ctx, op):
    """SSD prior boxes (reference detection/prior_box_op.h): per feature-
    map cell, boxes for each (min_size, aspect_ratio) pair + optional
    max_size geometric means."""
    feat = ctx.in1(op, "Input")  # [N, C, H, W]
    image = ctx.in1(op, "Image")  # [N, C, IH, IW]
    min_sizes = [float(s) for s in op.attr("min_sizes", [])]
    max_sizes = [float(s) for s in op.attr("max_sizes", []) or []]
    ars = [float(a) for a in op.attr("aspect_ratios", [1.0])]
    variances = [float(v) for v in op.attr("variances",
                                           [0.1, 0.1, 0.2, 0.2])]
    flip = bool(op.attr("flip", True))
    clip = bool(op.attr("clip", True))
    step_w = float(op.attr("step_w", 0.0))
    step_h = float(op.attr("step_h", 0.0))
    offset = float(op.attr("offset", 0.5))
    min_max_ar_first = bool(op.attr("min_max_aspect_ratios_order", False))

    h, w = feat.shape[2], feat.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    sw = step_w if step_w > 0 else iw / w
    sh = step_h if step_h > 0 else ih / h

    # expanded aspect ratios (reference ExpandAspectRatios: 1.0 first,
    # then each ratio and optionally its flip, deduped)
    out_ars = [1.0]
    for ar in ars:
        if any(abs(ar - e) < 1e-6 for e in out_ars):
            continue
        out_ars.append(ar)
        if flip:
            out_ars.append(1.0 / ar)

    # per-cell (width, height) list in the reference emission order
    whs = []
    for mi, ms in enumerate(min_sizes):
        if min_max_ar_first:
            # reference prior_box_op.h min_max_aspect_ratios_order=True:
            # [min (ar=1), max, remaining aspect ratios] — the layout
            # SSD-caffe checkpoints expect
            whs.append((float(ms), float(ms)))
            if max_sizes:
                mx = max_sizes[mi]
                whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
            for ar in out_ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
            continue
        for ar in out_ars:
            whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        if max_sizes:
            mx = max_sizes[mi]  # positional pairing (duplicates legal)
            whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
    whs = np.asarray(whs, np.float32)  # [P, 2]
    p = whs.shape[0]

    cx = (jnp.arange(w, dtype=jnp.float32) + offset) * sw
    cy = (jnp.arange(h, dtype=jnp.float32) + offset) * sh
    cxg, cyg = jnp.meshgrid(cx, cy)  # [H, W]
    cxg = cxg[..., None]  # [H, W, 1]
    cyg = cyg[..., None]
    bw = jnp.asarray(whs[:, 0]) / 2.0  # [P]
    bh = jnp.asarray(whs[:, 1]) / 2.0
    boxes = jnp.stack([
        (cxg - bw) / iw, (cyg - bh) / ih,
        (cxg + bw) / iw, (cyg + bh) / ih,
    ], axis=-1)  # [H, W, P, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           (h, w, p, 4))
    ctx.set_out(op, "Boxes", boxes)
    ctx.set_out(op, "Variances", var)


@register_lower("anchor_generator")
def _anchor_generator(ctx, op):
    """RCNN anchors — exact reference math (anchor_generator_op.h:53-75):
    rounded base sizes from the stride area, scale by anchor_size/stride,
    -1 half-extents, centers at idx*stride + offset*(stride-1)."""
    feat = ctx.in1(op, "Input")  # [N, C, H, W]
    sizes = [float(s) for s in op.attr("anchor_sizes", [])]
    ars = [float(a) for a in op.attr("aspect_ratios", [])]
    variances = [float(v) for v in op.attr("variances",
                                           [0.1, 0.1, 0.2, 0.2])]
    stride = [float(s) for s in op.attr("stride", [16.0, 16.0])]
    offset = float(op.attr("offset", 0.5))
    h, w = feat.shape[2], feat.shape[3]
    sw, sh = stride[0], stride[1]

    whs = []
    for ar in ars:  # ratio-major loop order (reference idx order)
        for size in sizes:
            base_w = np.round(np.sqrt(sw * sh / ar))
            base_h = np.round(base_w * ar)
            whs.append((size / sw * base_w, size / sh * base_h))
    whs = np.asarray(whs, np.float32)
    p = whs.shape[0]
    cx = jnp.arange(w, dtype=jnp.float32) * sw + offset * (sw - 1)
    cy = jnp.arange(h, dtype=jnp.float32) * sh + offset * (sh - 1)
    cxg, cyg = jnp.meshgrid(cx, cy)
    cxg, cyg = cxg[..., None], cyg[..., None]
    bw = 0.5 * (jnp.asarray(whs[:, 0]) - 1.0)
    bh = 0.5 * (jnp.asarray(whs[:, 1]) - 1.0)
    anchors = jnp.stack([cxg - bw, cyg - bh, cxg + bw, cyg + bh], axis=-1)
    ctx.set_out(op, "Anchors", anchors)
    ctx.set_out(op, "Variances", jnp.broadcast_to(
        jnp.asarray(variances, jnp.float32), (h, w, p, 4)))


@register_lower("iou_similarity")
def _iou_similarity(ctx, op):
    """Pairwise IoU (reference detection/iou_similarity_op.h):
    X [N, 4] vs Y [M, 4] -> [N, M]."""
    x = ctx.in1(op, "X")
    y = ctx.in1(op, "Y")
    box_normalized = bool(op.attr("box_normalized", True))
    d = 0.0 if box_normalized else 1.0

    def area(b):
        return (b[..., 2] - b[..., 0] + d) * (b[..., 3] - b[..., 1] + d)

    xi = x[:, None, :]  # [N, 1, 4]
    yi = y[None, :, :]  # [1, M, 4]
    ix1 = jnp.maximum(xi[..., 0], yi[..., 0])
    iy1 = jnp.maximum(xi[..., 1], yi[..., 1])
    ix2 = jnp.minimum(xi[..., 2], yi[..., 2])
    iy2 = jnp.minimum(xi[..., 3], yi[..., 3])
    iw = jnp.maximum(ix2 - ix1 + d, 0.0)
    ih = jnp.maximum(iy2 - iy1 + d, 0.0)
    inter = iw * ih
    union = area(x)[:, None] + area(y)[None, :] - inter
    ctx.set_out(op, "Out", inter / jnp.maximum(union, 1e-10))


@register_lower("box_coder")
def _box_coder(ctx, op):
    """Encode/decode target boxes against priors (reference
    detection/box_coder_op.h)."""
    prior = ctx.in1(op, "PriorBox")  # [M, 4]
    prior_var = ctx.in1(op, "PriorBoxVar")  # [M, 4] or None
    target = ctx.in1(op, "TargetBox")
    code_type = op.attr("code_type", "encode_center_size")
    box_normalized = bool(op.attr("box_normalized", True))
    axis = int(op.attr("axis", 0))
    d = 0.0 if box_normalized else 1.0

    pw = prior[:, 2] - prior[:, 0] + d
    ph = prior[:, 3] - prior[:, 1] + d
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    if prior_var is not None:
        pv = prior_var
    else:
        # variance may come as the 4-float attr instead of the tensor
        # input (mutually exclusive in the reference; SSD exports use
        # the attr form)
        var_attr = op.attr("variance", []) or []
        if var_attr:
            pv = jnp.broadcast_to(
                jnp.asarray([float(v) for v in var_attr], prior.dtype),
                (prior.shape[0], 4))
        else:
            pv = jnp.ones((prior.shape[0], 4), prior.dtype)

    if "encode" in code_type:
        # target [N, 4] vs priors [M, 4] -> [N, M, 4]
        tw = target[:, 2] - target[:, 0] + d
        th = target[:, 3] - target[:, 1] + d
        tcx = target[:, 0] + tw * 0.5
        tcy = target[:, 1] + th * 0.5
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :] / pv[None, :, 0]
        oy = (tcy[:, None] - pcy[None, :]) / ph[None, :] / pv[None, :, 1]
        ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :])) / pv[None, :, 2]
        oh = jnp.log(jnp.abs(th[:, None] / ph[None, :])) / pv[None, :, 3]
        out = jnp.stack([ox, oy, ow, oh], axis=-1)
    else:
        # decode: target [N, M, 4] deltas against priors broadcast on axis
        if axis == 0:
            pcx_b, pcy_b = pcx[None, :], pcy[None, :]
            pw_b, ph_b = pw[None, :], ph[None, :]
            pv_b = pv[None, :, :]
        else:
            pcx_b, pcy_b = pcx[:, None], pcy[:, None]
            pw_b, ph_b = pw[:, None], ph[:, None]
            pv_b = pv[:, None, :]
        dcx = pv_b[..., 0] * target[..., 0] * pw_b + pcx_b
        dcy = pv_b[..., 1] * target[..., 1] * ph_b + pcy_b
        dw = jnp.exp(pv_b[..., 2] * target[..., 2]) * pw_b
        dh = jnp.exp(pv_b[..., 3] * target[..., 3]) * ph_b
        out = jnp.stack([dcx - dw / 2, dcy - dh / 2,
                         dcx + dw / 2 - d, dcy + dh / 2 - d], axis=-1)
    ctx.set_out(op, "OutputBox", out)


@register_lower("yolo_box")
def _yolo_box(ctx, op):
    """YOLOv3 head decoding (reference detection/yolo_box_op.h)."""
    x = ctx.in1(op, "X")  # [N, A*(5+C), H, W]
    img_size = ctx.in1(op, "ImgSize")  # [N, 2] (h, w) int
    anchors = [int(a) for a in op.attr("anchors", [])]
    class_num = int(op.attr("class_num", 1))
    conf_thresh = float(op.attr("conf_thresh", 0.01))
    downsample = int(op.attr("downsample_ratio", 32))
    clip_bbox = bool(op.attr("clip_bbox", True))
    scale = float(op.attr("scale_x_y", 1.0))
    bias = -0.5 * (scale - 1.0)

    n, c, h, w = x.shape
    a = len(anchors) // 2
    xr = x.reshape(n, a, 5 + class_num, h, w)
    img_h = img_size[:, 0].astype(x.dtype)[:, None, None, None]
    img_w = img_size[:, 1].astype(x.dtype)[:, None, None, None]
    in_h = downsample * h
    in_w = downsample * w

    gx = jnp.arange(w, dtype=x.dtype)[None, None, None, :]
    gy = jnp.arange(h, dtype=x.dtype)[None, None, :, None]
    aw = jnp.asarray(anchors[0::2], x.dtype)[None, :, None, None]
    ah = jnp.asarray(anchors[1::2], x.dtype)[None, :, None, None]

    bx = (gx + jax.nn.sigmoid(xr[:, :, 0]) * scale + bias) * img_w / w
    by = (gy + jax.nn.sigmoid(xr[:, :, 1]) * scale + bias) * img_h / h
    bw = jnp.exp(xr[:, :, 2]) * aw * img_w / in_w
    bh = jnp.exp(xr[:, :, 3]) * ah * img_h / in_h
    conf = jax.nn.sigmoid(xr[:, :, 4])

    x1 = bx - bw / 2
    y1 = by - bh / 2
    x2 = bx + bw / 2
    y2 = by + bh / 2
    if clip_bbox:
        x1 = jnp.maximum(x1, 0.0)
        y1 = jnp.maximum(y1, 0.0)
        x2 = jnp.minimum(x2, img_w - 1)
        y2 = jnp.minimum(y2, img_h - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)  # [N, A, H, W, 4]
    # reference zeroes boxes whose conf < thresh
    keep = (conf >= conf_thresh)[..., None].astype(x.dtype)
    boxes = boxes * keep
    scores = (conf[..., None]
              * jax.nn.sigmoid(jnp.moveaxis(xr[:, :, 5:], 2, -1)))
    scores = scores * keep
    ctx.set_out(op, "Boxes", boxes.reshape(n, a * h * w, 4))
    ctx.set_out(op, "Scores", scores.reshape(n, a * h * w, class_num))


@register_lower("box_clip")
def _box_clip(ctx, op):
    boxes = ctx.in1(op, "Input")  # [N, 4] (single image) or [B, N, 4]
    im_info = ctx.in1(op, "ImInfo")  # [B, 3] (h, w, scale)
    # reference rounds the rescaled extent before the -1
    h = jnp.round(im_info[:, 0] / im_info[:, 2]) - 1.0
    w = jnp.round(im_info[:, 1] / im_info[:, 2]) - 1.0
    if boxes.ndim == 2:
        if im_info.shape[0] != 1:
            raise NotImplementedError(
                "box_clip with a flat [N,4] box tensor and multiple "
                "images needs LoD segments, which dense tensors do not "
                "carry; pass [B,N,4] batched boxes instead")
        h0, w0 = h[0], w[0]
        out = jnp.stack([
            jnp.clip(boxes[:, 0], 0, w0), jnp.clip(boxes[:, 1], 0, h0),
            jnp.clip(boxes[:, 2], 0, w0), jnp.clip(boxes[:, 3], 0, h0),
        ], axis=-1)
    else:
        hb = h[:, None]
        wb = w[:, None]
        out = jnp.stack([
            jnp.clip(boxes[..., 0], 0, wb), jnp.clip(boxes[..., 1], 0, hb),
            jnp.clip(boxes[..., 2], 0, wb), jnp.clip(boxes[..., 3], 0, hb),
        ], axis=-1)
    ctx.set_out(op, "Output", out)


# NMS / proposal / matching ops live in nms_ops.py (masked fixed-size
# lowerings with explicit valid counts).
