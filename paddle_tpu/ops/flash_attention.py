"""Fused flash-attention training op: Pallas fwd/bwd as one custom_vjp.

Role parity: reference operators/fused/multihead_matmul_op.cu plus the
training-side attention chain dist_transformer.py emits (matmul ->
mask-add -> softmax -> matmul).  The serving stack already runs Pallas
paged attention (ops/pallas_decode_attention.py); this module gives the
TRAINING graph the same treatment, as one graph-rewritable op that the
pass machinery anchors (framework/passes.py FlashAttentionPass).

Memory shape, which is the whole point (PR 8 telemetry shows training
attention materializing the [B,H,Sq,Sk] fp32 score tensor in both fwd
and bwd — O(N^2) HBM at the flagship seq lens):

- forward: classic tiled online-softmax — one (BQ,BK) score tile in
  VMEM at a time, running per-row max ``m`` and denominator ``l`` in
  scratch; what survives to HBM is the output plus one (Sq,)-sized
  logsumexp vector per (batch, head) — O(N).
- backward: RECOMPUTES the attention tile-by-tile from (q, k, v, lse)
  instead of saving probabilities.  Two kernels, each accumulating its
  result block in VMEM across the innermost grid axis:
    * dq kernel, grid (B*H, n_q, n_k): k-blocks stream past a resident
      dq accumulator;
    * dk/dv kernel, grid (B*H, n_k, n_q): q-blocks stream past
      resident dk/dv accumulators.
  ``delta = rowsum(do * o)`` is precomputed in plain jnp (one O(N*D)
  pass), matching the standard flash-attention backward split.

The pure-jnp masked-softmax reference (``flash_attention_ref``) is the
CPU/tier-1 default — numerically the same composition the unfused op
chain lowers to, so the FlashAttentionPass rewrite preserves loss to
fp32 roundoff on CPU; the Pallas path is pinned against it in
interpret mode (tests/test_flash_attention.py), per the established
kernel pattern (PR 10/11/13).  The additive mask is a CONSTANT
(padding/causal -1e9 masks): its cotangent is zero, and the graph pass
refuses to fuse chains whose mask wants gradients.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.lowering import register_lower

_NEG_INF = -1e30
_LANES = 128


# ---------------------------------------------------------------------------
# reference (CPU/tier-1 default; the rewrite's numerical oracle)
# ---------------------------------------------------------------------------


def flash_attention_ref(q, k, v, mask=None, *, sm_scale, causal=False):
    """Plain masked-softmax attention over (B, H, S, D): exactly the
    composition the unfused matmul/add/softmax/matmul chain lowers to,
    so a pass rewrite to this path is loss-parity-safe on CPU."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * sm_scale
    if mask is not None:
        s = s + mask.astype(s.dtype)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(cm[None, None], s, jnp.asarray(_NEG_INF, s.dtype))
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


# ---------------------------------------------------------------------------
# Pallas forward: online softmax, saves (out, lse)
# ---------------------------------------------------------------------------


def _bias_spec(bias, h, block_q, block_k, *, q_axis, k_axis):
    """(mode, BlockSpec) for the additive mask in its natural 4-D shape
    — broadcast dims map to block 0 so HBM traffic stays at the mask's
    true size.  ``q_axis``/``k_axis`` say which grid position carries
    the q-block / k-block index (fwd+dq iterate (bh, qb, kb); the dk/dv
    kernel iterates (bh, kb, qb))."""
    import jax.experimental.pallas as pl

    if bias is None:
        return "none", pl.BlockSpec((1, 1, 1, 1), lambda *_: (0, 0, 0, 0))
    bb, bh_, bq, _bk = bias.shape

    def idx(*g):
        b = 0 if bb == 1 else g[0] // h
        hh = 0 if bh_ == 1 else g[0] % h
        return (b, hh, 0 if bq == 1 else g[q_axis], g[k_axis])

    if bq == 1:  # key mask: one row broadcast over all queries
        return "key", pl.BlockSpec((1, 1, 1, block_k), idx)
    return "full", pl.BlockSpec((1, 1, block_q, block_k), idx)


def _causal_run(qb, kb, block_q, block_k):
    return (kb * block_k) <= (qb * block_q + block_q - 1)


def _tile_scores(q, k, bias_ref, bias_mode, qb, kb, sm_scale, causal,
                 block_q, block_k):
    """One (BQ, BK) score tile: qk^T * scale + mask (+ causal)."""
    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32) * sm_scale
    if bias_mode == "key":
        s = s + bias_ref[0, 0, 0].astype(jnp.float32)[None, :]
    elif bias_mode == "full":
        s = s + bias_ref[0, 0].astype(jnp.float32)
    if causal:
        rows = qb * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = kb * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(rows >= cols, s, _NEG_INF)
    return s


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref, m_scr,
                l_scr, acc_scr, *, sm_scale, causal, block_q, block_k,
                n_k, bias_mode):
    import jax.experimental.pallas as pl

    qb = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    run = _causal_run(qb, kb, block_q, block_k) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = _tile_scores(q, k, bias_ref, bias_mode, qb, kb, sm_scale,
                         causal, block_q, block_k)
        m_prev = m_scr[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(kb == n_k - 1)
    def _flush():
        l = l_scr[:, :1]
        safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / safe).astype(o_ref.dtype)
        # per-row softmax statistic the backward recompute needs:
        # lse = m + log(l); fully-masked rows pin to -inf
        lse = jnp.where(l == 0.0, _NEG_INF, m_scr[:, :1] + jnp.log(safe))
        lse_ref[0] = lse[:, 0]


def _fwd_call(q, k, v, bias, sm_scale, causal, block_q, block_k,
              interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    n_q, n_k = sq // block_q, sk // block_k
    bias_mode, bias_spec = _bias_spec(bias, h, block_q, block_k,
                                      q_axis=1, k_axis=2)
    bias_arr = bias if bias is not None else jnp.zeros((1, 1, 1, 1),
                                                       q.dtype)
    kern = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, n_k=n_k, bias_mode=bias_mode)
    out, lse = pl.pallas_call(
        kern,
        grid=(b * h, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qb, kb: (bh, qb, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qb, kb: (bh, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qb, kb: (bh, kb, 0)),
            bias_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qb, kb: (bh, qb, 0)),
            pl.BlockSpec((1, block_q), lambda bh, qb, kb: (bh, qb)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running denom
            pltpu.VMEM((block_q, d), jnp.float32),       # output acc
        ],
        interpret=interpret,
    )(q.reshape(b * h, sq, d), k.reshape(b * h, sk, d),
      v.reshape(b * h, sk, d), bias_arr)
    return out.reshape(b, h, sq, d), lse.reshape(b, h, sq)


# ---------------------------------------------------------------------------
# Pallas backward: per-tile recompute from (q, k, v, lse, delta)
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, dq_scr, *, sm_scale, causal,
                   block_q, block_k, n_k, bias_mode):
    import jax.experimental.pallas as pl

    qb = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    run = _causal_run(qb, kb, block_q, block_k) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = _tile_scores(q, k, bias_ref, bias_mode, qb, kb, sm_scale,
                         causal, block_q, block_k)
        p = jnp.exp(s - lse_ref[0][:, None])             # (BQ, BK)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, None]) * sm_scale
        dq_scr[...] += lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kb == n_k - 1)
    def _flush():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                    sm_scale, causal, block_q, block_k, n_q, bias_mode):
    import jax.experimental.pallas as pl

    kb = pl.program_id(1)
    qb = pl.program_id(2)

    @pl.when(qb == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    run = _causal_run(qb, kb, block_q, block_k) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = _tile_scores(q, k, bias_ref, bias_mode, qb, kb, sm_scale,
                         causal, block_q, block_k)
        p = jnp.exp(s - lse_ref[0][:, None])             # (BQ, BK)
        # dv += p^T do  — contract the q dim without materializing p^T
        dv_scr[...] += lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, None]) * sm_scale
        dk_scr[...] += lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qb == n_q - 1)
    def _flush():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_call(q, k, v, bias, out, lse, do, sm_scale, causal, block_q,
              block_k, interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    n_q, n_k = sq // block_q, sk // block_k
    bh = b * h
    qf = q.reshape(bh, sq, d)
    kf = k.reshape(bh, sk, d)
    vf = v.reshape(bh, sk, d)
    dof = do.reshape(bh, sq, d)
    lsef = lse.reshape(bh, sq)
    # delta_i = do_i . o_i — one O(N*D) pass in plain jnp, shared by
    # both kernels (the canonical flash backward precompute)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).reshape(bh, sq)

    bias_arr = bias if bias is not None else jnp.zeros((1, 1, 1, 1),
                                                       q.dtype)
    mode_q, bias_spec_q = _bias_spec(bias, h, block_q, block_k,
                                     q_axis=1, k_axis=2)
    kern_dq = functools.partial(
        _bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, n_k=n_k, bias_mode=mode_q)
    dq = pl.pallas_call(
        kern_dq,
        grid=(bh, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda g, qb, kb: (g, qb, 0)),
            pl.BlockSpec((1, block_k, d), lambda g, qb, kb: (g, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda g, qb, kb: (g, kb, 0)),
            bias_spec_q,
            pl.BlockSpec((1, block_q, d), lambda g, qb, kb: (g, qb, 0)),
            pl.BlockSpec((1, block_q), lambda g, qb, kb: (g, qb)),
            pl.BlockSpec((1, block_q), lambda g, qb, kb: (g, qb)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda g, qb, kb: (g, qb, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, bias_arr, dof, lsef, delta)

    mode_k, bias_spec_k = _bias_spec(bias, h, block_q, block_k,
                                     q_axis=2, k_axis=1)
    kern_dkv = functools.partial(
        _bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, n_q=n_q, bias_mode=mode_k)
    dk, dv = pl.pallas_call(
        kern_dkv,
        grid=(bh, n_k, n_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda g, kb, qb: (g, qb, 0)),
            pl.BlockSpec((1, block_k, d), lambda g, kb, qb: (g, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda g, kb, qb: (g, kb, 0)),
            bias_spec_k,
            pl.BlockSpec((1, block_q, d), lambda g, kb, qb: (g, qb, 0)),
            pl.BlockSpec((1, block_q), lambda g, kb, qb: (g, qb)),
            pl.BlockSpec((1, block_q), lambda g, kb, qb: (g, qb)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda g, kb, qb: (g, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda g, kb, qb: (g, kb, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, bias_arr, dof, lsef, delta)
    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, mask, sm_scale, causal, block_q, block_k, interpret):
    return _fwd_call(q, k, v, mask, sm_scale, causal, block_q, block_k,
                     interpret)[0]


def _flash_fwd_rule(q, k, v, mask, sm_scale, causal, block_q, block_k,
                    interpret):
    out, lse = _fwd_call(q, k, v, mask, sm_scale, causal, block_q,
                         block_k, interpret)
    return out, (q, k, v, mask, out, lse)


def _flash_bwd_rule(sm_scale, causal, block_q, block_k, interpret, res,
                    do):
    q, k, v, mask, out, lse = res
    dq, dk, dv = _bwd_call(q, k, v, mask, out, lse, do, sm_scale,
                           causal, block_q, block_k, interpret)
    # the mask is a constant (padding/causal -1e9): zero cotangent by
    # contract — the graph pass refuses chains whose mask wants grads
    dmask = None if mask is None else jnp.zeros_like(mask)
    return dq, dk, dv, dmask


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# ---------------------------------------------------------------------------
# public entry + op lowering
# ---------------------------------------------------------------------------


def _shape_ok(sq, sk, d):
    return sq % 128 == 0 and sk % 128 == 0 and d in (64, 128, 256)


def _check_mask(mask, b, h, sq, sk):
    if mask is None:
        return
    # Mosaic CLAMPS out-of-range block indices — a mis-sized mask would
    # silently reuse the last tile instead of erroring
    ok = (mask.ndim == 4
          and mask.shape[0] in (1, b) and mask.shape[1] in (1, h)
          and mask.shape[2] in (1, sq) and mask.shape[3] == sk)
    if not ok:
        raise ValueError(
            f"mask shape {tuple(mask.shape)} does not broadcast to "
            f"(B={b}, H={h}, Sq={sq}, Sk={sk}); the key dim must be "
            f"exactly Sk")


def flash_attention(q, k, v, mask=None, *, sm_scale=None, causal=False,
                    block_q=128, block_k=128, use_pallas=None,
                    interpret=False):
    """Fused attention over (B, H, S, D) q/k/v with an optional additive
    mask (None, key form [B,1,1,Sk], or full [B,H,Sq,Sk]).

    ``use_pallas``: True forces the Pallas kernels (``interpret=True``
    runs them on CPU for tests), False forces the jnp reference, None
    picks Pallas on TPU at kernel-aligned shapes and the reference
    everywhere else — the CPU/tier-1 default stays pure jnp.
    Differentiable in q/k/v via the custom VJP (tiled recompute
    backward); the mask is treated as a constant."""
    if q.ndim != 4:
        raise ValueError(f"flash_attention wants (B, H, S, D) inputs; "
                         f"got rank {q.ndim}")
    b, h, sq, d = q.shape
    sk = k.shape[2]
    _check_mask(mask, b, h, sq, sk)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if use_pallas is None:
        use_pallas = (jax.default_backend() == "tpu"
                      and _shape_ok(sq, sk, d))
    if not use_pallas:
        return flash_attention_ref(q, k, v, mask, sm_scale=sm_scale,
                                   causal=causal)
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"flash_attention needs seq multiples of the block "
            f"({block_q}/{block_k}); got Sq={sq}, Sk={sk}")
    return _flash(q, k, v, mask, float(sm_scale), bool(causal),
                  int(block_q), int(block_k), bool(interpret))


def _pallas_engaged(b, h, sq, sk, d):
    """FLAGS_flash_attention engagement for the rewritten op — the same
    contract as ops/fused.py: 'never' forces the reference, 'always'
    engages at any aligned shape, 'auto' only when the score tensor
    would threaten HBM on a TPU backend.  The ``fused._FORCE_INTERPRET``
    test hook engages the kernels in interpret mode off-TPU."""
    from . import fused

    return fused._flash_engaged(b, h, sq, sk, d)


@register_lower("flash_attention")
def _flash_attention_lower(ctx, op):
    from ..monitor import stat_add
    from . import fused

    q = ctx.in1(op, "Q")
    k = ctx.in1(op, "K")
    v = ctx.in1(op, "V")
    mask = ctx.in1(op, "Mask")
    b, h, sq, d = q.shape
    sk = k.shape[2]
    sm_scale = float(op.attr("scale", 0.0)) or 1.0 / math.sqrt(d)
    causal = bool(op.attr("causal", False))
    if _pallas_engaged(b, h, sq, sk, d):
        stat_add("flash_attention_engaged")
        out = flash_attention(
            q, k, v, mask, sm_scale=sm_scale, causal=causal,
            use_pallas=True,
            interpret=bool(fused._FORCE_INTERPRET
                           or jax.default_backend() != "tpu"))
    else:
        out = flash_attention(q, k, v, mask, sm_scale=sm_scale,
                              causal=causal, use_pallas=False)
    ctx.set_out(op, "Out", out)
