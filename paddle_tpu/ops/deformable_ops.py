"""Deformable convolution v1/v2 via bilinear sampling + matmul.

Reference parity: operators/deformable_conv_op.cu (v2, with modulation
Mask) and deformable_conv_v1_op.cu.  TPU-native: the deformable im2col
is a vectorized bilinear gather over all (kernel position, output
location) pairs — XLA turns it into gathers — followed by ONE MXU
matmul with the filter; backward comes from the generic vjp (gathers
transpose to scatters).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.lowering import register_lower
from .common import bilinear_sample_chw


def _deformable_conv(ctx, op, with_mask):
    x = ctx.in1(op, "Input")  # [N, C, H, W]
    offset = ctx.in1(op, "Offset")  # [N, 2*dg*kh*kw, OH, OW]
    mask = ctx.in1(op, "Mask") if with_mask else None  # [N, dg*kh*kw, OH, OW]
    f = ctx.in1(op, "Filter")  # [O, C/g, kh, kw]
    strides = [int(s) for s in op.attr("strides", [1, 1])]
    paddings = [int(p) for p in op.attr("paddings", [0, 0])]
    dilations = [int(d) for d in op.attr("dilations", [1, 1])]
    groups = int(op.attr("groups", 1) or 1)
    dg = int(op.attr("deformable_groups", 1) or 1)

    n, c, h, w = x.shape
    o, _cg, kh, kw = f.shape
    oh = offset.shape[2]
    ow = offset.shape[3]
    kk = kh * kw

    # base sampling grid per (kernel pos, output loc): [kh, kw, OH, OW]
    ky = (jnp.arange(kh) * dilations[0])[:, None, None, None]
    kx = (jnp.arange(kw) * dilations[1])[None, :, None, None]
    oy = (jnp.arange(oh) * strides[0] - paddings[0])[None, None, :, None]
    ox = (jnp.arange(ow) * strides[1] - paddings[1])[None, None, None, :]
    gy = (ky + oy).astype(x.dtype)  # [kh, kw, OH, OW] (broadcast)
    gx = (kx + ox).astype(x.dtype)
    gy = jnp.broadcast_to(gy, (kh, kw, oh, ow)).reshape(kk, oh, ow)
    gx = jnp.broadcast_to(gx, (kh, kw, oh, ow)).reshape(kk, oh, ow)

    # offsets: [N, dg, kk, 2, OH, OW] with (dy, dx) pairs
    off = offset.reshape(n, dg, kk, 2, oh, ow)
    cpg = c // dg  # channels per deformable group

    def per_image(img, off_i, mask_i):
        # img [C, H, W]; off_i [dg, kk, 2, OH, OW]
        cols = []
        for g in range(dg):
            ys = gy[None] + off_i[g, :, 0]  # [kk, OH, OW]
            xs = gx[None] + off_i[g, :, 1]
            sub = img[g * cpg:(g + 1) * cpg]
            s = bilinear_sample_chw(sub, ys, xs)  # [cpg, kk, OH, OW]
            if mask_i is not None:
                s = s * mask_i[g][None]  # [1, kk, OH, OW]
            cols.append(s)
        return jnp.concatenate(cols, axis=0)  # [C, kk, OH, OW]

    if mask is not None:
        m = mask.reshape(n, dg, kk, oh, ow)
        cols = jax.vmap(per_image)(x, off, m)
    else:
        cols = jax.vmap(lambda img, off_i: per_image(img, off_i, None))(
            x, off)
    # cols [N, C, kk, OH, OW] -> grouped matmul with the filter
    cg = c // groups
    og = o // groups
    cols_g = cols.reshape(n, groups, cg, kk, oh, ow)
    f_g = f.reshape(groups, og, cg, kk)
    out = jnp.einsum("ngckhw,gock->ngohw", cols_g, f_g)
    ctx.set_out(op, "Output", out.reshape(n, o, oh, ow))


@register_lower("deformable_conv")
def _deformable_conv_v2(ctx, op):
    _deformable_conv(ctx, op, with_mask=True)


@register_lower("deformable_conv_v1")
def _deformable_conv_v1(ctx, op):
    _deformable_conv(ctx, op, with_mask=False)
