"""Op-tail lowerings: CRF, spectral norm, pooling variants, padded
select family, sequence scatter.

Reference parity: linear_chain_crf_op.cc / crf_decoding_op.cc,
spectral_norm_op.cc, pool_with_index_op.cc (max_pool3d_with_index),
detection/psroi_pool_op.cc, detection/prroi_pool_op.cc,
sequence_ops/sequence_scatter_op.cc, index_sample_op.cc,
masked_select_op.cc, where_index_op.cc.

TPU-native notes:
- ops whose reference output shape is data-dependent (masked_select,
  where_index) return FIXED-size outputs: valid entries first, tail
  padded (0 / -1), plus an explicit Count output — the same masked
  fixed-size convention as nms_ops.py.
- index outputs (argmax positions) are int32: JAX on TPU runs with
  x64 disabled, so an int64 annotation would silently truncate anyway;
  int32 is the honest documented contract.
- CRF runs the forward algorithm / Viterbi in log space under
  `lax.scan` over time with a length mask — dense [B, T, D] batches
  with a Length input replace the reference's LoD walk.
"""
from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.lowering import register_lower


# ---------------------------------------------------------------------------
# padded select family
# ---------------------------------------------------------------------------

@register_lower("index_sample")
def _index_sample(ctx, op):
    x = ctx.in1(op, "X")          # [B, N]
    index = ctx.in1(op, "Index")  # [B, K] int
    ctx.set_out(op, "Out",
                jnp.take_along_axis(x, index.astype(jnp.int32), axis=1))


@register_lower("masked_select")
def _masked_select(ctx, op):
    """Dense redesign: Y keeps X's flat size — selected values first
    (stable order), zero-padded — plus Count (valid rows)."""
    x = jnp.ravel(ctx.in1(op, "X"))
    mask = jnp.ravel(ctx.in1(op, "Mask")).astype(bool)
    order = jnp.argsort(jnp.logical_not(mask), stable=True)
    ctx.set_out(op, "Y", jnp.where(mask[order], x[order],
                                   jnp.zeros_like(x)))
    ctx.set_out(op, "Count", mask.sum().astype(jnp.int32))


@register_lower("where_index")
def _where_index(ctx, op):
    """nonzero: Out is [numel, rank] int32, valid coordinates first
    (row-major order), tail rows -1, plus Count."""
    cond = ctx.in1(op, "Condition")
    flat = jnp.ravel(cond).astype(bool)
    n = flat.shape[0]
    order = jnp.argsort(jnp.logical_not(flat), stable=True)
    valid = flat[order]
    coords = jnp.stack(
        jnp.unravel_index(order, cond.shape), axis=1).astype(jnp.int32)
    out = jnp.where(valid[:, None], coords, -1)
    ctx.set_out(op, "Out", out)
    ctx.set_out(op, "Count", flat.sum().astype(jnp.int32))


@register_lower("sequence_scatter")
def _sequence_scatter(ctx, op):
    """Updates scattered into X by Ids (sequence_scatter_op.cc under
    the dense single-sequence contract: plus-scatter)."""
    x = ctx.in1(op, "X")
    ids = jnp.ravel(ctx.in1(op, "Ids")).astype(jnp.int32)
    upd = ctx.in1(op, "Updates").reshape((ids.shape[0],) + x.shape[1:])
    ctx.set_out(op, "Out", x.at[ids].add(upd))


# ---------------------------------------------------------------------------
# spectral norm
# ---------------------------------------------------------------------------

@register_lower("spectral_norm")
def _spectral_norm(ctx, op):
    """Weight / sigma via power iteration (spectral_norm_op.h): U/V are
    the persistent iteration vectors; `dim` rotates the reshaped axis."""
    w = ctx.in1(op, "Weight")
    u = jnp.ravel(ctx.in1(op, "U"))
    v = jnp.ravel(ctx.in1(op, "V"))
    dim = int(op.attr("dim", 0))
    power_iters = int(op.attr("power_iters", 1))
    eps = float(op.attr("eps", 1e-12))

    perm = None
    if dim != 0:
        perm = [dim] + [i for i in range(w.ndim) if i != dim]
        wm = jnp.transpose(w, perm)
    else:
        wm = w
    h = wm.shape[0]
    mat = wm.reshape(h, -1)

    def _l2(x):
        return x / (jnp.linalg.norm(x) + eps)

    for _ in range(power_iters):
        v = _l2(mat.T @ u)
        u = _l2(mat @ v)
    sigma = u @ mat @ v
    out = mat / sigma
    out = out.reshape(wm.shape)
    if perm is not None:
        inv = [perm.index(i) for i in range(w.ndim)]
        out = jnp.transpose(out, inv)
    ctx.set_out(op, "Out", out)


# ---------------------------------------------------------------------------
# pooling variants
# ---------------------------------------------------------------------------

@register_lower("max_pool3d_with_index")
def _max_pool_with_index(ctx, op):
    """3-D max pooling returning flat argmax positions within each
    image (pool_with_index_op.cc; the 2-D variant lives in
    vision_ops.py).  Mask is int32 (x64-off contract)."""
    x = ctx.in1(op, "X")  # [N, C, (D,) H, W]
    spatial = x.ndim - 2
    ksize = [int(k) for k in op.attr("ksize")]
    strides = [int(s) for s in op.attr("strides", [1] * spatial)]
    paddings = [int(p) for p in op.attr("paddings", [0] * spatial)]
    if bool(op.attr("global_pooling", False)):
        ksize = list(x.shape[2:])
        paddings = [0] * spatial
    if bool(op.attr("adaptive", False)):
        # adaptive bins: ksize IS the output size (same contract as the
        # 2-D variant in vision_ops.py)
        in_sp_a = x.shape[2:]
        if any(in_sp_a[i] % ksize[i] for i in range(spatial)):
            # non-divisible windows: shared fixed-width gather + masked
            # argmax (ops/common.py adaptive_max_with_index)
            from .common import adaptive_max_with_index

            out, flat = adaptive_max_with_index(x, tuple(ksize))
            ctx.set_out(op, "Out", out)
            ctx.set_out(op, "Mask", flat)
            return
        strides = [in_sp_a[i] // ksize[i] for i in range(spatial)]
        ksize = list(strides)
        paddings = [0] * spatial

    pads = [(0, 0), (0, 0)] + [(p, p) for p in paddings]
    xin = jnp.pad(x, pads, constant_values=-jnp.inf)
    in_sp = x.shape[2:]
    out_sp = [((in_sp[i] + 2 * paddings[i] - ksize[i]) // strides[i]) + 1
              for i in range(spatial)]

    # flat index of each padded position inside the ORIGINAL image
    # (reference indexes into the unpadded input)
    coords = [jnp.arange(xin.shape[2 + i]) - paddings[i]
              for i in range(spatial)]
    flat = jnp.zeros([xin.shape[2 + i] for i in range(spatial)], jnp.int32)
    mult = 1
    for i in reversed(range(spatial)):
        shape = [1] * spatial
        shape[i] = -1
        flat = flat + (coords[i].reshape(shape) * mult).astype(jnp.int32)
        mult *= in_sp[i]

    best = None
    besti = None
    for offs in itertools.product(*[range(k) for k in ksize]):
        sl = tuple(slice(None) for _ in range(2)) + tuple(
            slice(offs[i], offs[i] + out_sp[i] * strides[i], strides[i])
            for i in range(spatial))
        v = xin[sl]
        idx = jnp.broadcast_to(
            flat[tuple(slice(offs[i], offs[i] + out_sp[i] * strides[i],
                             strides[i]) for i in range(spatial))],
            v.shape)
        if best is None:
            best, besti = v, idx
        else:
            better = v > best
            best = jnp.where(better, v, best)
            besti = jnp.where(better, idx, besti)
    ctx.set_out(op, "Out", best)
    ctx.set_out(op, "Mask", besti)


def _roi_batch_split(rois, ctx, op):
    """Per-roi batch index; reuses the vision_ops helper and also honors
    the reference prroi slot name BatchRoINums."""
    from .vision_ops import _roi_boxes

    if op.inputs.get("BatchRoINums"):
        counts = ctx.get(op.inputs["BatchRoINums"][0]).astype(jnp.int32)
        batch_idx = jnp.repeat(jnp.arange(counts.shape[0]), counts,
                               total_repeat_length=rois.shape[0])
        return rois, batch_idx
    return _roi_boxes(ctx, op)


@register_lower("psroi_pool")
def _psroi_pool(ctx, op):
    """Position-sensitive ROI average pooling (psroi_pool_op.h): output
    channel c at bin (ph, pw) averages input channel
    c * ph_total * pw_total + ph * pw_total + pw over that bin."""
    x = ctx.in1(op, "X")          # [N, C_in, H, W]
    rois = ctx.in1(op, "ROIs")    # [R, 4]
    out_c = int(op.attr("output_channels"))
    ph_n = int(op.attr("pooled_height"))
    pw_n = int(op.attr("pooled_width"))
    scale = float(op.attr("spatial_scale", 1.0))
    N, C, H, W = x.shape
    if C != out_c * ph_n * pw_n:
        raise ValueError(
            f"psroi_pool input channels {C} != output_channels*ph*pw "
            f"({out_c}*{ph_n}*{pw_n})")
    rois, batch_idx = _roi_batch_split(rois, ctx, op)

    ys = jnp.arange(H, dtype=jnp.float32)
    xs = jnp.arange(W, dtype=jnp.float32)

    def _round_half_away(v):
        # C++ round(): halves go AWAY from zero (jnp.round is half-even)
        return jnp.sign(v) * jnp.floor(jnp.abs(v) + 0.5)

    def one_roi(roi, b):
        img = x[b]  # [C, H, W]
        # reference: round(roi) then +1 on the far edge, THEN scale
        # (psroi_pool_op.h roi_start/end)
        x1 = _round_half_away(roi[0]) * scale
        y1 = _round_half_away(roi[1]) * scale
        x2 = (_round_half_away(roi[2]) + 1.0) * scale
        y2 = (_round_half_away(roi[3]) + 1.0) * scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bw = rw / pw_n
        bh = rh / ph_n
        outs = []
        for ph in range(ph_n):
            for pw in range(pw_n):
                hs = jnp.floor(y1 + ph * bh)
                he = jnp.ceil(y1 + (ph + 1) * bh)
                ws = jnp.floor(x1 + pw * bw)
                we = jnp.ceil(x1 + (pw + 1) * bw)
                m = ((ys[:, None] >= hs) & (ys[:, None] < he)
                     & (xs[None, :] >= ws) & (xs[None, :] < we)
                     & (ys[:, None] >= 0) & (ys[:, None] < H)
                     & (xs[None, :] >= 0) & (xs[None, :] < W))
                area = jnp.maximum(m.sum(), 1)
                chans = jnp.arange(out_c) * ph_n * pw_n + ph * pw_n + pw
                vals = (img[chans] * m[None]).sum(axis=(1, 2)) / area
                empty = (he <= hs) | (we <= ws)
                outs.append(jnp.where(empty, 0.0, vals))
        return jnp.stack(outs, axis=1).reshape(out_c, ph_n, pw_n)

    ctx.set_out(op, "Out", jax.vmap(one_roi)(rois, batch_idx))


@register_lower("prroi_pool")
def _prroi_pool(ctx, op):
    """Precise ROI pooling (prroi_pool_op.h).  TPU-native approximation:
    the exact bilinear integral is replaced by a dense 8x8 bilinear
    sample average per bin (documented; converges to the integral and
    keeps everything vectorized on the VPU)."""
    x = ctx.in1(op, "X")
    rois = ctx.in1(op, "ROIs")
    ph_n = int(op.attr("pooled_height"))
    pw_n = int(op.attr("pooled_width"))
    scale = float(op.attr("spatial_scale", 1.0))
    S = 8  # samples per bin side
    N, C, H, W = x.shape
    rois, batch_idx = _roi_batch_split(rois, ctx, op)

    def bilinear(img, yy, xx):
        y0 = jnp.clip(jnp.floor(yy), 0, H - 1)
        x0 = jnp.clip(jnp.floor(xx), 0, W - 1)
        y1 = jnp.clip(y0 + 1, 0, H - 1)
        x1 = jnp.clip(x0 + 1, 0, W - 1)
        ly = yy - y0
        lx = xx - x0
        y0i, x0i, y1i, x1i = (v.astype(jnp.int32) for v in (y0, x0, y1, x1))
        v = (img[:, y0i, x0i] * (1 - ly) * (1 - lx)
             + img[:, y1i, x0i] * ly * (1 - lx)
             + img[:, y0i, x1i] * (1 - ly) * lx
             + img[:, y1i, x1i] * ly * lx)
        inside = (yy >= 0) & (yy <= H - 1) & (xx >= 0) & (xx <= W - 1)
        return jnp.where(inside, v, 0.0)

    def one_roi(roi, b):
        img = x[b]
        x1 = roi[0] * scale
        y1 = roi[1] * scale
        x2 = roi[2] * scale
        y2 = roi[3] * scale
        bw = jnp.maximum(x2 - x1, 0.0) / pw_n
        bh = jnp.maximum(y2 - y1, 0.0) / ph_n
        py = jnp.arange(ph_n, dtype=jnp.float32)
        px = jnp.arange(pw_n, dtype=jnp.float32)
        off = (jnp.arange(S, dtype=jnp.float32) + 0.5) / S
        gy = (y1 + py[:, None] * bh + off[None, :] * bh).reshape(-1)
        gx = (x1 + px[:, None] * bw + off[None, :] * bw).reshape(-1)
        yy = jnp.broadcast_to(gy[:, None], (gy.shape[0], gx.shape[0]))
        xx = jnp.broadcast_to(gx[None, :], (gy.shape[0], gx.shape[0]))
        vals = bilinear(img, yy.ravel(), xx.ravel())
        vals = vals.reshape(C, ph_n, S, pw_n, S)
        return vals.mean(axis=(2, 4))

    ctx.set_out(op, "Out", jax.vmap(one_roi)(rois, batch_idx))


# ---------------------------------------------------------------------------
# linear-chain CRF
# ---------------------------------------------------------------------------

def _crf_unpack(transition):
    # reference layout: row 0 = start weights, row 1 = stop weights,
    # rows 2.. = transition matrix [D, D]
    return transition[0], transition[1], transition[2:]


@register_lower("linear_chain_crf")
def _linear_chain_crf(ctx, op):
    """Negative of the CRF conditional log-likelihood per sequence
    (linear_chain_crf_op.h ForwardOneSequence): dense [B, T, D] emission
    + Length replaces the LoD walk.  LogLikelihood = logZ - path_score
    (the reference's sign: a POSITIVE loss value)."""
    emission = ctx.in1(op, "Emission")  # [B, T, D] or [T, D]
    transition = ctx.in1(op, "Transition")  # [D+2, D]
    label = ctx.in1(op, "Label")
    length = ctx.in1(op, "Length")
    squeeze = emission.ndim == 2
    if squeeze:
        emission = emission[None]
        label = label.reshape(1, -1)
    B, T, D = emission.shape
    label = label.reshape(B, T).astype(jnp.int32)
    if length is None:
        lens = jnp.full((B,), T, jnp.int32)
    else:
        lens = jnp.ravel(length).astype(jnp.int32)
    start_w, stop_w, trans = _crf_unpack(transition)

    def one(seq_e, seq_l, n):
        t_idx = jnp.arange(T)
        mask = t_idx < n

        # forward algorithm (log space)
        def step(alpha, t):
            nxt = jax.nn.logsumexp(alpha[:, None] + trans, axis=0) \
                + seq_e[t]
            alpha = jnp.where(mask[t], nxt, alpha)
            return alpha, None

        alpha0 = start_w + seq_e[0]
        alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))
        last_label = seq_l[n - 1]
        logz = jax.nn.logsumexp(alpha + stop_w)

        # gold path score
        em_score = jnp.where(mask, seq_e[t_idx, seq_l], 0.0).sum()
        tr = trans[seq_l[:-1], seq_l[1:]]
        tr_score = jnp.where(mask[1:], tr, 0.0).sum()
        path = start_w[seq_l[0]] + em_score + tr_score + stop_w[last_label]
        return logz - path

    ll = jax.vmap(one)(emission, label, lens)
    ctx.set_out(op, "LogLikelihood", ll.reshape(B, 1))
    # aux outputs for API-shape parity (grad comes from the generic vjp)
    ctx.set_out(op, "Alpha", jnp.zeros_like(emission))
    ctx.set_out(op, "EmissionExps", jnp.exp(emission))
    ctx.set_out(op, "TransitionExps", jnp.exp(transition))


@register_lower("crf_decoding")
def _crf_decoding(ctx, op):
    """Viterbi decode (crf_decoding_op.h): best path per sequence; when
    Label is given, emits the 0/1 correctness mask instead."""
    emission = ctx.in1(op, "Emission")
    transition = ctx.in1(op, "Transition")
    label = ctx.in1(op, "Label")
    length = ctx.in1(op, "Length")
    squeeze = emission.ndim == 2
    if squeeze:
        emission = emission[None]
    B, T, D = emission.shape
    if length is None:
        lens = jnp.full((B,), T, jnp.int32)
    else:
        lens = jnp.ravel(length).astype(jnp.int32)
    start_w, stop_w, trans = _crf_unpack(transition)

    def one(seq_e, n):
        mask = jnp.arange(T) < n

        def step(score, t):
            cand = score[:, None] + trans
            best_prev = jnp.argmax(cand, axis=0).astype(jnp.int32)
            nxt = jnp.max(cand, axis=0) + seq_e[t]
            new_score = jnp.where(mask[t], nxt, score)
            return new_score, jnp.where(mask[t], best_prev,
                                        jnp.arange(D, dtype=jnp.int32))

        score0 = start_w + seq_e[0]
        score, back = lax.scan(step, score0, jnp.arange(1, T))
        final = jnp.argmax(score + stop_w).astype(jnp.int32)

        # backtrack from position n-1 through the pointers
        def bt(cur, t):
            # back[t] holds pointers INTO step t; walking backwards from
            # the end, positions past n-1 pass through (identity rows)
            prev = back[t][cur]
            return prev, cur

        p0, path_rev = lax.scan(bt, final, jnp.arange(T - 2, -1, -1))
        # path_rev holds states at positions T-1..1; the final carry is
        # the state at position 0
        path = jnp.concatenate(
            [jnp.array([p0], jnp.int32), jnp.flip(path_rev)]) \
            if T > 1 else jnp.array([final], jnp.int32)
        # positions beyond the length are don't-care: zero them
        return jnp.where(mask, path, 0)

    paths = jax.vmap(one)(emission, lens)
    if label is not None:
        lbl = label.reshape(B, T).astype(jnp.int32)
        out = (paths == lbl).astype(jnp.int32) \
            * (jnp.arange(T)[None, :] < lens[:, None])
        ctx.set_out(op, "ViterbiPath", out.reshape(B, T)
                    if not squeeze else out.reshape(T, 1))
        return
    out = paths if not squeeze else paths.reshape(T, 1)
    ctx.set_out(op, "ViterbiPath", out)
