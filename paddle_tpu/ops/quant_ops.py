"""Fake-quantization ops (reference operators/fake_quantize_op.cc:739
family: fake_quantize_abs_max / fake_channel_wise_quantize_abs_max /
fake_quantize_moving_average_abs_max / fake_quantize_range_abs_max and
their *_dequantize_* variants, plus fake_dequantize_max_abs and the
moving_average_abs_max_scale observer).

TPU-native design: quant-dequant SIMULATION stays in float — on TPU the
MXU wants bf16, int8 buys no training-time win, so the value of these
ops is scale calibration + bit-exact export parity, not int arithmetic.
The straight-through estimator falls out of the emission
``x + stop_gradient(qdq(x) - x)``: the generic vjp path
(ops/grad_generic.py) then yields pass-through gradients with zero
bespoke backward kernels (the reference maintains FakeQuantDequantGrad
kernels for the same semantics).

**Real int8/fp8 lowering** (the inference half): ``dequant_matmul`` is
the op the PostTrainingWeightQuantPass (slim/quantization.py) rewrites
matmul-family ops into — the weight rides as a compact int8 (or
float8-e4m3) carrier plus per-output-channel scales, and the op
dequantizes at the MXU's doorstep: the pure-jnp reference path is the
CPU/tier-1 default, the Pallas kernel dequantizes weight tiles in VMEM
so the f32/bf16 weight is never materialized in HBM (same dispatch
pattern as ops/pallas_decode_attention.py; interpret-mode equivalence
is pinned in tests).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..framework.lowering import register_lower
from .common import as_scalar

# the ONE scale clamp, shared by every scale computation.  It must be
# applied to the PER-SLICE maxima (elementwise), never only to a global
# max: an all-zero channel/page otherwise yields a ~0 scale and the
# dequant divides by it (bugfix pinned in tests/test_quant_inference.py)
SCALE_EPS = 1e-8


def _clamp_scale(scale):
    """Clamp scale(s) away from zero — elementwise, so every slice of a
    per-channel/per-page scale tensor is individually protected."""
    return jnp.maximum(scale, SCALE_EPS)


def _qmax(op):
    return 2.0 ** (int(op.attr("bit_length", 8)) - 1) - 1


def _abs_max(x):
    return _clamp_scale(jnp.max(jnp.abs(x)))


def _channel_abs_max(x, axis):
    red = tuple(i for i in range(x.ndim) if i != axis)
    return _clamp_scale(jnp.max(jnp.abs(x), axis=red))


def _quant(x, scale, qmax):
    """Quantize to the integer grid, kept in float (reference outputs
    float tensors holding integer values)."""
    return jnp.clip(jnp.round(x / scale * qmax), -qmax, qmax)


def _qdq_ste(x, scale, qmax):
    """Quant-dequant with straight-through gradient."""
    qdq = _quant(x, scale, qmax) * scale / qmax
    return x + jax.lax.stop_gradient(qdq - x)


@register_lower("fake_quantize_abs_max")
def lower_fake_quantize_abs_max(ctx, op):
    x = ctx.in1(op, "X")
    qmax = _qmax(op)
    scale = _abs_max(x)
    ctx.set_out(op, "Out", _quant(x, scale, qmax))
    ctx.set_out(op, "OutScale", jnp.reshape(scale, (1,)))


@register_lower("fake_quantize_dequantize_abs_max")
def lower_fake_quantize_dequantize_abs_max(ctx, op):
    x = ctx.in1(op, "X")
    qmax = _qmax(op)
    scale = _abs_max(x)
    ctx.set_out(op, "Out", _qdq_ste(x, scale, qmax))
    ctx.set_out(op, "OutScale", jnp.reshape(scale, (1,)))


@register_lower("fake_channel_wise_quantize_abs_max")
def lower_fake_channel_wise_quantize_abs_max(ctx, op):
    x = ctx.in1(op, "X")
    axis = int(op.attr("quant_axis", 0))
    qmax = _qmax(op)
    scale = _channel_abs_max(x, axis)
    bshape = [1] * x.ndim
    bshape[axis] = -1
    ctx.set_out(op, "Out", _quant(x, scale.reshape(bshape), qmax))
    ctx.set_out(op, "OutScale", scale)


@register_lower("fake_channel_wise_quantize_dequantize_abs_max")
def lower_fake_channel_wise_qdq_abs_max(ctx, op):
    x = ctx.in1(op, "X")
    axis = int(op.attr("quant_axis", 0))
    qmax = _qmax(op)
    scale = _channel_abs_max(x, axis)
    bshape = [1] * x.ndim
    bshape[axis] = -1
    ctx.set_out(op, "Out", _qdq_ste(x, scale.reshape(bshape), qmax))
    ctx.set_out(op, "OutScale", scale)


def _moving_average_scale(ctx, op, x):
    """Shared accumulator update (fake_quantize_op.cc FindMovingAverage):
    state = rate*state + 1;  accum = rate*accum + abs_max(x);
    scale = accum / state.  In is_test mode the stored scale is used
    unchanged and no state is written."""
    rate = float(op.attr("moving_rate", 0.9))
    in_scale = as_scalar(ctx.in1(op, "InScale"))
    if op.attr("is_test", False):
        return jnp.maximum(in_scale, 1e-8), None, None
    state = as_scalar(ctx.in1(op, "InState"))
    accum = as_scalar(ctx.in1(op, "InAccum"))
    state = rate * state + 1.0
    accum = rate * accum + _abs_max(x)
    scale = accum / state
    return jnp.maximum(scale, 1e-8), state, accum


def _emit_moving_average_state(ctx, op, scale, state, accum):
    ctx.set_out(op, "OutScale", jnp.reshape(scale, (1,)))
    if state is not None:
        ctx.set_out(op, "OutState", jnp.reshape(state, (1,)))
        ctx.set_out(op, "OutAccum", jnp.reshape(accum, (1,)))


@register_lower("fake_quantize_moving_average_abs_max")
def lower_fake_quantize_moving_average_abs_max(ctx, op):
    x = ctx.in1(op, "X")
    qmax = _qmax(op)
    scale, state, accum = _moving_average_scale(ctx, op, x)
    ctx.set_out(op, "Out", _quant(x, scale, qmax))
    _emit_moving_average_state(ctx, op, scale, state, accum)


@register_lower("fake_quantize_dequantize_moving_average_abs_max")
def lower_fake_qdq_moving_average_abs_max(ctx, op):
    x = ctx.in1(op, "X")
    qmax = _qmax(op)
    scale, state, accum = _moving_average_scale(ctx, op, x)
    ctx.set_out(op, "Out", _qdq_ste(x, scale, qmax))
    _emit_moving_average_state(ctx, op, scale, state, accum)


@register_lower("fake_quantize_range_abs_max")
def lower_fake_quantize_range_abs_max(ctx, op):
    """Windowed running-max scale (fake_quantize_op.cc FindRangeAbsMax):
    a [window_size] ring buffer of per-step abs-maxes; the scale is the
    max over the window.  State rides explicit InScales/Iter slots
    (functional in-out pairs, same var wired to both) instead of the
    reference's in-place mutation."""
    x = ctx.in1(op, "X")
    qmax = _qmax(op)
    if op.attr("is_test", False):
        scale = jnp.maximum(as_scalar(ctx.in1(op, "InScale")), 1e-8)
        ctx.set_out(op, "Out", _quant(x, scale, qmax))
        return
    window = int(op.attr("window_size", 10000))
    cur = _abs_max(x)
    scales = ctx.in1(op, "InScales")
    it = jnp.asarray(as_scalar(ctx.in1(op, "Iter")), jnp.int32)
    if scales is None:  # windowless degenerate form: running max
        prev = as_scalar(ctx.in1(op, "InScale"))
        scale = jnp.maximum(jnp.maximum(prev, cur), 1e-8)
    else:
        scales = scales.at[it % window].set(cur)
        scale = jnp.maximum(jnp.max(scales), 1e-8)
        ctx.set_out(op, "OutScales", scales)
    ctx.set_out(op, "Out", _quant(x, scale, qmax))
    ctx.set_out(op, "OutScale", jnp.reshape(scale, (1,)))
    ctx.set_out(op, "OutIter", jnp.reshape(it + 1, (1,)))


@register_lower("moving_average_abs_max_scale")
def lower_moving_average_abs_max_scale(ctx, op):
    """Observer only: Out = X unchanged, scale state updated (used by
    the reference's OutScaleForTrainingPass)."""
    x = ctx.in1(op, "X")
    scale, state, accum = _moving_average_scale(ctx, op, x)
    if ctx.out_name(op, "Out"):
        ctx.set_out(op, "Out", x)
    _emit_moving_average_state(ctx, op, scale, state, accum)


@register_lower("fake_dequantize_max_abs")
def lower_fake_dequantize_max_abs(ctx, op):
    x = ctx.in1(op, "X")
    scale = as_scalar(ctx.in1(op, "Scale"))
    max_range = float(op.attr("max_range", 127.0))
    ctx.set_out(op, "Out", x * scale / max_range)


@register_lower("fake_channel_wise_dequantize_max_abs")
def lower_fake_channel_wise_dequantize_max_abs(ctx, op):
    x = ctx.in1(op, "X")
    scales = ctx.in_list(op, "Scales")
    axis = int(op.attr("quant_axis", 0))
    bits = op.attr("quant_bits", [8])
    bshape = [1] * x.ndim
    bshape[axis] = -1
    out = x * scales[0].reshape(bshape) / (2.0 ** (int(bits[0]) - 1) - 1)
    if len(scales) > 1:  # second-level (whole-tensor) scale, mul path
        out = out * as_scalar(scales[1]) / (2.0 ** (int(bits[1]) - 1) - 1)
    ctx.set_out(op, "Out", out)


# ---------------------------------------------------------------------------
# real int8/fp8 weight-only lowering (PostTrainingWeightQuantPass)
# ---------------------------------------------------------------------------

INT8_QMAX = 127.0
FP8_E4M3_MAX = 448.0  # largest finite float8_e4m3 magnitude

WEIGHT_QUANT_MODES = ("int8", "fp8_e4m3")


def resolve_quant_mode(mode: str) -> str:
    """Validate a weight-quant mode string, degrading ``fp8_e4m3`` to
    ``int8`` (counted as ``quant_fp8_unavailable``) when the installed
    jax lacks the dtype."""
    if mode not in WEIGHT_QUANT_MODES:
        raise ValueError(
            f"unknown weight-quant mode {mode!r}; expected one of "
            f"{WEIGHT_QUANT_MODES}")
    if mode == "fp8_e4m3":
        from ..framework import jax_compat

        if jax_compat.float8_e4m3_dtype() is None:
            from ..monitor import stat_add

            stat_add("quant_fp8_unavailable")
            return "int8"
    return mode


def quantize_weight(w, axis: int, mode: str = "int8"):
    """Post-training weight quantization: ``w`` -> ``(carrier, scale)``
    with per-output-channel step sizes along ``axis`` (the scale is
    clamped PER CHANNEL, so an all-zero channel dequantizes to exact
    zeros instead of dividing by ~0).  ``carrier * scale`` reconstructs
    the weight; int8 carriers hold the rounded grid, fp8 carriers the
    scaled value itself."""
    w = jnp.asarray(w)
    mode = resolve_quant_mode(mode)
    red = tuple(i for i in range(w.ndim) if i != axis)
    qmax = INT8_QMAX if mode == "int8" else FP8_E4M3_MAX
    scale = _clamp_scale(jnp.max(jnp.abs(w), axis=red) / qmax)
    bshape = [1] * w.ndim
    bshape[axis] = -1
    scaled = w / scale.reshape(bshape)
    if mode == "int8":
        q = jnp.clip(jnp.round(scaled), -INT8_QMAX, INT8_QMAX) \
            .astype(jnp.int8)
    else:
        from ..framework import jax_compat

        fp8 = jax_compat.float8_e4m3_dtype()
        q = jnp.clip(scaled, -FP8_E4M3_MAX, FP8_E4M3_MAX).astype(fp8)
    return q, scale.astype(jnp.float32)


def dequantize_weight(q, scale, axis: int, dtype=jnp.float32):
    """Inverse of :func:`quantize_weight` (the reference path — the
    Pallas kernel below does the same per tile in VMEM)."""
    bshape = [1] * q.ndim
    bshape[axis] = -1
    return (q.astype(jnp.float32)
            * scale.astype(jnp.float32).reshape(bshape)).astype(dtype)


def quantize_weight_stacked(w, axis: int, mode: str = "int8"):
    """Per-expert variant of :func:`quantize_weight` for stacked
    ``[E, ...]`` MoE weights: the scale keeps BOTH the leading stack
    axis and the output-channel ``axis`` (shape ``[E, out]``), so each
    expert calibrates its own step sizes — a shared scale would let one
    hot expert's outliers crush every other expert's resolution.  The
    ``[E, out]`` layout also shards alongside the carrier: carrier
    ``P('ep', ...)`` pairs with scale ``P('ep', None)``."""
    w = jnp.asarray(w)
    if w.ndim < 2 or axis == 0:
        raise ValueError(
            f"stacked quantization needs a [E, ...] weight with an "
            f"output-channel axis != 0, got shape {w.shape} axis {axis}")
    mode = resolve_quant_mode(mode)
    red = tuple(i for i in range(w.ndim) if i not in (0, axis))
    qmax = INT8_QMAX if mode == "int8" else FP8_E4M3_MAX
    scale = _clamp_scale(jnp.max(jnp.abs(w), axis=red) / qmax)
    bshape = [1] * w.ndim
    bshape[0] = w.shape[0]
    bshape[axis] = w.shape[axis]
    scaled = w / scale.reshape(bshape)
    if mode == "int8":
        q = jnp.clip(jnp.round(scaled), -INT8_QMAX, INT8_QMAX) \
            .astype(jnp.int8)
    else:
        from ..framework import jax_compat

        fp8 = jax_compat.float8_e4m3_dtype()
        q = jnp.clip(scaled, -FP8_E4M3_MAX, FP8_E4M3_MAX).astype(fp8)
    return q, scale.astype(jnp.float32)


def dequantize_weight_stacked(q, scale, axis: int, dtype=jnp.float32):
    """Inverse of :func:`quantize_weight_stacked`."""
    bshape = [1] * q.ndim
    bshape[0] = q.shape[0]
    bshape[axis] = q.shape[axis]
    return (q.astype(jnp.float32)
            * scale.astype(jnp.float32).reshape(bshape)).astype(dtype)


def _dequant_matmul_kernel(x_ref, w_ref, s_ref, o_ref, acc_scr, *, n_k):
    """One (bm, bn) output tile: accumulate x_tile @ dequant(w_tile)
    over the K grid axis.  The carrier tile is dequantized in VMEM —
    the full-precision weight never exists in HBM."""
    import jax.experimental.pallas as pl

    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    x = x_ref[...].astype(jnp.float32)
    w = w_ref[...].astype(jnp.float32) * s_ref[0].astype(jnp.float32)
    acc_scr[...] += jax.lax.dot(x, w,
                                preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == n_k - 1)
    def _flush():
        o_ref[...] = acc_scr[...].astype(o_ref.dtype)


def _dequant_matmul_call(x, qw, scale, out_dtype, interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = x.shape
    _, n = qw.shape
    bm = min(m, 256)
    bk = min(k, 512)
    bn = min(n, 256)
    grid = (m // bm, n // bn, k // bk)
    kern = functools.partial(_dequant_matmul_kernel, n_k=grid[2])
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            # scale rides as a (1, bn) row so the block stays 2D (lane-
            # aligned) on real Mosaic
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(x, qw, scale.reshape(1, n))


def dequant_matmul(x, qw, scale, *, use_pallas="auto", interpret=False,
                   out_dtype=None):
    """``x [M, K] @ dequant(qw [K, N], scale [N])`` with the dequant
    fused into the matmul.  ``use_pallas`` dispatch matches
    ``ops/pallas_decode_attention.py``: 'auto' engages the kernel on
    the TPU backend only (tier-1 stays Mosaic-free), 'always' forces it
    (combine with ``interpret=True`` off-TPU), 'never' forces the
    pure-jnp reference.  Shapes the tiling cannot cover fall back to
    the reference (``quant_pallas_fallback_shape``)."""
    out_dtype = out_dtype or x.dtype
    if use_pallas == "auto":
        use_pallas = "always" if jax.default_backend() == "tpu" \
            else "never"
    if use_pallas == "always":
        m, k = x.shape
        n = qw.shape[1]
        if m % min(m, 256) == 0 and k % min(k, 512) == 0 \
                and n % min(n, 256) == 0:
            return _dequant_matmul_call(x, qw, scale, out_dtype,
                                        interpret)
        from ..monitor import stat_add

        stat_add("quant_pallas_fallback_shape")
    w = qw.astype(jnp.float32) * scale.astype(jnp.float32)[None, :]
    return jnp.dot(x.astype(jnp.float32), w).astype(out_dtype)


def _prod(t):
    p = 1
    for v in t:
        p *= int(v)
    return p


@register_lower("dequant_matmul")
def lower_dequant_matmul(ctx, op):
    """The weight-quantized matmul family: ``Y`` is the int8/fp8
    carrier, ``Scale`` the per-output-channel step sizes.  The op
    preserves the ORIGINAL op's semantics (``orig_type`` attr: mul's
    flattening dims, matmul's transpose flags); the weight is
    dequantized at ``X``'s dtype so AMP-bypassed casts keep their
    numerics.  The fused Pallas path engages for the plain 2D
    column-scaled case; everything else dequantizes then matmuls (XLA
    fuses the product into the dot on TPU anyway)."""
    x = ctx.in1(op, "X")
    qw = ctx.in1(op, "Y")
    scale = ctx.in1(op, "Scale")
    axis = int(op.attr("weight_axis", 1))
    orig = op.attr("orig_type", "matmul_v2")
    use_pallas = op.attr("use_pallas", "auto")
    fused_ok = (qw.ndim == 2 and axis == 1)
    if orig == "mul":
        xn = int(op.attr("x_num_col_dims", 1))
        yn = int(op.attr("y_num_col_dims", 1))
        xs, ys = x.shape, qw.shape
        x2 = x.reshape((-1, int(_prod(xs[xn:]))))
        out_shape = tuple(xs[:xn]) + tuple(ys[yn:])
        if fused_ok and yn == 1:
            out = dequant_matmul(x2, qw, scale, use_pallas=use_pallas,
                                 out_dtype=x.dtype)
        else:
            w = dequantize_weight(qw, scale, axis, x.dtype)
            out = x2 @ w.reshape((int(_prod(ys[:yn])), -1))
        ctx.set_out(op, "Out", out.reshape(out_shape))
        return
    trans_x = bool(op.attr("transpose_X", op.attr("trans_x", False)))
    trans_y = bool(op.attr("transpose_Y", op.attr("trans_y", False)))
    alpha = float(op.attr("alpha", 1.0))
    if fused_ok and not trans_x and not trans_y and x.ndim == 2:
        out = dequant_matmul(x, qw, scale, use_pallas=use_pallas,
                             out_dtype=x.dtype)
    else:
        w = dequantize_weight(qw, scale, axis, x.dtype)
        if trans_x and x.ndim > 1:
            x = jnp.swapaxes(x, -1, -2)
        if trans_y and w.ndim > 1:
            w = jnp.swapaxes(w, -1, -2)
        out = jnp.matmul(x, w)
    if alpha != 1.0:
        out = out * alpha
    ctx.set_out(op, "Out", out)


def quant_quality_delta(logits_q, logits_ref):
    """The quantization tax, measured: max-abs-logit delta and greedy
    top-1 agreement of quantized logits vs their full-precision oracle
    over a fixed eval batch.  Returns the report dict AND mirrors it
    onto /metrics (``quant_quality_max_abs_logit_delta_micro``,
    ``quant_quality_top1_agreement_ppm``) so the tax is monitored,
    never assumed."""
    import numpy as np

    from ..monitor import stat_set

    q = np.asarray(logits_q, dtype=np.float32)
    ref = np.asarray(logits_ref, dtype=np.float32)
    if q.shape != ref.shape:
        raise ValueError(
            f"logit shapes differ: {q.shape} vs {ref.shape}")
    q2 = q.reshape(-1, q.shape[-1])
    r2 = ref.reshape(-1, ref.shape[-1])
    max_abs = float(np.max(np.abs(q2 - r2))) if q2.size else 0.0
    agree = float(np.mean(np.argmax(q2, axis=-1)
                          == np.argmax(r2, axis=-1))) if len(q2) else 1.0
    stat_set("quant_quality_max_abs_logit_delta_micro",
             int(max_abs * 1e6))
    stat_set("quant_quality_top1_agreement_ppm", int(agree * 1e6))
    return {"max_abs_logit_delta": max_abs, "top1_agreement": agree}
