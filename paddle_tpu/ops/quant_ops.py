"""Fake-quantization ops (reference operators/fake_quantize_op.cc:739
family: fake_quantize_abs_max / fake_channel_wise_quantize_abs_max /
fake_quantize_moving_average_abs_max / fake_quantize_range_abs_max and
their *_dequantize_* variants, plus fake_dequantize_max_abs and the
moving_average_abs_max_scale observer).

TPU-native design: quant-dequant SIMULATION stays in float — on TPU the
MXU wants bf16, int8 buys no training-time win, so the value of these
ops is scale calibration + bit-exact export parity, not int arithmetic.
The straight-through estimator falls out of the emission
``x + stop_gradient(qdq(x) - x)``: the generic vjp path
(ops/grad_generic.py) then yields pass-through gradients with zero
bespoke backward kernels (the reference maintains FakeQuantDequantGrad
kernels for the same semantics).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.lowering import register_lower
from .common import as_scalar


def _qmax(op):
    return 2.0 ** (int(op.attr("bit_length", 8)) - 1) - 1


def _abs_max(x):
    return jnp.maximum(jnp.max(jnp.abs(x)), 1e-8)


def _channel_abs_max(x, axis):
    red = tuple(i for i in range(x.ndim) if i != axis)
    return jnp.maximum(jnp.max(jnp.abs(x), axis=red), 1e-8)


def _quant(x, scale, qmax):
    """Quantize to the integer grid, kept in float (reference outputs
    float tensors holding integer values)."""
    return jnp.clip(jnp.round(x / scale * qmax), -qmax, qmax)


def _qdq_ste(x, scale, qmax):
    """Quant-dequant with straight-through gradient."""
    qdq = _quant(x, scale, qmax) * scale / qmax
    return x + jax.lax.stop_gradient(qdq - x)


@register_lower("fake_quantize_abs_max")
def lower_fake_quantize_abs_max(ctx, op):
    x = ctx.in1(op, "X")
    qmax = _qmax(op)
    scale = _abs_max(x)
    ctx.set_out(op, "Out", _quant(x, scale, qmax))
    ctx.set_out(op, "OutScale", jnp.reshape(scale, (1,)))


@register_lower("fake_quantize_dequantize_abs_max")
def lower_fake_quantize_dequantize_abs_max(ctx, op):
    x = ctx.in1(op, "X")
    qmax = _qmax(op)
    scale = _abs_max(x)
    ctx.set_out(op, "Out", _qdq_ste(x, scale, qmax))
    ctx.set_out(op, "OutScale", jnp.reshape(scale, (1,)))


@register_lower("fake_channel_wise_quantize_abs_max")
def lower_fake_channel_wise_quantize_abs_max(ctx, op):
    x = ctx.in1(op, "X")
    axis = int(op.attr("quant_axis", 0))
    qmax = _qmax(op)
    scale = _channel_abs_max(x, axis)
    bshape = [1] * x.ndim
    bshape[axis] = -1
    ctx.set_out(op, "Out", _quant(x, scale.reshape(bshape), qmax))
    ctx.set_out(op, "OutScale", scale)


@register_lower("fake_channel_wise_quantize_dequantize_abs_max")
def lower_fake_channel_wise_qdq_abs_max(ctx, op):
    x = ctx.in1(op, "X")
    axis = int(op.attr("quant_axis", 0))
    qmax = _qmax(op)
    scale = _channel_abs_max(x, axis)
    bshape = [1] * x.ndim
    bshape[axis] = -1
    ctx.set_out(op, "Out", _qdq_ste(x, scale.reshape(bshape), qmax))
    ctx.set_out(op, "OutScale", scale)


def _moving_average_scale(ctx, op, x):
    """Shared accumulator update (fake_quantize_op.cc FindMovingAverage):
    state = rate*state + 1;  accum = rate*accum + abs_max(x);
    scale = accum / state.  In is_test mode the stored scale is used
    unchanged and no state is written."""
    rate = float(op.attr("moving_rate", 0.9))
    in_scale = as_scalar(ctx.in1(op, "InScale"))
    if op.attr("is_test", False):
        return jnp.maximum(in_scale, 1e-8), None, None
    state = as_scalar(ctx.in1(op, "InState"))
    accum = as_scalar(ctx.in1(op, "InAccum"))
    state = rate * state + 1.0
    accum = rate * accum + _abs_max(x)
    scale = accum / state
    return jnp.maximum(scale, 1e-8), state, accum


def _emit_moving_average_state(ctx, op, scale, state, accum):
    ctx.set_out(op, "OutScale", jnp.reshape(scale, (1,)))
    if state is not None:
        ctx.set_out(op, "OutState", jnp.reshape(state, (1,)))
        ctx.set_out(op, "OutAccum", jnp.reshape(accum, (1,)))


@register_lower("fake_quantize_moving_average_abs_max")
def lower_fake_quantize_moving_average_abs_max(ctx, op):
    x = ctx.in1(op, "X")
    qmax = _qmax(op)
    scale, state, accum = _moving_average_scale(ctx, op, x)
    ctx.set_out(op, "Out", _quant(x, scale, qmax))
    _emit_moving_average_state(ctx, op, scale, state, accum)


@register_lower("fake_quantize_dequantize_moving_average_abs_max")
def lower_fake_qdq_moving_average_abs_max(ctx, op):
    x = ctx.in1(op, "X")
    qmax = _qmax(op)
    scale, state, accum = _moving_average_scale(ctx, op, x)
    ctx.set_out(op, "Out", _qdq_ste(x, scale, qmax))
    _emit_moving_average_state(ctx, op, scale, state, accum)


@register_lower("fake_quantize_range_abs_max")
def lower_fake_quantize_range_abs_max(ctx, op):
    """Windowed running-max scale (fake_quantize_op.cc FindRangeAbsMax):
    a [window_size] ring buffer of per-step abs-maxes; the scale is the
    max over the window.  State rides explicit InScales/Iter slots
    (functional in-out pairs, same var wired to both) instead of the
    reference's in-place mutation."""
    x = ctx.in1(op, "X")
    qmax = _qmax(op)
    if op.attr("is_test", False):
        scale = jnp.maximum(as_scalar(ctx.in1(op, "InScale")), 1e-8)
        ctx.set_out(op, "Out", _quant(x, scale, qmax))
        return
    window = int(op.attr("window_size", 10000))
    cur = _abs_max(x)
    scales = ctx.in1(op, "InScales")
    it = jnp.asarray(as_scalar(ctx.in1(op, "Iter")), jnp.int32)
    if scales is None:  # windowless degenerate form: running max
        prev = as_scalar(ctx.in1(op, "InScale"))
        scale = jnp.maximum(jnp.maximum(prev, cur), 1e-8)
    else:
        scales = scales.at[it % window].set(cur)
        scale = jnp.maximum(jnp.max(scales), 1e-8)
        ctx.set_out(op, "OutScales", scales)
    ctx.set_out(op, "Out", _quant(x, scale, qmax))
    ctx.set_out(op, "OutScale", jnp.reshape(scale, (1,)))
    ctx.set_out(op, "OutIter", jnp.reshape(it + 1, (1,)))


@register_lower("moving_average_abs_max_scale")
def lower_moving_average_abs_max_scale(ctx, op):
    """Observer only: Out = X unchanged, scale state updated (used by
    the reference's OutScaleForTrainingPass)."""
    x = ctx.in1(op, "X")
    scale, state, accum = _moving_average_scale(ctx, op, x)
    if ctx.out_name(op, "Out"):
        ctx.set_out(op, "Out", x)
    _emit_moving_average_state(ctx, op, scale, state, accum)


@register_lower("fake_dequantize_max_abs")
def lower_fake_dequantize_max_abs(ctx, op):
    x = ctx.in1(op, "X")
    scale = as_scalar(ctx.in1(op, "Scale"))
    max_range = float(op.attr("max_range", 127.0))
    ctx.set_out(op, "Out", x * scale / max_range)


@register_lower("fake_channel_wise_dequantize_max_abs")
def lower_fake_channel_wise_dequantize_max_abs(ctx, op):
    x = ctx.in1(op, "X")
    scales = ctx.in_list(op, "Scales")
    axis = int(op.attr("quant_axis", 0))
    bits = op.attr("quant_bits", [8])
    bshape = [1] * x.ndim
    bshape[axis] = -1
    out = x * scales[0].reshape(bshape) / (2.0 ** (int(bits[0]) - 1) - 1)
    if len(scales) > 1:  # second-level (whole-tensor) scale, mul path
        out = out * as_scalar(scales[1]) / (2.0 ** (int(bits[1]) - 1) - 1)
    ctx.set_out(op, "Out", out)
