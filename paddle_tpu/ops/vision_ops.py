"""Vision / spatial ops: roi ops, pixel shuffles, grid sampler, 3-D conv,
local response norm, unfold, and friends.

Reference parity: operators/{roi_align,roi_pool,grid_sampler,
pixel_shuffle,space_to_depth,shuffle_channel,unfold,temporal_shift,
affine_channel,label_smooth,lrn,pad_constant_like,crop,crop_tensor,
reverse,conv3d,...}_op.cc and detection/.  Gradients via generic vjp.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.lowering import register_lower
from .nn_ops import _conv_paddings


@register_lower("pixel_shuffle")
def _pixel_shuffle(ctx, op):
    x = ctx.in1(op, "X")  # [N, C*r^2, H, W]
    r = int(op.attr("upscale_factor", 1))
    n, c, h, w = x.shape
    oc = c // (r * r)
    y = x.reshape(n, oc, r, r, h, w)
    y = jnp.transpose(y, (0, 1, 4, 2, 5, 3)).reshape(n, oc, h * r, w * r)
    ctx.set_out(op, "Out", y)


@register_lower("space_to_depth")
def _space_to_depth(ctx, op):
    x = ctx.in1(op, "X")
    b = int(op.attr("blocksize", 1))
    n, c, h, w = x.shape
    y = x.reshape(n, c, h // b, b, w // b, b)
    y = jnp.transpose(y, (0, 3, 5, 1, 2, 4)).reshape(
        n, c * b * b, h // b, w // b)
    ctx.set_out(op, "Out", y)


@register_lower("shuffle_channel")
def _shuffle_channel(ctx, op):
    x = ctx.in1(op, "X")
    g = int(op.attr("group", 1))
    n, c, h, w = x.shape
    y = x.reshape(n, g, c // g, h, w)
    y = jnp.transpose(y, (0, 2, 1, 3, 4)).reshape(n, c, h, w)
    ctx.set_out(op, "Out", y)


@register_lower("temporal_shift")
def _temporal_shift(ctx, op):
    x = ctx.in1(op, "X")  # [N*T, C, H, W]
    t = int(op.attr("seg_num", 1))
    ratio = float(op.attr("shift_ratio", 0.25))
    nt, c, h, w = x.shape
    n = nt // t
    c1 = int(c * ratio)
    c2 = int(c * 2 * ratio)
    y = x.reshape(n, t, c, h, w)
    fwd = jnp.concatenate([y[:, 1:, :c1], jnp.zeros_like(y[:, :1, :c1])], 1)
    bwd = jnp.concatenate([jnp.zeros_like(y[:, :1, c1:c2]), y[:, :-1, c1:c2]], 1)
    keep = y[:, :, c2:]
    out = jnp.concatenate([fwd, bwd, keep], axis=2).reshape(nt, c, h, w)
    ctx.set_out(op, "Out", out)


@register_lower("affine_channel")
def _affine_channel(ctx, op):
    x = ctx.in1(op, "X")
    scale = ctx.in1(op, "Scale")
    bias = ctx.in1(op, "Bias")
    layout = op.attr("data_layout", "NCHW") or "NCHW"
    caxis = 1 if layout == "NCHW" else x.ndim - 1
    shape = [1] * x.ndim
    shape[caxis] = x.shape[caxis]
    ctx.set_out(op, "Out", x * scale.reshape(shape) + bias.reshape(shape))


@register_lower("label_smooth")
def _label_smooth(ctx, op):
    x = ctx.in1(op, "X")
    dist = ctx.in1(op, "PriorDist")
    eps = float(op.attr("epsilon", 0.0))
    k = x.shape[-1]
    if dist is not None:
        out = (1 - eps) * x + eps * dist.reshape((1,) * (x.ndim - 1) + (k,))
    else:
        out = (1 - eps) * x + eps / k
    ctx.set_out(op, "Out", out)


@register_lower("lrn")
def _lrn(ctx, op):
    x = ctx.in1(op, "X")  # NCHW
    n_size = int(op.attr("n", 5))
    alpha = float(op.attr("alpha", 1e-4))
    beta = float(op.attr("beta", 0.75))
    k = float(op.attr("k", 1.0))
    sq = jnp.square(x)
    half = n_size // 2
    pad = jnp.pad(sq, ((0, 0), (half, n_size - 1 - half), (0, 0), (0, 0)))
    acc = sum(pad[:, i:i + x.shape[1]] for i in range(n_size))
    mid = k + alpha * acc
    ctx.set_out(op, "MidOut", mid)
    ctx.set_out(op, "Out", x / jnp.power(mid, beta))


@register_lower("pad_constant_like")
def _pad_constant_like(ctx, op):
    x = ctx.in1(op, "X")  # big
    y = ctx.in1(op, "Y")  # small
    val = float(op.attr("pad_value", 0.0))
    pads = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    ctx.set_out(op, "Out", jnp.pad(y, pads, constant_values=val))


@register_lower("crop", "crop_tensor")
def _crop(ctx, op):
    x = ctx.in1(op, "X")
    offsets = op.attr("offsets", []) or [0] * x.ndim
    shape = op.attr("shape", []) or list(x.shape)
    off_in = ctx.in1(op, "Offsets")
    if off_in is not None:
        offsets = [int(v) for v in np.asarray(off_in)]
    shape = [x.shape[i] if s in (-1, 0) else int(s)
             for i, s in enumerate(shape)]
    sl = tuple(slice(int(o), int(o) + int(s)) for o, s in zip(offsets, shape))
    ctx.set_out(op, "Out", x[sl])


@register_lower("reverse")
def _reverse(ctx, op):
    x = ctx.in1(op, "X")
    axes = [int(a) for a in op.attr("axis", [0])]
    ctx.set_out(op, "Out", jnp.flip(x, axis=tuple(axes)))


@register_lower("unfold")
def _unfold(ctx, op):
    """im2col (reference unfold_op.cc): [N,C,H,W] -> [N, C*kh*kw, L]."""
    x = ctx.in1(op, "X")
    ks = [int(k) for k in op.attr("kernel_sizes", [1, 1])]
    st = [int(s) for s in op.attr("strides", [1, 1])]
    pd = [int(p) for p in op.attr("paddings", [0, 0, 0, 0])]
    dl = [int(d) for d in op.attr("dilations", [1, 1])]
    if len(pd) == 2:
        pd = [pd[0], pd[1], pd[0], pd[1]]
    n, c, h, w = x.shape
    patches = jax.lax.conv_general_dilated_patches(
        x, filter_shape=ks, window_strides=st,
        padding=((pd[0], pd[2]), (pd[1], pd[3])), rhs_dilation=dl,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # patches: [N, C*kh*kw, OH, OW]
    ctx.set_out(op, "Y", patches.reshape(n, patches.shape[1], -1))


@register_lower("grid_sampler")
def _grid_sampler(ctx, op):
    """Grid sampling (reference grid_sampler_op.cc): bilinear/nearest,
    zeros/border padding, align_corners attr honored."""
    x = ctx.in1(op, "X")  # [N, C, H, W]
    grid = ctx.in1(op, "Grid")  # [N, Ho, Wo, 2] in [-1, 1]
    mode = op.attr("mode", "bilinear") or "bilinear"
    padding_mode = op.attr("padding_mode", "zeros") or "zeros"
    align_corners = bool(op.attr("align_corners", True))
    if padding_mode not in ("zeros", "border", "reflection"):
        raise NotImplementedError(
            f"grid_sampler padding_mode {padding_mode!r} is not lowered")
    n, c, h, w = x.shape
    if align_corners:
        gx = (grid[..., 0] + 1.0) * (w - 1) / 2.0
        gy = (grid[..., 1] + 1.0) * (h - 1) / 2.0
    else:
        gx = ((grid[..., 0] + 1.0) * w - 1.0) / 2.0
        gy = ((grid[..., 1] + 1.0) * h - 1.0) / 2.0

    if padding_mode == "reflection":
        # reflect coordinates (reference GridSampler reflection: over
        # [0, S-1] with align_corners, [-0.5, S-0.5] without), then
        # border-clamp for the actual taps
        def _reflect(coord, size):
            if align_corners:
                span = size - 1
                if span == 0:
                    return jnp.zeros_like(coord)
                t = jnp.mod(coord, 2.0 * span)
                return jnp.where(t > span, 2.0 * span - t, t)
            t = jnp.mod(coord + 0.5, 2.0 * size)
            t = size - jnp.abs(t - size)
            return jnp.clip(t - 0.5, 0.0, size - 1)

        gx = _reflect(gx, w)
        gy = _reflect(gy, h)
        padding_mode = "border"

    if mode == "nearest":
        def gather(yy, xx):
            yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            vals = jax.vmap(lambda img, ys, xs: img[:, ys, xs])(x, yc, xc)
            if padding_mode == "zeros":
                valid = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
                vals = vals * valid[:, None].astype(x.dtype)
            return vals

        out = gather(jnp.round(gy), jnp.round(gx))
    else:
        from .common import bilinear_sample_chw

        out = jax.vmap(
            lambda img, ys, xs: bilinear_sample_chw(
                img, ys, xs, padding=padding_mode))(x, gy, gx)
    ctx.set_out(op, "Output", out)


def _roi_boxes(ctx, op):
    rois = ctx.in1(op, "ROIs")  # [R, 4] (x1, y1, x2, y2)
    rois_num = op.inputs.get("RoisNum") or op.inputs.get("RoisLod")
    # batch assignment: RoisNum gives per-image counts; without it all
    # rois belong to image 0 (single-image static case)
    if rois_num:
        counts = ctx.get(rois_num[0])
        batch_idx = jnp.repeat(
            jnp.arange(counts.shape[0]), counts.astype(jnp.int32),
            total_repeat_length=rois.shape[0])
    else:
        batch_idx = jnp.zeros((rois.shape[0],), jnp.int32)
    return rois, batch_idx


@register_lower("roi_align")
def _roi_align(ctx, op):
    x = ctx.in1(op, "X")  # [N, C, H, W]
    rois, batch_idx = _roi_boxes(ctx, op)
    ph = int(op.attr("pooled_height", 1))
    pw = int(op.attr("pooled_width", 1))
    scale = float(op.attr("spatial_scale", 1.0))
    ratio = int(op.attr("sampling_ratio", -1))
    ratio = ratio if ratio > 0 else 2
    # aligned=True (paddle 2.x roi_align default): -0.5 pixel offset and
    # no min-size clamp (Detectron2 "aligned" correction)
    aligned = bool(op.attr("aligned", False))
    n, c, h, w = x.shape

    def one_roi(roi, bi):
        img = x[bi]  # [C, H, W]
        off = 0.5 if aligned else 0.0
        x1, y1, x2, y2 = roi * scale - off
        if aligned:
            rh = y2 - y1
            rw = x2 - x1
        else:
            rh = jnp.maximum(y2 - y1, 1.0)
            rw = jnp.maximum(x2 - x1, 1.0)
        bh, bw = rh / ph, rw / pw
        iy = (jnp.arange(ph)[:, None] * bh + y1
              + (jnp.arange(ratio)[None, :] + 0.5) * bh / ratio)  # [ph, r]
        ix = (jnp.arange(pw)[:, None] * bw + x1
              + (jnp.arange(ratio)[None, :] + 0.5) * bw / ratio)  # [pw, r]
        yy = iy.reshape(-1)  # [ph*r]
        xx = ix.reshape(-1)  # [pw*r]

        y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
        x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
        wy = jnp.clip(yy, 0, h - 1) - y0
        wx = jnp.clip(xx, 0, w - 1) - x0
        y0 = y0.astype(jnp.int32)
        x0 = x0.astype(jnp.int32)
        # bilinear at the [ph*r, pw*r] grid of sample points
        def at(yi, xi):
            return img[:, yi][:, :, xi]  # [C, ph*r, pw*r]
        v = (at(y0, x0) * ((1 - wy)[:, None] * (1 - wx)[None, :])
             + at(y0, x1i) * ((1 - wy)[:, None] * wx[None, :])
             + at(y1i, x0) * (wy[:, None] * (1 - wx)[None, :])
             + at(y1i, x1i) * (wy[:, None] * wx[None, :]))
        v = v.reshape(c, ph, ratio, pw, ratio)
        return v.mean(axis=(2, 4))

    out = jax.vmap(one_roi)(rois, batch_idx)
    ctx.set_out(op, "Out", out)


@register_lower("roi_pool")
def _roi_pool(ctx, op):
    x = ctx.in1(op, "X")
    rois, batch_idx = _roi_boxes(ctx, op)
    ph = int(op.attr("pooled_height", 1))
    pw = int(op.attr("pooled_width", 1))
    scale = float(op.attr("spatial_scale", 1.0))
    n, c, h, w = x.shape

    def one_roi(roi, bi):
        img = x[bi]
        x1 = jnp.round(roi[0] * scale)
        y1 = jnp.round(roi[1] * scale)
        x2 = jnp.round(roi[2] * scale)
        y2 = jnp.round(roi[3] * scale)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        bh, bw = rh / ph, rw / pw
        ys = jnp.arange(h, dtype=x.dtype)
        xs = jnp.arange(w, dtype=x.dtype)
        out = jnp.zeros((c, ph, pw), x.dtype)
        # membership masks per output bin (static ph*pw loop)
        vals = []
        for i in range(ph):
            ylo = jnp.floor(y1 + i * bh)
            yhi = jnp.ceil(y1 + (i + 1) * bh)
            ym = ((ys >= ylo) & (ys < yhi)).astype(x.dtype)
            for j in range(pw):
                xlo = jnp.floor(x1 + j * bw)
                xhi = jnp.ceil(x1 + (j + 1) * bw)
                xm = ((xs >= xlo) & (xs < xhi)).astype(x.dtype)
                m = ym[:, None] * xm[None, :]
                neg = jnp.full_like(img, -jnp.inf)
                sel = jnp.where(m[None] > 0, img, neg)
                v = jnp.max(sel, axis=(1, 2))
                vals.append(jnp.where(jnp.isfinite(v), v, 0.0))
        return jnp.stack(vals, axis=1).reshape(c, ph, pw)

    out = jax.vmap(one_roi)(rois, batch_idx)
    ctx.set_out(op, "Out", out)
    ctx.set_out(op, "Argmax", jnp.zeros(out.shape, jnp.int32))


@register_lower("conv3d")
def _conv3d(ctx, op):
    x = ctx.in1(op, "Input")  # NCDHW
    w = ctx.in1(op, "Filter")  # OIDHW
    strides = [int(s) for s in op.attr("strides", [1, 1, 1])]
    dilations = [int(d) for d in op.attr("dilations", [1, 1, 1])]
    groups = int(op.attr("groups", 1) or 1)
    pads = _conv_paddings(
        op.attr("paddings", [0, 0, 0]), op.attr("padding_algorithm", "EXPLICIT"),
        w.shape[2:], strides, dilations, x.shape[2:])
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pads, rhs_dilation=dilations,
        feature_group_count=groups,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
    ctx.set_out(op, "Output", out)


@register_lower("pool3d")
def _pool3d(ctx, op):
    x = ctx.in1(op, "X")  # NCDHW
    ptype = op.attr("pooling_type", "max")
    ksize = [int(k) for k in op.attr("ksize", [1, 1, 1])]
    strides = [int(s) for s in op.attr("strides", [1, 1, 1])]
    if bool(op.attr("global_pooling", False)):
        red = jnp.max if ptype == "max" else jnp.mean
        ctx.set_out(op, "Out", red(x, axis=(2, 3, 4), keepdims=True))
        return
    pads = _conv_paddings(
        op.attr("paddings", [0, 0, 0]), op.attr("padding_algorithm", "EXPLICIT"),
        ksize, strides, [1, 1, 1], x.shape[2:])
    window = (1, 1) + tuple(ksize)
    st = (1, 1) + tuple(strides)
    pd = [(0, 0), (0, 0)] + pads
    if ptype == "max":
        out = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window, st, pd)
    else:
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, st, pd)
        cnt = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                    window, st, pd)
        out = s / cnt
    ctx.set_out(op, "Out", out)


@register_lower("max_pool2d_with_index")
def _max_pool2d_with_index(ctx, op):
    """Max pool returning the flat h*w argmax per window (reference
    max_pool2d_with_index; the Mask feeds unpool)."""
    x = ctx.in1(op, "X")
    ksize = [int(k) for k in op.attr("ksize", [1, 1])]
    strides = [int(s) for s in op.attr("strides", [1, 1])]
    paddings = [int(p) for p in op.attr("paddings", [0, 0])]
    if bool(op.attr("global_pooling", False)):
        ksize = list(x.shape[2:])
        paddings = [0, 0]
    n, c, h, w = x.shape
    if bool(op.attr("adaptive", False)):
        # adaptive bins (AdaptiveMaxPool2D): ksize IS the output size
        oh, ow = ksize
        if h % oh or w % ow:
            # non-divisible: per-cell variable windows (floor/ceil
            # bounds) via a fixed max-width gather; argmax over the
            # masked window recovers the flat h*w index the Mask
            # contract needs
            from .common import adaptive_max_with_index

            out, flat = adaptive_max_with_index(x, (oh, ow))
            ctx.set_out(op, "Out", out)
            ctx.set_out(op, "Mask", flat)
            return
        ksize = [h // oh, w // ow]
        strides = [h // oh, w // ow]
        paddings = [0, 0]
    kh, kw = ksize
    # pad with -inf so padding never wins the max, then VALID patches
    xp = jnp.pad(x, ((0, 0), (0, 0), (paddings[0],) * 2, (paddings[1],) * 2),
                 constant_values=-jnp.inf)
    patches = jax.lax.conv_general_dilated_patches(
        xp, filter_shape=ksize, window_strides=strides, padding="VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    oh, ow = patches.shape[2], patches.shape[3]
    pv = patches.reshape(n, c, kh * kw, oh, ow)
    out = jnp.max(pv, axis=2)
    arg = jnp.argmax(pv, axis=2)  # window-local index
    hs = (jnp.arange(oh) * strides[0] - paddings[0])[:, None]
    ws = (jnp.arange(ow) * strides[1] - paddings[1])[None, :]
    flat = (hs + arg // kw) * w + (ws + arg % kw)
    ctx.set_out(op, "Out", out)
    # int32: x64 is disabled on TPU; an int64 annotation would
    # silently truncate anyway (documented contract)
    ctx.set_out(op, "Mask", flat.astype(jnp.int32))


@register_lower("im2sequence")
def _im2sequence(ctx, op):
    x = ctx.in1(op, "X")
    ks = [int(k) for k in op.attr("kernels", [1, 1])]
    st = [int(s) for s in op.attr("strides", [1, 1])]
    pd = [int(p) for p in op.attr("paddings", [0, 0, 0, 0])]
    n, c, h, w = x.shape
    patches = jax.lax.conv_general_dilated_patches(
        x, filter_shape=ks, window_strides=st,
        padding=((pd[0], pd[2]), (pd[1], pd[3])),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # [N, C*kh*kw, OH, OW] -> [N*OH*OW, C*kh*kw]
    nck = patches.shape[1]
    out = jnp.transpose(patches, (0, 2, 3, 1)).reshape(-1, nck)
    ctx.set_out(op, "Out", out)


@register_lower("cvm")
def _cvm(ctx, op):
    x = ctx.in1(op, "X")
    use_cvm = bool(op.attr("use_cvm", True))
    if use_cvm:
        # log the first two "show/click" columns (reference cvm_op semantics)
        sc = jnp.log1p(jnp.maximum(x[:, :2], 0.0))
        ctx.set_out(op, "Y", jnp.concatenate([sc, x[:, 2:]], axis=1))
    else:
        ctx.set_out(op, "Y", x[:, 2:])
