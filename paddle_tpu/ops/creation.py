"""Creation / random ops.

Reference parity: operators/fill_constant_op.cc, gaussian_random_op.cc,
uniform_random_op.cc, truncated_gaussian_random_op.cc, assign_value_op.cc,
fill_zeros_like_op.cc, range_op.cc, linspace_op.cc, eye_op.cc.
RNG is threefry (TPU-native); bitwise parity with the reference's Philox
streams is a non-goal (SURVEY.md §7 'RNG parity').
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.lowering import register_lower
from .common import attr_dtype, op_seed_key


@register_lower("fill_constant")
def _fill_constant(ctx, op):
    dtype = attr_dtype(op)
    shape = [int(s) for s in op.attr("shape", [])]
    st = op.inputs.get("ShapeTensor") or op.inputs.get("ShapeTensorList")
    if st:
        # XLA needs static shapes: the shape tensor must be concrete here
        vals = [ctx.get(n) for n in st]
        try:
            if len(vals) == 1 and np.asarray(vals[0]).size > 1:
                shape = [int(v) for v in np.asarray(vals[0])]
            else:
                shape = [int(np.asarray(v).item()) for v in vals]
        except Exception as e:  # traced (data-dependent) shape
            raise NotImplementedError(
                "fill_constant with a runtime-computed ShapeTensor is not "
                "supported under XLA static shapes; pass the shape attr"
            ) from e
    value = op.attr("value", 0.0)
    if op.attr("str_value", ""):
        value = float(op.attr("str_value"))
    ctx.set_out(op, "Out", jnp.full(shape, value, dtype=dtype))


@register_lower("fill_any_like", "fill_zeros_like")
def _fill_any_like(ctx, op):
    x = ctx.in1(op, "X")
    value = op.attr("value", 0.0)
    dt = op.attr("dtype", -1)
    dtype = x.dtype if dt in (-1, 0, None) else attr_dtype(op)
    ctx.set_out(op, "Out", jnp.full(x.shape, value, dtype=dtype))


@register_lower("gaussian_random")
def _gaussian_random(ctx, op):
    dtype = attr_dtype(op)
    shape = [int(s) for s in op.attr("shape", [])]
    mean = op.attr("mean", 0.0)
    std = op.attr("std", 1.0)
    k = op_seed_key(ctx, op)
    out = mean + std * jax.random.normal(k, shape, dtype=jnp.float32)
    ctx.set_out(op, "Out", out.astype(dtype))


@register_lower("truncated_gaussian_random")
def _truncated_gaussian_random(ctx, op):
    dtype = attr_dtype(op)
    shape = [int(s) for s in op.attr("shape", [])]
    mean = op.attr("mean", 0.0)
    std = op.attr("std", 1.0)
    k = op_seed_key(ctx, op)
    out = mean + std * jax.random.truncated_normal(k, -2.0, 2.0, shape, dtype=jnp.float32)
    ctx.set_out(op, "Out", out.astype(dtype))


@register_lower("uniform_random")
def _uniform_random(ctx, op):
    dtype = attr_dtype(op)
    shape = [int(s) for s in op.attr("shape", [])]
    lo = op.attr("min", -1.0)
    hi = op.attr("max", 1.0)
    k = op_seed_key(ctx, op)
    out = jax.random.uniform(k, shape, minval=lo, maxval=hi, dtype=jnp.float32)
    ctx.set_out(op, "Out", out.astype(dtype))


@register_lower("randint")
def _randint(ctx, op):
    dtype = attr_dtype(op, default="int64")
    shape = [int(s) for s in op.attr("shape", [])]
    k = op_seed_key(ctx, op)
    out = jax.random.randint(k, shape, op.attr("low", 0), op.attr("high", 1))
    ctx.set_out(op, "Out", out.astype(dtype))


@register_lower("randperm")
def _randperm(ctx, op):
    n = int(op.attr("n"))
    k = op_seed_key(ctx, op)
    ctx.set_out(op, "Out", jax.random.permutation(k, n).astype(attr_dtype(op, default="int64")))


@register_lower("dropout")
def _dropout(ctx, op):
    x = ctx.in1(op, "X")
    p = float(op.attr("dropout_prob", 0.5))
    is_test = bool(op.attr("is_test", False))
    impl = op.attr("dropout_implementation", "downgrade_in_infer")
    if is_test:
        out = x if impl == "upscale_in_train" else x * (1.0 - p)
        ctx.set_out(op, "Out", out)
        ctx.set_out(op, "Mask", jnp.ones_like(x, dtype=jnp.uint8))
        return
    # per_shard: each dp shard masks ITS batch slice independently
    k = op_seed_key(ctx, op, per_shard=True)
    keep = jax.random.bernoulli(k, 1.0 - p, x.shape)
    if impl == "upscale_in_train":
        scale = 0.0 if p >= 1.0 else 1.0 / (1.0 - p)
        out = jnp.where(keep, x * scale, jnp.zeros_like(x))
    else:
        out = jnp.where(keep, x, jnp.zeros_like(x))
    ctx.set_out(op, "Out", out)
    ctx.set_out(op, "Mask", keep.astype(jnp.uint8))


@register_lower("dropout_grad")
def _dropout_grad(ctx, op):
    dy = ctx.in1(op, "Out@GRAD")
    mask = ctx.in1(op, "Mask")
    p = float(op.attr("dropout_prob", 0.5))
    impl = op.attr("dropout_implementation", "downgrade_in_infer")
    keep = mask.astype(dy.dtype)
    if impl == "upscale_in_train":
        scale = 0.0 if p >= 1.0 else 1.0 / (1.0 - p)
        dx = dy * keep * scale
    else:
        dx = dy * keep
    ctx.set_out(op, "X@GRAD", dx)


@register_lower("range")
def _range(ctx, op):
    start = ctx.in1(op, "Start")
    end = ctx.in1(op, "End")
    step = ctx.in1(op, "Step")
    # XLA needs static sizes: range bounds must be trace-time constants.
    start, end, step = (np.asarray(v).item() for v in (start, end, step))
    ctx.set_out(op, "Out", jnp.arange(start, end, step))


@register_lower("linspace")
def _linspace(ctx, op):
    start = np.asarray(ctx.in1(op, "Start")).item()
    stop = np.asarray(ctx.in1(op, "Stop")).item()
    num = int(np.asarray(ctx.in1(op, "Num")).item())
    ctx.set_out(op, "Out", jnp.linspace(start, stop, num, dtype=attr_dtype(op)))


@register_lower("eye")
def _eye(ctx, op):
    n = int(op.attr("num_rows"))
    m = int(op.attr("num_columns", -1))
    m = n if m in (-1, 0) else m
    ctx.set_out(op, "Out", jnp.eye(n, m, dtype=attr_dtype(op)))


@register_lower("assign")
def _assign(ctx, op):
    ctx.set_out(op, "Out", ctx.in1(op, "X"))


@register_lower("assign_value")
def _assign_value(ctx, op):
    dtype = attr_dtype(op)
    shape = [int(s) for s in op.attr("shape", [])]
    for key in ("fp32_values", "int32_values", "int64_values", "bool_values"):
        vals = op.attr(key, None)
        if vals:
            ctx.set_out(op, "Out", jnp.asarray(vals, dtype=dtype).reshape(shape))
            return
    ctx.set_out(op, "Out", jnp.zeros(shape, dtype=dtype))
