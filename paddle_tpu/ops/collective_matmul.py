"""Latency-hiding collective matmul (FLAGS_collective_matmul_chunks).

"Overlapping Communication with Dependent Computation via
Decomposition" (Wang et al., ASPLOS 2023) applied to the Megatron
row-parallel pattern this framework's ShardingPropagationPass anchors:
a matmul whose contraction dim is mp-sharded produces a PARTIAL sum
that must be reduced over 'mp'.  Lowered whole, the reduce serializes
behind the full matmul — wire time fully exposed.  Decomposed into k
output-row chunks, chunk i's reduce is independent of chunk i+1's
matmul, so hardware with async collectives (TPU) overlaps them; the
last chunk's reduce is the only exposed latency.

Two consumers:

- the GSPMD tensor-parallel path (``framework/executor.py``
  trace_block): each chunk's partial output gets the anchor's
  ``with_sharding_constraint``, so XLA places one mp reduce PER CHUNK
  and its latency-hiding scheduler interleaves them with the remaining
  chunk matmuls;
- the manual pipeline×mp path (``distributed/pipeline.py``): each
  chunk is psum'd over 'mp' through the Megatron g operator
  (:func:`g_psum`) explicitly.

The decomposition re-lowers the ORIGINAL op per chunk (the chunk rides
the op's own registered lowering with a sliced X), so mul's
flatten-dims and matmul's transpose handling are never re-implemented
— and the math per output element is the unchanged contraction, which
is why the jnp semantics stay exact on CPU tier-1 runs.

Chunking is a pure trace-time rewrite: a shape the chunk count does
not divide (including the chunked dim's mesh-axis sharding) falls back
to the unchunked lowering, counted ``collective_matmul_fallback``.
"""
from __future__ import annotations

import functools

__all__ = ["f_identity", "g_psum", "chunk_row_axis", "chunked_lower",
           "maybe_chunked_gspmd"]


@functools.lru_cache(maxsize=None)
def _g_fn(axis):
    """Megatron's g operator: forward all-reduce over ``axis``, backward
    identity (the cotangent of the replicated sum IS each shard's
    cotangent — an explicit vjp, so the manual pipeline×mp backward
    never depends on jax's psum-transpose conventions)."""
    import jax
    from jax import lax

    @jax.custom_vjp
    def g(x):
        return lax.psum(x, axis)

    def fwd(x):
        return lax.psum(x, axis), None

    def bwd(_, ct):
        return (ct,)

    g.defvjp(fwd, bwd)
    return g


@functools.lru_cache(maxsize=None)
def _f_fn(axis):
    """Megatron's f operator: forward identity, backward all-reduce —
    wrapped around the replicated INPUT of a column-parallel matmul so
    the input's cotangent (each mp rank contributes only its weight
    shard's share) is summed to the full gradient."""
    import jax
    from jax import lax

    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, ct):
        return (lax.psum(ct, axis),)

    f.defvjp(fwd, bwd)
    return f


def g_psum(x, axis):
    return _g_fn(axis)(x)


def f_identity(x, axis):
    return _f_fn(axis)(x)


def chunk_row_axis(op, x):
    """The axis of X that carries the op's output rows — the safe
    chunking dim for the decomposition (chunking rows never touches the
    contraction, so per-element numerics are unchanged).  None when the
    op shape/attrs put row chunking out of scope (trans_x, mul with
    x_num_col_dims != 1, vectors)."""
    nd = getattr(x, "ndim", 0)
    if op.type == "mul":
        # x flattened at x_num_col_dims: only the single-row-dim form
        # chunks cleanly (xs[:1] survives into the output shape)
        if int(op.attr("x_num_col_dims", 1) or 1) != 1 or nd < 2:
            return None
        return 0
    if op.type in ("matmul", "matmul_v2"):
        if bool(op.attr("transpose_X", op.attr("trans_x", False))):
            return None
        if op.type == "matmul" and float(op.attr("alpha", 1.0)) != 1.0:
            # alpha scales the whole product; chunk-exactness holds but
            # keep the first cut conservative
            return None
        if nd < 2:
            return None
        return nd - 2
    return None


def chunked_lower(ctx, op, k, per_chunk, mesh=None, chunk_spec=None):
    """Lower matmul-family ``op`` as ``k`` row chunks: slice X along its
    row axis, re-run the op's own registered lowering per chunk, apply
    ``per_chunk(value, index)`` to each chunk's output (the GSPMD
    sharding constraint, or the manual mp psum), and concatenate.

    Returns True when the chunked lowering was emitted; False when the
    shape/attrs fall outside the decomposition's scope (the caller then
    lowers unchunked — counted ``collective_matmul_fallback``).
    ``chunk_spec`` (the anchor's partition tuple) guards divisibility:
    the chunked output dim must still divide over its mesh axis."""
    import jax.numpy as jnp

    from ..framework.lowering import get_lowering as _get_lowering
    from ..monitor import stat_add
    from ..observe import tracer as otrace

    k = int(k)
    if k <= 1:
        return False
    xs = op.inputs.get("X", [])
    outs = op.output_arg_names()
    if len(xs) != 1 or len(outs) != 1:
        return False
    x = ctx.env.get(xs[0])
    if x is None:
        return False
    axis = chunk_row_axis(op, x)
    if axis is None:
        return False
    rows = int(x.shape[axis])
    if rows % k != 0:
        stat_add("collective_matmul_fallback")
        return False
    # the chunked OUTPUT dim: mul keeps row dim 0; matmul keeps ndim-2.
    # When the anchor spec shards that dim over a mesh axis, every chunk
    # must still divide over it or GSPMD degrades the layout per chunk.
    if chunk_spec and mesh is not None:
        out_axis = 0 if op.type == "mul" else max(len(chunk_spec) - 2, 0)
        ax_name = chunk_spec[out_axis] if out_axis < len(chunk_spec) \
            else None
        if ax_name is not None and ax_name in mesh.axis_names \
                and (rows // k) % int(mesh.shape[ax_name]) != 0:
            stat_add("collective_matmul_fallback")
            return False

    step = rows // k
    pieces = []
    orig_x = x
    orig_out = ctx.env.get(outs[0])
    try:
        for i in range(k):
            sl = [slice(None)] * x.ndim
            sl[axis] = slice(i * step, (i + 1) * step)
            ctx.env[xs[0]] = x[tuple(sl)]
            with otrace.span("overlap/chunk", i=i, op=op.type):
                _get_lowering(op.type)(ctx, op)
                pieces.append(per_chunk(ctx.env[outs[0]], i))
    finally:
        ctx.env[xs[0]] = orig_x
        if orig_out is not None:
            ctx.env[outs[0]] = orig_out
        else:
            ctx.env.pop(outs[0], None)
    out_axis = 0 if op.type == "mul" else pieces[0].ndim - 2
    ctx.env[outs[0]] = jnp.concatenate(pieces, axis=out_axis)
    stat_add("collective_matmul_chunked")
    return True


def maybe_chunked_gspmd(ctx, op, mesh, k):
    """GSPMD-path driver: chunk a matmul-family op whose SINGLE anchor
    is a partial-sum (contracted) anchor on its own output, pinning
    each chunk's partial with the anchor's sharding constraint so XLA
    emits one mp reduce per chunk.  Returns True when the chunked
    lowering replaced the normal one (the caller then skips both the
    plain lowering and ``apply_tp_constraints``)."""
    from ..framework.passes import TP_CONSTRAINT_ATTR, decode_anchor
    from ..monitor import stat_add

    ents = op.attr(TP_CONSTRAINT_ATTR, []) or []
    anchors = [decode_anchor(e) for e in ents]
    outs = op.output_arg_names()
    partial = [(n, s) for n, s, p in anchors if p]
    if len(anchors) != 1 or len(partial) != 1 or len(outs) != 1 \
            or partial[0][0] != outs[0]:
        return False  # not a chunk candidate (e.g. a layout anchor)
    # GSPMD scope guard — checked only for REAL candidates so the
    # fallback counter means "a chunkable op was not chunked": the
    # decomposition is only emitted on an mp-ONLY tp mesh.  With a live
    # dp axis, XLA's SPMD partitioner (probed on this jax/jaxlib)
    # mis-partitions the sliced-operand + partial-constraint pattern —
    # the chunk values come back scaled by the mp degree, and interior
    # pins don't help because the dp layout of DOWNSTREAM consumers
    # back-propagates into the chunk region.  The dp×mp(×pp)
    # compositions get their chunked collective matmul through the
    # pipeline's manual shard_map path instead, where the per-chunk
    # psum is explicit and exact.
    if any(a != "mp" and int(mesh.shape[a]) > 1 for a in mesh.axis_names):
        stat_add("collective_matmul_fallback")
        return False
    spec = partial[0][1]
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    sh = NamedSharding(mesh, PartitionSpec(*spec))

    def per_chunk(v, _i):
        if getattr(v, "ndim", None) != len(spec):
            return v
        return jax.lax.with_sharding_constraint(v, sh)

    return chunked_lower(ctx, op, k, per_chunk, mesh=mesh,
                         chunk_spec=spec)
