"""Optimizer update ops.

Reference parity: operators/optimizers/ (sgd_op.cc, momentum_op.cc,
adam_op.cc, adamax_op.cc, adagrad_op.cc, adadelta_op.cc, rmsprop_op.cc,
ftrl_op.cc, lamb_op.cc, lars_momentum_op.cc) and operators/amp/
(check_finite_and_unscale_op.cc, update_loss_scaling_op.cc).

These run inside the same compiled train-step XLA computation as forward
and backward — the whole reference "executor hot loop" is one executable.
Param outputs reuse the param var name, so the SSA env + donated state give
in-place update memory behavior.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.lowering import register_lower
from .common import as_scalar


@register_lower("sgd")
def _sgd(ctx, op):
    p = ctx.in1(op, "Param")
    g = ctx.in1(op, "Grad")
    lr = as_scalar(ctx.in1(op, "LearningRate"))
    ctx.set_out(op, "ParamOut", (p - lr.astype(p.dtype) * g.astype(p.dtype)).astype(p.dtype))


@register_lower("momentum")
def _momentum(ctx, op):
    p = ctx.in1(op, "Param")
    g = ctx.in1(op, "Grad").astype(p.dtype)
    v = ctx.in1(op, "Velocity")
    lr = as_scalar(ctx.in1(op, "LearningRate")).astype(p.dtype)
    mu = jnp.asarray(op.attr("mu", 0.9), p.dtype)
    use_nesterov = bool(op.attr("use_nesterov", False))
    rd = float(op.attr("regularization_coeff", 0.0))
    if op.attr("regularization_method", "") == "l2_decay" and rd:
        g = g + rd * p
    v_new = mu * v + g
    if use_nesterov:
        p_new = p - lr * (g + mu * v_new)
    else:
        p_new = p - lr * v_new
    ctx.set_out(op, "ParamOut", p_new)
    ctx.set_out(op, "VelocityOut", v_new)


@register_lower("adam", "adamw")
def _adam(ctx, op):
    p = ctx.in1(op, "Param")
    # barrier: without it XLA fuses the weight-grad dot INTO the update
    # kernel (kOutput fusion), demoting the contraction from an MXU
    # custom-call to a vector-unit transpose-reuse emitter (~6x slower on
    # BERT's [3072,768] params); the barrier materializes the grad and
    # keeps the dot on the MXU
    g = jax.lax.optimization_barrier(
        ctx.in1(op, "Grad").astype(jnp.float32))
    m1 = ctx.in1(op, "Moment1")
    m2 = ctx.in1(op, "Moment2")
    b1p = ctx.in1(op, "Beta1Pow")
    b2p = ctx.in1(op, "Beta2Pow")
    lr = as_scalar(ctx.in1(op, "LearningRate")).astype(jnp.float32)
    b1 = jnp.asarray(op.attr("beta1", 0.9), jnp.float32)
    b2 = jnp.asarray(op.attr("beta2", 0.999), jnp.float32)
    eps = jnp.asarray(op.attr("epsilon", 1e-8), jnp.float32)

    pf = p.astype(jnp.float32)
    if op.type == "adamw":
        coeff = float(op.attr("coeff", op.attr("weight_decay", 0.01)))
        with_decay = bool(op.attr("with_decay", True))
        if with_decay:
            pf = pf * (1.0 - lr * coeff)
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * jnp.square(g)
    # reference adam_op: bias correction uses the *input* pows (beta^t at
    # step t, accumulators initialized to beta), pows advance afterwards
    lr_t = lr * jnp.sqrt(1 - as_scalar(b2p)) / (1 - as_scalar(b1p))
    b1pn = b1p * b1
    b2pn = b2p * b2
    pn = pf - lr_t * m1n / (jnp.sqrt(m2n) + eps)
    ctx.set_out(op, "ParamOut", pn.astype(p.dtype))
    ctx.set_out(op, "Moment1Out", m1n)
    ctx.set_out(op, "Moment2Out", m2n)
    ctx.set_out(op, "Beta1PowOut", b1pn)
    ctx.set_out(op, "Beta2PowOut", b2pn)


@register_lower("adamax")
def _adamax(ctx, op):
    p = ctx.in1(op, "Param")
    g = ctx.in1(op, "Grad")
    m = ctx.in1(op, "Moment")
    inf_norm = ctx.in1(op, "InfNorm")
    b1p = ctx.in1(op, "Beta1Pow")
    lr = as_scalar(ctx.in1(op, "LearningRate"))
    b1 = op.attr("beta1", 0.9)
    b2 = op.attr("beta2", 0.999)
    eps = op.attr("epsilon", 1e-8)
    mn = b1 * m + (1 - b1) * g
    inf_n = jnp.maximum(b2 * inf_norm, jnp.abs(g) + eps)
    pn = p - (lr / (1 - as_scalar(b1p))) * (mn / inf_n)
    ctx.set_out(op, "ParamOut", pn)
    ctx.set_out(op, "MomentOut", mn)
    ctx.set_out(op, "InfNormOut", inf_n)


@register_lower("adagrad")
def _adagrad(ctx, op):
    p = ctx.in1(op, "Param")
    g = ctx.in1(op, "Grad")
    mom = ctx.in1(op, "Moment")
    lr = as_scalar(ctx.in1(op, "LearningRate"))
    eps = op.attr("epsilon", 1e-6)
    mn = mom + jnp.square(g)
    pn = p - lr * g / (jnp.sqrt(mn) + eps)
    ctx.set_out(op, "ParamOut", pn)
    ctx.set_out(op, "MomentOut", mn)


@register_lower("adadelta")
def _adadelta(ctx, op):
    p = ctx.in1(op, "Param")
    g = ctx.in1(op, "Grad")
    avg_sq = ctx.in1(op, "AvgSquaredGrad")
    avg_upd = ctx.in1(op, "AvgSquaredUpdate")
    rho = op.attr("rho", 0.95)
    eps = op.attr("epsilon", 1e-6)
    asq = rho * avg_sq + (1 - rho) * jnp.square(g)
    upd = jnp.sqrt(avg_upd + eps) / jnp.sqrt(asq + eps) * g
    aupd = rho * avg_upd + (1 - rho) * jnp.square(upd)
    ctx.set_out(op, "ParamOut", p - upd)
    ctx.set_out(op, "AvgSquaredGradOut", asq)
    ctx.set_out(op, "AvgSquaredUpdateOut", aupd)


@register_lower("rmsprop")
def _rmsprop(ctx, op):
    p = ctx.in1(op, "Param")
    g = ctx.in1(op, "Grad")
    ms = ctx.in1(op, "MeanSquare")
    mom = ctx.in1(op, "Moment")
    lr = as_scalar(ctx.in1(op, "LearningRate"))
    eps = op.attr("epsilon", 1e-10)
    rho = op.attr("decay", 0.9)
    momentum = op.attr("momentum", 0.0)
    centered = bool(op.attr("centered", False))
    msn = rho * ms + (1 - rho) * jnp.square(g)
    if centered:
        mg = ctx.in1(op, "MeanGrad")
        mgn = rho * mg + (1 - rho) * g
        denom = msn - jnp.square(mgn) + eps
        ctx.set_out(op, "MeanGradOut", mgn)
    else:
        denom = msn + eps
    momn = momentum * mom + lr * g / jnp.sqrt(denom)
    ctx.set_out(op, "ParamOut", p - momn)
    ctx.set_out(op, "MeanSquareOut", msn)
    ctx.set_out(op, "MomentOut", momn)


@register_lower("lamb")
def _lamb(ctx, op):
    p = ctx.in1(op, "Param")
    g = ctx.in1(op, "Grad").astype(jnp.float32)
    m1 = ctx.in1(op, "Moment1")
    m2 = ctx.in1(op, "Moment2")
    b1p = ctx.in1(op, "Beta1Pow")
    b2p = ctx.in1(op, "Beta2Pow")
    lr = as_scalar(ctx.in1(op, "LearningRate")).astype(jnp.float32)
    b1 = op.attr("beta1", 0.9)
    b2 = op.attr("beta2", 0.999)
    eps = op.attr("epsilon", 1e-6)
    wd = op.attr("weight_decay", 0.01)
    pf = p.astype(jnp.float32)
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * jnp.square(g)
    mhat = m1n / (1 - as_scalar(b1p))
    vhat = m2n / (1 - as_scalar(b2p))
    r = mhat / (jnp.sqrt(vhat) + eps) + wd * pf
    w_norm = jnp.linalg.norm(pf)
    r_norm = jnp.linalg.norm(r)
    trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
    pn = pf - lr * trust * r
    ctx.set_out(op, "ParamOut", pn.astype(p.dtype))
    ctx.set_out(op, "Moment1Out", m1n)
    ctx.set_out(op, "Moment2Out", m2n)
    ctx.set_out(op, "Beta1PowOut", b1p * b1)
    ctx.set_out(op, "Beta2PowOut", b2p * b2)


@register_lower("lars_momentum")
def _lars_momentum(ctx, op):
    p = ctx.in1(op, "Param")
    g = ctx.in1(op, "Grad")
    v = ctx.in1(op, "Velocity")
    lr = as_scalar(ctx.in1(op, "LearningRate"))
    mu = op.attr("mu", 0.9)
    lars_coeff = op.attr("lars_coeff", 0.001)
    lars_wd = op.attr("lars_weight_decay", 0.0005)
    eps = op.attr("epsilon", 0.0)
    p_norm = jnp.linalg.norm(p)
    g_norm = jnp.linalg.norm(g)
    local_lr = jnp.where(
        (p_norm > 0) & (g_norm > 0),
        lr * lars_coeff * p_norm / (g_norm + lars_wd * p_norm + eps),
        lr,
    )
    vn = mu * v + local_lr * (g + lars_wd * p)
    ctx.set_out(op, "ParamOut", p - vn)
    ctx.set_out(op, "VelocityOut", vn)


@register_lower("ftrl")
def _ftrl(ctx, op):
    p = ctx.in1(op, "Param")
    g = ctx.in1(op, "Grad")
    sq = ctx.in1(op, "SquaredAccumulator")
    lin = ctx.in1(op, "LinearAccumulator")
    lr = as_scalar(ctx.in1(op, "LearningRate"))
    l1 = op.attr("l1", 0.0)
    l2 = op.attr("l2", 0.0)
    lr_power = op.attr("lr_power", -0.5)
    new_sq = sq + jnp.square(g)
    sigma = (jnp.power(new_sq, -lr_power) - jnp.power(sq, -lr_power)) / lr
    new_lin = lin + g - sigma * p
    x = -new_lin
    y = jnp.power(new_sq, -lr_power) / lr + 2 * l2
    pre_shrink = jnp.where(jnp.abs(new_lin) > l1, (x + jnp.sign(new_lin) * l1) / y, jnp.zeros_like(p))
    ctx.set_out(op, "ParamOut", pre_shrink)
    ctx.set_out(op, "SquaredAccumOut", new_sq)
    ctx.set_out(op, "LinearAccumOut", new_lin)


# ---------------------------------------------------------------------------
# AMP loss-scaling state machine (reference operators/amp/)
# ---------------------------------------------------------------------------


@register_lower("dpsgd")
def _dpsgd(ctx, op):
    """Differentially-private SGD (reference operators/optimizers/
    dpsgd_op.cc): L2-clip the per-batch gradient to ``clip`` and add
    Gaussian noise scaled by ``sigma/batch_size`` before the SGD step."""
    from .common import op_seed_key

    p = ctx.in1(op, "Param")
    g = ctx.in1(op, "Grad").astype(jnp.float32)
    lr = as_scalar(ctx.in1(op, "LearningRate")).astype(jnp.float32)
    clip = jnp.float32(op.attr("clip", 10.0))
    batch_size = jnp.float32(op.attr("batch_size", 16.0))
    sigma = jnp.float32(op.attr("sigma", 1.0))
    norm = jnp.sqrt(jnp.sum(g * g))
    g = g * jnp.minimum(1.0, clip / jnp.maximum(norm, 1e-12))
    noise = jax.random.normal(op_seed_key(ctx, op), g.shape,
                              jnp.float32) * (clip * sigma / batch_size)
    ctx.set_out(op, "ParamOut",
                (p.astype(jnp.float32) - lr * (g + noise)).astype(p.dtype))


@register_lower("ema_update")
def _ema_update(ctx, op):
    """Shadow accumulator for ExponentialMovingAverage (reference
    optimizer.py:3443 builds this from scale/sum primitives; one op here
    keeps it fusable): shadow' = decay*shadow + (1-decay)*param."""
    p = ctx.in1(op, "Param").astype(jnp.float32)
    s = ctx.in1(op, "Shadow").astype(jnp.float32)
    decay = as_scalar(ctx.in1(op, "Decay")) if op.inputs.get("Decay") \
        else jnp.float32(op.attr("decay", 0.999))
    out = decay * s + (1.0 - decay) * p
    ctx.set_out(op, "ShadowOut", out)


@register_lower("check_finite_and_unscale")
def _check_finite_and_unscale(ctx, op):
    scale = as_scalar(ctx.in1(op, "Scale"))
    found_inf = jnp.zeros((), jnp.bool_)
    outs = op.outputs.get("Out", [])
    for name_in, name_out in zip(op.inputs.get("X", []), outs):
        x = ctx.get(name_in)
        xs = x.astype(jnp.float32) / scale
        found_inf = found_inf | ~jnp.all(jnp.isfinite(xs))
        ctx.set(name_out, xs.astype(x.dtype) if x.dtype != jnp.float16 else xs)
    if ctx.axis_env:
        # cross-replica agreement: an overflow on ANY dp shard must shrink
        # the (replicated) loss scale on every shard, or the scaling state
        # diverges across replicas (reference runs the check after the
        # dense allreduce; here pre-comm local grads can differ)
        from jax import lax

        found_inf = lax.pmax(found_inf.astype(jnp.int32),
                             tuple(ctx.axis_env)).astype(jnp.bool_)
    ctx.set_out(op, "FoundInfinite", found_inf.reshape((1,)))


@register_lower("update_loss_scaling")
def _update_loss_scaling(ctx, op):
    found_inf = jnp.reshape(ctx.in1(op, "FoundInfinite"), ())
    scale = as_scalar(ctx.in1(op, "PrevLossScaling"))
    good = as_scalar(ctx.in1(op, "InGoodSteps"))
    bad = as_scalar(ctx.in1(op, "InBadSteps"))
    incr_every = op.attr("incr_every_n_steps", 1000)
    decr_every = op.attr("decr_every_n_nan_or_inf", 2)
    incr_ratio = op.attr("incr_ratio", 2.0)
    decr_ratio = op.attr("decr_ratio", 0.5)

    new_bad = jnp.where(found_inf, bad + 1, jnp.zeros_like(bad))
    new_good = jnp.where(found_inf, jnp.zeros_like(good), good + 1)
    shrink = new_bad >= decr_every
    grow = new_good >= incr_every
    new_scale = jnp.where(
        shrink, jnp.maximum(scale * decr_ratio, 1.0), jnp.where(grow, scale * incr_ratio, scale)
    )
    new_bad = jnp.where(shrink, jnp.zeros_like(new_bad), new_bad)
    new_good = jnp.where(grow, jnp.zeros_like(new_good), new_good)
    ctx.set_out(op, "LossScaling", new_scale.reshape((1,)))
    ctx.set_out(op, "OutGoodSteps", new_good.reshape((1,)).astype(jnp.int32))
    ctx.set_out(op, "OutBadSteps", new_bad.reshape((1,)).astype(jnp.int32))
    # zero grads when non-finite (reference semantics: skip the update)
    for name_in, name_out in zip(op.inputs.get("X", []), op.outputs.get("Out", [])):
        x = ctx.get(name_in)
        ctx.set(name_out, jnp.where(found_inf, jnp.zeros_like(x), x))
