"""Custom Pallas TPU flash-attention kernel with streamed additive bias.

Role parity: reference operators/fused/multihead_matmul_op.cu +
operators/math/bert_encoder_functor.cu (the fused scores->mask->softmax->
context chain).  The stock jax flash kernel takes an `ab` bias only as a
materialized [B,H,S,S] tensor — exactly the HBM blowup flash exists to
avoid; a [B,1,1,S] key-padding mask broadcast to BERT-base shapes at
S=4096 is 8 GiB.  This kernel STREAMS the bias block-by-block instead:
key-mask form [B,1,1,S] is read as (1,1,BK) tiles (broadcast over rows
in-register), full form [B,H,S,S] as (1,BQ,BK) tiles, so HBM traffic for
a key mask is O(B*S), not O(B*H*S^2).

Forward: classic online-softmax flash (running row-max/denominator in
VMEM scratch, one (BQ,BK) tile in flight).  Backward: a q-chunked
recomputation — peak memory O(BQ*Sk) per chunk instead of the plain
path's O(Sq*Sk) score tensor — wired through jax.custom_vjp so the
framework's generic vjp-replay gradients (ops/grad_generic.py)
differentiate through it unchanged.  ``interpret=True`` runs the same
kernel on CPU for tests (tests/test_pallas_attention.py).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30
_LANES = 128  # TPU vector lane width; row stats broadcast across lanes


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, m_scr, l_scr,
                acc_scr, *, sm_scale, causal, block_q, block_k, n_k,
                bias_mode):
    import jax.experimental.pallas as pl

    qb = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    if causal:
        # blocks fully above the diagonal contribute nothing
        run = (kb * block_k) <= (qb * block_q + block_q - 1)
    else:
        run = True

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)           # (BQ, D)
        k = k_ref[0].astype(jnp.float32)           # (BK, D)
        v = v_ref[0].astype(jnp.float32)           # (BK, D)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # (BQ, BK)
        if bias_mode == "key":
            s = s + bias_ref[0, 0, 0].astype(jnp.float32)[None, :]
        elif bias_mode == "full":
            s = s + bias_ref[0, 0].astype(jnp.float32)
        if causal:
            rows = qb * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kb * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)

        m_prev = m_scr[:, :1]                      # (BQ, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(kb == n_k - 1)
    def _flush():
        l = l_scr[:, :1]
        o_ref[0] = (acc_scr[...] / jnp.where(l == 0.0, 1.0, l)).astype(
            o_ref.dtype)


def _bias_layout(bias, h, block_q, block_k):
    """(mode, BlockSpec) for the bias in its NATURAL 4-D shape — no
    broadcast materialization: broadcast dims map to block 0 in the
    index map, so HBM traffic stays at the bias's true size."""
    import jax.experimental.pallas as pl

    if bias is None:
        return "none", pl.BlockSpec((1, 1, 1, 1),
                                    lambda bh, qb, kb: (0, 0, 0, 0))
    bb, bh_, bq, _bk = bias.shape
    if bq == 1:  # key mask: one row broadcast over all queries
        return "key", pl.BlockSpec(
            (1, 1, 1, block_k),
            lambda bh, qb, kb: (0 if bb == 1 else bh // h,
                                0 if bh_ == 1 else bh % h, 0, kb))
    return "full", pl.BlockSpec(
        (1, 1, block_q, block_k),
        lambda bh, qb, kb: (0 if bb == 1 else bh // h,
                            0 if bh_ == 1 else bh % h, qb, kb))


def _flash_call(q, k, v, bias, sm_scale, causal, block_q, block_k,
                interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    n_q, n_k = sq // block_q, sk // block_k
    bias_mode, bias_spec = _bias_layout(bias, h, block_q, block_k)
    bias_arr = bias if bias is not None else \
        jnp.zeros((1, 1, 1, 1), q.dtype)

    kern = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, n_k=n_k, bias_mode=bias_mode)
    out = pl.pallas_call(
        kern,
        grid=(b * h, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qb, kb: (bh, qb, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qb, kb: (bh, kb, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, qb, kb: (bh, kb, 0)),
            bias_spec,
        ],
        out_specs=pl.BlockSpec((1, block_q, d),
                               lambda bh, qb, kb: (bh, qb, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running denom
            pltpu.VMEM((block_q, d), jnp.float32),       # output acc
        ],
        interpret=interpret,
    )(q.reshape(b * h, sq, d), k.reshape(b * h, sk, d),
      v.reshape(b * h, sk, d), bias_arr)
    return out.reshape(b, h, sq, d)


# -- backward: q-chunked recompute ------------------------------------


def _chunked_bwd(q, k, v, bias, do, sm_scale, causal, block_q):
    """dq/dk/dv/dbias with O(BQ*Sk) live scores: scan over q chunks,
    accumulating dk/dv (and a broadcast-reduced dbias) in the carry —
    the flash backward recurrence expressed as XLA ops, fusion keeps
    each chunk on-chip."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    n_chunks = sq // block_q
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    bias_q_bcast = bias is not None and bias.shape[2] == 1

    def chunk(carry, idx):
        dk_acc, dv_acc, db_acc = carry
        off = idx * block_q
        qc = lax.dynamic_slice_in_dim(q, off, block_q, 2).astype(
            jnp.float32)                              # (B,H,BQ,D)
        doc = lax.dynamic_slice_in_dim(do, off, block_q, 2).astype(
            jnp.float32)
        s = jnp.einsum("bhqd,bhkd->bhqk", qc, kf) * sm_scale
        if bias is not None:
            bb = bias.astype(jnp.float32)
            bq = bb if bias_q_bcast else \
                lax.dynamic_slice_in_dim(bb, off, block_q, 2)
            s = s + bq
        if causal:
            rows = off + lax.broadcasted_iota(
                jnp.int32, (block_q, sk), 0)
            cols = lax.broadcasted_iota(jnp.int32, (block_q, sk), 1)
            s = jnp.where((rows >= cols)[None, None], s, _NEG_INF)
        p = jax.nn.softmax(s, axis=-1)                # (B,H,BQ,Sk)
        dv_c = jnp.einsum("bhqk,bhqd->bhkd", p, doc)
        dp = jnp.einsum("bhqd,bhkd->bhqk", doc, vf)
        delta = jnp.sum(p * dp, axis=-1, keepdims=True)
        ds_raw = p * (dp - delta)       # = dL/ds before the qk scale
        ds = ds_raw * sm_scale
        dq_c = jnp.einsum("bhqk,bhkd->bhqd", ds, kf)
        dk_c = jnp.einsum("bhqk,bhqd->bhkd", ds, qc)
        db_c = None
        if bias is not None:
            db_c = ds_raw  # dL/dbias contribution of this q chunk
            if bias.shape[1] == 1:
                db_c = db_c.sum(1, keepdims=True)
            if bias.shape[0] == 1:
                db_c = db_c.sum(0, keepdims=True)
            if bias_q_bcast:
                db_acc = db_acc + db_c.sum(2, keepdims=True)
                db_c = jnp.zeros((), jnp.float32)  # carried, not stacked
        return (dk_acc + dk_c, dv_acc + dv_c, db_acc), (dq_c, db_c)

    db_init = jnp.zeros((), jnp.float32) if bias is None or not \
        bias_q_bcast else jnp.zeros(
            (bias.shape[0], bias.shape[1], 1, sk), jnp.float32)
    init = (jnp.zeros((b, h, sk, d), jnp.float32),
            jnp.zeros((b, h, sk, d), jnp.float32), db_init)
    (dk, dv, db_acc), (dq_chunks, db_chunks) = lax.scan(
        chunk, init, jnp.arange(n_chunks))
    dq = jnp.moveaxis(dq_chunks, 0, 2).reshape(b, h, sq, d)
    dbias = None
    if bias is not None:
        if bias_q_bcast:
            dbias = db_acc.astype(bias.dtype)
        else:
            dbias = jnp.moveaxis(db_chunks, 0, 2).reshape(
                bias.shape[0], bias.shape[1], sq, sk).astype(bias.dtype)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            dbias)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, bias, sm_scale, causal, block_q, block_k, interpret):
    return _flash_call(q, k, v, bias, sm_scale, causal, block_q, block_k,
                       interpret)


def _flash_fwd_rule(q, k, v, bias, sm_scale, causal, block_q, block_k,
                    interpret):
    out = _flash_call(q, k, v, bias, sm_scale, causal, block_q, block_k,
                      interpret)
    return out, (q, k, v, bias)


def _flash_bwd_rule(sm_scale, causal, block_q, block_k, interpret, res,
                    do):
    q, k, v, bias = res
    dq, dk, dv, dbias = _chunked_bwd(q, k, v, bias, do, sm_scale,
                                     causal, block_q)
    return dq, dk, dv, dbias


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention_bias(q, k, v, bias=None, *, sm_scale=None,
                         causal=False, block_q=128, block_k=128,
                         interpret=False):
    """Flash attention over (B, H, S, D) tensors with a streamed
    additive bias: ``bias`` is None, a key mask [B,1,1,Sk], or a full
    [B,H,Sq,Sk] tensor (additive -1e9-style masks included).
    Differentiable (q-chunked recompute backward; bias treated as a
    constant)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"flash_attention_bias needs seq multiples of the block "
            f"({block_q}/{block_k}); got Sq={sq}, Sk={sk}")
    if bias is not None:
        # Mosaic CLAMPS out-of-range block indices — a mis-sized bias
        # would silently reuse the last tile instead of erroring
        ok = (bias.ndim == 4
              and bias.shape[0] in (1, b) and bias.shape[1] in (1, h)
              and bias.shape[2] in (1, sq) and bias.shape[3] == sk)
        if not ok:
            raise ValueError(
                f"bias shape {tuple(bias.shape)} does not broadcast to "
                f"(B={b}, H={h}, Sq={sq}, Sk={sk}); the key dim must be "
                f"exactly Sk")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    return _flash(q, k, v, bias, float(sm_scale), bool(causal),
                  int(block_q), int(block_k), bool(interpret))
