"""Recurrent ops: the v2 `rnn` op (LSTM/GRU/simple, multi-layer, bidi)
plus the fluid-era cell/sequence ops (gru_unit, lstm_unit, gru, lstm).

Reference parity: operators/rnn_op.cc (cudnn-style fused RNN over
time-major input with a flat WeightList), gru_unit_op.cc, lstm_unit_op.cc,
gru_op.cc, lstm_op.cc.  TPU-native: one `lax.scan` per (layer, direction)
— the recurrence compiles to a single fused loop; no cudnn descriptors,
no Reserve workspace (XLA remat owns backward memory).

WeightList layout (reference nn/layer/rnn.py flatten_parameters): all
[w_ih, w_hh] pairs for each (layer, direction) first, then all
[b_ih, b_hh] pairs in the same order.  Gate order: i,f,g,o for LSTM and
r,z,n (reset-after, cudnn semantics) for GRU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.lowering import register_lower


def _lstm_cell(x_g, h, c, w_hh, b_hh):
    gates = x_g + h @ w_hh.T + (b_hh if b_hh is not None else 0.0)
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c2 = f * c + i * g
    return jnp.tanh(c2) * o, c2


def _gru_cell(x_g, h, w_hh, b_hh):
    hg = h @ w_hh.T + (b_hh if b_hh is not None else 0.0)
    xr, xz, xn = jnp.split(x_g, 3, axis=-1)
    hr, hz, hn = jnp.split(hg, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    n = jnp.tanh(xn + r * hn)
    return (1.0 - z) * n + z * h


def _run_direction(x, h0, c0, w_ih, w_hh, b_ih, b_hh, mode, reverse):
    """x: [T, B, I] time-major; returns (outs [T,B,H], hT, cT|None)."""
    if reverse:
        x = jnp.flip(x, axis=0)
    # input projection for ALL steps at once -> one big MXU matmul
    x_g = jnp.einsum("tbi,gi->tbg", x, w_ih)
    if b_ih is not None:
        x_g = x_g + b_ih

    if mode == "LSTM":
        def step(carry, xg):
            h, c = carry
            h2, c2 = _lstm_cell(xg, h, c, w_hh, b_hh)
            return (h2, c2), h2

        (hT, cT), outs = jax.lax.scan(step, (h0, c0), x_g)
    elif mode == "GRU":
        def step(h, xg):
            h2 = _gru_cell(xg, h, w_hh, b_hh)
            return h2, h2

        hT, outs = jax.lax.scan(step, h0, x_g)
        cT = None
    else:  # RNN_TANH / RNN_RELU
        act = jnp.tanh if mode == "RNN_TANH" else jax.nn.relu

        def step(h, xg):
            h2 = act(xg + h @ w_hh.T + (b_hh if b_hh is not None else 0.0))
            return h2, h2

        hT, outs = jax.lax.scan(step, h0, x_g)
        cT = None
    if reverse:
        outs = jnp.flip(outs, axis=0)
    return outs, hT, cT


@register_lower("rnn")
def _rnn(ctx, op):
    mode = op.attr("mode", "LSTM")
    x = ctx.in1(op, "Input")  # [T, B, I]
    pre_states = ctx.in_list(op, "PreState")
    weights = ctx.in_list(op, "WeightList")
    num_layers = int(op.attr("num_layers", 1))
    bidi = bool(op.attr("is_bidirec", False))
    n_dir = 2 if bidi else 1
    hidden = int(op.attr("hidden_size", 0)) or pre_states[0].shape[-1]

    n_ld = num_layers * n_dir
    has_bias = len(weights) >= 4 * n_ld
    w_pairs = weights[:2 * n_ld]
    b_pairs = weights[2 * n_ld:4 * n_ld] if has_bias else [None] * (2 * n_ld)

    h0 = pre_states[0]  # [L*D, B, H]
    c0 = pre_states[1] if mode == "LSTM" and len(pre_states) > 1 else None

    y = x
    hTs, cTs = [], []
    for layer in range(num_layers):
        outs_dir = []
        for d in range(n_dir):
            ld = layer * n_dir + d
            w_ih, w_hh = w_pairs[2 * ld], w_pairs[2 * ld + 1]
            b_ih, b_hh = b_pairs[2 * ld], b_pairs[2 * ld + 1]
            outs, hT, cT = _run_direction(
                y, h0[ld], c0[ld] if c0 is not None else None,
                w_ih, w_hh, b_ih, b_hh, mode, reverse=(d == 1))
            outs_dir.append(outs)
            hTs.append(hT)
            if cT is not None:
                cTs.append(cT)
        y = outs_dir[0] if n_dir == 1 else jnp.concatenate(outs_dir, axis=-1)

    ctx.set_out(op, "Out", y)
    state_names = op.outputs.get("State", [])
    states = [jnp.stack(hTs)]
    if mode == "LSTM":
        states.append(jnp.stack(cTs) if cTs else jnp.zeros_like(states[0]))
    for name, val in zip(state_names, states):
        ctx.set(name, val)
    if op.outputs.get("Reserve"):
        ctx.set_out(op, "Reserve", jnp.zeros((1,), jnp.uint8))
    if op.outputs.get("DropoutState"):
        ctx.set_out(op, "DropoutState", jnp.zeros((1,), jnp.uint8))


@register_lower("gru_unit")
def _gru_unit(ctx, op):
    """Single GRU step (reference gru_unit_op.cc): fluid gate layout
    [update, reset, cell] over Input [B, 3H] + HiddenPrev @ Weight."""
    x = ctx.in1(op, "Input")  # [B, 3H] (already x@W_ih + b)
    h_prev = ctx.in1(op, "HiddenPrev")
    w = ctx.in1(op, "Weight")  # [H, 3H]: [:, :2H] gates, [:, 2H:] candidate
    bias = ctx.in1(op, "Bias")
    hid = h_prev.shape[-1]
    if bias is not None:
        x = x + bias.reshape((-1,))
    gu = x[:, :2 * hid] + h_prev @ w[:, :2 * hid]
    u, r = jnp.split(jax.nn.sigmoid(gu), 2, axis=-1)
    c = jnp.tanh(x[:, 2 * hid:] + (r * h_prev) @ w[:, 2 * hid:])
    # gru_unit_op.h: origin_mode=True -> u*h_prev + (1-u)*c; the default
    # (False) is u*c + (1-u)*h_prev (gru_kernel.h gru_finalOutput).
    if bool(op.attr("origin_mode", False)):
        h = u * h_prev + (1.0 - u) * c
    else:
        h = u * c + (1.0 - u) * h_prev
    ctx.set_out(op, "Gate", jnp.concatenate([u, r, c], axis=-1))
    ctx.set_out(op, "ResetHiddenPrev", r * h_prev)
    ctx.set_out(op, "Hidden", h)


@register_lower("lstm_unit")
def _lstm_unit(ctx, op):
    """Single LSTM step (reference lstm_unit_op.h:64-72): X [B,4H]
    pre-gates in (i, f, o, g) chunk order, forget_bias added to f;
    C_prev [B,H]."""
    x = ctx.in1(op, "X")
    c_prev = ctx.in1(op, "C_prev")
    forget_bias = float(op.attr("forget_bias", 0.0))
    i, f, o, g = jnp.split(x, 4, axis=-1)
    c = jax.nn.sigmoid(f + forget_bias) * c_prev \
        + jax.nn.sigmoid(i) * jnp.tanh(g)
    h = jax.nn.sigmoid(o) * jnp.tanh(c)
    ctx.set_out(op, "C", c)
    ctx.set_out(op, "H", h)


def _act(name):
    return {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
            "relu": jax.nn.relu, "identity": lambda v: v}[name]


@register_lower("gru")
def _gru(ctx, op):
    """Fluid LoD gru (gru_op.cc) under uniform/dense semantics: Input
    [T, 3H] is ONE sequence of pre-projected gates (x@W_ih+b done by the
    surrounding fc, reference layers.dynamic_gru contract).  Ragged
    batches are padded+masked upstream per SURVEY §7 LoD mitigation."""
    x = ctx.in1(op, "Input")  # [T, 3H]
    w = ctx.in1(op, "Weight")  # [H, 3H]
    bias = ctx.in1(op, "Bias")
    h0 = ctx.in1(op, "H0")
    hid = w.shape[0]
    gate_act = _act(op.attr("gate_activation", "sigmoid"))
    cand_act = _act(op.attr("activation", "tanh"))
    reverse = bool(op.attr("is_reverse", False))
    origin_mode = bool(op.attr("origin_mode", False))
    if bias is not None:
        x = x + bias.reshape((-1,))
    if reverse:
        x = jnp.flip(x, axis=0)
    h_init = h0 if h0 is not None else jnp.zeros((hid,), x.dtype)

    def step(h, xg):
        gu = gate_act(xg[:2 * hid] + h @ w[:, :2 * hid])
        u, r = gu[:hid], gu[hid:]
        c = cand_act(xg[2 * hid:] + (r * h) @ w[:, 2 * hid:])
        if origin_mode:
            h2 = u * h + (1.0 - u) * c
        else:
            h2 = u * c + (1.0 - u) * h
        return h2, (h2, r * h, gu)

    hT, (hidden, reset_h, gates) = jax.lax.scan(step, h_init, x)
    if reverse:
        hidden = jnp.flip(hidden, axis=0)
    ctx.set_out(op, "Hidden", hidden)
    ctx.set_out(op, "BatchGate", jnp.concatenate(
        [gates, jnp.zeros((x.shape[0], hid), x.dtype)], axis=-1)[:, :3 * hid])
    ctx.set_out(op, "BatchResetHiddenPrev", reset_h)
    ctx.set_out(op, "BatchHidden", hidden)


@register_lower("lstm", "lstmp")
def _lstm(ctx, op):
    """Fluid LoD lstm/lstmp (lstm_op.cc) under single-sequence dense
    semantics: Input [T, 4H] pre-projected gates; lstmp adds a recurrent
    projection ProjWeight [H, P]."""
    x = ctx.in1(op, "Input")  # [T, 4H]
    w = ctx.in1(op, "Weight")  # [H or P, 4H]
    bias = ctx.in1(op, "Bias")
    h0 = ctx.in1(op, "H0")
    c0 = ctx.in1(op, "C0")
    proj = ctx.in1(op, "ProjWeight") if op.type == "lstmp" else None
    hid = x.shape[-1] // 4
    use_peepholes = bool(op.attr("use_peepholes", False))
    reverse = bool(op.attr("is_reverse", False))
    gate_act = _act(op.attr("gate_activation", "sigmoid"))
    cell_act = _act(op.attr("cell_activation", "tanh"))
    cand_act = _act(op.attr("candidate_activation", "tanh"))
    if bias is not None:
        b = bias.reshape((-1,))
        x = x + b[:4 * hid]
        peep = b[4 * hid:] if use_peepholes and b.shape[0] > 4 * hid else None
    else:
        peep = None
    if reverse:
        x = jnp.flip(x, axis=0)
    rec_dim = w.shape[0]
    h_init = h0 if h0 is not None else jnp.zeros((rec_dim,), x.dtype)
    c_init = c0 if c0 is not None else jnp.zeros((hid,), x.dtype)

    def step(carry, xg):
        h, c = carry
        g = xg + h @ w
        i, f, cc, o = jnp.split(g, 4, axis=-1)
        if peep is not None:
            wic, wfc, woc = jnp.split(peep, 3)
            i = i + wic * c
            f = f + wfc * c
        i, f = gate_act(i), gate_act(f)
        c2 = f * c + i * cand_act(cc)
        if peep is not None:
            o = o + woc * c2
        o = gate_act(o)
        h2 = o * cell_act(c2)
        if proj is not None:
            h2 = h2 @ proj
        return (h2, c2), (h2, c2)

    (hT, cT), (hidden, cell) = jax.lax.scan(step, (h_init, c_init), x)
    if reverse:
        hidden, cell = jnp.flip(hidden, axis=0), jnp.flip(cell, axis=0)
    ctx.set_out(op, "Hidden", hidden)
    ctx.set_out(op, "Cell", cell)
    if op.type == "lstmp":
        ctx.set_out(op, "Projection", hidden)
    ctx.set_out(op, "BatchGate", jnp.zeros_like(x))
    ctx.set_out(op, "BatchCellPreAct", jnp.zeros_like(cell))
