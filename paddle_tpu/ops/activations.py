"""Activation ops (reference operators/activation_op.cc — 60+ activations)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.lowering import register_lower


def _unary(fn):
    def lower(ctx, op):
        ctx.set_out(op, "Out", fn(ctx.in1(op, "X"), op))

    return lower


_SIMPLE = {
    "relu": lambda x, op: jax.nn.relu(x),
    "relu6": lambda x, op: jnp.clip(x, 0.0, float(op.attr("threshold", 6.0))),
    "sigmoid": lambda x, op: jax.nn.sigmoid(x),
    "tanh": lambda x, op: jnp.tanh(x),
    "tanh_shrink": lambda x, op: x - jnp.tanh(x),
    "softplus": lambda x, op: jax.nn.softplus(x),
    "softsign": lambda x, op: x / (1 + jnp.abs(x)),
    "softshrink": lambda x, op: _softshrink(x, float(op.attr("lambda", 0.5))),
    "hard_shrink": lambda x, op: jnp.where(
        jnp.abs(x) > float(op.attr("threshold", 0.5)), x, jnp.zeros_like(x)
    ),
    "hard_sigmoid": lambda x, op: jnp.clip(
        float(op.attr("slope", 0.2)) * x + float(op.attr("offset", 0.5)), 0.0, 1.0
    ),
    "hard_swish": lambda x, op: x
    * jnp.clip(x + float(op.attr("offset", 3.0)), 0.0, float(op.attr("threshold", 6.0)))
    / float(op.attr("scale", 6.0)),
    "swish": lambda x, op: x * jax.nn.sigmoid(float(op.attr("beta", 1.0)) * x),
    "silu": lambda x, op: jax.nn.silu(x),
    "mish": lambda x, op: x * jnp.tanh(jax.nn.softplus(x)),
    "elu": lambda x, op: jax.nn.elu(x, alpha=float(op.attr("alpha", 1.0))),
    "celu": lambda x, op: jax.nn.celu(x, alpha=float(op.attr("alpha", 1.0))),
    "selu": lambda x, op: float(op.attr("scale", 1.0507009873554805))
    * jnp.where(
        x > 0,
        x,
        float(op.attr("alpha", 1.6732632423543772)) * (jnp.exp(x) - 1),
    ),
    "leaky_relu": lambda x, op: jax.nn.leaky_relu(x, float(op.attr("alpha", 0.02))),
    "logsigmoid": lambda x, op: jax.nn.log_sigmoid(x),
    "thresholded_relu": lambda x, op: jnp.where(
        x > float(op.attr("threshold", 1.0)), x, jnp.zeros_like(x)
    ),
    "stanh": lambda x, op: float(op.attr("scale_b", 1.7159))
    * jnp.tanh(float(op.attr("scale_a", 0.67)) * x),
    "brelu": lambda x, op: jnp.clip(
        x, float(op.attr("t_min", 0.0)), float(op.attr("t_max", 24.0))
    ),
    "expm1": lambda x, op: jnp.expm1(x),
    "atanh": lambda x, op: jnp.arctanh(x),
    "asinh": lambda x, op: jnp.arcsinh(x),
    "acosh": lambda x, op: jnp.arccosh(x),
}


def _softshrink(x, lam):
    return jnp.where(x > lam, x - lam, jnp.where(x < -lam, x + lam, jnp.zeros_like(x)))


for _name, _fn in _SIMPLE.items():
    register_lower(_name)(_unary(_fn))


@register_lower("gelu")
def _gelu(ctx, op):
    x = ctx.in1(op, "X")
    ctx.set_out(op, "Out", jax.nn.gelu(x, approximate=bool(op.attr("approximate", False))))


@register_lower("prelu")
def _prelu(ctx, op):
    x = ctx.in1(op, "X")
    alpha = ctx.in1(op, "Alpha")
    mode = op.attr("mode", "all")
    if mode == "channel" and alpha.size > 1:
        shape = [1, -1] + [1] * (x.ndim - 2)
        alpha = alpha.reshape(shape)
    ctx.set_out(op, "Out", jnp.where(x > 0, x, alpha * x))


@register_lower("maxout")
def _maxout(ctx, op):
    x = ctx.in1(op, "X")  # NCHW
    groups = int(op.attr("groups"))
    axis = int(op.attr("axis", 1))
    c = x.shape[axis]
    new_shape = list(x.shape)
    new_shape[axis : axis + 1] = [c // groups, groups]
    ctx.set_out(op, "Out", jnp.max(x.reshape(new_shape), axis=axis + 1))
