"""Shared helpers for op lowering rules."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtypes


def attr_dtype(op, name="dtype", default="float32"):
    """Resolve a dtype attribute (IR enum int or string) to a jnp dtype.

    64-bit integer/float requests collapse to their 32-bit forms
    explicitly: x64 is disabled on TPU, so jax would truncate anyway —
    this makes the documented int32/float32 contract silent instead of
    a per-op UserWarning."""
    v = op.attr(name, None)
    if v is None or v == 0:
        dt = jnp.dtype(default)
    else:
        dt = dtypes.to_jnp(v)
    if not jax.config.read("jax_enable_x64"):
        dt = {jnp.dtype("int64"): jnp.dtype("int32"),
              jnp.dtype("uint64"): jnp.dtype("uint32"),
              jnp.dtype("float64"): jnp.dtype("float32")}.get(
            jnp.dtype(dt), dt)
    return dt


def op_seed_key(ctx, op, per_shard=False):
    """Deterministic key for a random op: explicit nonzero `seed` attr wins
    (reference per-op seed semantics), else draw from the threaded program
    key.  ``per_shard`` folds the dp shard index in (dropout-style ops on
    sharded activations); replica-invariant ops (initializers) leave it
    False so every shard sees the same stream."""
    seed = int(op.attr("seed", 0) or 0)
    if seed:
        k = jax.random.PRNGKey(seed)
        return ctx.fold_shard(k) if per_shard else k
    return ctx.next_key(per_shard=per_shard)


def bcast_shapes_elementwise(x, y, axis: int):
    """Reference elementwise broadcast: align y's dims to x starting at
    `axis` (reference operators/elementwise/elementwise_op_function.h trim/
    expand semantics), then rely on numpy-style broadcasting."""
    if x.ndim == y.ndim or y.ndim == 0:
        return x, y
    if y.ndim > x.ndim:
        # mirrored case: broadcast x into y (resolve axis against y's rank)
        y2, x2 = bcast_shapes_elementwise(y, x, axis)
        return x2, y2
    if axis == -1:
        axis = x.ndim - y.ndim
    new_shape = [1] * x.ndim
    new_shape[axis : axis + y.ndim] = list(y.shape)
    return x, y.reshape(new_shape)


def resolve_shape_attr(shape, env_get=None):
    return [int(s) for s in shape]


def adaptive_windows(size: int, out_size: int):
    """Adaptive-pool window indices (reference AdaptiveStartIndex/
    AdaptiveEndIndex: cell i covers [floor(i*S/O), ceil((i+1)*S/O))):
    returns (idx [out, maxw] clipped, valid mask, maxw)."""
    starts = (np.arange(out_size) * size) // out_size
    ends = -(-(np.arange(1, out_size + 1) * size) // out_size)  # ceil
    maxw = int((ends - starts).max())
    idx = starts[:, None] + np.arange(maxw)[None, :]
    valid = idx < ends[:, None]
    return np.minimum(idx, size - 1), valid, maxw


def as_scalar(x):
    """Ops like sgd receive learning rate as a [1] tensor."""
    return jnp.reshape(x, ()) if hasattr(x, "shape") and np.prod(x.shape) == 1 else x


def bilinear_sample_chw(img, ys, xs, padding="zeros"):
    """Bilinear sampling of img [C, H, W] at float coords ys/xs [...].

    padding="zeros": out-of-range taps contribute 0 (reference
    DmcnIm2colBilinear / grid_sampler zeros semantics — the validity
    test runs on the UNCLIPPED coordinate, so coords in (-1, 0) get the
    partial in-range contribution).  padding="border": coords clamp to
    the edge pixel.  Shared by deformable conv and grid_sampler so the
    subtle boundary semantics live in one place.
    """
    import jax.numpy as jnp

    c, h, w = img.shape
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)

    def at(yy, xx):
        yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        vals = img[:, yc, xc]  # [C, ...]
        if padding == "zeros":
            valid = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
            vals = vals * valid.astype(img.dtype)
        return vals

    wy = ys - y0
    wx = xs - x0
    return (at(y0, x0) * (1 - wy) * (1 - wx)
            + at(y0, x0 + 1) * (1 - wy) * wx
            + at(y0 + 1, x0) * wy * (1 - wx)
            + at(y0 + 1, x0 + 1) * wy * wx)
