"""Shared helpers for op lowering rules."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtypes


def attr_dtype(op, name="dtype", default="float32"):
    """Resolve a dtype attribute (IR enum int or string) to a jnp dtype.

    64-bit integer/float requests collapse to their 32-bit forms
    explicitly: x64 is disabled on TPU, so jax would truncate anyway —
    this makes the documented int32/float32 contract silent instead of
    a per-op UserWarning."""
    v = op.attr(name, None)
    if v is None or v == 0:
        dt = jnp.dtype(default)
    else:
        dt = dtypes.to_jnp(v)
    if not jax.config.read("jax_enable_x64"):
        dt = {jnp.dtype("int64"): jnp.dtype("int32"),
              jnp.dtype("uint64"): jnp.dtype("uint32"),
              jnp.dtype("float64"): jnp.dtype("float32")}.get(
            jnp.dtype(dt), dt)
    return dt


def op_seed_key(ctx, op, per_shard=False):
    """Deterministic key for a random op: explicit nonzero `seed` attr wins
    (reference per-op seed semantics), else draw from the threaded program
    key.  ``per_shard`` folds the dp shard index in (dropout-style ops on
    sharded activations); replica-invariant ops (initializers) leave it
    False so every shard sees the same stream."""
    seed = int(op.attr("seed", 0) or 0)
    if seed:
        k = jax.random.PRNGKey(seed)
        return ctx.fold_shard(k) if per_shard else k
    return ctx.next_key(per_shard=per_shard)


def bcast_shapes_elementwise(x, y, axis: int):
    """Reference elementwise broadcast: align y's dims to x starting at
    `axis` (reference operators/elementwise/elementwise_op_function.h trim/
    expand semantics), then rely on numpy-style broadcasting."""
    if x.ndim == y.ndim or y.ndim == 0:
        return x, y
    if y.ndim > x.ndim:
        # mirrored case: broadcast x into y (resolve axis against y's rank)
        y2, x2 = bcast_shapes_elementwise(y, x, axis)
        return x2, y2
    if axis == -1:
        axis = x.ndim - y.ndim
    new_shape = [1] * x.ndim
    new_shape[axis : axis + y.ndim] = list(y.shape)
    return x, y.reshape(new_shape)


def resolve_shape_attr(shape, env_get=None):
    return [int(s) for s in shape]


def adaptive_windows(size: int, out_size: int):
    """Adaptive-pool window indices (reference AdaptiveStartIndex/
    AdaptiveEndIndex: cell i covers [floor(i*S/O), ceil((i+1)*S/O))):
    returns (idx [out, maxw] clipped, valid mask, maxw)."""
    starts = (np.arange(out_size) * size) // out_size
    ends = -(-(np.arange(1, out_size + 1) * size) // out_size)  # ceil
    maxw = int((ends - starts).max())
    idx = starts[:, None] + np.arange(maxw)[None, :]
    valid = idx < ends[:, None]
    return np.minimum(idx, size - 1), valid, maxw


def adaptive_max_with_index(x, out_sizes):
    """N-D non-divisible adaptive max pool with flat argmax indices.

    ``x`` is [N, C, *spatial]; each output cell gathers its variable
    floor/ceil window through a fixed max-width index table and reduces
    under a validity mask; the masked argmax decomposes back into
    original coordinates to give the reference Mask contract (flat
    index into the unpadded spatial volume).  Returns (out, flat_int32).
    """
    import jax.numpy as jnp

    spatial = len(out_sizes)
    in_sp = [int(s) for s in x.shape[2:2 + spatial]]
    wins = [adaptive_windows(in_sp[i], int(out_sizes[i]))
            for i in range(spatial)]
    g = x
    for i in range(spatial):
        axis = 2 + 2 * i  # dims before it already split into (o, m)
        idx, _, maxw = wins[i]
        g = jnp.take(g, jnp.asarray(idx.ravel()), axis=axis)
        g = g.reshape(g.shape[:axis] + (int(out_sizes[i]), maxw)
                      + g.shape[axis + 1:])
    perm = ([0, 1] + [2 + 2 * i for i in range(spatial)]
            + [3 + 2 * i for i in range(spatial)])
    g = jnp.transpose(g, perm)  # [N, C, o..., m...]

    mask = None
    for i, (_, valid, _) in enumerate(wins):
        shape = [1] * (2 * spatial)
        shape[i] = valid.shape[0]
        shape[spatial + i] = valid.shape[1]
        m = jnp.asarray(valid).reshape(shape)
        mask = m if mask is None else (mask & m)
    lowest = (jnp.iinfo(g.dtype).min
              if jnp.issubdtype(g.dtype, jnp.integer)
              else jnp.asarray(-jnp.inf, g.dtype))
    gm = jnp.where(mask[None, None], g, lowest)

    maxws = [w[2] for w in wins]
    m_total = int(np.prod(maxws))
    head = gm.shape[:2 + spatial]
    flatwin = gm.reshape(head + (m_total,))
    out = jnp.max(flatwin, axis=-1)
    arg = jnp.argmax(flatwin, axis=-1)  # window-local flat

    flat = jnp.zeros_like(arg)
    stride = 1
    rem = arg
    # decompose window-local index back-to-front; map through each
    # axis's index table to the ORIGINAL coordinate
    ks = []
    for i in reversed(range(spatial)):
        ks.append(rem % maxws[i])
        rem = rem // maxws[i]
    ks = list(reversed(ks))
    for i in reversed(range(spatial)):
        idx_tab = jnp.asarray(wins[i][0])  # [o_i, maxw_i]
        tab = idx_tab.reshape([1, 1] + [
            idx_tab.shape[0] if j == i else 1 for j in range(spatial)
        ] + [idx_tab.shape[1]])
        tab = jnp.broadcast_to(tab, head + (idx_tab.shape[1],))
        coord = jnp.take_along_axis(tab, ks[i][..., None],
                                    axis=-1)[..., 0]
        flat = flat + coord * stride
        stride *= in_sp[i]
    return out, flat.astype(jnp.int32)


def as_scalar(x):
    """Ops like sgd receive learning rate as a [1] tensor."""
    return jnp.reshape(x, ()) if hasattr(x, "shape") and np.prod(x.shape) == 1 else x


def bilinear_sample_chw(img, ys, xs, padding="zeros"):
    """Bilinear sampling of img [C, H, W] at float coords ys/xs [...].

    padding="zeros": out-of-range taps contribute 0 (reference
    DmcnIm2colBilinear / grid_sampler zeros semantics — the validity
    test runs on the UNCLIPPED coordinate, so coords in (-1, 0) get the
    partial in-range contribution).  padding="border": coords clamp to
    the edge pixel.  Shared by deformable conv and grid_sampler so the
    subtle boundary semantics live in one place.
    """
    import jax.numpy as jnp

    c, h, w = img.shape
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)

    def at(yy, xx):
        yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        vals = img[:, yc, xc]  # [C, ...]
        if padding == "zeros":
            valid = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
            vals = vals * valid.astype(img.dtype)
        return vals

    wy = ys - y0
    wx = xs - x0
    return (at(y0, x0) * (1 - wy) * (1 - wx)
            + at(y0, x0 + 1) * (1 - wy) * wx
            + at(y0 + 1, x0) * wy * (1 - wx)
            + at(y0 + 1, x0 + 1) * wy * wx)
