"""Op lowering library: importing this package registers all lowering rules.

Layer parity: reference paddle/fluid/operators/ (657 REGISTER_OPERATOR
sites) — here each op is a trace-time jax emission rule (SURVEY.md §2.4
'TPU equivalent').
"""
from . import (  # noqa: F401
    activations,
    collective,
    control_flow,
    creation,
    deformable_ops,
    detection_ops,
    embedding_ops,
    flash_attention,
    fused,
    grad_generic,
    interp_ops,
    layer_scan,
    linalg_ops,
    loss_ops,
    math_ops,
    misc,
    misc_ops,
    moe_ops,
    nms_ops,
    nn_ops,
    optimizer_ops,
    quant_ops,
    rnn_ops,
    sampling_ops,
    sequence_ops,
    tail_ops,
    tensor_ops,
    vision_ops,
)

from ..framework.lowering import LOWERINGS


def registered_ops():
    return sorted(LOWERINGS)
