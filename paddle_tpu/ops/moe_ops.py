"""Mixture-of-experts routed FFN: top-k routing, capacity-factor
dispatch, stacked per-expert einsums, all-to-all combine.

Role parity: the reference's incubate MoE layer (distributed expert
parallelism over its fleet collectives).  TPU-native shape (GShard/
Switch lineage): the router scores every token against E experts,
keeps the top-k gates, and DISPATCHES tokens into a dense
[E, capacity, D] buffer — a static shape, so one compiled executable
serves every routing outcome; tokens past an expert's capacity are
DROPPED (their combine weight is zero, so the residual stream simply
passes them through unchanged).  Expert FFNs run as ONE stacked einsum
per chip over the locally-resident experts ([E, D, H] weights), and
the combine einsum scatters expert outputs back to token order.

Expert parallelism is pure GSPMD: when the plan stamped the op
(``__moe_ep__``) and the mesh has an 'ep' axis, the [E, C, D] dispatch
buffer is sharding-constrained to ``P('ep', None, None)`` — XLA
materializes the dispatch all-to-all in front of the expert compute
and the combine all-to-all behind it.  Latency hiding generalizes the
PR 15 collective-matmul chunking to all-to-all: slice the CAPACITY
axis into FLAGS_moe_alltoall_chunks chunks, so chunk k's all-to-all
overlaps chunk k+1's expert einsums.  Chunk outputs are CONCATENATED
and combined once — every (e, c) slot's compute is independent along
the capacity axis, so chunked and sequential schedules are
bitwise-identical by construction (the A/B the bench asserts).

The pure-jnp reference (``moe_ffn_ref``) is the CPU/tier-1 default and
the only path tier-1 exercises — no Pallas anywhere in this op.  The
router's aux loss is the Switch load-balance loss
``E * sum_e f_e * P_e`` (f_e = fraction of tokens whose TOP-1 choice
is e, P_e = mean router probability of e): differentiable through
P_e, so the generic vjp gives the router gradient for free.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.lowering import register_lower

__all__ = [
    "moe_capacity",
    "moe_router_ref",
    "moe_ffn_ref",
    "moe_balance_gauges",
]


def moe_capacity(num_tokens: int, num_experts: int, top_k: int,
                 capacity_factor: float) -> int:
    """Static per-expert slot count: ceil(S*K/E * factor), >= 1."""
    return max(1, int(math.ceil(
        num_tokens * top_k * capacity_factor / num_experts)))


# ---------------------------------------------------------------------------
# router (pure jnp; shared by training lowering and serving)
# ---------------------------------------------------------------------------


def moe_router_ref(x2d, gate_w, *, num_experts, top_k, capacity_factor):
    """Route [S, D] tokens: returns (combine [S,E,C] f32, aux_loss
    scalar, expert_load [E] f32 kept-token counts).

    Deterministic: ties in top-k resolve by lax.top_k's stable index
    order, and capacity slots are claimed in (choice, token) order —
    choice 0 of every token outranks choice 1 of any token, and within
    a choice lower token index wins (the GShard priority rule).
    """
    s = x2d.shape[0]
    e = int(num_experts)
    k = int(top_k)
    cap = moe_capacity(s, e, k, capacity_factor)

    logits = jnp.einsum("sd,de->se", x2d.astype(jnp.float32),
                        gate_w.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)                    # [S, E]
    gate_vals, gate_idx = lax.top_k(probs, k)                  # [S, K]
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)

    combine = jnp.zeros((s, e, cap), jnp.float32)
    counts = jnp.zeros((e,), jnp.float32)   # slots claimed per expert
    for choice in range(k):
        oh = jax.nn.one_hot(gate_idx[:, choice], e,
                            dtype=jnp.float32)                 # [S, E]
        # slot index of each token within its expert: tokens of this
        # choice queue behind every earlier choice's claims
        pos = jnp.cumsum(oh, axis=0) - oh + counts[None, :]    # [S, E]
        slot = jnp.sum(pos * oh, axis=-1)                      # [S]
        # one_hot zeroes out-of-range slots, so slot >= cap == dropped
        slot_oh = jax.nn.one_hot(slot, cap, dtype=jnp.float32)
        slot_oh = slot_oh * jnp.sum(oh, axis=-1, keepdims=True)
        combine = combine + (gate_vals[:, choice, None, None]
                             * oh[:, :, None] * slot_oh[:, None, :])
        counts = counts + jnp.sum(oh, axis=0)

    expert_load = jnp.sum(combine > 0.0, axis=(0, 2)).astype(jnp.float32)
    # Switch aux loss: top-1 assignment fraction x mean router prob
    f = jnp.mean(jax.nn.one_hot(gate_idx[:, 0], e, dtype=jnp.float32),
                 axis=0)
    p = jnp.mean(probs, axis=0)
    aux_loss = jnp.asarray(e, jnp.float32) * jnp.sum(
        lax.stop_gradient(f) * p)
    return combine, aux_loss, expert_load


# ---------------------------------------------------------------------------
# expert FFN body
# ---------------------------------------------------------------------------


def _expert_ffn(dispatched, w1, b1, w2, b2):
    """[E, C', D] dispatched slots -> [E, C', D] expert outputs; one
    stacked einsum pair over the locally-resident experts."""
    h = jnp.einsum("ecd,edh->ech", dispatched, w1) + b1[:, None, :]
    h = jax.nn.gelu(h)
    return jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]


def _ep_constraint(val, mesh, spec):
    from jax.sharding import NamedSharding, PartitionSpec

    return lax.with_sharding_constraint(
        val, NamedSharding(mesh, PartitionSpec(*spec)))


def moe_ffn_ref(x, gate_w, w1, b1, w2, b2, *, num_experts, top_k,
                capacity_factor, mesh=None, ep=False, chunks=0):
    """Full routed FFN over x [..., D] -> (out [..., D], aux_loss,
    expert_load [E]).  ``ep=True`` + a mesh with an 'ep' axis adds the
    GSPMD sharding constraints that materialize the dispatch/combine
    all-to-alls; ``chunks`` > 1 slices the capacity axis (bitwise-equal
    to the sequential schedule, see module docstring)."""
    lead = x.shape[:-1]
    d = x.shape[-1]
    x2d = x.reshape((-1, d))
    combine, aux_loss, expert_load = moe_router_ref(
        x2d, gate_w, num_experts=num_experts, top_k=top_k,
        capacity_factor=capacity_factor)
    cap = combine.shape[-1]
    dispatch = (combine > 0.0).astype(x2d.dtype)               # [S,E,C]
    combine = combine.astype(x2d.dtype)

    use_ep = bool(ep) and mesh is not None and "ep" in getattr(
        mesh, "axis_names", ())
    k = int(chunks or 0)
    chunked = k > 1 and cap % k == 0

    def body(disp_slice):
        buf = jnp.einsum("sec,sd->ecd", disp_slice, x2d)
        if use_ep:
            buf = _ep_constraint(buf, mesh, ("ep", None, None))
        y = _expert_ffn(buf, w1, b1, w2, b2)
        if use_ep:
            y = _ep_constraint(y, mesh, ("ep", None, None))
        return y

    if chunked:
        cc = cap // k
        y = jnp.concatenate(
            [body(dispatch[:, :, i * cc:(i + 1) * cc])
             for i in range(k)], axis=1)
    else:
        y = body(dispatch)
    out = jnp.einsum("sec,ecd->sd", combine, y)
    if use_ep:
        # token order is the caller's layout again: pin it replicated
        # over 'ep' so the combine all-to-all lands HERE, not later
        out = _ep_constraint(out, mesh, (None, None))
    return out.reshape(lead + (d,)), aux_loss, expert_load, chunked


# ---------------------------------------------------------------------------
# gauges (host-side; bench + serving)
# ---------------------------------------------------------------------------


def moe_balance_gauges(expert_load, num_tokens: int, top_k: int,
                       publish: bool = True):
    """Utilization gauges from one step's kept-token counts: balance =
    mean/max load in ppm (1e6 = perfectly even), dropped fraction of
    routed assignments in ppm.  Published via monitor stat_set."""
    import numpy as np

    load = np.asarray(expert_load, dtype=np.float64)
    routed = float(max(1, num_tokens * top_k))
    kept = float(load.sum())
    balance = float(load.mean() / load.max()) if load.max() > 0 else 0.0
    gauges = {
        "moe_expert_balance_ppm": int(balance * 1e6),
        "moe_dropped_fraction_ppm": int(
            max(0.0, 1.0 - kept / routed) * 1e6),
    }
    if publish:
        from ..monitor import stat_set

        for key, val in gauges.items():
            stat_set(key, val)
    return gauges


# ---------------------------------------------------------------------------
# lowering
# ---------------------------------------------------------------------------


def _dequant_stacked(carrier, scale):
    """Per-expert per-output-channel dequant of a stacked [E, *, O]
    carrier with scale [E, O] (ops/quant_ops.quantize_weight_stacked)."""
    return carrier.astype(scale.dtype) * scale[:, None, :]


@register_lower("moe_ffn")
def _moe_ffn_lower(ctx, op):
    from ..framework import flags as _flags
    from ..framework.passes import MOE_EP_ATTR
    from ..monitor import stat_add

    x = ctx.in1(op, "X")
    gate_w = ctx.in1(op, "GateW")
    w1 = ctx.in1(op, "W1")
    b1 = ctx.in1(op, "B1")
    w2 = ctx.in1(op, "W2")
    b2 = ctx.in1(op, "B2")
    s1 = ctx.in1(op, "W1Scale")
    s2 = ctx.in1(op, "W2Scale")
    if s1 is not None:
        w1 = _dequant_stacked(w1, s1)
    if s2 is not None:
        w2 = _dequant_stacked(w2, s2)

    chunks = int(_flags.flag("moe_alltoall_chunks") or 0)
    ep = bool(op.attr(MOE_EP_ATTR, False))
    manual = bool(getattr(ctx, "axis_env", ()) or ())
    if ep and manual:
        # The GPipe pipeline traces inside a shard_map with EVERY mesh
        # axis manual, where GSPMD sharding constraints are illegal —
        # and a manual slab/psum expert split would need the router's
        # gate gradient psum'd over 'ep', which the pipeline's grad
        # accumulation (dp-only) does not do.  Experts therefore stay
        # REPLICATED inside pipeline stages: each rank computes the
        # full routed FFN bitwise-identically, the plan's ep marks
        # still price the intended all-to-alls in the ledger, and this
        # counter records the runtime fallback.
        stat_add("moe_ep_manual_replicated")
        ep = False
    out, aux, load, chunked = moe_ffn_ref(
        x, gate_w, w1, b1, w2, b2,
        num_experts=int(op.attr("num_experts")),
        top_k=int(op.attr("top_k", 1)),
        capacity_factor=float(op.attr("capacity_factor", 1.0)),
        mesh=ctx.mesh, ep=ep, chunks=chunks)
    stat_add("moe_ffn_engaged")
    if chunked:
        stat_add("moe_alltoall_chunked")
    elif chunks > 1:
        stat_add("moe_alltoall_fallback")
    ctx.set_out(op, "Out", out)
    ctx.set_out(op, "AuxLoss", jnp.reshape(aux, (1,)))
    ctx.set_out(op, "ExpertLoad", load)
