"""Collective op lowerings: c_* ops -> XLA collectives.

Role parity: reference paddle/fluid/operators/collective/ —
c_allreduce_{sum,max,min,prod} (c_allreduce_op.h:55/109 -> ncclAllReduce
:157), c_broadcast, c_allgather, c_reducescatter, c_reduce_*, barrier,
c_gen_nccl_id / c_comm_init / c_sync_*_stream.

TPU-native redesign (SURVEY.md §5 'Distributed communication backend'):
there are no comm rings, id exchanges, or stream-sync ops — the mesh IS
the communicator.  Each op lowers to the matching `jax.lax` collective
(psum/pmax/pmin/all_gather/psum_scatter/ppermute) INSIDE the compiled
program; XLA schedules them over ICI/DCN.  When no mesh axis is in scope
(single device), every collective degenerates to identity, which is also
the reference's nranks==1 behavior.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.lowering import LoweringContext, register_lower


def _axis(ctx: LoweringContext, op):
    """Resolve the mesh axis (or axes) this op's ring_id maps to.

    Reference ring_id selects an NCCL communicator
    (collective_helper.h:50); here it selects a mesh axis by convention:
    ring 0 = the data-parallel axis (all axes named 'dp', else all in
    scope).  Returns None when no axis is in scope -> identity.
    """
    if not ctx.axis_env:
        return None
    ring = int(op.attr("ring_id", 0) or 0)
    mapping = getattr(ctx, "ring_axes", None) or {}
    if ring in mapping:
        return mapping[ring]
    if "dp" in ctx.axis_env:
        return "dp"
    return tuple(ctx.axis_env)


@register_lower("c_allreduce_sum", "allreduce", "mp_allreduce_sum")
def _c_allreduce_sum(ctx, op):
    x = ctx.in1(op, "X")
    ax = _axis(ctx, op)
    ctx.set_out(op, "Out", x if ax is None else lax.psum(x, ax))


@register_lower("c_allreduce_max")
def _c_allreduce_max(ctx, op):
    x = ctx.in1(op, "X")
    ax = _axis(ctx, op)
    ctx.set_out(op, "Out", x if ax is None else lax.pmax(x, ax))


@register_lower("c_allreduce_min")
def _c_allreduce_min(ctx, op):
    x = ctx.in1(op, "X")
    ax = _axis(ctx, op)
    ctx.set_out(op, "Out", x if ax is None else lax.pmin(x, ax))


@register_lower("c_allreduce_prod")
def _c_allreduce_prod(ctx, op):
    x = ctx.in1(op, "X")
    ax = _axis(ctx, op)
    if ax is None:
        ctx.set_out(op, "Out", x)
        return
    # no lax.pprod: exp(psum(log)) breaks for negatives; use all_gather+prod
    g = lax.all_gather(x, ax)
    ctx.set_out(op, "Out", jnp.prod(g, axis=0))


@register_lower("c_broadcast")
def _c_broadcast(ctx, op):
    x = ctx.in1(op, "X")
    ax = _axis(ctx, op)
    if ax is None:
        ctx.set_out(op, "Out", x)
        return
    root = int(op.attr("root", 0) or 0)
    idx = lax.axis_index(ax)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    ctx.set_out(op, "Out", lax.psum(masked, ax))


@register_lower("c_allgather")
def _c_allgather(ctx, op):
    x = ctx.in1(op, "X")
    ax = _axis(ctx, op)
    if ax is None:
        ctx.set_out(op, "Out", x)
        return
    ctx.set_out(op, "Out", lax.all_gather(x, ax, axis=0, tiled=True))


@register_lower("c_reducescatter")
def _c_reducescatter(ctx, op):
    x = ctx.in1(op, "X")
    ax = _axis(ctx, op)
    if ax is None:
        ctx.set_out(op, "Out", x)
        return
    ctx.set_out(op, "Out", lax.psum_scatter(x, ax, scatter_dimension=0, tiled=True))


def _c_reduce(reduce_fn):
    def rule(ctx, op):
        x = ctx.in1(op, "X")
        ax = _axis(ctx, op)
        if ax is None:
            ctx.set_out(op, "Out", x)
            return
        root = int(op.attr("root_id", op.attr("root", 0)) or 0)
        red = reduce_fn(x, ax)
        idx = lax.axis_index(ax)
        # result lands on root; other ranks keep their input (reference
        # leaves non-root outputs untouched)
        ctx.set_out(op, "Out", jnp.where(idx == root, red, x))

    return rule


register_lower("c_reduce_sum")(_c_reduce(lax.psum))
register_lower("c_reduce_max")(_c_reduce(lax.pmax))
register_lower("c_reduce_min")(_c_reduce(lax.pmin))


@register_lower("c_scatter")
def _c_scatter(ctx, op):
    x = ctx.in1(op, "X")
    ax = _axis(ctx, op)
    if ax is None:
        ctx.set_out(op, "Out", x)
        return
    root = int(op.attr("root", 0) or 0)
    # root's tensor is [nranks*shard, ...]; every rank takes its slice of
    # the broadcasted value
    idx = lax.axis_index(ax)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    full = lax.psum(masked, ax)
    shard = full.shape[0] // int(ctx.axis_size(ax))
    ctx.set_out(op, "Out", lax.dynamic_slice_in_dim(full, idx * shard, shard, 0))


@register_lower("c_concat")
def _c_concat(ctx, op):
    x = ctx.in1(op, "X")
    ax = _axis(ctx, op)
    out = x if ax is None else lax.all_gather(x, ax, axis=-1, tiled=True)
    ctx.set_out(op, "Out", out)


@register_lower("c_split")
def _c_split(ctx, op):
    x = ctx.in1(op, "X")
    ax = _axis(ctx, op)
    if ax is None:
        ctx.set_out(op, "Out", x)
        return
    idx = lax.axis_index(ax)
    shard = x.shape[-1] // int(ctx.axis_size(ax))
    ctx.set_out(op, "Out", lax.dynamic_slice_in_dim(x, idx * shard, shard, -1))


@register_lower("c_identity")
def _c_identity(ctx, op):
    ctx.set_out(op, "Out", ctx.in1(op, "X"))


@register_lower("barrier")
def _barrier(ctx, op):
    # inside one XLA program ordering is data-flow: a barrier is a psum of
    # a dummy scalar (forces a rendezvous point, like gloo Barrier)
    x = ctx.in1(op, "X")
    ax = _axis(ctx, op)
    if ax is not None:
        lax.psum(jnp.zeros((), jnp.float32), ax)
    if x is not None:
        ctx.set_out(op, "Out", x)


# comm-bootstrap ops survive as no-ops: mesh construction replaced them
@register_lower("c_gen_nccl_id", "c_comm_init", "c_comm_init_all",
                "c_sync_calc_stream", "c_sync_comm_stream", "c_wait_comm",
                "c_wait_compute")
def _c_noop(ctx, op):
    # pass X through if the op has the in/out slots
    x = ctx.in1(op, "X")
    if x is not None:
        ctx.set_out(op, "Out", x)


@register_lower("send_v2", "partial_send")
def _send_v2(ctx, op):
    """Generic p2p send (reference collective/send_v2_op.cc).

    SPMD redesign: the reference runs DIFFERENT programs per rank and
    moves bytes over an NCCL channel; here every rank runs the SAME
    program, so a send_v2/recv_v2 pair with one ring_id forms a
    point-to-point channel lowered by the RECV into a single ppermute
    edge (src = recv's peer, dst = send's peer).  The send just parks
    its operand for the matching recv in program order."""
    x = ctx.in1(op, "X")
    if op.type == "partial_send":
        # reference partial_send_op.cc: transmit the id-th of num equal
        # flat chunks (pipeline tensor-fusion traffic shaping); same
        # enforcements as the reference, loudly
        num = int(op.attr("num", 1) or 1)
        pid = int(op.attr("id", 0) or 0)
        flat = x.reshape(-1)
        if flat.shape[0] % num:
            raise ValueError(
                f"partial_send: numel {flat.shape[0]} is not divisible "
                f"by num={num} (elements would be silently dropped)")
        if not 0 <= pid < num:
            raise ValueError(
                f"partial_send: id={pid} out of range for num={num}")
        chunk = flat.shape[0] // num
        x = jax.lax.dynamic_slice_in_dim(flat, pid * chunk, chunk, 0)
    pend = getattr(ctx, "_pending_sends", None)
    if pend is None:
        pend = ctx._pending_sends = {}
    ring = int(op.attr("ring_id", 0) or 0)
    pend.setdefault(ring, []).append((int(op.attr("peer", 0) or 0), x))


@register_lower("recv_v2", "partial_recv")
def _recv_v2(ctx, op):
    """Generic p2p recv: pairs with the program-order-matching send_v2
    on the same ring and emits one ppermute edge src->dst.  Ranks off
    the edge receive zeros (XLA ppermute semantics; the reference's
    other ranks simply would not run the op).  Reference
    collective/recv_v2_op.cc."""
    ring = int(op.attr("ring_id", 0) or 0)
    pend = getattr(ctx, "_pending_sends", {}) or {}
    queue = pend.get(ring) or []
    if not queue:
        raise NotImplementedError(
            f"recv_v2(ring_id={ring}) has no matching send_v2 earlier "
            f"in this program: SPMD p2p lowers a send/recv PAIR to one "
            f"ppermute edge, so both ops must appear in the same "
            f"program (the pipeline executor pairs them per stage); a "
            f"recv with no send has no defined source value")
    dst, x = queue.pop(0)
    src = int(op.attr("peer", 0) or 0)
    ax = _axis(ctx, op)
    out = x if ax is None else lax.ppermute(
        x, ax if not isinstance(ax, tuple) else ax[0], [(src, dst)])
    if op.type == "partial_recv":
        # reference partial_recv_op.cc: the received chunk lands at
        # offset id*chunk of the FULL-size Out buffer (other slots 0)
        num = int(op.attr("num", 1) or 1)
        pid = int(op.attr("id", 0) or 0)
        if not 0 <= pid < num:
            raise ValueError(
                f"partial_recv: id={pid} out of range for num={num}")
        chunk = out.reshape(-1).shape[0]
        full = jnp.zeros((chunk * num,), out.dtype)
        full = jax.lax.dynamic_update_slice_in_dim(
            full, out.reshape(-1), pid * chunk, 0)
        shape = [int(s) for s in (op.attr("out_shape", []) or [])]
        out = full.reshape(shape) if shape and all(
            s > 0 for s in shape) else full
    ctx.set_out(op, "Out", out)


@register_lower("dgc")
def _dgc(ctx, op):
    """Deep gradient compression (reference operators/dgc_op.cc):
    momentum-corrected top-k gradient sparsification with local
    residual accumulation.

        u = m*u + g;  v = v + u
        mask = |v| among the top-k   (k = ratio * numel, static)
        encoded = v * mask;  v' = v*(1-mask);  u' = u*(1-mask)

    Pre-rampup steps (CurrentStep < rampup_begin_step) are a pure
    early-return (reference dgc_op.h): the dense grad passes through
    and U/V are left UNCHANGED — accumulating "warmup momentum" into U
    during passthrough would double-apply those gradients the moment
    compression engages (once via the dense grads already consumed by
    the optimizer, once via the accumulated U flushing into V).
    TPU-native note: the reference ships k (value,index) pairs over
    NCCL; XLA collectives are dense, so the masked-dense tensor rides
    the normal psum — convergence semantics (what DGC is for) are
    identical, and the top-k stays a static-shape lax.top_k the MXU
    pipeline can schedule."""
    g = ctx.in1(op, "Grad")
    u = ctx.in1(op, "U")
    v = ctx.in1(op, "V")
    step = ctx.in1(op, "CurrentStep")
    m = float(op.attr("m", 0.9))
    ratio = float(op.attr("ratio", 0.001))
    rampup_begin = float(op.attr("rampup_begin_step", 0.0))

    u_new = m * u + g
    v_new = v + u_new
    flat = jnp.abs(v_new).reshape(-1)
    k = max(1, int(round(ratio * flat.shape[0])))
    thr = lax.top_k(flat, k)[0][-1]
    mask = (jnp.abs(v_new) >= thr).astype(g.dtype)
    engaged = (jnp.reshape(step, ()) >= rampup_begin) if step is not None \
        else jnp.asarray(True)
    encoded = jnp.where(engaged, v_new * mask, g)
    keep = 1.0 - mask
    ctx.set_out(op, "U_out", jnp.where(engaged, u_new * keep, u))
    ctx.set_out(op, "V_out", jnp.where(engaged, v_new * keep, v))
    ctx.set_out(op, "EncodeGrad", encoded)
    ctx.set_out(op, "Grad_out", encoded)
    if ctx.out_name(op, "GatherBuff"):
        ctx.set_out(op, "GatherBuff", encoded)


@register_lower("uncoalesce_tensor")
def _uncoalesce_tensor(ctx, op):
    """Split a fused 1-D buffer back into its member tensors: sections
    give the flat lengths, dims/ranks encode each member's shape
    (attr lists are flat ints, so shapes ride as dims chunked by rank).
    Inverse of `coalesce_tensor` (ops/misc.py); the pair is emitted by
    framework/passes.py FuseAllReducePass around each bucketed
    gradient allreduce (reference fuse_all_reduce_op_pass +
    coalesce_tensor_op.cc, in a functional non-aliasing form)."""
    fused = ctx.get(op.inputs["Input"][0])
    sections = [int(s) for s in (op.attr("sections", []) or [])]
    dims = [int(d) for d in (op.attr("dims", []) or [])]
    ranks = [int(r) for r in (op.attr("ranks", []) or [])]
    outs = op.outputs.get("Output", [])
    if not (len(outs) == len(sections) == len(ranks)):
        raise ValueError(
            f"uncoalesce_tensor: {len(outs)} outputs vs "
            f"{len(sections)} sections / {len(ranks)} ranks")
    off = di = 0
    for name, n, r in zip(outs, sections, ranks):
        shape = tuple(dims[di:di + r])
        di += r
        ctx.set(name, fused[off:off + n].reshape(shape))
        off += n


@register_lower("c_shard_slice")
def _c_shard_slice(ctx, op):
    """ZeRO-1 helper (sharding meta-optimizer): this rank's dim-0 shard of
    a replicated tensor.  Reference ShardingOptimizer assigns whole params
    to ranks (sharding_optimizer.py:33); the TPU-native form slices every
    param/grad evenly so the optimizer update runs on 1/nranks of the
    elements per device.  Identity when no mesh axis is in scope."""
    x = ctx.in1(op, "X")
    ax = _axis(ctx, op)
    if ax is None:
        ctx.set_out(op, "Out", x)
        return
    n = int(ctx.axis_size(ax))
    if x.shape[0] % n:
        raise ValueError(
            f"c_shard_slice: dim 0 ({x.shape[0]}) not divisible by the "
            f"{n}-way mesh axis {ax!r}; the sharding transpiler must leave "
            f"this tensor replicated")
    idx = lax.axis_index(ax)
    shard = x.shape[0] // n
    ctx.set_out(op, "Out", lax.dynamic_slice_in_dim(x, idx * shard, shard, 0))
