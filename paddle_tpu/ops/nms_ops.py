"""NMS / proposal / matching ops as masked fixed-size lowerings.

Reference parity: operators/detection/multiclass_nms_op.cc (NMSFast:
greedy suppression with adaptive eta, :606 MultiClassNMS),
matrix_nms_op.cc (parallel decay), generate_proposals_op.cc (RPN
decode -> clip -> min-size filter -> NMS), bipartite_match_op.cc
(greedy global-argmax matching).

TPU-native redesign (SURVEY §7 LoD mitigation): the reference emits
LoD tensors whose row count is data-dependent; XLA needs static shapes,
so every op here returns FIXED-size outputs padded at the tail plus an
explicit valid count:

- multiclass_nms / multiclass_nms2 / multiclass_nms3: Out is
  [B, keep_top_k, 6] with invalid rows marked class = -1 (the
  reference's own no-detection marker), multiclass_nms2/3 add Index
  [B, keep_top_k] (-1 pad) and NmsRoisNum [B].
- matrix_nms: same contract (Out/Index/RoisNum).
- generate_proposals: RpnRois [B, post_nms_topN, 4], RpnRoiProbs
  [B, post_nms_topN, 1], RpnRoisNum [B]; pad rows are zero with prob 0.
- bipartite_match: dense [B, rows, cols] (or single [rows, cols])
  DistMat; outputs already fixed-shape in the reference.

The sequential suppression loop is a `lax.fori_loop` over a top-k
pre-sorted candidate list with an O(K^2) IoU matrix — K = nms_top_k is
a compile-time bound, so everything tiles statically onto the VPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.lowering import register_lower

NEG = -1e9  # python float: no backend touch at import time


def _pairwise_iou(boxes, normalized):
    """IoU matrix [M, M] (reference JaccardOverlap): +1 extent when the
    boxes are in un-normalized pixel coordinates."""
    off = 0.0 if normalized else 1.0
    x1, y1, x2, y2 = (boxes[:, i] for i in range(4))
    area = jnp.maximum(x2 - x1 + off, 0) * jnp.maximum(y2 - y1 + off, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    iw = jnp.maximum(ix2 - ix1 + off, 0)
    ih = jnp.maximum(iy2 - iy1 + off, 0)
    inter = iw * ih
    union = area[:, None] + area[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _greedy_nms_keep(boxes, valid, iou_threshold, eta, normalized):
    """Keep-mask over score-desc-sorted boxes (reference NMSFast):
    each CANDIDATE is tested against the threshold as decayed by all
    previously KEPT boxes (adaptive eta applies at candidate time, not
    keeper time); after every kept box the threshold decays by eta
    while it stays above 0.5."""
    m = boxes.shape[0]
    iou = _pairwise_iou(boxes, normalized)
    idx = jnp.arange(m)

    def body(j, carry):
        keep, thr = carry
        ov = jnp.max(jnp.where(jnp.logical_and(idx < j, keep), iou[j], 0.0))
        kj = jnp.logical_and(valid[j], ov <= thr)
        keep = keep.at[j].set(kj)
        if eta < 1.0:
            thr = jnp.where(jnp.logical_and(kj, thr > 0.5), thr * eta, thr)
        return keep, thr

    keep, _ = lax.fori_loop(0, m, body,
                            (jnp.zeros((m,), bool),
                             jnp.float32(iou_threshold)))
    return keep


def _per_class_nms(boxes, scores, background, score_thr, nms_top_k,
                   iou_thr, eta, normalized):
    """One image.  boxes [M, 4], scores [C, M] -> per-candidate
    (score, class, box_index) for C*K candidates, suppressed ones at
    score NEG."""
    C, M = scores.shape
    K = M if nms_top_k <= 0 else min(int(nms_top_k), M)

    def one_class(c, s):
        s = jnp.where(s > score_thr, s, NEG)
        top_s, order = lax.top_k(s, K)
        valid = top_s > NEG / 2
        keep = _greedy_nms_keep(boxes[order], valid, iou_thr, eta,
                                normalized)
        is_bg = c == background
        sel = jnp.where(jnp.logical_and(keep, jnp.logical_not(is_bg)),
                        top_s, NEG)
        return sel, order

    sel, order = jax.vmap(one_class)(jnp.arange(C), scores)
    cls = jnp.broadcast_to(jnp.arange(C)[:, None], (C, K))
    return sel.reshape(-1), cls.reshape(-1), order.reshape(-1)


def _merge_keep_top_k(sel, cls, order, boxes, keep_top_k):
    """Cross-class merge (reference keep_top_k stage): final rows
    [keep, 6] = (label, score, box), -1-class padded, plus indices and
    the valid count."""
    total = sel.shape[0]
    keep = total if keep_top_k <= 0 else min(int(keep_top_k), total)
    top_s, top_i = lax.top_k(sel, keep)
    valid = top_s > NEG / 2
    label = jnp.where(valid, cls[top_i], -1).astype(jnp.int32)
    bidx = jnp.where(valid, order[top_i], -1).astype(jnp.int32)
    b = jnp.where(valid[:, None], boxes[order[top_i]], 0.0)
    score = jnp.where(valid, top_s, 0.0)
    out = jnp.concatenate([label[:, None].astype(boxes.dtype),
                           score[:, None], b], axis=1)
    return out, bidx, valid.sum().astype(jnp.int32)


def _nms_common(ctx, op, with_index):
    boxes = ctx.in1(op, "BBoxes")   # [B, M, 4]
    scores = ctx.in1(op, "Scores")  # [B, C, M]
    if boxes.ndim == 2:
        boxes = boxes[None]
    if scores.ndim == 2:
        scores = scores[None]
    background = int(op.attr("background_label", 0))
    score_thr = float(op.attr("score_threshold", 0.0))
    nms_top_k = int(op.attr("nms_top_k", -1))
    iou_thr = float(op.attr("nms_threshold", 0.3))
    eta = float(op.attr("nms_eta", 1.0))
    keep_top_k = int(op.attr("keep_top_k", -1))
    normalized = bool(op.attr("normalized", True))

    def one_image(b, s):
        sel, cls, order = _per_class_nms(b, s, background, score_thr,
                                         nms_top_k, iou_thr, eta,
                                         normalized)
        return _merge_keep_top_k(sel, cls, order, b, keep_top_k)

    out, index, count = jax.vmap(one_image)(boxes, scores)
    ctx.set_out(op, "Out", out)
    if with_index:
        ctx.set_out(op, "Index", index)
    ctx.set_out(op, "NmsRoisNum", count)
    ctx.set_out(op, "RoisNum", count)


@register_lower("multiclass_nms")
def _multiclass_nms(ctx, op):
    _nms_common(ctx, op, with_index=False)


@register_lower("multiclass_nms2", "multiclass_nms3")
def _multiclass_nms2(ctx, op):
    _nms_common(ctx, op, with_index=True)


@register_lower("matrix_nms")
def _matrix_nms(ctx, op):
    """Parallel soft-NMS (reference matrix_nms_op.cc): each candidate's
    score decays by the worst-case overlap with any higher-scored
    candidate, compensated by that candidate's own overlap history —
    no sequential loop, a perfect fit for the VPU."""
    boxes = ctx.in1(op, "BBoxes")
    scores = ctx.in1(op, "Scores")
    if boxes.ndim == 2:
        boxes = boxes[None]
    if scores.ndim == 2:
        scores = scores[None]
    background = int(op.attr("background_label", 0))
    score_thr = float(op.attr("score_threshold", 0.0))
    post_thr = float(op.attr("post_threshold", 0.0))
    nms_top_k = int(op.attr("nms_top_k", -1))
    keep_top_k = int(op.attr("keep_top_k", -1))
    use_gaussian = bool(op.attr("use_gaussian", False))
    sigma = float(op.attr("gaussian_sigma", 2.0))
    normalized = bool(op.attr("normalized", True))
    C, M = scores.shape[1], scores.shape[2]
    K = M if nms_top_k <= 0 else min(int(nms_top_k), M)

    def one_class(c, s, b):
        s = jnp.where(s > score_thr, s, NEG)
        top_s, order = lax.top_k(s, K)
        valid = top_s > NEG / 2
        iou = _pairwise_iou(b[order], normalized)
        tri = jnp.tril(jnp.ones((K, K), bool), -1)  # i<j pairs: iou[j, i]
        iou_masked = jnp.where(tri, iou, 0.0)       # row j: overlaps w/ prev
        comp = jnp.max(iou_masked, axis=1)          # compensate_iou per box
        if use_gaussian:
            decay = jnp.exp((comp[None, :] ** 2 - iou_masked ** 2) * sigma)
        else:
            decay = (1.0 - iou_masked) / (1.0 - comp[None, :])
        decay = jnp.where(tri, decay, 1.0)
        dmin = jnp.min(decay, axis=1)
        ds = top_s * dmin
        sel = jnp.where(jnp.logical_and(
            jnp.logical_and(valid, ds > post_thr), c != background), ds, NEG)
        return sel, order

    def one_image(b, s):
        sel, order = jax.vmap(lambda c, sc: one_class(c, sc, b))(
            jnp.arange(C), s)
        cls = jnp.broadcast_to(jnp.arange(C)[:, None], (C, K))
        return _merge_keep_top_k(sel.reshape(-1), cls.reshape(-1),
                                 order.reshape(-1), b, keep_top_k)

    out, index, count = jax.vmap(one_image)(boxes, scores)
    ctx.set_out(op, "Out", out)
    ctx.set_out(op, "Index", index)
    ctx.set_out(op, "RoisNum", count)


@register_lower("bipartite_match")
def _bipartite_match(ctx, op):
    """Greedy global-argmax matching (reference bipartite_match_op.cc):
    repeatedly take the largest remaining entry, binding its row to its
    column; `per_prediction` then fills unmatched columns by per-column
    argmax over the distance threshold."""
    dist = ctx.in1(op, "DistMat")
    squeeze = dist.ndim == 2
    if squeeze:
        dist = dist[None]
    match_type = str(op.attr("match_type", "bipartite"))
    dist_threshold = float(op.attr("dist_threshold", 0.5))
    B, R, C = dist.shape

    def one(d):
        def body(_, carry):
            dm, idx, val = carry
            flat = dm.reshape(-1)
            k = jnp.argmax(flat)
            v = flat[k]
            r, c = k // C, k % C
            do = v > 0
            idx = jnp.where(do, idx.at[c].set(r.astype(jnp.int32)), idx)
            val = jnp.where(do, val.at[c].set(v), val)
            dm = jnp.where(do, dm.at[r, :].set(NEG).at[:, c].set(NEG), dm)
            return dm, idx, val

        _, idx, val = lax.fori_loop(
            0, min(R, C), body,
            (d, jnp.full((C,), -1, jnp.int32), jnp.zeros((C,), d.dtype)))
        if match_type == "per_prediction":
            col_best = jnp.argmax(d, axis=0).astype(jnp.int32)
            col_val = jnp.max(d, axis=0)
            fill = jnp.logical_and(idx < 0, col_val >= dist_threshold)
            idx = jnp.where(fill, col_best, idx)
            val = jnp.where(fill, col_val, val)
        return idx, val

    idx, val = jax.vmap(one)(dist)
    if squeeze:
        idx, val = idx[0][None], val[0][None]  # reference emits [1, C]
    ctx.set_out(op, "ColToRowMatchIndices", idx)
    ctx.set_out(op, "ColToRowMatchDist", val)


@register_lower("generate_proposals", "generate_proposals_v2")
def _generate_proposals(ctx, op):
    """RPN proposal generation (reference generate_proposals_op.cc):
    per image, top pre_nms_topN anchor scores -> delta decode -> clip ->
    min-size filter -> greedy NMS -> post_nms_topN, dense-padded."""
    scores = ctx.in1(op, "Scores")        # [B, A, H, W]
    deltas = ctx.in1(op, "BboxDeltas")    # [B, 4A, H, W]
    im_info = ctx.in1(op, "ImInfo")
    v1 = im_info is not None              # v1 carries (h, w, scale)
    if im_info is None:
        im_info = ctx.in1(op, "ImShape")  # v2: [B, 2] (h, w)
    anchors = ctx.in1(op, "Anchors").reshape(-1, 4)    # [H*W*A, 4]
    variances = ctx.in1(op, "Variances").reshape(-1, 4)
    pre_n = int(op.attr("pre_nms_topN", 6000))
    post_n = int(op.attr("post_nms_topN", 1000))
    nms_thr = float(op.attr("nms_thresh", 0.5))
    min_size = float(op.attr("min_size", 0.1))
    eta = float(op.attr("eta", 1.0))
    pixel_offset = bool(op.attr("pixel_offset", True))
    off = 1.0 if pixel_offset else 0.0

    B, A, H, W = scores.shape
    N = A * H * W
    # reference layout: scores/deltas transposed to (H, W, A[, 4]) to
    # match the anchor tensor's flattening
    sc = jnp.transpose(scores, (0, 2, 3, 1)).reshape(B, N)
    dl = jnp.transpose(deltas.reshape(B, A, 4, H, W),
                       (0, 3, 4, 1, 2)).reshape(B, N, 4)
    pre_k = min(pre_n, N) if pre_n > 0 else N

    def decode(anchor, var, d):
        aw = anchor[2] - anchor[0] + off
        ah = anchor[3] - anchor[1] + off
        acx = anchor[0] + 0.5 * aw
        acy = anchor[1] + 0.5 * ah
        bbox_clip = jnp.log(1000.0 / 16.0)
        cx = var[0] * d[0] * aw + acx
        cy = var[1] * d[1] * ah + acy
        w = jnp.exp(jnp.minimum(var[2] * d[2], bbox_clip)) * aw
        h = jnp.exp(jnp.minimum(var[3] * d[3], bbox_clip)) * ah
        return jnp.stack([cx - 0.5 * w, cy - 0.5 * h,
                          cx + 0.5 * w - off, cy + 0.5 * h - off])

    def one(s, d, info):
        top_s, order = lax.top_k(s, pre_k)
        props = jax.vmap(decode)(anchors[order], variances[order], d[order])
        ih, iw = info[0], info[1]
        props = jnp.stack([
            jnp.clip(props[:, 0], 0, iw - off),
            jnp.clip(props[:, 1], 0, ih - off),
            jnp.clip(props[:, 2], 0, iw - off),
            jnp.clip(props[:, 3], 0, ih - off)], axis=1)
        # reference FilterBoxes: min_size clamps to >= 1 and v1 compares
        # ORIGIN-scale extents ((x2-x1)/im_scale + 1) using im_info[2]
        ms = max(min_size, 1.0)
        if v1:
            scale = info[2]
            pw = (props[:, 2] - props[:, 0]) / scale + 1.0
            ph = (props[:, 3] - props[:, 1]) / scale + 1.0
        else:
            pw = props[:, 2] - props[:, 0] + off
            ph = props[:, 3] - props[:, 1] + off
        valid = jnp.logical_and(pw >= ms, ph >= ms)
        cand = jnp.where(valid, top_s, NEG)
        keep = _greedy_nms_keep(props, cand > NEG / 2, nms_thr, eta,
                                not pixel_offset)
        sel = jnp.where(keep, cand, NEG)
        kk = min(post_n, pre_k)
        fs, fi = lax.top_k(sel, kk)
        ok = fs > NEG / 2
        rois = jnp.where(ok[:, None], props[fi], 0.0)
        probs = jnp.where(ok, fs, 0.0)
        return rois, probs[:, None], ok.sum().astype(jnp.int32)

    rois, probs, count = jax.vmap(one)(sc, dl, im_info)
    ctx.set_out(op, "RpnRois", rois)
    ctx.set_out(op, "RpnRoiProbs", probs)
    ctx.set_out(op, "RpnRoisNum", count)
