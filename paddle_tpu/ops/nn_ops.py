"""Neural-net ops: conv, pool, softmax/cross-entropy, norms, embedding.

Reference parity: operators/conv_op.cc, pool_op.cc, softmax_op.cc,
softmax_with_cross_entropy_op.cc, cross_entropy_op.cc, batch_norm_op.cc,
layer_norm_op.cc, lookup_table_v2_op.cc.  Convs/matmuls stay big and
bfloat16-friendly for the MXU; XLA picks layouts.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.lowering import register_lower


# ---------------------------------------------------------------------------
# convolution
# ---------------------------------------------------------------------------


def _conv_paddings(paddings, padding_algorithm, ksize, strides, dilations, in_hw):
    """Resolve reference padding semantics -> lax ((lo, hi), ...) pairs."""
    nd = len(ksize)
    if padding_algorithm == "VALID":
        return [(0, 0)] * nd
    if padding_algorithm == "SAME":
        pads = []
        for i in range(nd):
            eff = (ksize[i] - 1) * dilations[i] + 1
            out = -(-in_hw[i] // strides[i])
            total = max(0, (out - 1) * strides[i] + eff - in_hw[i])
            pads.append((total // 2, total - total // 2))
        return pads
    paddings = [int(p) for p in paddings]
    if len(paddings) == nd:
        return [(p, p) for p in paddings]
    if len(paddings) == 2 * nd:
        return [(paddings[2 * i], paddings[2 * i + 1]) for i in range(nd)]
    raise ValueError(f"bad paddings {paddings}")


@register_lower("conv2d", "depthwise_conv2d")
def _conv2d(ctx, op):
    x = ctx.in1(op, "Input")
    w = ctx.in1(op, "Filter")  # OIHW
    strides = [int(s) for s in op.attr("strides", [1, 1])]
    dilations = [int(d) for d in op.attr("dilations", [1, 1])]
    groups = int(op.attr("groups", 1) or 1)
    data_format = op.attr("data_format", "NCHW") or "NCHW"
    if data_format in ("NHWC", "NDHWC"):
        x = jnp.transpose(x, (0, 3, 1, 2))
    if op.type == "depthwise_conv2d":
        groups = x.shape[1]
    ksize = w.shape[2:]
    pads = _conv_paddings(
        op.attr("paddings", [0, 0]),
        op.attr("padding_algorithm", "EXPLICIT"),
        ksize,
        strides,
        dilations,
        x.shape[2:],
    )
    out = jax.lax.conv_general_dilated(
        x,
        w,
        window_strides=strides,
        padding=pads,
        rhs_dilation=dilations,
        feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    if data_format in ("NHWC", "NDHWC"):
        out = jnp.transpose(out, (0, 2, 3, 1))
    ctx.set_out(op, "Output", out)


@register_lower("conv2d_transpose")
def _conv2d_transpose(ctx, op):
    x = ctx.in1(op, "Input")
    w = ctx.in1(op, "Filter")  # [in, out/groups, kh, kw]
    strides = [int(s) for s in op.attr("strides", [1, 1])]
    dilations = [int(d) for d in op.attr("dilations", [1, 1])]
    groups = int(op.attr("groups", 1) or 1)
    ksize = w.shape[2:]
    fwd_pads = _conv_paddings(
        op.attr("paddings", [0, 0]),
        op.attr("padding_algorithm", "EXPLICIT"),
        ksize,
        strides,
        dilations,
        x.shape[2:],
    )
    # conv_transpose's `padding` refers to the DILATED input: the reference
    # (and torch) "padding=p" maps to (k-1)*dilation - p on each side
    pads = [
        ((k - 1) * d - lo, (k - 1) * d - hi)
        for k, d, (lo, hi) in zip(ksize, dilations, fwd_pads)
    ]

    def one_group(xg, wg):
        return jax.lax.conv_transpose(
            xg,
            wg,
            strides=strides,
            padding=pads,
            rhs_dilation=dilations,
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            transpose_kernel=True,
        )

    if groups == 1:
        out = one_group(x, w)
    else:
        # lax.conv_transpose has no grouping; split channels per group
        xs = jnp.split(x, groups, axis=1)
        ws = jnp.split(w, groups, axis=0)
        out = jnp.concatenate([one_group(a, b) for a, b in zip(xs, ws)], axis=1)
    output_padding = [int(p) for p in op.attr("output_padding", []) or []]
    if output_padding and any(output_padding):
        out = jnp.pad(
            out,
            [(0, 0), (0, 0)] + [(0, p) for p in output_padding],
        )
    ctx.set_out(op, "Output", out)


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------


def _adaptive_pool_1d(x, axis, out_size, ptype):
    """Adaptive pooling along one axis with arbitrary output size:
    gather each cell's window (fixed max width) and reduce under a
    validity mask.  Dtype-preserving like the divisible-size branch."""
    from .common import adaptive_windows

    ih = int(x.shape[axis])
    idx, valid, maxw = adaptive_windows(ih, out_size)
    g = jnp.take(x, jnp.asarray(idx.ravel()), axis=axis)
    new_shape = (x.shape[:axis] + (out_size, maxw)
                 + x.shape[axis + 1:])
    g = g.reshape(new_shape)
    mshape = [1] * len(new_shape)
    mshape[axis], mshape[axis + 1] = out_size, maxw
    m = jnp.asarray(valid).reshape(mshape)
    if ptype == "max":
        lowest = (jnp.iinfo(g.dtype).min
                  if jnp.issubdtype(g.dtype, jnp.integer)
                  else jnp.asarray(-jnp.inf, g.dtype))
        return jnp.max(jnp.where(m, g, lowest), axis=axis + 1)
    counts = jnp.asarray(valid.sum(1)).astype(g.dtype).reshape(
        [out_size if i == axis else 1 for i in range(len(new_shape) - 1)])
    zero = jnp.zeros((), g.dtype)
    return jnp.sum(jnp.where(m, g, zero), axis=axis + 1) / counts


@register_lower("pool2d")
def _pool2d(ctx, op):
    x = ctx.in1(op, "X")
    ptype = op.attr("pooling_type", "max")
    ksize = [int(k) for k in op.attr("ksize", [1, 1])]
    strides = [int(s) for s in op.attr("strides", [1, 1])]
    adaptive = bool(op.attr("adaptive", False))
    global_pool = bool(op.attr("global_pooling", False))
    data_format = op.attr("data_format", "NCHW") or "NCHW"
    if data_format == "NHWC":
        x = jnp.transpose(x, (0, 3, 1, 2))

    if global_pool or (adaptive and ksize == [1, 1]):
        red = jnp.max if ptype == "max" else jnp.mean
        out = red(x, axis=(2, 3), keepdims=True)
    elif adaptive:
        oh, ow = ksize
        ih, iw = x.shape[2:]
        if ih % oh == 0 and iw % ow == 0:
            x5 = x.reshape(x.shape[0], x.shape[1], oh, ih // oh, ow, iw // ow)
            red = jnp.max if ptype == "max" else jnp.mean
            out = red(x5, axis=(3, 5))
        else:
            # non-divisible windows (reference AdaptivePool: cell i pools
            # [floor(i*I/O), ceil((i+1)*I/O))): window lengths differ by
            # at most 1, so gather a fixed max-width window per cell and
            # mask the tail — static shapes, separable per axis
            out = _adaptive_pool_1d(x, 2, oh, ptype)
            out = _adaptive_pool_1d(out, 3, ow, ptype)
    else:
        pads = _conv_paddings(
            op.attr("paddings", [0, 0]),
            op.attr("padding_algorithm", "EXPLICIT"),
            ksize,
            strides,
            [1, 1],
            x.shape[2:],
        )
        window = (1, 1) + tuple(ksize)
        strides4 = (1, 1) + tuple(strides)
        pads4 = [(0, 0), (0, 0)] + pads
        if ptype == "max":
            init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
            out = jax.lax.reduce_window(x, init, jax.lax.max, window, strides4, pads4)
        else:
            ones = jnp.ones_like(x)
            s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides4, pads4)
            if bool(op.attr("exclusive", True)):
                cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides4, pads4)
            else:
                cnt = float(np.prod(ksize))
            out = s / cnt
    if data_format == "NHWC":
        out = jnp.transpose(out, (0, 2, 3, 1))
    ctx.set_out(op, "Out", out)


# ---------------------------------------------------------------------------
# softmax & losses
# ---------------------------------------------------------------------------


@register_lower("softmax")
def _softmax(ctx, op):
    """bf16-transparent: exp/sum run in fp32 (bf16's 8 mantissa bits lose
    small probabilities), Out follows x.dtype so attention prob tensors
    stay bf16 under AMP."""
    x = ctx.in1(op, "X")
    axis = int(op.attr("axis", -1))
    out = jax.nn.softmax(x.astype(jnp.float32), axis=axis)
    ctx.set_out(op, "Out", out.astype(x.dtype))


@register_lower("log_softmax")
def _log_softmax(ctx, op):
    x = ctx.in1(op, "X")
    ctx.set_out(op, "Out", jax.nn.log_softmax(x, axis=int(op.attr("axis", -1))))


def _one_hot_last(labels, depth, dtype):
    return jax.nn.one_hot(jnp.squeeze(labels, -1) if labels.shape[-1] == 1 else labels, depth, dtype=dtype)


@register_lower("softmax_with_cross_entropy")
def _softmax_with_cross_entropy(ctx, op):
    logits = ctx.in1(op, "Logits")
    label = ctx.in1(op, "Label")
    axis = int(op.attr("axis", -1)) % logits.ndim
    soft_label = bool(op.attr("soft_label", False))
    ignore_index = int(op.attr("ignore_index", -100))
    logp = jax.nn.log_softmax(logits, axis=axis)
    softmax = jnp.exp(logp)
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lbl = label
        if lbl.ndim == logits.ndim and lbl.shape[axis] == 1:
            lbl = jnp.squeeze(lbl, axis)
        # clip negative ignore labels (e.g. -1/-100) before the gather;
        # out-of-range wrap would otherwise pick a real vocab row
        safe = jnp.clip(lbl, 0, logits.shape[axis] - 1)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, axis), axis=axis)
        # paddle semantics: positions whose label == ignore_index carry
        # zero loss regardless of the ignore_index sign (reference
        # softmax_with_cross_entropy_op.h hard-codes the compare)
        mask = jnp.expand_dims(lbl, axis) != ignore_index
        loss = jnp.where(mask, -picked, jnp.zeros_like(picked))
    ctx.set_out(op, "Softmax", softmax)
    ctx.set_out(op, "Loss", loss)


@register_lower("softmax_with_cross_entropy_grad")
def _softmax_with_cross_entropy_grad(ctx, op):
    softmax = ctx.in1(op, "Softmax")
    label = ctx.in1(op, "Label")
    dloss = ctx.in1(op, "Loss@GRAD")
    axis = int(op.attr("axis", -1)) % softmax.ndim
    soft_label = bool(op.attr("soft_label", False))
    ignore_index = int(op.attr("ignore_index", -100))
    if soft_label:
        dlogits = (softmax - label) * dloss
    else:
        lbl = label
        if lbl.ndim == softmax.ndim and lbl.shape[axis] == 1:
            lbl = jnp.squeeze(lbl, axis)
        safe = jnp.clip(lbl, 0, softmax.shape[axis] - 1)
        onehot = jax.nn.one_hot(safe, softmax.shape[axis], axis=axis, dtype=softmax.dtype)
        dlogits = (softmax - onehot) * dloss
        # ignored positions contribute zero loss -> zero gradient
        mask = jnp.expand_dims(lbl != ignore_index, axis)
        dlogits = jnp.where(mask, dlogits, jnp.zeros_like(dlogits))
    ctx.set_out(op, "Logits@GRAD", dlogits)


@register_lower("cross_entropy", "cross_entropy2")
def _cross_entropy(ctx, op):
    x = ctx.in1(op, "X")  # probabilities
    label = ctx.in1(op, "Label")
    soft_label = bool(op.attr("soft_label", False))
    eps = 1e-12
    logp = jnp.log(jnp.clip(x, eps, 1.0))
    if soft_label:
        loss = -jnp.sum(label * logp, axis=-1, keepdims=True)
    else:
        lbl = jnp.squeeze(label, -1) if label.ndim == x.ndim and label.shape[-1] == 1 else label
        picked = jnp.take_along_axis(logp, jnp.expand_dims(lbl, -1), axis=-1)
        loss = -picked
    ctx.set_out(op, "Y", loss)
    if op.outputs.get("XShape"):
        ctx.set_out(op, "XShape", jnp.zeros((0,), x.dtype))


@register_lower("sigmoid_cross_entropy_with_logits")
def _bce_logits(ctx, op):
    x = ctx.in1(op, "X")
    label = ctx.in1(op, "Label")
    # stable: max(x,0) - x*z + log(1+exp(-|x|))
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ignore_index = int(op.attr("ignore_index", -100))
    if ignore_index != -100:
        loss = jnp.where(label == ignore_index, jnp.zeros_like(loss), loss)
    if bool(op.attr("normalize", False)):
        norm = jnp.maximum(jnp.sum((label != ignore_index).astype(x.dtype)), 1.0)
        loss = loss / norm
    ctx.set_out(op, "Out", loss)


@register_lower("square_error_cost")
def _square_error_cost(ctx, op):
    x = ctx.in1(op, "X")
    y = ctx.in1(op, "Y")
    ctx.set_out(op, "Out", jnp.square(x - y))


@register_lower("huber_loss")
def _huber_loss(ctx, op):
    x = ctx.in1(op, "X")
    y = ctx.in1(op, "Y")
    d = float(op.attr("delta", 1.0))
    r = y - x
    a = jnp.abs(r)
    loss = jnp.where(a <= d, 0.5 * r * r, d * (a - 0.5 * d))
    ctx.set_out(op, "Out", loss)
    ctx.set_out(op, "Residual", r)


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------


@register_lower("batch_norm", "sync_batch_norm")
def _batch_norm(ctx, op):
    """bf16-transparent batch norm: statistics and normalization run in
    fp32 regardless of input dtype, but Y comes back in x.dtype, so under
    AMP the activation chain conv->bn->relu->pool stays bf16 end-to-end
    (the HBM-bandwidth win that dominates ResNet step time on TPU) while
    running mean/var and Saved* stay fp32.  Reference keeps batch_norm in
    the AMP black list instead (fp16_lists.py) because CUDA BN kernels are
    fp32; XLA fuses the casts so the fp32 island costs nothing here."""
    x = ctx.in1(op, "X")
    scale = ctx.in1(op, "Scale")
    bias = ctx.in1(op, "Bias")
    mean = ctx.in1(op, "Mean")
    var = ctx.in1(op, "Variance")
    eps = float(op.attr("epsilon", 1e-5))
    momentum = float(op.attr("momentum", 0.9))
    is_test = bool(op.attr("is_test", False))
    use_global = bool(op.attr("use_global_stats", False)) or is_test
    data_layout = op.attr("data_layout", "NCHW") or "NCHW"

    caxis = 1 if data_layout == "NCHW" else x.ndim - 1
    red_axes = tuple(i for i in range(x.ndim) if i != caxis)
    bshape = [1] * x.ndim
    bshape[caxis] = x.shape[caxis]

    xf = x.astype(jnp.float32)
    if use_global:
        m, v = mean.astype(jnp.float32), var.astype(jnp.float32)
        saved_mean, saved_var = m, v
    else:
        # one-pass moments: mean(x) and mean(x^2) are sibling reductions
        # XLA fuses into a single read of x; jnp.var's (x-m)^2 form would
        # read the activation tensor twice (m must land before the second
        # pass).  Deliberate trade-off: E[x^2]-E[x]^2 in fp32 loses
        # accuracy when |mean| >> std (cancellation), which is the same
        # trade flax/haiku BatchNorm make on TPU; worth ~9% ResNet-50
        # step time.
        m = jnp.mean(xf, axis=red_axes)
        v = jnp.mean(jnp.square(xf), axis=red_axes) - jnp.square(m)
        if op.type == "sync_batch_norm" and ctx.axis_env:
            # cross-replica moments ride ICI (reference sync_batch_norm_pass)
            ex2 = v + jnp.square(m)
            for ax in ctx.axis_env:
                m = jax.lax.pmean(m, ax)
                ex2 = jax.lax.pmean(ex2, ax)
            v = ex2 - jnp.square(m)
        # fp32 cancellation in E[x^2]-E[x]^2 can dip slightly negative for
        # large-mean/small-std activations; rsqrt(neg+eps) would be NaN
        v = jnp.maximum(v, 0.0)
        saved_mean, saved_var = m, v
        new_running_mean = momentum * mean + (1 - momentum) * m.astype(mean.dtype)
        new_running_var = momentum * var + (1 - momentum) * v.astype(var.dtype)
        ctx.set_out(op, "MeanOut", new_running_mean)
        ctx.set_out(op, "VarianceOut", new_running_var)
    inv = jax.lax.rsqrt(v + eps)
    out = (xf - m.reshape(bshape)) * inv.reshape(bshape) \
        * scale.astype(jnp.float32).reshape(bshape) \
        + bias.astype(jnp.float32).reshape(bshape)
    ctx.set_out(op, "Y", out.astype(x.dtype))
    if use_global:
        ctx.set_out(op, "MeanOut", mean)
        ctx.set_out(op, "VarianceOut", var)
    ctx.set_out(op, "SavedMean", saved_mean)
    ctx.set_out(op, "SavedVariance", jax.lax.rsqrt(saved_var + eps))


@register_lower("layer_norm")
def _layer_norm(ctx, op):
    x = ctx.in1(op, "X")
    scale = ctx.get_opt(op.inputs.get("Scale", [None])[0] if op.inputs.get("Scale") else None)
    bias = ctx.get_opt(op.inputs.get("Bias", [None])[0] if op.inputs.get("Bias") else None)
    eps = float(op.attr("epsilon", 1e-5))
    begin = int(op.attr("begin_norm_axis", 1))
    red = tuple(range(begin, x.ndim))
    xf = x.astype(jnp.float32)
    # one-pass fp32 moments (sibling reductions fuse into a single read;
    # same deliberate cancellation trade-off as batch_norm above)
    m = jnp.mean(xf, axis=red, keepdims=True)
    v = jnp.maximum(
        jnp.mean(jnp.square(xf), axis=red, keepdims=True) - jnp.square(m),
        0.0)
    y = (xf - m) * jax.lax.rsqrt(v + eps)
    norm_shape = x.shape[begin:]
    if scale is not None:
        y = y * scale.reshape(norm_shape).astype(jnp.float32)
    if bias is not None:
        y = y + bias.reshape(norm_shape).astype(jnp.float32)
    ctx.set_out(op, "Y", y.astype(x.dtype))
    ctx.set_out(op, "Mean", jnp.squeeze(m, red).reshape((-1,)))
    ctx.set_out(op, "Variance", jnp.squeeze(v, red).reshape((-1,)))


@register_lower("instance_norm")
def _instance_norm(ctx, op):
    x = ctx.in1(op, "X")
    scale = ctx.in1(op, "Scale")
    bias = ctx.in1(op, "Bias")
    eps = float(op.attr("epsilon", 1e-5))
    red = tuple(range(2, x.ndim))
    m = jnp.mean(x, axis=red, keepdims=True)
    v = jnp.var(x, axis=red, keepdims=True)
    y = (x - m) * jax.lax.rsqrt(v + eps)
    shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
    y = y * scale.reshape(shape) + bias.reshape(shape)
    ctx.set_out(op, "Y", y)
    ctx.set_out(op, "SavedMean", jnp.squeeze(m))
    ctx.set_out(op, "SavedVariance", jnp.squeeze(jax.lax.rsqrt(v + eps)))


@register_lower("group_norm")
def _group_norm(ctx, op):
    x = ctx.in1(op, "X")  # NCHW
    scale = ctx.get_opt(op.inputs.get("Scale", [None])[0] if op.inputs.get("Scale") else None)
    bias = ctx.get_opt(op.inputs.get("Bias", [None])[0] if op.inputs.get("Bias") else None)
    eps = float(op.attr("epsilon", 1e-5))
    groups = int(op.attr("groups", 1))
    n, c = x.shape[0], x.shape[1]
    xg = x.reshape((n, groups, c // groups) + x.shape[2:])
    red = tuple(range(2, xg.ndim))
    m = jnp.mean(xg, axis=red, keepdims=True)
    v = jnp.var(xg, axis=red, keepdims=True)
    y = ((xg - m) * jax.lax.rsqrt(v + eps)).reshape(x.shape)
    shape = [1, c] + [1] * (x.ndim - 2)
    if scale is not None:
        y = y * scale.reshape(shape)
    if bias is not None:
        y = y + bias.reshape(shape)
    ctx.set_out(op, "Y", y)
    ctx.set_out(op, "Mean", m.reshape((n, groups)))
    ctx.set_out(op, "Variance", v.reshape((n, groups)))


# embedding (lookup_table/lookup_table_v2) moved to embedding_ops.py —
# the sharded-engine dispatch lives with the all-to-all machinery there
