"""Sequence ops under dense/masked semantics.

Reference parity: operators/sequence_ops/*.cc, which operate on LoD
(ragged) tensors.  TPU-native (SURVEY §7 "LoD -> dense padding + mask"):
ragged batches are padded to [B, T, ...] upstream; ops that need real
lengths take them via the Length input (sequence_pad/unpad) or treat the
time axis uniformly.  This matches how the XLA-era successors of these
APIs behave; bitwise LoD parity is a non-goal (documented).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.lowering import register_lower


@register_lower("sequence_pool")
def _sequence_pool(ctx, op):
    """[B, T, ...] -> [B, ...] pooled over the time axis (uniform-length
    dense form of the reference LoD pooling)."""
    x = ctx.in1(op, "X")
    ptype = op.attr("pooltype", "AVERAGE").upper()
    if ptype == "AVERAGE":
        out = jnp.mean(x, axis=1)
    elif ptype == "SUM":
        out = jnp.sum(x, axis=1)
    elif ptype == "SQRT":
        out = jnp.sum(x, axis=1) / np.sqrt(x.shape[1])
    elif ptype == "MAX":
        out = jnp.max(x, axis=1)
    elif ptype == "LAST":
        out = x[:, -1]
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise NotImplementedError(f"sequence_pool {ptype}")
    ctx.set_out(op, "Out", out)
    if op.outputs.get("MaxIndex"):
        ctx.set_out(op, "MaxIndex",
                    jnp.argmax(x, axis=1).astype(jnp.int32))


@register_lower("sequence_softmax")
def _sequence_softmax(ctx, op):
    x = ctx.in1(op, "X")
    ctx.set_out(op, "Out", jax.nn.softmax(
        x.astype(jnp.float32), axis=1).astype(x.dtype))


@register_lower("sequence_reverse")
def _sequence_reverse(ctx, op):
    x = ctx.in1(op, "X")
    ctx.set_out(op, "Y", jnp.flip(x, axis=1 if x.ndim > 2 else 0))


@register_lower("sequence_concat")
def _sequence_concat(ctx, op):
    xs = ctx.in_list(op, "X")
    ctx.set_out(op, "Out", jnp.concatenate(xs, axis=1 if xs[0].ndim > 2 else 0))


@register_lower("sequence_reshape")
def _sequence_reshape(ctx, op):
    x = ctx.in1(op, "X")
    new_dim = int(op.attr("new_dim", x.shape[-1]))
    ctx.set_out(op, "Out", x.reshape(-1, new_dim))


@register_lower("sequence_expand")
def _sequence_expand(ctx, op):
    """Dense form: tile X's rows to match Y's time extent (uniform
    expansion, reference sequence_expand with uniform ref lod)."""
    x = ctx.in1(op, "X")
    y = ctx.in1(op, "Y")
    times = y.shape[0] // x.shape[0]
    ctx.set_out(op, "Out", jnp.repeat(x, times, axis=0))


@register_lower("sequence_expand_as")
def _sequence_expand_as(ctx, op):
    x = ctx.in1(op, "X")
    y = ctx.in1(op, "Y")
    times = y.shape[0] // x.shape[0]
    ctx.set_out(op, "Out", jnp.repeat(x, times, axis=0))


@register_lower("sequence_pad")
def _sequence_pad(ctx, op):
    """[sum_T, D] + Length -> [B, maxlen, D] (reference sequence_pad_op);
    dense uniform: rows are already grouped per sequence with uniform
    stride, so this is a reshape + mask fill."""
    x = ctx.in1(op, "X")
    pad_value = ctx.in1(op, "PadValue")
    length = ctx.in1(op, "Length")
    padded_len = int(op.attr("padded_length", -1))
    if length is not None:
        b = length.shape[0]
        t = x.shape[0] // b
        maxlen = padded_len if padded_len > 0 else t
        xr = x.reshape((b, t) + x.shape[1:])
        if maxlen > t:
            pads = [(0, 0), (0, maxlen - t)] + [(0, 0)] * (x.ndim - 1)
            xr = jnp.pad(xr, pads)
        mask = (jnp.arange(xr.shape[1])[None, :]
                < length.reshape(-1, 1)).astype(x.dtype)
        mshape = mask.shape + (1,) * (xr.ndim - 2)
        pv = pad_value.reshape(()) if pad_value.size == 1 else pad_value
        out = xr * mask.reshape(mshape) + pv * (1 - mask.reshape(mshape))
        ctx.set_out(op, "Out", out)
        ctx.set_out(op, "Length", length)
    else:
        raise NotImplementedError("sequence_pad needs the Length input")


@register_lower("sequence_unpad")
def _sequence_unpad(ctx, op):
    """[B, maxlen, D] + Length -> dense [B*maxlen, D] with padded rows
    zeroed (static shapes forbid true ragged output; consumers mask)."""
    x = ctx.in1(op, "X")
    length = ctx.in1(op, "Length")
    mask = (jnp.arange(x.shape[1])[None, :]
            < length.reshape(-1, 1)).astype(x.dtype)
    out = x * mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    ctx.set_out(op, "Out", out.reshape((-1,) + x.shape[2:]))


@register_lower("sequence_slice")
def _sequence_slice(ctx, op):
    x = ctx.in1(op, "X")
    offset = ctx.in1(op, "Offset")
    length = ctx.in1(op, "Length")
    off = int(np.asarray(offset).ravel()[0])
    ln = int(np.asarray(length).ravel()[0])
    ctx.set_out(op, "Out", x[off:off + ln])


@register_lower("sequence_enumerate")
def _sequence_enumerate(ctx, op):
    x = ctx.in1(op, "X")  # [T] or [T, 1] ids
    win = int(op.attr("win_size", 2))
    pad = int(op.attr("pad_value", 0))
    flat = x.reshape(-1)
    t = flat.shape[0]
    idx = jnp.arange(t)[:, None] + jnp.arange(win)[None, :]
    vals = jnp.where(idx < t, flat[jnp.clip(idx, 0, t - 1)], pad)
    ctx.set_out(op, "Out", vals.astype(x.dtype))


@register_lower("sequence_mask")
def _sequence_mask(ctx, op):
    x = ctx.in1(op, "X")  # lengths
    maxlen = int(op.attr("maxlen", -1))
    if maxlen <= 0:
        raise NotImplementedError(
            "sequence_mask needs a static maxlen attr on TPU (data-"
            "dependent max length breaks XLA static shapes)")
    from ..framework import dtypes as _dt

    out_dtype = op.attr("out_dtype", None)
    dt = _dt.to_jnp(out_dtype) if out_dtype else jnp.int64
    mask = jnp.arange(maxlen)[None, :] < x.reshape(-1, 1)
    ctx.set_out(op, "Y", mask.astype(dt))


@register_lower("sequence_conv")
def _sequence_conv(ctx, op):
    """Context-window conv over the time axis (reference
    sequence_conv_op): X [T, D], Filter [ctx_len*D, OD]."""
    x = ctx.in1(op, "X")
    f = ctx.in1(op, "Filter")
    ctx_len = int(op.attr("contextLength", 3))
    ctx_start = int(op.attr("contextStart", -1))
    t, d = x.shape
    cols = []
    for k in range(ctx_len):
        shift = ctx_start + k
        rows = jnp.arange(t) + shift
        valid = (rows >= 0) & (rows < t)
        g = x[jnp.clip(rows, 0, t - 1)] * valid[:, None].astype(x.dtype)
        cols.append(g)
    im2col = jnp.concatenate(cols, axis=1)  # [T, ctx_len*D]
    ctx.set_out(op, "Out", im2col @ f)


@register_lower("row_conv")
def _row_conv(ctx, op):
    """Lookahead row convolution (reference row_conv_op): X [T, D],
    Filter [future_ctx, D]."""
    x = ctx.in1(op, "X")
    f = ctx.in1(op, "Filter")
    t, d = x.shape
    k = f.shape[0]
    out = jnp.zeros_like(x)
    for i in range(k):
        rows = jnp.arange(t) + i
        valid = (rows < t).astype(x.dtype)[:, None]
        out = out + x[jnp.clip(rows, 0, t - 1)] * valid * f[i][None, :]
    ctx.set_out(op, "Out", out)
