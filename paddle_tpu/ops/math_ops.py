"""Math ops: matmul family, elementwise family, reductions.

Reference parity: operators/matmul_op.cc, mul_op.cc, matmul_v2_op.cc,
elementwise/*, reduce_ops/*.  All lower to single XLA HLOs; the MXU sees
plain dot_general / broadcasts, fusion is XLA's job.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.lowering import register_lower
from .common import bcast_shapes_elementwise


# ---------------------------------------------------------------------------
# matmul family
# ---------------------------------------------------------------------------


@register_lower("mul")
def _mul(ctx, op):
    """Flattening matmul: X flattened at x_num_col_dims, Y at y_num_col_dims."""
    x = ctx.in1(op, "X")
    y = ctx.in1(op, "Y")
    xn = int(op.attr("x_num_col_dims", 1))
    yn = int(op.attr("y_num_col_dims", 1))
    xs, ys = x.shape, y.shape
    x2 = x.reshape((-1, int(_prod(xs[xn:]))))
    y2 = y.reshape((int(_prod(ys[:yn])), -1))
    out = x2 @ y2
    out_shape = tuple(xs[:xn]) + tuple(ys[yn:])
    ctx.set_out(op, "Out", out.reshape(out_shape))


def _prod(t):
    p = 1
    for v in t:
        p *= int(v)
    return p


def _matmul_common(x, y, trans_x, trans_y, alpha=1.0):
    if trans_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if trans_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    if x.ndim == 1 and y.ndim == 1:
        out = jnp.dot(x, y)
    else:
        out = jnp.matmul(x, y)
    if alpha != 1.0:
        out = out * alpha
    return out


@register_lower("matmul")
def _matmul(ctx, op):
    x = ctx.in1(op, "X")
    y = ctx.in1(op, "Y")
    out = _matmul_common(
        x,
        y,
        bool(op.attr("transpose_X", False)),
        bool(op.attr("transpose_Y", False)),
        float(op.attr("alpha", 1.0)),
    )
    ctx.set_out(op, "Out", out)


@register_lower("matmul_v2")
def _matmul_v2(ctx, op):
    x = ctx.in1(op, "X")
    y = ctx.in1(op, "Y")
    out = _matmul_common(
        x, y, bool(op.attr("trans_x", False)), bool(op.attr("trans_y", False))
    )
    ctx.set_out(op, "Out", out)


@register_lower("dot")
def _dot(ctx, op):
    x = ctx.in1(op, "X")
    y = ctx.in1(op, "Y")
    ctx.set_out(op, "Out", jnp.sum(x * y, axis=-1, keepdims=x.ndim > 1))


@register_lower("bmm")
def _bmm(ctx, op):
    ctx.set_out(op, "Out", jnp.matmul(ctx.in1(op, "X"), ctx.in1(op, "Y")))


# ---------------------------------------------------------------------------
# elementwise binary family (axis-broadcast semantics of the reference)
# ---------------------------------------------------------------------------

_BINARY = {
    "elementwise_add": jnp.add,
    "elementwise_sub": jnp.subtract,
    "elementwise_mul": jnp.multiply,
    "elementwise_div": jnp.divide,
    "elementwise_max": jnp.maximum,
    "elementwise_min": jnp.minimum,
    "elementwise_pow": jnp.power,
    "elementwise_mod": jnp.mod,
    "elementwise_floordiv": jnp.floor_divide,
}


def _make_binary(fn):
    def lower(ctx, op):
        x = ctx.in1(op, "X")
        y = ctx.in1(op, "Y")
        axis = int(op.attr("axis", -1))
        x, y = bcast_shapes_elementwise(x, y, axis)
        ctx.set_out(op, "Out", fn(x, y))

    return lower


for _name, _fn in _BINARY.items():
    register_lower(_name)(_make_binary(_fn))


@register_lower("scale")
def _scale(ctx, op):
    x = ctx.in1(op, "X")
    scale = op.attr("scale", 1.0)
    s_in = ctx.in_list(op, "ScaleTensor")
    if s_in:
        scale = jnp.reshape(s_in[0], ())
    bias = op.attr("bias", 0.0)
    if bool(op.attr("bias_after_scale", True)):
        out = x * scale + jnp.asarray(bias, x.dtype)
    else:
        out = (x + jnp.asarray(bias, x.dtype)) * scale
    ctx.set_out(op, "Out", out.astype(x.dtype))


@register_lower("sum")
def _sum(ctx, op):
    xs = ctx.in_list(op, "X")
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    ctx.set_out(op, "Out", out)


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------


def _reduce_axes(op, x):
    axes = op.attr("dim", None)
    if op.attr("reduce_all", False) or axes is None or axes == []:
        return None
    return tuple(int(a) % x.ndim for a in (axes if isinstance(axes, (list, tuple)) else [axes]))


def _make_reduce(fn):
    def lower(ctx, op):
        x = ctx.in1(op, "X")
        axes = _reduce_axes(op, x)
        keep = bool(op.attr("keep_dim", False))
        out = fn(x, axis=axes, keepdims=keep)
        ctx.set_out(op, "Out", out)

    return lower


for _name, _fn in {
    "reduce_sum": jnp.sum,
    "reduce_mean": jnp.mean,
    "reduce_max": jnp.max,
    "reduce_min": jnp.min,
    "reduce_prod": jnp.prod,
}.items():
    register_lower(_name)(_make_reduce(_fn))


@register_lower("reduce_all")
def _reduce_all(ctx, op):
    x = ctx.in1(op, "X")
    ctx.set_out(op, "Out", jnp.all(x, axis=_reduce_axes(op, x), keepdims=bool(op.attr("keep_dim", False))))


@register_lower("reduce_any")
def _reduce_any(ctx, op):
    x = ctx.in1(op, "X")
    ctx.set_out(op, "Out", jnp.any(x, axis=_reduce_axes(op, x), keepdims=bool(op.attr("keep_dim", False))))


@register_lower("mean")
def _mean(ctx, op):
    # reference mean_op reduces to a single-element tensor of shape [1]
    ctx.set_out(op, "Out", jnp.mean(ctx.in1(op, "X")).reshape((1,)))


@register_lower("mean_grad")
def _mean_grad(ctx, op):
    x = ctx.in1(op, "X")
    dy = ctx.in1(op, "Out@GRAD")
    ctx.set_out(op, "X@GRAD", jnp.broadcast_to(jnp.reshape(dy, ()) / x.size, x.shape).astype(x.dtype))


# ---------------------------------------------------------------------------
# comparison / logical
# ---------------------------------------------------------------------------

for _name, _fn in {
    "equal": jnp.equal,
    "not_equal": jnp.not_equal,
    "less_than": jnp.less,
    "less_equal": jnp.less_equal,
    "greater_than": jnp.greater,
    "greater_equal": jnp.greater_equal,
}.items():

    def _mk(fn):
        def lower(ctx, op):
            x = ctx.in1(op, "X")
            y = ctx.in1(op, "Y")
            x, y = bcast_shapes_elementwise(x, y, int(op.attr("axis", -1)))
            ctx.set_out(op, "Out", fn(x, y))

        return lower

    register_lower(_name)(_mk(_fn))

for _name, _fn in {
    "logical_and": jnp.logical_and,
    "logical_or": jnp.logical_or,
    "logical_xor": jnp.logical_xor,
}.items():

    def _mk2(fn):
        def lower(ctx, op):
            ctx.set_out(op, "Out", fn(ctx.in1(op, "X"), ctx.in1(op, "Y")))

        return lower

    register_lower(_name)(_mk2(_fn))


@register_lower("logical_not")
def _logical_not(ctx, op):
    ctx.set_out(op, "Out", jnp.logical_not(ctx.in1(op, "X")))


# ---------------------------------------------------------------------------
# unary math (non-activation)
# ---------------------------------------------------------------------------

for _name, _fn in {
    "exp": jnp.exp,
    "log": jnp.log,
    "log2": jnp.log2,
    "log10": jnp.log10,
    "log1p": jnp.log1p,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: jax.lax.rsqrt(x),
    "abs": jnp.abs,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "round": jnp.round,
    "cos": jnp.cos,
    "sin": jnp.sin,
    "tan": jnp.tan,
    "acos": jnp.arccos,
    "asin": jnp.arcsin,
    "atan": jnp.arctan,
    "cosh": jnp.cosh,
    "sinh": jnp.sinh,
    "reciprocal": lambda x: 1.0 / x,
    "square": jnp.square,
    "sign": jnp.sign,
    "erf": jax.scipy.special.erf,
}.items():

    def _mku(fn):
        def lower(ctx, op):
            ctx.set_out(op, "Out", fn(ctx.in1(op, "X")))

        return lower

    register_lower(_name)(_mku(_fn))


@register_lower("pow")
def _pow(ctx, op):
    x = ctx.in1(op, "X")
    factor = op.attr("factor", 1.0)
    f_in = ctx.in_list(op, "FactorTensor")
    if f_in:
        factor = jnp.reshape(f_in[0], ())
    ctx.set_out(op, "Out", jnp.power(x, factor))


@register_lower("clip")
def _clip(ctx, op):
    x = ctx.in1(op, "X")
    lo = op.attr("min", None)
    hi = op.attr("max", None)
    ctx.set_out(op, "Out", jnp.clip(x, lo, hi))


@register_lower("isfinite", "isfinite_v2")
def _isfinite(ctx, op):
    x = ctx.in1(op, "X")
    out = jnp.all(jnp.isfinite(x)) if op.type == "isfinite" else jnp.isfinite(x)
    ctx.set_out(op, "Out", out)


@register_lower("isnan_v2")
def _isnan(ctx, op):
    ctx.set_out(op, "Out", jnp.isnan(ctx.in1(op, "X")))


@register_lower("isinf_v2")
def _isinf(ctx, op):
    ctx.set_out(op, "Out", jnp.isinf(ctx.in1(op, "X")))


@register_lower("maximum")
def _maximum(ctx, op):
    ctx.set_out(op, "Out", jnp.maximum(ctx.in1(op, "X"), ctx.in1(op, "Y")))


@register_lower("minimum")
def _minimum(ctx, op):
    ctx.set_out(op, "Out", jnp.minimum(ctx.in1(op, "X"), ctx.in1(op, "Y")))
