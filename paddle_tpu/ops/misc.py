"""Metrics, misc, and grad-infrastructure ops.

Reference parity: operators/metrics/accuracy_op.cc, coalesce-free grad
accumulation (sum), clip_by_norm_op.cc, squared_l2_norm_op.cc,
fill ops used by append_backward, increment/assign used by LR schedules
and control flow.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.lowering import register_lower
from .common import as_scalar


@register_lower("accuracy")
def _accuracy(ctx, op):
    pred_idx = ctx.in1(op, "Indices")  # [N, k] from top_k
    label = ctx.in1(op, "Label")  # [N, 1]
    if label.ndim == 1:
        label = label[:, None]
    correct = jnp.any(pred_idx == label, axis=1)
    num_correct = jnp.sum(correct.astype(jnp.float32))
    total = jnp.asarray(pred_idx.shape[0], jnp.float32)
    ctx.set_out(op, "Accuracy", (num_correct / total).reshape((1,)))
    ctx.set_out(op, "Correct", num_correct.astype(jnp.int32).reshape((1,)))
    ctx.set_out(op, "Total", jnp.asarray([pred_idx.shape[0]], jnp.int32))


@register_lower("increment")
def _increment(ctx, op):
    x = ctx.in1(op, "X")
    ctx.set_out(op, "Out", x + jnp.asarray(op.attr("step", 1.0), x.dtype))


@register_lower("clip_by_norm")
def _clip_by_norm(ctx, op):
    x = ctx.in1(op, "X")
    max_norm = op.attr("max_norm")
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    factor = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12), 1.0)
    ctx.set_out(op, "Out", x * factor.astype(x.dtype))


@register_lower("squared_l2_norm")
def _squared_l2_norm(ctx, op):
    x = ctx.in1(op, "X")
    ctx.set_out(op, "Out", jnp.sum(jnp.square(x.astype(jnp.float32))).reshape((1,)))


@register_lower("p_norm")
def _p_norm(ctx, op):
    x = ctx.in1(op, "X")
    porder = float(op.attr("porder", 2.0))
    axis = op.attr("axis", None)
    keepdim = bool(op.attr("keepdim", False))
    if axis is None or axis == [] or bool(op.attr("asvector", False)):
        axis = None
    else:
        axis = int(axis)
    if porder == float("inf"):
        out = jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    elif porder == float("-inf"):
        out = jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    else:
        out = jnp.power(
            jnp.sum(jnp.power(jnp.abs(x), porder), axis=axis, keepdims=keepdim),
            1.0 / porder,
        )
    ctx.set_out(op, "Out", out)


@register_lower("frobenius_norm")
def _frobenius_norm(ctx, op):
    x = ctx.in1(op, "X")
    axes = tuple(int(a) for a in op.attr("dim", []))
    keep = bool(op.attr("keep_dim", False))
    if op.attr("reduce_all", False) or not axes:
        axes = None
    ctx.set_out(op, "Out", jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=keep)))


@register_lower("auc")
def _auc(ctx, op):
    # streaming AUC needs host-side state; provide the batch statistic path
    preds = ctx.in1(op, "Predict")
    label = ctx.in1(op, "Label")
    pos_score = preds[:, 1]
    lbl = jnp.squeeze(label, -1) if label.ndim == 2 else label
    n_pos = jnp.sum(lbl == 1)
    n_neg = jnp.sum(lbl == 0)
    order = jnp.argsort(pos_score)
    ranks = jnp.empty_like(order).at[order].set(jnp.arange(1, pos_score.shape[0] + 1))
    sum_pos_ranks = jnp.sum(jnp.where(lbl == 1, ranks, 0))
    auc = (sum_pos_ranks - n_pos * (n_pos + 1) / 2.0) / jnp.maximum(n_pos * n_neg, 1)
    ctx.set_out(op, "AUC", auc.reshape((1,)).astype(jnp.float64))


@register_lower("print")
def _print(ctx, op):
    x = ctx.in1(op, "In")
    jax.debug.print("{} = {}", op.attr("message", op.input("In")[0]), x)
    ctx.set_out(op, "Out", x)


@register_lower("coalesce_tensor")
def _coalesce_tensor(ctx, op):
    # XLA fuses; grad-fusion buffers are a no-op — pass values through.
    for name_in, name_out in zip(op.inputs.get("Input", []), op.outputs.get("Output", [])):
        ctx.set(name_out, ctx.get(name_in))
    fused = op.outputs.get("FusedOutput")
    if fused:
        vals = [jnp.ravel(ctx.get(n)) for n in op.inputs.get("Input", [])]
        ctx.set(fused[0], jnp.concatenate(vals) if vals else jnp.zeros((0,)))


@register_lower("share_data", "memcpy", "memcpy_h2d", "memcpy_d2h")
def _share_data(ctx, op):
    ctx.set_out(op, "Out", ctx.in1(op, "X"))


@register_lower("beam_search")
def _beam_search(ctx, op):
    """One beam-search selection step (reference
    paddle/fluid/operators/math/beam_search.cc, layers/rnn.py:3136).

    TPU-native dense semantics (SURVEY §7 LoD mitigation): no per-batch
    beam shrinking — rows stay [batch*beam] and finished lanes (pre_id
    == end_id) compete with a single frozen-score end_id candidate.
    Inputs: pre_ids/pre_scores [B*K, 1], scores [B*K, C] (+ optional
    ids [B*K, C], else candidate j means token j); outputs
    selected_ids/selected_scores [B*K, 1] and parent_idx [B*K] (GLOBAL
    row index of each selected lane's parent).  The functional API
    (paddle_tpu.text.decode) is the recommended jit-native front end.
    """
    pre_ids = ctx.in1(op, "pre_ids")
    pre_scores = ctx.in1(op, "pre_scores")
    scores = ctx.in1(op, "scores")
    ids = ctx.in1(op, "ids")
    K = int(op.attr("beam_size"))
    end_id = int(op.attr("end_id"))
    accumulated = bool(op.attr("is_accumulated", True))
    BK, C = scores.shape
    if BK % K:
        raise ValueError(
            f"beam_search rows {BK} not divisible by beam_size {K}")
    B = BK // K
    NEG = jnp.float32(-1e9)

    if ids is None:
        ids = jnp.broadcast_to(jnp.arange(C, dtype=jnp.int32), (BK, C))
    ids = ids.astype(jnp.int32)
    pre_s = pre_scores.reshape(BK).astype(jnp.float32)
    acc = scores.astype(jnp.float32) if accumulated \
        else pre_s[:, None] + jnp.log(jnp.maximum(scores.astype(jnp.float32),
                                                  1e-30))
    finished = pre_ids.reshape(BK) == end_id
    # finished lanes: single end_id candidate at the frozen score
    only_end = jnp.full((C,), NEG).at[0].set(0.0)
    acc = jnp.where(finished[:, None], pre_s[:, None] + only_end[None, :],
                    acc)
    ids = jnp.where(finished[:, None], jnp.int32(end_id), ids)

    flat = acc.reshape(B, K * C)
    top_scores, top_idx = jax.lax.top_k(flat, K)  # [B, K]
    parent_in_group = top_idx // C
    sel_ids = jnp.take_along_axis(
        ids.reshape(B, K * C), top_idx, axis=1).astype(jnp.int32)
    parent_global = (jnp.arange(B)[:, None] * K
                     + parent_in_group).astype(jnp.int32)
    ctx.set_out(op, "selected_ids", sel_ids.reshape(BK, 1))
    ctx.set_out(op, "selected_scores", top_scores.reshape(BK, 1))
    ctx.set_out(op, "parent_idx", parent_global.reshape(BK))


@register_lower("beam_search_decode")
def _beam_search_decode(ctx, op):
    """Backtrack stacked beam-search steps into full hypotheses
    (reference beam_search_decode_op.cc, layers/rnn.py:3295).

    Dense redesign: instead of LoD TensorArrays, Ids/ParentIdx arrive
    stacked [T, B*K] (tokens and GLOBAL parent rows per step, as emitted
    by the beam_search lowering) and Scores [T, B*K]; outputs
    SentenceIds [B*K, T] (each final lane's full token path) and
    SentenceScores [B*K] (its final accumulated score).
    """
    from .linalg_ops import backtrack_beams

    ids = ctx.in1(op, "Ids").astype(jnp.int32)        # [T, BK]
    parents = ctx.in1(op, "ParentIdx").astype(jnp.int32)
    scores = ctx.in1(op, "Scores")
    K = int(op.attr("beam_size"))
    T, BK = ids.shape
    # global parent rows -> per-group local beams, then the shared
    # gather_tree ancestry walk
    sent = backtrack_beams(ids.reshape(T, BK // K, K),
                           (parents % K).reshape(T, BK // K, K))
    ctx.set_out(op, "SentenceIds",
                jnp.transpose(sent.reshape(T, BK), (1, 0)))
    ctx.set_out(op, "SentenceScores", scores[T - 1].reshape(BK))
