"""Tensor manipulation ops: reshape/transpose/concat/split/slice/gather/...

Reference parity: operators/reshape_op.cc, transpose_op.cc, concat_op.cc,
split_op.cc, slice_op.cc, gather_op.cc, scatter_op.cc, squeeze_op.cc,
unsqueeze_op.cc, stack_op.cc, tile/expand ops, cast_op.cc, top_k_op.cc,
arg_max/min, where/select ops, pad ops, one_hot.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.lowering import register_lower
from .common import attr_dtype


def _resolve_reshape(x, shape):
    out = list(int(s) for s in shape)
    for i, s in enumerate(out):
        if s == 0:
            out[i] = x.shape[i]
    return out


@register_lower("reshape", "reshape2")
def _reshape(ctx, op):
    x = ctx.in1(op, "X")
    shape = op.attr("shape", [])
    st = op.inputs.get("ShapeTensor") or op.inputs.get("Shape")
    if st:
        vals = [int(np.asarray(ctx.get(n)).item()) if np.asarray(ctx.get(n)).size == 1 else None for n in st]
        if len(st) == 1 and vals[0] is None:
            shape = [int(v) for v in np.asarray(ctx.get(st[0]))]
        elif all(v is not None for v in vals):
            shape = vals
    out = x.reshape(_resolve_reshape(x, shape))
    ctx.set_out(op, "Out", out)
    if op.outputs.get("XShape"):
        ctx.set_out(op, "XShape", jnp.zeros((0,) + tuple(x.shape), x.dtype))


@register_lower("reshape2_grad")
def _reshape2_grad(ctx, op):
    dy = ctx.in1(op, "Out@GRAD")
    xshape = ctx.in1(op, "XShape")
    ctx.set_out(op, "X@GRAD", dy.reshape(tuple(xshape.shape)[1:]))


@register_lower("transpose", "transpose2")
def _transpose(ctx, op):
    x = ctx.in1(op, "X")
    axis = [int(a) for a in op.attr("axis", [])]
    out = jnp.transpose(x, axis)
    ctx.set_out(op, "Out", out)
    if op.outputs.get("XShape"):
        ctx.set_out(op, "XShape", jnp.zeros((0,) + tuple(x.shape), x.dtype))


@register_lower("transpose2_grad")
def _transpose2_grad(ctx, op):
    dy = ctx.in1(op, "Out@GRAD")
    axis = [int(a) for a in op.attr("axis", [])]
    inv = np.argsort(axis)
    ctx.set_out(op, "X@GRAD", jnp.transpose(dy, inv))


@register_lower("flatten", "flatten2")
def _flatten(ctx, op):
    x = ctx.in1(op, "X")
    axis = int(op.attr("axis", 1))
    lead = 1
    for s in x.shape[:axis]:
        lead *= int(s)
    out = x.reshape((lead, -1))
    ctx.set_out(op, "Out", out)
    if op.outputs.get("XShape"):
        ctx.set_out(op, "XShape", jnp.zeros((0,) + tuple(x.shape), x.dtype))


@register_lower("flatten_contiguous_range")
def _flatten_range(ctx, op):
    x = ctx.in1(op, "X")
    start = int(op.attr("start_axis", 1)) % max(x.ndim, 1)
    stop = int(op.attr("stop_axis", -1)) % max(x.ndim, 1)
    shape = list(x.shape[:start]) + [-1] + list(x.shape[stop + 1 :])
    ctx.set_out(op, "Out", x.reshape(shape))
    if op.outputs.get("XShape"):
        ctx.set_out(op, "XShape", jnp.zeros((0,) + tuple(x.shape), x.dtype))


@register_lower("squeeze", "squeeze2")
def _squeeze(ctx, op):
    x = ctx.in1(op, "X")
    axes = [int(a) % x.ndim for a in op.attr("axes", [])]
    if not axes:
        axes = [i for i, s in enumerate(x.shape) if s == 1]
    axes = [a for a in axes if x.shape[a] == 1]
    ctx.set_out(op, "Out", jnp.squeeze(x, tuple(axes)) if axes else x)
    if op.outputs.get("XShape"):
        ctx.set_out(op, "XShape", jnp.zeros((0,) + tuple(x.shape), x.dtype))


@register_lower("unsqueeze", "unsqueeze2")
def _unsqueeze(ctx, op):
    x = ctx.in1(op, "X")
    axes = [int(a) for a in op.attr("axes", [])]
    out = x
    for a in sorted(axes):
        out = jnp.expand_dims(out, a if a >= 0 else a + out.ndim + 1)
    ctx.set_out(op, "Out", out)
    if op.outputs.get("XShape"):
        ctx.set_out(op, "XShape", jnp.zeros((0,) + tuple(x.shape), x.dtype))


@register_lower("concat")
def _concat(ctx, op):
    xs = ctx.in_list(op, "X")
    axis = int(op.attr("axis", 0))
    at = op.inputs.get("AxisTensor")
    if at:
        axis = int(np.asarray(ctx.get(at[0])).item())
    ctx.set_out(op, "Out", jnp.concatenate(xs, axis=axis))


@register_lower("split")
def _split(ctx, op):
    x = ctx.in1(op, "X")
    axis = int(op.attr("axis", 0))
    num = int(op.attr("num", 0))
    sections = [int(s) for s in op.attr("sections", []) or []]
    outs = op.outputs.get("Out", [])
    if sections:
        # sections may contain one -1
        total = x.shape[axis]
        known = sum(s for s in sections if s > 0)
        sections = [s if s > 0 else total - known for s in sections]
        idx = np.cumsum(sections)[:-1]
        parts = jnp.split(x, idx, axis=axis)
    else:
        parts = jnp.split(x, num or len(outs), axis=axis)
    for name, p in zip(outs, parts):
        ctx.set(name, p)


@register_lower("stack")
def _stack(ctx, op):
    xs = ctx.in_list(op, "X")
    ctx.set_out(op, "Y", jnp.stack(xs, axis=int(op.attr("axis", 0))))


@register_lower("unstack")
def _unstack(ctx, op):
    x = ctx.in1(op, "X")
    axis = int(op.attr("axis", 0))
    parts = [jnp.squeeze(p, axis) for p in jnp.split(x, x.shape[axis], axis=axis)]
    for name, p in zip(op.outputs.get("Y", []), parts):
        ctx.set(name, p)


@register_lower("slice")
def _slice(ctx, op):
    x = ctx.in1(op, "Input")
    axes = [int(a) for a in op.attr("axes", [])]
    starts = [int(s) for s in op.attr("starts", [])]
    ends = [int(e) for e in op.attr("ends", [])]
    decrease = [int(d) for d in op.attr("decrease_axis", []) or []]
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        dim = x.shape[a]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        idx[a] = slice(s, e)
    out = x[tuple(idx)]
    if decrease:
        out = jnp.squeeze(out, tuple(d for d in decrease if out.shape[d] == 1))
    ctx.set_out(op, "Out", out)


@register_lower("strided_slice")
def _strided_slice(ctx, op):
    x = ctx.in1(op, "Input")
    axes = [int(a) for a in op.attr("axes", [])]
    starts = [int(s) for s in op.attr("starts", [])]
    ends = [int(e) for e in op.attr("ends", [])]
    strides = [int(s) for s in op.attr("strides", [])]
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(axes, starts, ends, strides):
        idx[a] = slice(s, e, st)
    ctx.set_out(op, "Out", x[tuple(idx)])


@register_lower("gather")
def _gather(ctx, op):
    x = ctx.in1(op, "X")
    index = ctx.in1(op, "Index")
    axis = int(op.attr("axis", 0))
    if index.ndim == 2 and index.shape[1] == 1:
        index = jnp.squeeze(index, -1)
    ctx.set_out(op, "Out", jnp.take(x, index, axis=axis))


@register_lower("gather_nd")
def _gather_nd(ctx, op):
    x = ctx.in1(op, "X")
    index = ctx.in1(op, "Index")
    k = index.shape[-1]
    idx = tuple(index[..., i] for i in range(k))
    ctx.set_out(op, "Out", x[idx])


@register_lower("scatter")
def _scatter(ctx, op):
    x = ctx.in1(op, "X")
    ids = ctx.in1(op, "Ids")
    updates = ctx.in1(op, "Updates")
    if ids.ndim == 2 and ids.shape[1] == 1:
        ids = jnp.squeeze(ids, -1)
    if bool(op.attr("overwrite", True)):
        out = x.at[ids].set(updates)
    else:
        out = x.at[ids].add(updates)
    ctx.set_out(op, "Out", out)


@register_lower("scatter_nd_add")
def _scatter_nd_add(ctx, op):
    x = ctx.in1(op, "X")
    index = ctx.in1(op, "Index")
    updates = ctx.in1(op, "Updates")
    k = index.shape[-1]
    idx = tuple(index[..., i] for i in range(k))
    ctx.set_out(op, "Out", x.at[idx].add(updates))


@register_lower("index_select")
def _index_select(ctx, op):
    x = ctx.in1(op, "X")
    index = ctx.in1(op, "Index")
    ctx.set_out(op, "Out", jnp.take(x, index, axis=int(op.attr("dim", 0))))


@register_lower("cast")
def _cast(ctx, op):
    x = ctx.in1(op, "X")
    ctx.set_out(op, "Out", x.astype(attr_dtype(op, "out_dtype")))


@register_lower("expand", "tile")
def _expand(ctx, op):
    x = ctx.in1(op, "X")
    times = [int(t) for t in (op.attr("expand_times", None) or op.attr("repeat_times", []))]
    if len(times) < x.ndim:
        times = [1] * (x.ndim - len(times)) + times
    elif len(times) > x.ndim:
        x = x.reshape((1,) * (len(times) - x.ndim) + x.shape)
    ctx.set_out(op, "Out", jnp.tile(x, times))


@register_lower("expand_as", "expand_as_v2")
def _expand_as(ctx, op):
    x = ctx.in1(op, "X")
    target = op.inputs.get("Y") or op.inputs.get("target_tensor")
    shape = tuple(ctx.get(target[0]).shape) if target else tuple(op.attr("target_shape", []))
    ctx.set_out(op, "Out", jnp.broadcast_to(x, shape))


@register_lower("expand_v2")
def _expand_v2(ctx, op):
    x = ctx.in1(op, "X")
    shape = [int(s) for s in op.attr("shape", [])]
    if len(shape) > x.ndim:
        x = x.reshape((1,) * (len(shape) - x.ndim) + x.shape)
    shape = [x.shape[i] if s == -1 else s for i, s in enumerate(shape)]
    ctx.set_out(op, "Out", jnp.broadcast_to(x, shape))


@register_lower("top_k", "top_k_v2")
def _top_k(ctx, op):
    x = ctx.in1(op, "X")
    k = int(op.attr("k", 1))
    kt = op.inputs.get("K")
    if kt:
        k = int(np.asarray(ctx.get(kt[0])).item())
    axis = int(op.attr("axis", -1))
    largest = bool(op.attr("largest", True))
    if axis % x.ndim != x.ndim - 1:
        xm = jnp.moveaxis(x, axis, -1)
    else:
        xm = x
    vals, idx = jax.lax.top_k(xm if largest else -xm, k)
    if not largest:
        vals = -vals
    if axis % x.ndim != x.ndim - 1:
        vals = jnp.moveaxis(vals, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis)
    ctx.set_out(op, "Out", vals)
    ctx.set_out(op, "Indices", idx.astype(jnp.int32))


@register_lower("arg_max")
def _arg_max(ctx, op):
    x = ctx.in1(op, "X")
    axis = op.attr("axis", -1)
    keepdims = bool(op.attr("keepdims", False))
    flatten = bool(op.attr("flatten", False))
    if flatten:
        x = x.reshape(-1)
        axis = 0
    out = jnp.argmax(x, axis=int(axis))
    if keepdims and not flatten:
        out = jnp.expand_dims(out, int(axis))
    ctx.set_out(op, "Out", out.astype(attr_dtype(op, "dtype", default="int64")))


@register_lower("arg_min")
def _arg_min(ctx, op):
    x = ctx.in1(op, "X")
    axis = int(op.attr("axis", -1))
    out = jnp.argmin(x, axis=axis)
    if bool(op.attr("keepdims", False)):
        out = jnp.expand_dims(out, axis)
    ctx.set_out(op, "Out", out.astype(attr_dtype(op, "dtype", default="int64")))


@register_lower("argsort")
def _argsort(ctx, op):
    x = ctx.in1(op, "X")
    axis = int(op.attr("axis", -1))
    desc = bool(op.attr("descending", False))
    idx = jnp.argsort(-x if desc else x, axis=axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    ctx.set_out(op, "Out", out)
    ctx.set_out(op, "Indices", idx.astype(jnp.int32))


@register_lower("where")
def _where(ctx, op):
    cond = ctx.in1(op, "Condition")
    x = ctx.in1(op, "X")
    y = ctx.in1(op, "Y")
    ctx.set_out(op, "Out", jnp.where(cond, x, y))


# where_index lives in tail_ops.py (masked fixed-size lowering).


@register_lower("one_hot", "one_hot_v2")
def _one_hot(ctx, op):
    x = ctx.in1(op, "X")
    depth = int(op.attr("depth", -1))
    dt = op.inputs.get("depth_tensor")
    if dt:
        depth = int(np.asarray(ctx.get(dt[0])).item())
    if op.type == "one_hot" and x.ndim >= 2 and x.shape[-1] == 1:
        x = jnp.squeeze(x, -1)
    ctx.set_out(op, "Out", jax.nn.one_hot(x, depth, dtype=jnp.float32))


@register_lower("shape")
def _shape(ctx, op):
    x = ctx.in1(op, "Input")
    ctx.set_out(op, "Out", jnp.asarray(x.shape, dtype=jnp.int32))


@register_lower("size")
def _size(ctx, op):
    x = ctx.in1(op, "Input")
    ctx.set_out(op, "Out", jnp.asarray(x.size, dtype=jnp.int64))


@register_lower("pad")
def _pad(ctx, op):
    x = ctx.in1(op, "X")
    paddings = [int(p) for p in op.attr("paddings", [])]
    pairs = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(x.ndim)]
    ctx.set_out(op, "Out", jnp.pad(x, pairs, constant_values=op.attr("pad_value", 0.0)))


@register_lower("pad2d", "pad3d")
def _pad2d(ctx, op):
    x = ctx.in1(op, "X")
    paddings = [int(p) for p in op.attr("paddings", [])]
    mode = op.attr("mode", "constant")
    fmt = op.attr("data_format", "NCHW")
    nspatial = x.ndim - 2
    # paddings given as [left,right,top,bottom,...] per reference pad2d/pad3d
    spatial_pairs = [
        (paddings[2 * i], paddings[2 * i + 1]) for i in range(len(paddings) // 2)
    ]
    spatial_pairs = list(reversed(spatial_pairs))[:nspatial]
    while len(spatial_pairs) < nspatial:
        spatial_pairs.insert(0, (0, 0))
    if fmt.endswith("C"):  # NHWC/NDHWC
        pairs = [(0, 0)] + spatial_pairs + [(0, 0)]
    else:
        pairs = [(0, 0), (0, 0)] + spatial_pairs
    jmode = {"constant": "constant", "reflect": "reflect", "edge": "edge", "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        out = jnp.pad(x, pairs, constant_values=op.attr("value", op.attr("pad_value", 0.0)))
    else:
        out = jnp.pad(x, pairs, mode=jmode)
    ctx.set_out(op, "Out", out)


@register_lower("tril_triu")
def _tril_triu(ctx, op):
    x = ctx.in1(op, "X")
    diag = int(op.attr("diagonal", 0))
    lower = bool(op.attr("lower", True))
    ctx.set_out(op, "Out", jnp.tril(x, diag) if lower else jnp.triu(x, diag))


@register_lower("cumsum")
def _cumsum(ctx, op):
    x = ctx.in1(op, "X")
    axis = int(op.attr("axis", -1))
    flatten = bool(op.attr("flatten", False))
    if flatten:
        x = x.reshape(-1)
        axis = 0
    out = jnp.cumsum(x, axis=axis)
    if bool(op.attr("reverse", False)):
        out = jnp.flip(jnp.cumsum(jnp.flip(x, axis), axis=axis), axis)
    if bool(op.attr("exclusive", False)):
        out = out - x
    ctx.set_out(op, "Out", out)


@register_lower("take_along_axis")
def _take_along_axis(ctx, op):
    x = ctx.in1(op, "Input")
    idx = ctx.in1(op, "Index")
    ctx.set_out(op, "Result", jnp.take_along_axis(x, idx, axis=int(op.attr("Axis", 0))))


@register_lower("meshgrid")
def _meshgrid(ctx, op):
    xs = ctx.in_list(op, "X")
    outs = jnp.meshgrid(*xs, indexing="ij")
    for name, o in zip(op.outputs.get("Out", []), outs):
        ctx.set(name, o)


@register_lower("flip")
def _flip(ctx, op):
    x = ctx.in1(op, "X")
    axes = [int(a) for a in op.attr("axis", [])]
    ctx.set_out(op, "Out", jnp.flip(x, tuple(axes)))


@register_lower("roll")
def _roll(ctx, op):
    x = ctx.in1(op, "X")
    shifts = [int(s) for s in op.attr("shifts", [])]
    axes = op.attr("axis", []) or None
    if axes is not None:
        axes = [int(a) for a in axes]
        ctx.set_out(op, "Out", jnp.roll(x, shifts, axes))
    else:
        ctx.set_out(op, "Out", jnp.roll(x.reshape(-1), shifts[0]).reshape(x.shape))


@register_lower("recompute_barrier")
def _recompute_barrier(ctx, op):
    """CSE fence for activation recompute (framework/backward.py
    _emit_recompute_segments): identity through lax.optimization_barrier so
    XLA cannot common-subexpression the re-emitted forward segment with the
    original and keep the activations alive."""
    x = ctx.in1(op, "X")
    ctx.set_out(op, "Out", jax.lax.optimization_barrier(x))
