"""Fused ops: multi-head attention, flash-kernel engagement by flag.

Role parity: reference operators/fused/multihead_matmul_op.cu (the
transformer attention fusion used by inference + the fused bert encoder
functors in operators/math/bert_encoder_functor.cu).

Three lowerings share one op:
- plain XLA composition (default; XLA's own fusion is speed-competitive
  with flash at flagship shapes — see _flash_engaged's measurements);
- the stock jax Pallas flash kernel for big UNBIASED attention (keeps
  the [B,H,S,S] score tensor out of HBM);
- the custom Pallas kernel (ops/pallas_attention.py) for big BIASED
  attention — it streams the additive mask block-by-block, which the
  stock kernel cannot.
Engagement is controlled by FLAGS_flash_attention (auto/always/never)
and tested off-TPU through interpret mode.  All kernels carry a custom
VJP, so the framework's generic vjp-replay gradient path
(ops/grad_generic.py) differentiates through them unchanged.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..framework.lowering import register_lower


def _plain_attention(q, k, v, bias, sm_scale, causal=False):
    """Reference composition: softmax((q k^T) * scale + bias) v, fp32
    softmax internals, inputs' dtype out."""
    dt = q.dtype
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(mask[None, None], s, jnp.float32(-1e30))
    p = jax.nn.softmax(s, axis=-1).astype(dt)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


_FORCE_INTERPRET = False  # tests: engage the pallas path on CPU


def _flash_mode() -> str:
    from ..framework.flags import flag

    return str(flag("flash_attention"))


def _shape_ok(sq, sk, d):
    # pallas kernels want lane-aligned sequence blocks; head dims
    # 64/128/256 map cleanly onto the MXU
    return sq % 128 == 0 and sk % 128 == 0 and d in (64, 128, 256)


def _flash_engaged(b, h, sq, sk, d):
    """Flag-controlled engagement (FLAGS_flash_attention).

    'auto': measured on v5e, XLA's own attention fusion MATCHES the
    pallas kernel on speed through S=4096 fwd+bwd (0.94-1.02x) and
    beats it at S=128 (235 vs 335 ms/step on BERT-base), so flash's
    value is the MEMORY ceiling, not throughput — the plain path
    materializes the [B,H,Sq,Sk] fp32 score tensor in backward.  Auto
    engages only when that tensor would threaten HBM (>2 GB).
    'always' engages at any aligned shape (A/B testing, memory-bound
    configs the heuristic misses); 'never' forces the plain path."""
    mode = _flash_mode()
    if mode == "never" or not _shape_ok(sq, sk, d):
        return False
    if not (_FORCE_INTERPRET or jax.default_backend() == "tpu"):
        return False
    if mode == "always":
        return True
    return 4 * b * h * sq * sk > (2 << 30)


@register_lower("fused_multihead_attention")
def _fused_mha(ctx, op):
    q = ctx.in1(op, "Q")
    k = ctx.in1(op, "K")
    v = ctx.in1(op, "V")
    bias = ctx.in1(op, "BiasQK")  # additive mask, [B,1,1,S] or [B,H,S,S]
    n_heads = int(op.attr("head_number", op.attr("num_heads", 1)))
    b, s, hidden = q.shape
    d = hidden // n_heads
    sm_scale = float(op.attr("alpha", 0.0)) or 1.0 / math.sqrt(d)

    def heads(x):
        return jnp.transpose(x.reshape(b, s, n_heads, d), (0, 2, 1, 3))

    qh, kh, vh = heads(q), heads(k), heads(v)
    causal = bool(op.attr("causal", False))

    if bool(op.attr("sequence_parallel", False)):
        # EXPLICIT opt-in: the caller asserts the op runs inside an 'sp'
        # shard_map with q/k/v sequence-sharded (shard i holds global
        # positions [i*S_local, (i+1)*S_local)); presence of an sp axis
        # alone is not enough — replicated inputs would make each rank
        # compute a different wrong answer
        from ..distributed.ring_attention import ring_attention

        if "sp" not in getattr(ctx, "axis_env", ()):
            raise ValueError(
                "fused_multihead_attention(sequence_parallel=True) needs "
                "an 'sp' mesh axis in scope (run under a sequence-sharded "
                "shard_map)")
        if bias is not None and not (bias.shape[1] == 1
                                     and bias.shape[2] == 1):
            raise NotImplementedError(
                "fused attention under sequence parallelism takes only a "
                "key mask [B,1,1,S_local] (it rotates around the ring "
                "with its k/v shard); a full [B,H,S,S] bias has no "
                "shardable rotation form")
        out = ring_attention(qh, kh, vh, axis_name="sp", sm_scale=sm_scale,
                             causal=causal, bias=bias)
    elif _flash_engaged(b, n_heads, s, s, d):
        from ..monitor import stat_add

        stat_add("flash_attention_engaged")
        if bias is not None:
            # biased attention: OUR kernel streams the additive mask
            # block-by-block (pallas_attention.py) — the stock kernel
            # only takes a pre-materialized [B,H,S,S] `ab`, which is the
            # HBM blowup flash exists to avoid
            from .pallas_attention import flash_attention_bias

            out = flash_attention_bias(
                qh, kh, vh, bias, sm_scale=sm_scale, causal=causal,
                interpret=jax.default_backend() != "tpu")
        elif jax.default_backend() == "tpu":
            from jax.experimental.pallas.ops.tpu.flash_attention import (
                flash_attention,
            )

            out = flash_attention(qh, kh, vh, sm_scale=sm_scale,
                                  causal=causal)
        else:  # _FORCE_INTERPRET engagement off-TPU (tests)
            from .pallas_attention import flash_attention_bias

            out = flash_attention_bias(qh, kh, vh, None,
                                       sm_scale=sm_scale, causal=causal,
                                       interpret=True)
    else:
        out = _plain_attention(qh, kh, vh, bias, sm_scale, causal=causal)

    out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, s, hidden)
    ctx.set_out(op, "Out", out)
