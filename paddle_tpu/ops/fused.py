"""Fused ops: multi-head attention via the Pallas TPU flash kernel.

Role parity: reference operators/fused/multihead_matmul_op.cu (the
transformer attention fusion used by inference + the fused bert encoder
functors in operators/math/bert_encoder_functor.cu).  TPU-native: the
whole scores->mask->softmax->context chain runs as one Pallas flash
kernel — the [B,H,S,S] probability tensor never touches HBM, which is
the difference between ~39% and ~48% MFU on BERT-base (see BENCH_r03).

The kernel ships its own custom VJP, so the framework's generic
vjp-replay gradient path (ops/grad_generic.py) differentiates through it
for free.  Off-TPU (CPU tests, simulation meshes) the lowering falls
back to the plain jnp composition with identical semantics.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..framework.lowering import register_lower


def _plain_attention(q, k, v, bias, sm_scale, causal=False):
    """Reference composition: softmax((q k^T) * scale + bias) v, fp32
    softmax internals, inputs' dtype out."""
    dt = q.dtype
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sm_scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        s = jnp.where(mask[None, None], s, jnp.float32(-1e30))
    p = jax.nn.softmax(s, axis=-1).astype(dt)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _flash_ok(b, h, sq, sk, d):
    # pallas kernel wants lane-aligned sequence blocks; head dims are
    # padded internally so 64/128/256 all map cleanly onto the MXU.
    # Measured on v5e: XLA's own attention fusion MATCHES the pallas
    # kernel on speed through S=4096 fwd+bwd (0.94-1.02x) and beats it
    # at S=128 (235 vs 335 ms/step on BERT-base), so the kernel's value
    # is the MEMORY ceiling, not throughput: the plain path materializes
    # the [B,H,Sq,Sk] fp32 score tensor in backward.  Engage flash only
    # when that tensor would be big enough to threaten HBM (>2 GB).
    if not (sq % 128 == 0 and sk % 128 == 0 and d in (64, 128, 256)):
        return False
    scores_bytes = 4 * b * h * sq * sk
    return scores_bytes > (2 << 30)


@register_lower("fused_multihead_attention")
def _fused_mha(ctx, op):
    q = ctx.in1(op, "Q")
    k = ctx.in1(op, "K")
    v = ctx.in1(op, "V")
    bias = ctx.in1(op, "BiasQK")  # additive mask, [B,1,1,S] or [B,H,S,S]
    n_heads = int(op.attr("head_number", op.attr("num_heads", 1)))
    b, s, hidden = q.shape
    d = hidden // n_heads
    sm_scale = float(op.attr("alpha", 0.0)) or 1.0 / math.sqrt(d)

    def heads(x):
        return jnp.transpose(x.reshape(b, s, n_heads, d), (0, 2, 1, 3))

    qh, kh, vh = heads(q), heads(k), heads(v)
    causal = bool(op.attr("causal", False))

    if bool(op.attr("sequence_parallel", False)):
        # EXPLICIT opt-in: the caller asserts the op runs inside an 'sp'
        # shard_map with q/k/v sequence-sharded (shard i holds global
        # positions [i*S_local, (i+1)*S_local)); presence of an sp axis
        # alone is not enough — replicated inputs would make each rank
        # compute a different wrong answer
        from ..distributed.ring_attention import ring_attention

        if "sp" not in getattr(ctx, "axis_env", ()):
            raise ValueError(
                "fused_multihead_attention(sequence_parallel=True) needs "
                "an 'sp' mesh axis in scope (run under a sequence-sharded "
                "shard_map)")
        if bias is not None:
            raise NotImplementedError(
                "fused attention under sequence parallelism does not take "
                "an additive bias yet (pack sequences; causal via attr)")
        out = ring_attention(qh, kh, vh, axis_name="sp", sm_scale=sm_scale,
                             causal=causal)
    elif jax.default_backend() == "tpu" and _flash_ok(b, n_heads, s, s, d):
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention,
        )

        ab = None
        if bias is not None:
            # pallas applies sm_scale AFTER adding ab (s += ab; s *=
            # sm_scale in flash_attention.py), while our semantics are
            # softmax(sm_scale*qk + bias): pre-divide the bias so both
            # paths agree.  The broadcast does materialize [B,H,S,S] in
            # HBM — acceptable for additive relative-position biases,
            # wasteful for pure key-padding masks (TODO: lower 0/-inf
            # key masks to the kernel's segment_ids instead).
            ab = jnp.broadcast_to(
                (bias.astype(jnp.float32) / sm_scale).astype(qh.dtype),
                (b, n_heads, s, s))
        out = flash_attention(qh, kh, vh, ab=ab, sm_scale=sm_scale,
                              causal=causal)
    else:
        out = _plain_attention(qh, kh, vh, bias, sm_scale, causal=causal)

    out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, s, hidden)
    ctx.set_out(op, "Out", out)
