"""Loss ops beyond the softmax/cross-entropy family.

Reference parity: operators/{bce_loss,nll_loss,kldiv_loss,log_loss,
hinge_loss,rank_loss,margin_rank_loss,smooth_l1_loss,sigmoid_focal_loss,
bpr_loss,warpctc,...}_op.cc — each a few jnp lines on TPU; gradients come
from the generic vjp fallback.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.lowering import register_lower


@register_lower("bce_loss")
def _bce_loss(ctx, op):
    x = ctx.in1(op, "X")  # probabilities
    label = ctx.in1(op, "Label")
    eps = 1e-12
    xc = jnp.clip(x, eps, 1.0 - eps)
    out = -(label * jnp.log(xc) + (1.0 - label) * jnp.log1p(-xc))
    ctx.set_out(op, "Out", out)


@register_lower("nll_loss")
def _nll_loss(ctx, op):
    x = ctx.in1(op, "X")  # log-probabilities [N, C, ...]
    label = ctx.in1(op, "Label")
    weight = ctx.in1(op, "Weight")
    ignore_index = int(op.attr("ignore_index", -100))
    reduction = op.attr("reduction", "mean")
    safe = jnp.clip(label, 0, x.shape[1] - 1)
    picked = jnp.take_along_axis(x, safe[:, None], axis=1)[:, 0]
    w = weight[safe] if weight is not None else jnp.ones_like(picked)
    w = jnp.where(label == ignore_index, jnp.zeros_like(w), w)
    loss = -picked * w
    total_w = jnp.sum(w)
    if reduction == "mean":
        out = jnp.sum(loss) / jnp.maximum(total_w, 1e-12)
    elif reduction == "sum":
        out = jnp.sum(loss)
    else:
        out = loss
    ctx.set_out(op, "Out", out)
    ctx.set_out(op, "Total_weight", total_w)


@register_lower("kldiv_loss")
def _kldiv_loss(ctx, op):
    x = ctx.in1(op, "X")  # log-probabilities
    target = ctx.in1(op, "Target")
    reduction = op.attr("reduction", "mean")
    loss = jnp.where(target > 0, target * (jnp.log(jnp.maximum(target, 1e-12)) - x),
                     jnp.zeros_like(target))
    if reduction == "mean":
        out = jnp.mean(loss)
    elif reduction == "sum":
        out = jnp.sum(loss)
    elif reduction == "batchmean":
        out = jnp.sum(loss) / x.shape[0]
    else:
        out = loss
    ctx.set_out(op, "Loss", out)


@register_lower("log_loss")
def _log_loss(ctx, op):
    p = ctx.in1(op, "Predicted")
    label = ctx.in1(op, "Labels")
    eps = float(op.attr("epsilon", 1e-4))
    out = -label * jnp.log(p + eps) - (1.0 - label) * jnp.log(1.0 - p + eps)
    ctx.set_out(op, "Loss", out)


@register_lower("hinge_loss")
def _hinge_loss(ctx, op):
    logits = ctx.in1(op, "Logits")
    labels = ctx.in1(op, "Labels")
    out = jnp.maximum(1.0 - (2.0 * labels - 1.0) * logits, 0.0)
    ctx.set_out(op, "Loss", out)


@register_lower("rank_loss")
def _rank_loss(ctx, op):
    label = ctx.in1(op, "Label")
    left = ctx.in1(op, "Left")
    right = ctx.in1(op, "Right")
    d = left - right
    out = jnp.logaddexp(0.0, d) - label * d
    ctx.set_out(op, "Out", out)


@register_lower("margin_rank_loss")
def _margin_rank_loss(ctx, op):
    label = ctx.in1(op, "Label")
    x1 = ctx.in1(op, "X1")
    x2 = ctx.in1(op, "X2")
    margin = float(op.attr("margin", 0.0))
    out = jnp.maximum(-label * (x1 - x2) + margin, 0.0)
    ctx.set_out(op, "Out", out)
    ctx.set_out(op, "Activated", (out > 0).astype(x1.dtype))


@register_lower("smooth_l1_loss")
def _smooth_l1_loss(ctx, op):
    x = ctx.in1(op, "X")
    y = ctx.in1(op, "Y")
    in_w = ctx.in1(op, "InsideWeight")
    out_w = ctx.in1(op, "OutsideWeight")
    sigma = float(op.attr("sigma", 1.0))
    s2 = sigma * sigma
    d = x - y
    if in_w is not None:
        d = d * in_w
    ad = jnp.abs(d)
    loss = jnp.where(ad < 1.0 / s2, 0.5 * d * d * s2, ad - 0.5 / s2)
    if out_w is not None:
        loss = loss * out_w
    ctx.set_out(op, "Diff", d)
    # reference smooth_l1_loss_op always emits Out of shape [N, 1]
    out = (jnp.sum(loss, axis=tuple(range(1, loss.ndim))).reshape(-1, 1)
           if loss.ndim > 1 else loss)
    ctx.set_out(op, "Out", out)


@register_lower("sigmoid_focal_loss")
def _sigmoid_focal_loss(ctx, op):
    x = ctx.in1(op, "X")  # [N, C] logits
    label = ctx.in1(op, "Label")  # [N, 1] int; 0 = background
    fg_num = ctx.in1(op, "FgNum")
    gamma = float(op.attr("gamma", 2.0))
    alpha = float(op.attr("alpha", 0.25))
    n, c = x.shape
    # target[i, j] = 1 if label[i] == j+1 (classes are 1-based; 0 = bg)
    tgt = (label.reshape(-1, 1) == jnp.arange(1, c + 1)[None, :]).astype(x.dtype)
    p = jax.nn.sigmoid(x)
    ce = jnp.maximum(x, 0) - x * tgt + jnp.log1p(jnp.exp(-jnp.abs(x)))
    p_t = p * tgt + (1.0 - p) * (1.0 - tgt)
    a_t = alpha * tgt + (1.0 - alpha) * (1.0 - tgt)
    fg = jnp.maximum(fg_num.astype(x.dtype).reshape(()), 1.0)
    out = a_t * jnp.power(1.0 - p_t, gamma) * ce / fg
    ctx.set_out(op, "Out", out)


@register_lower("bpr_loss")
def _bpr_loss(ctx, op):
    x = ctx.in1(op, "X")  # [N, C]
    label = ctx.in1(op, "Label")  # [N, 1]
    n, c = x.shape
    pos = jnp.take_along_axis(x, label.reshape(-1, 1), axis=1)
    diff = pos - x  # [N, C]
    lse = jnp.logaddexp(0.0, -diff)  # stable: log(1+exp(-diff))
    mask = jnp.ones((n, c), x.dtype).at[
        jnp.arange(n), label.reshape(-1)].set(0.0)
    out = jnp.sum(lse * mask, axis=1, keepdims=True) / (c - 1)
    ctx.set_out(op, "Y", out)


@register_lower("l1_norm")
def _l1_norm(ctx, op):
    x = ctx.in1(op, "X")
    ctx.set_out(op, "Out", jnp.sum(jnp.abs(x)))


@register_lower("cos_sim")
def _cos_sim(ctx, op):
    x = ctx.in1(op, "X")
    y = ctx.in1(op, "Y")
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=-1, keepdims=True))
    out = jnp.sum(x * y, axis=-1, keepdims=True) / jnp.maximum(xn * yn, 1e-12)
    ctx.set_out(op, "Out", out)
    ctx.set_out(op, "XNorm", xn)
    ctx.set_out(op, "YNorm", yn)


@register_lower("warpctc")
def _warpctc(ctx, op):
    """CTC loss (reference warpctc_op.cc wrapping the warp-ctc lib).
    TPU-native: optax.ctc_loss on dense [B, T, C] logits with
    length tensors (the v2 padded interface)."""
    import optax

    logits = ctx.in1(op, "Logits")
    label = ctx.in1(op, "Label")
    logits_len = ctx.in1(op, "LogitsLength")
    label_len = ctx.in1(op, "LabelLength")
    blank = int(op.attr("blank", 0))
    norm_by_times = bool(op.attr("norm_by_times", False))
    if logits_len is None or label_len is None:
        raise NotImplementedError(
            "warpctc requires LogitsLength/LabelLength (padded dense "
            "interface); LoD-style inputs are not supported on TPU")
    # optax wants [B, T, C]; paddle's padded interface is [T, B, C]
    lp = jax.nn.log_softmax(jnp.transpose(logits, (1, 0, 2)), axis=-1)
    t = lp.shape[1]
    logit_pad = (jnp.arange(t)[None, :] >= logits_len.reshape(-1, 1)
                 ).astype(lp.dtype)
    lm = label.shape[1]
    label_pad = (jnp.arange(lm)[None, :] >= label_len.reshape(-1, 1)
                 ).astype(lp.dtype)
    loss = optax.ctc_loss(lp, logit_pad, label.astype(jnp.int32), label_pad,
                          blank_id=blank)
    if norm_by_times:
        loss = loss / jnp.maximum(logits_len.astype(loss.dtype), 1.0)
    ctx.set_out(op, "Loss", loss.reshape(-1, 1))
    ctx.set_out(op, "WarpCTCGrad", jnp.zeros_like(logits))
