"""Pallas TPU paged decode-attention kernel (one query token per slot).

Role parity: the decode-phase half of the fused attention story
(`ops/pallas_attention.py` covers training/prefill flash attention).
Autoregressive serving holds each slot's K/V history in fixed-size
pages (`serving/kv_cache.py`); at decode each slot contributes exactly
ONE query token that must attend over its own live history:

    q          : [S, H, D]            one token per slot
    k/v_pages  : [P, page, H, D]      the shared page pool (one layer)
    page_table : [S, pps]  int32      slot -> ordered page ids
    lengths    : [S]       int32      live positions per slot

The Pallas kernel iterates grid (slot, page) with the page table and
lengths as SCALAR-PREFETCH operands: the page id is known before the
body runs, so each (slot, page) step DMAs exactly one page of K and V
from the pool — HBM traffic is O(sum(live pages)), never
O(S * max_seq).  Pages at or past the slot's length are skipped
entirely (`pl.when`), and the partial page at the tail is masked by
position.  Online softmax (running max / denominator in VMEM scratch)
accumulates across pages exactly like the prefill flash kernel.

``decode_attention_reference`` is the pure-jnp oracle — gather the
page table (O(S * max_seq) materialization) and do masked attention.
It is also the CPU-backend default so tier-1 stays green without
Mosaic; ``interpret=True`` runs the real kernel on CPU for tests.

``paged_chunk_attention`` generalizes the kernel to R query rows per
slot with per-row causal lengths over one shared page table — the
attention shape of chunked/suffix prefill and speculative verification
(serving/decode.py), where shared and partially-filled pages need no
special casing beyond the mask.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30
_LANES = 128  # TPU vector lane width; row stats broadcast across lanes


def decode_attention_reference(q, k, v, lengths, *, sm_scale=None):
    """Masked single-token attention over full-width K/V.

    q: [S, H, D]; k/v: [S, T, H, D] (slot-major, any width T >= max
    length); lengths: [S] — position t of slot s participates iff
    t < lengths[s].  This exact formulation (mask -> -1e30, softmax
    over the full width) is shared by the decode fallback AND the
    prefill path in serving/decode.py, which is what makes
    decode-with-cache logits bitwise-comparable to a full recompute.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    s = jnp.einsum("shd,sthd->sht", qf, kf) * sm_scale      # [S, H, T]
    t = jnp.arange(k.shape[1], dtype=jnp.int32)
    mask = t[None, None, :] < lengths[:, None, None]
    s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("sht,sthd->shd", p, vf)
    return out.astype(q.dtype)


def _decode_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, *rest,
                   sm_scale, page, n_pages, quantized=False):
    import jax.experimental.pallas as pl

    if quantized:
        # int8 pages ride with their per-page scale planes; the
        # dequant happens HERE, on the tile already in VMEM — the f32
        # K/V never exists in HBM (the dequant-fused contract)
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
        ks_ref = vs_ref = None

    s_idx = pl.program_id(0)
    p_idx = pl.program_id(1)

    @pl.when(p_idx == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = len_ref[s_idx]
    # pages wholly past the live length contribute nothing — skip the
    # compute (the DMA still landed, clamped to a valid pool index)
    @pl.when(p_idx * page < length)
    def _compute():
        q = q_ref[0].astype(jnp.float32)              # (H, D)
        k = k_ref[0].astype(jnp.float32)              # (page, H, D)
        v = v_ref[0].astype(jnp.float32)
        if ks_ref is not None:
            k = k * ks_ref[0].astype(jnp.float32)[..., None]
            v = v * vs_ref[0].astype(jnp.float32)[..., None]
        # scores per head over this page's positions: (H, page)
        s = lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (1,))),
            preferred_element_type=jnp.float32) * sm_scale
        pos = p_idx * page + lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(pos < length, s, _NEG_INF)

        m_prev = m_scr[:, :1]                          # (H, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                         # (H, page)
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + lax.dot_general(
            p, v, (((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(p_idx == n_pages - 1)
    def _flush():
        l = l_scr[:, :1]
        o_ref[0] = (acc_scr[...] / jnp.where(l == 0.0, 1.0, l)).astype(
            o_ref.dtype)


def _paged_call(q, k_pages, v_pages, page_table, lengths, sm_scale,
                interpret, k_scales=None, v_scales=None):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_slots, h, d = q.shape
    pps = page_table.shape[1]
    page = k_pages.shape[1]
    flat_table = page_table.reshape(-1).astype(jnp.int32)
    quantized = k_scales is not None

    in_specs = [
        pl.BlockSpec((1, h, d), lambda s, p, pt, ln: (s, 0, 0)),
        # THE paged-attention move: the K/V block index is read out
        # of the prefetched page table, so each grid step DMAs one
        # pool page — no gather materialization
        pl.BlockSpec((1, page, h, d),
                     lambda s, p, pt, ln: (pt[s * pps + p], 0, 0, 0)),
        pl.BlockSpec((1, page, h, d),
                     lambda s, p, pt, ln: (pt[s * pps + p], 0, 0, 0)),
    ]
    operands = [q, k_pages, v_pages]
    if quantized:
        # the scale planes ride the same page-id indexing as the pages
        in_specs += [
            pl.BlockSpec((1, page, h),
                         lambda s, p, pt, ln: (pt[s * pps + p], 0, 0)),
            pl.BlockSpec((1, page, h),
                         lambda s, p, pt, ln: (pt[s * pps + p], 0, 0)),
        ]
        operands += [k_scales, v_scales]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # (flat page table, lengths)
        grid=(n_slots, pps),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, d), lambda s, p, pt, ln: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, _LANES), jnp.float32),   # running max
            pltpu.VMEM((h, _LANES), jnp.float32),   # running denom
            pltpu.VMEM((h, d), jnp.float32),        # output accumulator
        ],
    )
    kern = functools.partial(_decode_kernel, sm_scale=sm_scale,
                             page=page, n_pages=pps,
                             quantized=quantized)
    return pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_slots, h, d), q.dtype),
        interpret=interpret,
    )(flat_table, lengths.astype(jnp.int32), *operands)


def _gather_dequant(pages, scales, page_table):
    """Reference-path page gather: [S, pps*page, H, D] at full width,
    dequantized inline when a scale pool rides along."""
    s, pps = page_table.shape
    page = pages.shape[1]
    g = pages[page_table]                    # [S, pps, page, H, D]
    if scales is not None:
        g = g.astype(jnp.float32) \
            * scales[page_table].astype(jnp.float32)[..., None]
    return g.reshape(s, pps * page, *pages.shape[2:])


def paged_decode_attention(q, k_pages, v_pages, page_table, lengths, *,
                           sm_scale=None, use_pallas="auto",
                           interpret=False, k_scales=None,
                           v_scales=None):
    """Decode attention straight off the page pool.

    q [S,H,D]; k/v_pages [P,page,H,D] (ONE layer's pool); page_table
    [S,pps] i32; lengths [S] i32.  ``use_pallas``: 'auto' engages the
    Pallas kernel on the TPU backend only (CPU gets the gather+mask
    reference, keeping tier-1 Mosaic-free), 'always' forces it
    (combine with interpret=True off-TPU), 'never' forces the
    reference.  ``k_scales``/``v_scales`` [P,page,H] arm the quantized
    path (FLAGS_decode_kv_quant): pages are int8 and BOTH paths
    dequantize them inline — the Pallas kernel per tile in VMEM, the
    reference during the gather — before the one shared masked-softmax
    formulation.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if use_pallas == "auto":
        use_pallas = "always" if jax.default_backend() == "tpu" \
            else "never"
    if use_pallas == "always":
        return _paged_call(q, k_pages, v_pages, page_table, lengths,
                           float(sm_scale), interpret,
                           k_scales=k_scales, v_scales=v_scales)
    # reference: gather the page table to full width, then mask
    k = _gather_dequant(k_pages, k_scales, page_table)
    v = _gather_dequant(v_pages, v_scales, page_table)
    return decode_attention_reference(q, k, v, lengths,
                                      sm_scale=sm_scale)


# -- multi-row variant: chunked prefill + speculative verify --------------


def _chunk_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, *rest,
                  sm_scale, page, n_pages, n_rows, quantized=False):
    """The decode kernel generalized to R query rows per slot (a
    prefill chunk or a speculative t0+draft window).  Row r of slot s
    attends positions ``t < len_ref[s*R + r]`` — per-row causal masks
    over one shared page table, so shared and partially-filled pages
    need no special casing beyond the mask."""
    import jax.experimental.pallas as pl

    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
        ks_ref = vs_ref = None

    s_idx = pl.program_id(0)
    p_idx = pl.program_id(1)

    @pl.when(p_idx == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # the widest row bounds whether this page matters at all — taken
    # over ALL rows, so the contract holds for arbitrary (not just
    # ascending) per-row lengths
    row_len = jnp.stack(
        [len_ref[s_idx * n_rows + r] for r in range(n_rows)])
    max_len = jnp.max(row_len)

    @pl.when(p_idx * page < max_len)
    def _compute():
        q = q_ref[0].astype(jnp.float32)              # (R, H, D)
        k = k_ref[0].astype(jnp.float32)              # (page, H, D)
        v = v_ref[0].astype(jnp.float32)
        if ks_ref is not None:  # dequant-fused: int8 tile * VMEM scale
            k = k * ks_ref[0].astype(jnp.float32)[..., None]
            v = v * vs_ref[0].astype(jnp.float32)[..., None]
        # scores per head per row over this page: (H, R, page)
        s = lax.dot_general(
            q, k, (((2,), (2,)), ((1,), (1,))),
            preferred_element_type=jnp.float32) * sm_scale
        pos = p_idx * page + lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(pos < row_len[None, :, None], s, _NEG_INF)

        m_prev = m_scr[:, :, :1]                       # (H, R, 1)
        m_cur = jnp.max(s, axis=2, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                         # (H, R, page)
        l_new = alpha * l_scr[:, :, :1] \
            + jnp.sum(p, axis=2, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + lax.dot_general(
            p, v, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)        # (H, R, D)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(p_idx == n_pages - 1)
    def _flush():
        l = l_scr[:, :, :1]
        out = acc_scr[...] / jnp.where(l == 0.0, 1.0, l)  # (H, R, D)
        o_ref[0] = out.transpose(1, 0, 2).astype(o_ref.dtype)


def _chunk_call(q, k_pages, v_pages, page_table, row_lengths, sm_scale,
                interpret, k_scales=None, v_scales=None):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_slots, n_rows, h, d = q.shape
    pps = page_table.shape[1]
    page = k_pages.shape[1]
    flat_table = page_table.reshape(-1).astype(jnp.int32)
    flat_lengths = row_lengths.reshape(-1).astype(jnp.int32)
    quantized = k_scales is not None

    in_specs = [
        pl.BlockSpec((1, n_rows, h, d),
                     lambda s, p, pt, ln: (s, 0, 0, 0)),
        pl.BlockSpec((1, page, h, d),
                     lambda s, p, pt, ln: (pt[s * pps + p], 0, 0, 0)),
        pl.BlockSpec((1, page, h, d),
                     lambda s, p, pt, ln: (pt[s * pps + p], 0, 0, 0)),
    ]
    operands = [q, k_pages, v_pages]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, page, h),
                         lambda s, p, pt, ln: (pt[s * pps + p], 0, 0)),
            pl.BlockSpec((1, page, h),
                         lambda s, p, pt, ln: (pt[s * pps + p], 0, 0)),
        ]
        operands += [k_scales, v_scales]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # (flat page table, flat row lengths)
        grid=(n_slots, pps),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, n_rows, h, d),
                               lambda s, p, pt, ln: (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, n_rows, _LANES), jnp.float32),  # running max
            pltpu.VMEM((h, n_rows, _LANES), jnp.float32),  # denominator
            pltpu.VMEM((h, n_rows, d), jnp.float32),       # accumulator
        ],
    )
    kern = functools.partial(_chunk_kernel, sm_scale=sm_scale,
                             page=page, n_pages=pps, n_rows=n_rows,
                             quantized=quantized)
    return pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_slots, n_rows, h, d), q.dtype),
        interpret=interpret,
    )(flat_table, flat_lengths, *operands)


def paged_chunk_attention(q, k_pages, v_pages, page_table, row_lengths,
                          *, sm_scale=None, use_pallas="auto",
                          interpret=False, k_scales=None,
                          v_scales=None):
    """Multi-row attention off the page pool — R query rows per slot.

    q [S,R,H,D]; k/v_pages [P,page,H,D] (ONE layer's pool); page_table
    [S,pps] i32; row_lengths [S,R] i32 — row r of slot s attends
    positions ``t < row_lengths[s, r]``.  Serves both tentpole callers
    in serving/decode.py: chunked prefill (R = chunk rows, one slot at
    a time) and speculative-decode verification (R = 1 + draft window,
    every slot jointly).  The reference path broadcasts each slot's
    gathered K/V across its rows and reuses
    ``decode_attention_reference`` VERBATIM — the single masked-softmax
    formulation at one width that keeps every cache path bitwise-equal
    to the full-recompute oracle.  ``use_pallas`` dispatch and the
    quantized ``k_scales``/``v_scales`` contract match
    ``paged_decode_attention``.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if use_pallas == "auto":
        use_pallas = "always" if jax.default_backend() == "tpu" \
            else "never"
    if use_pallas == "always":
        return _chunk_call(q, k_pages, v_pages, page_table, row_lengths,
                           float(sm_scale), interpret,
                           k_scales=k_scales, v_scales=v_scales)
    s, r = q.shape[:2]
    k = _gather_dequant(k_pages, k_scales, page_table)
    v = _gather_dequant(v_pages, v_scales, page_table)
    kr = jnp.broadcast_to(k[:, None], (s, r) + k.shape[1:]) \
        .reshape(s * r, *k.shape[1:])
    vr = jnp.broadcast_to(v[:, None], (s, r) + v.shape[1:]) \
        .reshape(s * r, *v.shape[1:])
    out = decode_attention_reference(
        q.reshape((s * r,) + q.shape[2:]), kr, vr,
        row_lengths.reshape(-1), sm_scale=sm_scale)
    return out.reshape(q.shape)
