"""Misc op batch: dense LoD shims, conv-transpose variants, TensorArray
ops, affine_grid, unpool, host-callback py_func, and friends.

Reference parity noted per op.  Gradients via generic vjp fallback.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.lowering import LOWERINGS, register_lower


@register_lower("lod_reset")
def _lod_reset(ctx, op):
    # dense tensors carry no LoD: pass-through (reference lod_reset_op
    # only rewrites metadata)
    ctx.set_out(op, "Out", ctx.in1(op, "X"))


@register_lower("get_tensor_from_selected_rows", "merge_selected_rows")
def _selected_rows_passthrough(ctx, op):
    # SelectedRows lower to dense on TPU (SURVEY §7): both ops are identity
    ctx.set_out(op, "Out", ctx.in1(op, "X"))


@register_lower("depthwise_conv2d_transpose")
def _depthwise_conv2d_transpose(ctx, op):
    LOWERINGS["conv2d_transpose"](ctx, op)


@register_lower("conv3d_transpose")
def _conv3d_transpose(ctx, op):
    x = ctx.in1(op, "Input")  # NCDHW
    w = ctx.in1(op, "Filter")  # [in, out, kd, kh, kw]
    strides = [int(s) for s in op.attr("strides", [1, 1, 1])]
    dilations = [int(d) for d in op.attr("dilations", [1, 1, 1])]
    paddings = [int(p) for p in op.attr("paddings", [0, 0, 0])]
    ksize = w.shape[2:]
    pads = [((k - 1) * d - p, (k - 1) * d - p)
            for k, d, p in zip(ksize, dilations, paddings)]
    out = jax.lax.conv_transpose(
        x, w, strides=strides, padding=pads, rhs_dilation=dilations,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"), transpose_kernel=True)
    ctx.set_out(op, "Output", out)


@register_lower("conv_shift")
def _conv_shift(ctx, op):
    """Circular correlation (reference conv_shift_op): X [B, D], Y [B, K]."""
    x = ctx.in1(op, "X")
    y = ctx.in1(op, "Y")
    b, d = x.shape
    k = y.shape[1]
    half = k // 2
    idx = (jnp.arange(d)[:, None] + jnp.arange(-half, k - half)[None, :]) % d
    ctx.set_out(op, "Out", jnp.einsum("bdk,bk->bd", x[:, idx], y))


@register_lower("fsp")
def _fsp(ctx, op):
    """FSP matrix for distillation (reference fsp_op): mean over H*W of
    outer products between channel maps."""
    x = ctx.in1(op, "X")  # [N, Cx, H, W]
    y = ctx.in1(op, "Y")  # [N, Cy, H, W]
    hw = x.shape[2] * x.shape[3]
    out = jnp.einsum("nchw,ndhw->ncd", x, y) / hw
    ctx.set_out(op, "Out", out)


@register_lower("data_norm")
def _data_norm(ctx, op):
    """Global data normalization (reference data_norm_op): running
    size/sum/squared-sum stats produce mean/scale."""
    x = ctx.in1(op, "X")
    bsize = ctx.in1(op, "BatchSize")
    bsum = ctx.in1(op, "BatchSum")
    bsq = ctx.in1(op, "BatchSquareSum")
    eps = float(op.attr("epsilon", 1e-4))
    mean = bsum / bsize
    scale = jnp.sqrt(bsize / (bsq - bsum * mean + eps))
    y = (x - mean) * scale
    ctx.set_out(op, "Y", y)
    ctx.set_out(op, "Means", jnp.broadcast_to(mean, x.shape))
    ctx.set_out(op, "Scales", jnp.broadcast_to(scale, x.shape))


@register_lower("affine_grid")
def _affine_grid(ctx, op):
    """theta [N, 2, 3] -> sampling grid [N, H, W, 2] (reference
    affine_grid_op, align_corners=True semantics)."""
    theta = ctx.in1(op, "Theta")
    shape = op.attr("output_shape", [])
    osize = ctx.in1(op, "OutputShape")
    if osize is not None:
        shape = [int(v) for v in np.asarray(osize)]
    n, _c, h, w = (int(s) for s in shape)
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    if not bool(op.attr("align_corners", True)):
        # pixel-center convention: shrink extremes by (size-1)/size
        ys = ys * (h - 1) / h
        xs = xs * (w - 1) / w
    gx, gy = jnp.meshgrid(xs, ys)  # [H, W]
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)  # [H, W, 3]
    out = jnp.einsum("hwk,njk->nhwj", base, theta)
    ctx.set_out(op, "Output", out)


@register_lower("unpool")
def _unpool(ctx, op):
    """Max unpooling by stored flat indices (reference unpool_op)."""
    x = ctx.in1(op, "X")  # [N, C, H, W]
    idx = ctx.in1(op, "Indices")  # flat h*w indices into the output map
    ksize = [int(k) for k in op.attr("ksize", [2, 2])]
    strides = [int(s) for s in op.attr("strides", [2, 2])]
    paddings = [int(p) for p in op.attr("paddings", [0, 0])]
    n, c, h, w = x.shape
    oh = (h - 1) * strides[0] - 2 * paddings[0] + ksize[0]
    ow = (w - 1) * strides[1] - 2 * paddings[1] + ksize[1]
    flat = jnp.zeros((n, c, oh * ow), x.dtype)
    out = flat.at[
        jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None],
        idx.reshape(n, c, -1)].add(x.reshape(n, c, -1))
    ctx.set_out(op, "Out", out.reshape(n, c, oh, ow))


@register_lower("center_loss")
def _center_loss(ctx, op):
    x = ctx.in1(op, "X")  # [N, D] features
    label = ctx.in1(op, "Label")
    centers = ctx.in1(op, "Centers")  # [C, D]
    update_rate = ctx.in1(op, "CenterUpdateRate")
    need_update = bool(op.attr("need_update", True))
    lbl = label.reshape(-1)
    picked = centers[lbl]
    diff = x - picked
    loss = 0.5 * jnp.sum(jnp.square(diff), axis=1, keepdims=True)
    ctx.set_out(op, "Loss", loss)
    ctx.set_out(op, "SampleCenterDiff", diff)
    if need_update:
        cnt = jnp.zeros((centers.shape[0],), x.dtype).at[lbl].add(1.0)
        upd = jnp.zeros_like(centers).at[lbl].add(diff)
        alpha = update_rate.reshape(()) if update_rate is not None else 0.5
        new_centers = centers + alpha * upd / (cnt[:, None] + 1.0)
        ctx.set_out(op, "CentersOut", new_centers)
    else:
        ctx.set_out(op, "CentersOut", centers)


@register_lower("shuffle_batch")
def _shuffle_batch(ctx, op):
    x = ctx.in1(op, "X")
    perm = jax.random.permutation(ctx.next_key(), x.shape[0])
    ctx.set_out(op, "Out", x[perm])
    ctx.set_out(op, "ShuffleIdx", perm.astype(jnp.int32))


@register_lower("batch_fc")
def _batch_fc(ctx, op):
    x = ctx.in1(op, "Input")  # [B, N, D]
    w = ctx.in1(op, "W")  # [B, D, O]
    bias = ctx.in1(op, "Bias")  # [B, 1, O]
    out = jnp.einsum("bnd,bdo->bno", x, w)
    if bias is not None:
        out = out + bias
    ctx.set_out(op, "Out", out)


@register_lower("select_input")
def _select_input(ctx, op):
    xs = ctx.in_list(op, "X")
    mask = ctx.in1(op, "Mask").reshape(()).astype(jnp.int32)
    out = xs[0]
    for i, x in enumerate(xs[1:], start=1):
        out = jnp.where(mask == i, x, out)
    ctx.set_out(op, "Out", out)


@register_lower("select_output")
def _select_output(ctx, op):
    x = ctx.in1(op, "X")
    mask = ctx.in1(op, "Mask").reshape(()).astype(jnp.int32)
    for i, name in enumerate(op.outputs.get("Out", [])):
        # each branch output gets x where selected, zeros otherwise (the
        # consuming conditional_block reads only the live branch)
        ctx.set(name, jnp.where(mask == i, x, jnp.zeros_like(x)))


# --- TensorArray ops: the env holds a python list at trace time -------
# (reference lod_tensor_array; usable with statically-unrolled loops —
# lax.while_loop bodies need fixed-shape carries instead)


@register_lower("write_to_array")
def _write_to_array(ctx, op):
    x = ctx.in1(op, "X")
    i = int(np.asarray(ctx.in1(op, "I")).ravel()[0])
    name = op.outputs["Out"][0]
    arr = list(ctx.env.get(name, []))
    while len(arr) <= i:
        arr.append(None)
    arr[i] = x
    ctx.set(name, arr)


@register_lower("read_from_array")
def _read_from_array(ctx, op):
    arr = ctx.get(op.inputs["X"][0])
    i = int(np.asarray(ctx.in1(op, "I")).ravel()[0])
    ctx.set_out(op, "Out", arr[i])


@register_lower("lod_array_length")
def _lod_array_length(ctx, op):
    arr = ctx.get(op.inputs["X"][0])
    ctx.set_out(op, "Out", jnp.asarray([len(arr)], jnp.int64))


@register_lower("array_to_lod_tensor")
def _array_to_lod_tensor(ctx, op):
    arr = ctx.get(op.inputs["X"][0])
    ctx.set_out(op, "Out", jnp.concatenate([jnp.atleast_1d(a) for a in arr],
                                           axis=0))


@register_lower("lod_tensor_to_array")
def _lod_tensor_to_array(ctx, op):
    x = ctx.in1(op, "X")
    ctx.set_out(op, "Out", [x[i] for i in range(x.shape[0])])


@register_lower("py_func")
def _py_func(ctx, op):
    """Host-side python function embedded in the program (reference
    py_func_op).  TPU-native: jax.pure_callback — the callable runs on
    host per executable call; registered via misc_ops.register_py_func."""
    fid = int(op.attr("forward_callable_id", op.attr("func_id", -1)))
    fn = _PY_FUNCS.get(fid)
    if fn is None:
        raise NotImplementedError(
            f"py_func id {fid} is not registered in this process; call "
            f"paddle_tpu.ops.misc_ops.register_py_func")
    xs = ctx.in_list(op, "X")
    out_names = op.outputs.get("Out", [])
    # shapes/dtypes must be declared on the output vars
    specs = []
    for n in out_names:
        var = ctx.block._find_var_recursive(n)
        from ..framework import dtypes as _dt

        shape = [int(s) for s in var.shape]
        for i, s in enumerate(shape):
            if s < 0:
                # dynamic dim: resolve from the first input (batch dim)
                if not xs or i >= xs[0].ndim:
                    raise ValueError(
                        f"py_func output {n!r} has dynamic dim {i} that "
                        f"cannot be resolved from the inputs; declare a "
                        f"static shape on the output var")
                shape[i] = int(xs[0].shape[i])
        specs.append(jax.ShapeDtypeStruct(tuple(shape), _dt.to_np(var.dtype)))
    outs = jax.pure_callback(lambda *a: fn(*a), tuple(specs), *xs)
    for n, v in zip(out_names, outs):
        ctx.set(n, v)


_PY_FUNCS = {}


def register_py_func(fid, fn):
    _PY_FUNCS[fid] = fn


@register_lower("diag", "diag_v2")
def _diag(ctx, op):
    x = ctx.in1(op, "X")
    offset = int(op.attr("offset", 0))
    pad = float(op.attr("padding_value", 0.0))
    if x.ndim == 1:
        out = jnp.diag(x, k=offset)
        if pad:
            mask = jnp.diag(jnp.ones_like(x), k=offset)
            out = out + pad * (1 - mask)
    else:
        out = jnp.diagonal(x, offset=offset)
    ctx.set_out(op, "Out", out)


@register_lower("allclose")
def _allclose(ctx, op):
    x = ctx.in1(op, "Input")
    y = ctx.in1(op, "Other")
    rtol = float(op.attr("rtol", 1e-5) or 1e-5)
    atol = float(op.attr("atol", 1e-8) or 1e-8)
    ctx.set_out(op, "Out", jnp.allclose(
        x, y, rtol=rtol, atol=atol,
        equal_nan=bool(op.attr("equal_nan", False))))


@register_lower("histogram")
def _histogram(ctx, op):
    x = ctx.in1(op, "X")
    bins = int(op.attr("bins", 100))
    lo = float(op.attr("min", 0))
    hi = float(op.attr("max", 0))
    if lo == 0 and hi == 0:
        # reference uses data min/max; needs static range on TPU
        raise NotImplementedError(
            "histogram needs explicit min/max attrs on TPU (data-dependent "
            "range is not XLA-static)")
    h, _ = jnp.histogram(x.reshape(-1), bins=bins, range=(lo, hi))
    ctx.set_out(op, "Out", h.astype(jnp.int32))


@register_lower("bincount")
def _bincount(ctx, op):
    x = ctx.in1(op, "X")
    w = ctx.in1(op, "Weights")
    minlength = int(op.attr("minlength", 0))
    # static length: bounded by minlength (callers must size it; dynamic
    # max(x)+1 is not XLA-static)
    if minlength <= 0:
        raise NotImplementedError(
            "bincount needs minlength > 0 on TPU (static output shape)")
    out = jnp.bincount(x.reshape(-1).astype(jnp.int32),
                       weights=None if w is None else w.reshape(-1),
                       length=minlength)
    ctx.set_out(op, "Out", out)


@register_lower("broadcast_to")
def _broadcast_to(ctx, op):
    x = ctx.in1(op, "X")
    shape = [int(s) for s in op.attr("shape", [])]
    shape = [x.shape[i - (len(shape) - x.ndim)] if s == -1 and i >= len(shape) - x.ndim else s
             for i, s in enumerate(shape)]
    ctx.set_out(op, "Out", jnp.broadcast_to(x, shape))


@register_lower("full_like")
def _full_like(ctx, op):
    x = ctx.in1(op, "X")
    value = op.attr("value", 0.0)
    dtype = op.attr("dtype", -1)
    from ..framework import dtypes as _dt

    dt = x.dtype if dtype in (-1, None) else _dt.to_jnp(dtype)
    ctx.set_out(op, "Out", jnp.full(x.shape, value, dtype=dt))


@register_lower("put_along_axis")
def _put_along_axis(ctx, op):
    x = ctx.in1(op, "Input")
    idx = ctx.in1(op, "Index")
    val = ctx.in1(op, "Value")
    axis = int(op.attr("Axis", 0))
    reduce = op.attr("Reduce", "assign")
    val = jnp.broadcast_to(val, idx.shape).astype(x.dtype)
    if reduce == "add":
        out = _scatter_along_axis(x, idx, val, axis, "add")
    elif reduce == "multiply" or reduce == "mul":
        out = _scatter_along_axis(x, idx, val, axis, "mul")
    else:
        out = _scatter_along_axis(x, idx, val, axis, "set")
    ctx.set_out(op, "Result", out)


def _scatter_along_axis(x, idx, val, axis, mode):
    grids = jnp.meshgrid(*[jnp.arange(s) for s in idx.shape], indexing="ij")
    coords = list(grids)
    coords[axis] = idx
    at = x.at[tuple(coords)]
    return {"add": at.add, "mul": at.multiply, "set": at.set}[mode](val)
