"""Sampling-based ops: NCE, sample_logits, correlation cost volume —
plus the jit-safe token samplers (greedy / top-k / top-p) the decode
engine (serving/decode.py) runs INSIDE its compiled step.

Reference parity: operators/nce_op.{cc,h} (noise-contrastive estimation
with uniform/log-uniform samplers), operators/sample_logits_op.cc, and
operators/correlation_op.cu (FlowNet cost volume).

Token-sampler contract: every draw takes an EXPLICIT PRNG key (the
engine derives one per request from its seed via fold_in, so a
request's token stream is independent of which slot or replica served
it, and — with ``jax_threefry_partitionable`` enabled process-wide at
Executor construction since PR 7 — independent of how XLA shards the
batch).  ``tests/test_decode_engine.py`` pins two replicas given the same
seed emitting identical tokens.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.lowering import register_lower
from .common import op_seed_key


def _sampler_prob(idx, sampler, n_classes, custom_probs=None):
    """P(class) under the sampler — ONE home for the Zipfian formula
    (reference sampler.cc LogUniformSampler::Probability; CustomSampler
    reads the user distribution)."""
    if sampler == 2:
        return custom_probs[jnp.asarray(idx).astype(jnp.int32)]
    if sampler == 0:
        return jnp.full(jnp.shape(idx), 1.0 / n_classes)
    idxf = jnp.asarray(idx).astype(jnp.float32)
    return (jnp.log((idxf + 2.0) / (idxf + 1.0))) / np.log(n_classes + 1.0)


def _draw_samples(ctx, op, n_samples, n_classes):
    """-> (samples, sample_probs, custom_probs-or-None).  The custom
    distribution is fetched + normalized HERE, once, for every caller
    (nce, sample_logits) — the sampling draw and the probability
    corrections must read the same normalized values."""
    sampler = int(op.attr("sampler", 0))
    k = op_seed_key(ctx, op)
    custom_probs = None
    if sampler == 0:  # uniform
        s = jax.random.randint(k, (n_samples,), 0, n_classes)
    elif sampler == 1:  # log-uniform (Zipfian), reference math
        u = jax.random.uniform(k, (n_samples,))
        s = (jnp.exp(u * np.log(n_classes + 1.0)) - 1.0).astype(jnp.int32)
        s = jnp.clip(s, 0, n_classes - 1)
    elif sampler == 2:
        # custom distribution (reference CustomSampler builds an alias
        # table from CustomDistProbs/Alias/AliasProbs; categorical over
        # the same probs is the TPU-native equivalent — identical
        # distribution, no table plumbing)
        custom_probs = ctx.in1(op, "CustomDistProbs")
        if custom_probs is None:
            raise ValueError(
                f"{op.type} sampler=2 (custom_dist) needs the "
                f"CustomDistProbs input (per-class sampling "
                f"probabilities)")
        custom_probs = custom_probs.reshape(-1).astype(jnp.float32)
        # normalize: categorical would silently normalize raw counts,
        # desynchronizing the draw from the reported probabilities
        custom_probs = custom_probs / jnp.sum(custom_probs)
        s = jax.random.categorical(
            k, jnp.log(jnp.maximum(custom_probs, 1e-30)), shape=(n_samples,))
        s = s.astype(jnp.int32)
    else:
        raise NotImplementedError(f"{op.type} sampler {sampler} is unknown")
    return (s, _sampler_prob(s, sampler, n_classes,
                             custom_probs=custom_probs), custom_probs)


@register_lower("nce")
def _nce(ctx, op):
    """Noise-contrastive estimation (reference nce_op.h): binary logistic
    loss over the true class + num_neg_samples drawn noise classes."""
    x = ctx.in1(op, "Input")  # [B, D]
    label = ctx.in1(op, "Label")  # [B, T] true classes
    w = ctx.in1(op, "Weight")  # [num_classes, D]
    b = ctx.in1(op, "Bias")  # [num_classes] or None
    n_classes = int(op.attr("num_total_classes"))
    n_neg = int(op.attr("num_neg_samples", 10))

    bsz = x.shape[0]
    t = label.shape[1] if label.ndim > 1 else 1
    lbl = label.reshape(bsz, t)
    samples, sample_prob, custom_probs = _draw_samples(
        ctx, op, n_neg, n_classes)

    true_logit = jnp.einsum("bd,btd->bt", x, w[lbl])
    if b is not None:
        true_logit = true_logit + b[lbl]
    noise_logit = x @ w[samples].T  # [B, n_neg]
    if b is not None:
        noise_logit = noise_logit + b[samples]

    sampler = int(op.attr("sampler", 0))
    p_true = _sampler_prob(lbl, sampler, n_classes,
                           custom_probs=custom_probs)
    # NCE: sigmoid cross-entropy against logit - log(k * P_noise);
    # softplus keeps large logits finite (log1p(exp(x)) overflows)
    k = float(n_neg)
    true_adj = true_logit - jnp.log(k * p_true)
    noise_adj = noise_logit - jnp.log(k * sample_prob)[None, :]
    pos_loss = jax.nn.softplus(-true_adj).sum(axis=1)
    neg_loss = jax.nn.softplus(noise_adj).sum(axis=1)
    ctx.set_out(op, "Cost", (pos_loss + neg_loss).reshape(bsz, 1))
    ctx.set_out(op, "SampleLogits",
                jnp.concatenate([true_logit, noise_logit], axis=1))
    ctx.set_out(op, "SampleLabels", jnp.concatenate(
        [lbl, jnp.broadcast_to(samples[None], (bsz, n_neg))],
        axis=1).astype(jnp.int32))


@register_lower("sample_logits")
def _sample_logits(ctx, op):
    """Sampled-softmax helper (reference sample_logits_op): gather the
    true-label logits plus sampled-class logits, with the log-prob
    correction, for a cheap softmax over num_samples classes."""
    logits = ctx.in1(op, "Logits")  # [B, C]
    label = ctx.in1(op, "Labels")  # [B, T]
    n_samples = int(op.attr("num_samples", 10))
    c = logits.shape[1]
    bsz = logits.shape[0]
    t = label.shape[1]
    samples, prob, custom_probs = _draw_samples(ctx, op, n_samples, c)
    all_idx = jnp.concatenate(
        [label.astype(jnp.int32),
         jnp.broadcast_to(samples[None].astype(jnp.int32),
                          (bsz, n_samples))], axis=1)
    picked = jnp.take_along_axis(logits, all_idx, axis=1)
    if bool(op.attr("remove_accidental_hits", True)):
        acc = (all_idx[:, t:, None]
               == label[:, None, :].astype(jnp.int32)).any(-1)
        picked = picked.at[:, t:].add(-1e20 * acc.astype(picked.dtype))
    # subtract log Q as in sampled softmax (true labels use the SAME
    # sampler distribution as the drawn negatives)
    sampler = int(op.attr("sampler", 0))
    logq = jnp.concatenate(
        [jnp.log(_sampler_prob(label, sampler, c,
                               custom_probs=custom_probs)),
         jnp.broadcast_to(jnp.log(prob)[None], (bsz, n_samples))], axis=1)
    ctx.set_out(op, "SampledLogits", picked - logq)
    ctx.set_out(op, "SampledLabels",
                jnp.broadcast_to(jnp.arange(t)[None], (bsz, t))
                .astype(jnp.int32))
    ctx.set_out(op, "Samples", all_idx.astype(jnp.int32))
    ctx.set_out(op, "Probabilities", jnp.exp(logq))
    ctx.set_out(op, "LogitsDim", jnp.asarray(logits.shape, jnp.int32))
    ctx.set_out(op, "LabelsDim", jnp.asarray(label.shape, jnp.int32))


@register_lower("correlation")
def _correlation(ctx, op):
    """FlowNet correlation cost volume (reference correlation_op.cu):
    for each displacement in the max_displacement neighborhood, the
    channel-mean of x1(p) * x2(p + d) over kernel patches."""
    x1 = ctx.in1(op, "Input1")  # [N, C, H, W]
    x2 = ctx.in1(op, "Input2")
    pad = int(op.attr("pad_size", 0))
    ks = int(op.attr("kernel_size", 1))
    max_disp = int(op.attr("max_displacement", 1))
    stride1 = int(op.attr("stride1", 1))
    stride2 = int(op.attr("stride2", 1))
    if ks % 2 == 0:
        raise NotImplementedError("correlation kernel_size must be odd")
    kr = (ks - 1) // 2
    n, c, h, w = x1.shape
    # over-pad by the kernel radius so centered windows at every
    # sampled position (and every displacement) stay in bounds
    pw = pad + kr
    x1p = jnp.pad(x1, ((0, 0), (0, 0), (pw, pw), (pw, pw)))
    x2p = jnp.pad(x2, ((0, 0), (0, 0), (pw, pw), (pw, pw)))
    # reference grid: radius = max_disp // stride2, displacements are
    # multiples of stride2 (correlation_op InferShape)
    radius = max_disp // stride2
    disps = [i * stride2 for i in range(-radius, radius + 1)]
    outs = []
    hp, wp = h + 2 * pad, w + 2 * pad
    # reference geometry (correlation_op.cc CorrelationOutputSize):
    # border_radius = max_displacement + kernel_radius bounds both the
    # output size and the sample centers
    border = max_disp + kr
    oh = -(-(hp - 2 * border) // stride1)  # ceil div
    ow = -(-(wp - 2 * border) // stride1)
    # in top-left-corner coordinates of the k-window box filter, the
    # sampled centers land back at border + stride1*i (pad frame)
    base_y = border + stride1 * jnp.arange(oh)
    base_x = border + stride1 * jnp.arange(ow)
    for dy in disps:
        for dx in disps:
            # roll-shift: wraparound rows/cols sit outside every
            # accessed window (centers stop border short of the edge
            # and |d| <= max_disp <= border), so they are never read
            x2s = jnp.roll(x2p, (-dy, -dx), axis=(2, 3))
            prod = jnp.mean(x1p * x2s, axis=1)  # channel mean [N,Hp,Wp]
            if ks > 1:
                # restrict to the accessed band, then stride the window
                # reduce — corners land exactly on the sample centers
                # (no wasted rows/cols when stride1 > 1)
                lim_y = border + stride1 * (oh - 1) + 2 * kr + 1
                lim_x = border + stride1 * (ow - 1) + 2 * kr + 1
                band = prod[:, border:lim_y, border:lim_x]
                outs.append(jax.lax.reduce_window(
                    band, 0.0, jax.lax.add, (1, ks, ks),
                    (1, stride1, stride1), "VALID") / float(ks * ks))
            else:
                outs.append(prod[:, base_y[:, None], base_x[None, :]])
    ctx.set_out(op, "Output", jnp.stack(outs, axis=1))


# -- decode-time token samplers (serving/decode.py) -----------------------


def greedy_sample(logits):
    """argmax over the vocab axis -> int32 token ids."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def filter_top_k_top_p(logits, top_k, top_p):
    """Mask logits outside the per-row top-k / nucleus-p sets to -inf.

    Fully jit-safe with DYNAMIC per-row knobs: ``top_k`` [..] int32
    (<= 0 disables) and ``top_p`` [..] float (>= 1.0 disables) are
    data, not static arguments, so one compiled step serves any mix of
    per-slot sampling configs.  Ties at the threshold logit are kept
    (the standard sorted-threshold caveat).
    """
    v = logits.shape[-1]
    desc = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
    # top-k: keep logits >= the k-th largest (k clipped into [1, V])
    k_idx = jnp.clip(top_k - 1, 0, v - 1)
    thresh_k = jnp.take_along_axis(desc, k_idx[..., None], axis=-1)
    keep_k = (top_k <= 0)[..., None] | (logits >= thresh_k)
    # top-p: over the sorted distribution keep the minimal prefix whose
    # mass reaches p (the first token is always kept: cum - prob < p)
    probs = jax.nn.softmax(desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = (cum - probs) < top_p[..., None]
    thresh_p = jnp.min(jnp.where(keep_sorted, desc, jnp.inf), axis=-1,
                       keepdims=True)
    keep_p = (top_p >= 1.0)[..., None] | (logits >= thresh_p)
    return jnp.where(keep_k & keep_p, logits, -jnp.inf)


def sample_tokens(keys, logits, temperature, top_k, top_p):
    """One token per row: greedy when temperature <= 0, else a
    categorical draw over the temperature-scaled, top-k/top-p-filtered
    distribution.  ``keys`` [S, 2] uint32 (one PRNGKey per row — the
    explicit key thread), logits [S, V]; temperature/top_k/top_p [S].
    """
    greedy = temperature <= 0.0
    t = jnp.where(greedy, 1.0, temperature)
    filt = filter_top_k_top_p(logits / t[..., None], top_k, top_p)
    drawn = jax.vmap(jax.random.categorical)(keys, filt)
    return jnp.where(greedy, greedy_sample(logits),
                     drawn.astype(jnp.int32))
