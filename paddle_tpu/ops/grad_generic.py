"""Generic gradient lowering: vjp over the recomputed forward.

Any ``<type>_grad`` op without an explicit lowering lands here.  The op
carries the forward op's full slots + attrs (see backward.default_grad_maker);
we rebuild the forward emission in a sub-environment and differentiate it
with ``jax.vjp``.  Forward and backward share one XLA computation, so XLA's
CSE removes the duplicated forward — runtime cost is the same as a
hand-written gradient, with none of the per-op backward-kernel surface the
reference maintains (its ~500 GradOpDescMaker + grad kernels).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework import lowering as _lowering
from ..framework.lowering import LoweringContext, register_lower
from ..framework.program import Operator

GRAD_SUFFIX = "@GRAD"


def _is_float(v):
    return jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating) or jnp.issubdtype(
        jnp.asarray(v).dtype, jnp.complexfloating
    )


def lower_generic_grad(ctx: LoweringContext, gop) -> None:
    fwd_type = gop.attr("__fwd_type__")
    if not fwd_type:
        raise NotImplementedError(
            f"op {gop.type!r}: no lowering and no __fwd_type__ attr for the "
            "generic vjp path"
        )
    out_slots = set(gop.attr("__fwd_out_slots__", []) or [])
    in_slots = [
        s
        for s in gop.inputs
        if s not in out_slots and not s.endswith(GRAD_SUFFIX)
    ]
    fwd_lower = _lowering.LOWERINGS[fwd_type]
    attrs = {k: v for k, v in gop.attrs.items() if not k.startswith("__fwd_")}

    fwd_inputs = {s: list(gop.inputs[s]) for s in in_slots}
    fwd_outputs = {s: list(gop.inputs[s]) for s in out_slots if s in gop.inputs}

    # which (slot, idx) need grads, and which are differentiable floats
    want = {}  # slot -> [(idx, grad_out_name)]
    for s in in_slots:
        gnames = gop.outputs.get(s + GRAD_SUFFIX, [])
        pairs = [(i, g) for i, g in enumerate(gnames) if g]
        if pairs:
            want[s] = pairs

    diff_args = []  # list of (slot, idx) that are float and wanted
    for s, pairs in want.items():
        for i, _ in pairs:
            val = ctx.get(fwd_inputs[s][i])
            if _is_float(val):
                diff_args.append((s, i))

    const_env = {}
    for s in in_slots:
        for n in fwd_inputs[s]:
            const_env[n] = ctx.get(n)

    def run_forward(diff_vals):
        """Re-emit the forward op in a sub-env; returns env after the op."""
        env = dict(const_env)
        for (s, i), v in zip(diff_args, diff_vals):
            env[fwd_inputs[s][i]] = v
        fop = Operator.__new__(Operator)
        fop.block = ctx.block
        fop.type = fwd_type
        fop.inputs = fwd_inputs
        fop.outputs = fwd_outputs
        fop.attrs = attrs
        fop.callstack = gop.callstack
        sub = LoweringContext(ctx.block, env, rng_key=None, mesh=ctx.mesh, axis_env=ctx.axis_env)
        fwd_lower(sub, fop)
        return env

    if not diff_args:
        # nothing differentiable wanted; emit zeros for requested int grads
        for s, pairs in want.items():
            for i, gname in pairs:
                val = ctx.get(fwd_inputs[s][i])
                ctx.set(gname, jnp.zeros_like(val))
        return

    diff_vals = tuple(ctx.get(fwd_inputs[s][i]) for s, i in diff_args)

    # probe with abstract values to learn which outputs are float
    probe = jax.eval_shape(lambda dv: run_forward(dv), diff_vals)
    float_outs = []  # (slot, index_in_slot, var_name)
    for s in fwd_outputs:
        for j, n in enumerate(fwd_outputs[s]):
            if jnp.issubdtype(probe[n].dtype, jnp.floating) or jnp.issubdtype(
                probe[n].dtype, jnp.complexfloating
            ):
                float_outs.append((s, j, n))

    def fwd_fn(*dv):
        env = run_forward(dv)
        return tuple(env[n] for _, _, n in float_outs)

    primals, vjp_fn = jax.vjp(fwd_fn, *diff_vals)

    cots = []
    for (s, j, n), ref in zip(float_outs, primals):
        gnames = gop.inputs.get(s + GRAD_SUFFIX, [])
        gname = gnames[j] if j < len(gnames) else ""
        if gname:
            cots.append(ctx.get(gname).astype(ref.dtype))
        else:
            cots.append(jnp.zeros_like(ref))
    grads = vjp_fn(tuple(cots))

    grad_by_arg = dict(zip(diff_args, grads))
    for s, pairs in want.items():
        for i, gname in pairs:
            if (s, i) in grad_by_arg:
                val = ctx.get(fwd_inputs[s][i])
                ctx.set(gname, grad_by_arg[(s, i)].astype(val.dtype))
            else:
                ctx.set(gname, jnp.zeros_like(ctx.get(fwd_inputs[s][i])))


# install as the fallback for unregistered *_grad ops
_lowering.GENERIC_GRAD_LOWERING = lower_generic_grad


@register_lower("reshape_like_grad")
def _reshape_like_grad(ctx, op):
    dy = ctx.in1(op, "Out@GRAD")
    x = ctx.in1(op, "X")
    ctx.set_out(op, "X@GRAD", dy.reshape(x.shape))
