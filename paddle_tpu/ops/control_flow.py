"""Control-flow op lowerings: while / cond with sub-block attrs.

Role parity: reference paddle/fluid/operators/controlflow/ — while_op.cc
(`while` executes its sub-block via a nested Executor until Condition is
false) and conditional_block_op.cc (predicated single-branch execution),
built by python/paddle/fluid/layers/control_flow.py (While:1020,
while_loop:1035, cond:2333).

TPU-native redesign (SURVEY.md §7 "Control flow"): scopes do not exist
inside XLA, so sub-blocks lower to `lax.while_loop` / `lax.cond` with
EXPLICIT carried state.  The layer builders record the carried var names
on the op (slot "X" == slot "Out"); everything else the sub-block reads
is closed over as a constant.  The loop body must keep carried
shapes/dtypes fixed (an XLA requirement the reference does not have —
violations raise at trace time with the op's build site).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.lowering import LoweringContext, register_lower


def _trace_sub_block(ctx, sub_block, env):
    """Lower every op of a sub-block into `env` (same registry)."""
    from ..framework.lowering import PSEUDO_OPS, get_lowering

    sub_ctx = LoweringContext(sub_block, env, rng_key=None, mesh=ctx.mesh,
                              axis_env=ctx.axis_env, ring_axes=ctx.ring_axes)
    for op in sub_block.ops:
        if op.type in PSEUDO_OPS:
            continue
        try:
            get_lowering(op.type)(sub_ctx, op)
        except Exception as e:
            site = op.callstack[-1] if op.callstack else "<unknown>"
            raise type(e)(
                f"while lowering sub-block op {op.type!r} (built at "
                f"{site}): {e}") from e
    return env


def _as_pred(value):
    """Scalar bool for lax.cond/while_loop predicates."""
    v = jnp.asarray(value)
    if v.size != 1:
        raise ValueError(
            f"control-flow condition must be a single element, got shape "
            f"{v.shape}")
    return v.reshape(()).astype(jnp.bool_)


@register_lower("while")
def _while(ctx, op):
    sub = ctx.program.blocks[int(op.attr("sub_block"))]
    cond_name = op.inputs["Condition"][0]
    carry_names = list(op.inputs.get("X", []))
    if cond_name not in carry_names:
        carry_names = [cond_name] + carry_names

    # loud guard: a var written only inside the loop but read by later
    # parent ops has no initial carry value — tell the user to initialize
    # it before the loop so it becomes loop state (fluid scope semantics
    # tolerate this; explicit carry does not)
    sub_written = {n for sop in sub.ops for n in sop.output_arg_names()}
    after = False
    escaping = set()
    for pop in ctx.block.ops:
        if pop is op:
            after = True
            continue
        if after:
            for n in pop.input_arg_names():
                if n in sub_written and n not in carry_names \
                        and n not in ctx.env:
                    escaping.add(n)
    if escaping:
        raise ValueError(
            f"vars {sorted(escaping)} are written inside the while loop and "
            f"read after it, but were never initialized before the loop; "
            f"give them an initial value (e.g. fill_constant) before the "
            f"loop so they join the carried state")

    init = tuple(ctx.get(n) for n in carry_names)
    cond_idx = carry_names.index(cond_name)

    def cond_fun(carry):
        return _as_pred(carry[cond_idx])

    def body_fun(carry):
        env = dict(ctx.env)
        env.update(zip(carry_names, carry))
        _trace_sub_block(ctx, sub, env)
        new = []
        for n, old in zip(carry_names, carry):
            v = env[n]
            if jnp.shape(v) != jnp.shape(old) or \
                    jnp.asarray(v).dtype != jnp.asarray(old).dtype:
                raise TypeError(
                    f"while loop carried var {n!r} changed from "
                    f"{jnp.asarray(old).dtype}{jnp.shape(old)} to "
                    f"{jnp.asarray(v).dtype}{jnp.shape(v)}; XLA loops need "
                    f"loop-invariant shapes/dtypes")
            new.append(v)
        return tuple(new)

    final = lax.while_loop(cond_fun, body_fun, init)
    for n, v in zip(carry_names, final):
        ctx.set(n, v)


@register_lower("conditional_block")
def _conditional_block(ctx, op):
    """Predicated single-branch execution (conditional_block_op.cc): when
    the condition is false, outputs keep their previous values (zeros when
    previously undefined — the reference leaves them uninitialized, which
    XLA cannot express)."""
    sub = ctx.program.blocks[int(op.attr("sub_block"))]
    pred = _as_pred(ctx.in1(op, "Cond"))
    out_names = list(op.outputs.get("Out", []))

    def true_fn(_):
        env = dict(ctx.env)
        _trace_sub_block(ctx, sub, env)
        return tuple(env[n] for n in out_names)

    def false_fn(_):
        vals = []
        probe = jax.eval_shape(true_fn, None)
        for n, sd in zip(out_names, probe):
            if n in ctx.env:
                vals.append(jnp.asarray(ctx.env[n]).astype(sd.dtype))
            else:
                vals.append(jnp.zeros(sd.shape, sd.dtype))
        return tuple(vals)

    outs = lax.cond(pred, true_fn, false_fn, None)
    for n, v in zip(out_names, outs):
        ctx.set(n, v)


@register_lower("cond_pair")
def _cond_pair(ctx, op):
    """Two-branch functional cond (the 2.0 layers.cond builder): both
    branches are sub-blocks; their per-branch output names are recorded in
    attrs, results land in the op's Out names."""
    sub_t = ctx.program.blocks[int(op.attr("sub_block_t"))]
    sub_f = ctx.program.blocks[int(op.attr("sub_block_f"))]
    t_outs = list(op.attr("t_outs", []) or [])
    f_outs = list(op.attr("f_outs", []) or [])
    out_names = list(op.outputs.get("Out", []))
    pred = _as_pred(ctx.in1(op, "Cond"))

    def true_fn(_):
        env = dict(ctx.env)
        _trace_sub_block(ctx, sub_t, env)
        return tuple(jnp.asarray(env[n]) for n in t_outs)

    def false_fn(_):
        env = dict(ctx.env)
        _trace_sub_block(ctx, sub_f, env)
        return tuple(jnp.asarray(env[n]) for n in f_outs)

    t_shapes = jax.eval_shape(true_fn, None)
    f_shapes = jax.eval_shape(false_fn, None)
    for n, (ts, fs) in enumerate(zip(t_shapes, f_shapes)):
        if ts.shape != fs.shape or ts.dtype != fs.dtype:
            raise TypeError(
                f"cond branches disagree on output {n}: true_fn gives "
                f"{ts.dtype}{ts.shape}, false_fn gives {fs.dtype}{fs.shape}")
    outs = lax.cond(pred, true_fn, false_fn, None)
    for n, v in zip(out_names, outs):
        ctx.set(n, v)
