"""Lowering of the LayerScanPass region ops (framework/passes.py).

``layer_scan`` — ONE ``jax.lax.scan`` whose body lowers the template
block (the first segment of an isomorphic repeated-layer run) once:
per-layer weights arrive stacked on a leading ``num_layers`` axis as
scan xs, the chained activation/gradient flows through the carry, and
per-layer outputs come back as stacked ys.  The RNG key threads through
the carry so the split chain is BITWISE the one the unrolled program
would draw (iteration k performs exactly the splits unrolled layer k
performed, in the same order).  The body is optionally wrapped in
``jax.checkpoint`` under the pass's remat policy
(framework/jax_compat.py guarded accessors; a jax without
``checkpoint_policies`` degrades to plain checkpoint and counts
``remat_policy_unavailable``).

``layer_index`` — materializes one per-layer member out of a stacked
carrier for the few consumers the pass left unrolled (an edge layer a
trimmed run excluded, a fetch of a mid-stack activation).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework import jax_compat as _jc
from ..framework.lowering import (LoweringContext, apply_tp_constraints,
                                  get_lowering, register_lower)


def _ints(op, name):
    return [int(v) for v in (op.attr(name, []) or [])]


def _strs(op, name):
    return [str(v) for v in (op.attr(name, []) or [])]


@register_lower("layer_scan")
def _layer_scan(ctx: LoweringContext, op):
    from ..framework import flags
    from ..framework.passes import TP_CONSTRAINT_ATTR

    program = ctx.program
    tblock = program.blocks[int(op.attr("layer_block"))]
    n_layers = int(op.attr("num_layers"))

    carry_in_tpl = _strs(op, "carry_in_tpl")
    carry_out_tpl = _strs(op, "carry_out_tpl")
    shared_names = op.inputs.get("Shared", [])
    xs_tpl = _strs(op, "xs_tpl")
    xs_src = _strs(op, "xs_src")
    xs_flip = _ints(op, "xs_flip")
    xs_start = _ints(op, "xs_start")
    xs_stop = _ints(op, "xs_stop")
    ys_tpl = _strs(op, "ys_tpl")
    ys_pre = _ints(op, "ys_pre")
    ys_flip = _ints(op, "ys_flip")
    ys_ustart = _ints(op, "ys_update_start")

    # -- assemble the scan xs ---------------------------------------------
    stacked_in = list(op.inputs.get("StackedIn", []))
    gather_in = list(op.inputs.get("GatherIn", []))
    xs_vals = []
    si = gi = 0
    for i in range(len(xs_tpl)):
        if xs_src[i] == "c":
            v = ctx.get(stacked_in[si])
            si += 1
            if xs_start[i] >= 0:
                v = v[xs_start[i]:xs_stop[i]]
            if xs_flip[i]:
                v = jnp.flip(v, axis=0)
        else:  # "g": members exist individually; stack at trace time
            v = jnp.stack([ctx.get(n)
                           for n in gather_in[gi:gi + n_layers]], axis=0)
            gi += n_layers
        xs_vals.append(v)

    shared_vals = {n: ctx.get(n) for n in shared_names}
    init = tuple(ctx.get(n) for n in op.inputs.get("CarryIn", []))
    has_key = ctx.rng_key is not None
    consumed = [False]
    mesh = ctx.mesh

    def body(carry, x):
        if has_key:
            key, cvals = carry[0], carry[1:]
        else:
            key, cvals = None, carry
        env = dict(shared_vals)
        env.update(zip(carry_in_tpl, cvals))
        if xs_tpl:
            env.update(zip(xs_tpl, x))
        bctx = LoweringContext(tblock, env, rng_key=key, mesh=mesh,
                               axis_env=ctx.axis_env,
                               ring_axes=ctx.ring_axes,
                               fold_axes=ctx.fold_axes)
        # pre-ys (a carry's value at iteration START) snapshot before
        # the body may rebind the name
        pre_vals = {t: env[t] for t, p in zip(ys_tpl, ys_pre) if p}
        for top in tblock.ops:
            try:
                get_lowering(top.type)(bctx, top)
                if mesh is not None and top.has_attr(TP_CONSTRAINT_ATTR):
                    apply_tp_constraints(env, top, mesh)
            except Exception as e:
                site = top.callstack[-1] if top.callstack else "<unknown>"
                raise type(e)(
                    f"while lowering op {top.type!r} inside layer_scan "
                    f"(built at {site}): {e}") from e
        consumed[0] = consumed[0] or bctx.rng_consumed
        ys = tuple(pre_vals[t] if p else env[t]
                   for t, p in zip(ys_tpl, ys_pre))
        new_carry = tuple(env[w] for w in carry_out_tpl)
        if has_key:
            new_key = bctx.rng_key if bctx.rng_consumed else key
            return (new_key,) + new_carry, ys
        return new_carry, ys

    body = _jc.wrap_checkpoint(body, str(op.attr("remat_policy", "") or ""))
    init_carry = ((ctx.rng_key,) + init) if has_key else init
    final_carry, ys_stacks = _jc.scan(
        body, init_carry, tuple(xs_vals) if xs_vals else None,
        length=n_layers,
        unroll=int(flags.flag("layer_scan_unroll") or 1))

    if has_key:
        new_key, final_vals = final_carry[0], final_carry[1:]
        if consumed[0]:
            ctx._rng = new_key
            ctx.rng_consumed = True
    else:
        final_vals = final_carry

    for name, v in zip(op.outputs.get("CarryOut", []), final_vals):
        ctx.set(name, v)
    for i, (name, v) in enumerate(zip(op.outputs.get("StackedOut", []),
                                      ys_stacks)):
        if ys_flip[i]:
            v = jnp.flip(v, axis=0)
        if ys_ustart[i] >= 0:
            # in-place slice update of an existing carrier (a trimmed
            # run updating the middle of a wider weight stack)
            cur = ctx.get(name)
            v = cur.at[ys_ustart[i]:ys_ustart[i] + n_layers].set(v)
        ctx.set(name, v)


@register_lower("layer_index")
def _layer_index(ctx: LoweringContext, op):
    x = ctx.in1(op, "X")
    ctx.set_out(op, "Out", x[int(op.attr("index", 0))])
