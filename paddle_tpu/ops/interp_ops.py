"""Image interpolation ops: nearest/linear/bilinear/bicubic/trilinear.

Reference parity: operators/interpolate_op.cc (+ *_v2 variants) — on TPU
these are gathers/weighted gathers XLA vectorizes; align_corners follows
the reference coordinate transforms exactly so OpTest parity holds.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.lowering import register_lower


def _out_hw(op, in_hw, ndim):
    """Resolve target spatial size: OutSize/SizeTensor input > out_* attrs
    > scale (attr or Scale input)."""
    names = ["out_d", "out_h", "out_w"][-ndim:]
    sizes = [int(op.attr(n, -1) or -1) for n in names]
    if all(s > 0 for s in sizes):
        return sizes
    scale = op.attr("scale", None)
    if isinstance(scale, (list, tuple)) and scale:
        return [int(round(s * f)) for s, f in zip(in_hw, scale)]
    if isinstance(scale, (int, float)) and scale > 0:
        return [int(round(s * float(scale))) for s in in_hw]
    raise NotImplementedError(
        "interpolate needs static out_h/out_w or scale attrs (dynamic "
        "OutSize tensors do not fit XLA static shapes; resolve upstream)")


def _src_index(out_len, in_len, align_corners, align_mode,
               dtype=jnp.float32, clip=True):
    i = jnp.arange(out_len, dtype=dtype)
    if align_corners:
        ratio = (in_len - 1) / max(out_len - 1, 1)
        return i * ratio
    ratio = in_len / out_len
    if align_mode == 0:
        src = ratio * (i + 0.5) - 0.5
        # bilinear kernels clamp negative src at 0 (reference
        # interpolate_op.h); bicubic keeps the negative coordinate and
        # clamps the GATHERS instead (clip=False)
        return jnp.clip(src, 0.0, None) if clip else src
    return ratio * i


def _linear_axis(x, axis, out_len, align_corners, align_mode):
    in_len = x.shape[axis]
    src = _src_index(out_len, in_len, align_corners, align_mode)
    lo = jnp.floor(src).astype(jnp.int32)
    hi = jnp.clip(lo + 1, 0, in_len - 1)
    lo = jnp.clip(lo, 0, in_len - 1)
    w = (src - lo).astype(x.dtype)
    xl = jnp.take(x, lo, axis=axis)
    xh = jnp.take(x, hi, axis=axis)
    shape = [1] * x.ndim
    shape[axis] = out_len
    w = w.reshape(shape)
    return xl * (1 - w) + xh * w


def _nearest_axis(x, axis, out_len, align_corners):
    in_len = x.shape[axis]
    if align_corners:
        src = jnp.round(_src_index(out_len, in_len, True, 1))
    else:
        src = jnp.floor(jnp.arange(out_len) * (in_len / out_len))
    idx = jnp.clip(src.astype(jnp.int32), 0, in_len - 1)
    return jnp.take(x, idx, axis=axis)


def _cubic_axis(x, axis, out_len, align_corners):
    in_len = x.shape[axis]
    src = _src_index(out_len, in_len, align_corners, 0, clip=False)
    i0 = jnp.floor(src).astype(jnp.int32)
    t = (src - i0).astype(x.dtype)
    a = -0.75
    # standard keys cubic weights
    def w(d):
        d = jnp.abs(d)
        return jnp.where(
            d <= 1, (a + 2) * d ** 3 - (a + 3) * d ** 2 + 1,
            jnp.where(d < 2, a * d ** 3 - 5 * a * d ** 2 + 8 * a * d - 4 * a,
                      jnp.zeros_like(d)))
    shape = [1] * x.ndim
    shape[axis] = out_len
    out = 0.0
    for k in range(-1, 3):
        idx = jnp.clip(i0 + k, 0, in_len - 1)
        out = out + jnp.take(x, idx, axis=axis) * w(t - k).reshape(shape)
    return out


def _interp(ctx, op, method, nd):
    x = ctx.in1(op, "X")  # NCHW / NCDHW / NCW
    data_layout = op.attr("data_layout", "NCHW") or "NCHW"
    channel_last = data_layout.endswith("C") and len(data_layout) == x.ndim
    if channel_last:
        perm = (0, x.ndim - 1) + tuple(range(1, x.ndim - 1))
        x = jnp.transpose(x, perm)
    in_hw = x.shape[2:]
    out_hw = _out_hw(op, in_hw, nd)
    align_corners = bool(op.attr("align_corners", True))
    align_mode = int(op.attr("align_mode", 1))
    y = x
    for i, (o, s) in enumerate(zip(out_hw, in_hw)):
        axis = 2 + i
        if o == s:
            continue
        if method == "nearest":
            y = _nearest_axis(y, axis, o, align_corners)
        elif method == "cubic":
            y = _cubic_axis(y, axis, o, align_corners)
        else:
            y = _linear_axis(y, axis, o, align_corners, align_mode)
    if channel_last:
        inv = (0,) + tuple(range(2, x.ndim)) + (1,)
        y = jnp.transpose(y, inv)
    ctx.set_out(op, "Out", y)


@register_lower("nearest_interp", "nearest_interp_v2")
def _nearest_interp(ctx, op):
    _interp(ctx, op, "nearest", 2)


@register_lower("bilinear_interp", "bilinear_interp_v2")
def _bilinear_interp(ctx, op):
    _interp(ctx, op, "linear", 2)


@register_lower("bicubic_interp", "bicubic_interp_v2")
def _bicubic_interp(ctx, op):
    _interp(ctx, op, "cubic", 2)


@register_lower("trilinear_interp", "trilinear_interp_v2")
def _trilinear_interp(ctx, op):
    _interp(ctx, op, "linear", 3)


@register_lower("linear_interp", "linear_interp_v2")
def _linear_interp(ctx, op):
    _interp(ctx, op, "linear", 1)
