"""Embedding lookups: lookup_table(_v2) lowering + the sharded engine.

Role parity: lookup_table_v2_op.cc plus the reference's whole sparse
remote-lookup stack (SelectedRows gradients, the gRPC/bRPC parameter
server, distributed/ps/*).  TPU-native replacement: a large table lives
ROW-SHARDED over the mesh's 'mp' axis — rows ``[r*V/mp, (r+1)*V/mp)``
on mp rank ``r`` — and a lookup is one all-to-all of ids to their
owning shards, a local gather, and one all-to-all of the rows back.
No parameter-server process exists; the "server" is the shard itself.

Four lowering paths, dispatched per op at trace time:

1. **manual pipeline×mp** (op stamped ``EMB_SHARD_ATTR`` and 'mp' in
   ``ctx.axis_env``): the trace runs per-device inside shard_map and
   the env holds the LOCAL row shard — :func:`sharded_embedding_lookup`
   runs the explicit all-to-all engine.  Its backward is a
   ``custom_vjp`` (the PR-15 f/g idiom): a dense scatter-add of the
   routed cotangent rows onto the owning shard, so ``jax.vjp`` of the
   staged forward (ops/grad_generic.py) yields exact shard gradients.
2. **GSPMD** (stamped, mesh set, empty axis_env): the traced value is
   the global table; :func:`embedding_lookup_ref` keeps the same
   custom_vjp gather/scatter-add semantics on the global value and the
   pass-stamped layout anchor (``TP_CONSTRAINT_ATTR``) pins the output
   replicated-on-mp so XLA's SPMD partitioner places the gather comm.
3. **sparse fallback** (``is_sparse`` requested but no sharding plan
   stamped the op): counted ``emb_sparse_fallback_dense`` + warned
   once — the flag silently degrading to dense was a bug.
4. **plain dense** (everything else): ``jnp.take`` + padding mask,
   byte-identical to the historical lowering (BERT word embeddings
   etc. ride this path unchanged).

``padding_idx`` contract on every path: the padding row's output is
zero AND its gradient is exactly zero (pinned inside the custom_vjp
backward, masked on the dense path).
"""
from __future__ import annotations

import functools

import numpy as np

from ..framework.lowering import register_lower

__all__ = [
    "embedding_lookup_ref",
    "sharded_embedding_lookup",
    "alltoall_bytes_per_lookup",
]


# ---------------------------------------------------------------------------
# dense reference: custom_vjp gather with an explicit scatter-add backward
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _dense_ref_fn(padding_idx: int):
    """Dense lookup with the engine's gradient semantics made explicit:
    forward ``take`` (+ padding mask), backward a dense scatter-add
    ``zeros_like(W).at[ids].add(ct)`` with the padding row pinned zero
    and out-of-range ids dropped.  Cached per static padding_idx so the
    custom_vjp identity is stable across traces (lru idiom of
    ops/collective_matmul.py)."""
    import jax
    import jax.numpy as jnp

    @jax.custom_vjp
    def lookup(w, ids):
        return _fwd(w, ids)[0]

    def _fwd(w, ids):
        # engine contract (same as the all-to-all path): out-of-vocab
        # ids yield zero rows, never wraparound/NaN-fill
        keep = (ids >= 0) & (ids < w.shape[0])
        if padding_idx >= 0:
            keep = keep & (ids != padding_idx)
        out = jnp.take(w, jnp.where(keep, ids, 0), axis=0)
        out = out * keep[..., None].astype(out.dtype)
        return out, (ids, w.shape)

    def _bwd(res, ct):
        ids, wshape = res
        flat = ids.reshape(-1)
        ctf = ct.reshape(-1, wshape[-1])
        keep = (flat >= 0) & (flat < wshape[0])
        if padding_idx >= 0:
            keep = keep & (flat != padding_idx)
        ctf = ctf * keep[:, None].astype(ct.dtype)
        idx = jnp.where(keep, flat, wshape[0])  # OOB -> dropped
        g = jnp.zeros(wshape, ct.dtype).at[idx].add(ctf, mode="drop")
        if padding_idx >= 0:
            g = g.at[padding_idx].set(0.0)
        return g, np.zeros(ids.shape, jax.dtypes.float0)

    lookup.defvjp(_fwd, _bwd)
    return lookup


def embedding_lookup_ref(w, ids, padding_idx=-1):
    """Pure-jnp dense reference (the CPU/tier-1 default for the engine
    paths): exact gather/scatter-add semantics as a ``custom_vjp``."""
    return _dense_ref_fn(int(padding_idx))(w, ids)


# ---------------------------------------------------------------------------
# sharded engine: all-to-all id routing + local gather, per-shard trace
# ---------------------------------------------------------------------------


def _route(ids_slice, degree, rows_per_shard):
    """Static routing plan for one rank's id slice: stable-sort by
    owning shard, bucket offsets via searchsorted, and the (degree,
    cap) send buffer of ids (-1 = empty slot).  Invalid ids (out of
    [0, degree*rows_per_shard)) sort into a virtual bucket ``degree``
    whose writes fall off the buffer (``mode='drop'``)."""
    import jax.numpy as jnp

    cap = ids_slice.shape[0]
    vocab = degree * rows_per_shard
    owner = ids_slice // rows_per_shard
    valid = (ids_slice >= 0) & (ids_slice < vocab)
    owner = jnp.where(valid, owner, degree)
    order = jnp.argsort(owner, stable=True)
    s_ids = ids_slice[order]
    s_owner = owner[order]
    start = jnp.searchsorted(s_owner, jnp.arange(degree + 1))
    pos = jnp.arange(cap) - start[jnp.clip(s_owner, 0, degree)]
    ok = s_owner < degree
    send = jnp.full((degree, cap), -1, ids_slice.dtype)
    send = send.at[s_owner, pos].set(s_ids, mode="drop")
    return order, s_owner, pos, ok, send


@functools.lru_cache(maxsize=None)
def _sharded_inner_fn(axis_name: str, degree: int, rows_per_shard: int):
    """The engine core as a ``custom_vjp`` over (local_rows, padded
    ids).  The vjp boundary is the PER-RANK output slice — the final
    all_gather (and the padding mask) stay OUTSIDE so jax transposes
    them natively (all_gather^T = reduce-scatter), and the backward
    receives each rank's exact cotangent slice with no dependence on
    shard_map's replicated-output transpose convention.

    forward: slice my cap ids -> all-to-all ids to owners -> local
    gather on the row shard -> all-to-all rows back -> unsort.
    backward: re-route (same plan), all-to-all the cotangent rows to
    the owners, dense scatter-add onto the local shard; ids get a
    float0 cotangent."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.custom_vjp
    def inner(local_rows, ids_p):
        return _fwd(local_rows, ids_p)[0]

    def _my_slice(ids_p):
        cap = ids_p.shape[0] // degree
        r = lax.axis_index(axis_name)
        return lax.dynamic_slice_in_dim(ids_p, r * cap, cap), cap, r

    def _fwd(local_rows, ids_p):
        my, cap, r = _my_slice(ids_p)
        order, s_owner, pos, ok, send = _route(my, degree, rows_per_shard)
        recv = lax.all_to_all(send, axis_name, split_axis=0, concat_axis=0)
        lid = recv - r * rows_per_shard
        rvalid = (recv >= 0) & (lid >= 0) & (lid < rows_per_shard)
        rows = jnp.where(
            rvalid[..., None],
            jnp.take(local_rows, jnp.clip(lid, 0, rows_per_shard - 1),
                     axis=0), 0.0)
        back = lax.all_to_all(rows, axis_name, split_axis=0, concat_axis=0)
        gathered = back[jnp.clip(s_owner, 0, degree - 1), pos]
        gathered = jnp.where(ok[..., None], gathered, 0.0)
        out = jnp.zeros((cap, local_rows.shape[1]),
                        local_rows.dtype).at[order].set(gathered)
        return out, (ids_p, local_rows.shape)

    def _bwd(res, ct_slice):
        ids_p, lshape = res
        my, cap, r = _my_slice(ids_p)
        order, s_owner, pos, ok, send = _route(my, degree, rows_per_shard)
        ct_send = jnp.zeros((degree, cap, ct_slice.shape[1]),
                            ct_slice.dtype).at[s_owner, pos].set(
                                ct_slice[order], mode="drop")
        ct_recv = lax.all_to_all(ct_send, axis_name,
                                 split_axis=0, concat_axis=0)
        id_recv = lax.all_to_all(send, axis_name,
                                 split_axis=0, concat_axis=0)
        lid = id_recv - r * rows_per_shard  # negative/OOB -> dropped
        g = jnp.zeros(lshape, ct_slice.dtype).at[lid.reshape(-1)].add(
            ct_recv.reshape(-1, ct_slice.shape[1]), mode="drop")
        return g, np.zeros(ids_p.shape, jax.dtypes.float0)

    inner.defvjp(_fwd, _bwd)
    return inner


def alltoall_bytes_per_lookup(n_ids, degree, emb_dim, ids_itemsize=8,
                              row_itemsize=4):
    """Static per-rank all-to-all payload of one sharded lookup (the
    ``emb_alltoall_bytes`` accounting): the id routing buffer out plus
    the gathered rows back."""
    cap = -(-int(n_ids) // int(degree))
    return int(degree) * cap * (int(ids_itemsize)
                                + int(emb_dim) * int(row_itemsize))


def sharded_embedding_lookup(local_rows, ids, axis_name="mp", degree=None,
                             padding_idx=-1):
    """All-to-all embedding lookup over a row-sharded table; call
    inside shard_map (the manual pipeline×mp trace, or directly — see
    distributed/embedding.py).  ``local_rows`` is THIS rank's
    ``(vocab/degree, dim)`` shard; ``ids`` is replicated on
    ``axis_name`` and may have any shape.  Returns the full
    ``ids.shape + (dim,)`` lookup, replicated on ``axis_name``.
    Out-of-vocab ids yield zero rows (and zero gradient)."""
    import jax.numpy as jnp
    from jax import lax

    if degree is None:
        raise ValueError("sharded_embedding_lookup requires the static "
                         "shard degree (mesh axis size)")
    degree = int(degree)
    rows_per_shard = int(local_rows.shape[0])
    flat = ids.reshape(-1)
    n = int(flat.shape[0])
    npad = -(-n // degree) * degree
    if npad != n:
        flat = jnp.concatenate(
            [flat, jnp.full((npad - n,), -1, flat.dtype)])
    inner = _sharded_inner_fn(axis_name, degree, rows_per_shard)
    out_slice = inner(local_rows, flat)
    full = lax.all_gather(out_slice, axis_name, tiled=True)[:n]
    if padding_idx is not None and int(padding_idx) >= 0:
        full = full * (flat[:n] != int(padding_idx))[:, None].astype(
            full.dtype)
    return full.reshape(tuple(ids.shape) + (local_rows.shape[1],))


# ---------------------------------------------------------------------------
# lowering
# ---------------------------------------------------------------------------

_warned_sparse_fallback = False


def _warn_sparse_fallback(op):
    """is_sparse=True with no active sharding plan: the historical code
    silently ignored the flag; degrade loudly instead (once per
    process; the counter covers every occurrence)."""
    global _warned_sparse_fallback
    from ..monitor import stat_add

    stat_add("emb_sparse_fallback_dense")
    if not _warned_sparse_fallback:
        _warned_sparse_fallback = True
        import warnings

        site = op.callstack[-1] if getattr(op, "callstack", None) else "?"
        warnings.warn(
            "embedding(is_sparse=True) has no active sharding plan — "
            "falling back to a dense replicated table (counted "
            "emb_sparse_fallback_dense).  For the distributed engine, "
            "train under fleet with a mesh that has an 'mp' axis "
            f"(fleet.distributed_embedding; op built at {site})",
            stacklevel=2)


@register_lower("lookup_table", "lookup_table_v2")
def _lookup_table(ctx, op):
    import jax.numpy as jnp

    from ..monitor import stat_add, stat_set
    from ..observe import tracer as otrace

    w = ctx.in1(op, "W")
    ids = ctx.in1(op, "Ids")
    padding_idx = int(op.attr("padding_idx", -1))
    if op.type == "lookup_table" and ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = jnp.squeeze(ids, -1)

    from ..framework.passes import EMB_SHARD_ATTR

    degree = int(op.attr(EMB_SHARD_ATTR, 0) or 0)
    if degree > 1 and "mp" in ctx.axis_env:
        # manual pipeline×mp: w IS the local row shard; explicit engine
        with otrace.span("embedding/lookup", path="alltoall",
                         degree=degree, n_ids=int(np.prod(ids.shape))):
            out = sharded_embedding_lookup(
                w, ids, axis_name="mp", degree=degree,
                padding_idx=padding_idx)
        stat_set("emb_rows_per_shard", int(w.shape[0]))
        stat_add("emb_alltoall_bytes", alltoall_bytes_per_lookup(
            int(np.prod(ids.shape)), degree, int(w.shape[1]),
            ids_itemsize=int(jnp.dtype(ids.dtype).itemsize)))
        ctx.set_out(op, "Out", out)
        return
    if degree > 1:
        # GSPMD: w is the global table (NamedSharding P('mp', None)
        # from the plan); keep the engine's custom_vjp semantics on the
        # global value — the stamped anchor pins the output layout and
        # XLA places the gather/scatter comm at this op
        with otrace.span("embedding/lookup", path="gspmd",
                         degree=degree, n_ids=int(np.prod(ids.shape))):
            out = embedding_lookup_ref(w, ids, padding_idx)
        stat_set("emb_rows_per_shard", int(w.shape[0]) // degree)
        stat_add("emb_alltoall_bytes", alltoall_bytes_per_lookup(
            int(np.prod(ids.shape)), degree, int(w.shape[1]),
            ids_itemsize=int(jnp.dtype(ids.dtype).itemsize)))
        ctx.set_out(op, "Out", out)
        return
    if bool(op.attr("is_sparse", False)):
        _warn_sparse_fallback(op)
        ctx.set_out(op, "Out", embedding_lookup_ref(w, ids, padding_idx))
        return
    # plain dense path — unchanged historical semantics
    out = jnp.take(w, ids, axis=0)
    if padding_idx >= 0:
        mask = (ids != padding_idx)[..., None].astype(out.dtype)
        out = out * mask
    ctx.set_out(op, "Out", out)
