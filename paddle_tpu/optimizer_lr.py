"""Learning-rate schedulers.

Role parity: reference python/paddle/fluid/dygraph/learning_rate_scheduler.py
and paddle.optimizer.lr.  Host-side design: ``step()`` computes the new LR
and writes the scalar into the scope var the compiled train step reads —
a 4-byte H2D per step, no recompile (the reference instead builds LR
subgraphs with ops; the value-update contract is identical).
"""
from __future__ import annotations

import math


class LRScheduler:
    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        self.base_lr = float(learning_rate)
        self.last_epoch = last_epoch
        self.last_lr = float(learning_rate)
        self.verbose = verbose
        self._optimizer = None
        self.step()

    def _bind(self, optimizer):
        self._optimizer = optimizer

    def get_lr(self) -> float:
        raise NotImplementedError

    def step(self, epoch=None):
        self.last_epoch = self.last_epoch + 1 if epoch is None else epoch
        self.last_lr = self.get_lr()
        if self._optimizer is not None:
            self._optimizer.set_lr(self.last_lr)

    def state_dict(self):
        return {"last_epoch": self.last_epoch, "last_lr": self.last_lr}

    def set_state_dict(self, state):
        self.last_epoch = state.get("last_epoch", self.last_epoch)
        self.last_lr = state.get("last_lr", self.last_lr)


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0, **kw):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, **kw)

    def get_lr(self):
        step = max(self.last_epoch, 1)
        return (
            self.base_lr
            * self.d_model ** -0.5
            * min(step**-0.5, step * self.warmup_steps**-1.5)
        )


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, **kw):
        self.boundaries = list(boundaries)
        self.values = list(values)
        super().__init__(values[0], **kw)

    def get_lr(self):
        for b, v in zip(self.boundaries, self.values):
            if self.last_epoch < b:
                return v
        return self.values[len(self.boundaries)]


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, **kw):
        self.gamma = gamma
        super().__init__(learning_rate, **kw)

    def get_lr(self):
        return self.base_lr * math.exp(-self.gamma * self.last_epoch)


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, **kw):
        self.gamma = gamma
        super().__init__(learning_rate, **kw)

    def get_lr(self):
        return self.base_lr * self.gamma**self.last_epoch


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, **kw):
        self.gamma = gamma
        super().__init__(learning_rate, **kw)

    def get_lr(self):
        return self.base_lr / (1 + self.gamma * self.last_epoch)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0, cycle=False, **kw):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, **kw)

    def get_lr(self):
        step = self.last_epoch
        if self.cycle and step > 0:
            decay_steps = self.decay_steps * math.ceil(step / self.decay_steps)
        else:
            decay_steps = self.decay_steps
            step = min(step, decay_steps)
        frac = (1 - step / max(decay_steps, 1)) ** self.power
        return (self.base_lr - self.end_lr) * frac + self.end_lr


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0.0, **kw):
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, **kw)

    def get_lr(self):
        return (
            self.eta_min
            + (self.base_lr - self.eta_min)
            * (1 + math.cos(math.pi * self.last_epoch / self.T_max))
            / 2
        )


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr, **kw):
        self.lr_after = learning_rate
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        base = end_lr if not isinstance(learning_rate, LRScheduler) else learning_rate.base_lr
        super().__init__(base, **kw)

    def get_lr(self):
        if self.last_epoch < self.warmup_steps:
            return self.start_lr + (self.end_lr - self.start_lr) * self.last_epoch / self.warmup_steps
        if isinstance(self.lr_after, LRScheduler):
            self.lr_after.last_epoch = self.last_epoch - self.warmup_steps
            return self.lr_after.get_lr()
        return float(self.lr_after)


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, **kw):
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, **kw)

    def get_lr(self):
        return self.base_lr * self.gamma ** (self.last_epoch // self.step_size)


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, **kw):
        self.milestones = list(milestones)
        self.gamma = gamma
        super().__init__(learning_rate, **kw)

    def get_lr(self):
        n = sum(1 for m in self.milestones if self.last_epoch >= m)
        return self.base_lr * self.gamma**n


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, **kw):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, **kw)

    def get_lr(self):
        return self.base_lr * self.lr_lambda(self.last_epoch)


class ReduceOnPlateau(LRScheduler):
    def __init__(
        self,
        learning_rate,
        mode="min",
        factor=0.1,
        patience=10,
        threshold=1e-4,
        cooldown=0,
        min_lr=0.0,
        **kw,
    ):
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.best = None
        self.num_bad = 0
        self.cooldown_counter = 0
        self._lr = float(learning_rate)
        super().__init__(learning_rate, **kw)

    def get_lr(self):
        return self._lr

    def step(self, metrics=None, epoch=None):
        if metrics is None:
            return
        val = float(metrics)
        better = (
            self.best is None
            or (self.mode == "min" and val < self.best - self.threshold)
            or (self.mode == "max" and val > self.best + self.threshold)
        )
        if better:
            self.best = val
            self.num_bad = 0
        elif self.cooldown_counter > 0:
            self.cooldown_counter -= 1
        else:
            self.num_bad += 1
            if self.num_bad > self.patience:
                self._lr = max(self._lr * self.factor, self.min_lr)
                self.cooldown_counter = self.cooldown
                self.num_bad = 0
        self.last_lr = self._lr
        if self._optimizer is not None:
            self._optimizer.set_lr(self._lr)
