"""Model statistics: parameter counts and FLOPs.

Role parity: reference python/paddle/hapi (paddle.summary / paddle.flops
backed by fluid/contrib/model_stat.py).  TPU-native: stats come from a
static Program walk — the same op stream XLA compiles — so the numbers
cover exactly what runs, including fused attention and backward ops when
a whole train program is passed.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np


def _prod(xs):
    out = 1
    for x in xs:
        # dynamic (-1) dims count as 1: a static-graph program with a
        # symbolic batch reports per-sample FLOPs (traced programs have
        # concrete batch dims, so paddle.flops(net, input_size) is exact)
        out *= max(int(x), 1)
    return out


def _shape_of(block, name):
    v = block._find_var_recursive(name)
    return list(v.shape) if v is not None and v.shape else []


def _conv_flops(block, op):
    out = _shape_of(block, op.output("Output")[0])
    w = _shape_of(block, op.input("Filter")[0])
    if len(out) < 3 or not w:
        return 0
    if op.type == "conv2d_transpose":
        # filter is (Cin, Cout/groups, kh, kw): each INPUT element
        # scatters into Cout/g*kh*kw outputs — MACs = in_elems*prod(w[1:])
        inp = _shape_of(block, op.input("Input")[0])
        return 2 * _prod(inp) * _prod(w[1:])
    # forward conv filter is (Cout, Cin/groups, kh, kw): w[1:] is the
    # per-output fan-in.  MACs = out_elems * prod(w[1:]); FLOPs = 2*MACs
    return 2 * _prod(out) * _prod(w[1:])


def _matmul_flops(block, op):
    x = _shape_of(block, op.input("X")[0])
    y = _shape_of(block, op.input("Y")[0])
    out_slot = "Out"
    out = _shape_of(block, op.output(out_slot)[0])
    if not x or not y:
        return 0
    k = x[-1] if not bool(op.attr("transpose_X",
                                  op.attr("trans_x", False))) else x[-2]
    return 2 * _prod(out) * int(k) if out else 0


def _flash_attention_flops(block, op):
    """Model FLOPs of the fused attention op: the two score/context
    contractions it replaced (2*MACs each over B*H*Sq*Sk*D).  The
    backward's tile recompute is an implementation cost, not model
    work, so — like activation recompute under remat — it is NOT
    priced; this keeps MFU comparable across FLAGS_flash_attention
    settings at identical config."""
    q = _shape_of(block, op.input("Q")[0])
    k = _shape_of(block, op.input("K")[0])
    if len(q) != 4 or len(k) != 4:
        return 0
    b, h, sq, d = q
    sk = k[2]
    return 4 * _prod([b, h, sq, sk, d])


_ELEMENTWISE = {
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min", "relu",
    "sigmoid", "tanh", "gelu", "scale", "softmax", "cast", "clip",
}

_GRAD_CONV = {"conv2d_grad", "depthwise_conv2d_grad",
              "conv2d_transpose_grad"}
_GRAD_MATMUL = {"matmul_grad", "matmul_v2_grad", "mul_grad"}


class _FwdSlotView:
    """Grad ops carry the forward op's full slots in their INPUTS
    (backward.default_grad_maker copies them); this shim re-views a
    grad op through the forward slot names so the forward estimators
    can price the backward work.  A matmul/conv backward is two
    forward-sized contractions (dX and dY/dFilter), hence the 2x in
    ``program_flops``."""

    __slots__ = ("_op", "type")

    def __init__(self, op):
        self._op = op
        self.type = op.attr("__fwd_type__", None) or op.type[:-len("_grad")]

    def input(self, slot):
        return self._op.inputs.get(slot, [])

    def output(self, slot):  # fwd outputs live in the grad op's inputs
        return self._op.inputs.get(slot, [])

    def attr(self, name, default=None):
        return self._op.attr(name, default)


def program_flops(program, detail=False):
    """FLOPs of one execution of ``program``'s global block.

    Matmuls/convs count 2*MACs (the MXU work) and their ``_grad``
    siblings 2x that (dX + dW are forward-sized contractions, priced
    through the forward slots the grad maker copies); elementwise ops
    count one FLOP per output element (VPU work); everything else is
    free (layout, control, IO).  Returns total FLOPs, plus a
    per-op-type breakdown when ``detail=True``."""
    block = program.global_block
    per_type: Dict[str, int] = {}
    for op in block.ops:
        if op.type in ("conv2d", "depthwise_conv2d", "conv2d_transpose"):
            f = _conv_flops(block, op)
        elif op.type in ("matmul", "matmul_v2", "mul"):
            f = _matmul_flops(block, op)
        elif op.type == "flash_attention":
            f = _flash_attention_flops(block, op)
        elif op.type == "flash_attention_grad":
            # dQ/dK + dV/dP: four forward-sized contractions vs the
            # forward's two — same 2x convention as matmul_grad
            try:
                f = 2 * _flash_attention_flops(block, _FwdSlotView(op))
            except (IndexError, KeyError):
                f = 0
        elif op.type in _GRAD_CONV or op.type in _GRAD_MATMUL:
            # backward = dX + dW, each a forward-sized contraction
            est = _conv_flops if op.type in _GRAD_CONV else _matmul_flops
            try:
                f = 2 * est(block, _FwdSlotView(op))
            except (IndexError, KeyError):  # hand-built grad op missing
                f = 0                       # the forward slots: skip
        elif op.type in _ELEMENTWISE:
            outs = op.output_arg_names()
            f = _prod(_shape_of(block, outs[0])) if outs else 0
        else:
            f = 0
        if f:
            per_type[op.type] = per_type.get(op.type, 0) + f
    total = sum(per_type.values())
    if detail:
        return total, dict(sorted(per_type.items(),
                                  key=lambda kv: -kv[1]))
    return total


_DTYPE_BYTES = {"float32": 4, "float64": 8, "int32": 4, "int64": 8,
                "uint32": 4, "uint64": 8, "float16": 2, "bfloat16": 2,
                "int16": 2, "uint16": 2, "uint8": 1, "int8": 1,
                "bool": 1}


def memory_usage(program, batch_size=1) -> Dict[str, float]:
    """Rough per-device memory estimate for one execution (reference
    fluid/contrib/memory_usage_calc.py role).  Under XLA the true peak
    depends on fusion/liveness, so this is the same upper-bound the
    reference computes: sum of var sizes, split into parameters vs
    activations, with -1 batch dims filled by ``batch_size``."""
    params = acts = 0
    for var in program.global_block.vars.values():
        shape = list(var.shape or [])
        if not shape:
            continue
        n = 1
        for s in shape:
            n *= batch_size if int(s) in (-1, 0) else int(s)
        dt = getattr(var, "dtype_str", None) or str(var.dtype)
        nbytes = n * _DTYPE_BYTES.get(str(dt), 4)
        if getattr(var, "persistable", False):
            params += nbytes
        else:
            acts += nbytes
    return {"parameter_mb": round(params / 2**20, 3),
            "activation_mb": round(acts / 2**20, 3),
            "total_mb": round((params + acts) / 2**20, 3)}


def flops(net, input_size=None, dtype="float32", print_detail=False):
    """Reference paddle.flops: FLOPs of one forward pass.

    ``net`` is an nn.Layer (traced into a program at ``input_size``,
    which includes the batch dim) or an already-built static Program.
    """
    from ..framework.program import Program

    if isinstance(net, Program):
        prog = net
    else:
        if input_size is None:
            raise ValueError("flops(net, input_size=...) needs the input "
                             "shape (batch dim included)")
        from ..dygraph import base as dy_base
        from ..dygraph import jit as djit
        from ..dygraph.tensor import Tensor

        x = Tensor(np.zeros(tuple(input_size), dtype))
        with dy_base.guard():
            _, tl = djit.TracedLayer.trace(
                net.forward if hasattr(net, "forward") else net, [x])
        prog = tl.program
    total, per_type = program_flops(prog, detail=True)
    if print_detail:
        print(f"Total FLOPs: {total:,}")
        for t, f in per_type.items():
            print(f"  {t:24s} {f:,}")
    return total


def summary(net, input_size=None, dtypes=None):
    """Reference paddle.summary: parameter table + totals for a Layer.
    ``dtypes`` sets the traced input dtype for the FLOPs pass (e.g.
    'int64' for embedding inputs)."""
    lines = [f"Model: {type(net).__name__}"]
    total = trainable = 0
    for name, p in net.named_parameters():
        n = _prod(p.shape)
        total += n
        if getattr(p, "trainable", True):
            trainable += n
        lines.append(f"  {name:40s} {str(list(p.shape)):20s} {n}")
    lines.append(f"Total params: {total}")
    lines.append(f"Trainable params: {trainable}")
    print("\n".join(lines))
    out = {"total_params": total, "trainable_params": trainable}
    if input_size is not None:
        dt = dtypes if isinstance(dtypes, str) else \
            (dtypes[0] if dtypes else "float32")
        out["flops"] = flops(net, input_size, dtype=dt)
    return out
