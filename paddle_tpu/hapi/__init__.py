"""High-level API (reference python/paddle/hapi/)."""
from . import callbacks  # noqa: F401
from .callbacks import (  # noqa: F401
    BenchmarkCallback,
    Callback,
    EarlyStopping,
    LRScheduler,
    ModelCheckpoint,
    ProgBarLogger,
)
from .model import InputSpec, Model  # noqa: F401
