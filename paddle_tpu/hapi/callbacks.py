"""High-level API callbacks (reference python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import numbers
import os
import time


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def dispatch(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)

            return dispatch
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._t0 = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def _fmt(self, logs):
        parts = []
        for k, v in (logs or {}).items():
            if isinstance(v, numbers.Number):
                parts.append(f"{k}: {v:.4f}")
            elif hasattr(v, "__len__") and len(v) == 1:
                parts.append(f"{k}: {float(v[0]):.4f}")
        return " - ".join(parts)

    def on_train_batch_end(self, step, logs=None):
        if self.verbose > 1 and (step + 1) % self.log_freq == 0:
            print(f"step {step + 1}/{self.steps or '?'} - {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            print(f"Epoch {epoch + 1} done ({dt:.1f}s) - {self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    """Epoch checkpointing through ``paddle_tpu.ckpt``: commits are
    atomic (manifest + rename — a killed run can't leave a torn epoch
    dir), ``keep_n`` retention-GCs old epochs, and ``async_save=True``
    hands serialization + writes to the background writer so the train
    loop only blocks for the host-side state capture.  ``on_train_end``
    drains pending saves and still writes the legacy ``final`` export
    via ``Model.save``.  ``restore_latest(model)`` reloads the newest
    intact epoch (falling back past corrupt ones).

    On-disk layout: epochs land as manager ``step_<epoch>`` dirs (npz
    shards + manifest), NOT the reference's ``save_dir/{epoch}``
    ``Model.save`` files.  Pass ``legacy_format=True`` to keep the old
    paddle-parity per-epoch layout (synchronous ``Model.save``, no
    atomicity/retention) for consumers that load those paths."""

    def __init__(self, save_freq=1, save_dir=None, keep_n=0,
                 async_save=None, legacy_format=False):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir
        self.keep_n = keep_n
        self.async_save = async_save
        self.legacy_format = legacy_format
        self._manager = None

    def _mgr(self):
        if self._manager is None:
            from ..ckpt import CheckpointManager

            self._manager = CheckpointManager(
                self.save_dir, keep_n=self.keep_n,
                async_save=self.async_save)
        return self._manager

    def _capture(self):
        """Host-side state dicts (the blocking part of an async save).
        Mirrors Model.save(training=True): network params + optimizer
        state, prefixed so one flat dict round-trips both.  Dict-valued
        optimizer entries (the LR_Scheduler state) can't ride the array
        shard — they return separately to travel as host-state JSON."""
        import numpy as np

        model = self.model
        if getattr(model, "_static_mode", False) and model._st is not None:
            model._sync_scope_to_network()
        state = {"param/" + k: np.asarray(v.numpy())
                 for k, v in model.network.state_dict().items()}
        opt_json = {}
        opt = getattr(model, "_optimizer", None)
        if opt is not None and hasattr(opt, "state_dict"):
            import json
            import logging

            for k, v in opt.state_dict().items():
                if isinstance(v, dict):
                    # numpy scalars -> plain floats: this rides the
                    # json-serialized host_state.  An un-JSON-able
                    # entry is dropped (with a warning), not fatal — a
                    # checkpoint missing one scheduler field beats
                    # killing training at epoch end.
                    try:
                        opt_json[k] = json.loads(
                            json.dumps(v, default=float))
                    except (TypeError, ValueError):
                        logging.getLogger(__name__).warning(
                            "ModelCheckpoint: optimizer state %r is not "
                            "JSON-serializable; it will not ride the "
                            "checkpoint", k)
                else:
                    state["opt/" + k] = np.asarray(v)
        return state, opt_json

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            if self.legacy_format:
                self.model.save(os.path.join(self.save_dir, f"{epoch}"))
            else:
                state, opt_json = self._capture()
                self._mgr().save(epoch, state=state,
                                 host_state={"epoch": epoch,
                                             "opt_json": opt_json})

    def on_train_end(self, logs=None):
        if self.save_dir:
            if self._manager is not None:
                self._manager.wait()
            self.model.save(os.path.join(self.save_dir, "final"))

    def restore_latest(self, model=None):
        """Load the newest intact epoch checkpoint into ``model`` (or
        the attached one).  Returns the epoch number, or None when the
        directory holds no committed checkpoint.  With
        ``legacy_format=True`` this loads the newest ``save_dir/{epoch}``
        ``Model.save`` files instead of manager step dirs."""
        import numpy as np

        model = model or self.model
        if self.legacy_format:
            try:
                entries = os.listdir(self.save_dir)
            except OSError:
                return None
            epochs = sorted(int(e[:-len(".pdparams")]) for e in entries
                            if e.endswith(".pdparams")
                            and e[:-len(".pdparams")].isdigit())
            if not epochs:
                return None
            model.load(os.path.join(self.save_dir, str(epochs[-1])))
            return epochs[-1]
        meta = self._mgr().restore()
        if meta is None:
            return None
        state = meta["state"]
        sd = {k[len("param/"):]: np.asarray(v) for k, v in state.items()
              if k.startswith("param/")}
        model.network.set_state_dict(sd)
        if getattr(model, "_static_mode", False) and model._st is not None:
            scope = model._st["scope"]
            for p in model.network.parameters():
                scope.set_var(p.name, np.asarray(p.numpy()))
        opt = getattr(model, "_optimizer", None)
        od = {k[len("opt/"):]: np.asarray(v) for k, v in state.items()
              if k.startswith("opt/")}
        od.update(meta["host_state"].get("opt_json") or {})
        if od and opt is not None and hasattr(opt, "set_state_dict"):
            opt.set_state_dict(od)
        return int(meta["host_state"].get("epoch", meta["step"]))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.wait = 0
        self.best = None
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.stopped_epoch = 0

    def _better(self, cur, best):
        if self.mode == "min":
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if hasattr(cur, "__len__"):
            cur = float(cur[0])
        if self.best is None or self._better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class BenchmarkCallback(Callback):
    """Step-telemetry callback (the hapi face of ``paddle_tpu.observe``).

    Times every train batch into the ``hapi_step_time_seconds``
    histogram (log-bucketed; p50/p95/p99 ride ``export_stats()``,
    ``/stats`` and ``/metrics``) and reports a throughput + MFU summary
    at ``on_train_end``.  Works in both adapters: in static mode the
    Executor's own StepTimer supplies the FLOPs/allreduce accounting
    (merged into ``summary()``); in dygraph mode pass
    ``flops_per_step=`` (e.g. from ``paddle.flops``) for an MFU number.
    """

    HIST = "hapi_step_time_seconds"

    def __init__(self, batch_size=None, flops_per_step=None, log_freq=0,
                 peak_tflops=None):
        super().__init__()
        self.batch_size = batch_size
        self.flops_per_step = flops_per_step
        self.log_freq = int(log_freq)
        self.peak_tflops = peak_tflops
        self.last_summary = None
        self._t0 = None
        self._steps = 0
        self._time = 0.0

    def on_train_begin(self, logs=None):
        from .. import observe

        observe.histogram(self.HIST).reset()
        self._steps = 0
        self._time = 0.0

    def on_train_batch_begin(self, step, logs=None):
        self._t0 = time.perf_counter()

    def on_train_batch_end(self, step, logs=None):
        if self._t0 is None:
            return
        from .. import observe

        dt = time.perf_counter() - self._t0
        observe.stat_time(self.HIST, dt)
        self._steps += 1
        self._time += dt
        if self.log_freq and (step + 1) % self.log_freq == 0:
            s = observe.histogram(self.HIST).summary()
            print(f"[bench] step {step + 1}: "
                  f"p50 {s.get('p50', 0) * 1e3:.2f}ms "
                  f"p95 {s.get('p95', 0) * 1e3:.2f}ms "
                  f"({self._steps / max(self._time, 1e-9):.1f} steps/s)")

    def summary(self):
        from .. import observe

        hist = observe.histogram(self.HIST).summary()
        out = {"steps": self._steps, "step_time_s": hist}
        if self._steps and self._time > 0:
            out["steps_per_sec"] = round(self._steps / self._time, 3)
            if self.batch_size:
                out["examples_per_sec"] = round(
                    self.batch_size * self._steps / self._time, 3)
            if self.flops_per_step:
                from ..framework import flags as _flags

                peak = self.peak_tflops if self.peak_tflops is not None \
                    else float(_flags.flag("device_peak_tflops"))
                if peak > 0.0:
                    mfu = observe.mfu_estimate(
                        self.flops_per_step, self._time / self._steps,
                        peak)
                    out["mfu"] = float(f"{mfu:.4g}")
                else:
                    # no peak configured: no denominator — null, not a
                    # misleading 0.0 (matches StepTimer.summary)
                    out["mfu"] = None
        if "mfu" not in out:
            # static adapter: the Executor's StepTimer priced the
            # program IR (hapi/model_stat.py) — reuse its MFU
            exec_summary = observe.step_timer().summary(self.peak_tflops)
            for k in ("mfu", "flops_per_step", "allreduce_bytes_per_step"):
                if k in exec_summary:
                    out[k] = exec_summary[k]
        return out

    def on_train_end(self, logs=None):
        self.last_summary = s = self.summary()
        if self._steps:
            parts = [f"steps {s['steps']}",
                     f"p50 {s['step_time_s'].get('p50', 0) * 1e3:.2f}ms",
                     f"p95 {s['step_time_s'].get('p95', 0) * 1e3:.2f}ms"]
            if "examples_per_sec" in s:
                parts.append(f"{s['examples_per_sec']:.1f} ex/s")
            if s.get("mfu") is not None:  # None = peak tflops unset
                parts.append(f"MFU {s['mfu']:.3f}")
            print("[bench] " + " - ".join(parts))


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step, self.by_epoch = by_step, by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s:
            s.step()


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     verbose=2, log_freq=1, save_freq=1, save_dir=None,
                     metrics=None):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(log_freq, verbose=verbose))
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    cl = CallbackList(cbks)
    cl.set_model(model)
    cl.set_params({"epochs": epochs, "steps": steps, "verbose": verbose,
                   "metrics": metrics or []})
    return cl
