"""`paddle.Model`: the high-level train/eval/predict loop.

Role parity: reference python/paddle/hapi/model.py:819 — prepare:1250,
fit:1306, evaluate:1516, predict:1617, save/load, train_batch/eval_batch.
TPU-native: runs the dygraph path (eager ops on the chip); batches
should keep static shapes so XLA caches compiles (drop_last=True is the
friendly setting).
"""
from __future__ import annotations

import os
import pickle
from typing import List, Optional, Sequence

import numpy as np

from ..dygraph import no_grad, to_variable
from ..metric import Metric
from .callbacks import config_callbacks


class InputSpec:
    """Reference paddle.static.InputSpec parity (shape/dtype/name)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name


class _Deferred:
    """A not-yet-materialized log value (thunk over a lazy StepHandle)."""

    __slots__ = ("fn",)

    def __init__(self, fn):
        self.fn = fn

    def __call__(self):
        return self.fn()


class LazyLogs(dict):
    """Batch logs whose values may be deferred: the static adapter's
    ``train_batch``/``eval_batch`` run through the pipelined Executor
    and return lazy StepHandles — a log value only forces the
    device→host sync when something actually READS it (a callback
    printing at ``log_freq``, the epoch-end history append), so the
    training loop keeps steps in flight instead of blocking on every
    loss.  Reads materialize in place; ``raw()`` returns the thunk
    without forcing it (the evaluate loop defers its per-batch losses
    to one sync at epoch end)."""

    def _force(self, k, v):
        if isinstance(v, _Deferred):
            v = v()
            dict.__setitem__(self, k, v)
        return v

    def __getitem__(self, k):
        return self._force(k, dict.__getitem__(self, k))

    def get(self, k, default=None):
        if k in self:
            return self.__getitem__(k)
        return default

    def items(self):
        return [(k, self._force(k, dict.__getitem__(self, k)))
                for k in self]

    def values(self):
        return [v for _, v in self.items()]

    def raw(self, k, default=None):
        """The stored value — a ``_Deferred`` thunk if not yet forced."""
        return dict.get(self, k, default)

    def force(self):
        """Materialize every value in place (plain floats afterwards —
        safe to dict()/copy()/unpack)."""
        self.items()
        return self

    def copy(self):
        return dict(self.items())  # a snapshot never leaks thunks


def _callbacks_tolerate_lazy(cbks) -> bool:
    """Only the framework's own callbacks are KNOWN not to snapshot
    logs via dict(logs)/copy()/{**} (which bypass LazyLogs' lazy reads
    and would leak _Deferred thunks).  Any user callback gets fully
    materialized logs — correctness over overlap."""
    return all(type(c).__module__.startswith("paddle_tpu.")
               for c in getattr(cbks, "callbacks", []))


class Model:
    """Mode follows the global graph mode at construction (reference
    hapi/model.py:819 picks _AdapterStatic vs dynamic the same way):
    under ``paddle_tpu.enable_static()`` the Model builds train/eval/
    predict Programs once in prepare() and drives them through the
    Executor — one XLA compile per program, the TPU-friendly loop —
    while dygraph mode runs eager batches."""

    def __init__(self, network, inputs=None, labels=None):
        from ..dygraph.base import in_dygraph_mode

        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self.stop_training = False
        self._static_mode = not in_dygraph_mode()
        self._st = None  # static-mode program bundle

    # -- setup -----------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        else:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) else [metrics]
        for m in self._metrics:
            assert isinstance(m, Metric), "metrics must be paddle.metric.Metric"
        if self._static_mode:
            self._build_static()
        return self

    # -- static adapter ---------------------------------------------------
    def _swap_params_static(self):
        """Swap every eager parameter for a static graph Parameter (same
        name, NumpyArrayInitializer from the live value) for the
        duration of a program build — otherwise the dual dispatch bakes
        the weights as inline constants and nothing trains.  Returns the
        restore list."""
        from ..initializer import NumpyArrayInitializer
        from ..layer_helper import LayerHelper
        from ..param_attr import ParamAttr

        helper = LayerHelper("hapi_static")
        saved = []
        for _, sub in self.network.named_sublayers(include_self=True):
            for pname, p in list(sub._parameters.items()):
                if p is None:
                    continue
                arr = np.asarray(p.numpy())
                sv = helper.create_parameter(
                    attr=ParamAttr(name=p.name,
                                   initializer=NumpyArrayInitializer(arr),
                                   trainable=getattr(p, "trainable", True)),
                    shape=list(arr.shape), dtype=str(arr.dtype))
                saved.append((sub, pname, p))
                sub._parameters[pname] = sv
        return saved

    @staticmethod
    def _restore_params(saved):
        for sub, pname, p in saved:
            sub._parameters[pname] = p

    def _build_static(self):
        from .. import Executor, layers
        from ..framework.place import _default_place
        from ..framework.program import Program, program_guard
        from ..framework.scope import Scope

        if not self._inputs:
            raise ValueError(
                "static-graph Model needs inputs=[InputSpec(...)] at "
                "construction (reference hapi/model.py static adapter)")

        def feeds(specs, prefix):
            vars_ = []
            for i, s in enumerate(specs or []):
                shape = list(s.shape)
                if shape and (shape[0] is None or shape[0] == -1):
                    shape = shape[1:]  # layers.data adds the batch dim
                vars_.append(layers.data(s.name or f"{prefix}_{i}", shape,
                                         dtype=s.dtype))
            return vars_

        st = {"startup": Program(), "train": Program()}
        with program_guard(st["train"], st["startup"]):
            saved = self._swap_params_static()
            try:
                ins = feeds(self._inputs, "input")
                lbs = feeds(self._labels, "label")
                outs = self.network(*ins)
                outs_l = list(outs) if isinstance(outs, (list, tuple)) \
                    else [outs]
                st["feed_names"] = [v.name for v in ins]
                st["label_names"] = [v.name for v in lbs]
                st["out_names"] = [o.name for o in outs_l]
                loss = None
                if self._loss is not None and lbs:
                    loss = self._loss(*outs_l, *lbs)
                    st["loss_name"] = loss.name
                # eval shares the graph with is_test flipped, cloned
                # BEFORE the optimizer ops join
                st["eval"] = st["train"].clone(for_test=True)
                if self._optimizer is not None and loss is not None:
                    self._optimizer.minimize(
                        loss, startup_program=st["startup"])
            finally:
                self._restore_params(saved)
        # predict program: same network, no labels; parameters keep
        # their names, so it reads the one scope the train startup fills
        st["predict"] = Program()
        with program_guard(st["predict"], Program()):
            saved = self._swap_params_static()
            try:
                ins = feeds(self._inputs, "input")
                outs = self.network(*ins)
                outs_l = list(outs) if isinstance(outs, (list, tuple)) \
                    else [outs]
                st["pred_feed_names"] = [v.name for v in ins]
                st["pred_out_names"] = [o.name for o in outs_l]
            finally:
                self._restore_params(saved)
        st["predict"] = st["predict"].clone(for_test=True)
        st["scope"] = Scope()
        st["exe"] = Executor(_default_place())
        st["exe"].run(st["startup"], scope=st["scope"])
        self._st = st

    def _sync_scope_to_network(self):
        """After static training, push scope values back into the eager
        parameters (names tie them) so save()/state_dict see the result."""
        scope = self._st["scope"]
        for p in self.network.parameters():
            v = scope.find_var(p.name) if scope.has_var(p.name) else None
            if v is not None:
                p.set_value(np.asarray(v.get_tensor()))

    def _static_feed(self, names, data):
        vals = data if isinstance(data, (list, tuple)) else [data]
        return {n: np.asarray(v) for n, v in zip(names, vals)}

    # -- single-batch steps ----------------------------------------------
    def _to_vars(self, data):
        if isinstance(data, (list, tuple)):
            return [to_variable(np.asarray(d)) for d in data]
        return [to_variable(np.asarray(data))]

    def train_batch(self, inputs, labels=None):
        if self._static_mode:
            return self._static_batch("train", inputs, labels)
        self.network.train()
        ins = self._to_vars(inputs)
        outs = self.network(*ins)
        outs_list = outs if isinstance(outs, (list, tuple)) else [outs]
        logs = {}
        if labels is not None and self._loss is not None:
            lbs = self._to_vars(labels)
            loss = self._loss(*outs_list, *lbs)
            loss.backward()
            self._optimizer.step()
            self._optimizer.clear_grad()
            logs["loss"] = float(np.asarray(loss.numpy()).ravel()[0])
            for m in self._metrics:
                _metric_update(m, outs_list[0], lbs[0])
        return logs

    @no_grad()
    def eval_batch(self, inputs, labels=None):
        if self._static_mode:
            return self._static_batch("eval", inputs, labels)
        self.network.eval()
        ins = self._to_vars(inputs)
        outs = self.network(*ins)
        outs_list = outs if isinstance(outs, (list, tuple)) else [outs]
        logs = {}
        if labels is not None:
            lbs = self._to_vars(labels)
            if self._loss is not None:
                loss = self._loss(*outs_list, *lbs)
                logs["loss"] = float(np.asarray(loss.numpy()).ravel()[0])
            for m in self._metrics:
                _metric_update(m, outs_list[0], lbs[0])
        return logs

    @no_grad()
    def predict_batch(self, inputs):
        if self._static_mode:
            st = self._require_static()
            feed = self._static_feed(st["pred_feed_names"], inputs)
            outs = st["exe"].run(st["predict"], feed=feed,
                                 fetch_list=st["pred_out_names"],
                                 scope=st["scope"])
            return [np.asarray(o) for o in outs]
        self.network.eval()
        outs = self.network(*self._to_vars(inputs))
        outs_list = outs if isinstance(outs, (list, tuple)) else [outs]
        return [np.asarray(o.numpy()) for o in outs_list]

    def _require_static(self):
        if self._st is None:
            raise RuntimeError("static-graph Model: call prepare() first")
        return self._st

    def _static_batch(self, kind, inputs, labels):
        st = self._require_static()
        feed = self._static_feed(st["feed_names"], inputs)
        if labels is not None:
            feed.update(self._static_feed(st["label_names"], labels))
        fetch = list(st["out_names"])
        has_loss = "loss_name" in st and labels is not None
        if has_loss:
            fetch.append(st["loss_name"])
        prog = st["train"] if kind == "train" else st["eval"]
        outs = st["exe"].run(prog, feed=feed, fetch_list=fetch,
                             scope=st["scope"])
        logs = LazyLogs()
        if has_loss:
            # deferred: the pipelined Executor returned a lazy handle —
            # the loss only syncs when a callback/history actually reads
            # it, so dispatch of the next batch is never blocked here.
            # Capture ONLY the loss's device scalar, not the handle: a
            # thunk pinning the whole fetch list would keep every
            # batch's predictions alive for as long as the logs live
            # (evaluate accumulates one thunk per batch)
            loss_ref = (outs.device_arrays()[-1]
                        if hasattr(outs, "device_arrays") else outs[-1])
            logs["loss"] = _Deferred(
                lambda: float(np.asarray(loss_ref).ravel()[0]))
        if labels is not None and self._metrics:
            from ..dygraph.tensor import Tensor

            # metrics READ the prediction: materialize it (this is the
            # one per-batch sync a metric-carrying loop genuinely needs)
            pred = Tensor(np.asarray(outs[0]))
            lbl = Tensor(np.asarray(
                labels[0] if isinstance(labels, (list, tuple)) else labels))
            for m in self._metrics:
                _metric_update(m, pred, lbl)
        return logs

    # -- loops -----------------------------------------------------------
    def _as_loader(self, data, batch_size, shuffle, drop_last=False):
        from ..io import DataLoader, Dataset

        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              drop_last=drop_last)
        return data  # any iterable of batches

    @staticmethod
    def _split_batch(batch):
        """(x, y) convention: last element is the label."""
        if isinstance(batch, (list, tuple)) and len(batch) >= 2:
            return list(batch[:-1]), [batch[-1]]
        return [batch], None

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None):
        loader = self._as_loader(train_data, batch_size, shuffle,
                                 drop_last=drop_last)
        steps = None
        try:
            steps = len(loader)
        except TypeError:
            pass
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                steps=steps, verbose=verbose,
                                log_freq=log_freq, save_dir=save_dir,
                                save_freq=save_freq,
                                metrics=[n for m in self._metrics
                                         for n in _as_list(m.name())])
        self.stop_training = False
        cbks.on_train_begin()
        lazy_ok = _callbacks_tolerate_lazy(cbks)
        history = {"loss": []}
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(loader):
                cbks.on_train_batch_begin(step)
                xs, ys = self._split_batch(batch)
                logs = self.train_batch(xs, ys)
                for m in self._metrics:
                    for n, v in zip(_as_list(m.name()), _as_list(m.accumulate())):
                        logs[n] = v
                if not lazy_ok and isinstance(logs, LazyLogs):
                    logs.force()  # user callbacks see plain floats
                cbks.on_train_batch_end(step, logs)
            history["loss"].append(logs.get("loss"))
            cbks.on_epoch_end(epoch, logs)
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_data, batch_size=batch_size,
                                          verbose=0, _callbacks=cbks)
                for k, v in eval_logs.items():
                    history.setdefault("eval_" + k, []).append(v)
            if self.stop_training:
                break
        cbks.on_train_end()
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, _callbacks=None):
        loader = self._as_loader(eval_data, batch_size, False)
        cbks = _callbacks or config_callbacks(callbacks, model=self,
                                              verbose=verbose)
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin()
        lazy_ok = _callbacks_tolerate_lazy(cbks)
        logs = {}
        losses = []
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            xs, ys = self._split_batch(batch)
            logs = self.eval_batch(xs, ys)
            if not lazy_ok and isinstance(logs, LazyLogs):
                logs.force()
            if "loss" in logs:
                # keep the thunk: all batch losses sync in ONE pass at
                # the end instead of serializing the eval pipeline
                losses.append(logs.raw("loss")
                              if isinstance(logs, LazyLogs)
                              else logs["loss"])
            cbks.on_eval_batch_end(step, logs)
        if losses:
            logs["loss"] = float(np.mean(
                [v() if isinstance(v, _Deferred) else v for v in losses]))
        for m in self._metrics:
            for n, v in zip(_as_list(m.name()), _as_list(m.accumulate())):
                logs[n] = v
        cbks.on_eval_end(logs)
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = self._as_loader(test_data, batch_size, False)
        outputs = []
        for batch in loader:
            xs, _ = self._split_batch(batch)
            outputs.append(self.predict_batch(xs))
        if stack_outputs and outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs]) for i in range(n_out)]
        return outputs

    # -- persistence -----------------------------------------------------
    def save(self, path, training=True):
        """Reference Model.save: ``training=True`` saves a state dict (+
        optimizer state); ``training=False`` exports a servable inference
        model via the trace-based jit.save path (hapi/model.py:199)."""
        if not training:
            from .. import jit

            if not self._inputs:
                raise ValueError(
                    "Model.save(training=False) needs the Model to be "
                    "constructed with `inputs=[InputSpec(...)]` so the "
                    "forward can be traced for export")
            if self._static_mode and self._st is not None:
                # trained values live in the executor scope; the traced
                # export reads the eager parameters
                self._sync_scope_to_network()
            was_training = getattr(self.network, "training", False)
            self.network.eval()
            try:
                jit.save(self.network, path, input_spec=self._inputs)
            finally:
                if was_training:
                    self.network.train()
            return
        dirname = os.path.dirname(path)
        if dirname:
            os.makedirs(dirname, exist_ok=True)
        if self._static_mode and self._st is not None:
            self._sync_scope_to_network()
        sd = {k: np.asarray(v.numpy())
              for k, v in self.network.state_dict().items()}
        with open(path + ".pdparams", "wb") as f:
            pickle.dump(sd, f)
        if training and self._optimizer is not None \
                and hasattr(self._optimizer, "state_dict"):
            od = {k: np.asarray(v) for k, v in self._optimizer.state_dict().items()
                  if not isinstance(v, dict)}
            with open(path + ".pdopt", "wb") as f:
                pickle.dump(od, f)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        with open(path + ".pdparams", "rb") as f:
            sd = pickle.load(f)
        missing, unexpected = self.network.set_state_dict(sd)
        if not skip_mismatch and (missing or unexpected):
            raise RuntimeError(
                f"state dict mismatch: missing={missing}, "
                f"unexpected={unexpected} (pass skip_mismatch=True to ignore)")
        if self._static_mode and self._st is not None:
            # push loaded values into the executor scope (names tie the
            # eager parameters to the static vars)
            scope = self._st["scope"]
            for p in self.network.parameters():
                scope.set_var(p.name, np.asarray(p.numpy()))
        if not reset_optimizer and self._optimizer is not None \
                and os.path.exists(path + ".pdopt"):
            with open(path + ".pdopt", "rb") as f:
                od = pickle.load(f)
            if hasattr(self._optimizer, "set_state_dict"):
                self._optimizer.set_state_dict(od)

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .model_stat import summary as _summary

        return _summary(self.network, input_size=input_size, dtypes=dtype)


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


def _metric_update(metric, pred, label):
    """compute() may return one value or a (pred, label)-style tuple; the
    reference unpacks it into update() (hapi/model.py metric handling)."""
    res = metric.compute(pred, label)
    if isinstance(res, tuple):
        metric.update(*res)
    else:
        metric.update(res)
