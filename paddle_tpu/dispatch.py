"""Dual-mode op dispatch for the 2.0 API.

Role parity: the reference 2.0 API functions each contain
``if in_dygraph_mode(): return core.ops.xxx(...)`` followed by a
LayerHelper/append_op static branch (e.g. python/paddle/tensor/math.py).
Here that pattern is one helper: eager inputs run the lowering rule now
(dygraph/eager.py); graph Variables append an IR op for later whole-block
XLA compilation.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from .framework.program import Variable
from .layer_helper import LayerHelper


def _is_eager(x) -> bool:
    from .dygraph.tensor import Tensor

    return isinstance(x, Tensor)


def _any_static(inputs: Dict) -> bool:
    for v in inputs.values():
        if v is None:
            continue
        vs = v if isinstance(v, (list, tuple)) else [v]
        for x in vs:
            if isinstance(x, Variable):
                return True
    return False


def _any_eager(inputs: Dict) -> bool:
    for v in inputs.values():
        if v is None:
            continue
        vs = v if isinstance(v, (list, tuple)) else [v]
        for x in vs:
            if _is_eager(x):
                return True
    return False


def in_dygraph_mode() -> bool:
    from .dygraph.base import in_dygraph_mode as _m

    return _m()


def op_call(op_type: str, inputs: Dict, attrs: Optional[dict] = None,
            outs: Sequence[str] = ("Out",), dtype=None, name: Optional[str] = None,
            out_counts: Optional[Dict[str, int]] = None):
    """Run/append one op; returns a value per out slot (single value if one).

    Mode resolution: eager inputs -> eager; Variables -> static graph;
    neither (e.g. creation ops) -> static if paddle.enable_static() was
    called OR we are inside a program_guard block, else eager.
    """
    from .framework.program import in_program_guard

    static = _any_static(inputs) or (
        not _any_eager(inputs) and (not in_dygraph_mode() or in_program_guard()))
    if not static:
        from .dygraph.eager import run_op

        res = run_op(op_type, inputs, attrs, out_slots=tuple(outs),
                     out_counts=out_counts)
        vals = [res.get(s) for s in outs]
        return vals[0] if len(outs) == 1 else tuple(vals)

    helper = LayerHelper(name or op_type)
    in_names = {}
    for slot, v in inputs.items():
        if v is None:
            continue
        vs = v if isinstance(v, (list, tuple)) else [v]
        names = []
        for x in vs:
            if isinstance(x, Variable):
                names.append(x.name)
            else:
                # inline constant: materialize through fill/assign
                names.append(_const_to_var(helper, x).name)
        in_names[slot] = names

    out_vars = {}
    first_dtype = dtype
    if first_dtype is None:
        for slot, v in inputs.items():
            vs = v if isinstance(v, (list, tuple)) else ([v] if v is not None else [])
            for x in vs:
                if isinstance(x, Variable):
                    first_dtype = x.dtype
                    break
            if first_dtype is not None:
                break
    for slot in outs:
        n = (out_counts or {}).get(slot, 1)
        vars_ = [helper.create_variable_for_type_inference(first_dtype or "float32")
                 for _ in range(n)]
        out_vars[slot] = vars_

    helper.append_op(op_type, in_names,
                     {s: [v.name for v in vs] for s, vs in out_vars.items()},
                     attrs or {})
    vals = []
    for slot in outs:
        vs = out_vars[slot]
        n = (out_counts or {}).get(slot)
        vals.append(vs if n is not None else vs[0])
    return vals[0] if len(outs) == 1 else tuple(vals)


def _const_to_var(helper: LayerHelper, x) -> Variable:
    from .framework import dtypes

    arr = np.asarray(x)
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    out = helper.create_variable_for_type_inference(str(arr.dtype))
    if arr.ndim == 0:
        helper.append_op("fill_constant", {}, {"Out": out},
                         {"shape": [1], "dtype": dtypes.to_enum(str(arr.dtype)),
                          "value": float(arr)})
    else:
        from .initializer import NumpyArrayInitializer

        key = {"float32": "fp32_values", "int32": "int32_values",
               "int64": "int64_values", "bool": "bool_values"}.get(str(arr.dtype), "fp32_values")
        helper.append_op("assign_value", {}, {"Out": out},
                         {"shape": list(arr.shape), "dtype": dtypes.to_enum(str(arr.dtype)),
                          key: arr.ravel().tolist()})
    return out


def to_tensor_or_var(x, dtype=None):
    """Wrap python data as an eager Tensor (dygraph) — the 2.0 to_tensor."""
    from .dygraph.base import to_variable

    return to_variable(x, dtype=dtype)
