"""Profiler: RecordEvent-style annotations + trace capture over jax.profiler.

Role parity: reference ``python/paddle/fluid/profiler.py`` (``profiler``
context manager :255, ``start_profiler`` :131, ``stop_profiler`` :198) and
the C++ ``RecordEvent`` scoped annotations (platform/profiler.cc:53).
TPU-native redesign: instead of CUPTI device tracing + a custom
profiler.proto, capture goes through ``jax.profiler`` — the trace contains
every XLA executable launch and on-device op, viewable in
TensorBoard/Perfetto (replaces tools/timeline.py's chrome://tracing dump).
``RecordEvent`` maps to ``jax.profiler.TraceAnnotation`` so user-code
phases appear on the host timeline alongside device ops — and
dual-feeds the always-on in-process span tracer
(``paddle_tpu.observe``): the TraceAnnotation path lights up when an
XLA capture is live, the ring-buffer span whenever
``FLAGS_enable_tracer`` is set, so one annotation serves both the
heavyweight capture and the exportable host timeline.
"""
from __future__ import annotations

import contextlib
import os
import time
from typing import Optional

from .observe import tracer as _otracer

_state = {"running": False, "dir": None, "t0": None}


class RecordEvent:
    """Scoped host-side annotation (reference platform/profiler.cc:53).

    Usable as a context manager, via explicit begin()/end(), or as a
    function decorator (``@RecordEvent("serving/batch")`` wraps every
    call of the function in its own span).  Shows up as a named span on
    the profiler timeline when a capture is active AND in the observe
    tracer's ring buffer when ``FLAGS_enable_tracer`` is set; costs
    ~nothing when neither is running.
    """

    def __init__(self, name: str):
        import threading

        self.name = name
        # per-THREAD LIFO of live annotations: one RecordEvent instance
        # may be shared across threads or re-entered (explicit
        # begin()/end() API) without corrupting the tracer's span stack
        # or leaking a TraceAnnotation
        self._local = threading.local()

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with RecordEvent(self.name):
                return fn(*args, **kwargs)

        return wrapped

    def _entries(self):
        st = getattr(self._local, "entries", None)
        if st is None:
            st = self._local.entries = []
        return st

    def begin(self):
        import jax

        # tracer begin/end are balance-safe across FLAGS_enable_tracer
        # flips (disabled begin pushes a discard sentinel)
        _otracer.begin(self.name)
        ann = jax.profiler.TraceAnnotation(self.name)
        ann.__enter__()
        self._entries().append(ann)

    def end(self):
        entries = self._entries()
        if entries:
            entries.pop().__exit__(None, None, None)
        _otracer.end()

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def start_profiler(state: str = "All", tracer_option: str = "Default",
                   profile_path: Optional[str] = None):
    """Begin a trace capture (reference fluid/profiler.py:131).

    ``state``/``tracer_option`` are accepted for API parity; XLA traces
    host + device unconditionally (there is no CPU-only tracer to pick).
    """
    import jax

    if _state["running"]:
        raise RuntimeError("profiler is already running")
    out = profile_path or os.environ.get("PADDLE_TPU_PROFILE_DIR",
                                         "/tmp/paddle_tpu_profile")
    os.makedirs(out, exist_ok=True)
    _state.update(running=True, dir=out, t0=time.perf_counter())
    try:
        jax.profiler.start_trace(out)
    except Exception:
        # a failed capture must not wedge the "already running" check
        # for the rest of the process
        _state.update(running=False, dir=None, t0=None)
        raise


def stop_profiler(sorted_key: Optional[str] = None,
                  profile_path: Optional[str] = None) -> str:
    """End the capture and return the trace directory (reference
    fluid/profiler.py:198).  ``sorted_key`` is parity-only: aggregation
    and sorting happen in TensorBoard/Perfetto over the dumped trace, not
    in-process."""
    import jax

    if not _state["running"]:
        raise RuntimeError("profiler is not running")
    out = _state["dir"]
    jax.profiler.stop_trace()
    # full reset (not just the running bit): a later start must never
    # see this capture's dir/t0
    _state.update(running=False, dir=None, t0=None)
    return out


@contextlib.contextmanager
def profiler(state: str = "All", sorted_key: str = "total",
             profile_path: Optional[str] = None, tracer_option: str = "Default"):
    """Context manager parity with ``fluid.profiler.profiler`` (:255)::

        with profiler(profile_path="/tmp/trace"):
            exe.run(main, feed=..., fetch_list=[loss])
    """
    start_profiler(state, tracer_option, profile_path)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def cuda_profiler(*args, **kwargs):  # pragma: no cover - trivial
    """Reference API shim: CUDA-specific; on TPU this is the same XLA
    trace capture (kept so fluid scripts run unchanged)."""
    with profiler():
        yield
