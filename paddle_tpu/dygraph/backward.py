"""Dygraph autograd engine: reverse-topological VJP replay over the tape.

Role parity: reference imperative/basic_engine.cc (`Init`:38 seeds the
root grad, `PrepareDeps`:134 counts consumers, `Execute`:171 walks the
queue) + gradient_accumulator.cc (leaf grad summation) +
partial_grad_engine.cc (`paddle.grad` over an input subset).  TPU-native:
each node's backward is `jax.vjp` of its re-run forward; under `jit` the
recomputation is CSE'd by XLA, so cost matches hand-written grad kernels.
"""
from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from .tensor import Tensor


def _reachable_nodes(roots: List[Tensor]):
    seen = set()
    order = []
    stack = [t.grad_node for t in roots if t.grad_node is not None]
    while stack:
        node = stack.pop()
        if node is None or id(node) in seen:
            continue
        seen.add(id(node))
        order.append(node)
        for t in node.in_tensors:
            if t.grad_node is not None:
                stack.append(t.grad_node)
    return {id(n): n for n in order}


def run_backward(roots: List[Tensor], seeds: Optional[List] = None,
                 inputs: Optional[List[Tensor]] = None,
                 retain_graph: bool = False,
                 accumulate_leaf: bool = True) -> Dict[int, object]:
    """Core engine.  Returns {id(tensor): raw grad} for every tensor touched.

    `seeds[i]` is the cotangent for `roots[i]` (defaults to ones, matching
    the reference's scalar-loss seeding in BasicEngine::Init).
    """
    seeds = seeds or [None] * len(roots)
    grads: Dict[int, object] = {}
    keep: Dict[int, Tensor] = {}

    for t, s in zip(roots, seeds):
        if s is None:
            if t.size != 1:
                raise RuntimeError(
                    f"grad can be implicitly created only for scalar outputs; "
                    f"got shape {t.shape} (pass grad_tensor)")
            s = jnp.ones_like(t._value)
        g = grads.get(id(t))
        grads[id(t)] = s if g is None else g + s
        keep[id(t)] = t

    nodes = _reachable_nodes(roots)

    # consumer edge counts within the reachable subgraph (PrepareDeps parity)
    pending: Dict[int, int] = {nid: 0 for nid in nodes}
    for node in nodes.values():
        for t in node.in_tensors:
            if t.grad_node is not None and id(t.grad_node) in nodes:
                pending[id(t.grad_node)] += 1

    # a root's node starts ready only once all its reachable consumers ran
    ready = deque(n for nid, n in nodes.items() if pending[nid] == 0)
    executed = 0
    while ready:
        node = ready.popleft()
        executed += 1
        # cotangents for this node's float outputs
        cots = []
        for i in node.float_out_idx:
            t = node.out_tensors[i]
            g = grads.get(id(t))
            cots.append(jnp.zeros_like(t._value) if g is None else
                        jnp.asarray(g, dtype=t._value.dtype))

        primals = [t._value for t in node.in_tensors]

        def fwd_float(*vals, _node=node):
            outs = _node.fwd(*vals)
            return tuple(outs[i] for i in _node.float_out_idx)

        _, vjp_fn = jax.vjp(fwd_float, *primals)
        in_grads = vjp_fn(tuple(cots))

        for t, g in zip(node.in_tensors, in_grads):
            if t.stop_gradient and t.grad_node is None:
                pass  # constant input: discard
            else:
                prev = grads.get(id(t))
                grads[id(t)] = g if prev is None else prev + g
                keep[id(t)] = t
            if t.grad_node is not None and id(t.grad_node) in nodes:
                pending[id(t.grad_node)] -= 1
                if pending[id(t.grad_node)] == 0:
                    ready.append(t.grad_node)

        if not retain_graph:
            node.release()

    if executed != len(nodes):
        # disconnected remainder (e.g. some root unreachable); still correct
        pass

    if accumulate_leaf:
        for tid, t in keep.items():
            if t.grad_node is None and not t.stop_gradient:
                g = grads.get(tid)
                if g is None:
                    continue
                if t.grad is None:
                    t.grad = Tensor(g, name=t.name + "@GRAD", stop_gradient=True)
                else:
                    t.grad._set_raw(t.grad._value + g)

    if not retain_graph:
        for t in roots:
            t.grad_node = None
    return grads


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """`paddle.grad` (reference partial_grad_engine.cc / dygraph base.grad).

    create_graph (double grad) is not supported yet — documented gap.
    """
    if create_graph:
        raise NotImplementedError("create_graph=True (double grad) not yet supported")
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is not None:
        grad_outputs = grad_outputs if isinstance(grad_outputs, (list, tuple)) else [grad_outputs]
        seeds = [None if g is None else g._value for g in grad_outputs]
    else:
        seeds = None
    retain = True if retain_graph is None else retain_graph
    grads = run_backward(list(outputs), seeds, retain_graph=retain,
                         accumulate_leaf=False)
    result = []
    for t in inputs:
        g = grads.get(id(t))
        if g is None:
            if not allow_unused:
                raise RuntimeError(
                    f"input {t.name} is unreachable from outputs "
                    "(set allow_unused=True to get None)")
            result.append(None)
        else:
            result.append(Tensor(g, stop_gradient=True))
    return result
