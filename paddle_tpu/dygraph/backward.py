"""Dygraph autograd engine: reverse-topological VJP replay over the tape.

Role parity: reference imperative/basic_engine.cc (`Init`:38 seeds the
root grad, `PrepareDeps`:134 counts consumers, `Execute`:171 walks the
queue) + gradient_accumulator.cc (leaf grad summation) +
partial_grad_engine.cc (`paddle.grad` over an input subset).  TPU-native:
each node's backward is `jax.vjp` of its re-run forward; under `jit` the
recomputation is CSE'd by XLA, so cost matches hand-written grad kernels.
"""
from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from .tensor import Tensor


def _t_add(a: Tensor, b: Tensor) -> Tensor:
    """Tensor addition recorded on the tape (grad accumulation must stay
    differentiable under create_graph)."""
    from . import eager

    return eager.apply_jax(jnp.add, a, b)


def _node_backward_recorded(node, fwd_float, grads):
    """Run one node's VJP as a tape-recorded operation: the returned input
    grads are Tensors whose own grad_nodes re-enter the engine, which is
    exactly what makes grad-of-grad work (TPU-native equivalent of the
    reference's double-grad op graph, partial_grad_engine.cc)."""
    from . import eager

    cot_tensors = []
    for i in node.float_out_idx:
        t = node.out_tensors[i]
        g = grads.get(id(t))
        had_grad = g is not None
        if g is None:
            g = Tensor(jnp.zeros_like(t._value), stop_gradient=True)
        elif not isinstance(g, Tensor):
            g = Tensor(jnp.asarray(g, dtype=t._value.dtype),
                       stop_gradient=True)
        elif g._value.dtype != t._value.dtype:
            # vjp rejects cotangents whose dtype differs from the primal
            # (same coercion the non-recorded path applies)
            g = eager.apply_jax(
                lambda v, dt=t._value.dtype: v.astype(dt), g)
        if had_grad and t.__dict__.get("_grad_hooks"):
            # hooks fire on the recorded path too (but never on a
            # fabricated zero grad); the hooked value becomes BOTH the
            # cotangent and this tensor's reported gradient
            g = Tensor(jnp.asarray(t._apply_grad_hooks(g._value),
                                   dtype=t._value.dtype),
                       stop_gradient=True)
            grads[id(t)] = g
        cot_tensors.append(g)

    n_in = len(node.in_tensors)

    def bwd(*vals):
        prim, cots = vals[:n_in], vals[n_in:]
        _, vjp_fn = jax.vjp(fwd_float, *prim)
        return tuple(vjp_fn(tuple(cots)))

    bwd.__name__ = f"{node.op_type}_double_grad"
    outs = eager.apply_jax(bwd, *(list(node.in_tensors) + cot_tensors),
                           n_out=n_in)
    return outs if isinstance(outs, list) else [outs]


def _reachable_nodes(roots: List[Tensor]):
    seen = set()
    order = []
    stack = [t.grad_node for t in roots if t.grad_node is not None]
    while stack:
        node = stack.pop()
        if node is None or id(node) in seen:
            continue
        seen.add(id(node))
        order.append(node)
        for t in node.in_tensors:
            if t.grad_node is not None:
                stack.append(t.grad_node)
    return {id(n): n for n in order}


def run_backward(roots: List[Tensor], seeds: Optional[List] = None,
                 inputs: Optional[List[Tensor]] = None,
                 retain_graph: bool = False,
                 accumulate_leaf: bool = True,
                 create_graph: bool = False) -> Dict[int, object]:
    """Core engine.  Returns {id(tensor): grad} for every tensor touched —
    raw jax values normally, tape-recorded Tensors under ``create_graph``
    (so a second grad() differentiates the backward itself; reference
    partial_grad_engine.cc double-grad role).

    `seeds[i]` is the cotangent for `roots[i]` (defaults to ones, matching
    the reference's scalar-loss seeding in BasicEngine::Init).
    """
    if create_graph:
        retain_graph = True
    seeds = seeds or [None] * len(roots)
    grads: Dict[int, object] = {}
    keep: Dict[int, Tensor] = {}

    def as_val(s):
        return s._value if isinstance(s, Tensor) else s

    for t, s in zip(roots, seeds):
        if s is None:
            if t.size != 1:
                raise RuntimeError(
                    f"grad can be implicitly created only for scalar outputs; "
                    f"got shape {t.shape} (pass grad_tensor)")
            s = jnp.ones_like(t._value)
        if create_graph:
            s = s if isinstance(s, Tensor) else Tensor(s, stop_gradient=True)
            g = grads.get(id(t))
            grads[id(t)] = s if g is None else _t_add(g, s)
        else:
            s = as_val(s)
            g = grads.get(id(t))
            grads[id(t)] = s if g is None else g + s
        keep[id(t)] = t

    nodes = _reachable_nodes(roots)

    # consumer edge counts within the reachable subgraph (PrepareDeps parity)
    pending: Dict[int, int] = {nid: 0 for nid in nodes}
    for node in nodes.values():
        for t in node.in_tensors:
            if t.grad_node is not None and id(t.grad_node) in nodes:
                pending[id(t.grad_node)] += 1

    # a root's node starts ready only once all its reachable consumers ran
    ready = deque(n for nid, n in nodes.items() if pending[nid] == 0)
    executed = 0
    while ready:
        node = ready.popleft()
        executed += 1

        def fwd_float(*vals, _node=node):
            outs = _node.fwd(*vals)
            return tuple(outs[i] for i in _node.float_out_idx)

        if create_graph:
            in_grads = _node_backward_recorded(node, fwd_float, grads)
        else:
            # cotangents for this node's float outputs
            cots = []
            for i in node.float_out_idx:
                t = node.out_tensors[i]
                g = grads.get(id(t))
                if g is None:
                    cots.append(jnp.zeros_like(t._value))
                    continue
                g = jnp.asarray(g, dtype=t._value.dtype)
                if t.__dict__.get("_grad_hooks"):
                    # reference VarBase hooks: fire when this tensor's
                    # gradient is computed (never on a fabricated zero);
                    # the hooked value is BOTH the upstream cotangent and
                    # this tensor's reported gradient
                    g = jnp.asarray(t._apply_grad_hooks(g),
                                    dtype=t._value.dtype)
                    grads[id(t)] = g
                cots.append(g)

            primals = [t._value for t in node.in_tensors]
            _, vjp_fn = jax.vjp(fwd_float, *primals)
            in_grads = vjp_fn(tuple(cots))

        for t, g in zip(node.in_tensors, in_grads):
            if t.stop_gradient and t.grad_node is None:
                pass  # constant input: discard
            else:
                prev = grads.get(id(t))
                if create_graph:
                    grads[id(t)] = g if prev is None else _t_add(prev, g)
                else:
                    grads[id(t)] = g if prev is None else prev + g
                keep[id(t)] = t
            if t.grad_node is not None and id(t.grad_node) in nodes:
                pending[id(t.grad_node)] -= 1
                if pending[id(t.grad_node)] == 0:
                    ready.append(t.grad_node)

        if not retain_graph:
            node.release()

    if executed != len(nodes):
        # disconnected remainder (e.g. some root unreachable); still correct
        pass

    # leaf hooks fire once the leaf's gradient is final — through EVERY
    # engine entry (backward() and paddle.grad alike), and the hooked
    # value is what the grads dict reports
    for tid, t in keep.items():
        if t.grad_node is None and t.__dict__.get("_grad_hooks"):
            g = grads.get(tid)
            if g is not None:
                grads[tid] = t._apply_grad_hooks(g)

    if accumulate_leaf:
        for tid, t in keep.items():
            if t.grad_node is None and not t.stop_gradient:
                g = grads.get(tid)
                if g is None:
                    continue
                if t.grad is None:
                    t.grad = Tensor(g, name=t.name + "@GRAD", stop_gradient=True)
                else:
                    t.grad._set_raw(t.grad._value + g)

    if not retain_graph:
        for t in roots:
            t.grad_node = None
    return grads


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """`paddle.grad` (reference partial_grad_engine.cc / dygraph
    base.grad).  ``create_graph=True`` records the backward on the tape so
    the returned grads are themselves differentiable (double grad)."""
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is not None:
        grad_outputs = grad_outputs if isinstance(grad_outputs, (list, tuple)) else [grad_outputs]
        seeds = [None if g is None else g._value for g in grad_outputs]
    else:
        seeds = None
    retain = True if retain_graph is None else retain_graph
    grads = run_backward(list(outputs), seeds, retain_graph=retain,
                         accumulate_leaf=False, create_graph=create_graph)
    result = []
    for t in inputs:
        g = grads.get(id(t))
        if g is None:
            if not allow_unused:
                raise RuntimeError(
                    f"input {t.name} is unreachable from outputs "
                    "(set allow_unused=True to get None)")
            result.append(None)
        elif isinstance(g, Tensor):
            result.append(g)
        else:
            result.append(Tensor(g, stop_gradient=True))
    return result
